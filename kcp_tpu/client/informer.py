"""Informers: list+watch caches with indexers and event handlers.

The analog of the reference's shared informer factories (generated in
pkg/client/informers/**, used by every controller). Differences, by
design:

- async tasks instead of goroutines
- handlers receive (event_type, old, new) and are called on the event
  loop; controllers usually just enqueue keys — the heavy lifting happens
  in the batched reconcile tick
- a periodic resync replays the full cache as MODIFIED events, the
  level-triggered safety net that bounds missed-event damage
  (reference resyncPeriod=10h, pkg/syncer/syncer.go:27)
"""

from __future__ import annotations

import asyncio
import logging
import os
import random
from typing import Awaitable, Callable, Iterable

from ..apis.scheme import GVR
from ..store.selectors import LabelSelector
from ..store.store import ADDED, DELETED, MODIFIED, Event
from ..utils import errors
from ..utils.trace import REGISTRY
from .client import Client

log = logging.getLogger(__name__)

Handler = Callable[[str, dict | None, dict | None], None]
IndexFunc = Callable[[dict], Iterable[str]]

# Standard indexers, mirroring the reference's
# (pkg/reconciler/cluster/controller.go:50-60, 134-149).
def by_cluster(obj: dict) -> list[str]:
    return [obj["metadata"].get("clusterName", "")]


def by_namespace(obj: dict) -> list[str]:
    return [obj["metadata"].get("namespace", "")]


def by_location(obj: dict) -> list[str]:
    """APIResourceImport spec.location indexer (LocationInLogicalCluster)."""
    return [f'{obj["metadata"].get("clusterName", "")}/{obj.get("spec", {}).get("location", "")}']


def by_location_and_gvr(obj: dict) -> list[str]:
    """GVRForLocationInLogicalCluster analog."""
    spec = obj.get("spec", {})
    gv = spec.get("groupVersion", {})
    gvr = f'{gv.get("group", "")}/{gv.get("version", "")}/{spec.get("plural", "")}'
    return [
        f'{obj["metadata"].get("clusterName", "")}/{spec.get("location", "")}/{gvr}'
    ]


class Informer:
    """A list+watch cache for one GVR (optionally selector/namespace bound)."""

    def __init__(
        self,
        client: Client,
        gvr: GVR | str,
        selector: LabelSelector | None = None,
        namespace: str | None = None,
        resync_period: float | None = None,
        watch_list: bool | None = None,
    ):
        self.client = client
        self.gvr = gvr
        self.selector = selector
        self.namespace = namespace
        self.resync_period = resync_period
        self.cache: dict[tuple[str, str, str], dict] = {}  # (cluster, ns, name) -> obj
        self._handlers: list[Handler] = []
        self._indexers: dict[str, IndexFunc] = {}
        self._indices: dict[str, dict[str, set[tuple[str, str, str]]]] = {}
        self._synced = asyncio.Event()
        self._task: asyncio.Task | None = None
        self._resync_task: asyncio.Task | None = None
        self._watch = None
        self._stopping = False
        self.rewatch_backoff = 0.2  # reflector retry pacing on stream loss
        self.retry_after_cap = 30.0  # ceiling on server Retry-After hints
        # resume point: the highest RV this informer has OBSERVED —
        # advanced by delivered events and, crucially, by server
        # BOOKMARKs absorbed into the watch's last_rv (no handler wakes,
        # no resync) — so a stream dropped after a quiet period resumes
        # inside the watch window instead of relisting the world
        self._rv = 0
        # KEP-3157-style watch-list start (opt-in: ctor arg, or
        # KCP_WATCH_LIST=1): the initial state arrives as ADDED events
        # on the watch stream itself, ending in a sync BOOKMARK — the
        # informer never holds a whole list body. Only clients that
        # advertise support (RestClient family) use it; others (and any
        # refusal at runtime) fall back to classic list+watch.
        if watch_list is None:
            watch_list = os.environ.get("KCP_WATCH_LIST", "") == "1"
        self._watch_list = bool(watch_list) and bool(
            getattr(client, "supports_watch_list", False))

    def _retry_delay(self, err: BaseException | None) -> float:
        """Reflector retry pacing: the flat rewatch backoff, unless the
        server sent a 429 with a Retry-After hint — then sleep the
        hinted interval (jittered up to +25% so a fleet of informers
        doesn't re-arrive in lockstep, capped so a bogus hint can't
        park the cache for minutes)."""
        if isinstance(err, errors.GoneError):
            # 410 Gone: the server said the watch window is EXPIRED —
            # waiting cannot revive it, and every second of backoff is a
            # second the cache serves stale state. Re-list immediately
            # (the router's shard-death catchup path depends on this).
            return 0.0
        hint = getattr(err, "retry_after", None)
        if hint is None:
            return self.rewatch_backoff
        try:
            base = min(float(hint), self.retry_after_cap)
        except (TypeError, ValueError):
            return self.rewatch_backoff
        return max(self.rewatch_backoff, base * (1.0 + 0.25 * random.random()))

    # ------------------------------------------------------------ wiring

    def add_handler(self, handler: Handler) -> None:
        self._handlers.append(handler)
        # late subscribers see the existing cache as adds, as in client-go
        for obj in list(self.cache.values()):
            try:
                handler(ADDED, None, obj)
            except Exception:  # noqa: BLE001
                log.exception("informer %s: handler failed on replay", self.gvr)

    def add_indexer(self, name: str, fn: IndexFunc) -> None:
        self._indexers[name] = fn
        self._indices[name] = {}
        for key, obj in self.cache.items():
            self._index_insert(name, key, obj)

    def index(self, name: str, value: str) -> list[dict]:
        keys = self._indices.get(name, {}).get(value, set())
        return [self.cache[k] for k in keys if k in self.cache]

    # ------------------------------------------------------------- cache

    @staticmethod
    def _key(obj: dict) -> tuple[str, str, str]:
        m = obj["metadata"]
        return (m.get("clusterName", ""), m.get("namespace", ""), m["name"])

    def get(self, cluster: str, name: str, namespace: str = "") -> dict | None:
        return self.cache.get((cluster, namespace, name))

    def list(self) -> list[dict]:
        return list(self.cache.values())

    def _index_insert(self, iname: str, key, obj) -> None:
        for v in self._indexers[iname](obj):
            self._indices[iname].setdefault(v, set()).add(key)

    def _index_remove(self, iname: str, key, obj) -> None:
        for v in self._indexers[iname](obj):
            s = self._indices[iname].get(v)
            if s:
                s.discard(key)

    def _apply(self, etype: str, obj: dict) -> None:
        key = self._key(obj)
        old = self.cache.get(key)
        if etype == DELETED:
            if old is not None:
                del self.cache[key]
                for iname in self._indexers:
                    self._index_remove(iname, key, old)
            new = None
        else:
            self.cache[key] = obj
            for iname in self._indexers:
                if old is not None:
                    self._index_remove(iname, key, old)
                self._index_insert(iname, key, obj)
            new = obj
        self._notify(etype, old, new)

    def _notify(self, etype: str, old: dict | None, new: dict | None) -> None:
        # a throwing handler must not kill the pump task (and with it all
        # cache updates for every consumer of this informer)
        for h in self._handlers:
            try:
                h(etype, old, new)
            except Exception:  # noqa: BLE001
                log.exception("informer %s: handler failed on %s event", self.gvr, etype)

    # --------------------------------------------------------------- run

    async def start(self) -> None:
        """List, populate, open the watch, and start the pump task.

        In watch-list mode the list+watch is ONE stream: the server
        sends the current state as ADDED events, then the sync BOOKMARK
        that marks the cache consistent, and the same stream carries the
        live tail — the informer is synced without ever buffering a
        whole list response."""
        started = False
        if self._watch_list:
            started = await self._start_watch_list()
        if not started:
            items, rv = self.client.list(self.gvr, self.namespace,
                                         self.selector)
            for obj in items:
                self._apply(ADDED, obj)
            self._rv = max(self._rv, rv)
            self._watch = self.client.watch(
                self.gvr, self.namespace, self.selector, since_rv=rv
            )
        self._synced.set()
        self._task = asyncio.create_task(self._pump())
        if self.resync_period:
            self._resync_task = asyncio.create_task(self._resync_loop())

    async def _start_watch_list(self) -> bool:
        """Consume initial ADDED events until the server's
        initial-events-end BOOKMARK, then keep the very same stream as
        the live watch. False (with the partial state discarded by
        replace-semantics on the fallback list) on any refusal — an
        older server, a router wildcard — so start() degrades to
        classic list+watch."""
        try:
            w = self.client.watch(self.gvr, self.namespace, self.selector,
                                  initial_events=True)
        except Exception:  # noqa: BLE001 — client can't even build it
            log.warning("informer %s: watch-list unsupported; falling "
                        "back to list+watch", self.gvr, exc_info=True)
            return False
        try:
            async for ev in w:
                if ev.type == "BOOKMARK":
                    self._rv = max(self._rv, ev.rv,
                                   getattr(w, "last_rv", 0) or 0)
                    self._watch = w
                    REGISTRY.counter(
                        "informer_watch_list_starts_total",
                        "informer syncs served as one watch-list "
                        "stream (no whole-list buffering)").inc()
                    return True
                self._apply(ev.type, ev.object)
                if ev.rv:
                    self._rv = max(self._rv, ev.rv)
            # stream ended before the sync marker (refusal or drop)
        except Exception:  # noqa: BLE001 — server refused (400/410/...)
            pass
        log.warning("informer %s: watch-list start failed; falling back "
                    "to list+watch", self.gvr)
        w.close()
        return False

    async def _pump(self) -> None:
        """Dispatch watch events; on stream end, resume or re-list.

        The reflector loop of client-go: an in-process store Watch only
        ends when closed, but a REST watch ends on connection drop, an
        eviction, or an expired watch window (410). A dropped stream
        first tries a FAST RESUME — re-watch from the highest observed
        RV (events + absorbed bookmarks), no relist — so a reconnect
        storm of N informers costs N window resumes served from the
        store's shared watch-cache index, not N full lists. A 410 (the
        window really is gone, or we were evicted) or a fast resume that
        delivers nothing re-lists, exactly as before.
        """
        assert self._watch is not None
        delay = self.rewatch_backoff
        fast_budget = 1
        while True:
            delivered = 0
            err: BaseException | None = None
            try:
                async for ev in self._watch:
                    self._dispatch(ev)
                    if ev.rv:
                        self._rv = max(self._rv, ev.rv)
                    delivered += 1
                delay = self.rewatch_backoff
            except Exception as e:  # noqa: BLE001 — expired window / transport error
                err = e
                delay = self._retry_delay(err)
                log.warning("informer %s: watch failed; resuming in %.2fs",
                            self.gvr, delay, exc_info=True)
            # BOOKMARK progress markers advanced the stream's last_rv
            # without waking any handler — absorb them into the resume
            # point here, once, at stream end
            self._rv = max(self._rv, getattr(self._watch, "last_rv", 0) or 0)
            if self._stopping:
                return
            if delivered:
                fast_budget = 1
            use_fast = (fast_budget > 0 and self._rv > 0
                        and not isinstance(err, errors.GoneError))
            if err is not None or not (use_fast and delivered):
                await asyncio.sleep(delay)
            try:
                if use_fast:
                    # resume from where the stream left off: no relist,
                    # no cache churn — the server replays (since_rv, now]
                    # from its watch window or answers a 410 we turn
                    # into a relist on the next lap
                    fast_budget -= 1
                    self._watch = self.client.watch(
                        self.gvr, self.namespace, self.selector,
                        since_rv=self._rv)
                    REGISTRY.counter(
                        "informer_fast_resumes_total",
                        "dropped informer streams resumed from the last "
                        "observed RV without a relist").inc()
                else:
                    rv = self._relist()
                    self._rv = max(self._rv, rv)
                    self._watch = self.client.watch(
                        self.gvr, self.namespace, self.selector,
                        since_rv=rv)
                    fast_budget = 1
                delay = self.rewatch_backoff
            except Exception as err2:  # noqa: BLE001 — server down or shedding load
                # an overloaded frontend's 429 hint paces the next lap;
                # a 410 on the fast resume falls through to a relist
                if isinstance(err2, errors.GoneError):
                    fast_budget = 0
                delay = self._retry_delay(err2)
                log.warning("informer %s: %s failed; retrying in %.2fs",
                            self.gvr,
                            "fast resume" if use_fast else "re-list",
                            delay, exc_info=True)

    def _relist(self) -> int:
        """Fresh list reconciled against the cache (replace semantics)."""
        items, rv = self.client.list(self.gvr, self.namespace, self.selector)
        fresh = {self._key(o): o for o in items}
        for key, old in list(self.cache.items()):
            if key not in fresh:
                self._apply(DELETED, old)
        for key, obj in fresh.items():
            old = self.cache.get(key)
            if old is not None:
                old_rv = old["metadata"].get("resourceVersion")
                if old_rv is not None and old_rv == obj["metadata"].get("resourceVersion"):
                    # unchanged since the last observation: nothing was
                    # missed for this key, so skip the MODIFIED fan-out
                    # (a relist after a dropped stream would otherwise
                    # wake every controller for the whole cache)
                    self.cache[key] = obj
                    continue
            self._apply(MODIFIED if old is not None else ADDED, obj)
        return rv

    def _dispatch(self, ev: Event) -> None:
        self._apply(ev.type, ev.object)

    async def _resync_loop(self) -> None:
        while True:
            await asyncio.sleep(self.resync_period)
            self.resync()

    def resync(self) -> None:
        """Replay the cache as MODIFIED events (level-triggered safety net)."""
        for obj in list(self.cache.values()):
            self._notify(MODIFIED, obj, obj)

    async def wait_synced(self) -> None:
        await self._synced.wait()

    @property
    def synced(self) -> bool:
        return self._synced.is_set()

    async def stop(self) -> None:
        self._stopping = True
        for t in (self._task, self._resync_task):
            if t is not None:
                t.cancel()
                try:
                    await t
                except asyncio.CancelledError:
                    pass
        self._task = self._resync_task = None
        if self._watch is not None:
            self._watch.close()
            self._watch = None


class SharedInformerFactory:
    """One informer per GVR, shared across controllers.

    The analog of the reference's externalversions.SharedInformerFactory
    (generated; used at pkg/server/server.go:231-250).
    """

    def __init__(self, client: Client, resync_period: float | None = None):
        self.client = client
        self.resync_period = resync_period
        self._informers: dict[str, Informer] = {}

    def informer(self, gvr: GVR | str, selector: LabelSelector | None = None) -> Informer:
        key = str(gvr) + ("|" + str(selector) if selector and not selector.empty else "")
        if key not in self._informers:
            self._informers[key] = Informer(
                self.client, gvr, selector, resync_period=self.resync_period
            )
        return self._informers[key]

    async def start(self) -> None:
        await asyncio.gather(
            *(i.start() for i in self._informers.values() if not i.synced)
        )

    async def stop(self) -> None:
        await asyncio.gather(*(i.stop() for i in self._informers.values()))


async def run_informers(*informers: Informer) -> None:
    await asyncio.gather(*(i.start() for i in informers))


HandlerCoro = Callable[[str, dict | None, dict | None], Awaitable[None]]
