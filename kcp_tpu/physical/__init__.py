from .fake import FakeClusterAgent, PhysicalRegistry

__all__ = ["PhysicalRegistry", "FakeClusterAgent"]
