from .fake import ChurnDriver, FakeClusterAgent, PhysicalRegistry

__all__ = ["PhysicalRegistry", "FakeClusterAgent", "ChurnDriver"]
