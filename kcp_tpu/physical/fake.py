"""Fake physical clusters — the framework's kind-replacement.

The reference tests against real kind (Kubernetes-in-Docker) clusters
(contrib/demo/clusters/kind/). This framework ships an in-process
substitute so the whole multi-cluster story — registration, API import,
sync, placement — runs hermetically (SURVEY.md §4 implication):

- :class:`PhysicalRegistry` resolves a Cluster's ``spec.kubeconfig`` to a
  client. ``fake://<name>`` creates/returns an in-process store; anything
  else is resolved by pluggable factories (the REST client registers an
  ``https://`` factory).
- :class:`FakeClusterAgent` plays the part of the cluster's controllers:
  it marks Deployments ready (status counters follow spec.replicas), so
  pull-mode health checks and status upsync have something to observe.
"""

from __future__ import annotations

import asyncio
import logging
from typing import Callable

from ..apis.scheme import Scheme, default_scheme
from ..client import Client, Informer
from ..store.store import LogicalStore

log = logging.getLogger(__name__)

FAKE_PREFIX = "fake://"
PHYSICAL_CLUSTER_NAME = "physical"


class PhysicalRegistry:
    """kubeconfig string -> physical-cluster Client."""

    def __init__(self):
        self._fakes: dict[str, LogicalStore] = {}
        self._schemes: dict[str, Scheme] = {}
        self._factories: dict[str, Callable[[str], Client]] = {}

    def register_factory(self, scheme: str, factory: Callable[[str], Client]) -> None:
        self._factories[scheme] = factory

    def resolve(self, kubeconfig: str) -> Client:
        if not kubeconfig or not kubeconfig.strip():
            raise ValueError("empty kubeconfig")
        if kubeconfig.startswith(FAKE_PREFIX):
            name = kubeconfig[len(FAKE_PREFIX):]
            if not name:
                raise ValueError("fake:// kubeconfig needs a cluster name")
            store = self._fakes.get(name)
            if store is None:
                store = LogicalStore()
                self._fakes[name] = store
                self._schemes[name] = default_scheme()
            # every client resolved for one fake shares one scheme: a
            # physical cluster has ONE API surface, so a type a test
            # registers (e.g. a custom resource the importer should
            # discover) is visible to the controllers' clients too
            return Client(store, PHYSICAL_CLUSTER_NAME, self._schemes[name])
        scheme = kubeconfig.split("://", 1)[0] if "://" in kubeconfig else ""
        factory = self._factories.get(scheme)
        if factory is None:
            raise ValueError(f"unsupported kubeconfig {kubeconfig!r}")
        return factory(kubeconfig)

    def fake_store(self, name: str) -> LogicalStore | None:
        return self._fakes.get(name)


class FakeClusterAgent:
    """Simulates a physical cluster's deployment controller: any
    Deployment becomes fully ready shortly after creation/update."""

    def __init__(self, client: Client, delay: float = 0.0):
        self.client = client
        self.delay = delay
        self.informer = Informer(client, "deployments.apps")
        self._tasks: set[asyncio.Task] = set()
        self.informer.add_handler(self._on_event)

    def _on_event(self, etype: str, old: dict | None, new: dict | None) -> None:
        if etype == "DELETED" or new is None:
            return
        replicas = (new.get("spec") or {}).get("replicas", 0) or 0
        status = new.get("status") or {}
        if status.get("readyReplicas") == replicas and status.get("replicas") == replicas:
            return
        t = asyncio.get_event_loop().create_task(self._mark_ready(new, replicas))
        self._tasks.add(t)
        t.add_done_callback(self._tasks.discard)

    async def _mark_ready(self, obj: dict, replicas: int) -> None:
        if self.delay:
            await asyncio.sleep(self.delay)
        m = obj["metadata"]
        try:
            fresh = self.client.get("deployments.apps", m["name"], m.get("namespace", ""))
            fresh["status"] = {
                "replicas": replicas,
                "updatedReplicas": replicas,
                "readyReplicas": replicas,
                "availableReplicas": replicas,
                "unavailableReplicas": 0,
                "observedGeneration": fresh["metadata"].get("generation", 1),
                "conditions": [{"type": "Available", "status": "True",
                                "reason": "MinimumReplicasAvailable"}],
            }
            self.client.update_status("deployments.apps", fresh,
                                      namespace=m.get("namespace", ""))
        except Exception:  # noqa: BLE001 — object may be gone; agent is best-effort
            log.debug("fake agent: could not mark %s ready", m.get("name"))

    async def start(self) -> None:
        await self.informer.start()

    async def stop(self) -> None:
        for t in list(self._tasks):
            t.cancel()
        await self.informer.stop()
