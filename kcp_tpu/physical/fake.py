"""Fake physical clusters — the framework's kind-replacement.

The reference tests against real kind (Kubernetes-in-Docker) clusters
(contrib/demo/clusters/kind/). This framework ships an in-process
substitute so the whole multi-cluster story — registration, API import,
sync, placement — runs hermetically (SURVEY.md §4 implication):

- :class:`PhysicalRegistry` resolves a Cluster's ``spec.kubeconfig`` to a
  client. ``fake://<name>`` creates/returns an in-process store; anything
  else is resolved by pluggable factories (the REST client registers an
  ``https://`` factory).
- :class:`FakeClusterAgent` plays the part of the cluster's controllers:
  it marks Deployments ready (status counters follow spec.replicas), so
  pull-mode health checks and status upsync have something to observe.
"""

from __future__ import annotations

import asyncio
import logging
from typing import Callable

from ..apis.scheme import Scheme, default_scheme
from ..client import Client, Informer
from ..store.store import LogicalStore

log = logging.getLogger(__name__)

FAKE_PREFIX = "fake://"
PHYSICAL_CLUSTER_NAME = "physical"


class PhysicalRegistry:
    """kubeconfig string -> physical-cluster Client."""

    def __init__(self):
        self._fakes: dict[str, LogicalStore] = {}
        self._schemes: dict[str, Scheme] = {}
        self._factories: dict[str, Callable[[str], Client]] = {}

    def register_factory(self, scheme: str, factory: Callable[[str], Client]) -> None:
        self._factories[scheme] = factory

    def resolve(self, kubeconfig: str) -> Client:
        if not kubeconfig or not kubeconfig.strip():
            raise ValueError("empty kubeconfig")
        if kubeconfig.startswith(FAKE_PREFIX):
            name = kubeconfig[len(FAKE_PREFIX):]
            if not name:
                raise ValueError("fake:// kubeconfig needs a cluster name")
            store = self._fakes.get(name)
            if store is None:
                store = LogicalStore()
                self._fakes[name] = store
                self._schemes[name] = default_scheme()
            # every client resolved for one fake shares one scheme: a
            # physical cluster has ONE API surface, so a type a test
            # registers (e.g. a custom resource the importer should
            # discover) is visible to the controllers' clients too
            return Client(store, PHYSICAL_CLUSTER_NAME, self._schemes[name])
        scheme = kubeconfig.split("://", 1)[0] if "://" in kubeconfig else ""
        factory = self._factories.get(scheme)
        if factory is None:
            raise ValueError(f"unsupported kubeconfig {kubeconfig!r}")
        return factory(kubeconfig)

    def fake_store(self, name: str) -> LogicalStore | None:
        return self._fakes.get(name)


class ChurnDriver:
    """Seeded, replayable health/capacity churn over a fleet of fake
    pclusters — shared by the fleet scenarios and the unit tests instead
    of ad-hoc per-test condition flipping.

    The whole fleet story is a *pure function of the constructor
    arguments*: capacities are drawn once from a skewed lognormal,
    locality labels round-robin over ``regions``, and every Ready flap is
    precomputed as per-cluster NotReady intervals — same (seed, n, ticks,
    rates) ⇒ the same schedule bit-for-bit on any host, so a scenario
    scorecard names the seed and anyone can replay the run.

    Two kinds of outage drive the hysteresis story:

    - *flaps*: NotReady dips of ``flap_len`` ticks — shorter than the
      evacuation hysteresis window, so the inventory must ride through
      them with ZERO placement churn
    - *outages*: sustained NotReady of ``outage_len`` ticks — these must
      evacuate past the window and readmit on recovery

    ``capacity_churn`` additionally shrinks a cluster's allocatable to
    half its capacity for the duration of an outage-free "pressure"
    interval, exercising capacity-delta re-solves without a health edge.
    """

    def __init__(self, n: int, seed: int = 0, ticks: int = 64,
                 flap_rate: float = 0.05, flap_len: int = 1,
                 outage_rate: float = 0.008, outage_len: int = 12,
                 capacity_churn: float = 0.01,
                 base_capacity: int = 64, skew: float = 1.0,
                 regions: tuple[str, ...] = ("us-east", "us-west",
                                             "eu-west", "ap-south")):
        import numpy as np

        if ticks < 1 or n < 1:
            raise ValueError("ChurnDriver needs n >= 1, ticks >= 1")
        self.n, self.ticks, self.seed = n, ticks, seed
        rng = np.random.default_rng(seed)
        self.names = [f"pc-{i:04d}" for i in range(n)]
        # skewed capacity: a few big clusters, a long tail of small ones
        self.capacity = np.maximum(
            1, np.round(base_capacity * rng.lognormal(0.0, skew, n))
        ).astype(np.int64)
        self.region = [regions[int(r)] for r in rng.integers(0, len(regions), n)]
        down = np.zeros((ticks, n), dtype=bool)
        pressure = np.zeros((ticks, n), dtype=bool)
        flap_starts = rng.random((ticks, n)) < flap_rate
        outage_starts = rng.random((ticks, n)) < outage_rate
        pressure_starts = rng.random((ticks, n)) < capacity_churn
        for t in range(ticks):
            for starts, length, mask in ((flap_starts, flap_len, down),
                                         (outage_starts, outage_len, down),
                                         (pressure_starts, outage_len,
                                          pressure)):
                idx = starts[t].nonzero()[0]
                if idx.size:
                    mask[t:t + length, idx] = True
        self._down = down
        self._pressure = pressure

    # ------------------------------------------------------ pure queries

    def ready_at(self, tick: int) -> "list[bool]":
        """Per-cluster Ready at ``tick`` (ticks past the end = final
        state healed: everything Ready — scenarios settle there)."""
        if tick >= self.ticks:
            return [True] * self.n
        return (~self._down[tick]).tolist()

    def allocatable_at(self, tick: int) -> "list[int]":
        """Health-adjusted allocatable at ``tick`` (pressure halves it)."""
        caps = self.capacity.copy()
        if tick < self.ticks:
            caps[self._pressure[tick]] //= 2
        return caps.tolist()

    def transitions(self, tick: int) -> list[tuple[int, bool]]:
        """(cluster index, now-ready) edges between tick-1 and tick —
        tick 0 is measured against the all-Ready birth state."""
        now = self.ready_at(tick)
        prev = [True] * self.n if tick == 0 else self.ready_at(tick - 1)
        return [(i, now[i]) for i in range(self.n) if now[i] != prev[i]]

    def flap_count(self) -> int:
        import numpy as np

        edges = np.diff(self._down.astype(np.int8), axis=0)
        return int((edges != 0).sum() + self._down[0].sum())

    # --------------------------------------------- Cluster-API applicator

    def seed_fleet(self, client: Client) -> None:
        """Create the fleet's Cluster objects (capacity + locality set,
        all Ready) in ``client``'s logical cluster."""
        from ..apis import cluster as capi

        for i, name in enumerate(self.names):
            obj = capi.new_cluster(name, kubeconfig=f"fake://{name}")
            capi.set_capacity(obj, int(self.capacity[i]),
                              region=self.region[i])
            capi.set_ready(obj)
            client.create(capi.CLUSTERS, obj)

    def apply(self, client: Client, tick: int) -> int:
        """Write ``tick``'s health/capacity deltas onto the Cluster
        objects (delta-based: untouched clusters see no write). Returns
        the number of objects updated."""
        from ..apis import cluster as capi

        ready = self.ready_at(tick)
        alloc = self.allocatable_at(tick)
        prev_alloc = (self.allocatable_at(tick - 1) if tick > 0
                      else self.capacity.tolist())
        changed = {i for i, _ in self.transitions(tick)}
        changed.update(i for i in range(self.n)
                       if alloc[i] != prev_alloc[i])
        for i in sorted(changed):
            obj = client.get(capi.CLUSTERS, self.names[i])
            if ready[i]:
                capi.set_ready(obj)
            else:
                capi.set_not_ready(obj, capi.REASON_SYNCER_NOT_READY,
                                   "churn: heartbeat missed")
            obj.setdefault("status", {})["allocatable"] = {
                capi.CAPACITY_KEY: alloc[i]}
            client.update_status(capi.CLUSTERS, obj)
        return len(changed)


class FakeClusterAgent:
    """Simulates a physical cluster's deployment controller: any
    Deployment becomes fully ready shortly after creation/update."""

    def __init__(self, client: Client, delay: float = 0.0):
        self.client = client
        self.delay = delay
        self.informer = Informer(client, "deployments.apps")
        self._tasks: set[asyncio.Task] = set()
        self.informer.add_handler(self._on_event)

    def _on_event(self, etype: str, old: dict | None, new: dict | None) -> None:
        if etype == "DELETED" or new is None:
            return
        replicas = (new.get("spec") or {}).get("replicas", 0) or 0
        status = new.get("status") or {}
        if status.get("readyReplicas") == replicas and status.get("replicas") == replicas:
            return
        t = asyncio.get_event_loop().create_task(self._mark_ready(new, replicas))
        self._tasks.add(t)
        t.add_done_callback(self._tasks.discard)

    async def _mark_ready(self, obj: dict, replicas: int) -> None:
        if self.delay:
            await asyncio.sleep(self.delay)
        m = obj["metadata"]
        try:
            fresh = self.client.get("deployments.apps", m["name"], m.get("namespace", ""))
            fresh["status"] = {
                "replicas": replicas,
                "updatedReplicas": replicas,
                "readyReplicas": replicas,
                "availableReplicas": replicas,
                "unavailableReplicas": 0,
                "observedGeneration": fresh["metadata"].get("generation", 1),
                "conditions": [{"type": "Available", "status": "True",
                                "reason": "MinimumReplicasAvailable"}],
            }
            self.client.update_status("deployments.apps", fresh,
                                      namespace=m.get("namespace", ""))
        except Exception:  # noqa: BLE001 — object may be gone; agent is best-effort
            log.debug("fake agent: could not mark %s ready", m.get("name"))

    async def start(self) -> None:
        await self.informer.start()

    async def stop(self) -> None:
        for t in list(self._tasks):
            t.cancel()
        await self.informer.stop()
