"""Pull-mode pod emulation: run the installed syncer like a kubelet would.

In the reference's pull mode, installSyncer deploys a Pod into the
physical cluster whose container runs the standalone syncer binary with
``-from_kubeconfig /kcp/kubeconfig -cluster <name> <resources...>``
(pkg/reconciler/cluster/syncer.go:38-227; binary flags
cmd/syncer/main.go:17-28), and kubelet makes it run. There is no kubelet
against a fake physical cluster, so this module is the stand-in: it
reads the installed Deployment + ConfigMap back out of the physical
cluster, parses the container args exactly as the syncer binary would,
and starts the same in-process ``Syncer`` the standalone CLI runs.

Because it consumes the *installed manifests* — not the installer's
inputs — it keeps the manifests honest: an arg or mount drift between
installer and binary breaks the pull-mode tests, the same way it would
break a real pod.
"""

from __future__ import annotations

from typing import Callable

from ..client import Client
from ..syncer.syncer import Syncer, start_syncer
from ..utils import errors
from ..reconcilers.cluster.installer import SYNCER_NAME, SYNCER_NAMESPACE


class PodSpecError(Exception):
    """The installed manifests do not form a runnable syncer pod."""


def parse_installed_syncer(physical: Client) -> tuple[str, str, list[str], str]:
    """Read back (kcp_kubeconfig, cluster_name, resources, mesh_spec)
    from the installed Deployment + ConfigMap, the way the container
    would see them (kubeconfig via the volume mount, the rest via
    args)."""
    try:
        dep = physical.get("deployments.apps", SYNCER_NAME, SYNCER_NAMESPACE)
        cm = physical.get("configmaps", f"{SYNCER_NAME}-kubeconfig", SYNCER_NAMESPACE)
    except errors.NotFoundError as err:
        raise PodSpecError(f"syncer not installed: {err}") from err

    kubeconfig = (cm.get("data") or {}).get("kubeconfig")
    if not kubeconfig:
        raise PodSpecError("kubeconfig ConfigMap has no 'kubeconfig' key")

    containers = (((dep.get("spec") or {}).get("template") or {})
                  .get("spec") or {}).get("containers") or []
    if not containers:
        raise PodSpecError("syncer Deployment has no containers")
    args = list(containers[0].get("args") or [])

    # parse through the binary's OWN parser (kcp_tpu/cli/syncer.py) so
    # installer output, the deployed binary, and this emulator share one
    # argument surface — any drift fails here the way it would in a pod
    from ..cli.syncer import build_parser

    try:
        ns = build_parser(pod_form_only=True).parse_args(args)
    except SystemExit as err:  # argparse reports to stderr then exits
        raise PodSpecError(
            f"installed syncer args not parseable by the syncer binary: {args}"
        ) from err
    if not ns.from_kubeconfig:
        raise PodSpecError("no -from_kubeconfig arg in syncer Deployment")
    return kubeconfig, ns.cluster, list(ns.resources), getattr(ns, "mesh", "")


async def run_installed_syncer(
    physical: Client,
    resolve_kubeconfig: Callable[[str], Client],
    backend: str = "tpu",  # the deployed binary's default (cli/syncer.py)
) -> Syncer:
    """Start the syncer exactly as the installed pod would.

    ``resolve_kubeconfig`` turns the mounted kubeconfig content into a
    kcp upstream client (the fake-registry analog of client-go building
    a clientset from /kcp/kubeconfig).
    """
    kubeconfig, cluster, resources, mesh_spec = parse_installed_syncer(physical)
    upstream = resolve_kubeconfig(kubeconfig)
    mesh = None
    if mesh_spec:
        from ..parallel.mesh import mesh_from_spec

        mesh = mesh_from_spec(mesh_spec)
    # start_syncer, not Syncer: the pod's binary validates the resource
    # set via discovery first (RetryableError while a resource is not
    # served yet), and the emulator must fail the same way
    return await start_syncer(upstream, physical, resources, cluster,
                              backend=backend, mesh=mesh)
