"""Scenario engine: drive a spec against a real topology, score SLOs.

One scenario run = one topology brought up for real (ServerThreads over
HTTP), one seeded op schedule executed by writer threads, one observer
per (tenant, slot) holding a raw watch stream with the production
resume discipline, phases interleaving fault schedules
(``KCP_FAULTS``-seeded) and chaos actions (rolling restarts — graceful
vs kill —, primary SIGKILL, watcher storms, tenant floods), and one
scorecard: every declared SLO with its observed value, plus the raw
counts that justify it.

Determinism: the schedule is a pure function of (seed, spec) and its
hash rides the scorecard; faults use the seeded injector; actions fire
at fixed points in the phase sequence. Wall-clock measurements
(latencies) vary run to run — the SLOs bound them; the schedule and
the derived final-state expectation never vary.

``scenario.phase`` is a KCP_FAULTS injection point at every phase
boundary: ``latency`` stalls the transition, ``error`` aborts the run
— the harness's own failure path has a drill like everything else.
"""

from __future__ import annotations

import asyncio
import logging
import math
import os
import threading
import time

from .. import faults as faults_mod
from ..server.rest import RestClient
from ..utils import errors
from ..utils.trace import REGISTRY
from .spec import ScenarioSpec
from .topology import make_topology
from .workload import (
    NAMESPACE,
    RESOURCE,
    StreamObserver,
    WriterStats,
    build_schedule,
    expected_final_state,
    run_consistent_reader,
    run_flood,
    run_writer,
    schedule_hash,
    tenant_name,
)

log = logging.getLogger(__name__)

#: process-global counters whose per-run deltas scenarios assert on
TRACKED_COUNTERS = ("repl_promotions_total", "repl_rehome_total",
                    "router_rehome_total", "smart_client_direct_total",
                    "smart_client_fallback_total",
                    "smart_client_ring_refreshes_total",
                    "store_commit_windows_total",
                    "repl_ack_batched_total",
                    "migration_records_total",
                    "migration_fenced_writes_total",
                    "repl_fenced_writes_total",
                    "fault_injected_link_partition_total",
                    "fault_injected_link_delay_total",
                    "placement_resolves_total",
                    "placement_churn_total",
                    "cluster_evacuations_total",
                    "cluster_readmissions_total",
                    "consistent_read_waits_total",
                    "consistent_read_timeouts_total",
                    "router_replica_reads_total",
                    "router_replica_fallback_total")


def pctile(vals: list[float], q: float) -> float:
    if not vals:
        return 0.0
    s = sorted(vals)
    i = max(0, min(len(s) - 1, math.ceil(q * len(s)) - 1))
    return s[i]


# ---------------------------------------------------------------------------
# actions
# ---------------------------------------------------------------------------


def _topology_rss_kb(topology) -> int | None:
    """Resident-set size (kB) of the process actually serving the
    topology: the spawned child for a proc-mode Monolith, this process
    for in-thread topologies (RouterFleet / ReplicatedPrimary servers
    live in our ServerThreads). None when /proc isn't readable — the
    soak SLO then fails loudly as "never measured" instead of passing
    on a hole in the data."""
    child = getattr(topology, "_child", None)
    pid = child.pid if child is not None else os.getpid()
    try:
        with open(f"/proc/{pid}/status", encoding="ascii") as f:
            for line in f:
                if line.startswith("VmRSS:"):
                    return int(line.split()[1])
    except (OSError, ValueError, IndexError):
        return None
    return None


async def _run_action(action: str, topology, observers, loop) -> None:
    """Fire a phase's chaos action once the writers are under way."""
    await asyncio.sleep(0.25)
    if action in ("rolling_restart_drain", "rolling_restart_kill"):
        drain = action.endswith("drain")
        for i in range(len(topology.shards)):
            await loop.run_in_executor(None, topology.restart_shard, i,
                                       drain)
            await asyncio.sleep(0.3)
    elif action == "kill_primary":
        await loop.run_in_executor(None, topology.kill_primary)
    elif action == "move_shard":
        # the ring change: drain a live-workload shard, restart it on a
        # NEW address, republish /ring — smart clients must absorb the
        # move with one-shot fallbacks, routed clients with retries
        await loop.run_in_executor(None, topology.move_shard)
    elif action == "scale_out":
        # elastic capacity: grow the fleet by one shard LIVE — the
        # grown ring publishes with movers pinned, each pinned
        # cluster's WAL streams to the new owner, ownership flips
        # atomically per cluster; writers eat fence-503 retries and
        # watchers ride typed 410 relists, never a lost acked write
        await loop.run_in_executor(None, topology.scale_out)
    elif action == "drop_watchers":
        # the reconnect storm: EVERY stream severed in the same instant,
        # every observer resumes from its last_rv at once
        for obs in observers:
            obs.drop()
    else:
        raise ValueError(f"unknown scenario action {action!r}")


# ---------------------------------------------------------------------------
# CRD / schema-negotiation workload
# ---------------------------------------------------------------------------


def run_crd_tenant(base_url: str, tenant: str, ops, phase_idx: int,
                   stats: WriterStats, shared: dict) -> None:
    """One tenant's CRD lifecycle slice (blocking worker thread).

    Even phases: create the tenant's CRD, measure create→servable
    latency (the schema-negotiation convergence the BASELINE config
    lanes care about), then churn CRs. Odd phases: update the CRD
    schema (negotiation churn), churn more CRs, verify the fold, then
    tear the CRD down and measure create→404 teardown latency. The
    lifecycle is a 2-beat cycle, so a soak run repeating the
    establish/negotiate block N times runs N full lifecycles."""
    from ..apis import crd as crdapi

    group = f"{tenant}.scenario.kcp.dev"
    resource = f"widgets.{group}"
    api_version = f"{group}/v1"
    c = RestClient(base_url, cluster=tenant)

    def cr(name: str, step: int) -> dict:
        return {"apiVersion": api_version, "kind": "Widget",
                "metadata": {"name": name, "namespace": NAMESPACE,
                             "clusterName": tenant},
                "spec": {"v": step}}

    try:
        if phase_idx % 2 == 0:
            crd = crdapi.new_crd(group, "v1", "widgets", "Widget")
            crd["metadata"]["clusterName"] = tenant
            t0 = time.monotonic()
            c.create("customresourcedefinitions.apiextensions.k8s.io", crd)
            # establishment poll: the resource is servable once the CRD
            # lifecycle controller registered it into the serving scheme
            deadline = time.monotonic() + 30.0
            while True:
                try:
                    c.create(resource, cr(f"{tenant}-canary", 0))
                    break
                except errors.NotFoundError:
                    if time.monotonic() > deadline:
                        stats.note("gave_up")
                        return
                    time.sleep(0.05)
            with stats._lock:
                shared.setdefault("servable_s", []).append(
                    time.monotonic() - t0)
            c.delete(resource, f"{tenant}-canary", NAMESPACE)
        else:
            # negotiation churn: widen the schema; serving must not blip
            got = c.get("customresourcedefinitions.apiextensions.k8s.io",
                        f"widgets.{group}")
            got["spec"]["versions"][0]["schema"] = {"openAPIV3Schema": {
                "type": "object",
                "properties": {"spec": {"type": "object"}}}}
            c.update("customresourcedefinitions.apiextensions.k8s.io", got)
        live: set[str] = set(shared.setdefault(("live", tenant), set()))
        for op in ops:
            deadline = time.monotonic() + 20.0
            while True:
                try:
                    if op.kind == "create":
                        c.create(resource, cr(op.name, op.step))
                        live.add(op.name)
                    elif op.kind == "update":
                        c.update(resource, cr(op.name, op.step))
                    else:
                        c.delete(resource, op.name, NAMESPACE)
                        live.discard(op.name)
                    stats.ack(tenant, op.name, 0, op.kind)
                    break
                except (errors.UnavailableError, ConnectionError,
                        OSError):
                    stats.note("http_5xx")
                    if time.monotonic() > deadline:
                        stats.note("gave_up")
                        break
                    time.sleep(0.05)
        with stats._lock:
            shared[("live", tenant)] = live
        if phase_idx % 2 == 1:
            # verify the fold against the server BEFORE teardown
            items, _rv = c.list(resource, NAMESPACE)
            have = {o["metadata"]["name"] for o in items}
            lost = len(live - have) + len(have - live)
            with stats._lock:
                shared["cr_lost"] = shared.get("cr_lost", 0) + lost
            # teardown: reap the surviving CRs first (the store does
            # not GC CR objects with their CRD — a later lifecycle
            # recreating the CRD would resurrect them into its fold),
            # then delete the CRD; the endpoint must 404 promptly
            for name in live:
                try:
                    c.delete(resource, name, NAMESPACE)
                except errors.ApiError:
                    pass
            t0 = time.monotonic()
            c.delete("customresourcedefinitions.apiextensions.k8s.io",
                     f"widgets.{group}", "")
            # the CRs died with the CRD: reset the fold so a soak's
            # next lifecycle starts from an honest empty ledger
            with stats._lock:
                shared[("live", tenant)] = set()
            deadline = time.monotonic() + 30.0
            while time.monotonic() < deadline:
                try:
                    c.list(resource, NAMESPACE)
                    time.sleep(0.05)
                except errors.NotFoundError:
                    with stats._lock:
                        shared.setdefault("teardown_s", []).append(
                            time.monotonic() - t0)
                    break
    finally:
        c.close()


# ---------------------------------------------------------------------------
# the run
# ---------------------------------------------------------------------------


async def _drive(sspec: ScenarioSpec, seed: int, schedule, topology,
                 stats: WriterStats, measurements: dict) -> list:
    loop = asyncio.get_running_loop()
    # the default loop executor (cpu+4 threads) is sized for nothing:
    # every tenant writer occupies a thread for a whole phase, and
    # observer relists queue BEHIND them — at storm scale that turns a
    # reconnect into a phase-long stall. Size for writers + relist slack.
    from concurrent.futures import ThreadPoolExecutor

    loop.set_default_executor(ThreadPoolExecutor(
        max_workers=sspec.tenants + 32, thread_name_prefix="scenario-io"))
    base = topology.client_url
    observers: list[StreamObserver] = []
    if sspec.workload == "configmaps" and sspec.watchers_per_tenant:
        for ti in range(sspec.tenants):
            for _ in range(sspec.watchers_per_tenant):
                observers.append(StreamObserver(base, tenant_name(ti)))
        await asyncio.gather(*(o.start() for o in observers))
    pace = float(sspec.options.get("pace_s", 0.0))
    try:
        for phase_idx, phase in enumerate(sspec.phases):
            delay = faults_mod.maybe_fail("scenario.phase")
            if delay:
                await asyncio.sleep(delay)
            inj = None
            if phase.faults:
                # WAN-link specs name peers by ROLE ({primary}, ...);
                # the topology knows the host:port each role landed on
                fspec = phase.faults
                if "{" in fspec and hasattr(topology, "peer_addrs"):
                    for role, addr in topology.peer_addrs().items():
                        fspec = fspec.replace("{" + role + "}", addr)
                inj = faults_mod.FaultInjector(fspec, seed)
                faults_mod.install(inj)
            try:
                writer_futs = []
                if sspec.workload == "crd":
                    shared = measurements.setdefault("_crd", {})
                    for ti, ops in enumerate(schedule[phase.name]):
                        writer_futs.append(loop.run_in_executor(
                            None, run_crd_tenant, base, tenant_name(ti),
                            ops, phase_idx, stats, shared))
                elif sspec.workload == "fleet":
                    from .fleetload import run_fleet_phase

                    shared = measurements.setdefault("_fleet", {})
                    writer_futs.append(loop.run_in_executor(
                        None, run_fleet_phase, base, phase.name, sspec,
                        seed, shared))
                elif sspec.workload == "placement":
                    from .fleetload import run_placement_phase

                    shared = measurements.setdefault("_placement", {})
                    writer_futs.append(loop.run_in_executor(
                        None, run_placement_phase, phase.name, sspec,
                        seed, shared))
                else:
                    # smart_half: even-index tenants write DIRECT over
                    # the ring (SmartRestClient), odd ones stay routed —
                    # the same seeded schedule through both paths.
                    # smart_all: every tenant direct — the gauntlet's
                    # default driver shape (smart clients are the
                    # production common case since the router-hop cut).
                    smart_half = bool(sspec.options.get("smart_half"))
                    smart_all = bool(sspec.options.get("smart_all"))
                    for ti, ops in enumerate(schedule[phase.name]):
                        if ops:
                            writer_futs.append(loop.run_in_executor(
                                None, run_writer, base, tenant_name(ti),
                                ops, stats, phase.name, "quiet", 30.0,
                                pace,
                                smart_all or (smart_half and ti % 2 == 0)))
                reader_futs = []
                reader_stop = threading.Event()
                if sspec.options.get("consistent_readers"):
                    # session-consistency probers ride alongside the
                    # writers: every read pins the tenant's own max
                    # acked RV — a lagging replica must park, fall
                    # back, or refuse, never answer below the floor
                    shared = measurements.setdefault("_consistent", {
                        "_lock": threading.Lock(), "consistent_reads": 0,
                        "stale_consistent_reads": 0,
                        "consistent_read_errors": 0})
                    for ti in range(sspec.tenants):
                        reader_futs.append(loop.run_in_executor(
                            None, run_consistent_reader, base,
                            tenant_name(ti), stats, shared, reader_stop))
                flood_fut = None
                if phase.action == "flood":
                    flood_fut = loop.run_in_executor(
                        None, run_flood, base, "flood",
                        int(sspec.options.get("flood_ops", 300)), stats)
                action_fut = None
                if phase.action and phase.action != "flood":
                    action_fut = asyncio.ensure_future(
                        _run_action(phase.action, topology, observers,
                                    loop))
                if writer_futs:
                    await asyncio.gather(*writer_futs)
                if flood_fut is not None:
                    ok, throttled = await flood_fut
                    measurements["flood_ok"] = ok
                    measurements["flood_429"] = throttled
                if action_fut is not None:
                    await action_fut
                if reader_futs:
                    reader_stop.set()
                    await asyncio.gather(*reader_futs)
            finally:
                reader_stop.set()
                if inj is not None:
                    faults_mod.clear()
            traces = await loop.run_in_executor(
                None, _fetch_slowest_traces, base)
            if traces:
                measurements.setdefault("_traces", {})[phase.name] = traces
            # soak accounting: RSS at every phase boundary, so a run's
            # scorecard shows WHERE memory went, not just that it grew
            rss = _topology_rss_kb(topology)
            if rss is not None:
                measurements.setdefault("_rss", {})[phase.name] = rss
            if phase.settle_s:
                await asyncio.sleep(phase.settle_s)
        # coverage settle: give observers time to catch up with every
        # acked (name, rv) before we freeze the loss accounting
        if observers:
            await _await_coverage(stats, observers, timeout=float(
                sspec.options.get("coverage_timeout_s", 15.0)))
    finally:
        for o in observers:
            await o.stop()
    return observers


def _fetch_slowest_traces(base_url: str, n: int = 3) -> list[dict]:
    """The 3 slowest assembled traces at a phase boundary, compacted for
    the scorecard (kcp_tpu/obs): an SLO breach in SCENARIOS_rNN.json
    ships with its own explanation. On a router topology the endpoint
    scatter-gathers every shard's buffer; best-effort — a topology mid-
    chaos may refuse, and the scorecard then simply has no trace."""
    from .. import obs
    from ..obs import assemble

    if not base_url or not obs.TRACER.enabled:
        return []
    client = RestClient(base_url)
    try:
        body = client._request("GET", f"/debug/trace?slowest={n}") or {}
    except (errors.ApiError, ConnectionError, OSError):
        return []
    finally:
        client.close()
    out = []
    for t in body.get("traces", [])[:n]:
        summary = assemble.summarize_trace(t.get("spans", []), t.get("id"))
        if summary:
            out.append(summary)
    return out


def _acked_by_tenant(stats: WriterStats) -> dict[str, set]:
    by_tenant: dict[str, set] = {}
    with stats._lock:
        acks = list(stats.acks)
    for tenant, name, rv, kind, _t in acks:
        if kind != "delete" and rv:
            by_tenant.setdefault(tenant, set()).add((name, rv))
    return by_tenant


async def _await_coverage(stats: WriterStats,
                          observers: list[StreamObserver],
                          timeout: float) -> None:
    want = _acked_by_tenant(stats)
    deadline = asyncio.get_running_loop().time() + timeout
    while asyncio.get_running_loop().time() < deadline:
        missing = 0
        for obs in observers:
            need = want.get(obs.tenant)
            if need:
                ev = obs.stats.events
                # membership probes against the live dict — at 10k
                # observers, rebuilding a set per observer per lap was
                # the coverage check's own hot loop
                missing += sum(1 for k in need if k not in ev)
        if missing == 0:
            return
        await asyncio.sleep(0.1)


def _verify_final_state(base: str, sspec: ScenarioSpec, expect,
                        measurements: dict) -> None:
    lost = 0
    for ti in range(sspec.tenants):
        tenant = tenant_name(ti)
        names = expect[tenant]
        c = RestClient(base, cluster=tenant)
        try:
            for attempt in range(40):
                try:
                    items, _rv = c.list(RESOURCE, NAMESPACE)
                    break
                except (errors.ApiError, ConnectionError, OSError):
                    if attempt == 39:
                        raise
                    time.sleep(0.25)
            have = {o["metadata"]["name"] for o in items}
        finally:
            c.close()
        # both directions: an acked create/update missing is a lost
        # write; an acked delete still present is a lost delete
        lost += len(names - have) + len(have - names)
    measurements["lost_acked_writes"] = lost


def _collect(sspec: ScenarioSpec, stats: WriterStats, observers,
             measurements: dict, counters_before: dict,
             duration_s: float) -> dict:
    want = _acked_by_tenant(stats)
    lost_events = 0
    for obs in observers:
        need = want.get(obs.tenant)
        if need:
            ev = obs.stats.events
            lost_events += sum(1 for k in need if k not in ev)
    conv: list[float] = []
    obs_by_tenant: dict[str, list[StreamObserver]] = {}
    for obs in observers:
        obs_by_tenant.setdefault(obs.tenant, []).append(obs)
    with stats._lock:
        acks = list(stats.acks)
        lat = {ph: {k: list(v) for k, v in kl.items()}
               for ph, kl in stats.latencies.items()}
    for tenant, name, rv, kind, t_ack in acks:
        if kind == "delete" or not rv:
            continue
        for obs in obs_by_tenant.get(tenant, ()):
            t_obs = obs.stats.events.get((name, rv))
            if t_obs is not None:
                conv.append(max(0.0, t_obs - t_ack))
    m = measurements
    m["acked"] = len(acks)
    m["events_observed"] = sum(len(o.stats.events) for o in observers)
    m["lost_watch_events"] = lost_events
    m["unclean_stream_ends"] = sum(o.stats.unclean_ends
                                   for o in observers)
    m["terminal_statuses"] = sum(o.stats.terminal_statuses
                                 for o in observers)
    m["gone_410"] = sum(o.stats.gone_410 for o in observers)
    m["relists"] = sum(o.stats.relists for o in observers)
    m["reconnects"] = sum(o.stats.reconnects for o in observers)
    if observers:
        resumes = [s for o in observers for s in o.stats.resume_s]
        # drop→first-event latency across the whole storm; 0.0 when no
        # deliberate drops happened (the paired `reconnects` SLO guards
        # a vacuous pass)
        m["resume_p99_ms"] = round(pctile(resumes, 0.99) * 1000, 3)
        m["resume_p50_ms"] = round(pctile(resumes, 0.50) * 1000, 3)
    m["p50_convergence_ms"] = round(pctile(conv, 0.50) * 1000, 3)
    m["p99_convergence_ms"] = round(pctile(conv, 0.99) * 1000, 3)
    m["http_5xx"] = stats.http_5xx
    m["quiet_429"] = stats.http_429
    m["ambiguous_acks"] = stats.ambiguous
    m["gave_up"] = stats.gave_up
    m["duration_s"] = round(duration_s, 3)
    if duration_s > 0:
        m["acked_per_sec"] = round(len(acks) / duration_s, 3)
    # soak memory SLO inputs: per-phase RSS plus last/first growth
    # ratio. Deliberately ABSENT (not defaulted) when sampling failed:
    # a scenario declaring `memory_growth_ratio` then scores
    # "metric never measured" and fails — per the no-silent-holes
    # discipline, an unmeasured SLO is a failing SLO.
    rss = m.pop("_rss", None)
    if rss:
        m["rss_kb_per_phase"] = dict(rss)
        first = next(iter(rss.values()))
        last = list(rss.values())[-1]
        if first > 0:
            m["memory_growth_ratio"] = round(last / first, 3)
    # per-phase writer p99: what a client-visible op cost during each
    # phase — the ring-change scenario bounds the fallback window's
    # (`phase_move_p99_ms`) so "the move was absorbed" is a latency
    # claim, not just a zero-loss claim
    for ph, klasses in lat.items():
        quiet = klasses.get("quiet", [])
        if quiet:
            m[f"phase_{ph}_p99_ms"] = round(
                pctile(quiet, 0.99) * 1000, 3)
    # noisy-neighbor ratio: quiet p99 during the storm phase vs baseline
    base_lat = lat.get("baseline", {}).get("quiet", [])
    storm_lat = lat.get("storm", {}).get("quiet", [])
    if base_lat and storm_lat:
        b99 = max(pctile(base_lat, 0.99), 1e-6)
        m["quiet_p99_ratio"] = round(pctile(storm_lat, 0.99) / b99, 3)
    # CRD workload measurements
    crd = m.pop("_crd", None)
    if crd is not None:
        m["crd_servable_p99_ms"] = round(
            pctile(crd.get("servable_s", []), 0.99) * 1000, 3)
        m["crd_teardown_p99_ms"] = round(
            pctile(crd.get("teardown_s", []), 0.99) * 1000, 3)
        m["crd_established"] = len(crd.get("servable_s", []))
        m["crd_torn_down"] = len(crd.get("teardown_s", []))
        # one establish per even phase, one teardown per odd phase —
        # a soak run's repeated lifecycle multiplies the expectation
        up_beats = (len(sspec.phases) + 1) // 2
        down_beats = len(sspec.phases) // 2
        m["crd_unestablished"] = (sspec.tenants * up_beats
                                  - m["crd_established"])
        m["crd_undestroyed"] = (sspec.tenants * down_beats
                                - m["crd_torn_down"])
        m["lost_acked_writes"] = crd.get("cr_lost", 0)
    # fleet/placement workload measurements: the driver's shared dict
    # holds scratch state (_-prefixed) AND final numbers — fold only
    # the numbers, under their final metric names
    for key in ("_fleet", "_placement", "_consistent"):
        drv_shared = m.pop(key, None)
        if drv_shared is not None:
            m.update({k: v for k, v in drv_shared.items()
                      if not k.startswith("_")
                      and isinstance(v, (int, float))})
    for name in TRACKED_COUNTERS:
        short = name[:-len("_total")]
        m[short] = REGISTRY.counter(name).value - counters_before[name]
    return m


def _run_pass(sspec: ScenarioSpec, seed: int, schedule, workdir: str
              ) -> dict:
    """One full workload execution on a fresh topology; returns the
    measurement dict."""
    measurements: dict = {}
    stats = WriterStats()
    counters_before = {n: REGISTRY.counter(n).value
                       for n in TRACKED_COUNTERS}
    topology = make_topology(sspec, workdir)
    t0 = time.monotonic()
    observers: list = []
    try:
        topology.start()
        observers = asyncio.run(
            _drive(sspec, seed, schedule, topology, stats, measurements))
        if hasattr(topology, "audit"):
            # post-run replication facts (exactly-one-writable-primary,
            # fencing landed, follower lag drained) — the partition and
            # WAN-lag drills' SLOs key on these
            measurements.update(topology.audit())
        if sspec.workload == "configmaps":
            _verify_final_state(topology.client_url, sspec,
                                expected_final_state(schedule, sspec),
                                measurements)
    finally:
        faults_mod.clear()
        topology.stop()
    return _collect(sspec, stats, observers, measurements,
                    counters_before, time.monotonic() - t0)


def run_scenario(spec: ScenarioSpec, seed: int = 42, scale: float = 1.0,
                 workdir: str = "/tmp/kcp-scenarios") -> dict:
    """Run one scenario end to end; returns its scorecard entry."""
    import os

    sspec = spec.scaled(scale)
    schedule = build_schedule(seed, sspec)
    shash = schedule_hash(seed, sspec, schedule)
    wd = os.path.join(workdir, f"{sspec.name}-{seed}")
    os.makedirs(wd, exist_ok=True)
    log.info("scenario %s: seed=%d scale=%s hash=%s", sspec.name, seed,
             scale, shash)
    result: dict = {
        "name": sspec.name, "description": sspec.description,
        "seed": seed, "scale": scale, "topology": sspec.topology,
        "tenants": sspec.tenants,
        "schedule": {
            "hash": shash,
            "ops": sum(len(ops) for tenants in schedule.values()
                       for ops in tenants),
            "phases": [{"name": p.name, "ops_per_tenant": p.ops_per_tenant,
                        "faults": p.faults, "action": p.action}
                       for p in sspec.phases],
        },
    }
    try:
        measurements = _run_pass(sspec, seed, schedule, wd)
    except (faults_mod.InjectedFault, errors.ApiError) as e:
        # an injected scenario.phase abort (or an unrecoverable engine
        # refusal): the scenario fails loudly with the cause on record
        result["passed"] = False
        result["aborted"] = f"{type(e).__name__}: {e}"
        result["slos"] = []
        return result
    if sspec.options.get("compare_kill"):
        # the drain-vs-kill demonstration: the same workload on a fresh
        # fleet with graceful drain BYPASSED — the violations the drain
        # pass must not show are recorded (and asserted present via the
        # bypass_* metrics)
        bypass_spec = _bypass_kill_spec(sspec)
        bypass_sched = build_schedule(seed + 1, bypass_spec)
        try:
            bypass = _run_pass(bypass_spec, seed + 1, bypass_sched,
                               wd + "-kill")
        except (faults_mod.InjectedFault, errors.ApiError) as e:
            bypass = {"aborted": f"{type(e).__name__}: {e}",
                      "unclean_stream_ends": 0}
        result["drain_bypassed"] = {
            k: bypass.get(k) for k in (
                "unclean_stream_ends", "lost_watch_events", "gone_410",
                "lost_acked_writes", "terminal_statuses", "http_5xx",
                "aborted") if k in bypass}
        measurements["bypass_unclean_ends"] = bypass.get(
            "unclean_stream_ends", 0)
        measurements["bypass_stream_breaches"] = (
            bypass.get("unclean_stream_ends", 0)
            + bypass.get("gone_410", 0)
            + bypass.get("lost_watch_events", 0))
    slo_rows = []
    passed = True
    for slo in sspec.slos:
        if slo.metric not in measurements:
            slo_rows.append({"name": slo.name, "metric": slo.metric,
                             "op": slo.op, "target": slo.target,
                             "observed": None, "passed": False,
                             "error": "metric never measured"})
            passed = False
            continue
        observed = measurements[slo.metric]
        ok = slo.check(observed)
        passed = passed and ok
        slo_rows.append({"name": slo.name, "metric": slo.metric,
                         "op": slo.op, "target": slo.target,
                         "observed": observed, "passed": ok})
    traces = measurements.get("_traces")
    if traces:
        # the 3 slowest assembled convergence traces per phase: the
        # scorecard's own explanation for any latency SLO it reports
        result["traces"] = traces
    result["measurements"] = {k: v for k, v in measurements.items()
                              if not k.startswith("_")}
    result["slos"] = slo_rows
    result["passed"] = passed
    return result


def _bypass_kill_spec(sspec: ScenarioSpec):
    import dataclasses

    phases = tuple(
        dataclasses.replace(p, action="rolling_restart_kill")
        if p.action == "rolling_restart_drain" else p
        for p in sspec.phases)
    options = {k: v for k, v in sspec.options.items()
               if k != "compare_kill"}
    return dataclasses.replace(sspec, name=sspec.name + "-kill",
                               phases=phases, options=options)
