"""The named scenarios: the repo's end-to-end acceptance suite.

Each entry composes subsystems PRs 1–9 shipped individually — CRUD
serving + encode-once lists, watch fan-out + informer discipline,
admission/flow control, the shard router, WAL replication + promotion,
graceful drain — into one declared-SLO workload. ``scripts/scenarios.py
run --all --seed N`` runs them all and emits one JSON scorecard;
``scripts/ci.sh`` gates a reduced-scale subset.

SLO targets are deliberately scale-independent (ScenarioSpec.scaled
never touches them): an objective that only holds at toy scale is not
an objective. Latency bounds leave headroom for loaded CI hosts —
regressions they exist to catch (lost events, lost writes, unthrottled
floods, silent stream deaths) are step functions, not millisecond
drift.
"""

from __future__ import annotations

from .spec import SLO, Phase, ScenarioSpec

CRUD_CHURN = ScenarioSpec(
    name="crud-churn",
    description="N-tenant CRUD churn under a watcher fleet: the "
                "bread-and-butter lane — every ack converges to every "
                "stream, nothing is lost, nothing 5xxes.",
    topology="monolith",
    tenants=8,
    watchers_per_tenant=2,
    phases=(Phase("warm", ops_per_tenant=20),
            Phase("churn", ops_per_tenant=60)),
    slos=(
        SLO("convergence", "p99_convergence_ms", "<=", 400.0),
        SLO("no-lost-acked-writes", "lost_acked_writes", "==", 0),
        SLO("no-lost-watch-events", "lost_watch_events", "==", 0),
        SLO("no-unclean-stream-deaths", "unclean_stream_ends", "==", 0),
        SLO("error-budget-5xx", "http_5xx", "==", 0),
    ),
)

NOISY_NEIGHBOR = ScenarioSpec(
    name="noisy-neighbor",
    description="One tenant floods writes at many times its token rate "
                "while quiet tenants keep working: flow control must "
                "throttle the flood (429 + Retry-After) and keep the "
                "quiet tenants' p99 within a declared ratio of their "
                "no-storm baseline.",
    topology="monolith",
    tenants=6,
    watchers_per_tenant=1,
    env={"KCP_FLOW_RATE": "80", "KCP_FLOW_BURST": "40"},
    phases=(Phase("baseline", ops_per_tenant=40),
            Phase("storm", ops_per_tenant=40, action="flood")),
    options={"flood_ops": 600, "pace_s": 0.02},
    slos=(
        SLO("quiet-tenant-p99-ratio", "quiet_p99_ratio", "<=", 3.0),
        SLO("no-quiet-throttling", "quiet_429", "==", 0),
        SLO("flood-throttled", "flood_429", ">=", 1),
        SLO("no-lost-acked-writes", "lost_acked_writes", "==", 0),
        SLO("no-lost-watch-events", "lost_watch_events", "==", 0),
    ),
)

RECONNECT_STORM = ScenarioSpec(
    name="reconnect-storm",
    description="10,000 watch streams severed in the same instant while "
                "writes continue; every observer resumes from its last "
                "RV at once against one server. The shared watch-cache "
                "window must absorb the storm: zero lost events, zero "
                "unrecoverable (410) resumes, and drop-to-first-event "
                "resume latency bounded at p99. Runs against a real "
                "server SUBPROCESS so the 10k-stream fd bill is split "
                "across processes (scale 1.0 needs ~10k fds per side).",
    topology="monolith",
    topology_args={"proc": True},
    tenants=20,
    watchers_per_tenant=500,
    phases=(Phase("warm", ops_per_tenant=15),
            Phase("storm", ops_per_tenant=40, action="drop_watchers",
                  settle_s=1.0),
            Phase("recover", ops_per_tenant=15, settle_s=1.0)),
    options={"pace_s": 0.01, "coverage_timeout_s": 120.0},
    slos=(
        SLO("no-lost-watch-events", "lost_watch_events", "==", 0),
        SLO("no-unrecoverable-resumes", "gone_410", "==", 0),
        SLO("storm-happened", "reconnects", ">=", 1),
        # the bound is the 1-cpu host reality: re-establishing 10k TCP
        # streams serializes on one accept loop (~500 conns/s), so the
        # herd converges together near the tail; the SLOs exist to catch
        # step-function regressions (lost events, 410 storms, resumes
        # that relist), not millisecond drift
        SLO("resume-latency", "resume_p99_ms", "<=", 30000.0),
        SLO("convergence", "p99_convergence_ms", "<=", 30000.0),
        SLO("no-lost-acked-writes", "lost_acked_writes", "==", 0),
        SLO("error-budget-5xx", "http_5xx", "==", 0),
        # soak memory: server RSS at the last phase boundary vs the
        # first. 10k resumes each relisting the world is exactly where
        # unpaged list bodies balloon; paged relists keep this flat.
        # Declared (not best-effort) so a run where RSS sampling broke
        # FAILS as "metric never measured" instead of passing blind.
        SLO("bounded-rss-growth", "memory_growth_ratio", "<=", 3.0),
    ),
)

ROLLING_RESTART = ScenarioSpec(
    name="rolling-restart",
    description="A durable shard fleet behind the router restarted one "
                "shard at a time USING GRACEFUL DRAIN, under live "
                "writes and watches: zero lost acked writes, zero lost "
                "watch events, every stream ended by a terminal Status. "
                "The same workload re-runs with drain bypassed (kill) "
                "and must demonstrate the breach drain prevents.",
    topology="fleet",
    topology_args={"shards": 2},
    tenants=6,
    watchers_per_tenant=2,
    phases=(Phase("warm", ops_per_tenant=20),
            Phase("restart", ops_per_tenant=90,
                  action="rolling_restart_drain", settle_s=1.0)),
    options={"pace_s": 0.02, "compare_kill": True,
             "coverage_timeout_s": 25.0},
    slos=(
        SLO("no-lost-acked-writes", "lost_acked_writes", "==", 0),
        SLO("no-lost-watch-events", "lost_watch_events", "==", 0),
        SLO("no-unclean-stream-deaths", "unclean_stream_ends", "==", 0),
        SLO("drain-terminated-streams", "terminal_statuses", ">=", 1),
        SLO("error-budget-5xx", "http_5xx", "<=", 400),
        SLO("kill-bypass-breaches", "bypass_stream_breaches", ">=", 1),
    ),
)

KILL_PRIMARY = ScenarioSpec(
    name="kill-primary",
    description="SIGKILL the primary mid-workload behind a router with "
                "standby + replica: the standby promotes, the replica "
                "re-homes its feed onto the promoted standby, the "
                "router re-routes writes to it — no manual restarts, "
                "zero acked writes lost.",
    topology="replicated",
    tenants=5,
    watchers_per_tenant=2,
    phases=(Phase("warm", ops_per_tenant=25),
            Phase("failover", ops_per_tenant=80, action="kill_primary",
                  faults="repl.ship:latency=2ms", settle_s=1.5),
            Phase("recovered", ops_per_tenant=25, settle_s=1.0)),
    options={"pace_s": 0.02, "coverage_timeout_s": 30.0},
    slos=(
        SLO("no-lost-acked-writes", "lost_acked_writes", "==", 0),
        SLO("standby-promoted", "repl_promotions", ">=", 1),
        SLO("replica-rehomed", "repl_rehome", ">=", 1),
        SLO("router-rerouted-writes", "router_rehome", ">=", 1),
        SLO("no-lost-watch-events", "lost_watch_events", "==", 0),
        SLO("error-budget-5xx", "http_5xx", "<=", 600),
    ),
)

CRD_CHURN = ScenarioSpec(
    name="crd-churn",
    description="Per-tenant CRD creation, schema-negotiation churn and "
                "teardown with live CR traffic: a created CRD must "
                "become servable within the convergence bound, schema "
                "updates must not blip serving, and a deleted CRD's "
                "endpoint must 404 promptly.",
    topology="monolith",
    topology_args={"controllers": True},
    tenants=4,
    watchers_per_tenant=0,
    workload="crd",
    phases=(Phase("establish", ops_per_tenant=15, settle_s=0.5),
            Phase("negotiate", ops_per_tenant=25, settle_s=0.5)),
    slos=(
        SLO("schema-negotiation-convergence", "crd_servable_p99_ms",
            "<=", 5000.0),
        SLO("all-crds-established", "crd_unestablished", "==", 0),
        SLO("all-crds-torn-down", "crd_undestroyed", "==", 0),
        SLO("no-lost-acked-cr-writes", "lost_acked_writes", "==", 0),
        SLO("error-budget-5xx", "http_5xx", "==", 0),
    ),
)

RING_CHANGE = ScenarioSpec(
    name="ring-change-under-load",
    description="A live-workload shard drains and restarts on a NEW "
                "address mid-phase and the router republishes /ring: "
                "smart clients (even-index tenants go DIRECT to the HRW "
                "owner) must absorb the move via one-shot router "
                "fallbacks + a ring re-fetch, routed tenants via plain "
                "retries — zero lost acked writes, zero stuck clients, "
                "and a bounded p99 through the fallback window.",
    topology="fleet",
    topology_args={"shards": 3},
    tenants=6,
    watchers_per_tenant=1,
    options={"pace_s": 0.02, "smart_half": True,
             "coverage_timeout_s": 30.0},
    phases=(Phase("warm", ops_per_tenant=20),
            Phase("move", ops_per_tenant=80, action="move_shard",
                  settle_s=1.5),
            Phase("after", ops_per_tenant=20, settle_s=1.0)),
    slos=(
        SLO("no-lost-acked-writes", "lost_acked_writes", "==", 0),
        SLO("no-stuck-clients", "gave_up", "==", 0),
        SLO("no-lost-watch-events", "lost_watch_events", "==", 0),
        SLO("fallback-window-p99", "phase_move_p99_ms", "<=", 15000.0),
        SLO("smart-went-direct", "smart_client_direct", ">=", 1),
        SLO("move-absorbed-by-fallback", "smart_client_fallback", ">=", 1),
        SLO("ring-refetched", "smart_client_ring_refreshes", ">=", 1),
        SLO("error-budget-5xx", "http_5xx", "<=", 400),
    ),
)

SCALE_OUT = ScenarioSpec(
    name="scale-out-under-load",
    description="Elastic capacity: a 2-shard durable fleet DOUBLES to 4 "
                "shards live, one shard per grow phase, while tenants "
                "write and watch throughout (even-index tenants go "
                "direct via smart clients). Each grow publishes the "
                "grown ring with every moving cluster pinned to its old "
                "owner, streams the cluster's WAL to the new shard "
                "through the fenced filtered feed, and flips ownership "
                "atomically per cluster. Zero lost acked writes, zero "
                "lost watch events, no stuck clients, bounded p99 "
                "through both migration windows — and the WAL actually "
                "moved (migration_records). Typed 410s are EXPECTED "
                "here (fences and flips turn them into retries/relists) "
                "so no gone_410 SLO: honesty about the mechanism, not "
                "silence about it.",
    topology="fleet",
    topology_args={"shards": 2},
    tenants=6,
    watchers_per_tenant=1,
    options={"pace_s": 0.02, "smart_half": True,
             "coverage_timeout_s": 30.0},
    phases=(Phase("warm", ops_per_tenant=20),
            Phase("grow23", ops_per_tenant=60, action="scale_out",
                  settle_s=1.5),
            Phase("grow34", ops_per_tenant=60, action="scale_out",
                  settle_s=1.5),
            Phase("after", ops_per_tenant=20, settle_s=1.0)),
    slos=(
        SLO("no-lost-acked-writes", "lost_acked_writes", "==", 0),
        SLO("no-lost-watch-events", "lost_watch_events", "==", 0),
        SLO("no-stuck-clients", "gave_up", "==", 0),
        SLO("grow23-window-p99", "phase_grow23_p99_ms", "<=", 15000.0),
        SLO("grow34-window-p99", "phase_grow34_p99_ms", "<=", 15000.0),
        SLO("wal-actually-migrated", "migration_records", ">=", 1),
        SLO("smart-went-direct", "smart_client_direct", ">=", 1),
        SLO("error-budget-5xx", "http_5xx", "<=", 400),
    ),
)

WRITE_STORM = ScenarioSpec(
    name="write-storm",
    description="The whole tenant fleet writes flat-out with group "
                "commit on (KCP_GROUP_COMMIT=1, the default) and the "
                "primary is SIGKILLed mid-storm behind a router with "
                "standby + replica: the standby promotes and zero "
                "ACKED writes are lost — an unsynced commit window was "
                "never acked, so grouping cannot widen the loss window "
                "— while the commit-window counters prove the write "
                "path actually grouped under the storm.",
    topology="replicated",
    tenants=6,
    watchers_per_tenant=1,
    env={"KCP_GROUP_COMMIT": "1"},
    phases=(Phase("warm", ops_per_tenant=20),
            Phase("storm", ops_per_tenant=120, action="kill_primary",
                  settle_s=1.5),
            Phase("recovered", ops_per_tenant=20, settle_s=1.0)),
    options={"pace_s": 0.0, "coverage_timeout_s": 30.0},
    slos=(
        SLO("no-lost-acked-writes", "lost_acked_writes", "==", 0),
        SLO("standby-promoted", "repl_promotions", ">=", 1),
        SLO("writes-actually-grouped", "store_commit_windows", ">=", 1),
        SLO("no-lost-watch-events", "lost_watch_events", "==", 0),
        SLO("error-budget-5xx", "http_5xx", "<=", 2000),
    ),
)

SCENARIOS: dict[str, ScenarioSpec] = {
    s.name: s for s in (CRUD_CHURN, NOISY_NEIGHBOR, RECONNECT_STORM,
                        ROLLING_RESTART, KILL_PRIMARY, CRD_CHURN,
                        RING_CHANGE, SCALE_OUT, WRITE_STORM)
}
