"""The named scenarios: the repo's end-to-end acceptance suite.

Each entry composes subsystems PRs 1–9 shipped individually — CRUD
serving + encode-once lists, watch fan-out + informer discipline,
admission/flow control, the shard router, WAL replication + promotion,
graceful drain — into one declared-SLO workload. ``scripts/scenarios.py
run --all --seed N`` runs them all and emits one JSON scorecard;
``scripts/ci.sh`` gates a reduced-scale subset.

SLO targets are deliberately scale-independent (ScenarioSpec.scaled
never touches them): an objective that only holds at toy scale is not
an objective. Latency bounds leave headroom for loaded CI hosts —
regressions they exist to catch (lost events, lost writes, unthrottled
floods, silent stream deaths) are step functions, not millisecond
drift.
"""

from __future__ import annotations

from .spec import SLO, Phase, ScenarioSpec

CRUD_CHURN = ScenarioSpec(
    name="crud-churn",
    description="N-tenant CRUD churn under a watcher fleet: the "
                "bread-and-butter lane — every ack converges to every "
                "stream, nothing is lost, nothing 5xxes.",
    topology="monolith",
    tenants=8,
    watchers_per_tenant=2,
    phases=(Phase("warm", ops_per_tenant=20),
            Phase("churn", ops_per_tenant=60)),
    slos=(
        SLO("convergence", "p99_convergence_ms", "<=", 400.0),
        SLO("no-lost-acked-writes", "lost_acked_writes", "==", 0),
        SLO("no-lost-watch-events", "lost_watch_events", "==", 0),
        SLO("no-unclean-stream-deaths", "unclean_stream_ends", "==", 0),
        SLO("error-budget-5xx", "http_5xx", "==", 0),
    ),
)

NOISY_NEIGHBOR = ScenarioSpec(
    name="noisy-neighbor",
    description="One tenant floods writes at many times its token rate "
                "while quiet tenants keep working: flow control must "
                "throttle the flood (429 + Retry-After) and keep the "
                "quiet tenants' p99 within a declared ratio of their "
                "no-storm baseline.",
    topology="monolith",
    tenants=6,
    watchers_per_tenant=1,
    env={"KCP_FLOW_RATE": "80", "KCP_FLOW_BURST": "40"},
    phases=(Phase("baseline", ops_per_tenant=40),
            Phase("storm", ops_per_tenant=40, action="flood")),
    options={"flood_ops": 600, "pace_s": 0.02},
    slos=(
        SLO("quiet-tenant-p99-ratio", "quiet_p99_ratio", "<=", 3.0),
        SLO("no-quiet-throttling", "quiet_429", "==", 0),
        SLO("flood-throttled", "flood_429", ">=", 1),
        SLO("no-lost-acked-writes", "lost_acked_writes", "==", 0),
        SLO("no-lost-watch-events", "lost_watch_events", "==", 0),
    ),
)

RECONNECT_STORM = ScenarioSpec(
    name="reconnect-storm",
    description="10,000 watch streams severed in the same instant while "
                "writes continue; every observer resumes from its last "
                "RV at once against one server. The shared watch-cache "
                "window must absorb the storm: zero lost events, zero "
                "unrecoverable (410) resumes, and drop-to-first-event "
                "resume latency bounded at p99. Runs against a real "
                "server SUBPROCESS so the 10k-stream fd bill is split "
                "across processes (scale 1.0 needs ~10k fds per side).",
    topology="monolith",
    topology_args={"proc": True},
    tenants=20,
    watchers_per_tenant=500,
    phases=(Phase("warm", ops_per_tenant=15),
            Phase("storm", ops_per_tenant=40, action="drop_watchers",
                  settle_s=1.0),
            Phase("recover", ops_per_tenant=15, settle_s=1.0)),
    options={"pace_s": 0.01, "coverage_timeout_s": 120.0},
    slos=(
        SLO("no-lost-watch-events", "lost_watch_events", "==", 0),
        SLO("no-unrecoverable-resumes", "gone_410", "==", 0),
        SLO("storm-happened", "reconnects", ">=", 1),
        # the bound is the 1-cpu host reality: re-establishing 10k TCP
        # streams serializes on one accept loop (~500 conns/s), so the
        # herd converges together near the tail; the SLOs exist to catch
        # step-function regressions (lost events, 410 storms, resumes
        # that relist), not millisecond drift
        SLO("resume-latency", "resume_p99_ms", "<=", 30000.0),
        SLO("convergence", "p99_convergence_ms", "<=", 30000.0),
        SLO("no-lost-acked-writes", "lost_acked_writes", "==", 0),
        SLO("error-budget-5xx", "http_5xx", "==", 0),
        # soak memory: server RSS at the last phase boundary vs the
        # first. 10k resumes each relisting the world is exactly where
        # unpaged list bodies balloon; paged relists keep this flat.
        # Declared (not best-effort) so a run where RSS sampling broke
        # FAILS as "metric never measured" instead of passing blind.
        SLO("bounded-rss-growth", "memory_growth_ratio", "<=", 3.0),
    ),
)

ROLLING_RESTART = ScenarioSpec(
    name="rolling-restart",
    description="A durable shard fleet behind the router restarted one "
                "shard at a time USING GRACEFUL DRAIN, under live "
                "writes and watches: zero lost acked writes, zero lost "
                "watch events, every stream ended by a terminal Status. "
                "The same workload re-runs with drain bypassed (kill) "
                "and must demonstrate the breach drain prevents.",
    topology="fleet",
    topology_args={"shards": 2},
    tenants=6,
    watchers_per_tenant=2,
    phases=(Phase("warm", ops_per_tenant=20),
            Phase("restart", ops_per_tenant=90,
                  action="rolling_restart_drain", settle_s=1.0)),
    options={"pace_s": 0.02, "compare_kill": True,
             "coverage_timeout_s": 25.0},
    slos=(
        SLO("no-lost-acked-writes", "lost_acked_writes", "==", 0),
        SLO("no-lost-watch-events", "lost_watch_events", "==", 0),
        SLO("no-unclean-stream-deaths", "unclean_stream_ends", "==", 0),
        SLO("drain-terminated-streams", "terminal_statuses", ">=", 1),
        SLO("error-budget-5xx", "http_5xx", "<=", 400),
        SLO("kill-bypass-breaches", "bypass_stream_breaches", ">=", 1),
    ),
)

KILL_PRIMARY = ScenarioSpec(
    name="kill-primary",
    description="SIGKILL the primary mid-workload behind a router with "
                "standby + replica: the standby promotes, the replica "
                "re-homes its feed onto the promoted standby, the "
                "router re-routes writes to it — no manual restarts, "
                "zero acked writes lost.",
    topology="replicated",
    tenants=5,
    watchers_per_tenant=2,
    phases=(Phase("warm", ops_per_tenant=25),
            Phase("failover", ops_per_tenant=80, action="kill_primary",
                  faults="repl.ship:latency=2ms", settle_s=1.5),
            Phase("recovered", ops_per_tenant=25, settle_s=1.0)),
    options={"pace_s": 0.02, "coverage_timeout_s": 30.0},
    slos=(
        SLO("no-lost-acked-writes", "lost_acked_writes", "==", 0),
        SLO("standby-promoted", "repl_promotions", ">=", 1),
        SLO("replica-rehomed", "repl_rehome", ">=", 1),
        SLO("router-rerouted-writes", "router_rehome", ">=", 1),
        SLO("no-lost-watch-events", "lost_watch_events", "==", 0),
        SLO("error-budget-5xx", "http_5xx", "<=", 600),
    ),
)

CRD_CHURN = ScenarioSpec(
    name="crd-churn",
    description="Per-tenant CRD creation, schema-negotiation churn and "
                "teardown with live CR traffic: a created CRD must "
                "become servable within the convergence bound, schema "
                "updates must not blip serving, and a deleted CRD's "
                "endpoint must 404 promptly.",
    topology="monolith",
    topology_args={"controllers": True},
    tenants=4,
    watchers_per_tenant=0,
    workload="crd",
    phases=(Phase("establish", ops_per_tenant=15, settle_s=0.5),
            Phase("negotiate", ops_per_tenant=25, settle_s=0.5)),
    slos=(
        SLO("schema-negotiation-convergence", "crd_servable_p99_ms",
            "<=", 5000.0),
        SLO("all-crds-established", "crd_unestablished", "==", 0),
        SLO("all-crds-torn-down", "crd_undestroyed", "==", 0),
        SLO("no-lost-acked-cr-writes", "lost_acked_writes", "==", 0),
        SLO("error-budget-5xx", "http_5xx", "==", 0),
    ),
)

RING_CHANGE = ScenarioSpec(
    name="ring-change-under-load",
    description="A live-workload shard drains and restarts on a NEW "
                "address mid-phase and the router republishes /ring: "
                "smart clients (even-index tenants go DIRECT to the HRW "
                "owner) must absorb the move via one-shot router "
                "fallbacks + a ring re-fetch, routed tenants via plain "
                "retries — zero lost acked writes, zero stuck clients, "
                "and a bounded p99 through the fallback window.",
    topology="fleet",
    topology_args={"shards": 3},
    tenants=6,
    watchers_per_tenant=1,
    options={"pace_s": 0.02, "smart_half": True,
             "coverage_timeout_s": 30.0},
    phases=(Phase("warm", ops_per_tenant=20),
            Phase("move", ops_per_tenant=80, action="move_shard",
                  settle_s=1.5),
            Phase("after", ops_per_tenant=20, settle_s=1.0)),
    slos=(
        SLO("no-lost-acked-writes", "lost_acked_writes", "==", 0),
        SLO("no-stuck-clients", "gave_up", "==", 0),
        SLO("no-lost-watch-events", "lost_watch_events", "==", 0),
        SLO("fallback-window-p99", "phase_move_p99_ms", "<=", 15000.0),
        SLO("smart-went-direct", "smart_client_direct", ">=", 1),
        SLO("move-absorbed-by-fallback", "smart_client_fallback", ">=", 1),
        SLO("ring-refetched", "smart_client_ring_refreshes", ">=", 1),
        SLO("error-budget-5xx", "http_5xx", "<=", 400),
    ),
)

SCALE_OUT = ScenarioSpec(
    name="scale-out-under-load",
    description="Elastic capacity: a 2-shard durable fleet DOUBLES to 4 "
                "shards live, one shard per grow phase, while tenants "
                "write and watch throughout (even-index tenants go "
                "direct via smart clients). Each grow publishes the "
                "grown ring with every moving cluster pinned to its old "
                "owner, streams the cluster's WAL to the new shard "
                "through the fenced filtered feed, and flips ownership "
                "atomically per cluster. Zero lost acked writes, zero "
                "lost watch events, no stuck clients, bounded p99 "
                "through both migration windows — and the WAL actually "
                "moved (migration_records). Typed 410s are EXPECTED "
                "here (fences and flips turn them into retries/relists) "
                "so no gone_410 SLO: honesty about the mechanism, not "
                "silence about it.",
    topology="fleet",
    topology_args={"shards": 2},
    tenants=6,
    watchers_per_tenant=1,
    options={"pace_s": 0.02, "smart_half": True,
             "coverage_timeout_s": 30.0},
    phases=(Phase("warm", ops_per_tenant=20),
            Phase("grow23", ops_per_tenant=60, action="scale_out",
                  settle_s=1.5),
            Phase("grow34", ops_per_tenant=60, action="scale_out",
                  settle_s=1.5),
            Phase("after", ops_per_tenant=20, settle_s=1.0)),
    slos=(
        SLO("no-lost-acked-writes", "lost_acked_writes", "==", 0),
        SLO("no-lost-watch-events", "lost_watch_events", "==", 0),
        SLO("no-stuck-clients", "gave_up", "==", 0),
        SLO("grow23-window-p99", "phase_grow23_p99_ms", "<=", 15000.0),
        SLO("grow34-window-p99", "phase_grow34_p99_ms", "<=", 15000.0),
        SLO("wal-actually-migrated", "migration_records", ">=", 1),
        SLO("smart-went-direct", "smart_client_direct", ">=", 1),
        SLO("error-budget-5xx", "http_5xx", "<=", 400),
    ),
)

WRITE_STORM = ScenarioSpec(
    name="write-storm",
    description="The whole tenant fleet writes flat-out with group "
                "commit on (KCP_GROUP_COMMIT=1, the default) and the "
                "primary is SIGKILLed mid-storm behind a router with "
                "standby + replica: the standby promotes and zero "
                "ACKED writes are lost — an unsynced commit window was "
                "never acked, so grouping cannot widen the loss window "
                "— while the commit-window counters prove the write "
                "path actually grouped under the storm.",
    topology="replicated",
    tenants=6,
    watchers_per_tenant=1,
    env={"KCP_GROUP_COMMIT": "1"},
    phases=(Phase("warm", ops_per_tenant=20),
            Phase("storm", ops_per_tenant=120, action="kill_primary",
                  settle_s=1.5),
            Phase("recovered", ops_per_tenant=20, settle_s=1.0)),
    options={"pace_s": 0.0, "coverage_timeout_s": 30.0},
    slos=(
        SLO("no-lost-acked-writes", "lost_acked_writes", "==", 0),
        SLO("standby-promoted", "repl_promotions", ">=", 1),
        SLO("writes-actually-grouped", "store_commit_windows", ">=", 1),
        SLO("no-lost-watch-events", "lost_watch_events", "==", 0),
        SLO("error-budget-5xx", "http_5xx", "<=", 2000),
    ),
)

FLEET_CHURN = ScenarioSpec(
    name="fleet-churn",
    description="Hundreds of physical clusters with skewed capacity "
                "flap Ready/NotReady in a seeded storm while the "
                "in-server fleet control plane (KCP_FLEET=1) keeps "
                "root Deployments placed: every flap stays inside the "
                "evacuation hysteresis, so the storm phase must move "
                "ZERO replicas and evacuate ZERO pclusters — and the "
                "healed fleet's live assignment must equal the numpy "
                "host twin's answer for the final state.",
    topology="monolith",
    topology_args={"controllers": True},
    tenants=2,
    watchers_per_tenant=0,
    workload="fleet",
    env={"KCP_FLEET": "1"},
    options={"pclusters": 200, "roots": 30, "ticks": 6,
             "flap_rate": 0.15, "skew": 1.0},
    phases=(Phase("seed", settle_s=0.3),
            Phase("storm", settle_s=0.3),
            Phase("verify", settle_s=0.3)),
    slos=(
        SLO("zero-churn-under-flaps", "fleet_storm_churn", "==", 0),
        SLO("zero-evacuations-under-flaps", "fleet_storm_evacuations",
            "==", 0),
        SLO("storm-actually-flapped", "fleet_flaps", ">=", 50),
        SLO("seed-fully-placed", "fleet_seed_unplaced", "==", 0),
        SLO("assignment-matches-host-twin", "assignment_mismatches",
            "==", 0),
        SLO("healed-fully-placed", "fleet_unplaced", "==", 0),
        SLO("solver-actually-ran", "placement_resolves", ">=", 1),
        SLO("driver-clean", "fleet_driver_errors", "==", 0),
    ),
)

CAPACITY_SKEW = ScenarioSpec(
    name="capacity-skew-binpack",
    description="The BASELINE-shape bin-pack study: 10k workspaces "
                "over 8 pclusters with lognormal-skewed capacity, "
                "solved in ONE device batch. The assignment must be "
                "byte-identical to the numpy host twin, never "
                "overcommit a row or land on a non-candidate, and a "
                "37-row candidate delta must re-solve exactly those "
                "rows to the same answer a from-scratch solve gives.",
    topology="none",
    tenants=2,
    watchers_per_tenant=0,
    workload="placement",
    options={"workspaces": 10000, "pclusters": 8, "spread": 2,
             "skew": 1.2, "dirty_rows": 37},
    phases=(Phase("solve", settle_s=0.0),),
    slos=(
        SLO("baseline-shape", "placement_rows", ">=", 10000),
        SLO("assignment-byte-identical", "placement_mismatches",
            "==", 0),
        SLO("no-overcommitted-rows", "placement_overcommit_rows",
            "==", 0),
        SLO("never-onto-non-candidates",
            "placement_noncandidate_replicas", "==", 0),
        SLO("incremental-touches-only-dirty-rows",
            "placement_incremental_extra_rows", "==", 0),
        SLO("incremental-matches-full-solve",
            "placement_incremental_mismatches", "==", 0),
        SLO("batched-solve-bounded", "placement_batched_ms",
            "<=", 5000.0),
        SLO("driver-clean", "placement_driver_errors", "==", 0),
    ),
)

PARTITION_PROMOTION = ScenarioSpec(
    name="partition-during-promotion",
    description="A WAN partition cuts every peer's link TO the primary "
                "(feed fan-out stays up — the partition is directed) "
                "mid-workload: the standby's probes fail, it promotes "
                "behind the epoch fence, the router re-homes writes "
                "onto it, and when the link heals the fence lands on "
                "the old primary. The epoch fence must HOLD: zero "
                "acked writes lost, exactly one writable primary at "
                "the end, the fenced ex-primary behind the promoted "
                "epoch with no commits the new primary never saw.",
    topology="replicated",
    tenants=5,
    watchers_per_tenant=2,
    phases=(Phase("warm", ops_per_tenant=25),
            Phase("partition", ops_per_tenant=60,
                  faults="link.partition:drop@peer=*>{primary}",
                  settle_s=2.0),
            Phase("healed", ops_per_tenant=25, settle_s=2.0)),
    options={"pace_s": 0.02, "coverage_timeout_s": 30.0},
    slos=(
        SLO("no-lost-acked-writes", "lost_acked_writes", "==", 0),
        SLO("partition-actually-cut",
            "fault_injected_link_partition", ">=", 1),
        SLO("standby-promoted", "repl_promotions", ">=", 1),
        SLO("router-rerouted-writes", "router_rehome", ">=", 1),
        SLO("one-writable-primary", "writable_primaries", "==", 1),
        SLO("old-primary-fenced", "fenced_nodes", ">=", 1),
        SLO("no-dual-primary-commits", "stale_primary_excess_rv",
            "==", 0),
        SLO("epoch-fence-held", "epoch_fence_held", "==", 1),
        SLO("no-lost-watch-events", "lost_watch_events", "==", 0),
        SLO("error-budget-5xx", "http_5xx", "<=", 2000),
    ),
)

WAN_REPLICA_LAG = ScenarioSpec(
    name="wan-replica-lag",
    description="The replica's feed link crosses a slow WAN path "
                "(seeded 30-60ms per batch, jittered) while writes "
                "continue at full rate: the primary's fan-out must lag "
                "ONLY that follower (the semi-sync standby acks at LAN "
                "speed, so client acks never slow), and once the link "
                "heals the replica must drain its lag to zero — "
                "bounded staleness, not silent divergence. Session-"
                "consistency probers ride alongside the writers, every "
                "read pinned to the tenant's own max acked RV "
                "(X-Kcp-Min-Rv): whichever node answers through the "
                "lagging link — parked on its RV barrier or fallen "
                "back to the primary — the response must never come "
                "back below the session floor, with zero surfaced "
                "errors.",
    topology="replicated",
    tenants=5,
    watchers_per_tenant=1,
    phases=(Phase("warm", ops_per_tenant=20),
            Phase("lag", ops_per_tenant=60,
                  faults="link.delay:latency=30ms@jitter=30ms"
                         "@peer=repl.feed>replica",
                  settle_s=1.0),
            Phase("drain", ops_per_tenant=20, settle_s=2.0)),
    options={"pace_s": 0.02, "coverage_timeout_s": 30.0,
             "consistent_readers": True},
    slos=(
        SLO("no-lost-acked-writes", "lost_acked_writes", "==", 0),
        SLO("wan-delay-actually-fired",
            "fault_injected_link_delay", ">=", 1),
        SLO("replica-drained-after-heal", "replica_lag", "==", 0),
        SLO("one-writable-primary", "writable_primaries", "==", 1),
        SLO("no-spurious-promotion", "repl_promotions", "==", 0),
        SLO("no-lost-watch-events", "lost_watch_events", "==", 0),
        SLO("error-budget-5xx", "http_5xx", "==", 0),
        SLO("consistent-reads-served", "consistent_reads", ">=", 1),
        SLO("zero-stale-consistent-reads",
            "stale_consistent_reads", "==", 0),
        SLO("zero-consistent-read-errors",
            "consistent_read_errors", "==", 0),
        SLO("barrier-parked-under-lag",
            "consistent_read_waits", ">=", 1),
    ),
)

SCENARIOS: dict[str, ScenarioSpec] = {
    s.name: s for s in (CRUD_CHURN, NOISY_NEIGHBOR, RECONNECT_STORM,
                        ROLLING_RESTART, KILL_PRIMARY, CRD_CHURN,
                        RING_CHANGE, SCALE_OUT, WRITE_STORM,
                        FLEET_CHURN, CAPACITY_SKEW, PARTITION_PROMOTION,
                        WAN_REPLICA_LAG)
}
