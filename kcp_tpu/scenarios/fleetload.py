"""Fleet + placement scenario workloads (engine-side drivers).

Two workload shapes beyond the configmap/CRD writers:

- ``fleet``: a seeded :class:`~kcp_tpu.physical.fake.ChurnDriver`
  storms a real server's Cluster API over REST while the in-server
  fleet control plane (``KCP_FLEET=1``) keeps root Deployments placed.
  Counter deltas are captured at phase boundaries so the
  zero-churn-under-flaps claim is phase-scoped, not run-scoped, and
  the healed fleet's live assignment is checked against the host
  twin's answer for the final state — the device path's decisions are
  auditable from outside the process.
- ``placement``: no servers at all — the BASELINE-shape bin-pack
  study (10k workspaces x 8 pclusters, skewed lognormal capacity)
  runs engine-side: batched device solve vs numpy host twin
  byte-equality, plus a candidate-delta incremental re-solve that
  must touch exactly the dirty rows. Its numbers ARE the
  measurements.

Both drivers run as the phase's single "writer" future; any internal
failure is recorded (``*_driver_errors``) instead of raised, so a
broken driver fails its SLOs loudly rather than aborting the whole
catalog run.
"""

from __future__ import annotations

import logging
import time

import numpy as np

from ..fleet.solver import FleetSolver, solve_host
from ..reconcilers.deployment.controller import (
    CLUSTER_LABEL,
    DEPLOYMENTS,
    OWNED_BY_LABEL,
)
from ..server.rest import RestClient
from ..utils.trace import REGISTRY

log = logging.getLogger(__name__)

#: the logical cluster the fleet workload lives in
FLEET_TENANT = "fleet"

#: counters whose PHASE deltas the fleet workload asserts on (the
#: engine's TRACKED_COUNTERS are run-scoped; zero-churn-under-flaps is
#: a claim about the storm phase alone)
_PHASE_COUNTERS = ("placement_churn_total", "placement_resolves_total",
                   "cluster_evacuations_total")


def _counters() -> dict[str, float]:
    return {n: REGISTRY.counter(n).value for n in _PHASE_COUNTERS}


def _root(name: str, replicas: int) -> dict:
    return {"apiVersion": "apps/v1", "kind": "Deployment",
            "metadata": {"name": name, "namespace": "default",
                         "clusterName": FLEET_TENANT},
            "spec": {"replicas": replicas,
                     "template": {"spec": {"containers": []}}}}


def _placed(c: RestClient) -> dict[str, dict[str, int]]:
    """root -> {pcluster: replicas} from the live leaf Deployments."""
    items, _rv = c.list(DEPLOYMENTS, "default")
    out: dict[str, dict[str, int]] = {}
    for o in items:
        labels = o["metadata"].get("labels") or {}
        owner = labels.get(OWNED_BY_LABEL)
        if not owner:
            continue
        n = int(o.get("spec", {}).get("replicas", 0) or 0)
        if n:
            out.setdefault(owner, {})[labels.get(CLUSTER_LABEL, "")] = n
    return out


def run_fleet_phase(base_url: str, phase_name: str, sspec, seed: int,
                    shared: dict) -> None:
    """One fleet-workload phase (blocking worker thread)."""
    shared.setdefault("fleet_driver_errors", 0)
    try:
        _fleet_phase(base_url, phase_name, sspec, seed, shared)
    except Exception:  # noqa: BLE001 — fail via SLOs, not an abort
        log.exception("fleet workload phase %r failed", phase_name)
        shared["fleet_driver_errors"] += 1


def _fleet_phase(base_url: str, phase_name: str, sspec, seed: int,
                 shared: dict) -> None:
    from ..physical.fake import ChurnDriver

    opts = sspec.options
    c = RestClient(base_url, cluster=FLEET_TENANT)
    try:
        if phase_name == "seed":
            drv = ChurnDriver(
                int(opts.get("pclusters", 150)), seed=seed,
                ticks=int(opts.get("ticks", 6)),
                flap_rate=float(opts.get("flap_rate", 0.15)),
                flap_len=1, outage_rate=0.0, capacity_churn=0.0,
                base_capacity=int(opts.get("base_capacity", 64)),
                skew=float(opts.get("skew", 1.0)))
            shared["_drv"] = drv
            drv.seed_fleet(c)
            rng = np.random.default_rng(seed + 1)
            demands = rng.integers(1, 24,
                                   int(opts.get("roots", 24))).tolist()
            shared["_demands"] = demands
            for j, d in enumerate(demands):
                c.create(DEPLOYMENTS, _root(f"app-{j:03d}", int(d)))
            want_total = sum(demands)
            deadline = time.monotonic() + 60.0
            while time.monotonic() < deadline:
                placed = _placed(c)
                got = sum(sum(v.values()) for v in placed.values())
                if got == want_total and len(placed) == len(demands):
                    break
                time.sleep(0.2)
            shared["fleet_seed_unplaced"] = want_total - sum(
                sum(v.values()) for v in _placed(c).values())
            shared["_before"] = _counters()
        elif phase_name == "storm":
            drv = shared["_drv"]
            tick_s = float(opts.get("tick_s", 0.08))
            for tick in range(drv.ticks):
                drv.apply(c, tick)
                time.sleep(tick_s)
            # heal INSIDE the phase: every flap window stays far inside
            # the evacuation hysteresis, so the storm's churn delta is
            # a clean claim about flaps, not about a trailing outage
            drv.apply(c, drv.ticks)
            time.sleep(0.4)
            before = shared.pop("_before")
            now = _counters()
            shared["fleet_storm_churn"] = (
                now["placement_churn_total"]
                - before["placement_churn_total"])
            shared["fleet_storm_evacuations"] = (
                now["cluster_evacuations_total"]
                - before["cluster_evacuations_total"])
            shared["fleet_flaps"] = shared["_drv"].flap_count()
        elif phase_name == "verify":
            drv, demands = shared["_drv"], shared["_demands"]
            alloc = np.asarray(drv.allocatable_at(drv.ticks), np.int32)
            R = len(demands)
            # host-twin answer for the healed fleet: unlabeled roots
            # carry no locality bonus, so uniform regions/homes solve
            # to the same assignment the live scheduler's -1 homes do
            want = solve_host(np.asarray(demands, np.int32),
                              np.ones((R, drv.n), bool), alloc,
                              np.zeros(drv.n, np.int32),
                              np.zeros(R, np.int32))
            want_map = {
                f"app-{j:03d}": {drv.names[i]: int(want[j, i])
                                 for i in range(drv.n) if want[j, i]}
                for j in range(R)}
            deadline = time.monotonic() + 30.0
            while True:
                placed = _placed(c)
                mism = sum(1 for r, m in want_map.items()
                           if placed.get(r, {}) != m)
                if mism == 0 or time.monotonic() > deadline:
                    break
                time.sleep(0.25)
            shared["assignment_mismatches"] = mism
            shared["fleet_unplaced"] = sum(demands) - sum(
                sum(v.values()) for v in placed.values())
        else:
            raise ValueError(f"unknown fleet phase {phase_name!r}")
    finally:
        c.close()


# ---------------------------------------------------------------------------
# placement study (no topology)
# ---------------------------------------------------------------------------


def run_placement_phase(phase_name: str, sspec, seed: int,
                        shared: dict) -> None:
    """The BASELINE-shape bin-pack study (blocking worker thread)."""
    shared.setdefault("placement_driver_errors", 0)
    try:
        _placement_study(sspec, seed, shared)
    except Exception:  # noqa: BLE001 — fail via SLOs, not an abort
        log.exception("placement study phase %r failed", phase_name)
        shared["placement_driver_errors"] += 1


def _placement_study(sspec, seed: int, shared: dict) -> None:
    opts = sspec.options
    W = int(opts.get("workspaces", 10000))
    P = int(opts.get("pclusters", 8))
    spread = int(opts.get("spread", 2))
    rng = np.random.default_rng(seed)
    demand = rng.integers(0, 48, W).astype(np.int32)
    # skewed fleet: a few huge pclusters, a long tail of small ones
    alloc = np.maximum(1, np.minimum(
        rng.lognormal(3.0, float(opts.get("skew", 1.2)), P),
        30000.0)).astype(np.int32)
    cand = rng.random((W, P)) < 0.9
    region = rng.integers(0, 4, P).astype(np.int32)
    home = rng.integers(-1, 4, W).astype(np.int32)
    solver = FleetSolver(spread=spread)
    solver.solve(demand, cand, alloc, region, home)  # warm (compile)
    t0 = time.perf_counter()
    dev = solver.solve(demand, cand, alloc, region, home).copy()
    shared["placement_batched_ms"] = round(
        (time.perf_counter() - t0) * 1000, 3)
    t0 = time.perf_counter()
    host = solve_host(demand, cand, alloc, region, home, spread)
    shared["placement_host_ms"] = round(
        (time.perf_counter() - t0) * 1000, 3)
    shared["placement_rows"] = W
    shared["placement_pclusters"] = P
    shared["placement_mismatches"] = int((dev != host).any(axis=1).sum())
    shared["placement_overcommit_rows"] = int(
        (dev.sum(axis=1) > demand).sum())
    shared["placement_noncandidate_replicas"] = int(dev[~cand].sum())
    # candidate-delta incremental re-solve: exactly the dirty rows
    # re-dispatch; untouched rows keep their cached assignment and the
    # result must still match a from-scratch host solve of the new state
    k = int(opts.get("dirty_rows", 37))
    dirty = rng.choice(W, size=k, replace=False)
    cand2 = cand.copy()
    cand2[dirty] = rng.random((k, P)) < 0.7
    before = solver.stats["rows_solved"]
    dev2 = solver.solve(demand, cand2, alloc, region, home,
                        rows=[int(i) for i in dirty])
    solved = solver.stats["rows_solved"] - before
    shared["placement_incremental_extra_rows"] = solved - k
    host2 = solve_host(demand, cand2, alloc, region, home, spread)
    shared["placement_incremental_mismatches"] = int(
        (dev2 != host2).any(axis=1).sum())
