"""Topology drivers: the real server constellations scenarios run on.

Everything here drives REAL servers over real HTTP — ServerThread per
process-analog, each with its own event loop and store, exactly the
harness discipline tests/helpers.py established (its ``shard_fleet`` /
``restart_shard`` now live here and are re-exported there). Three
shapes cover the deployment matrix the scenarios exercise:

- :class:`Monolith` — one server (optionally with in-process
  controllers, for the CRD/schema-negotiation scenarios);
- :class:`RouterFleet` — N durable shards behind a ``--role router``
  scatter-gather frontend, restartable one at a time (gracefully via
  :meth:`~kcp_tpu.server.server.Server.drain` or abruptly via
  ``kill()`` — the rolling-restart scenario's A/B);
- :class:`ReplicatedPrimary` — primary + standby + replica behind a
  router whose shard entry lists the followers as read replicas; the
  kill-the-primary scenario's stage (standby promotion, replica
  re-homing, router write re-routing).
"""

from __future__ import annotations

import contextlib
import dataclasses
import os
import signal
import subprocess
import sys
import time
from urllib.parse import urlsplit

from ..server.server import Config
from ..server.threaded import ServerThread


def spawn_server(extra_args: list[str] | None = None,
                 env_overrides: dict | None = None,
                 timeout: float = 60.0):
    """Spawn a real ``kcp start`` SUBPROCESS (plaintext, no controllers)
    and block until it announces its serving address; returns
    ``(Popen, address)``.

    The out-of-process shape exists for watcher-scale scenarios: a
    10k-stream storm is 10k fds on each side of the wire, and holding
    both sides in one process doubles the bill against RLIMIT_NOFILE.
    The child never imports jax, and engine-side ``KCP_FAULTS``
    schedules do NOT reach it — subprocess topologies drill client-side
    and wire-level chaos (drops, storms), not server-internal points."""
    cmd = [sys.executable, "-m", "kcp_tpu.cli.kcp", "start",
           "--no-install-controllers", "--no-tls",
           "--syncer-mode", "none"] + list(extra_args or [])
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    env.pop("KCP_FAULTS", None)  # engine-phase schedules stay engine-side
    env["KCP_NO_COMPILE_CACHE"] = "1"
    env.update({k: str(v) for k, v in (env_overrides or {}).items()})
    p = subprocess.Popen(cmd, stdout=subprocess.PIPE,
                         stderr=subprocess.DEVNULL, env=env, text=True)
    deadline = time.time() + timeout
    while True:
        line = p.stdout.readline()
        if not line:
            raise RuntimeError(
                f"kcp start exited rc={p.poll()} before serving: {cmd}")
        if line.startswith("kcp-tpu serving at "):
            return p, line.rsplit(None, 1)[-1]
        if time.time() > deadline:
            p.kill()
            raise RuntimeError(f"kcp start did not serve in {timeout}s")


# ---------------------------------------------------------------------------
# fleet primitives (moved from tests/helpers.py; re-exported there)
# ---------------------------------------------------------------------------


@contextlib.contextmanager
def shard_fleet(n: int, tls: bool = False, durable: bool = False,
                root_dir: str | None = None):
    """A sharded control plane: ``n`` shard servers plus a router
    fronting them over a consistent-hash ring.

    Yields ``(router_thread, shard_threads, ring)``; ``shard_threads``
    is a mutable list so chaos tests can kill and
    :func:`restart_shard` entries in place. ``durable=True`` gives each
    shard a WAL under ``root_dir/shard<i>`` so a restarted shard
    resumes with its data AND its RV sequence (the honest recovery
    story; in-memory shards come back empty at RV 0)."""
    from ..sharding import ShardRing

    if durable and root_dir is None:
        raise ValueError("durable shard_fleet needs a root_dir")
    shards: list[ServerThread] = []
    router = None
    names = ",".join(f"s{i}" for i in range(n))
    try:
        for i in range(n):
            # every shard knows the ring's NAMES and its own, so direct
            # smart-client requests (X-Kcp-Ring-Epoch stamped) are
            # ownership-verified; routed traffic is untouched
            kw: dict = dict(durable=durable, install_controllers=False,
                            tls=tls, shard_name=f"s{i}", ring_names=names,
                            ring_epoch=1)
            if durable:
                kw["root_dir"] = os.path.join(root_dir, f"shard{i}")
            shards.append(ServerThread(Config(**kw)).start())
        spec = ",".join(f"s{i}={t.address}" for i, t in enumerate(shards))
        router = ServerThread(Config(role="router", shards=spec,
                                     durable=False, tls=tls)).start()
        yield router, shards, ShardRing.from_spec(spec)
    finally:
        if router is not None:
            router.stop()
        for s in shards:
            s.stop()


def restart_shard(shards: list, i: int, timeout: float = 30.0):
    """Restart shard ``i`` on its OLD address (the ring entry is fixed
    at fleet start — a revived shard must come back where the router
    expects it). The old thread must already be stopped."""
    old = shards[i]
    cfg = dataclasses.replace(old.server.config,
                              listen_port=urlsplit(old.address).port)
    # the freed port can linger briefly; retry the bind a few times
    last: Exception | None = None
    for _ in range(10):
        try:
            shards[i] = ServerThread(cfg).start(timeout=timeout)
            return shards[i]
        except RuntimeError as e:  # port not yet released
            last = e
            time.sleep(0.2)
    raise last


def move_shard(shards: list, i: int, router_url: str, drain: bool = True,
               timeout: float = 30.0):
    """The elastic-topology primitive: take shard ``i`` down (drain by
    default), bring it back on a NEW ephemeral address, and republish
    the ring (``POST /ring``) so the router re-points its pools and
    bumps the ring epoch. Smart clients going direct to the old address
    fall back through the router once, re-fetch ``GET /ring``, and
    follow the move; routed clients never notice beyond the restart
    window. The shard's WAL (durable fleets) carries its data and RV
    sequence across the move."""
    from ..server.rest import RestClient

    old = shards[i]
    if drain:
        old.drain()
    old.stop()
    cfg = dataclasses.replace(
        old.server.config, listen_port=0,
        ring_epoch=(old.server.config.ring_epoch or 1) + 1)
    shards[i] = ServerThread(cfg).start(timeout=timeout)
    spec = ",".join(
        f"{t.server.config.shard_name or f's{j}'}={t.address}"
        for j, t in enumerate(shards))
    c = RestClient(router_url)
    try:
        c._request("POST", "/ring", {"shards": spec})
    finally:
        c.close()
    return shards[i]


# ---------------------------------------------------------------------------
# scenario topologies
# ---------------------------------------------------------------------------


@contextlib.contextmanager
def _env_patch(env: dict):
    """Apply server-process env overrides for the duration of server
    CONSTRUCTION (flow-control rates, drain budgets — read once at
    startup); restored immediately after so scenarios compose."""
    saved = {k: os.environ.get(k) for k in env}
    os.environ.update({k: str(v) for k, v in env.items()})
    try:
        yield
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


class Monolith:
    """One server process; controllers optional (CRD scenarios).

    ``proc=True`` runs the server as a real SUBPROCESS instead of a
    ServerThread — the watcher-scale shape (10k streams = 10k fds per
    side; one process holding both sides pays double against
    RLIMIT_NOFILE). Scenario ``env`` reaches the child's environment;
    engine-side KCP_FAULTS schedules do not (see :func:`spawn_server`).
    """

    kind = "monolith"

    def __init__(self, root_dir: str, env: dict | None = None,
                 durable: bool = False, controllers: bool = False,
                 proc: bool = False):
        self.root_dir = root_dir
        self.env = env or {}
        self.durable = durable
        self.controllers = controllers
        self.proc = proc
        self.server: ServerThread | None = None
        self._child: subprocess.Popen | None = None
        self._child_url = ""

    def start(self) -> "Monolith":
        if self.proc:
            if self.controllers:
                raise ValueError(
                    "proc=True monolith runs --no-install-controllers; "
                    "CRD scenarios need the in-process shape")
            args = ["--listen-port", "0"]
            if self.durable:
                args += ["--root-dir", os.path.join(self.root_dir, "mono")]
            else:
                args += ["--in-memory"]
            self._child, self._child_url = spawn_server(args, self.env)
            return self
        kw: dict = dict(durable=self.durable,
                        install_controllers=self.controllers, tls=False)
        if self.durable:
            kw["root_dir"] = os.path.join(self.root_dir, "mono")
        with _env_patch(self.env):
            self.server = ServerThread(Config(**kw)).start()
        return self

    @property
    def client_url(self) -> str:
        if self._child is not None:
            return self._child_url
        return self.server.address

    def stop(self) -> None:
        if self._child is not None:
            # SIGTERM = graceful drain (the CLI's handler); escalate if
            # the child outlives a generous budget
            self._child.send_signal(signal.SIGTERM)
            try:
                self._child.wait(timeout=15)
            except subprocess.TimeoutExpired:
                self._child.kill()
                self._child.wait(timeout=5)
            self._child = None
        if self.server is not None:
            self.server.stop()
            self.server = None


class RouterFleet:
    """N durable shards behind a router, restartable in place."""

    kind = "fleet"

    def __init__(self, root_dir: str, env: dict | None = None,
                 shards: int = 2, durable: bool = True):
        self.root_dir = root_dir
        self.env = env or {}
        self.n = shards
        self.durable = durable
        self.shards: list[ServerThread] = []
        self.router: ServerThread | None = None

    def start(self) -> "RouterFleet":
        with _env_patch(self.env):
            names = ",".join(f"s{i}" for i in range(self.n))
            for i in range(self.n):
                kw: dict = dict(durable=self.durable,
                                install_controllers=False, tls=False,
                                shard_name=f"s{i}", ring_names=names,
                                ring_epoch=1)
                if self.durable:
                    kw["root_dir"] = os.path.join(self.root_dir,
                                                  f"shard{i}")
                self.shards.append(ServerThread(Config(**kw)).start())
            spec = ",".join(f"s{i}={t.address}"
                            for i, t in enumerate(self.shards))
            self.router = ServerThread(Config(role="router", shards=spec,
                                              durable=False,
                                              tls=False)).start()
        return self

    @property
    def client_url(self) -> str:
        return self.router.address

    def restart_shard(self, i: int, drain: bool = True) -> None:
        """Take shard ``i`` down (gracefully or by SIGKILL-equivalent)
        and bring it back on its old address — one step of a rolling
        restart."""
        if drain:
            self.shards[i].drain()
        else:
            self.shards[i].kill()
        restart_shard(self.shards, i)

    def move_shard(self, i: int | None = None) -> None:
        """The ring-change-under-load lever: drain a shard, restart it
        on a NEW address, republish ``/ring``. With no index given, the
        shard owning tenant ``t0`` moves — guaranteed to sit on a live
        workload's write path."""
        from ..sharding import ShardRing

        if i is None:
            spec = ",".join(f"s{j}={t.address}"
                            for j, t in enumerate(self.shards))
            i = ShardRing.from_spec(spec).owner_index("t0")
        move_shard(self.shards, i, self.router.address)

    def scale_out(self) -> ServerThread:
        """The elastic-capacity lever: grow the fleet by ONE shard while
        the workload runs. Starts ``s<n>`` (durable, booted with the
        grown ring identity so epoch-stamped direct requests verify),
        then drives :func:`kcp_tpu.sharding.migrate.scale_out` against
        the router — the grown ring publishes with every moving cluster
        pinned to its old owner, each pinned cluster's WAL streams to
        the new shard, and ownership flips atomically per cluster.
        Raises (scenario fails) if any migration step refuses."""
        from ..sharding import migrate

        i = len(self.shards)
        with _env_patch(self.env):
            names = ",".join(
                [t.server.config.shard_name or f"s{j}"
                 for j, t in enumerate(self.shards)] + [f"s{i}"])
            kw: dict = dict(durable=self.durable,
                            install_controllers=False, tls=False,
                            shard_name=f"s{i}", ring_names=names,
                            ring_epoch=1)
            if self.durable:
                kw["root_dir"] = os.path.join(self.root_dir, f"shard{i}")
            new = ServerThread(Config(**kw)).start()
        self.shards.append(new)
        self.n += 1
        migrate.scale_out(self.router.address, f"s{i}={new.address}")
        return new

    def stop(self) -> None:
        if self.router is not None:
            self.router.stop()
            self.router = None
        for s in self.shards:
            s.stop()
        self.shards = []


class ReplicatedPrimary:
    """Primary + standby + replica behind a router (one ring entry with
    the followers as read replicas). The replica's ``--primary`` is the
    CANDIDATE list ``primary,standby`` so re-homing engages after a
    failover."""

    kind = "replicated"

    def __init__(self, root_dir: str, env: dict | None = None,
                 hysteresis_s: float = 0.6):
        self.root_dir = root_dir
        self.env = env or {}
        self.hysteresis_s = hysteresis_s
        self.primary: ServerThread | None = None
        self.standby: ServerThread | None = None
        self.replica: ServerThread | None = None
        self.router: ServerThread | None = None

    def start(self) -> "ReplicatedPrimary":
        with _env_patch(self.env):
            self.primary = ServerThread(Config(
                durable=True, install_controllers=False, tls=False,
                root_dir=os.path.join(self.root_dir, "p"))).start()
            self.standby = ServerThread(Config(
                role="standby", primary=self.primary.address,
                repl_hysteresis_s=self.hysteresis_s,
                durable=True, install_controllers=False, tls=False,
                root_dir=os.path.join(self.root_dir, "s"))).start()
            self.replica = ServerThread(Config(
                role="replica",
                primary=f"{self.primary.address},{self.standby.address}",
                repl_hysteresis_s=self.hysteresis_s,
                durable=True, install_controllers=False, tls=False,
                root_dir=os.path.join(self.root_dir, "r"))).start()
            spec = (f"s0={self.primary.address}|{self.standby.address}"
                    f"|{self.replica.address}")
            self.router = ServerThread(Config(
                role="router", shards=spec, durable=False,
                tls=False)).start()
        return self

    @property
    def client_url(self) -> str:
        return self.router.address

    def peer_addrs(self) -> dict[str, str]:
        """host:port per replication role. The engine templates
        ``{primary}``/``{standby}``/``{replica}`` in phase fault specs
        into these — link faults key on the netloc, not the URL."""
        return {name: urlsplit(t.address).netloc
                for name, t in (("primary", self.primary),
                                ("standby", self.standby),
                                ("replica", self.replica))
                if t is not None}

    def audit(self, timeout: float = 12.0) -> dict:
        """Post-run replication facts for the scorecard: poll every
        node's ``/replication/status`` until the constellation settles
        — exactly one writable primary (fencing landed), every live
        unfenced follower drained to the primary's applied RV — then
        report. A fleet that never settles reports its last snapshot
        and the SLOs fail loudly.

        ``stale_primary_excess_rv`` is the dual-primary-commit
        evidence: a fenced ex-primary that committed writes the
        promoted primary never saw would sit AHEAD of it in the shared
        RV sequence."""
        from ..server.rest import RestClient
        from ..utils import errors

        def snap() -> dict:
            out = {}
            for name, t in (("primary", self.primary),
                            ("standby", self.standby),
                            ("replica", self.replica)):
                if t is None:
                    continue
                c = RestClient(t.address)
                try:
                    out[name] = c._request(
                        "GET", "/replication/status") or {}
                except (errors.ApiError, ConnectionError, OSError):
                    out[name] = None  # dead node (e.g. killed primary)
                finally:
                    c.close()
            return out

        deadline = time.time() + timeout
        while True:
            st = [s for s in snap().values() if s]
            prim = [s for s in st
                    if s.get("role") == "primary" and not s.get("fenced")
                    and not s.get("read_only")]
            fenced = [s for s in st if s.get("fenced")]
            lag = excess = 0
            if len(prim) == 1:
                head = int(prim[0].get("applied_rv", 0) or 0)
                epoch = int(prim[0].get("epoch", 0) or 0)
                followers = [s for s in st
                             if s is not prim[0] and not s.get("fenced")]
                lag = max((head - int(s.get("applied_rv", 0) or 0)
                           for s in followers), default=0)
                excess = max((int(s.get("applied_rv", 0) or 0) - head
                              for s in fenced), default=0)
                # a fence stamps the SUPERSEDING epoch onto the sealed
                # store, so a fenced node sitting AHEAD of the writable
                # primary would mean a promotion this fleet never saw
                ahead = any(int(s.get("epoch", 0) or 0) > epoch
                            for s in fenced)
                if lag == 0 and excess <= 0:
                    break
            if time.time() > deadline:
                ahead = True
                break
            time.sleep(0.2)
        return {"writable_primaries": len(prim),
                "fenced_nodes": len(fenced),
                "replica_lag": max(lag, 0),
                "stale_primary_excess_rv": max(excess, 0),
                "epoch_fence_held": int(len(prim) == 1 and not ahead)}

    def kill_primary(self) -> None:
        """SIGKILL-equivalent primary death (Server.kill: no WAL
        compaction, streams die mid-chunk)."""
        self.primary.kill()

    def stop(self) -> None:
        for t in (self.router, self.replica, self.standby, self.primary):
            if t is not None:
                t.stop()
        self.router = self.replica = self.standby = self.primary = None


class NullTopology:
    """No servers at all. The placement-study workload is pure solver
    work driven engine-side; ``client_url`` is empty and the engine
    skips every HTTP-touching step (observers, traces, final-state
    verify)."""

    kind = "none"

    def __init__(self, root_dir: str, env: dict | None = None):
        self.root_dir = root_dir
        self.env = env or {}

    def start(self) -> "NullTopology":
        return self

    @property
    def client_url(self) -> str:
        return ""

    def stop(self) -> None:
        pass


def make_topology(spec, root_dir: str):
    """Instantiate the topology a spec names."""
    args = dict(spec.topology_args)
    if spec.topology == "monolith":
        return Monolith(root_dir, env=spec.env, **args)
    if spec.topology == "fleet":
        return RouterFleet(root_dir, env=spec.env, **args)
    if spec.topology == "replicated":
        return ReplicatedPrimary(root_dir, env=spec.env, **args)
    if spec.topology == "none":
        return NullTopology(root_dir, env=spec.env)
    raise ValueError(f"unknown topology {spec.topology!r}")
