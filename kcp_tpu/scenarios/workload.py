"""Seeded workloads + honest accounting: writers, observers, ledgers.

The determinism contract: the full op schedule is a pure function of
``(seed, spec)`` — generated up front, hashed into the scorecard, and
replayed identically on the same seed. Execution timing varies run to
run (real HTTP, real restarts); the *schedule* and the derived
final-state expectation never do.

Accounting is the point of the harness, so it is explicit:

- every acknowledged write is a ledger entry ``(tenant, name, rv,
  kind, t_ack)`` — "zero lost acked writes" is checked against a fold
  of the schedule, never against what the server claims;
- every observer is a raw watch stream with the client-side resume
  discipline spelled out (terminal drain Status → resume from
  ``last_rv``; abrupt death → resume, counting the breach; 410 →
  relist, counting the unrecoverable gap), so "zero lost watch events"
  distinguishes *delivered late* from *never delivered*;
- client-visible 5xx/429/ambiguous outcomes are counted per phase —
  the error-budget SLOs read these, not server metrics.
"""

from __future__ import annotations

import asyncio
import hashlib
import json
import random
import threading
import time
from dataclasses import dataclass, field

from .. import obs
from ..server.rest import RestClient
from ..utils import errors

RESOURCE = "configmaps"
NAMESPACE = "default"


# ---------------------------------------------------------------------------
# schedule
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class Op:
    tenant: str
    kind: str   # create | update | delete
    name: str
    step: int


def tenant_name(i: int) -> str:
    return f"t{i}"


def build_schedule(seed: int, spec) -> dict[str, list[list[Op]]]:
    """phase -> per-tenant op lists, derived from the seed alone.

    Each (tenant, phase) stream has its own PRNG keyed by name, so a
    scaled run changes other tenants' schedules not at all — the same
    per-point independence discipline as the fault injector."""
    out: dict[str, list[list[Op]]] = {}
    for phase in spec.phases:
        per_tenant: list[list[Op]] = []
        for ti in range(spec.tenants):
            t = tenant_name(ti)
            rng = random.Random(f"{seed}:{spec.name}:{phase.name}:{t}")
            live: list[str] = []
            counter = 0
            ops: list[Op] = []
            for step in range(phase.ops_per_tenant):
                roll = rng.random()
                if live and roll < 0.15:
                    name = live.pop(rng.randrange(len(live)))
                    ops.append(Op(t, "delete", name, step))
                elif live and roll < 0.45:
                    name = live[rng.randrange(len(live))]
                    ops.append(Op(t, "update", name, step))
                else:
                    name = f"{t}-{phase.name}-{counter}"
                    counter += 1
                    live.append(name)
                    ops.append(Op(t, "create", name, step))
            per_tenant.append(ops)
        out[phase.name] = per_tenant
    return out


def schedule_hash(seed: int, spec, schedule: dict) -> str:
    doc = {
        "seed": seed,
        "scenario": spec.name,
        "phases": [{"name": p.name, "faults": p.faults, "action": p.action}
                   for p in spec.phases],
        "ops": {ph: [[(o.kind, o.name) for o in ops] for ops in tenants]
                for ph, tenants in schedule.items()},
    }
    return hashlib.sha256(
        json.dumps(doc, sort_keys=True).encode()).hexdigest()[:16]


def expected_final_state(schedule: dict, spec) -> dict[str, set[str]]:
    """Fold the full schedule: tenant -> names that must exist at the
    end (the authority "zero lost acked writes" is checked against)."""
    expect: dict[str, set[str]] = {tenant_name(i): set()
                                   for i in range(spec.tenants)}
    for phase in spec.phases:
        for ops in schedule[phase.name]:
            for op in ops:
                if op.kind == "delete":
                    expect[op.tenant].discard(op.name)
                else:
                    expect[op.tenant].add(op.name)
    return expect


# ---------------------------------------------------------------------------
# writer ledger
# ---------------------------------------------------------------------------


@dataclass
class WriterStats:
    """Shared, lock-guarded ledger all writer threads append to."""

    acks: list[tuple] = field(default_factory=list)  # (tenant,name,rv,kind,t)
    latencies: dict[str, dict[str, list[float]]] = field(
        default_factory=dict)  # phase -> class -> per-op seconds
    http_5xx: int = 0
    http_429: int = 0
    ambiguous: int = 0      # ack lost but write landed (AlreadyExists etc.)
    gave_up: int = 0        # ops abandoned at their deadline
    max_rv: dict[str, int] = field(default_factory=dict)  # tenant -> rv
    _lock: threading.Lock = field(default_factory=threading.Lock)

    def ack(self, tenant: str, name: str, rv: int, kind: str) -> None:
        with self._lock:
            self.acks.append((tenant, name, rv, kind, time.monotonic()))
            if rv:
                self.max_rv[tenant] = max(self.max_rv.get(tenant, 0), rv)

    def note(self, what: str, n: int = 1) -> None:
        with self._lock:
            setattr(self, what, getattr(self, what) + n)

    def latency(self, phase: str, klass: str, seconds: float) -> None:
        with self._lock:
            self.latencies.setdefault(phase, {}).setdefault(
                klass, []).append(seconds)


def _obj(tenant: str, name: str, step: int) -> dict:
    return {"apiVersion": "v1", "kind": "ConfigMap",
            "metadata": {"name": name, "namespace": NAMESPACE,
                         "clusterName": tenant},
            "data": {"v": str(step)}}


def run_writer(base_url: str, tenant: str, ops: list[Op], stats: WriterStats,
               phase: str, klass: str = "quiet",
               op_deadline_s: float = 30.0, pace_s: float = 0.0,
               smart: bool = False) -> None:
    """Execute one tenant's op list (a blocking worker thread).

    Retry discipline mirrors a production client: 503/transport errors
    back off and retry until the per-op deadline (counting every
    client-visible 5xx into the error budget), 429 honors Retry-After,
    and an AlreadyExists/NotFound answer to a RETRIED create/delete is
    an ack whose response was lost — the write landed, counted
    ambiguous, never double-applied.

    ``smart=True`` writes through a shard-aware
    :class:`~kcp_tpu.client.smart.SmartRestClient` (direct to the HRW
    owner, one-shot router fallback on ring staleness) — the
    ring-change scenario runs smart and routed tenants side by side on
    the same schedule."""
    if smart:
        from ..client.smart import SmartRestClient

        c: RestClient = SmartRestClient(base_url, cluster=tenant)
    else:
        c = RestClient(base_url, cluster=tenant)
    try:
        for op in ops:
            if pace_s:
                time.sleep(pace_s)
            deadline = time.monotonic() + op_deadline_s
            backoff = 0.05
            retried = False
            while True:
                t0 = time.monotonic()
                # driver-side trace root: the whole op (incl. the server
                # round trip) is the convergence timeline's "write"
                # phase — the scenario engine attaches the slowest
                # assembled traces to the scorecard per phase
                tctx = None
                if obs.TRACER.enabled and obs.TRACER.head_sampled():
                    tctx = obs.TRACER.mint(sampled=True)
                tw0 = time.time()
                try:
                    with obs.use(tctx):
                        if op.kind == "create":
                            resp = c.create(RESOURCE, _obj(
                                op.tenant, op.name, op.step))
                        elif op.kind == "update":
                            resp = c.update(RESOURCE, _obj(
                                op.tenant, op.name, op.step))
                        else:
                            c.delete(RESOURCE, op.name, NAMESPACE)
                            resp = None
                    stats.latency(phase, klass, time.monotonic() - t0)
                    rv = 0
                    if resp is not None:
                        rv = int(resp.get("metadata", {})
                                 .get("resourceVersion", "0"))
                    if tctx is not None and tctx.sampled:
                        obs.phase("write", tctx, tw0, time.time(),
                                  rv=str(rv), obj=op.name)
                    stats.ack(op.tenant, op.name, rv, op.kind)
                    break
                except errors.AlreadyExistsError:
                    if op.kind == "create" and retried:
                        stats.note("ambiguous")
                        stats.ack(op.tenant, op.name, 0, op.kind)
                        break
                    raise
                except errors.NotFoundError:
                    if op.kind == "create":
                        raise  # a 404'd create is a harness bug
                    if op.kind == "delete":
                        # a retried delete whose first attempt landed —
                        # or a target a failed upstream op never created:
                        # either way the name is absent, which is the
                        # outcome; the final-state check arbitrates
                        if retried:
                            stats.note("ambiguous")
                        stats.ack(op.tenant, op.name, 0, op.kind)
                        break
                    # update of a vanished object (an upstream give-up
                    # or ambiguous delete): record and move on — the
                    # final-state verification reports the divergence
                    stats.note("gave_up")
                    break
                except errors.TooManyRequestsError as e:
                    stats.note("http_429")
                    if time.monotonic() > deadline:
                        stats.note("gave_up")
                        break
                    time.sleep(min(getattr(e, "retry_after", 0.2) or 0.2,
                                   1.0))
                    retried = True
                except (errors.UnavailableError, errors.GoneError,
                        ConnectionError, OSError) as e:
                    if isinstance(e, errors.ApiError):
                        stats.note("http_5xx")
                    if time.monotonic() > deadline:
                        stats.note("gave_up")
                        break
                    time.sleep(backoff)
                    backoff = min(backoff * 1.7, 0.5)
                    retried = True
    finally:
        c.close()


def run_consistent_reader(base_url: str, tenant: str, stats: WriterStats,
                          shared: dict, stop: threading.Event,
                          pace_s: float = 0.01) -> None:
    """Session-consistency prober (a blocking worker thread): reads the
    tenant's collection through the scenario's client endpoint with the
    session's own write floor pinned (``X-Kcp-Min-Rv`` = the tenant's
    max acked RV). Whichever node answers — the primary, the standby,
    or a WAN-lagged replica parked on its RV barrier — the response's
    list RV must never fall below the floor; a response that does is a
    stale read-your-write, the thing the consistent-read SLOs forbid.
    Counts fold into ``shared`` (``consistent_reads`` /
    ``stale_consistent_reads`` / ``consistent_read_errors``)."""
    c = RestClient(base_url, cluster=tenant)
    lock = shared["_lock"]
    target = f"/clusters/{tenant}/api/v1/namespaces/{NAMESPACE}/{RESOURCE}"
    try:
        while not stop.is_set():
            if pace_s:
                time.sleep(pace_s)
            with stats._lock:
                floor = stats.max_rv.get(tenant, 0)
            if not floor:
                continue
            ok = stale = err = 0
            for attempt in range(3):
                try:
                    s, _h, body = c.request_raw(
                        "GET", target,
                        headers={"X-Kcp-Min-Rv": str(floor)})
                except (ConnectionError, OSError, errors.ApiError):
                    s, body = 0, b""
                if s == 200:
                    rv = int(json.loads(body)["metadata"]
                             .get("resourceVersion", "0"))
                    if rv >= floor:
                        ok = 1
                    else:
                        stale = 1
                    break
                # transport hiccup or relayed 5xx: the router's fallback
                # should have absorbed it — brief retry before counting
                # a surfaced error against the zero-error SLO
                time.sleep(0.1)
            else:
                err = 1
            with lock:
                shared["consistent_reads"] += ok
                shared["stale_consistent_reads"] += stale
                shared["consistent_read_errors"] += err
    finally:
        c.close()


def run_flood(base_url: str, tenant: str, n_ops: int,
              stats: WriterStats) -> tuple[int, int]:
    """The noisy neighbor: fire creates as fast as the wire allows; no
    retries — the point is to be throttled. Returns (ok, throttled)."""
    c = RestClient(base_url, cluster=tenant)
    ok = throttled = 0
    try:
        for i in range(n_ops):
            name = f"{tenant}-flood-{i}"
            try:
                resp = c.create(RESOURCE, _obj(tenant, name, i))
                ok += 1
                stats.ack(tenant, name,
                          int(resp.get("metadata", {})
                              .get("resourceVersion", "0")), "create")
            except errors.TooManyRequestsError:
                throttled += 1
            except errors.ApiError:
                pass  # the flood takes what it gets
    finally:
        c.close()
    return ok, throttled


# ---------------------------------------------------------------------------
# observers
# ---------------------------------------------------------------------------


@dataclass
class ObserverStats:
    events: dict[tuple[str, int], float] = field(default_factory=dict)
    terminal_statuses: int = 0   # drain Status received (clean)
    unclean_ends: int = 0        # established stream died with no Status
    gone_410: int = 0            # resume refused: unrecoverable gap
    relists: int = 0
    reconnects: int = 0
    last_rv: int = 0
    # reconnect-storm accounting: seconds from a deliberate drop to the
    # resumed stream's first delivered event (the client-visible resume
    # latency the watcher-scale SLO bounds)
    resume_s: list[float] = field(default_factory=list)


class StreamObserver:
    """One raw watch stream per (tenant, slot) with the production
    resume discipline; the thing the watch-loss SLOs measure."""

    def __init__(self, base_url: str, tenant: str):
        self.base_url = base_url
        self.tenant = tenant
        self.client = RestClient(base_url, cluster=tenant)
        self.stats = ObserverStats()
        self.cache: dict[str, dict] = {}
        self._stopping = False
        self._dropped = False
        self._resume_t0: float | None = None
        self._watch = None
        self._task: asyncio.Task | None = None
        self.synced = asyncio.Event()

    async def start(self) -> None:
        self._task = asyncio.ensure_future(self._run())
        await self.synced.wait()

    def drop(self) -> None:
        """Sever the live stream (the reconnect-storm lever): the run
        loop notices the closed stream and resumes from last_rv."""
        self._dropped = True
        if self._watch is not None:
            self._watch.close()

    async def stop(self) -> None:
        self._stopping = True
        if self._watch is not None:
            self._watch.close()
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except (asyncio.CancelledError, Exception):
                pass
            self._task = None
        self.client.close()

    # ------------------------------------------------------------ loop

    def _relist(self) -> None:
        items, rv = self.client.list(RESOURCE, NAMESPACE)
        now = time.monotonic()
        self.cache = {o["metadata"]["name"]: o for o in items}
        # a relist is delivery, not amnesia: an informer synthesizes
        # events from the list contents, so every listed (name, rv)
        # counts as observed. Acked states OVERWRITTEN before the relist
        # stay unobserved — the intermediate-event gap a kill-without-
        # drain costs — and across a shard migration the target's
        # re-minted rvs are only ever coverable here (the 410→relist is
        # the designed hand-off, not a loss).
        for o in items:
            self.stats.events.setdefault(
                (o["metadata"]["name"],
                 int(o["metadata"].get("resourceVersion", "0"))), now)
        self.stats.last_rv = max(self.stats.last_rv, rv)
        self.stats.relists += 1
        # fd hygiene at watcher scale: a 10k-observer fleet must not
        # also pin 10k idle keep-alive list connections — the client
        # reopens on the next (rare) relist
        self.client.close()

    def _record(self, ev) -> None:
        now = time.monotonic()
        key = (ev.name, ev.rv)
        self.stats.events.setdefault(key, now)
        self.stats.last_rv = max(self.stats.last_rv, ev.rv)
        if self._resume_t0 is not None:
            self.stats.resume_s.append(now - self._resume_t0)
            self._resume_t0 = None
        if ev.type == "DELETED":
            self.cache.pop(ev.name, None)
        else:
            self.cache[ev.name] = ev.object

    async def _run(self) -> None:
        loop = asyncio.get_running_loop()
        # initial list+watch, retried while the endpoint comes up
        while not self._stopping:
            try:
                await loop.run_in_executor(None, self._relist)
                self.stats.relists -= 1  # the seed list is not a re-list
                break
            except Exception:  # noqa: BLE001 — endpoint still starting
                await asyncio.sleep(0.1)
        self.synced.set()
        while not self._stopping:
            w = self.client.watch(RESOURCE, NAMESPACE,
                                  since_rv=self.stats.last_rv)
            self._watch = w
            delivered = 0
            err: Exception | None = None
            try:
                async for ev in w:
                    self._record(ev)
                    delivered += 1
            except Exception as e:  # noqa: BLE001 — classified below
                err = e
            # bookmarks (including the drain terminal's final one) only
            # advance the stream's last_rv, they are not yielded events
            self.stats.last_rv = max(self.stats.last_rv, w.last_rv)
            if self._stopping:
                return
            if isinstance(err, errors.GoneError):
                # the server cannot replay the gap: INTERMEDIATE states
                # between our last_rv and the relist are UNRECOVERABLE —
                # exactly what kill-without-drain costs (still counted
                # as lost by the coverage check: an overwritten rv is in
                # nobody's relist). Current states land via the relist.
                self.stats.gone_410 += 1
                try:
                    await loop.run_in_executor(None, self._relist)
                except Exception:  # noqa: BLE001 — server mid-restart
                    await asyncio.sleep(0.15)
            elif isinstance(err, errors.UnavailableError):
                # the graceful-drain terminal Status: everything
                # committed before the drain was delivered; resume from
                # last_rv once the endpoint is back
                self.stats.terminal_statuses += 1
            elif self._dropped:
                # our own reconnect-storm drop: a deliberate client-side
                # severing, not a server-side breach. The clock on the
                # resume starts here and stops at the resumed stream's
                # first delivered event.
                self._dropped = False
                self.stats.reconnects += 1
                self._resume_t0 = time.monotonic()
            elif err is None and not getattr(w, "responded", True):
                # connect refused (endpoint restarting): not a stream
                # death, just a failed attempt
                self.stats.reconnects += 1
            else:
                # an ESTABLISHED stream ended with no terminal Status —
                # the violation drain exists to prevent
                self.stats.unclean_ends += 1
            await asyncio.sleep(0.15)
