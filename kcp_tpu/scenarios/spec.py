"""Scenario specs: declarative phases × tenant mixes × fault schedules
with declared SLOs.

A scenario is DATA, not code: the engine (engine.py) interprets the
same spec the scorecard reports, so what ran and what was asserted are
one artifact. Every run is seeded and replayable — the op schedule is
derived from the seed alone (workload.py), the fault schedule is a
``KCP_FAULTS`` spec string interpreted by the seeded injector, and the
scorecard carries a hash of both so "same seed ⇒ same schedule" is
checkable, not folklore.
"""

from __future__ import annotations

import dataclasses
import math
from dataclasses import dataclass, field

#: comparison operators an SLO may declare
SLO_OPS = ("<=", ">=", "==")


@dataclass(frozen=True)
class SLO:
    """One service-level objective: a named bound on a measurement the
    engine produces (``metric`` keys into the scenario's measurement
    dict; unknown metrics fail the scenario loudly — a typo'd SLO must
    never pass by vacuity)."""

    name: str
    metric: str
    op: str
    target: float

    def __post_init__(self):
        if self.op not in SLO_OPS:
            raise ValueError(f"SLO {self.name!r}: unknown op {self.op!r} "
                             f"(one of {SLO_OPS})")

    def check(self, observed: float) -> bool:
        if self.op == "<=":
            return observed <= self.target
        if self.op == ">=":
            return observed >= self.target
        return observed == self.target


@dataclass(frozen=True)
class Phase:
    """One scenario phase: a slice of the seeded workload, an optional
    fault schedule active for its duration, and an optional engine
    action (topology chaos, watcher storms) fired once the writers are
    under way."""

    name: str
    ops_per_tenant: int = 0
    faults: str = ""        # KCP_FAULTS spec installed for this phase;
    # {primary}/{standby}/{replica} template to the topology's
    # host:port for that role (WAN link faults are peer-pair-scoped)
    action: str = ""        # engine action: rolling_restart_drain |
    # rolling_restart_kill | kill_primary | drop_watchers | flood |
    # move_shard (drain a shard, restart on a NEW address, republish
    # /ring — the ring-change-under-load lever) | scale_out (grow the
    # fleet by one shard live and migrate every moving cluster's WAL
    # onto it — the elastic-capacity lever)
    settle_s: float = 0.3   # quiesce wait after the phase's work completes


@dataclass(frozen=True)
class ScenarioSpec:
    """A named, seeded, end-to-end scenario."""

    name: str
    description: str
    topology: str                      # monolith | fleet | replicated | none
    tenants: int
    phases: tuple[Phase, ...]
    slos: tuple[SLO, ...]
    workload: str = "configmaps"       # configmaps | crd | fleet | placement
    watchers_per_tenant: int = 1
    env: dict = field(default_factory=dict)       # server-process env
    options: dict = field(default_factory=dict)   # engine knobs
    topology_args: dict = field(default_factory=dict)

    def scaled(self, scale: float) -> "ScenarioSpec":
        """A reduced/enlarged copy for CI smokes vs full runs: tenant
        count and per-phase op counts scale (floored at useful minima);
        SLO targets do NOT scale — an objective that only holds at toy
        scale is not an objective."""
        if scale == 1.0:
            return self
        tenants = max(2, math.ceil(self.tenants * scale))
        watchers = (max(1, math.ceil(self.watchers_per_tenant * scale))
                    if self.watchers_per_tenant else 0)
        phases = tuple(
            dataclasses.replace(
                p, ops_per_tenant=(max(4, math.ceil(p.ops_per_tenant * scale))
                                   if p.ops_per_tenant else 0))
            for p in self.phases)
        options = dict(self.options)
        for k in ("flood_ops",):
            if k in options:
                options[k] = max(20, math.ceil(options[k] * scale))
        return dataclasses.replace(self, tenants=tenants,
                                   watchers_per_tenant=watchers,
                                   phases=phases, options=options)
