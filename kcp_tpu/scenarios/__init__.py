"""Scenario harness: seeded end-to-end chaos scenarios with SLO
scorecards (ROADMAP item 5 — the acceptance harness for the sharded /
replicated / fleet-batched stack).

- :mod:`.spec` — declarative scenario model (phases × tenant mixes ×
  fault schedules × SLOs);
- :mod:`.topology` — real-server constellations (monolith, shard fleet
  behind the router, primary+standby+replica);
- :mod:`.workload` — seeded replayable op schedules, writer ledgers,
  watch-stream observers with honest loss accounting;
- :mod:`.engine` — the run loop + scorecard;
- :mod:`.catalog` — the named scenarios ``scripts/scenarios.py`` runs.
"""

from .catalog import SCENARIOS
from .engine import run_scenario
from .spec import SLO, Phase, ScenarioSpec

__all__ = ["SCENARIOS", "run_scenario", "SLO", "Phase", "ScenarioSpec"]
