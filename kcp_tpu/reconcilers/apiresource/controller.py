"""API-resource negotiation: the CRD <-> import <-> negotiated 3-way machine.

Behavioral port of the reference's richest controller
(pkg/reconciler/apiresource/{controller,negotiation}.go, ~1.3k LoC):

- every ``APIResourceImport`` (one physical cluster's view of one API)
  is folded into a per-(logical cluster, GVR) ``NegotiatedAPIResource``
  via the LCD engine (kcp_tpu/schemacompat), stamping Compatible /
  Available conditions on the import (negotiation.go:460-585)
- a negotiated resource with ``spec.publish`` is published as a CRD
  (storage-version logic, owner reference, api-approved annotation for
  protected groups) and tracked with Submitted / Published conditions
  (negotiation.go:612-790)
- a manually created CRD (no NegotiatedAPIResource owner) *enforces* its
  schema: Enforced condition, negotiated schema overwritten, imports
  merely checked (negotiation.go:188-248)
- deletions cascade: orphaned negotiated resources are deleted, CRD
  versions pruned, conditions removed from imports
  (negotiation.go:109-123, 817-904)

TPU angle: the expensive part at 5k-tenant scale is not the state machine
but repeated LCD tree-walks over identical schemas. Every reconcile tick
tokenizes the batch's distinct schemas and hashes them on device in one
call (ops/schemahash, BASELINE configs[3]); LCD results are memoized by
(existing-hash, new-hash, narrow) so each distinct schema pair walks the
tree once per process lifetime.
"""

from __future__ import annotations

import copy
import logging
from typing import Sequence

import numpy as np

from ...apis import apiresource as ar
from ...apis import conditions as cond
from ...apis import crd as crdapi
from ...apis.scheme import GVR
from ...client import Client, Informer
from ...ops.hashing import canonical_json
from ...ops.schemahash import schema_hashes_jit, tokenize_schema
from ...reconciler.controller import BatchController
from ...schemacompat import ensure_structural_schema_compatibility
from ...utils import errors
from .versions import compare_kube_aware

log = logging.getLogger(__name__)

NEGOTIATED_KIND = "NegotiatedAPIResource"
API_VERSION_ANNOTATION = "apiresource.kcp.dev/apiVersion"

# queue element actions (reference controller.go:150-183)
CREATED = "created"
SPEC_CHANGED = "specChanged"
STATUS_ONLY = "statusOnlyChanged"
DELETED_ACTION = "deleted"


def crd_name_for(gvr: GVR) -> str:
    """CRD object name; the reference maps the core group to ``.core``
    (negotiation.go:617-623)."""
    return f"{gvr.resource}.{gvr.group or 'core'}"


def is_protected_group(group: str) -> bool:
    return group.endswith(".k8s.io") or group.endswith(".kubernetes.io") or group in (
        "k8s.io", "kubernetes.io")


def _gvr_of_spec(obj: dict) -> GVR:
    spec = obj["spec"]
    gv = spec["groupVersion"]
    return GVR(gv.get("group", ""), gv["version"], spec["plural"])


def _crd_gvrs(crd: dict) -> list[GVR]:
    return [
        GVR(crd["spec"]["group"], v["name"], crd["spec"]["names"]["plural"])
        for v in crd["spec"].get("versions", [])
    ]


class NegotiationController:
    """Batched-tick negotiation controller over the wildcard client."""

    def __init__(self, client: Client, auto_publish: bool = False, backend: str = "tpu"):
        self.client = client
        self.auto_publish = auto_publish
        self.backend = backend
        self.import_informer = Informer(client, ar.APIRESOURCEIMPORTS)
        self.negotiated_informer = Informer(client, ar.NEGOTIATEDAPIRESOURCES)
        self.crd_informer = Informer(client, crdapi.CRDS)
        # clusterNameAndGVR indexers (reference controller.go:46-50)
        self.import_informer.add_indexer("cluster_gvr", self._cluster_gvr_index)
        self.negotiated_informer.add_indexer("cluster_gvr", self._cluster_gvr_index)
        self.controller = BatchController(
            "apiresource-negotiation", self._process_batch,
            # item = ((obj_type, clusterName, name), action): fairness is
            # per logical cluster, not per object
            tenant_of=lambda item: item[0][1],
        )
        self.import_informer.add_handler(self._make_handler("import"))
        self.negotiated_informer.add_handler(self._make_handler("negotiated"))
        self.crd_informer.add_handler(self._make_handler("crd"))
        # (ex-hash, new-hash, narrow) -> (ex-canon, new-canon, lcd, errors)
        self._lcd_memo: dict[tuple[int, int, bool], tuple[str, str, dict | None, tuple[str, ...]]] = {}
        self._hash_by_canon: dict[str, int] = {}
        self._deleted: dict[tuple, dict] = {}
        self.stats = {"ticks": 0, "lcd_walks": 0, "lcd_hits": 0}

    @staticmethod
    def _cluster_gvr_index(obj: dict) -> list[str]:
        gvr = _gvr_of_spec(obj)
        cl = obj["metadata"].get("clusterName", "")
        return [f"{cl}|{gvr.group}|{gvr.version}|{gvr.resource}"]

    # ------------------------------------------------------------ events

    def _make_handler(self, obj_type: str):
        def handler(etype: str, old: dict | None, new: dict | None) -> None:
            obj = new or old
            m = obj["metadata"]
            key = (obj_type, m.get("clusterName", ""), m["name"])
            # classify, as the reference's enqueue does (controller.go:238-295)
            if etype == "ADDED":
                action = CREATED
            elif etype == "DELETED":
                action = DELETED_ACTION
                self._deleted[key] = obj
            elif (old or {}).get("metadata", {}).get("generation") != m.get("generation"):
                action = SPEC_CHANGED
            elif (old or {}).get("status") != obj.get("status"):
                action = STATUS_ONLY
            else:
                return  # annotation-only changes are enqueued-then-ignored upstream
            self.controller.enqueue((key, action))

        return handler

    # -------------------------------------------------------------- tick

    async def _process_batch(self, items: Sequence) -> list[tuple[object, Exception]]:
        self.stats["ticks"] += 1
        self._prehash_batch_schemas(items)
        failed = []
        for item in items:
            try:
                self._process(item)
            except errors.ConflictError as err:
                failed.append((item, err))
            except Exception as err:  # noqa: BLE001
                failed.append((item, err))
        return failed

    def _prehash_batch_schemas(self, items: Sequence) -> None:
        """Hash every schema the batch will touch in one device call.

        This is the configs[3] device path: at 5k tenants most imports
        carry one of a handful of distinct schemas; hashing them as one
        [B, T] batch and memoizing LCD by hash pair means the LCD tree
        walks stay O(distinct), not O(imports). Hashes are keyed by the
        canonical JSON of the schema (exact), never by object identity.
        """
        pending: dict[str, np.ndarray] = {}
        for (obj_type, cluster, name), _action in items:
            obj = None
            if obj_type == "import":
                obj = self.import_informer.get(cluster, name)
            elif obj_type == "negotiated":
                obj = self.negotiated_informer.get(cluster, name)
            if obj is not None:
                schema = obj.get("spec", {}).get("openAPIV3Schema")
                if schema is not None:
                    key = canonical_json(schema)
                    if key not in self._hash_by_canon and key not in pending:
                        pending[key] = tokenize_schema(schema)
        if not pending:
            return
        keys = list(pending)
        hashes = np.asarray(schema_hashes_jit(np.stack([pending[k] for k in keys])))
        for k, h in zip(keys, hashes):
            self._hash_by_canon[k] = int(h)

    def _schema_hash(self, schema: dict) -> tuple[str, int]:
        """(canonical json, uint32 hash) of a schema; cached exactly."""
        key = canonical_json(schema)
        h = self._hash_by_canon.get(key)
        if h is None:
            h = int(np.asarray(schema_hashes_jit(tokenize_schema(schema)[None, :]))[0])
            self._hash_by_canon[key] = h
        return key, h

    def _lcd(self, existing: dict, new: dict, narrow: bool, kind: str):
        ex_canon, ex_h = self._schema_hash(existing)
        new_canon, new_h = self._schema_hash(new)
        key = (ex_h, new_h, narrow)
        hit = self._lcd_memo.get(key)
        if hit is not None:
            # host-side equality re-check: a 32-bit collision must never
            # serve another schema pair's verdict
            hit_ex_canon, hit_new_canon, lcd, errs = hit
            if hit_ex_canon == ex_canon and hit_new_canon == new_canon:
                self.stats["lcd_hits"] += 1
                return copy.deepcopy(lcd), list(errs)
        lcd, errs = ensure_structural_schema_compatibility(
            existing, new, narrow_existing=narrow, fld_path=kind
        )
        self.stats["lcd_walks"] += 1
        self._lcd_memo[key] = (ex_canon, new_canon, copy.deepcopy(lcd), tuple(errs))
        return lcd, errs

    # ----------------------------------------------------------- process

    def _process(self, item) -> None:
        (obj_type, cluster, name), action = item
        if obj_type == "crd":
            self._process_crd(cluster, name, action)
        elif obj_type == "import":
            self._process_import(cluster, name, action)
        else:
            self._process_negotiated(cluster, name, action)
        self._deleted.pop((obj_type, cluster, name), None)

    # -- CRD events (negotiation.go:43-80)

    def _process_crd(self, cluster: str, name: str, action: str) -> None:
        crd = self.crd_informer.get(cluster, name) or self._deleted.get(("crd", cluster, name))
        if crd is None:
            return
        if action in (CREATED, SPEC_CHANGED):
            if self._is_manually_created_crd(crd):
                self._enforce_crd(cluster, crd)
            self._update_publishing_status(cluster, crd)
        elif action == STATUS_ONLY:
            self._update_publishing_status(cluster, crd)
        elif action == DELETED_ACTION:
            if self._is_manually_created_crd(crd):
                for gvr in _crd_gvrs(crd):
                    neg = self._negotiated_for(cluster, gvr)
                    if neg is not None:
                        self._delete_negotiated(cluster, neg)
            else:
                self._update_publishing_status(cluster, crd, deleted=True)

    # -- import events (negotiation.go:82-125)

    def _process_import(self, cluster: str, name: str, action: str) -> None:
        imp = self.import_informer.get(cluster, name)
        if imp is None:
            imp = self._deleted.get(("import", cluster, name))
            if imp is None:
                return
            gvr = _gvr_of_spec(imp)
            if self._negotiated_is_orphan(cluster, gvr):
                neg = self._negotiated_for(cluster, gvr)
                if neg is not None:
                    self._delete_negotiated(cluster, neg)
                return
            self.ensure_api_resource_compatibility(
                cluster, gvr, None, override_strategy=ar.UPDATE_PUBLISHED
            )
            return
        gvr = _gvr_of_spec(imp)
        if action in (CREATED, SPEC_CHANGED):
            self.ensure_api_resource_compatibility(cluster, gvr, imp)
        elif action == STATUS_ONLY:
            if (cond.find_condition(imp, ar.COMPATIBLE) is None
                    and cond.find_condition(imp, ar.AVAILABLE) is None):
                self.ensure_api_resource_compatibility(cluster, gvr, imp)

    # -- negotiated events (negotiation.go:126-171)

    def _process_negotiated(self, cluster: str, name: str, action: str) -> None:
        neg = self.negotiated_informer.get(cluster, name)
        if neg is None:
            neg = self._deleted.get(("negotiated", cluster, name))
            if neg is None:
                return
            if action == DELETED_ACTION:
                self._cleanup_negotiated(cluster, neg)
            return
        gvr = _gvr_of_spec(neg)
        if action in (CREATED, SPEC_CHANGED):
            if cond.is_condition_true(neg, ar.ENFORCED):
                self.ensure_api_resource_compatibility(
                    cluster, gvr, None, override_strategy=ar.UPDATE_NEVER
                )
            if neg["spec"].get("publish") and not cond.is_condition_true(neg, ar.ENFORCED):
                self._publish_negotiated(cluster, gvr, neg)
                neg = self.negotiated_informer.get(cluster, name) or neg
            self._update_related_imports(cluster, gvr, neg)
        elif action == STATUS_ONLY:
            self._update_related_imports(cluster, gvr, neg)
        elif action == DELETED_ACTION:
            self._cleanup_negotiated(cluster, neg)

    # ------------------------------------------------------------ helpers

    def _scoped(self, cluster: str) -> Client:
        return self.client.scoped(cluster)

    def _negotiated_for(self, cluster: str, gvr: GVR) -> dict | None:
        objs = self.negotiated_informer.index(
            "cluster_gvr", f"{cluster}|{gvr.group}|{gvr.version}|{gvr.resource}"
        )
        return copy.deepcopy(objs[0]) if objs else None

    def _imports_for(self, cluster: str, gvr: GVR) -> list[dict]:
        return [
            copy.deepcopy(o)
            for o in self.import_informer.index(
                "cluster_gvr", f"{cluster}|{gvr.group}|{gvr.version}|{gvr.resource}"
            )
        ]

    def _is_manually_created_crd(self, crd: dict) -> bool:
        for ref in crd["metadata"].get("ownerReferences") or []:
            if (ref.get("apiVersion") == f"{ar.GROUP}/{ar.VERSION}"
                    and ref.get("kind") == NEGOTIATED_KIND):
                return False
        return True

    # -- enforcement (negotiation.go:200-236)

    def _enforce_crd(self, cluster: str, crd: dict) -> None:
        for gvr in _crd_gvrs(crd):
            neg = self._negotiated_for(cluster, gvr)
            if neg is None:
                continue
            scoped = self._scoped(cluster)
            cond.set_condition(neg, ar.ENFORCED, cond.TRUE)
            neg = scoped.update_status(ar.NEGOTIATEDAPIRESOURCES, neg)
            version = crdapi.version_entry(crd, gvr.version)
            schema = ((version or {}).get("schema") or {}).get("openAPIV3Schema")
            if schema is not None:
                neg["spec"]["openAPIV3Schema"] = copy.deepcopy(schema)
                scoped.update(ar.NEGOTIATEDAPIRESOURCES, neg)

    # -- publishing status propagation (negotiation.go:239-293)

    def _update_publishing_status(self, cluster: str, crd: dict, deleted: bool = False) -> None:
        manually = self._is_manually_created_crd(crd)
        for gvr in _crd_gvrs(crd):
            neg = self._negotiated_for(cluster, gvr)
            if neg is None:
                continue
            if deleted:
                cond.set_condition(neg, ar.PUBLISHED, cond.FALSE, "CRDDeleted")
            elif (crdapi.is_established(crd)
                  and cond.is_condition_true(crd, crdapi.NAMES_ACCEPTED)):
                cond.set_condition(neg, ar.PUBLISHED, cond.TRUE)
            elif (cond.is_condition_false(crd, crdapi.ESTABLISHED)
                  or cond.is_condition_false(crd, crdapi.NAMES_ACCEPTED)):
                cond.set_condition(neg, ar.PUBLISHED, cond.FALSE, "Refused")
            cond.set_condition(neg, ar.ENFORCED, cond.TRUE if manually else cond.FALSE)
            self._scoped(cluster).update_status(ar.NEGOTIATEDAPIRESOURCES, neg)

    # -- the LCD fold (negotiation.go:338-585)

    def ensure_api_resource_compatibility(
        self,
        cluster: str,
        gvr: GVR,
        api_import: dict | None,
        override_strategy: str | None = None,
    ) -> None:
        negotiated = self._negotiated_for(cluster, gvr)
        imports = [api_import] if api_import is not None else self._imports_for(cluster, gvr)
        if not imports:
            return

        scoped = self._scoped(cluster)
        new_negotiated: dict | None = negotiated if api_import is not None else None
        updated_schema = False
        negotiated_existed = negotiated is not None

        # a manually created CRD supersedes everything (negotiation.go:390-455)
        crd = self.crd_informer.get(cluster, crd_name_for(gvr))
        if crd is not None and self._is_manually_created_crd(crd):
            version = crdapi.version_entry(crd, gvr.version)
            if version is not None:
                spec = ar.common_spec(
                    gvr.group, gvr.version,
                    crd["spec"]["names"]["plural"], crd["spec"]["names"]["kind"],
                    scope=crd["spec"].get("scope", "Namespaced"),
                    schema=(version.get("schema") or {}).get("openAPIV3Schema"),
                    sub_resources=(["status"] if "status" in (version.get("subresources") or {})
                                   else []),
                )
                new_negotiated = ar.new_negotiated_api_resource(spec, publish=True)
                new_negotiated["metadata"]["clusterName"] = cluster
                new_negotiated["metadata"].setdefault("annotations", {})[
                    API_VERSION_ANNOTATION
                ] = f"{gvr.group}/{gvr.version}" if gvr.group else gvr.version
                cond.set_condition(new_negotiated, ar.PUBLISHED, cond.TRUE)
                cond.set_condition(new_negotiated, ar.ENFORCED, cond.TRUE)

        import_status_writes: list[dict] = []
        for imp in imports:
            if new_negotiated is None:
                # first import founds the negotiated resource
                # (negotiation.go:461-486)
                new_negotiated = ar.new_negotiated_api_resource(
                    copy.deepcopy(
                        {k: v for k, v in imp["spec"].items()
                         if k not in ("location", "schemaUpdateStrategy")}
                    ),
                    publish=self.auto_publish,
                )
                new_negotiated["metadata"]["clusterName"] = cluster
                new_negotiated["metadata"].setdefault("annotations", {})[
                    API_VERSION_ANNOTATION
                ] = f"{gvr.group}/{gvr.version}" if gvr.group else gvr.version
                if negotiated is not None:
                    new_negotiated["metadata"]["resourceVersion"] = negotiated[
                        "metadata"]["resourceVersion"]
                    new_negotiated["spec"]["publish"] = negotiated["spec"].get("publish", False)
                updated_schema = True
                ar.set_compatible(imp, True)
            else:
                published = cond.is_condition_true(new_negotiated, ar.PUBLISHED)
                enforced = cond.is_condition_true(new_negotiated, ar.ENFORCED)
                if override_strategy == ar.UPDATE_NEVER:
                    allow_update = False
                elif override_strategy == ar.UPDATE_PUBLISHED:
                    allow_update = not enforced
                else:
                    allow_update = not enforced and ar.can_update(imp, published)
                import_schema = imp["spec"].get("openAPIV3Schema") or {}
                negotiated_schema = new_negotiated["spec"].get("openAPIV3Schema") or {}
                lcd, errs = self._lcd(
                    negotiated_schema, import_schema, allow_update,
                    new_negotiated["spec"].get("kind", "Schema"),
                )
                if errs:
                    ar.set_compatible(imp, False, "IncompatibleSchema", "; ".join(errs))
                else:
                    ar.set_compatible(imp, True)
                    if published:
                        ar.set_available(imp, True)
                    if allow_update and lcd != negotiated_schema:
                        new_negotiated["spec"]["openAPIV3Schema"] = lcd
                        updated_schema = True
            import_status_writes.append(imp)

        assert new_negotiated is not None
        if not negotiated_existed:
            try:
                created = scoped.create(ar.NEGOTIATEDAPIRESOURCES, new_negotiated)
            except errors.AlreadyExistsError:
                created = scoped.get(
                    ar.NEGOTIATEDAPIRESOURCES, new_negotiated["metadata"]["name"]
                )
            if (new_negotiated.get("status") or {}).get("conditions"):
                created["status"] = new_negotiated["status"]
                scoped.update_status(ar.NEGOTIATEDAPIRESOURCES, created)
        elif updated_schema:
            scoped.update(ar.NEGOTIATEDAPIRESOURCES, new_negotiated)

        for imp in import_status_writes:
            fresh = scoped.get(ar.APIRESOURCEIMPORTS, imp["metadata"]["name"])
            fresh["status"] = imp.get("status", {})
            scoped.update_status(ar.APIRESOURCEIMPORTS, fresh)

    def _negotiated_is_orphan(self, cluster: str, gvr: GVR) -> bool:
        if self._imports_for(cluster, gvr):
            return False
        neg = self._negotiated_for(cluster, gvr)
        if neg is None:
            return False
        return not cond.is_condition_true(neg, ar.ENFORCED)

    # -- CRD publication (negotiation.go:612-790)

    def _publish_negotiated(self, cluster: str, gvr: GVR, neg: dict) -> None:
        scoped = self._scoped(cluster)
        name = crd_name_for(gvr)
        schema = neg["spec"].get("openAPIV3Schema") or {"type": "object"}
        subresources = {}
        for sub in neg["spec"].get("subResources") or []:
            if sub.get("name") == "status":
                subresources["status"] = {}
            if sub.get("name") == "scale":
                subresources["scale"] = {
                    "specReplicasPath": ".spec.replicas",
                    "statusReplicasPath": ".status.replicas",
                }
        version_entry = {
            "name": gvr.version,
            "served": True,
            "storage": True,
            "schema": {"openAPIV3Schema": copy.deepcopy(schema)},
        }
        if subresources:
            version_entry["subresources"] = subresources
        owner_ref = {
            "apiVersion": f"{ar.GROUP}/{ar.VERSION}",
            "kind": NEGOTIATED_KIND,
            "name": neg["metadata"]["name"],
            "uid": neg["metadata"].get("uid"),
        }
        crd = self.crd_informer.get(cluster, name)
        if crd is None:
            new_crd = {
                "apiVersion": f"{crdapi.GROUP}/{crdapi.VERSION}",
                "kind": "CustomResourceDefinition",
                "metadata": {
                    "name": name,
                    "clusterName": cluster,
                    "ownerReferences": [owner_ref],
                },
                "spec": {
                    "group": gvr.group,
                    "scope": neg["spec"].get("scope", "Namespaced"),
                    "names": {
                        "plural": neg["spec"]["plural"],
                        "singular": neg["spec"].get("singular", ""),
                        "kind": neg["spec"]["kind"],
                        "listKind": neg["spec"].get("listKind", neg["spec"]["kind"] + "List"),
                    },
                    "versions": [version_entry],
                },
            }
            if is_protected_group(gvr.group):
                new_crd["metadata"]["annotations"] = {
                    crdapi.API_APPROVED_ANNOTATION: "https://github.com/kcp-dev/kubernetes/pull/4"
                }
            try:
                scoped.create(crdapi.CRDS, new_crd)
            except errors.AlreadyExistsError:
                pass
        elif not self._is_manually_created_crd(crd):
            crd = copy.deepcopy(crd)
            versions = crd["spec"].setdefault("versions", [])
            new_is_latest = all(
                compare_kube_aware(v["name"], gvr.version) <= 0 for v in versions
            )
            if not new_is_latest:
                version_entry["storage"] = False
            else:
                for v in versions:
                    v["storage"] = False
            for i, v in enumerate(versions):
                if v["name"] == gvr.version:
                    versions[i] = version_entry
                    break
            else:
                versions.append(version_entry)
            refs = crd["metadata"].setdefault("ownerReferences", [])
            if not any(r.get("name") == owner_ref["name"] and r.get("uid") == owner_ref["uid"]
                       for r in refs):
                refs.append(owner_ref)
            scoped.update(crdapi.CRDS, crd)

        fresh = scoped.get(ar.NEGOTIATEDAPIRESOURCES, neg["metadata"]["name"])
        cond.set_condition(fresh, ar.SUBMITTED, cond.TRUE)
        scoped.update_status(ar.NEGOTIATEDAPIRESOURCES, fresh)

    # -- Available propagation (negotiation.go:793-814)

    def _update_related_imports(self, cluster: str, gvr: GVR, neg: dict) -> None:
        published = cond.find_condition(neg, ar.PUBLISHED)
        if published is None:
            return
        scoped = self._scoped(cluster)
        for imp in self._imports_for(cluster, gvr):
            fresh = scoped.get(ar.APIRESOURCEIMPORTS, imp["metadata"]["name"])
            if cond.set_condition(fresh, ar.AVAILABLE, published["status"]):
                scoped.update_status(ar.APIRESOURCEIMPORTS, fresh)

    # -- deletion cascades (negotiation.go:295-332, 817-904)

    def _delete_negotiated(self, cluster: str, neg: dict) -> None:
        try:
            self._scoped(cluster).delete(
                ar.NEGOTIATEDAPIRESOURCES, neg["metadata"]["name"]
            )
        except errors.NotFoundError:
            pass

    def _cleanup_negotiated(self, cluster: str, neg: dict) -> None:
        gvr = _gvr_of_spec(neg)
        scoped = self._scoped(cluster)
        for imp in self._imports_for(cluster, gvr):
            fresh = scoped.get(ar.APIRESOURCEIMPORTS, imp["metadata"]["name"])
            removed = cond.remove_condition(fresh, ar.AVAILABLE)
            removed |= cond.remove_condition(fresh, ar.COMPATIBLE)
            if removed:
                scoped.update_status(ar.APIRESOURCEIMPORTS, fresh)

        crd = self.crd_informer.get(cluster, crd_name_for(gvr))
        if crd is None:
            return
        refs = crd["metadata"].get("ownerReferences") or []
        kept_refs = [r for r in refs
                     if not (r.get("name") == neg["metadata"]["name"]
                             and r.get("uid") == neg["metadata"].get("uid"))]
        if len(kept_refs) == len(refs):
            return  # not owned by this negotiated resource
        kept_versions = [v for v in crd["spec"].get("versions", [])
                         if v["name"] != gvr.version]
        if len(kept_versions) == len(crd["spec"].get("versions", [])):
            return
        if not kept_versions:
            try:
                scoped.delete(crdapi.CRDS, crd["metadata"]["name"])
            except errors.NotFoundError:
                pass
        else:
            crd = copy.deepcopy(crd)
            crd["spec"]["versions"] = kept_versions
            crd["metadata"]["ownerReferences"] = kept_refs
            scoped.update(crdapi.CRDS, crd)

    # ---------------------------------------------------------- lifecycle

    async def start(self, num_workers: int = 2) -> None:
        await self.import_informer.start()
        await self.negotiated_informer.start()
        await self.crd_informer.start()
        await self.controller.start(num_workers)

    async def stop(self) -> None:
        await self.controller.stop()
        await self.import_informer.stop()
        await self.negotiated_informer.stop()
        await self.crd_informer.stop()
