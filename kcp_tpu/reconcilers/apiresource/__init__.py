from .controller import NegotiationController

__all__ = ["NegotiationController"]
