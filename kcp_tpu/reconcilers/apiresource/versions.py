"""Kube-aware API version ordering (v2 > v1 > v1beta2 > v1beta1 > v1alpha1).

Needed by CRD publication to decide which version holds storage=true
(reference: negotiation.go:731-753 uses version.CompareKubeAwareVersionStrings).
"""

from __future__ import annotations

import re

_VERSION_RE = re.compile(r"^v(\d+)(?:(alpha|beta)(\d+)?)?$")

_STABILITY = {"alpha": 0, "beta": 1, None: 2}


def version_priority(v: str) -> tuple:
    """Sort key: higher tuple = newer/more stable version.

    Kube-style versions outrank everything else; among them stability wins
    (GA > beta > alpha), then major, then minor. Non-kube versions compare
    lexically among themselves.
    """
    m = _VERSION_RE.match(v)
    if not m:
        return (0, 0, 0, 0, v)
    major, stability, minor = m.groups()
    return (1, _STABILITY[stability], int(major), int(minor or 0), "")


def compare_kube_aware(a: str, b: str) -> int:
    """>0 if a outranks b, <0 if b outranks a, 0 if equal."""
    ka, kb = version_priority(a), version_priority(b)
    return (ka > kb) - (ka < kb)
