from .controller import ClusterController, SyncerMode

__all__ = ["ClusterController", "SyncerMode"]
