"""Cluster controller: connect physical clusters to the control plane.

Behavioral parity with the reference (pkg/reconciler/cluster/
{controller,cluster}.go):

- a ``Cluster`` CR names a physical cluster via ``spec.kubeconfig``;
  invalid kubeconfigs set Ready=False and deliberately do NOT retry
  (cluster.go:32-47 "return nil // Don't retry")
- per cluster, an :class:`APIImporter` polls the physical cluster's
  schemas into APIResourceImport objects (cluster.go:49-59)
- the synced resource set = imports with Compatible AND Available
  conditions (via the location index) plus built-in control-plane
  resources that intersect resources_to_sync (cluster.go:61-92)
- when the set changes, the syncer is (re)started: push mode runs
  :class:`kcp_tpu.syncer.Syncer` in-process, pull mode installs the
  syncer workload into the physical cluster (cluster.go:94-165)
- pull mode health is re-checked every reconcile; failure flips Ready
  (cluster.go:175-194)
- the cluster re-reconciles itself every poll interval (cluster.go:196-202)
- deletion stops the importer and syncer and uninstalls (cluster.go:206-239)
"""

from __future__ import annotations

import asyncio
import logging
from enum import Enum

from ...apis import apiresource as ar
from ...apis import cluster as clusterapi
from ...client import Client, Informer
from ...reconciler.controller import Controller
from ...syncer import Syncer
from ...utils import errors
from ..cluster.apiimporter import APIImporter
from . import installer
from .installer import DEFAULT_SYNCER_IMAGE

log = logging.getLogger(__name__)


class SyncerMode(Enum):
    PUSH = "push"
    PULL = "pull"
    NONE = "none"


DEFAULT_POLL_INTERVAL = 60.0  # reference: cluster.go:22


class ClusterController:
    def __init__(
        self,
        client: Client,  # wildcard multi-cluster client to the control plane
        registry,  # PhysicalRegistry
        resources_to_sync: list[str] | None = None,
        mode: SyncerMode = SyncerMode.PUSH,
        backend: str = "tpu",
        poll_interval: float = DEFAULT_POLL_INTERVAL,
        import_poll_interval: float | None = None,
        kcp_kubeconfig: str = "",
        syncer_image: str = DEFAULT_SYNCER_IMAGE,
        mesh=None,
        mesh_spec: str = "",
    ):
        self.client = client
        self.registry = registry
        self.resources_to_sync = resources_to_sync or ["deployments.apps"]
        self.mode = mode
        self.backend = backend
        self.mesh = mesh  # sharding for push-mode syncers' fused core
        # pull mode ships the sharding as a CLI spec in the pod manifest
        # (a live Mesh object cannot cross the process boundary)
        self.mesh_spec = mesh_spec
        self.poll_interval = poll_interval
        self.import_poll_interval = (
            import_poll_interval if import_poll_interval is not None else poll_interval
        )
        self.kcp_kubeconfig = kcp_kubeconfig
        self.syncer_image = syncer_image

        self.informer = Informer(client, clusterapi.CLUSTERS)
        self.import_informer = Informer(client, ar.APIRESOURCEIMPORTS)
        # LocationInLogicalCluster index (reference controller.go:134-149)
        self.import_informer.add_indexer(
            "location",
            lambda o: [f'{o["metadata"].get("clusterName", "")}/{o["spec"].get("location", "")}'],
        )
        self.controller = Controller("cluster", self._process)
        self.informer.add_handler(self._on_event)
        self.import_informer.add_handler(self._on_import_event)

        self.importers: dict[tuple[str, str], APIImporter] = {}
        self.syncers: dict[tuple[str, str], Syncer] = {}
        self._deleted: dict[tuple[str, str], dict] = {}

    # ------------------------------------------------------------ events

    def _on_event(self, etype: str, old: dict | None, new: dict | None) -> None:
        obj = new or old
        key = (obj["metadata"].get("clusterName", ""), obj["metadata"]["name"])
        if etype == "DELETED":
            self._deleted[key] = obj
        self.controller.enqueue(key)

    def _on_import_event(self, etype: str, old: dict | None, new: dict | None) -> None:
        # condition changes on imports re-trigger their cluster
        obj = new or old
        lc = obj["metadata"].get("clusterName", "")
        location = obj.get("spec", {}).get("location", "")
        if location:
            self.controller.enqueue((lc, location))

    # ----------------------------------------------------------- process

    async def _process(self, key) -> None:
        lc, name = key
        cluster = self.informer.get(lc, name)
        if cluster is None:
            await self._cleanup(key)
            return
        await self._reconcile(key, cluster)

    async def _reconcile(self, key, cluster: dict) -> None:
        lc, name = key
        scoped = self.client.scoped(lc)

        # 1. resolve the physical cluster (invalid => Ready=False, no retry)
        kubeconfig = cluster.get("spec", {}).get("kubeconfig", "")
        try:
            physical = self.registry.resolve(kubeconfig)
        except ValueError as err:
            self._set_status(scoped, cluster, ready=False,
                             reason=clusterapi.REASON_INVALID_KUBECONFIG, message=str(err))
            return  # don't retry (cluster.go:38)

        # 2. one importer per cluster (cluster.go:49-59)
        if key not in self.importers:
            imp = APIImporter(
                scoped, physical, name, self.resources_to_sync,
                poll_interval=self.import_poll_interval,
            )
            imp.start()
            self.importers[key] = imp

        # 3. synced resources = compatible∧available imports + builtins
        #    (cluster.go:61-92)
        ready_imports = [
            o for o in self.import_informer.index("location", f"{lc}/{name}")
            if ar.is_compatible_and_available(o)
        ]
        synced = {str(ar.gvr_of(o)) for o in ready_imports}
        builtin = {i.gvr.storage_name for i in self.client.scheme.all()}
        from ...apis.scheme import GVR
        synced |= {GVR.parse(r).storage_name for r in self.resources_to_sync
                   if GVR.parse(r).storage_name in builtin}

        if sorted(synced) != clusterapi.synced_resources(cluster):
            await self._restart_syncer(key, cluster, scoped, physical, sorted(synced))
            cluster = scoped.get(clusterapi.CLUSTERS, name)

        # 4. pull-mode health check (cluster.go:175-194). `cluster.health`
        #    is a KCP_FAULTS injection point: an injected error reads as
        #    an unhealthy syncer, so chaos schedules can flap a cluster's
        #    Ready condition deterministically (the flip feeds the
        #    deployment splitter's health-gated evacuation)
        if self.mode == SyncerMode.PULL and clusterapi.synced_resources(cluster):
            healthy, msg = installer.healthcheck_syncer(physical)
            try:
                from ... import faults

                faults.maybe_fail("cluster.health")
            except Exception as err:  # noqa: BLE001 — injected unhealth
                healthy, msg = False, f"injected fault: {err}"
            if not healthy:
                self._set_status(scoped, cluster, ready=False,
                                 reason=clusterapi.REASON_SYNCER_NOT_READY, message=msg)
            else:
                self._set_status(scoped, cluster, ready=True)

        # 5. periodic self-requeue (cluster.go:196-202)
        self.controller.enqueue_after(key, self.poll_interval)

    async def _restart_syncer(
        self, key, cluster: dict, scoped: Client, physical: Client, synced: list[str]
    ) -> None:
        lc, name = key
        old = self.syncers.pop(key, None)
        if old is not None:
            await old.stop()
        if not synced:
            self._set_status(scoped, cluster, ready=True, synced=synced)
            return
        if self.mode == SyncerMode.PUSH:
            try:
                syncer = Syncer(scoped, physical, synced, name,
                                backend=self.backend, mesh=self.mesh)
                await syncer.start()
                self.syncers[key] = syncer
            except Exception as err:  # noqa: BLE001
                self._set_status(scoped, cluster, ready=False,
                                 reason=clusterapi.REASON_ERROR_STARTING_SYNCER,
                                 message=str(err))
                raise
            self._set_status(scoped, cluster, ready=True, synced=synced)
        elif self.mode == SyncerMode.PULL:
            try:
                installer.install_syncer(
                    physical, name, self.kcp_kubeconfig, synced,
                    self.syncer_image, mesh_spec=self.mesh_spec,
                )
            except Exception as err:  # noqa: BLE001
                self._set_status(scoped, cluster, ready=False,
                                 reason=clusterapi.REASON_ERROR_INSTALLING_SYNCER,
                                 message=str(err))
                raise
            self._set_status(scoped, cluster, ready=None, synced=synced)
        else:  # SyncerMode.NONE: mark ready without syncing (cluster.go:166-171)
            self._set_status(scoped, cluster, ready=True, synced=synced)

    def _set_status(
        self, scoped: Client, cluster: dict, ready: bool | None,
        reason: str = "", message: str = "", synced: list[str] | None = None,
    ) -> None:
        name = cluster["metadata"]["name"]
        fresh = scoped.get(clusterapi.CLUSTERS, name)
        if synced is not None:
            clusterapi.set_synced_resources(fresh, synced)
        was_ready = clusterapi.is_ready(fresh)
        if ready is True:
            clusterapi.set_ready(fresh, reason, message)
        elif ready is False:
            clusterapi.set_not_ready(fresh, reason, message)
        if ready is not None and ready != was_ready:
            # flip telemetry: the evacuation runbook's flap-rate signal
            from ...utils.trace import REGISTRY

            REGISTRY.counter(
                "cluster_ready_transitions_total",
                "Ready condition flips written by the cluster reconciler",
            ).inc()
        try:
            scoped.update_status(clusterapi.CLUSTERS, fresh)
        except errors.ConflictError:
            self.controller.enqueue((cluster["metadata"].get("clusterName", ""), name))

    async def _cleanup(self, key) -> None:
        """Deletion teardown (cluster.go:206-239)."""
        imp = self.importers.pop(key, None)
        if imp is not None:
            imp.stop()
        syncer = self.syncers.pop(key, None)
        if syncer is not None:
            await syncer.stop()
        if self.mode == SyncerMode.PULL:
            deleted = self._deleted.pop(key, None)
            if deleted is not None:
                try:
                    physical = self.registry.resolve(
                        deleted.get("spec", {}).get("kubeconfig", "")
                    )
                    installer.uninstall_syncer(physical)
                except ValueError:
                    pass
        self._deleted.pop(key, None)

    # ---------------------------------------------------------- lifecycle

    async def start(self, num_workers: int = 2) -> None:
        await self.informer.start()
        await self.import_informer.start()
        await self.controller.start(num_workers)

    async def stop(self) -> None:
        await self.controller.stop()
        for imp in self.importers.values():
            imp.stop()
        await asyncio.gather(*(s.stop() for s in self.syncers.values()))
        self.importers.clear()
        self.syncers.clear()
        await self.informer.stop()
        await self.import_informer.stop()
