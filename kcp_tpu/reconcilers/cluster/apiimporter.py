"""APIImporter: periodic schema import from a physical cluster.

Behavioral parity with the reference's per-cluster import loop
(pkg/reconciler/cluster/apiimporter.go:29-207): every ``poll_interval``
the puller re-reads the physical cluster's view of the resources to sync
and reconciles ``APIResourceImport`` objects in the logical cluster —
create on first sight, update when the pulled schema changed, delete when
the physical cluster stops serving the resource.
"""

from __future__ import annotations

import asyncio
import logging

from ...apis import apiresource as ar
from ...apis import crd as crdapi
from ...client import Client
from ...crdpuller import SchemaPuller
from ...utils import errors

log = logging.getLogger(__name__)

DEFAULT_POLL_INTERVAL = 60.0  # reference: apiimporter.go:37


class APIImporter:
    def __init__(
        self,
        kcp: Client,  # scoped to the logical cluster
        physical: Client,
        location: str,  # Cluster object name
        resources_to_sync: list[str],
        poll_interval: float = DEFAULT_POLL_INTERVAL,
    ):
        self.kcp = kcp
        self.puller = SchemaPuller(physical)
        self.location = location
        self.resources_to_sync = list(resources_to_sync)
        self.poll_interval = poll_interval
        self._task: asyncio.Task | None = None
        self.done_event = asyncio.Event()  # set after each import pass

    def start(self) -> None:
        if self._task is None:
            self._task = asyncio.create_task(self._loop())

    async def _loop(self) -> None:
        while True:
            try:
                self.import_apis()
            except Exception:  # noqa: BLE001 — import is retried next tick
                log.exception("api import for %s failed", self.location)
            self.done_event.set()
            await asyncio.sleep(self.poll_interval)

    def import_apis(self) -> None:
        """One import pass (reference: ImportAPIs, apiimporter.go:77-207)."""
        pulled = self.puller.pull_crds(self.resources_to_sync)
        for resource, crd in pulled.items():
            if crd is None:
                self._delete_import_if_exists(resource)
                continue
            for version in crd["spec"].get("versions", []):
                spec = ar.common_spec(
                    group=crd["spec"]["group"],
                    version=version["name"],
                    plural=crd["spec"]["names"]["plural"],
                    kind=crd["spec"]["names"]["kind"],
                    scope=crd["spec"].get("scope", "Namespaced"),
                    schema=(version.get("schema") or {}).get("openAPIV3Schema"),
                    sub_resources=(
                        ["status"] if "status" in (version.get("subresources") or {}) else []
                    ),
                )
                obj = ar.new_api_resource_import(self.location, spec)
                name = obj["metadata"]["name"]
                try:
                    existing = self.kcp.get(ar.APIRESOURCEIMPORTS, name)
                except errors.NotFoundError:
                    self.kcp.create(ar.APIRESOURCEIMPORTS, obj)
                    log.info("created APIResourceImport %s", name)
                    continue
                if existing["spec"].get("openAPIV3Schema") != spec["openAPIV3Schema"]:
                    existing["spec"]["openAPIV3Schema"] = spec["openAPIV3Schema"]
                    self.kcp.update(ar.APIRESOURCEIMPORTS, existing)
                    log.info("updated APIResourceImport %s", name)

    def _delete_import_if_exists(self, resource: str) -> None:
        """Delete every import this location holds for the resource.

        Deletion goes by listing actual imports (matching location +
        plural + group), not by reconstructing names: the pulled CRD may
        have served any version(s), so a name rebuilt from the requested
        resource string would miss non-default-version imports.
        """
        from ...apis.scheme import GVR

        gvr = GVR.parse(resource)
        items, _ = self.kcp.list(ar.APIRESOURCEIMPORTS)
        for obj in items:
            spec = obj.get("spec", {})
            gv = spec.get("groupVersion", {})
            if (spec.get("location") == self.location
                    and spec.get("plural") == gvr.resource
                    and gv.get("group", "") == gvr.group):
                try:
                    self.kcp.delete(ar.APIRESOURCEIMPORTS, obj["metadata"]["name"])
                    log.info("deleted APIResourceImport %s (resource gone)",
                             obj["metadata"]["name"])
                except errors.NotFoundError:
                    pass

    def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            self._task = None


__all__ = ["APIImporter", "DEFAULT_POLL_INTERVAL", "crdapi"]
