"""Pull-mode syncer installation into a physical cluster.

Behavioral parity with the reference's installer (pkg/reconciler/cluster/
syncer.go:38-252): render the syncer's namespace, service account, RBAC,
kubeconfig ConfigMap and Deployment into the physical cluster; health is
judged from the running workload; uninstall deletes the namespace.

In the reference the deployed image is a Go binary; here the deployed
artifact is this framework's own syncer CLI (cli/syncer_main.py) — the
manifests carry its arguments the same way (cluster id + resource list).
"""

from __future__ import annotations

import logging

from ...client import Client
from ...utils import errors

log = logging.getLogger(__name__)

SYNCER_NAMESPACE = "kcp-syncer"
SYNCER_NAME = "syncer"
# the one definition of the default pull-mode image (contrib/syncer-image);
# Config, both CLIs, and the controller import it
DEFAULT_SYNCER_IMAGE = "kcp-tpu/syncer:latest"


def syncer_manifests(
    cluster_name: str, kcp_kubeconfig: str, resources: list[str], image: str,
    mesh_spec: str = "",
) -> list[tuple[str, dict]]:
    """(gvr, object) pairs to apply, mirroring installSyncer's manifest set
    (syncer.go:38-227). ``mesh_spec`` forwards the serving-mesh sharding
    to the pod's syncer CLI (--mesh) so pull mode shards like push mode."""
    mesh_args = ["--mesh", mesh_spec] if mesh_spec else []
    return [
        ("namespaces", {
            "apiVersion": "v1", "kind": "Namespace",
            "metadata": {"name": SYNCER_NAMESPACE},
        }),
        ("serviceaccounts", {
            "apiVersion": "v1", "kind": "ServiceAccount",
            "metadata": {"name": SYNCER_NAME, "namespace": SYNCER_NAMESPACE},
        }),
        ("clusterroles.rbac.authorization.k8s.io", {
            "apiVersion": "rbac.authorization.k8s.io/v1", "kind": "ClusterRole",
            "metadata": {"name": SYNCER_NAME},
            "rules": [
                {"apiGroups": ["*"], "resources": ["*"],
                 "verbs": ["create", "get", "list", "watch", "update", "patch", "delete"]},
            ],
        }),
        ("clusterrolebindings.rbac.authorization.k8s.io", {
            "apiVersion": "rbac.authorization.k8s.io/v1", "kind": "ClusterRoleBinding",
            "metadata": {"name": SYNCER_NAME},
            "roleRef": {"apiGroup": "rbac.authorization.k8s.io",
                        "kind": "ClusterRole", "name": SYNCER_NAME},
            "subjects": [{"kind": "ServiceAccount", "name": SYNCER_NAME,
                          "namespace": SYNCER_NAMESPACE}],
        }),
        ("configmaps", {
            "apiVersion": "v1", "kind": "ConfigMap",
            "metadata": {"name": f"{SYNCER_NAME}-kubeconfig", "namespace": SYNCER_NAMESPACE},
            "data": {"kubeconfig": kcp_kubeconfig},
        }),
        ("deployments.apps", {
            "apiVersion": "apps/v1", "kind": "Deployment",
            "metadata": {"name": SYNCER_NAME, "namespace": SYNCER_NAMESPACE},
            "spec": {
                "replicas": 1,
                "selector": {"matchLabels": {"app": SYNCER_NAME}},
                "template": {
                    "metadata": {"labels": {"app": SYNCER_NAME}},
                    "spec": {
                        "serviceAccountName": SYNCER_NAME,
                        "containers": [{
                            "name": SYNCER_NAME,
                            "image": image,
                            "args": (["-from_kubeconfig",
                                      "/kcp/kubeconfig",
                                      "-cluster", cluster_name]
                                     + mesh_args + list(resources)),
                            "volumeMounts": [{"name": "kubeconfig", "mountPath": "/kcp"}],
                        }],
                        "volumes": [{"name": "kubeconfig", "configMap": {
                            "name": f"{SYNCER_NAME}-kubeconfig"}}],
                    },
                },
            },
        }),
    ]


def install_syncer(
    physical: Client, cluster_name: str, kcp_kubeconfig: str,
    resources: list[str], image: str = DEFAULT_SYNCER_IMAGE,
    mesh_spec: str = "",
) -> None:
    for gvr, obj in syncer_manifests(cluster_name, kcp_kubeconfig, resources,
                                     image, mesh_spec):
        ns = obj["metadata"].get("namespace", "")
        try:
            physical.create(gvr, obj, namespace=ns)
        except errors.AlreadyExistsError:
            existing = physical.get(gvr, obj["metadata"]["name"], ns)
            obj["metadata"]["resourceVersion"] = existing["metadata"]["resourceVersion"]
            physical.update(gvr, obj, namespace=ns)


def uninstall_syncer(physical: Client) -> None:
    """Reference parity: deleting the namespace tears the syncer down
    (syncer.go:229-234)."""
    try:
        physical.delete("namespaces", SYNCER_NAMESPACE)
    except errors.NotFoundError:
        pass
    try:
        physical.delete("deployments.apps", SYNCER_NAME, SYNCER_NAMESPACE)
    except errors.NotFoundError:
        pass


def healthcheck_syncer(physical: Client) -> tuple[bool, str]:
    """Is the installed syncer workload healthy? (syncer.go:236-252 polls
    the pod phase; here the Deployment's readyReplicas stands in, since
    the fake agent maintains workload status.)"""
    try:
        dep = physical.get("deployments.apps", SYNCER_NAME, SYNCER_NAMESPACE)
    except errors.NotFoundError:
        return False, "syncer deployment not found"
    ready = (dep.get("status") or {}).get("readyReplicas", 0) or 0
    if ready < 1:
        return False, f"syncer not ready ({ready} ready replicas)"
    return True, ""
