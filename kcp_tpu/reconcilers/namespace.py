"""Namespace lifecycle: finalize-and-sweep on deletion, per tenant.

The reference wires the upstream Kubernetes namespace controller into
kcp as a post-start hook with a per-logical-cluster discovery function
("start-namespace-controller", pkg/server/server.go:325-356). Its job:
a deleted Namespace first gains a deletionTimestamp while its
``kubernetes`` finalizer holds it; the controller then deletes every
namespaced object inside, and only once the namespace is empty does it
strip the finalizer so the namespace disappears.

This controller re-expresses that per-tenant sweep over the logical
store: it watches namespaces across ALL logical clusters at once (one
wildcard watch instead of one controller instance per tenant — the
cross-tenant fan-in idiom this framework uses everywhere), discovers
namespaced resources from the live Scheme (the per-cluster discovery
analog), and sweeps with plain client deletes so cascades (objects with
their own finalizers) settle level-triggered over repeated reconciles.

The ``kubernetes`` finalizer itself is stamped synchronously at create
by the store (admission-style, store.py) so a create+delete race can
never skip the sweep; a DELETED namespace still reconciles once more to
sweep any orphaned contents (e.g. after a manual finalizer removal).
"""

from __future__ import annotations

import logging

from ..client import Client, Informer
from ..reconciler.controller import Controller
from ..utils.errors import RetryableError

log = logging.getLogger(__name__)

FINALIZER = "kubernetes"  # upstream's namespace lifecycle finalizer name
NAMESPACES = "namespaces"


class NamespaceLifecycleController:
    """Finalizer management + content sweep for namespace deletion."""

    def __init__(self, client: Client):
        self.client = client
        self.informer = Informer(client, NAMESPACES)
        self.controller = Controller("namespace-lifecycle", self._process)
        self.informer.add_handler(self._on_event)

    def _on_event(self, etype: str, old: dict | None, new: dict | None) -> None:
        # DELETED included: a final reconcile sweeps contents orphaned by
        # out-of-band finalizer removal
        m = (new or old)["metadata"]
        self.controller.enqueue((m.get("clusterName", ""), m["name"]))

    def _namespaced_resources(self) -> list[str]:
        """Discovery: every namespaced resource the scheme serves now.

        Runs per reconcile so CRD-backed resources registered after
        startup are swept too (the reference's per-logical-cluster
        discoveryFn is rebuilt per call the same way, server.go:336-344).
        """
        return [
            info.gvr.storage_name
            for info in self.client.scheme.all()
            if info.namespaced
        ]

    def _sweep(self, scoped: Client, name: str) -> int:
        """Delete namespace contents; return how many objects remain."""
        remaining = 0
        for resource in self._namespaced_resources():
            if resource == NAMESPACES:
                continue
            objs, _ = scoped.list(resource, namespace=name)
            for obj in objs:
                remaining += 1
                if not obj["metadata"].get("deletionTimestamp"):
                    scoped.delete(resource, obj["metadata"]["name"], namespace=name)
        return remaining

    async def _process(self, item) -> None:
        cluster, name = item
        scoped = self.client.scoped(cluster)
        ns = self.informer.get(cluster, name)
        if ns is None:
            # namespace already gone (e.g. finalizer removed out of
            # band): sweep orphaned contents so nothing leaks
            if self._sweep(scoped, name):
                raise RetryableError(f"orphaned contents of {cluster}/{name} draining")
            return
        meta = ns["metadata"]
        finalizers = meta.get("finalizers") or []

        if not meta.get("deletionTimestamp"):
            # the store stamps the finalizer at create; repair it here if
            # something stripped it from a live namespace
            if FINALIZER not in finalizers:
                fresh = scoped.get(NAMESPACES, name)
                fins = fresh["metadata"].setdefault("finalizers", [])
                if FINALIZER not in fins:  # re-check: informer copy is stale
                    fins.append(FINALIZER)
                    scoped.update(NAMESPACES, fresh)
            return

        # terminating: sweep contents, then release the finalizer
        if self._sweep(scoped, name):
            # cascading deletes (finalizered contents) settle over time;
            # retryable -> the workqueue's exponential backoff paces the
            # re-list instead of a fixed-rate poll
            raise RetryableError(f"namespace {cluster}/{name} not yet empty")
        if FINALIZER in finalizers:
            fresh = scoped.get(NAMESPACES, name)
            fresh["metadata"]["finalizers"] = [
                f for f in fresh["metadata"].get("finalizers", []) if f != FINALIZER
            ]
            scoped.update(NAMESPACES, fresh)  # store removes it once empty

    async def start(self) -> None:
        await self.informer.start()
        await self.controller.start(2)

    async def stop(self) -> None:
        await self.controller.stop()
        await self.informer.stop()
