"""Deployment splitter: multi-cluster workload placement, batched.

The reference controller (pkg/reconciler/deployment/) splits a root
Deployment's replicas across registered Clusters into labeled leaf
Deployments and aggregates leaf status back into the root, one object per
goroutine wakeup. Here the placement math for EVERY root across EVERY
logical cluster runs as one device program per tick
(ops/placement.split_replicas / aggregate_status — BASELINE.json
configs[2]: 10k workspaces x 8 clusters in one call).

Behavior parity (pkg/reconciler/deployment/deployment.go):
- a deployment without the ``kcp.dev/cluster`` label is a *root*; with it,
  a *leaf* (deployment.go:24)
- leafs are named ``<root>--<cluster>``, labeled with cluster + owned-by,
  owner-referenced to the root (deployment.go:127-157)
- replicas: even split; the whole remainder lands on the first cluster
  (deployment.go:127-145); no registered clusters -> Progressing=False
  with reason NoRegisteredClusters (deployment.go:110-123)
- leafs are only created when none exist yet (deployment.go:35-39);
  ``rebalance=True`` opts into re-splitting on root/cluster changes (an
  improvement over the reference, off by default for golden parity)
- status: sum the 5 replica counters over leafs; conditions copied from
  the first leaf; conflicts requeue (deployment.go:71-103)
"""

from __future__ import annotations

import copy
import logging
from typing import Sequence

import numpy as np

from ...apis.cluster import CLUSTERS
from ...apis.scheme import GVR
from ...client import Client, Informer
from ...fleet.inventory import ClusterInventory
from ...ops.encode import pad_pow2
from ...ops.placement import aggregate_status_jit
from ...reconciler.controller import BatchController
from ...utils import errors
from ...utils.trace import REGISTRY

log = logging.getLogger(__name__)

CLUSTER_LABEL = "kcp.dev/cluster"
OWNED_BY_LABEL = "kcp.dev/owned-by"

DEPLOYMENTS = GVR("apps", "v1", "deployments")

_COUNTERS = ("replicas", "updatedReplicas", "readyReplicas",
             "availableReplicas", "unavailableReplicas")

# health-gated evacuation: a cluster must hold NotReady for this long
# before its leaf deployments drain — a Ready->NotReady->Ready flap
# inside the window causes ZERO placement churn (hysteresis)
DEFAULT_EVAC_HYSTERESIS = 5.0


def _labels(obj: dict) -> dict:
    return (obj.get("metadata") or {}).get("labels") or {}


def is_root(obj: dict) -> bool:
    return not _labels(obj).get(CLUSTER_LABEL)


def leaf_name(root_name: str, cluster_name: str) -> str:
    return f"{root_name}--{cluster_name}"


class DeploymentSplitter:
    """Batched root-splitting + status fan-in over all logical clusters."""

    def __init__(
        self,
        client: Client,
        backend: str = "tpu",
        rebalance: bool = False,
        max_pclusters: int = 8,
        core=None,
        evac_hysteresis: float = DEFAULT_EVAC_HYSTERESIS,
        place: bool = True,
        inventory: ClusterInventory | None = None,
    ):
        self.client = client
        self.backend = backend
        self.fused = backend == "tpu"
        self.core = core  # FusedCore (tpu backend; lazily bound at start)
        self._pbucket = None
        self.rebalance = rebalance
        self.max_pclusters = max_pclusters
        # health-gated evacuation now lives in the shared fleet inventory
        # (fleet/inventory.py): Ready flips arm its hysteresis FSM, and
        # the same instance feeds the FleetScheduler when one is driving.
        # `place=False` hands the placement *decision* to that scheduler
        # while this controller keeps informers, status fan-in and drains.
        self.evac_hysteresis = evac_hysteresis
        self.inventory = (inventory if inventory is not None
                          else ClusterInventory(evac_hysteresis=evac_hysteresis))
        self.place = place
        self.replan_sink = None  # FleetScheduler's evac/readmit intake
        self._force_replan: set[tuple[str, str, str]] = set()
        self.informer = Informer(client, DEPLOYMENTS)
        self.cluster_informer = Informer(client, CLUSTERS)
        self.informer.add_indexer("owned_by", self._owned_by_index)
        self.informer.add_indexer("by_workspace", self._by_workspace_index)
        self.controller = BatchController(
            "deployment-splitter", self._process_batch,
            # item = ("root"|"leaf", (clusterName, ns, name)): fairness is
            # per logical cluster
            tenant_of=lambda item: item[1][0],
        )
        self.informer.add_handler(self._on_event)
        self.cluster_informer.add_handler(self._on_cluster_event)
        # fused bookkeeping: staged cluster-count per root (stale-apply
        # detection) and device counts awaiting a successful apply
        self._staged_n: dict[tuple[str, str, str], int] = {}
        self._retry_counts: dict[tuple[str, str, str], np.ndarray] = {}
        # applier pool: placement_apply is called from the core's tick
        # loop and must not block it (the SectionOwner contract)
        self._apply_q: "asyncio.Queue | None" = None
        self._apply_tasks: list = []
        self.stats = {"ticks": 0, "splits": 0, "aggregations": 0,
                      "fused_placements": 0}

    @staticmethod
    def _owned_by_index(obj: dict) -> list[str]:
        owner = _labels(obj).get(OWNED_BY_LABEL)
        m = obj["metadata"]
        if not owner:
            return []
        return [f'{m.get("clusterName", "")}/{m.get("namespace", "")}/{owner}']

    @staticmethod
    def _by_workspace_index(obj: dict) -> list[str]:
        """Roots keyed by logical cluster — replans look up ONE workspace
        instead of scanning every object of every tenant."""
        if not is_root(obj):
            return []
        return [obj["metadata"].get("clusterName", "")]

    @property
    def _evacuated(self) -> frozenset:
        """(workspace, cluster) pairs currently evacuated (a read-only
        view over the shared inventory; kept for tests/introspection)."""
        return self.inventory.evacuated_pairs

    # ------------------------------------------------------------ events

    def _on_event(self, etype: str, old: dict | None, new: dict | None) -> None:
        obj = new or old
        m = obj["metadata"]
        key = (m.get("clusterName", ""), m.get("namespace", ""), m["name"])
        if is_root(obj):
            self.controller.enqueue(("root", key))
        else:
            owner = _labels(obj).get(OWNED_BY_LABEL)
            root_key = (m.get("clusterName", ""), m.get("namespace", ""), owner)
            self.controller.enqueue(("leaf", root_key))

    def _on_cluster_event(self, etype: str, old: dict | None, new: dict | None) -> None:
        obj = new or old
        lc = obj["metadata"].get("clusterName", "")
        name = obj["metadata"]["name"]
        ckey = (lc, name)
        # health gate: the cluster reconciler's Ready flips feed the
        # shared fleet inventory's hysteresis FSM. NotReady arms the
        # clock (a delayed "health" item decides); Ready inside the
        # window disarms it with ZERO churn; Ready after evacuation
        # readmits the cluster and re-splits its workspace's roots
        d = self.inventory.observe(lc, obj, etype)
        if d.notready_started:
            self.controller.enqueue_after(
                ("health", ckey), self.evac_hysteresis)
        if d.readmitted:
            log.info("deployment-splitter: cluster %s/%s Ready again; "
                     "readmitting and re-splitting its roots", lc, name)
            self._replan_roots(lc)
        # the cluster set changed: with rebalancing on, every root in that
        # logical cluster gets re-planned (indexed — no fleet-wide scan)
        if not self.rebalance:
            return
        for obj in self.informer.index("by_workspace", lc):
            m = obj["metadata"]
            self.controller.enqueue(
                ("root", (lc, m.get("namespace", ""), m["name"]))
            )

    # --------------------------------------------- health-gated evacuation

    def _replan_roots(self, lc: str) -> None:
        """Force every root in a logical cluster through a fresh split
        (drain or readmit must move replicas even without `rebalance`).
        Routed through the by_workspace index — a Ready flip touches ONE
        workspace's roots, never a fleet-wide rescan. With the placement
        decision delegated (`place=False`) the keys flow to the fleet
        scheduler's sink instead."""
        rkeys = []
        for obj in self.informer.index("by_workspace", lc):
            m = obj["metadata"]
            rkeys.append((lc, m.get("namespace", ""), m["name"]))
        if not self.place:
            if self.replan_sink is not None:
                self.replan_sink(lc, rkeys)
            return
        for rkey in rkeys:
            self._force_replan.add(rkey)
            self.controller.enqueue(("root", rkey))

    def _check_health(self, ckey: tuple[str, str]) -> None:
        """The delayed hysteresis decision: evacuate only if the cluster
        is STILL explicitly NotReady a full window after the flip (the
        inventory re-checks its event-fed state and bumps its version
        only on the pending->evacuated transition)."""
        lc, name = ckey
        if self.inventory.check_evacuate(lc, name):
            log.warning("deployment-splitter: evacuating cluster %s/%s "
                        "after sustained NotReady (> %.1fs)", lc, name,
                        self.evac_hysteresis)
            self._replan_roots(lc)

    # -------------------------------------------------------------- tick

    async def _process_batch(self, items: Sequence) -> list[tuple[object, Exception]]:
        self.stats["ticks"] += 1
        roots: dict[tuple[str, str, str], None] = {}
        aggregates: dict[tuple[str, str, str], None] = {}
        for kind, key in items:
            if kind == "health":
                self._check_health(key)
            elif kind == "root":
                if self.place:  # else the FleetScheduler decides
                    roots[key] = None
            else:
                aggregates[key] = None

        failed: list[tuple[object, Exception]] = []
        failed_keys = set()

        # ---- placement lane
        plan_rows = []
        for key in roots:
            root = self.informer.cache.get(key)
            if root is None or not is_root(root):
                if self.fused and self._pbucket is not None:
                    # root gone: retire its placement row
                    self._pbucket.free_pl_row(key)
                    self._staged_n.pop(key, None)
                    self._retry_counts.pop(key, None)
                continue
            leafs = self.informer.index("owned_by", "/".join(key))
            if leafs and not self.rebalance and key not in self._force_replan:
                continue  # reference behavior: only split once
            clusters = self._clusters_for(key[0])
            plan_rows.append((key, root, clusters, leafs))

        if plan_rows and self.fused and self._pbucket is not None:
            # SERVED path: roots ride the FusedCore's placement lanes —
            # the same fused step that serves the sync sections computes
            # the split, and dirty rows come back via placement_apply.
            # Under fleet dispatch (KCP_FLEET_BATCH, the default) the
            # kick wakes the whole-fleet ragged batch: placement rows
            # from every bucket concatenate into ONE device program's
            # placement lanes, and the FleetBatch scatters the dirty
            # roots back to this bucket's placement_apply on collect —
            # so ONE kick per drained batch stays the right granularity
            kicked = False
            for key, root, clusters, leafs in plan_rows:
                if not clusters:
                    # NoRegisteredClusters is pure host-side status
                    try:
                        self._apply_placement(key, root, clusters, leafs, None)
                    except Exception as err:  # noqa: BLE001
                        failed_keys.add(("root", key))
                        failed.append((("root", key), err))
                    continue
                retry = self._retry_counts.pop(key, None)
                if (retry is not None
                        and len(clusters) == self._staged_n.get(key)
                        and not self._counts_stale(root, retry)):
                    # a device-computed split failed to apply earlier;
                    # re-apply from the cached counts (re-staging the
                    # same inputs would not re-dirty the device row).
                    # Stale cache (spec changed since) falls through to
                    # a fresh staging instead.
                    try:
                        self._apply_placement(key, root, clusters, leafs, retry)
                    except Exception as err:  # noqa: BLE001
                        self._retry_counts[key] = retry
                        failed_keys.add(("root", key))
                        failed.append((("root", key), err))
                    continue
                replicas = root.get("spec", {}).get("replicas", 0) or 0
                self._pbucket.stage_placement(key, int(replicas), len(clusters))
                self._staged_n[key] = len(clusters)
                kicked = True
            if kicked:
                self.core.kick(self._pbucket)
        elif plan_rows:
            reps = np.array(
                [r[1].get("spec", {}).get("replicas", 0) or 0 for r in plan_rows],
                dtype=np.int32,
            )
            # width follows the widest row (padded pow2 for shape stability);
            # max_pclusters is only the padding floor, never a silent cap
            width = pad_pow2(
                max((len(r[2]) for r in plan_rows), default=1), floor=self.max_pclusters
            )
            avail = np.zeros((len(plan_rows), width), dtype=bool)
            for i, (_, _, clusters, _) in enumerate(plan_rows):
                avail[i, : len(clusters)] = True
            leaf_counts = self._host_split(reps, avail)
            for i, (key, root, clusters, leafs) in enumerate(plan_rows):
                try:
                    self._apply_placement(key, root, clusters, leafs, leaf_counts[i])
                except Exception as err:  # noqa: BLE001
                    failed_keys.add(("root", key))
                    failed.append((("root", key), err))

        # ---- aggregation lane: batch all status fan-ins
        agg_rows = []
        for key in aggregates:
            root = self.informer.cache.get(key)
            if root is None:
                continue
            leafs = self.informer.index("owned_by", "/".join(key))
            if leafs:
                agg_rows.append((key, root, leafs))
        if agg_rows:
            width = pad_pow2(
                max((len(r[2]) for r in agg_rows), default=1), floor=self.max_pclusters
            )
            counters = np.zeros((len(agg_rows), width, len(_COUNTERS)), np.int32)
            mask = np.zeros((len(agg_rows), width), bool)
            for i, (_, _, leafs) in enumerate(agg_rows):
                for j, leaf in enumerate(leafs):
                    st = leaf.get("status") or {}
                    mask[i, j] = True
                    for c, field in enumerate(_COUNTERS):
                        counters[i, j, c] = st.get(field, 0) or 0
            if self.backend == "tpu":
                sums = np.asarray(aggregate_status_jit(counters, mask))
            else:
                sums = (counters * mask[..., None]).sum(axis=1)
            for i, (key, root, leafs) in enumerate(agg_rows):
                try:
                    self._apply_aggregation(key, root, leafs, sums[i])
                except errors.ConflictError as err:
                    # conflicts requeue (deployment.go:93-103)
                    failed_keys.add(("leaf", key))
                    failed.append((("leaf", key), err))
                except Exception as err:  # noqa: BLE001
                    failed_keys.add(("leaf", key))
                    failed.append((("leaf", key), err))
        return failed

    # ------------------------------------------------- fused-core seam

    def placement_apply(self, applies: list[tuple[tuple[str, str, str], np.ndarray]]) -> None:
        """Dirty placement rows from a collected fused tick: hand off to
        the applier pool — this runs on the core's tick loop and must not
        block it (the SectionOwner contract, syncer/core.py)."""
        for entry in applies:
            self._apply_q.put_nowait(entry)

    def _counts_stale(self, root: dict, counts: np.ndarray) -> bool:
        """Device counts are provably for THESE inputs only when their
        sum equals the root's current replicas (the split preserves the
        sum). A mismatch means the row was re-used or the spec changed
        while the wire was in flight — restage, never apply."""
        want = int(root.get("spec", {}).get("replicas", 0) or 0)
        return int(np.sum(counts)) != want

    async def _apply_worker(self) -> None:
        while True:
            key, counts = await self._apply_q.get()
            try:
                self._apply_one_fused(key, counts)
            except Exception:  # noqa: BLE001 — worker must survive
                log.exception("deployment-splitter: fused apply crashed")
            finally:
                self._apply_q.task_done()

    def _apply_one_fused(self, key, counts: np.ndarray) -> None:
        root = self.informer.cache.get(key)
        if root is None or not is_root(root):
            return
        clusters = self._clusters_for(key[0])
        if len(clusters) != self._staged_n.get(key) or self._counts_stale(root, counts):
            # the cluster set / spec / row assignment changed while the
            # tick was in flight: restage with current inputs instead of
            # applying stale counts. The device's `current` has already
            # advanced past the rejected split, so force the placement
            # rows to re-emit — identical re-staged inputs would never
            # re-dirty otherwise
            if self._pbucket is not None:
                self._pbucket.invalidate_placement()
            self.controller.enqueue(("root", key))
            return
        leafs = self.informer.index("owned_by", "/".join(key))
        if leafs and not self.rebalance and key not in self._force_replan:
            return
        try:
            self._apply_placement(key, root, clusters, leafs, counts)
            self.stats["fused_placements"] += 1
        except Exception as err:  # noqa: BLE001
            log.info("deployment-splitter: fused placement apply for %r "
                     "failed (%s); requeued", key, err)
            self._retry_counts[key] = np.asarray(counts)
            self.controller.queue.add_rate_limited(("root", key))

    @staticmethod
    def _host_split(reps: np.ndarray, avail: np.ndarray) -> np.ndarray:
        out = np.zeros_like(avail, dtype=np.int32)
        for i in range(len(reps)):
            idxs = np.nonzero(avail[i])[0]
            if len(idxs) == 0:
                continue
            base, rem = divmod(int(reps[i]), len(idxs))
            for rank, j in enumerate(idxs):
                out[i, j] = base + (rem if rank == 0 else 0)
        return out

    # ------------------------------------------------------------- apply

    def _clusters_for(self, logical_cluster: str) -> list[dict]:
        """Placement-eligible clusters: evacuated (sustained-NotReady)
        clusters are excluded, so every split — host or fused lane —
        routes replicas only onto healthy capacity."""
        return sorted(
            (c for c in self.cluster_informer.list()
             if c["metadata"].get("clusterName", "") == logical_cluster
             and not self.inventory.is_evacuated(
                 logical_cluster, c["metadata"]["name"])),
            key=lambda c: c["metadata"]["name"],
        )

    def _apply_placement(
        self,
        key: tuple[str, str, str],
        root: dict,
        clusters: list[dict],
        existing_leafs: list[dict],
        counts: np.ndarray,
    ) -> None:
        lc, ns, name = key
        # forced replans (evacuation drain / readmission) move replicas
        # between existing leafs even when `rebalance` is off
        forced = key in self._force_replan
        scoped = self.client.scoped(lc)
        # churn = replica-moving writes AFTER initial placement (updates,
        # drains, late creates on readmission) — the bounded-migration
        # number the fleet scenarios assert on. Initial splits are free.
        had_leafs = bool(existing_leafs)
        churn = 0
        REGISTRY.counter(
            "placement_resolves_total",
            "root placements solved and applied (initial or re-solve)").inc()
        if not clusters:
            if forced:
                # every cluster is evacuated: drain ALL placed leafs
                for stale in existing_leafs:
                    churn += self._drain_leaf(scoped, lc, ns, stale)
            fresh = scoped.get(DEPLOYMENTS, name, ns)
            conds = [{
                "type": "Progressing",
                "status": "False",
                "reason": "NoRegisteredClusters",
                "message": "kcp has no clusters registered to receive Deployments",
            }]
            # idempotent: a re-applied no-candidate placement must not
            # rewrite identical status — the write bumps the root's RV,
            # which re-enqueues the root and re-solves it forever
            if (fresh.get("status") or {}).get("conditions") != conds:
                fresh.setdefault("status", {})["conditions"] = conds
                scoped.update_status(DEPLOYMENTS, fresh, namespace=ns)
            self._force_replan.discard(key)
            self._count_churn(churn)
            return
        by_name = {leaf["metadata"]["name"]: leaf for leaf in existing_leafs}
        for j, cl in enumerate(clusters):
            cl_name = cl["metadata"]["name"]
            lname = leaf_name(name, cl_name)
            desired_replicas = int(counts[j])
            existing = by_name.pop(lname, None)
            if existing is None:
                leaf = copy.deepcopy(root)
                m = leaf["metadata"]
                m["name"] = lname
                for f in ("resourceVersion", "uid", "creationTimestamp", "generation"):
                    m.pop(f, None)
                labels = m.setdefault("labels", {})
                labels[CLUSTER_LABEL] = cl_name
                labels[OWNED_BY_LABEL] = name
                m["ownerReferences"] = [{
                    "apiVersion": "apps/v1",
                    "kind": "Deployment",
                    "uid": root["metadata"].get("uid"),
                    "name": name,
                }]
                leaf.pop("status", None)
                leaf.setdefault("spec", {})["replicas"] = desired_replicas
                scoped.create(DEPLOYMENTS, leaf, namespace=ns)
                self.stats["splits"] += 1
                if had_leafs:
                    churn += 1
            elif ((self.rebalance or forced)
                  and existing.get("spec", {}).get("replicas") != desired_replicas):
                fresh = scoped.get(DEPLOYMENTS, lname, ns)
                fresh["spec"]["replicas"] = desired_replicas
                scoped.update(DEPLOYMENTS, fresh, namespace=ns)
                self.stats["splits"] += 1
                churn += 1
        # rebalance/forced: drop leafs for clusters that no longer exist
        # or were evacuated
        if self.rebalance or forced:
            for stale in by_name.values():
                churn += self._drain_leaf(scoped, lc, ns, stale)
        self._force_replan.discard(key)
        self._count_churn(churn)

    @staticmethod
    def _count_churn(churn: int) -> None:
        if churn:
            REGISTRY.counter(
                "placement_churn_total",
                "replica-moving leaf writes after initial placement "
                "(updates, drains, readmission creates)").inc(churn)

    def _drain_leaf(self, scoped: Client, lc: str, ns: str, leaf: dict) -> int:
        try:
            scoped.delete(DEPLOYMENTS, leaf["metadata"]["name"], ns)
        except errors.NotFoundError:
            return 0
        if self.inventory.is_evacuated(lc, _labels(leaf).get(CLUSTER_LABEL, "")):
            REGISTRY.counter(
                "evacuations_total",
                "leaf deployments drained off evacuated "
                "(sustained-NotReady) clusters").inc()
        return 1

    def _apply_aggregation(
        self, key: tuple[str, str, str], root: dict, leafs: list[dict], sums: np.ndarray
    ) -> None:
        lc, ns, name = key
        scoped = self.client.scoped(lc)
        fresh = scoped.get(DEPLOYMENTS, name, ns)
        status = fresh.setdefault("status", {})
        changed = False
        for c, field in enumerate(_COUNTERS):
            if status.get(field, 0) != int(sums[c]):
                status[field] = int(sums[c])
                changed = True
        leaf_conds = (leafs[0].get("status") or {}).get("conditions")
        if leaf_conds and status.get("conditions") != leaf_conds:
            # reference "cheat": root conditions := first leaf's
            status["conditions"] = copy.deepcopy(leaf_conds)
            changed = True
        if changed:
            scoped.update_status(DEPLOYMENTS, fresh, namespace=ns)
            self.stats["aggregations"] += 1

    # ---------------------------------------------------------- lifecycle

    async def start(self) -> None:
        import asyncio

        if self.fused and self.place:
            if self.core is None:
                from ...syncer.core import FusedCore

                self.core = FusedCore.for_current_loop()
            self._pbucket = self.core.register_placement(
                self, p=self.max_pclusters)
            self._apply_q = asyncio.Queue()
            for _ in range(2):
                self._apply_tasks.append(
                    asyncio.create_task(self._apply_worker()))
            await self.core.start()
        await self.cluster_informer.start()
        await self.informer.start()
        await self.controller.start()

    async def stop(self) -> None:
        import asyncio

        await self.controller.stop()
        if self.fused and self.place and self.core is not None:
            await self.core.stop()
            # the core's shutdown drain may have enqueued final applies
            if self._apply_q is not None:
                try:
                    await asyncio.wait_for(self._apply_q.join(), timeout=5.0)
                except asyncio.TimeoutError:
                    log.warning("deployment-splitter: applier queue not "
                                "drained at stop")
            for t in self._apply_tasks:
                t.cancel()
            for t in self._apply_tasks:
                try:
                    await t
                except asyncio.CancelledError:
                    pass
            self._apply_tasks.clear()
            if self._pbucket is not None:
                for key in list(self._pbucket.pl_rows):
                    self._pbucket.free_pl_row(key)
                if self._pbucket.placement_owner is self:
                    self._pbucket.placement_owner = None
                self._pbucket = None
        await self.informer.stop()
        await self.cluster_informer.stop()
