from .controller import DeploymentSplitter

__all__ = ["DeploymentSplitter"]
