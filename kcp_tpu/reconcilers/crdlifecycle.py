"""CRD lifecycle: establish CRDs and serve their resources.

The analog of the apiextensions-apiserver's establishing controller,
which the reference gets from its forked apiserver: a created CRD gains
NamesAccepted + Established conditions and its resource becomes servable.
The negotiation controller's Published condition keys off Established
(reference: negotiation.go:239-255), so without this nothing ever
publishes.

Name conflicts cannot happen within one scheme the way they can in a real
apiserver (the store keys resources by plural.group), so establishment is
immediate. Registration into the Scheme makes the resource discoverable
to clients (``Client.resources``) and to the syncer's retry-until-served
discovery loop.
"""

from __future__ import annotations

import logging

from ..apis import crd as crdapi
from ..apis.scheme import ResourceInfo
from ..client import Client, Informer
from ..reconciler.controller import Controller
from ..utils import errors

log = logging.getLogger(__name__)


class CRDLifecycleController:
    def __init__(self, client: Client):
        self.client = client
        self.informer = Informer(client, crdapi.CRDS)
        self.controller = Controller("crd-lifecycle", self._process)
        self.informer.add_handler(self._on_event)

    def _on_event(self, etype: str, old: dict | None, new: dict | None) -> None:
        obj = new or old
        m = obj["metadata"]
        self.controller.enqueue((m.get("clusterName", ""), m["name"], etype == "DELETED"))

    async def _process(self, item) -> None:
        cluster, name, deleted = item
        if deleted:
            # serving teardown: the resource disappears from discovery when
            # no other logical cluster still defines it
            still_defined = any(
                c["metadata"]["name"] == name for c in self.informer.list()
            )
            if not still_defined:
                self.client.scheme.unregister(self._storage_name_from_crd_name(name))
            return
        crd = self.informer.get(cluster, name)
        if crd is None:
            return
        changed = False
        if not crdapi.is_established(crd):
            crdapi.establish(crd)
            changed = True
        gvr = crdapi.gvr_of(crd)
        if self.client.scheme.by_resource(gvr.storage_name) is None:
            names = crd["spec"]["names"]
            self.client.scheme.register(
                ResourceInfo(
                    gvr=gvr,
                    kind=names["kind"],
                    list_kind=names.get("listKind", names["kind"] + "List"),
                    singular=names.get("singular", names["kind"].lower()),
                    namespaced=crd["spec"].get("scope", "Namespaced") == "Namespaced",
                )
            )
        if changed:
            scoped = self.client.scoped(cluster)
            fresh = scoped.get(crdapi.CRDS, name)
            fresh["status"] = crd["status"]
            try:
                scoped.update_status(crdapi.CRDS, fresh)
            except errors.ConflictError:
                self.controller.enqueue(item)

    @staticmethod
    def _storage_name_from_crd_name(crd_name: str) -> str:
        # CRD names are ``<plural>.<group>`` with ``core`` for the core group
        plural, _, group = crd_name.partition(".")
        return plural if group == "core" else crd_name

    async def start(self) -> None:
        await self.informer.start()
        await self.controller.start(1)

    async def stop(self) -> None:
        await self.controller.stop()
        await self.informer.stop()
