from .mesh import (
    HOSTS_AXIS,
    TENANTS_AXIS,
    SLOTS_AXIS,
    get_serving_mesh,
    make_mesh,
    make_multihost_mesh,
    mesh_from_spec,
    set_serving_mesh,
    shard_state,
    state_sharding_tree,
    state_shardings,
)

__all__ = [
    "make_mesh",
    "make_multihost_mesh",
    "mesh_from_spec",
    "get_serving_mesh",
    "set_serving_mesh",
    "state_shardings",
    "state_sharding_tree",
    "shard_state",
    "HOSTS_AXIS",
    "TENANTS_AXIS",
    "SLOTS_AXIS",
]
