from .mesh import (
    TENANTS_AXIS,
    SLOTS_AXIS,
    make_mesh,
    shard_state,
    state_sharding_tree,
    state_shardings,
)

__all__ = [
    "make_mesh",
    "state_shardings",
    "state_sharding_tree",
    "shard_state",
    "TENANTS_AXIS",
    "SLOTS_AXIS",
]
