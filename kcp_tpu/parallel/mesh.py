"""Device meshes and shardings for the batched control plane.

The scale dimension of this framework is object-count x logical-cluster
count (SURVEY.md §5 "long-context" analog): the reconcile batch is a
[B, S] mirror where B spans every object of every tenant. Sharding
follows the scaling-book recipe — pick a mesh, annotate shardings, let
XLA insert the collectives:

- ``tenants`` axis (the data-parallel analog): rows are range-sharded, so
  each device owns a contiguous block of tenants' objects. All row-local
  math (diff lanes, scatter, placement) needs no communication.
- ``slots`` axis (the tensor-parallel analog): the slot/column dimension
  is sharded for very wide buckets; the diff's any-over-slots reduction
  then runs as a partial reduce + XLA-inserted all-reduce over ``slots``
  (riding ICI, never DCN, because slots is the minor mesh axis).

Global convergence statistics (dirty counts, decision histograms) are
full reductions; under jit with these shardings XLA lowers them to
psum-style collectives across both axes.

Multi-host: the same mesh spans hosts (jax.distributed); tenants-axis
blocks map to hosts so informer-delta ingestion stays host-local and
only the scalar stats cross DCN.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

TENANTS_AXIS = "tenants"
SLOTS_AXIS = "slots"


def make_mesh(
    n_devices: int | None = None,
    tenants: int | None = None,
    slots: int = 1,
    devices: list | None = None,
) -> Mesh:
    """A 2D (tenants, slots) mesh over the first ``n_devices`` devices.

    ``slots=1`` (the default) keeps all sharding on the tenants axis —
    the right choice until buckets grow past a few hundred slots.
    """
    devs = devices if devices is not None else jax.devices()
    if n_devices is not None:
        if len(devs) < n_devices:
            raise ValueError(
                f"requested {n_devices} devices but only {len(devs)} available"
            )
        devs = devs[:n_devices]
    n = len(devs)
    if tenants is None:
        tenants = n // slots
    if tenants * slots != n:
        raise ValueError(f"mesh {tenants}x{slots} != {n} devices")
    arr = np.array(devs).reshape(tenants, slots)
    return Mesh(arr, (TENANTS_AXIS, SLOTS_AXIS))


def state_shardings(mesh: Mesh) -> dict[str, NamedSharding]:
    """NamedShardings for the reconcile state pytree (models/reconcile_model).

    rows [B, S]    -> (tenants, slots)
    flags [B]      -> (tenants,)
    slot masks [S] -> (slots,)
    placement [R,*]-> (tenants, ...)
    selector [C]   -> replicated (every device matches its rows against
                      every cluster selector)
    """
    def s(*spec):
        return NamedSharding(mesh, P(*spec))

    return {
        "rows": s(TENANTS_AXIS, SLOTS_AXIS),
        "flags": s(TENANTS_AXIS),
        "slot_mask": s(SLOTS_AXIS),
        "placement": s(TENANTS_AXIS, None),
        "placement_rows": s(TENANTS_AXIS),
        "labels": s(TENANTS_AXIS, None),
        "selectors": s(),
        "replicated": s(),
    }


def state_sharding_tree(mesh: Mesh):
    """A ReconcileState pytree of NamedShardings — THE single source of
    truth for how reconcile state is laid out on a mesh (used by
    shard_state, jit out_shardings, and the sharding tests)."""
    from ..models.reconcile_model import ReconcileState

    sh = state_shardings(mesh)
    return ReconcileState(
        up_vals=sh["rows"],
        up_exists=sh["flags"],
        down_vals=sh["rows"],
        down_exists=sh["flags"],
        status_mask=sh["slot_mask"],
        replicas=sh["placement_rows"],
        avail=sh["placement"],
        current=sh["placement"],
        pair_hashes=sh["labels"],
        sel_hashes=sh["selectors"],
    )


def shard_state(state, mesh: Mesh):
    """device_put a ReconcileState pytree with the canonical shardings."""
    tree = state_sharding_tree(mesh)
    return jax.tree.map(jax.device_put, state, tree)
