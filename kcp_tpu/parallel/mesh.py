"""Device meshes and shardings for the batched control plane.

The scale dimension of this framework is object-count x logical-cluster
count (SURVEY.md §5 "long-context" analog): the reconcile batch is a
[B, S] mirror where B spans every object of every tenant. Sharding
follows the scaling-book recipe — pick a mesh, annotate shardings, let
XLA insert the collectives:

- ``tenants`` axis (the data-parallel analog): rows are range-sharded, so
  each device owns a contiguous block of tenants' objects. All row-local
  math (diff lanes, scatter, placement) needs no communication.
- ``slots`` axis (the tensor-parallel analog): the slot/column dimension
  is sharded for very wide buckets; the diff's any-over-slots reduction
  then runs as a partial reduce + XLA-inserted all-reduce over ``slots``
  (riding ICI, never DCN, because slots is the minor mesh axis).

Global convergence statistics (dirty counts, decision histograms) are
full reductions; under jit with these shardings XLA lowers them to
psum-style collectives across both axes.

Multi-host: :func:`make_multihost_mesh` adds an explicit ``hosts`` major
axis (jax.distributed process boundaries = DCN). Row dimensions then
fold over ``(hosts, tenants)`` so each host's devices own a contiguous
tenant block — informer-delta ingestion stays host-local (each host
scatters only its own tenants' deltas over ICI) and the only traffic
that crosses DCN is the scalar stats reduction, which XLA lowers to a
hierarchical psum (intra-host over ICI first, then one small inter-host
step). That is the whole distributed-communication story of a control
plane: no weight tensors, no activations — mirrors stay put, scalars
travel.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

HOSTS_AXIS = "hosts"
TENANTS_AXIS = "tenants"
SLOTS_AXIS = "slots"

# the process-wide serving mesh: set once at startup (server.Config.mesh
# / --mesh), read by FusedCore.for_current_loop when no explicit mesh is
# threaded through — so every sync engine in the process serves sharded
# without each call site re-plumbing it
_SERVING_MESH: Mesh | None = None


def set_serving_mesh(mesh: "Mesh | str | None") -> Mesh | None:
    """Install the process-default serving mesh (a Mesh or a spec string
    like ``"8"``, ``"4x2"``, ``"2x2x2"``). None clears it."""
    global _SERVING_MESH
    _SERVING_MESH = mesh_from_spec(mesh) if isinstance(mesh, str) else mesh
    return _SERVING_MESH


def get_serving_mesh() -> Mesh | None:
    return _SERVING_MESH


def mesh_from_spec(spec: str, devices: list | None = None) -> Mesh:
    """Build a mesh from a CLI/config spec string.

    ``"8"`` -> (tenants=8,); ``"4x2"`` -> (tenants=4, slots=2);
    ``"2x2x2"`` -> (hosts=2, tenants=2, slots=2); ``"auto"`` -> the
    canonical mesh over the live process topology (hosts-major on a
    multi-host pod). The flat device count must be available.
    """
    if spec.strip().lower() == "auto":
        from .distributed import pod_serving_mesh

        return pod_serving_mesh()
    parts = spec.lower().replace("*", "x").split("x")
    if not parts or any(not p.strip().isdigit() for p in parts):
        raise ValueError(f"bad mesh spec {spec!r}: want N, NxM or NxMxK")
    dims = [int(p) for p in parts]
    if any(d < 1 for d in dims) or len(dims) > 3:
        raise ValueError(f"bad mesh spec {spec!r}: want N, NxM or NxMxK")
    # validate the axis product against the available device count up
    # front with an actionable error — a short spec otherwise surfaces
    # deep inside jax as a device-array reshape failure
    devs = devices if devices is not None else jax.devices()
    n = 1
    for d in dims:
        n *= d
    if len(devs) < n:
        raise ValueError(
            f"mesh spec {spec!r} needs {n} devices, have {len(devs)}; "
            f"shrink the spec or add devices (virtual devices: "
            f"XLA_FLAGS=--xla_force_host_platform_device_count={n})")
    if len(dims) == 1:
        return make_mesh(n_devices=dims[0], slots=1, devices=devs)
    if len(dims) == 2:
        return make_mesh(n_devices=n, tenants=dims[0],
                         slots=dims[1], devices=devs)
    h, t, s = dims
    return make_multihost_mesh(hosts=h, tenants=t, slots=s, devices=devs[:n])


def make_mesh(
    n_devices: int | None = None,
    tenants: int | None = None,
    slots: int = 1,
    devices: list | None = None,
) -> Mesh:
    """A 2D (tenants, slots) mesh over the first ``n_devices`` devices.

    ``slots=1`` (the default) keeps all sharding on the tenants axis —
    the right choice until buckets grow past a few hundred slots.
    """
    devs = devices if devices is not None else jax.devices()
    if n_devices is not None:
        if len(devs) < n_devices:
            raise ValueError(
                f"requested {n_devices} devices but only {len(devs)} available"
            )
        devs = devs[:n_devices]
    n = len(devs)
    if tenants is None:
        tenants = n // slots
    if tenants * slots != n:
        raise ValueError(f"mesh {tenants}x{slots} != {n} devices")
    arr = np.array(devs).reshape(tenants, slots)
    return Mesh(arr, (TENANTS_AXIS, SLOTS_AXIS))


def make_multihost_mesh(
    hosts: int,
    tenants: int | None = None,
    slots: int = 1,
    devices: list | None = None,
) -> Mesh:
    """A 3D (hosts, tenants, slots) mesh with hosts as the major axis.

    On real multi-host pods, ``devices`` defaults to jax.devices() whose
    order groups by process — so the major axis maps exactly to DCN
    boundaries. Single-host tests pass virtual devices and the axis is
    purely logical (the sharding semantics are identical, which is what
    the tests pin down).
    """
    devs = devices if devices is not None else jax.devices()
    n = len(devs)
    if n % hosts:
        raise ValueError(f"{n} devices not divisible into {hosts} hosts")
    per_host = n // hosts
    if tenants is None:
        tenants = per_host // slots
    if tenants * slots != per_host:
        raise ValueError(f"per-host mesh {tenants}x{slots} != {per_host} devices")
    arr = np.array(devs).reshape(hosts, tenants, slots)
    return Mesh(arr, (HOSTS_AXIS, TENANTS_AXIS, SLOTS_AXIS))


def row_factor(mesh: Mesh) -> int:
    """Product of the row-axis sizes (hosts x tenants) — the shard count
    of every B/R dimension. THE single source for row-axis arithmetic
    (bucket padding, the Pallas mesh gate)."""
    dims = dict(zip(mesh.axis_names, mesh.devices.shape))
    return dims.get(HOSTS_AXIS, 1) * dims.get(TENANTS_AXIS, 1)


def slot_factor(mesh: Mesh) -> int:
    """Size of the slots axis (1 when absent)."""
    return dict(zip(mesh.axis_names, mesh.devices.shape)).get(SLOTS_AXIS, 1)


def state_shardings(mesh: Mesh) -> dict[str, NamedSharding]:
    """NamedShardings for the reconcile state pytree (models/reconcile_model).

    rows [B, S]    -> ((hosts?, tenants), slots)
    flags [B]      -> ((hosts?, tenants),)
    slot masks [S] -> (slots,)
    placement [R,*]-> ((hosts?, tenants), ...)
    selector [C]   -> replicated (every device matches its rows against
                      every cluster selector)

    With a :func:`make_multihost_mesh` mesh, row dimensions fold over
    (hosts, tenants) so tenant blocks nest inside host blocks.
    """
    row = (HOSTS_AXIS, TENANTS_AXIS) if HOSTS_AXIS in mesh.axis_names else TENANTS_AXIS

    def s(*spec):
        return NamedSharding(mesh, P(*spec))

    return {
        "rows": s(row, SLOTS_AXIS),
        "flags": s(row),
        "slot_mask": s(SLOTS_AXIS),
        "placement": s(row, None),
        "placement_rows": s(row),
        "labels": s(row, None),
        "selectors": s(),
        "replicated": s(),
    }


def state_sharding_tree(mesh: Mesh, row_status_mask: bool = False):
    """A ReconcileState pytree of NamedShardings — THE single source of
    truth for how reconcile state is laid out on a mesh (used by
    shard_state, jit out_shardings, and the sharding tests).

    ``row_status_mask`` selects the [B, S] per-row mask layout (the fused
    serving core's heterogeneous-vocabulary buckets)."""
    from ..models.reconcile_model import ReconcileState

    sh = state_shardings(mesh)
    return ReconcileState(
        up_vals=sh["rows"],
        up_exists=sh["flags"],
        down_vals=sh["rows"],
        down_exists=sh["flags"],
        status_mask=sh["rows"] if row_status_mask else sh["slot_mask"],
        replicas=sh["placement_rows"],
        avail=sh["placement"],
        current=sh["placement"],
        pair_hashes=sh["labels"],
        sel_hashes=sh["selectors"],
    )


def shard_state(state, mesh: Mesh):
    """device_put a ReconcileState pytree with the canonical shardings."""
    tree = state_sharding_tree(
        mesh, row_status_mask=np.asarray(state.status_mask).ndim == 2
    )
    return jax.tree.map(jax.device_put, state, tree)
