"""Multi-host bring-up: jax.distributed glue + topology-derived meshes.

The reference scales horizontally by sharding kcp instances over etcd
key ranges (future work in its docs, logical-clusters.md:83); this
framework's multi-host story is a jax process group over DCN: every
host runs the same server, `jax.distributed` forms the group, and the
serving mesh folds rows over a hosts-major axis (parallel/mesh.py) so
informer-delta ingestion stays host-local and only scalar stats cross
DCN.

``init_distributed`` wraps jax.distributed.initialize with explicit
args or environment fallbacks (JAX's own auto-detection handles TPU
pods where the metadata server provides topology). ``pod_serving_mesh``
builds the canonical serving mesh from the LIVE process topology — the
``--mesh auto`` spec.
"""

from __future__ import annotations

import logging
import os

from .mesh import Mesh, make_mesh, make_multihost_mesh

log = logging.getLogger(__name__)


def init_distributed(
    coordinator: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
    _dry_run: bool = False,
) -> dict:
    """Form the jax process group (idempotent; explicit single-process
    configuration is a no-op).

    Explicit args win; otherwise the JAX_COORDINATOR / JAX_NUM_PROCESSES
    / JAX_PROCESS_ID env vars; otherwise jax.distributed's own
    auto-detection runs (TPU pod metadata) — calling this function IS
    the multi-host intent, so with nothing configured initialize() is
    still invoked and left to auto-detect. Returns the kwargs used —
    ``_dry_run`` skips the actual initialize (arg-assembly tests).
    """
    kwargs: dict = {}
    coordinator = coordinator or os.environ.get("JAX_COORDINATOR")
    if coordinator:
        kwargs["coordinator_address"] = coordinator
    n = num_processes if num_processes is not None else os.environ.get(
        "JAX_NUM_PROCESSES")
    if n is not None:
        kwargs["num_processes"] = int(n)
    pid = process_id if process_id is not None else os.environ.get(
        "JAX_PROCESS_ID")
    if pid is not None:
        kwargs["process_id"] = int(pid)
    if _dry_run:
        return kwargs
    if kwargs.get("num_processes") == 1:
        log.info("explicit single-process serving; skipping jax.distributed")
        return kwargs
    import jax

    if jax.distributed.is_initialized():
        log.info("jax process group already formed; skipping initialize")
        return kwargs
    jax.distributed.initialize(**kwargs)
    log.info("jax process group up: process %d/%d",
             jax.process_index(), jax.process_count())
    return kwargs


def pod_serving_mesh(slots: int = 1) -> Mesh:
    """The canonical serving mesh over the LIVE topology: hosts-major
    when multi-process (DCN boundaries = process boundaries, so
    jax.devices() ordering groups by process), flat tenants otherwise.
    This is what ``--mesh auto`` resolves to — and what the fleet batch
    (syncer/core.py FleetBatch) shards the whole-fleet ragged state over
    via the same parallel/mesh.py shardings as any bucket state."""
    import jax

    n_devs = len(jax.devices())
    n_proc = jax.process_count()
    per = n_devs // max(n_proc, 1)
    if slots < 1 or per % slots:
        raise ValueError(
            f"slots={slots} does not divide the {per} devices per host "
            f"({n_devs} devices / {n_proc} processes); pick a slots axis "
            f"that divides the per-host device count")
    if n_proc > 1:
        return make_multihost_mesh(hosts=n_proc, slots=slots)
    return make_mesh(slots=slots)
