"""GVR/GVK registry.

The analog of the reference's scheme registration (pkg/apis/*/v1alpha1/
register.go) plus just enough discovery metadata for the dynamic client,
CRD puller, and API server.
"""

from __future__ import annotations

from dataclasses import dataclass


@dataclass(frozen=True)
class GVR:
    group: str
    version: str
    resource: str  # plural, lowercase

    @property
    def storage_name(self) -> str:
        """Store resource key: ``<plural>`` or ``<plural>.<group>``."""
        return f"{self.resource}.{self.group}" if self.group else self.resource

    @property
    def api_path(self) -> str:
        if self.group:
            return f"/apis/{self.group}/{self.version}/{self.resource}"
        return f"/api/{self.version}/{self.resource}"

    def __str__(self) -> str:
        return self.storage_name

    @classmethod
    def parse(cls, s: str, version: str = "v1") -> "GVR":
        """Parse ``deployments.apps`` / ``configmaps`` style strings."""
        if "/" in s:  # group/version/resource
            group, version, resource = s.split("/", 2)
            return cls(group, version, resource)
        resource, _, group = s.partition(".")
        return cls(group, _default_version(group) or version, resource)


_GROUP_VERSIONS = {
    "": "v1",
    "apps": "v1",
    "rbac.authorization.k8s.io": "v1",
    "apiextensions.k8s.io": "v1",
    "cluster.example.dev": "v1alpha1",
    "apiresource.kcp.dev": "v1alpha1",
}


def _default_version(group: str) -> str | None:
    return _GROUP_VERSIONS.get(group)


@dataclass(frozen=True)
class ResourceInfo:
    gvr: GVR
    kind: str
    list_kind: str
    singular: str
    namespaced: bool
    has_status: bool = True


class Scheme:
    """Registry of known resource types (built-ins + registered CRDs)."""

    def __init__(self):
        self._by_storage: dict[str, ResourceInfo] = {}
        self._by_kind: dict[tuple[str, str], ResourceInfo] = {}

    def register(self, info: ResourceInfo) -> None:
        self._by_storage[info.gvr.storage_name] = info
        self._by_kind[(info.gvr.group, info.kind)] = info

    def unregister(self, storage_name: str) -> None:
        info = self._by_storage.pop(storage_name, None)
        if info:
            self._by_kind.pop((info.gvr.group, info.kind), None)

    def by_resource(self, storage_name: str) -> ResourceInfo | None:
        return self._by_storage.get(storage_name)

    def by_kind(self, group: str, kind: str) -> ResourceInfo | None:
        return self._by_kind.get((group, kind))

    def all(self) -> list[ResourceInfo]:
        return sorted(self._by_storage.values(), key=lambda i: i.gvr.storage_name)

    def group_versions(self) -> dict[str, set[str]]:
        out: dict[str, set[str]] = {}
        for info in self._by_storage.values():
            out.setdefault(info.gvr.group, set()).add(info.gvr.version)
        return out


_CORE = [
    ("", "v1", "namespaces", "Namespace", False),
    ("", "v1", "configmaps", "ConfigMap", True),
    ("", "v1", "secrets", "Secret", True),
    ("", "v1", "serviceaccounts", "ServiceAccount", True),
    ("", "v1", "resourcequotas", "ResourceQuota", True),
    ("", "v1", "services", "Service", True),
    ("", "v1", "pods", "Pod", True),
    ("apps", "v1", "deployments", "Deployment", True),
    ("rbac.authorization.k8s.io", "v1", "clusterroles", "ClusterRole", False),
    ("rbac.authorization.k8s.io", "v1", "clusterrolebindings", "ClusterRoleBinding", False),
    ("apiextensions.k8s.io", "v1", "customresourcedefinitions", "CustomResourceDefinition", False),
    ("cluster.example.dev", "v1alpha1", "clusters", "Cluster", False),
    ("apiresource.kcp.dev", "v1alpha1", "apiresourceimports", "APIResourceImport", False),
    ("apiresource.kcp.dev", "v1alpha1", "negotiatedapiresources", "NegotiatedAPIResource", False),
]


def default_scheme() -> Scheme:
    """Scheme with the built-in control-plane types.

    The three CRD-backed types mirror the reference's embedded config
    manifests applied at startup (reference: embed.go:12-13,
    pkg/reconciler/cluster/controller.go:316-350 RegisterCRDs).
    """
    s = Scheme()
    for group, version, plural, kind, namespaced in _CORE:
        singular = kind.lower()
        s.register(
            ResourceInfo(
                gvr=GVR(group, version, plural),
                kind=kind,
                list_kind=kind + "List",
                singular=singular,
                namespaced=namespaced,
            )
        )
    return s
