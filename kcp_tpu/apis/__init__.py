from .conditions import (
    find_condition,
    is_condition_true,
    set_condition,
)
from .scheme import GVR, Scheme, default_scheme

__all__ = [
    "set_condition",
    "find_condition",
    "is_condition_true",
    "GVR",
    "Scheme",
    "default_scheme",
]
