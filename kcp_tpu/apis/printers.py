"""Server-side Table rendering — the ``kubectl get -o wide`` surface.

The reference curates printer columns per type with kubebuilder
annotations (e.g. APIResourceImport's Location / Schema update strategy
/ API Version / API Resource / Compatible / Available columns,
pkg/apis/apiresource/v1alpha1/apiresourceimport_types.go:32-37; Cluster's
Location / Ready / Synced API resources,
pkg/apis/cluster/v1alpha1/cluster_types.go kubebuilder block) and the
apiserver renders them when a client sends
``Accept: application/json;as=Table;v=v1;g=meta.k8s.io``. This module is
that rendering: per-resource column definitions + cell extraction over
plain objects, with a generic Name/Age fallback.
"""

from __future__ import annotations

import calendar
import time
from typing import Callable


def _condition(obj: dict, ctype: str) -> str:
    for c in ((obj.get("status") or {}).get("conditions") or []):
        if c.get("type") == ctype:
            return c.get("status", "Unknown")
    return "Unknown"


def _age(obj: dict, now: float | None = None) -> str:
    ts = (obj.get("metadata") or {}).get("creationTimestamp")
    if not ts:
        return "<unknown>"
    try:
        # timegm, not mktime: the timestamp is UTC and must not shift
        # with local DST
        created = calendar.timegm(time.strptime(ts, "%Y-%m-%dT%H:%M:%SZ"))
    except ValueError:
        return "<unknown>"
    secs = max(0, int((now if now is not None else time.time()) - created))
    if secs < 120:
        return f"{secs}s"
    if secs < 7200:
        return f"{secs // 60}m"
    if secs < 172800:
        return f"{secs // 3600}h"
    return f"{secs // 86400}d"


def _name(obj: dict) -> str:
    return (obj.get("metadata") or {}).get("name", "")


Column = tuple[str, str, Callable[[dict], str]]  # (name, type, cell fn)

_GENERIC: list[Column] = [
    ("Name", "string", _name),
    ("Age", "string", _age),
]

# per storage-name column sets (reference kubebuilder printcolumn blocks)
_COLUMNS: dict[str, list[Column]] = {
    "clusters.cluster.example.dev": [
        ("Name", "string", _name),
        ("Location", "string", _name),  # reference: Location = .metadata.name
        ("Ready", "string", lambda o: _condition(o, "Ready")),
        ("Synced API resources", "string",
         lambda o: ",".join((o.get("status") or {}).get("syncedResources") or [])),
        ("Age", "string", _age),
    ],
    "apiresourceimports.apiresource.kcp.dev": [
        ("Name", "string", _name),
        ("Location", "string", lambda o: (o.get("spec") or {}).get("location", "")),
        ("Schema update strategy", "string",
         lambda o: (o.get("spec") or {}).get("schemaUpdateStrategy", "")),
        ("API Version", "string",
         lambda o: (o.get("spec") or {}).get("groupVersion", "")),
        ("API Resource", "string", lambda o: (o.get("spec") or {}).get("plural", "")),
        ("Compatible", "string", lambda o: _condition(o, "Compatible")),
        ("Available", "string", lambda o: _condition(o, "Available")),
        ("Age", "string", _age),
    ],
    "negotiatedapiresources.apiresource.kcp.dev": [
        ("Name", "string", _name),
        ("Publish", "string",
         lambda o: str((o.get("spec") or {}).get("publish", False)).lower()),
        ("API Version", "string",
         lambda o: (o.get("spec") or {}).get("groupVersion", "")),
        ("API Resource", "string", lambda o: (o.get("spec") or {}).get("plural", "")),
        ("Published", "string", lambda o: _condition(o, "Published")),
        ("Enforced", "string", lambda o: _condition(o, "Enforced")),
        ("Age", "string", _age),
    ],
    "deployments.apps": [
        ("Name", "string", _name),
        ("Ready", "string", lambda o: (
            f"{(o.get('status') or {}).get('readyReplicas', 0)}/"
            f"{(o.get('spec') or {}).get('replicas', 1)}")),  # k8s defaults replicas to 1
        ("Up-to-date", "string",
         lambda o: str((o.get("status") or {}).get("updatedReplicas", 0))),
        ("Available", "string",
         lambda o: str((o.get("status") or {}).get("availableReplicas", 0))),
        ("Age", "string", _age),
    ],
    "namespaces": [
        ("Name", "string", _name),
        ("Status", "string", lambda o: (
            "Terminating" if (o.get("metadata") or {}).get("deletionTimestamp")
            else "Active")),
        ("Age", "string", _age),
    ],
    "configmaps": [
        ("Name", "string", _name),
        ("Data", "string", lambda o: str(len(o.get("data") or {}))),
        ("Age", "string", _age),
    ],
}


def wants_table(accept: str) -> bool:
    """Does the Accept header ask for the meta.k8s.io Table encoding?"""
    return "as=table" in (accept or "").lower().replace(" ", "")


def render_table(storage_name: str, items: list[dict], list_rv: int | None = None) -> dict:
    """A meta.k8s.io/v1 Table for the given objects."""
    cols = _COLUMNS.get(storage_name, _GENERIC)
    return {
        "kind": "Table",
        "apiVersion": "meta.k8s.io/v1",
        "metadata": {"resourceVersion": str(list_rv)} if list_rv is not None else {},
        "columnDefinitions": [
            {"name": n, "type": t, "format": "", "description": "", "priority": 0}
            for n, t, _fn in cols
        ],
        "rows": [
            {"cells": [fn(obj) for _n, _t, fn in cols],
             "object": {"kind": "PartialObjectMetadata",
                        "apiVersion": "meta.k8s.io/v1",
                        "metadata": obj.get("metadata", {})}}
            for obj in items
        ],
    }
