"""APIResource API types (apiresource.kcp.dev/v1alpha1).

Behavioral parity with the reference's two negotiation types
(pkg/apis/apiresource/v1alpha1/):

- ``APIResourceImport`` — one physical cluster's view of one API resource
  (conditions ``Compatible``, ``Available``; update strategies
  ``UpdateNever`` / ``UpdateUnpublished`` / ``UpdatePublished``,
  apiresourceimport_types.go:56-93)
- ``NegotiatedAPIResource`` — the LCD schema negotiated across all imports
  (conditions ``Submitted``, ``Published``, ``Enforced``,
  negociatedapiresource_types.go:59-81)

Both share a ``CommonAPIResourceSpec``: groupVersion + names + scope +
a raw JSON openAPIV3Schema + subresources + column definitions
(common_types.go:124-163).
"""

from __future__ import annotations

from .conditions import FALSE, TRUE, find_condition, is_condition_true, set_condition
from .scheme import GVR

GROUP = "apiresource.kcp.dev"
VERSION = "v1alpha1"
APIRESOURCEIMPORTS = GVR(GROUP, VERSION, "apiresourceimports")
NEGOTIATEDAPIRESOURCES = GVR(GROUP, VERSION, "negotiatedapiresources")

# APIResourceImport conditions
COMPATIBLE = "Compatible"
AVAILABLE = "Available"

# NegotiatedAPIResource conditions
SUBMITTED = "Submitted"
PUBLISHED = "Published"
ENFORCED = "Enforced"

# Schema update strategies (apiresourceimport_types.go:56-81)
UPDATE_NEVER = "UpdateNever"
UPDATE_UNPUBLISHED = "UpdateUnpublished"
UPDATE_PUBLISHED = "UpdatePublished"


def common_spec(
    group: str,
    version: str,
    plural: str,
    kind: str,
    scope: str = "Namespaced",
    schema: dict | None = None,
    sub_resources: list[str] | None = None,
) -> dict:
    return {
        "groupVersion": {"group": group, "version": version},
        "scope": scope,
        "plural": plural,
        "singular": kind.lower(),
        "kind": kind,
        "listKind": kind + "List",
        "openAPIV3Schema": schema or {"type": "object"},
        "subResources": [{"name": n} for n in (sub_resources or [])],
    }


def import_name(plural: str, group: str, version: str, location: str) -> str:
    """Canonical APIResourceImport object name.

    Reference naming: ``<location>.<plural>.<version>.<group>``
    (pkg/reconciler/cluster/apiimporter.go constructs one import per
    (cluster location, resource)).
    """
    return f"{location}.{plural}.{version}.{group or 'core'}"


def negotiated_name(plural: str, group: str, version: str) -> str:
    return f"{plural}.{version}.{group or 'core'}"


def new_api_resource_import(
    location: str,
    spec: dict,
    strategy: str = UPDATE_PUBLISHED,
) -> dict:
    gv = spec["groupVersion"]
    return {
        "apiVersion": f"{GROUP}/{VERSION}",
        "kind": "APIResourceImport",
        "metadata": {
            "name": import_name(spec["plural"], gv["group"], gv["version"], location)
        },
        "spec": {
            **spec,
            "location": location,
            "schemaUpdateStrategy": strategy,
        },
    }


def new_negotiated_api_resource(spec: dict, publish: bool = False) -> dict:
    gv = spec["groupVersion"]
    return {
        "apiVersion": f"{GROUP}/{VERSION}",
        "kind": "NegotiatedAPIResource",
        "metadata": {"name": negotiated_name(spec["plural"], gv["group"], gv["version"])},
        "spec": {**spec, "publish": publish},
    }


def can_update(api_import: dict, negotiated_is_published: bool) -> bool:
    """Whether this import may update the negotiated schema.

    Reference: apiresourceimport_types.go:83-93 ``CanUpdate`` — UpdateNever
    never updates; UpdateUnpublished only while unpublished;
    UpdatePublished always.
    """
    strategy = api_import["spec"].get("schemaUpdateStrategy", UPDATE_PUBLISHED)
    if strategy == UPDATE_NEVER:
        return False
    if strategy == UPDATE_UNPUBLISHED:
        return not negotiated_is_published
    return True


def set_compatible(obj: dict, ok: bool, reason: str = "", message: str = "") -> None:
    set_condition(obj, COMPATIBLE, TRUE if ok else FALSE, reason, message)


def set_available(obj: dict, ok: bool, reason: str = "", message: str = "") -> None:
    set_condition(obj, AVAILABLE, TRUE if ok else FALSE, reason, message)


def is_compatible_and_available(obj: dict) -> bool:
    """The gate for adding a resource to a Cluster's SyncedResources
    (reference: pkg/reconciler/cluster/cluster.go:61-77)."""
    return is_condition_true(obj, COMPATIBLE) and is_condition_true(obj, AVAILABLE)


def gvr_of(obj: dict) -> GVR:
    spec = obj["spec"]
    gv = spec["groupVersion"]
    return GVR(gv.get("group", ""), gv["version"], spec["plural"])


__all__ = [
    "GROUP",
    "VERSION",
    "APIRESOURCEIMPORTS",
    "NEGOTIATEDAPIRESOURCES",
    "COMPATIBLE",
    "AVAILABLE",
    "SUBMITTED",
    "PUBLISHED",
    "ENFORCED",
    "UPDATE_NEVER",
    "UPDATE_UNPUBLISHED",
    "UPDATE_PUBLISHED",
    "common_spec",
    "import_name",
    "negotiated_name",
    "new_api_resource_import",
    "new_negotiated_api_resource",
    "can_update",
    "set_compatible",
    "set_available",
    "is_compatible_and_available",
    "gvr_of",
    "find_condition",
]
