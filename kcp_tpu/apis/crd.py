"""CustomResourceDefinition — the minimal subset the framework serves.

Parity notes: the reference gets CRDs from the (un-vendored) apiextensions
apiserver; what this framework needs is the subset the negotiation
controller and API server touch (reference: pkg/reconciler/apiresource/
negotiation.go:612-790 publishNegotiatedResource — create/update CRD,
storage-version logic, api-approved annotation; conditions Established /
NamesAccepted).
"""

from __future__ import annotations

from .conditions import TRUE, is_condition_true, set_condition
from .scheme import GVR

GROUP = "apiextensions.k8s.io"
VERSION = "v1"
CRDS = GVR(GROUP, VERSION, "customresourcedefinitions")

ESTABLISHED = "Established"
NAMES_ACCEPTED = "NamesAccepted"

# Kubernetes requires this annotation for *.k8s.io / *.kubernetes.io groups;
# the reference stamps it when publishing (negotiation.go, api-approved).
API_APPROVED_ANNOTATION = "api-approved.kubernetes.io"


def crd_name(plural: str, group: str) -> str:
    return f"{plural}.{group}" if group else plural


def new_crd(
    group: str,
    version: str,
    plural: str,
    kind: str,
    scope: str = "Namespaced",
    schema: dict | None = None,
    subresources: dict | None = None,
    served: bool = True,
    storage: bool = True,
) -> dict:
    return {
        "apiVersion": f"{GROUP}/{VERSION}",
        "kind": "CustomResourceDefinition",
        "metadata": {"name": crd_name(plural, group)},
        "spec": {
            "group": group,
            "scope": scope,
            "names": {
                "plural": plural,
                "singular": kind.lower(),
                "kind": kind,
                "listKind": kind + "List",
            },
            "versions": [
                {
                    "name": version,
                    "served": served,
                    "storage": storage,
                    "schema": {"openAPIV3Schema": schema or {"type": "object"}},
                    **({"subresources": subresources} if subresources else {}),
                }
            ],
        },
    }


def storage_version(crd: dict) -> str | None:
    for v in crd["spec"].get("versions", []):
        if v.get("storage"):
            return v["name"]
    return None


def served_versions(crd: dict) -> list[str]:
    return [v["name"] for v in crd["spec"].get("versions", []) if v.get("served")]


def version_entry(crd: dict, version: str) -> dict | None:
    for v in crd["spec"].get("versions", []):
        if v["name"] == version:
            return v
    return None


def is_established(crd: dict) -> bool:
    return is_condition_true(crd, ESTABLISHED)


def establish(crd: dict) -> None:
    """Mark the CRD Established/NamesAccepted (the API server does this on
    registration; the real apiextensions controller races name conflicts,
    which a single-scheme store cannot have)."""
    set_condition(crd, NAMES_ACCEPTED, TRUE, "NoConflicts")
    set_condition(crd, ESTABLISHED, TRUE, "InitialNamesAccepted")
    stored = crd.setdefault("status", {}).setdefault("storedVersions", [])
    sv = storage_version(crd)
    if sv and sv not in stored:
        stored.append(sv)


def gvr_of(crd: dict) -> GVR:
    sv = storage_version(crd) or (crd["spec"]["versions"][0]["name"] if crd["spec"].get("versions") else "v1")
    return GVR(crd["spec"]["group"], sv, crd["spec"]["names"]["plural"])
