"""Cluster API type (cluster.example.dev/v1alpha1).

Behavioral parity with the reference type (pkg/apis/cluster/v1alpha1/
cluster_types.go:36-83): ``spec.kubeconfig`` points at a physical cluster;
``status.conditions`` carries Ready; ``status.syncedResources`` lists the
negotiated resources currently syncing.

Objects are plain dicts (the whole framework is dynamic/unstructured —
fixed Go structs would buy nothing here and dicts flow straight into the
device encoder).
"""

from __future__ import annotations

from .conditions import FALSE, TRUE, is_condition_true, set_condition
from .scheme import GVR

GROUP = "cluster.example.dev"
VERSION = "v1alpha1"
CLUSTERS = GVR(GROUP, VERSION, "clusters")

READY = "Ready"

# Reasons mirroring the reference's condition reasons
# (cluster_types.go / cluster.go reconcile error paths).
REASON_INVALID_KUBECONFIG = "InvalidKubeConfig"
REASON_ERROR_STARTING_SYNCER = "ErrorStartingSyncer"
REASON_ERROR_INSTALLING_SYNCER = "ErrorInstallingSyncer"
REASON_SYNCER_NOT_READY = "SyncerNotReady"


def new_cluster(name: str, kubeconfig: str = "") -> dict:
    return {
        "apiVersion": f"{GROUP}/{VERSION}",
        "kind": "Cluster",
        "metadata": {"name": name},
        "spec": {"kubeconfig": kubeconfig},
    }


def set_ready(cluster: dict, reason: str = "", message: str = "") -> None:
    set_condition(cluster, READY, TRUE, reason, message)


def set_not_ready(cluster: dict, reason: str, message: str = "") -> None:
    set_condition(cluster, READY, FALSE, reason, message)


def is_ready(cluster: dict) -> bool:
    return is_condition_true(cluster, READY)


# Fleet placement surface (kcp_tpu/fleet/): provisioned capacity lives
# in spec, the health-adjusted allocatable in status, WAN locality in
# labels — mirroring node capacity/allocatable + topology labels upstream.
CAPACITY_KEY = "replicas"
REGION_LABEL = "fleet.kcp.dev/region"


def set_capacity(cluster: dict, replicas: int,
                 allocatable: int | None = None,
                 region: str = "") -> None:
    cluster.setdefault("spec", {})["capacity"] = {CAPACITY_KEY: int(replicas)}
    cluster.setdefault("status", {})["allocatable"] = {
        CAPACITY_KEY: int(replicas if allocatable is None else allocatable)}
    if region:
        cluster.setdefault("metadata", {}).setdefault(
            "labels", {})[REGION_LABEL] = region


def capacity_of(cluster: dict) -> int:
    """Provisioned replica capacity (0 = unspecified/unlimited-legacy)."""
    return int(((cluster.get("spec") or {}).get("capacity") or {})
               .get(CAPACITY_KEY, 0) or 0)


def allocatable_of(cluster: dict) -> int:
    """Health-adjusted allocatable replicas; falls back to capacity."""
    alloc = ((cluster.get("status") or {}).get("allocatable") or {})
    if CAPACITY_KEY in alloc:
        return int(alloc[CAPACITY_KEY] or 0)
    return capacity_of(cluster)


def region_of(cluster: dict) -> str:
    return ((cluster.get("metadata") or {}).get("labels") or {}).get(
        REGION_LABEL, "")


def synced_resources(cluster: dict) -> list[str]:
    return (cluster.get("status") or {}).get("syncedResources") or []


def set_synced_resources(cluster: dict, resources: list[str]) -> None:
    cluster.setdefault("status", {})["syncedResources"] = sorted(resources)
