"""Condition helpers shared by all API types.

Conditions are the primary observable state surface of the framework, as in
the reference (Ready / Compatible / Available / Submitted / Published /
Enforced — reference: pkg/apis/cluster/v1alpha1/cluster_types.go:63-83,
pkg/apis/apiresource/v1alpha1/apiresourceimport_helpers.go:26-42).

A condition is ``{type, status, reason?, message?, lastTransitionTime}``;
``lastTransitionTime`` only moves when ``status`` flips.
"""

from __future__ import annotations

import time
from typing import Mapping

TRUE = "True"
FALSE = "False"
UNKNOWN = "Unknown"


def _now() -> str:
    return time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())


def _conditions(obj: dict) -> list[dict]:
    return obj.setdefault("status", {}).setdefault("conditions", [])


def find_condition(obj: Mapping, ctype: str) -> dict | None:
    for c in (obj.get("status") or {}).get("conditions") or []:
        if c.get("type") == ctype:
            return c
    return None


def set_condition(
    obj: dict,
    ctype: str,
    status: str,
    reason: str = "",
    message: str = "",
) -> bool:
    """Upsert a condition; returns True when anything changed."""
    conds = _conditions(obj)
    for c in conds:
        if c.get("type") == ctype:
            changed = (
                c.get("status") != status
                or c.get("reason", "") != reason
                or c.get("message", "") != message
            )
            if c.get("status") != status:
                c["lastTransitionTime"] = _now()
            c["status"] = status
            c["reason"] = reason
            c["message"] = message
            return changed
    conds.append(
        {
            "type": ctype,
            "status": status,
            "reason": reason,
            "message": message,
            "lastTransitionTime": _now(),
        }
    )
    return True


def remove_condition(obj: dict, ctype: str) -> bool:
    conds = (obj.get("status") or {}).get("conditions")
    if not conds:
        return False
    kept = [c for c in conds if c.get("type") != ctype]
    if len(kept) == len(conds):
        return False
    obj["status"]["conditions"] = kept
    return True


def is_condition_true(obj: Mapping, ctype: str) -> bool:
    c = find_condition(obj, ctype)
    return bool(c) and c.get("status") == TRUE


def is_condition_false(obj: Mapping, ctype: str) -> bool:
    c = find_condition(obj, ctype)
    return bool(c) and c.get("status") == FALSE
