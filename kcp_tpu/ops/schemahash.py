"""Batched schema hashing — bucket assignment for shape-homogeneous batches.

The negotiation controller (pkg/reconciler/apiresource) compares imported
schemas across thousands of tenants. The tree-walk LCD computation stays
host-side (kcp_tpu/schemacompat — irregular recursion), but the *bucketing*
decision ("which imports share a schema and can be batch-processed / which
negotiated schema does an import already match") reduces to hashing the
canonical token stream of each schema — BASELINE.json configs[3], 5k
tenant CRD sets.

Device computation: a polynomial rolling hash over fixed-length uint32
token vectors

    h = mix( sum_i tokens[i] * P^(T-1-i)  mod 2^32 )

The power-weighted sum is a plain dot product -> batches of thousands of
schemas hash as one [B, T] x [T] matmul-shaped reduction on the MXU/VPU,
with a murmur finalizer for avalanche.

Host-side :func:`tokenize_schema` produces the canonical token stream
(sorted keys, type tags), so equal schemas tokenize equally regardless of
dict ordering.
"""

from __future__ import annotations

from functools import lru_cache

import jax
import jax.numpy as jnp
import numpy as np

from .hashing import hash_str, hash_value

POLY = np.uint32(0x01000193)  # FNV prime as the polynomial base


def tokenize_schema(schema: dict, max_tokens: int = 256) -> np.ndarray:
    """Canonical uint32 token stream of a JSON-schema subtree.

    Deterministic: dict keys sorted; every structural element contributes
    (key-hash, value-token) pairs; nested dicts/lists recurse with
    open/close markers so different nestings cannot collide structurally.
    Overflow truncates (the trailing tokens still contribute via length
    token) — an acceptable, bounded collision source, and the LCD engine
    re-checks equality host-side before trusting a bucket hit.
    """
    toks: list[int] = []

    OPEN, CLOSE, LIST_OPEN, LIST_CLOSE = 0xA11CE, 0xB0B, 0xC0DE, 0xD00D

    def walk(v) -> None:
        if len(toks) >= max_tokens:
            return
        if isinstance(v, dict):
            toks.append(OPEN)
            for k in sorted(v.keys()):
                toks.append(hash_str(k))
                walk(v[k])
            toks.append(CLOSE)
        elif isinstance(v, list):
            toks.append(LIST_OPEN)
            for item in v:
                walk(item)
            toks.append(LIST_CLOSE)
        else:
            toks.append(hash_value(v))

    walk(schema)
    toks.append(len(toks))  # length token guards truncation collisions
    arr = np.zeros(max_tokens, dtype=np.uint32)
    arr[: min(len(toks), max_tokens)] = np.array(toks[:max_tokens], dtype=np.uint64).astype(
        np.uint32
    )
    return arr


@lru_cache(maxsize=8)
def _powers(t: int) -> np.ndarray:
    out = np.ones(t, dtype=np.uint64)
    for i in range(t - 2, -1, -1):
        out[i] = (out[i + 1] * int(POLY)) & 0xFFFFFFFF
    return out.astype(np.uint32)


def schema_hashes(tokens: jax.Array) -> jax.Array:
    """uint32 [B]: polynomial hash of each token row ([B, T])."""
    t = tokens.shape[-1]
    powers = jnp.asarray(_powers(t))
    h = (tokens * powers[None, :]).sum(axis=-1, dtype=jnp.uint32)
    # murmur3 finalizer
    h = h ^ (h >> 16)
    h = h * jnp.uint32(0x85EBCA6B)
    h = h ^ (h >> 13)
    h = h * jnp.uint32(0xC2B2AE35)
    h = h ^ (h >> 16)
    return h


schema_hashes_jit = jax.jit(schema_hashes)


def bucket_by_hash(hashes: np.ndarray) -> dict[int, np.ndarray]:
    """Host-side: group row indices by hash value (one argsort + one
    boundary scan instead of a per-row python loop — at 5k tenant CRD
    sets the loop was ~10x the device hash itself)."""
    h = np.asarray(hashes)
    if h.size == 0:
        return {}
    order = np.argsort(h, kind="stable").astype(np.int32)
    sorted_h = h[order]
    # boundaries of equal-hash runs in the sorted order
    starts = np.flatnonzero(np.r_[True, sorted_h[1:] != sorted_h[:-1]])
    ends = np.r_[starts[1:], sorted_h.size]
    return {int(sorted_h[s]): order[s:e] for s, e in zip(starts, ends)}
