"""Batched schema hashing — bucket assignment for shape-homogeneous batches.

The negotiation controller (pkg/reconciler/apiresource) compares imported
schemas across thousands of tenants. The tree-walk LCD computation stays
host-side (kcp_tpu/schemacompat — irregular recursion), but the *bucketing*
decision ("which imports share a schema and can be batch-processed / which
negotiated schema does an import already match") reduces to hashing the
canonical token stream of each schema — BASELINE.json configs[3], 5k
tenant CRD sets.

Device computation: a polynomial rolling hash over fixed-length uint32
token vectors

    h = mix( sum_i tokens[i] * P^(T-1-i)  mod 2^32 )

The power-weighted sum is a plain dot product -> batches of thousands of
schemas hash as one [B, T] x [T] matmul-shaped reduction on the MXU/VPU,
with a murmur finalizer for avalanche.

Host-side :func:`tokenize_schema` produces the canonical token stream
(sorted keys, type tags), so equal schemas tokenize equally regardless of
dict ordering.
"""

from __future__ import annotations

from functools import lru_cache

import jax
import jax.numpy as jnp
import numpy as np

from ..native import load_tokenizer, tokenize_schemas_native
from .hashing import canonical_json, hash_str, hash_value

POLY = np.uint32(0x01000193)  # FNV prime as the polynomial base


def tokenize_schema_py(schema: dict, max_tokens: int = 256) -> np.ndarray:
    """Pure-Python canonical uint32 token stream of a JSON-schema subtree.

    Deterministic: dict keys sorted; every structural element contributes
    (key-hash, value-token) pairs; nested dicts/lists recurse with
    open/close markers so different nestings cannot collide structurally.
    Overflow truncates (the trailing tokens still contribute via length
    token) — an acceptable, bounded collision source, and the LCD engine
    re-checks equality host-side before trusting a bucket hit.

    This is the reference implementation and fallback; the serving path
    goes through :func:`tokenize_schemas` (native C++ parse+walk,
    differential-tested against this walk in tests/test_native.py).
    """
    toks: list[int] = []

    OPEN, CLOSE, LIST_OPEN, LIST_CLOSE = 0xA11CE, 0xB0B, 0xC0DE, 0xD00D

    def walk(v) -> None:
        if len(toks) >= max_tokens:
            return
        if isinstance(v, dict):
            toks.append(OPEN)
            for k in sorted(v.keys()):
                toks.append(hash_str(k))
                walk(v[k])
            toks.append(CLOSE)
        elif isinstance(v, list):
            toks.append(LIST_OPEN)
            for item in v:
                walk(item)
            toks.append(LIST_CLOSE)
        else:
            toks.append(hash_value(v))

    walk(schema)
    toks.append(len(toks))  # length token guards truncation collisions
    arr = np.zeros(max_tokens, dtype=np.uint32)
    arr[: min(len(toks), max_tokens)] = np.array(toks[:max_tokens], dtype=np.uint64).astype(
        np.uint32
    )
    return arr


def _strictly_json(v) -> bool:
    """True iff ``v`` is built only from JSON-shaped Python types (the
    tokenizer tiers may only be used on input every tier renders the
    same way)."""
    if isinstance(v, dict):
        return all(
            isinstance(k, str) and _strictly_json(x) for k, x in v.items()
        )
    if isinstance(v, list):
        return all(_strictly_json(x) for x in v)
    return v is None or isinstance(v, (str, int, float, bool))


def tokenize_schemas(schemas: list[dict], max_tokens: int = 256) -> np.ndarray:
    """Batch tokenizer ``[B, T]`` — the hot path of BASELINE configs[3]
    (5k tenant CRD sets re-bucketed per negotiation pass).

    The per-schema Python walk costs ~50 µs; at 5k schemas that made the
    schema lane the suite's slowest by ~3 orders of magnitude (round-4
    verdict). Here each schema is serialized once with the C-accelerated
    ``json.dumps`` (canonical form: sorted keys, so the native parser
    sees pre-sorted input) and the whole batch crosses ctypes ONCE; the
    C++ side (native/encode.cc enc_tokenize_schemas) parses and walks
    with byte-identical token semantics. Falls back to the Python walk
    when the library is missing or any schema fails to serialize/parse.
    """
    if not schemas:
        return np.zeros((0, max_tokens), dtype=np.uint32)
    # tier 1: direct dict-walk extension — no serialize, no parse
    tok = load_tokenizer()
    if tok is not None:
        out = np.empty((len(schemas), max_tokens), dtype=np.uint32)
        schemas_list = schemas if isinstance(schemas, list) else list(schemas)
        if tok.tokenize(schemas_list, max_tokens, out) == 0:
            return out
        # a nonzero rc means some schema is not JSON-shaped (tuple,
        # non-str key, ...). Tier 2 would silently coerce it through
        # json.dumps (a tuple becomes an array) and diverge from the
        # Python walk's opaque-leaf hashing — only the walk itself is
        # faithful here, so skip straight to it.
    elif all(_strictly_json(s) for s in schemas):
        # tier 2: serialize host-side, parse+walk native. json.dumps
        # silently coerces non-JSON types (a tuple becomes an array,
        # diverging from the Python walk's opaque-leaf hash), so this
        # tier is gated on a cheap strict-type check — the same schema
        # must hash identically on hosts with and without the extension.
        try:
            blobs = [canonical_json(s).encode("utf-8") for s in schemas]
            out = tokenize_schemas_native(blobs, max_tokens)
        except (TypeError, ValueError):
            out = None  # non-JSON-serializable schema
        if out is not None:
            return out
    # final tier: the pure-Python reference walk
    return np.stack([tokenize_schema_py(s, max_tokens) for s in schemas])


def tokenize_schema(schema: dict, max_tokens: int = 256) -> np.ndarray:
    """Single-schema tokenizer (batch-of-1 through the native path)."""
    return tokenize_schemas([schema], max_tokens)[0]


@lru_cache(maxsize=8)
def _powers(t: int) -> np.ndarray:
    out = np.ones(t, dtype=np.uint64)
    for i in range(t - 2, -1, -1):
        out[i] = (out[i + 1] * int(POLY)) & 0xFFFFFFFF
    return out.astype(np.uint32)


def schema_hashes(tokens: jax.Array) -> jax.Array:
    """uint32 [B]: polynomial hash of each token row ([B, T])."""
    t = tokens.shape[-1]
    powers = jnp.asarray(_powers(t))
    h = (tokens * powers[None, :]).sum(axis=-1, dtype=jnp.uint32)
    # murmur3 finalizer
    h = h ^ (h >> 16)
    h = h * jnp.uint32(0x85EBCA6B)
    h = h ^ (h >> 13)
    h = h * jnp.uint32(0xC2B2AE35)
    h = h ^ (h >> 16)
    return h


schema_hashes_jit = jax.jit(schema_hashes)


def bucket_by_hash(hashes: np.ndarray) -> dict[int, np.ndarray]:
    """Host-side: group row indices by hash value (one argsort + one
    boundary scan instead of a per-row python loop — at 5k tenant CRD
    sets the loop was ~10x the device hash itself)."""
    h = np.asarray(hashes)
    if h.size == 0:
        return {}
    order = np.argsort(h, kind="stable").astype(np.int32)
    sorted_h = h[order]
    # boundaries of equal-hash runs in the sorted order
    starts = np.flatnonzero(np.r_[True, sorted_h[1:] != sorted_h[:-1]])
    ends = np.r_[starts[1:], sorted_h.size]
    return {int(sorted_h[s]): order[s:e] for s, e in zip(starts, ends)}
