"""Irregular objects -> regular tensors.

The central trick of the TPU build (SURVEY.md §7.1): Kubernetes-style
objects are open-schema JSON, but batched device kernels need fixed
shapes. Objects are therefore:

1. flattened to (field-path, leaf-value) pairs,
2. bucketed by schema (one :class:`BucketEncoder` per schema bucket, so
   every batch is shape-homogeneous),
3. encoded as a dense ``uint32[S]`` vector of value hashes indexed by a
   per-bucket slot vocabulary (path -> slot), 0 = absent,
4. padded to the bucket's power-of-two capacity.

Volatile metadata (resourceVersion, generation, uid, creationTimestamp,
managedFields) is excluded, matching the reference's diff semantics
(pkg/syncer/specsyncer.go:17-41 deepEqualApartFromStatus). ``status.*``
slots are flagged so the diff kernel can run the spec lane and the status
lane from one encoding (statussyncer.go:15-27 deepEqualStatus).

A bucket that outgrows its slot capacity raises :class:`BucketOverflow`;
the caller re-buckets at double capacity (the host escape hatch for odd
objects — capacities stay powers of two so XLA recompiles at most
log2(max_slots) times per bucket).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Iterable, Mapping, Sequence

import numpy as np

from .hashing import hash_value

VOLATILE_META = frozenset(
    {"resourceVersion", "generation", "uid", "creationTimestamp", "managedFields"}
)


class BucketOverflow(Exception):
    """Object needs more slots than the bucket has; re-bucket larger."""


def flatten_object(obj: Mapping, max_depth: int = 8) -> list[tuple[str, Any]]:
    """Flatten to dotted-path leaves. Lists and over-deep subtrees hash whole.

    Patch granularity is object-level (the host rebuilds patches from real
    objects; the device only *decides*), so leaves don't need to be scalar.
    """
    out: list[tuple[str, Any]] = []

    def walk(prefix: str, v: Any, depth: int) -> None:
        if isinstance(v, Mapping) and depth < max_depth:
            if not v:
                out.append((prefix, {}))
                return
            for k in sorted(v.keys()):
                if depth == 1 and prefix == "metadata" and k in VOLATILE_META:
                    continue
                walk(f"{prefix}.{k}" if prefix else str(k), v[k], depth + 1)
        else:
            out.append((prefix, v))

    for k in sorted(obj.keys()):
        if k in ("apiVersion", "kind"):
            out.append((k, obj[k]))
            continue
        walk(k, obj[k], 1)
    return out


@dataclass
class EncodedBatch:
    """A device-ready batch of encoded objects."""

    values: np.ndarray  # uint32 [N, S]
    exists: np.ndarray  # bool   [N]
    keys: list  # host-side row -> object key alignment

    @property
    def n(self) -> int:
        return int(self.values.shape[0])

    @property
    def slots(self) -> int:
        return int(self.values.shape[1])


@dataclass
class BucketEncoder:
    """Slot vocabulary + encoder for one schema bucket.

    When the native library (native/encode.cc) loads, encoding runs
    through the C++ flatten+hash pipeline — byte-for-byte identical to
    the Python path (differential-tested in tests/test_native.py) — and
    the vocabulary is mirrored back after each call so
    :meth:`status_mask` and callers keep working unchanged.
    """

    capacity: int = 64
    slots: dict[str, int] = field(default_factory=dict)
    slot_paths: list[str] = field(default_factory=list)
    _native: Any = field(default=None, repr=False, compare=False)
    _native_tried: bool = field(default=False, repr=False, compare=False)

    def _slot_for(self, path: str) -> int:
        slot = self.slots.get(path)
        if slot is None:
            if len(self.slot_paths) >= self.capacity:
                raise BucketOverflow(
                    f"bucket full at {self.capacity} slots (adding {path!r})"
                )
            slot = len(self.slot_paths)
            self.slots[path] = slot
            self.slot_paths.append(path)
        return slot

    def _native_bucket(self):
        if not self._native_tried:
            self._native_tried = True
            try:
                from ..native import NativeBucket, available

                if available():
                    nb = NativeBucket(self.capacity)
                    for path in self.slot_paths:  # seed existing vocab
                        nb.add_path(path)
                    self._native = nb
            except Exception:
                self._native = None
        return self._native

    def _sync_native_vocab(self, nb) -> None:
        if nb.nslots > len(self.slot_paths):
            for path in nb.slot_paths()[len(self.slot_paths):]:
                self.slots[path] = len(self.slot_paths)
                self.slot_paths.append(path)

    def encode(self, obj: Mapping, out: np.ndarray | None = None) -> np.ndarray:
        """Encode one object into a uint32[capacity] vector."""
        if out is None:
            out = np.zeros(self.capacity, dtype=np.uint32)
        nb = self._native_bucket()
        if nb is not None:
            import json

            try:
                payload = json.dumps(obj).encode("utf-8")
            except (TypeError, ValueError):
                payload = None
            rc = nb.encode_json(payload, out) if payload is not None else -2
            if rc == 0:
                self._sync_native_vocab(nb)
                return out
            if rc == -1:
                self._sync_native_vocab(nb)
                raise BucketOverflow(f"bucket full at {self.capacity} slots")
            # Parse anomaly (e.g. >128-deep nesting, non-serializable
            # value): retire the native bucket for good — continuing to
            # use it after the Python path grows the vocabulary would
            # break the prefix invariant _sync_native_vocab relies on and
            # silently scramble slot assignments.
            self._native = None
        for path, value in flatten_object(obj):
            out[self._slot_for(path)] = hash_value(value)
        return out

    def encode_batch(
        self,
        objs: Sequence[Mapping | None],
        keys: Sequence | None = None,
        pad_to: int | None = None,
    ) -> EncodedBatch:
        """Encode objects (None = absent) into a padded batch.

        ``pad_to`` rounds the batch dimension up (power-of-two padding keeps
        the number of distinct compiled shapes small).
        """
        n = len(objs)
        rows = pad_to if pad_to is not None else n
        values = np.zeros((rows, self.capacity), dtype=np.uint32)
        exists = np.zeros(rows, dtype=bool)
        for i, obj in enumerate(objs):
            if obj is None:
                continue
            self.encode(obj, out=values[i])
            exists[i] = True
        return EncodedBatch(values, exists, list(keys) if keys is not None else list(range(n)))

    def status_mask(self) -> np.ndarray:
        """bool[capacity]: True where the slot is a ``status.*`` path."""
        mask = np.zeros(self.capacity, dtype=bool)
        for path, slot in self.slots.items():
            if path == "status" or path.startswith("status."):
                mask[slot] = True
        return mask

    def grown(self) -> "BucketEncoder":
        """A fresh encoder at double capacity (same vocabulary prefix)."""
        enc = BucketEncoder(capacity=self.capacity * 2)
        enc.slots = dict(self.slots)
        enc.slot_paths = list(self.slot_paths)
        return enc


def pad_pow2(n: int, floor: int = 8) -> int:
    """Round up to a power of two (min ``floor``) for stable jit shapes."""
    if n <= floor:
        return floor
    return 1 << (n - 1).bit_length()


def encode_labels(
    labels: Mapping[str, str] | None, capacity: int
) -> tuple[np.ndarray, np.ndarray]:
    """Encode a label map as (pair_hashes, key_hashes) uint32[capacity].

    Used by the labelmatch kernel; 0-padded. Overflowing label maps keep
    the first ``capacity`` pairs sorted by key (deterministic) — the host
    matcher remains the escape hatch for pathological objects.
    """
    from .hashing import hash_key, hash_pair

    pairs = np.zeros(capacity, dtype=np.uint32)
    keys = np.zeros(capacity, dtype=np.uint32)
    if labels:
        for i, k in enumerate(sorted(labels.keys())[:capacity]):
            pairs[i] = hash_pair(k, str(labels[k]))
            keys[i] = hash_key(k)
    return pairs, keys


def encode_label_batch(
    label_maps: Iterable[Mapping[str, str] | None], capacity: int = 8, pad_to: int | None = None
) -> tuple[np.ndarray, np.ndarray]:
    maps = list(label_maps)
    rows = pad_to if pad_to is not None else len(maps)
    pairs = np.zeros((rows, capacity), dtype=np.uint32)
    keys = np.zeros((rows, capacity), dtype=np.uint32)
    for i, m in enumerate(maps):
        pairs[i], keys[i] = encode_labels(m, capacity)
    return pairs, keys
