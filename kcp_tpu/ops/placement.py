"""Batched replica placement + status fan-in — the deployment splitter math.

The reference splits a root Deployment's replicas evenly across registered
clusters, remainder to the first clusters, one root at a time in a
goroutine (pkg/reconciler/deployment/deployment.go:125-161), and
aggregates leaf status counters back into the root (deployment.go:71-91).

Here both run batched over every (workspace, root-deployment) pair at
once: B roots x P physical clusters. This is BASELINE.json configs[2]
(10k workspaces x 8 clusters) expressed as a few hundred fused VPU ops.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def split_replicas(replicas: jax.Array, avail: jax.Array, balanced: bool = False) -> jax.Array:
    """Even split with the remainder going to the first available cluster.

    replicas: int32 [B]   desired root replicas
    avail:    bool  [B,P] cluster availability (registered, not excluded)
    returns:  int32 [B,P] leaf replica counts (0 where unavailable)

    Parity (default): floor division, then the WHOLE remainder on the
    first cluster, matching deployment.go:127-145 (``replicasEach :=
    replicas / len(cls)``, ``rest := replicas % len(cls)``, and
    ``index == 0`` receives ``replicasEach + rest``). With no available
    clusters the row is all zeros (the host sets Progressing=False,
    deployment.go:110-123).

    ``balanced=True`` instead spreads the remainder +1 over the first
    ``rest`` clusters (max-min <= 1) — a strictly more even placement
    offered as an opt-in improvement over the reference.
    """
    avail_i = avail.astype(jnp.int32)
    n = avail_i.sum(axis=-1, keepdims=True)  # [B,1]
    n_safe = jnp.maximum(n, 1)
    base = replicas[:, None] // n_safe
    rem = replicas[:, None] - base * n_safe
    # rank of each available cluster among available ones, in column order
    rank = jnp.cumsum(avail_i, axis=-1) - 1
    if balanced:
        extra = (rank < rem).astype(jnp.int32)
    else:
        extra = (rank == 0) * rem
    leaf = base + extra
    return jnp.where(avail & (n > 0), leaf, 0)


def split_replicas_weighted(
    replicas: jax.Array, weights: jax.Array, sel: jax.Array, rank: jax.Array
) -> jax.Array:
    """Capacity-weighted split over a *selected* cluster subset.

    replicas: int32 [B]   desired root replicas (callers clip <= 65535)
    weights:  int32 [B,P] per-cluster weight (callers clip <= 32767 so
                          replicas*weight stays inside int32)
    sel:      bool  [B,P] solver-selected clusters (weight > 0 where True)
    rank:     int32 [B,P] selection order, rank 0 = best score; selected
                          clusters hold ranks 0..k-1 (fleet/solver.py's
                          argsort-of-argsort makes this an invariant)
    returns:  int32 [B,P] leaf counts: floor(replicas*w/W) each, then the
                          remainder (< k, one per cluster) dealt to the
                          best-ranked clusters. Integer-exact: the row sum
                          equals replicas whenever anything is selected,
                          and identical math on host numpy reproduces it
                          bit-for-bit (no floats anywhere).
    """
    w = jnp.where(sel, weights, 0).astype(jnp.int32)
    total = w.sum(axis=-1, keepdims=True)
    total_safe = jnp.maximum(total, 1)
    base = (replicas[:, None] * w) // total_safe
    rem = replicas - base.sum(axis=-1)
    extra = (rank < rem[:, None]) & sel
    return jnp.where(sel & (total > 0), base + extra.astype(jnp.int32), 0)


def aggregate_status(leaf_counters: jax.Array, leaf_mask: jax.Array) -> jax.Array:
    """Sum leaf status counters into root status counters.

    leaf_counters: int32 [B,P,C] (e.g. C=5: replicas, updated, ready,
                   available, unavailable — the five counters the
                   reference sums, deployment.go:71-91)
    leaf_mask:     bool  [B,P]   which leafs exist
    returns:       int32 [B,C]
    """
    return (leaf_counters * leaf_mask[..., None].astype(leaf_counters.dtype)).sum(axis=1)


def placement_changed(current: jax.Array, desired: jax.Array) -> jax.Array:
    """bool [B]: any leaf's replica count differs -> row needs patching."""
    return (current != desired).any(axis=-1)


split_replicas_jit = jax.jit(split_replicas, static_argnames=("balanced",))
split_replicas_weighted_jit = jax.jit(split_replicas_weighted)
aggregate_status_jit = jax.jit(aggregate_status)
