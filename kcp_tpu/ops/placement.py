"""Batched replica placement + status fan-in — the deployment splitter math.

The reference splits a root Deployment's replicas evenly across registered
clusters, remainder to the first clusters, one root at a time in a
goroutine (pkg/reconciler/deployment/deployment.go:125-161), and
aggregates leaf status counters back into the root (deployment.go:71-91).

Here both run batched over every (workspace, root-deployment) pair at
once: B roots x P physical clusters. This is BASELINE.json configs[2]
(10k workspaces x 8 clusters) expressed as a few hundred fused VPU ops.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def split_replicas(replicas: jax.Array, avail: jax.Array, balanced: bool = False) -> jax.Array:
    """Even split with the remainder going to the first available cluster.

    replicas: int32 [B]   desired root replicas
    avail:    bool  [B,P] cluster availability (registered, not excluded)
    returns:  int32 [B,P] leaf replica counts (0 where unavailable)

    Parity (default): floor division, then the WHOLE remainder on the
    first cluster, matching deployment.go:127-145 (``replicasEach :=
    replicas / len(cls)``, ``rest := replicas % len(cls)``, and
    ``index == 0`` receives ``replicasEach + rest``). With no available
    clusters the row is all zeros (the host sets Progressing=False,
    deployment.go:110-123).

    ``balanced=True`` instead spreads the remainder +1 over the first
    ``rest`` clusters (max-min <= 1) — a strictly more even placement
    offered as an opt-in improvement over the reference.
    """
    avail_i = avail.astype(jnp.int32)
    n = avail_i.sum(axis=-1, keepdims=True)  # [B,1]
    n_safe = jnp.maximum(n, 1)
    base = replicas[:, None] // n_safe
    rem = replicas[:, None] - base * n_safe
    # rank of each available cluster among available ones, in column order
    rank = jnp.cumsum(avail_i, axis=-1) - 1
    if balanced:
        extra = (rank < rem).astype(jnp.int32)
    else:
        extra = (rank == 0) * rem
    leaf = base + extra
    return jnp.where(avail & (n > 0), leaf, 0)


def aggregate_status(leaf_counters: jax.Array, leaf_mask: jax.Array) -> jax.Array:
    """Sum leaf status counters into root status counters.

    leaf_counters: int32 [B,P,C] (e.g. C=5: replicas, updated, ready,
                   available, unavailable — the five counters the
                   reference sums, deployment.go:71-91)
    leaf_mask:     bool  [B,P]   which leafs exist
    returns:       int32 [B,C]
    """
    return (leaf_counters * leaf_mask[..., None].astype(leaf_counters.dtype)).sum(axis=1)


def placement_changed(current: jax.Array, desired: jax.Array) -> jax.Array:
    """bool [B]: any leaf's replica count differs -> row needs patching."""
    return (current != desired).any(axis=-1)


split_replicas_jit = jax.jit(split_replicas, static_argnames=("balanced",))
aggregate_status_jit = jax.jit(aggregate_status)
