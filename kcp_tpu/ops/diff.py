"""Batched spec/status three-way diff — the syncer hot loop, vectorized.

The reference runs ``deepEqualApartFromStatus`` / ``deepEqualStatus`` on
every informer event in per-cluster goroutines (pkg/syncer/
specsyncer.go:17-41, statussyncer.go:15-27) and then decides per object:
create downstream, update downstream, delete downstream, or upsync status
(specsyncer.go:86-132, statussyncer.go:41-63).

Here the same decision runs once, vectorized over every object of every
logical cluster in a schema bucket: one fused XLA program of elementwise
compares + masked reductions (pure VPU work, HBM-bandwidth bound, which is
exactly what a TPU does well at 100k+ rows).

Decision codes (uint8):
    0 NOOP    — in sync (or neither side exists)
    1 CREATE  — upstream exists, downstream missing -> create downstream
    2 UPDATE  — both exist, spec lanes differ       -> update downstream
    3 DELETE  — upstream gone, downstream exists    -> delete downstream

``status_upsync`` is an independent lane (both exist and status differs ->
copy status upstream), matching the reference's two separate controllers.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

DECISION_NOOP = 0
DECISION_CREATE = 1
DECISION_UPDATE = 2
DECISION_DELETE = 3


class SyncDecisions(NamedTuple):
    decision: jax.Array  # uint8 [B]
    status_upsync: jax.Array  # bool [B]
    changed_slots: jax.Array  # bool [B, S] (valid where both sides exist)


def sync_decisions(
    up_vals: jax.Array,  # uint32 [B, S] upstream encodings
    up_exists: jax.Array,  # bool  [B]
    down_vals: jax.Array,  # uint32 [B, S] downstream encodings
    down_exists: jax.Array,  # bool [B]
    status_mask: jax.Array,  # bool [S] or [B, S]: True for status.* slots
) -> SyncDecisions:
    """``status_mask`` may be per-bucket ([S]) or per-row ([B, S]) — the
    fused serving core packs rows from engines with different slot
    vocabularies into one bucket, so each row carries its owner's mask."""
    mask = status_mask if status_mask.ndim == 2 else status_mask[None, :]
    neq = up_vals != down_vals  # [B, S]
    spec_dirty = (neq & ~mask).any(axis=-1)
    status_dirty = (neq & mask).any(axis=-1)

    both = up_exists & down_exists
    decision = jnp.where(
        up_exists & ~down_exists,
        jnp.uint8(DECISION_CREATE),
        jnp.where(
            ~up_exists & down_exists,
            jnp.uint8(DECISION_DELETE),
            jnp.where(both & spec_dirty, jnp.uint8(DECISION_UPDATE), jnp.uint8(DECISION_NOOP)),
        ),
    )
    return SyncDecisions(decision, both & status_dirty, neq)


sync_decisions_jit = jax.jit(sync_decisions)


def apply_deltas(
    vals: jax.Array,  # uint32 [B, S] device-resident mirror
    exists: jax.Array,  # bool  [B]
    idx: jax.Array,  # int32 [D] rows touched by this delta batch
    new_vals: jax.Array,  # uint32 [D, S] new encodings (ignored for deletes)
    new_exists: jax.Array,  # bool [D] False = delete
    valid: jax.Array,  # bool [D] padding mask for the delta batch
) -> tuple[jax.Array, jax.Array]:
    """Scatter a padded delta batch into the device-resident mirror.

    This is the informer-cache-update analog: instead of a Go indexer
    mutation per event, the reconcile tick scatters the whole drained
    event batch in one compiled op. Padding rows are routed out of bounds
    and dropped by the scatter. The host batcher must dedup deltas by key
    (last event wins) before building the batch — duplicate in-batch
    indices have unspecified scatter order.
    """
    oob = jnp.int32(vals.shape[0])
    idx = jnp.where(valid, idx, oob)
    vals = vals.at[idx].set(new_vals, mode="drop")
    exists = exists.at[idx].set(new_exists, mode="drop")
    return vals, exists


apply_deltas_jit = jax.jit(apply_deltas)


class PatchSet(NamedTuple):
    """A fixed-capacity compaction of the actionable rows of a tick.

    The reference hands each actionable object to a goroutine
    (pkg/syncer/syncer.go:293-341); our host applier instead receives this
    bounded patch set — only rows whose decision != NOOP or that need a
    status upsync — so the device->host link carries O(actionable), not
    O(fleet). ``idx`` rows past ``count`` are padding (== B); ``overflow``
    means more than ``capacity`` rows were actionable and the host should
    fetch the full decision lane (level-triggered, so nothing is lost —
    the next tick re-derives any row it skips).
    """

    idx: jax.Array  # int32 [K] actionable row indices, padded with B
    code: jax.Array  # uint8 [K] decision code per patch row
    upsync: jax.Array  # bool [K] status-upsync flag per patch row
    count: jax.Array  # int32 [] number of valid patch rows (clamped to K)
    overflow: jax.Array  # bool [] capacity exceeded this tick


def compact_patches(
    decision: jax.Array,  # uint8 [B]
    status_upsync: jax.Array,  # bool [B]
    capacity: int,
) -> PatchSet:
    """Compact the full decision lanes into a bounded patch set."""
    b = decision.shape[0]
    actionable = (decision != DECISION_NOOP) | status_upsync
    total = actionable.sum(dtype=jnp.int32)
    (idx,) = jnp.nonzero(actionable, size=capacity, fill_value=b)
    safe = jnp.minimum(idx, b - 1)
    valid = idx < b
    code = jnp.where(valid, decision[safe], jnp.uint8(DECISION_NOOP))
    upsync = jnp.where(valid, status_upsync[safe], False)
    return PatchSet(
        idx=idx.astype(jnp.int32),
        code=code,
        upsync=upsync,
        count=jnp.minimum(total, capacity),
        overflow=total > capacity,
    )
