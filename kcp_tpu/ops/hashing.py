"""Host-side hashing primitives feeding the device encoders.

Objects never cross the host<->device boundary as strings: every field
path, leaf value, label pair, and schema token is hashed host-side to a
uint32 and the device operates on hash tensors only. FNV-1a is used for
its simplicity and distribution; collisions are handled by design — a
hash collision can at worst cause a *missed* update (two different values
mapping to the same hash), and level-triggered resync bounds the damage
exactly the way the reference's 10h informer resyncs bound missed events
(reference: pkg/syncer/syncer.go:27).

These are pure functions of canonical JSON, so host and device (and any
future C++ encoder) agree byte-for-byte.
"""

from __future__ import annotations

import json
from functools import lru_cache
from typing import Any

FNV_OFFSET = 0x811C9DC5
FNV_PRIME = 0x01000193
_MASK = 0xFFFFFFFF


def fnv1a(data: bytes, seed: int = FNV_OFFSET) -> int:
    h = seed
    for b in data:
        h ^= b
        h = (h * FNV_PRIME) & _MASK
    return h


@lru_cache(maxsize=65536)
def hash_str(s: str) -> int:
    # memoized: schema keys and label names repeat across thousands of
    # objects, and the pure-python FNV byte loop dominates tokenization
    # otherwise (the suite's schema-bucketing lane measured it)
    return fnv1a(s.encode("utf-8"))


def canonical_json(value: Any) -> str:
    """Deterministic JSON: sorted keys, no whitespace."""
    return json.dumps(value, sort_keys=True, separators=(",", ":"), ensure_ascii=False)


@lru_cache(maxsize=65536)
def _hash_scalar(type_name: str, value) -> int:
    # type_name disambiguates python equality collisions (True == 1 and
    # hash(True) == hash(1), but canonical_json renders "true" vs "1" —
    # a bare value-keyed cache would alias them)
    h = fnv1a(canonical_json(value).encode("utf-8"))
    return h if h != 0 else 1


def hash_value(value: Any) -> int:
    """Hash a JSON leaf (or subtree) value; never returns 0.

    0 is reserved as the "absent" sentinel in encoded tensors. Scalar
    leaves are memoized (enum members, type names, and common field
    values repeat endlessly across a fleet's objects and schemas);
    dict/list subtrees hash uncached.
    """
    if value is None or isinstance(value, (str, int, float, bool)):
        if isinstance(value, float) and value == 0.0:
            # -0.0 == 0.0 with equal python hashes, but canonical_json
            # renders them "-0.0" vs "0.0" — a cache key would alias
            # them and make the hash first-caller-dependent across hosts
            h = fnv1a(canonical_json(value).encode("utf-8"))
            return h if h != 0 else 1
        return _hash_scalar(type(value).__name__, value)
    h = fnv1a(canonical_json(value).encode("utf-8"))
    return h if h != 0 else 1


def hash_pair(key: str, value: str) -> int:
    """Hash a label (key, value) pair into one uint32; never 0."""
    h = fnv1a(b"\x00".join((key.encode("utf-8"), value.encode("utf-8"))))
    return h if h != 0 else 1


def hash_key(key: str) -> int:
    h = hash_str(key)
    return h if h != 0 else 1


def mix32(h: int) -> int:
    """Murmur3 finalizer — avalanche a uint32."""
    h &= _MASK
    h ^= h >> 16
    h = (h * 0x85EBCA6B) & _MASK
    h ^= h >> 13
    h = (h * 0xC2B2AE35) & _MASK
    h ^= h >> 16
    return h
