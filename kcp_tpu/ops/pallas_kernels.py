"""Pallas TPU kernels for the reconcile hot path.

``decide_and_match`` fuses the two row-major lanes of the reconcile step
— the spec/status three-way diff (ops/diff.sync_decisions; reference hot
loop pkg/syncer/specsyncer.go:17-41 + statussyncer.go:15-27) and the
label-selector fan-out (ops/labelmatch.fanout_match; reference
pkg/syncer/syncer.go:106-108) — into ONE pass over the device-resident
mirrors. The un-fused XLA path streams ``up_vals``/``down_vals`` for the
diff and ``pair_hashes`` for the fan-out as separate kernels; this
kernel reads each row block into VMEM once and emits only the per-row
decision lanes and the per-selector match counts, so HBM traffic is the
two mirror reads plus O(B + C) outputs. Pure VPU work — no MXU — which
is exactly the profile of control-plane math: bandwidth-bound
elementwise compares and masked reductions.

Layout: everything is plane-native. Object rows are grouped 128 to a
plane row, so per-row scalars (exists in, decision/upsync out) are
``[B/128, 128]`` int32 planes — fully-utilized (8, 128) tiles — and the
value mirrors are ``[B/128, 128, S]`` so row reductions land directly in
plane shape. This avoids every Mosaic no-go found on v5e: ``[1, B]``
planes (8x sublane padding blows scoped VMEM), 1-D<->2-D shape casts
(``vector<8x128> -> vector<1024x1>`` unsupported), and minor-dim
insertion on 1-bit vectors (mask math runs in int32).

Grid: 1-D over row blocks (sequential on TPU, so the match-count
accumulator output block is carried in VMEM across steps — the standard
Pallas accumulation pattern).

``interpret=True`` (automatic on CPU backends) runs the same kernel
under the Pallas interpreter so the full test suite exercises it
without TPU hardware.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

DECISION_NOOP = 0
DECISION_CREATE = 1
DECISION_UPDATE = 2
DECISION_DELETE = 3

_LANES = 128  # rows per plane row; B must divide by it on TPU

# measured scoped-VMEM-safe budget in per-block row-words: at S=64, L=8,
# per-row mask, a block row loads ~3S+L = 200 uint32 words (up + down +
# mask + pair hashes); br=2048 (409,600 words) fits the v5e's 16 MB
# scoped limit with headroom while br=4096 allocates ~24 MB and OOMs
# (hardware-verified). The budget is calibrated to that safe point.
_VMEM_WORD_BUDGET = 2048 * 200


def max_block_rows(local_rows: int, slots: int, labels: int = 0,
                   per_row_mask: bool = True) -> int:
    """Largest block_rows that divides ``local_rows``, is a multiple of
    the 128-lane width, and fits the measured scoped-VMEM budget for
    this row footprint — ``slots``-wide value mirrors (×2), the status
    mask (per-row form loads another ``slots`` column), and the
    ``labels``-wide pair hashes all ride in the same block. 0 if none
    qualifies (caller falls back to the XLA lanes)."""
    words = (3 if per_row_mask else 2) * max(slots, 1) + labels
    cap = _VMEM_WORD_BUDGET // words
    for k in (2048, 1024, 512, 256, 128):
        if k <= cap and local_rows % k == 0:
            return k
    # even a 128-row block exceeds the budget: XLA lanes
    return 0


def default_interpret() -> bool:
    """Whether decide_and_match will run under the Pallas interpreter by
    default on the current backend (the single source of truth for the
    bench's '[interpret mode]' annotation)."""
    return jax.default_backend() == "cpu"


def _decide_match_kernel(up_ref, down_ref, upe_ref, dne_ref, mask_ref,
                         pair_ref, sel_ref,
                         decision_ref, upsync_ref, counts_ref):
    up = up_ref[...]          # u32 [PR, 128, S]
    down = down_ref[...]      # u32 [PR, 128, S]
    neq = up != down
    # bucket-wide [1, 1, S] or per-row [PR, 128, S] — both broadcast
    # against neq (the serving core's shared buckets carry per-row masks)
    status = mask_ref[...] != 0
    spec_dirty = jnp.any(neq & ~status, axis=-1)    # [PR, 128]
    status_dirty = jnp.any(neq & status, axis=-1)   # [PR, 128]

    upe_i = upe_ref[...]      # int32 [PR, 128]
    upe = upe_i != 0
    dne = dne_ref[...] != 0
    both = upe & dne
    decision_ref[...] = jnp.where(
        upe & ~dne,
        jnp.int32(DECISION_CREATE),
        jnp.where(
            ~upe & dne,
            jnp.int32(DECISION_DELETE),
            jnp.where(both & spec_dirty, jnp.int32(DECISION_UPDATE),
                      jnp.int32(DECISION_NOOP)),
        ),
    )
    upsync_ref[...] = (both & status_dirty).astype(jnp.int32)

    # fan-out: does row (p, r) carry selector c's pair hash? Unrolled
    # over the L label slots; temporaries stay [PR, 128, C].
    pair = pair_ref[...]      # u32 [PR, 128, L]
    sel = sel_ref[...][0]     # u32 [C]
    hit = pair[:, :, 0][:, :, None] == sel[None, None, :]
    for l in range(1, pair.shape[-1]):
        hit = hit | (pair[:, :, l][:, :, None] == sel[None, None, :])
    # only resident upstream objects fan out; mask-multiply in int32
    # (Mosaic can't insert a minor dim on 1-bit vectors)
    live = hit.astype(jnp.int32) * upe_i[:, :, None]
    partial = live.sum(axis=(0, 1))[None, :]  # [1, C]

    @pl.when(pl.program_id(0) == 0)
    def _init():
        counts_ref[...] = jnp.zeros_like(counts_ref)

    counts_ref[...] += partial


@functools.partial(jax.jit, static_argnames=("block_rows", "interpret"))
def decide_and_match(
    up_vals: jax.Array,      # uint32 [B, S]
    up_exists: jax.Array,    # bool [B]
    down_vals: jax.Array,    # uint32 [B, S]
    down_exists: jax.Array,  # bool [B]
    status_mask: jax.Array,  # bool [S] bucket-wide or [B, S] per-row
    pair_hashes: jax.Array,  # uint32 [B, L]
    sel_hashes: jax.Array,   # uint32 [C]
    block_rows: int = 2048,
    interpret: bool | None = None,
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Fused decision + fan-out: (decision u8 [B], upsync bool [B],
    match_counts int32 [C]).

    ``block_rows`` defaults to the measured scoped-VMEM-safe block for
    S=64 on a v5e: 4096-row blocks compile to a ~24 MB scoped allocation
    against the 16 MB limit (hardware-verified OOM), 2048 fits with
    headroom. Use :func:`max_block_rows` to scale the cap for wider
    buckets.

    Matches ops.diff.sync_decisions + ops.labelmatch.fanout_match
    (fan-out counted over resident upstream rows), differential-tested
    against both in tests/test_pallas.py. ``status_mask`` may be the
    bucket-wide [S] form or the serving core's per-row [B, S] form.
    """
    b, s = up_vals.shape
    c = sel_hashes.shape[0]
    l = pair_hashes.shape[1]
    per_row_mask = status_mask.ndim == 2
    br = min(block_rows, b)
    if b % br:
        raise ValueError(f"B={b} not divisible by block_rows={br}")
    if interpret is None:
        interpret = default_interpret()
    lanes = _LANES if br % _LANES == 0 else 1
    if not interpret and lanes == 1:
        raise ValueError(f"block_rows={br} must be a multiple of {_LANES} on TPU")
    pr = br // lanes  # plane rows per block
    nr = b // lanes   # plane rows total
    grid = (b // br,)

    val_block = lambda width: pl.BlockSpec((pr, lanes, width), lambda i: (i, 0, 0))
    plane_block = pl.BlockSpec((pr, lanes), lambda i: (i, 0))
    bcast3 = lambda width: pl.BlockSpec((1, 1, width), lambda i: (0, 0, 0))
    bcast2 = lambda width: pl.BlockSpec((1, width), lambda i: (0, 0))

    plane = lambda x: x.astype(jnp.int32).reshape(nr, lanes)

    if per_row_mask:
        mask_spec = val_block(s)
        mask_arg = status_mask.astype(jnp.int32).reshape(nr, lanes, s)
    else:
        mask_spec = bcast3(s)
        mask_arg = status_mask.astype(jnp.int32)[None, None, :]

    decision, upsync, counts = pl.pallas_call(
        _decide_match_kernel,
        grid=grid,
        in_specs=[
            val_block(s),          # up_vals    [NR, 128, S]
            val_block(s),          # down_vals
            plane_block,           # up_exists  [NR, 128]
            plane_block,           # down_exists
            mask_spec,             # status_mask [1,1,S] or [NR,128,S]
            val_block(l),          # pair_hashes [NR, 128, L]
            bcast2(c),             # sel_hashes  [1, C]
        ],
        out_specs=[
            plane_block,           # decision [NR, 128]
            plane_block,           # upsync
            bcast2(c),             # counts [1, C] accumulator
        ],
        out_shape=[
            jax.ShapeDtypeStruct((nr, lanes), jnp.int32),
            jax.ShapeDtypeStruct((nr, lanes), jnp.int32),
            jax.ShapeDtypeStruct((1, c), jnp.int32),
        ],
        interpret=interpret,
    )(
        up_vals.reshape(nr, lanes, s),
        down_vals.reshape(nr, lanes, s),
        plane(up_exists),
        plane(down_exists),
        mask_arg,
        pair_hashes.reshape(nr, lanes, l),
        sel_hashes[None, :],
    )
    return (
        decision.reshape(b).astype(jnp.uint8),
        upsync.reshape(b) != 0,
        counts[0],
    )


def decide_and_match_sharded(
    mesh,
    up_vals: jax.Array,      # uint32 [B, S], rows sharded over the mesh
    up_exists: jax.Array,    # bool [B]
    down_vals: jax.Array,    # uint32 [B, S]
    down_exists: jax.Array,  # bool [B]
    status_mask: jax.Array,  # bool [S] replicated or [B, S] row-sharded
    pair_hashes: jax.Array,  # uint32 [B, L]
    sel_hashes: jax.Array,   # uint32 [C] replicated
    block_rows: int = 2048,
    interpret: bool | None = None,
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """The fused pass on a sharded bucket: shard_map runs the Pallas
    kernel per device on its local row block (slot columns are gathered
    to full S per row — the kernel reduces over slots), and the
    per-selector match counts psum across the row axes. Decision lanes
    stay row-sharded; counts come back replicated.

    This is the TPU-idiomatic composition: the kernel never knows about
    the mesh, the mesh program never re-implements the kernel.
    """
    from jax.sharding import PartitionSpec as P

    from ..parallel.mesh import HOSTS_AXIS, TENANTS_AXIS

    row_axes = tuple(a for a in (HOSTS_AXIS, TENANTS_AXIS)
                     if a in mesh.axis_names)
    row = row_axes if len(row_axes) > 1 else row_axes[0]
    per_row_mask = status_mask.ndim == 2
    mask_spec = P(row, None) if per_row_mask else P()

    def body(uv, ue, dv, de, m, ph, sh):
        dec, ups, counts = decide_and_match(
            uv, ue, dv, de, m, ph, sh,
            block_rows=min(block_rows, uv.shape[0]), interpret=interpret)
        for a in row_axes:
            counts = jax.lax.psum(counts, axis_name=a)
        return dec, ups, counts

    smap = jax.shard_map(
        body, mesh=mesh,
        in_specs=(P(row, None), P(row), P(row, None), P(row), mask_spec,
                  P(row, None), P()),
        out_specs=(P(row), P(row), P()),
        # pallas_call has no varying-manual-axes rule; skip the check
        check_vma=False,
    )
    return smap(up_vals, up_exists, down_vals, down_exists, status_mask,
                pair_hashes, sel_hashes)


