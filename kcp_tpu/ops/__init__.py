"""Device-side decision math.

Everything in this package is regular, batched, fixed-shape tensor code —
the vectorized re-expression of the reference's per-goroutine hot loops
(SURVEY.md §2.4 table):

- ``encode``     objects -> fixed-shape hash tensors, schema bucketing
- ``diff``       batched spec/status three-way diff (pkg/syncer analog)
- ``placement``  replica bin-packing + status fan-in (deployment splitter)
- ``labelmatch`` label-selector match fan-out (informer filtering)
- ``schemahash`` batched schema hashing for bucket assignment
- ``hashing``    host-side FNV-1a primitives feeding the encoders
"""

from .diff import (
    DECISION_CREATE,
    DECISION_DELETE,
    DECISION_NOOP,
    DECISION_UPDATE,
    PatchSet,
    compact_patches,
    sync_decisions,
)
from .encode import BucketEncoder, EncodedBatch
from .placement import aggregate_status, split_replicas

__all__ = [
    "BucketEncoder",
    "EncodedBatch",
    "PatchSet",
    "compact_patches",
    "sync_decisions",
    "split_replicas",
    "aggregate_status",
    "DECISION_NOOP",
    "DECISION_CREATE",
    "DECISION_UPDATE",
    "DECISION_DELETE",
]
