"""Label-selector matching as a batched device kernel.

The reference filters every informer event stream server-side with a label
selector per (cluster, GVR) — ``kcp.dev/cluster=<id>`` (pkg/syncer/
syncer.go:106-108). At control-plane scale that is a match of N objects
against C selectors on every fan-out decision: BASELINE.json configs[4]
sizes it at 100k objects.

Encoding (see ops/encode.py): each object's labels become uint32 pair
hashes (hash(key\\0value)) and key hashes, 0-padded to L slots. Selectors
compile to R requirement rows of up to V alternative hashes:

    requirement satisfied = negate XOR (any alternative hash present)

which uniformly covers =, !=, in, notin, exists, !exists (Kubernetes
semantics: != and notin are satisfied by absence; label keys are unique
per object so pair-presence == key-equals-value).

Three paths:
- :func:`match_batch` — general: N objects x 1 compiled selector (device)
- :func:`fanout_match` — N objects x C single-pair selectors (the syncer
  fan-out shape, one ``kcp.dev/cluster=<id>`` per cluster) as one
  [N, C] compare reduce (device)
- :func:`match_batch_np` / :func:`fanout_match_np` — numpy twins of the
  same kernels for host-side consumers (the store's batched watch
  fan-out) where a device round trip per micro-batch would cost more
  than it saves

The hash functions are pluggable: the device path uses the 32-bit FNV
hashes (collision-tolerant — the syncer re-verifies on the host before
every write), while the store's exact fan-out passes interned label ids
so two distinct pairs can never alias.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from ..store.selectors import LabelSelector
from .hashing import hash_key, hash_pair


@dataclass(frozen=True)
class CompiledSelector:
    """Device-ready selector: [R, V] alternatives + per-row flags."""

    alts: np.ndarray  # uint32 [R, V] candidate hashes (0 = unused alt)
    negate: np.ndarray  # bool [R]
    use_key: np.ndarray  # bool [R] match against key hashes, not pair hashes
    valid: np.ndarray  # bool [R] requirement rows in use

    @property
    def rows(self) -> int:
        return int(self.alts.shape[0])


def compile_selector(
    sel: LabelSelector,
    max_reqs: int = 8,
    max_alts: int = 8,
    pair_hash=hash_pair,
    key_hash=hash_key,
) -> CompiledSelector:
    """Compile to the [R, V] kernel shape; raises ValueError when the
    selector exceeds it. ``pair_hash``/``key_hash`` default to the 32-bit
    FNV hashes the device kernels consume; exact host-side consumers pass
    interning functions instead (ids must be nonzero uint32)."""
    reqs = sel.requirements
    if len(reqs) > max_reqs:
        raise ValueError(f"selector has {len(reqs)} requirements (max {max_reqs})")
    alts = np.zeros((max_reqs, max_alts), dtype=np.uint32)
    negate = np.zeros(max_reqs, dtype=bool)
    use_key = np.zeros(max_reqs, dtype=bool)
    valid = np.zeros(max_reqs, dtype=bool)
    for i, r in enumerate(reqs):
        valid[i] = True
        if r.op in ("=", "in"):
            hashes = [pair_hash(r.key, v) for v in r.values]
        elif r.op in ("!=", "notin"):
            negate[i] = True
            hashes = [pair_hash(r.key, v) for v in r.values]
        elif r.op == "exists":
            use_key[i] = True
            hashes = [key_hash(r.key)]
        elif r.op == "!exists":
            negate[i] = True
            use_key[i] = True
            hashes = [key_hash(r.key)]
        else:
            raise ValueError(f"unknown op {r.op!r}")
        if len(hashes) > max_alts:
            raise ValueError(f"requirement on {r.key!r} has {len(hashes)} values (max {max_alts})")
        alts[i, : len(hashes)] = hashes
    return CompiledSelector(alts, negate, use_key, valid)


def try_compile_selector(
    sel: LabelSelector,
    max_reqs: int = 8,
    max_alts: int = 8,
    pair_hash=hash_pair,
    key_hash=hash_key,
) -> CompiledSelector | None:
    """:func:`compile_selector`, but a selector that exceeds the [R, V]
    kernel shape returns None (counted in ``labelmatch_fallback_total``)
    so callers fall back to host-path matching instead of erroring out —
    an oversized selector is a valid request, just not a kernel-shaped
    one. Unknown operators still raise."""
    reqs = sel.requirements
    oversized = len(reqs) > max_reqs or any(
        len(r.values) > max_alts for r in reqs)
    if oversized:
        from ..utils.trace import REGISTRY

        REGISTRY.counter(
            "labelmatch_fallback_total",
            "selectors too large for the match kernel, matched host-side",
        ).inc()
        return None
    return compile_selector(sel, max_reqs, max_alts, pair_hash, key_hash)


def match_batch(
    pair_hashes: jax.Array,  # uint32 [N, L]
    key_hashes: jax.Array,  # uint32 [N, L]
    alts: jax.Array,  # uint32 [R, V]
    negate: jax.Array,  # bool [R]
    use_key: jax.Array,  # bool [R]
    valid: jax.Array,  # bool [R]
) -> jax.Array:
    """bool [N]: does each object match the selector?"""
    table = jnp.where(use_key[:, None, None], key_hashes[None], pair_hashes[None])  # [R,N,L]
    alt_valid = alts != 0  # [R,V]
    # contains[R,N]: any (alt, slot) pair equal (and alt in use)
    eq = table[:, :, :, None] == alts[:, None, None, :]  # [R,N,L,V]
    contains = (eq & alt_valid[:, None, None, :]).any(axis=(2, 3))
    satisfied = jnp.logical_xor(contains, negate[:, None])  # [R,N]
    satisfied = satisfied | ~valid[:, None]
    return satisfied.all(axis=0)


match_batch_jit = jax.jit(match_batch)


def fanout_match(pair_hashes: jax.Array, selector_hashes: jax.Array) -> jax.Array:
    """bool [N, C]: object n carries selector c's (key=value) pair.

    The syncer fan-out shape: C logical "informers" each filtering on one
    equality pair. One broadcast compare + reduce; at N=100k, C=1k, L=8
    this is ~0.8G byte-compares — microseconds of VPU time, vs 100k Go
    selector evaluations per cluster in the reference.
    """
    return (pair_hashes[:, None, :] == selector_hashes[None, :, None]).any(axis=-1)


fanout_match_jit = jax.jit(fanout_match)


def match_batch_np(
    pair_hashes: np.ndarray,  # uint32 [N, L]
    key_hashes: np.ndarray,  # uint32 [N, L]
    cs: CompiledSelector,
) -> np.ndarray:
    """Numpy twin of :func:`match_batch`: bool [N], no device round trip.

    The store's watch fan-out runs this per micro-batch — tens to
    hundreds of rows, where a transfer would dominate the compare."""
    table = np.where(cs.use_key[:, None, None], key_hashes[None], pair_hashes[None])  # [R,N,L]
    eq = table[:, :, :, None] == cs.alts[:, None, None, :]  # [R,N,L,V]
    contains = (eq & (cs.alts != 0)[:, None, None, :]).any(axis=(2, 3))  # [R,N]
    satisfied = np.logical_xor(contains, cs.negate[:, None]) | ~cs.valid[:, None]
    return satisfied.all(axis=0)


def fanout_match_np(pair_hashes: np.ndarray, selector_hashes: np.ndarray) -> np.ndarray:
    """Numpy twin of :func:`fanout_match`: bool [N, C]."""
    return (pair_hashes[:, None, :] == selector_hashes[None, :, None]).any(axis=-1)


def match_host(sel: LabelSelector, labels_list: list[dict | None]) -> np.ndarray:
    """Host reference implementation (differential-test oracle)."""
    return np.array([sel.matches(labels or {}) for labels in labels_list], dtype=bool)
