"""Trace-tree assembly and convergence phase profiles.

Consumers of the per-process span buffers (:mod:`.trace`): the router's
``/debug/trace`` scatter-gather, ``scripts/tracetool.py``, the scenario
engine's scorecard attachments, and the ``bench.py --trace``
sum-reconciliation gate all share these pure functions.

A *trace tree* is just a list of span dicts (possibly from several
processes) sharing a trace id; :func:`build_tree` nests them by parent
span id (orphans — spans whose parent lives in an unscraped process —
become roots, honestly). A *phase profile* reduces a convergence trace
to ``{phase: seconds}`` over the canonical :data:`~.trace.PHASES`
timeline, deriving the two gap phases (``propagate``, ``observe``) from
adjacent span boundaries so the profile always sums to the end-to-end
wall time.
"""

from __future__ import annotations

from .trace import PHASES


def build_tree(spans: list[dict]) -> list[dict]:
    """Nest spans by parent id: returns root nodes, each a copy of the
    span dict with a ``children`` list, siblings ordered by t0."""
    nodes = {s["span"]: dict(s, children=[]) for s in spans}
    roots: list[dict] = []
    for node in nodes.values():
        parent = nodes.get(node.get("parent") or "")
        if parent is not None and parent is not node:
            parent["children"].append(node)
        else:
            roots.append(node)
    def _sort(ns: list[dict]) -> None:
        ns.sort(key=lambda n: n["t0"])
        for n in ns:
            _sort(n["children"])
    _sort(roots)
    return roots


def render_tree(spans: list[dict]) -> str:
    """Human-readable indented tree (tracetool's output)."""
    lines: list[str] = []

    def _walk(node: dict, depth: int) -> None:
        attrs = node.get("attrs") or {}
        extra = " ".join(f"{k}={v}" for k, v in sorted(attrs.items()))
        lines.append("%s%-24s %8.3fms  [%s]%s" % (
            "  " * depth, node["name"], node["dur"] * 1000.0,
            node.get("proc", "?"), ("  " + extra) if extra else ""))
        for c in node["children"]:
            _walk(c, depth + 1)

    for root in build_tree(spans):
        _walk(root, 0)
    return "\n".join(lines)


def merge_fragments(span_lists: list[list[dict]],
                    rv: str | int | None = None) -> list[dict]:
    """Union spans from several buffers into one logical trace. When
    ``rv`` is given, convergence fragments minted under a *different*
    trace id (cross-process engines, see :func:`~.trace.conv_begin`)
    are included if any of their spans carries a matching ``rv`` attr —
    the out-of-band join that keeps wire bytes untouched."""
    out: list[dict] = []
    seen: set[tuple[str, str]] = set()
    want_rv = str(rv) if rv is not None else None
    for spans in span_lists:
        frag_ok = want_rv is not None and any(
            str((s.get("attrs") or {}).get("rv", "")) == want_rv
            for s in spans)
        for s in spans:
            if want_rv is not None and not frag_ok:
                continue
            key = (s["trace"], s["span"])
            if key in seen:
                continue
            seen.add(key)
            out.append(s)
    out.sort(key=lambda s: s["t0"])
    return out


def phase_profile(spans: list[dict]) -> dict:
    """Reduce a convergence trace to ``{phase: seconds}`` plus
    ``e2e``/``sum``/``sum_ok``. Measured phases come from ``conv.<p>``
    spans; ``propagate`` and ``observe`` are derived from the gaps
    between adjacent measured boundaries (and the ``conv.e2e`` root), so
    the profile telescopes: sum(phases) == e2e whenever the write,
    engine, and e2e spans are all present (``sum_ok`` = within 5%)."""
    by_phase: dict[str, dict] = {}
    e2e = None
    for s in spans:
        name = s["name"]
        if name == "conv.e2e":
            e2e = s
        elif name.startswith("conv."):
            p = name[len("conv."):]
            # keep the earliest occurrence per phase (a retried apply
            # can re-record patch; the first is the causal one)
            if p not in by_phase or s["t0"] < by_phase[p]["t0"]:
                by_phase[p] = s
    prof: dict[str, float] = {}
    for p in PHASES:
        s = by_phase.get(p)
        if s is not None:
            prof[p] = s["dur"]
    # derived gap phases, from shared boundaries
    w, st = by_phase.get("write"), by_phase.get("stage")
    if "propagate" not in prof and w is not None and st is not None:
        prof["propagate"] = max(0.0, st["t0"] - (w["t0"] + w["dur"]))
    up = by_phase.get("upstatus")
    if "observe" not in prof and e2e is not None and up is not None:
        prof["observe"] = max(
            0.0, (e2e["t0"] + e2e["dur"]) - (up["t0"] + up["dur"]))
    out: dict = {"phases": {p: round(v, 6) for p, v in prof.items()}}
    total = sum(prof.values())
    out["sum"] = round(total, 6)
    if e2e is not None:
        out["e2e"] = e2e["dur"]
        out["sum_ok"] = (e2e["dur"] > 0
                         and abs(total - e2e["dur"]) / e2e["dur"] <= 0.05)
    return out


def diff_profiles(a: dict, b: dict) -> list[dict]:
    """Per-phase deltas between two phase profiles (tracetool diff):
    rows of {phase, a, b, delta}, ordered by the canonical timeline."""
    pa, pb = a.get("phases", a), b.get("phases", b)
    rows = []
    for p in PHASES:
        va, vb = pa.get(p), pb.get(p)
        if va is None and vb is None:
            continue
        rows.append({"phase": p, "a": va, "b": vb,
                     "delta": round((vb or 0.0) - (va or 0.0), 6)})
    return rows


def summarize_trace(spans: list[dict], trace_id: str | None = None) -> dict:
    """A compact scorecard attachment for one assembled trace."""
    if not spans:
        return {}
    t0 = min(s["t0"] for s in spans)
    t1 = max(s["t0"] + s["dur"] for s in spans)
    slowest = max(spans, key=lambda s: s["dur"])
    out = {
        "id": trace_id or spans[0]["trace"],
        "dur_ms": round((t1 - t0) * 1000.0, 3),
        "spans": len(spans),
        "procs": sorted({s.get("proc", "?") for s in spans}),
        "slowest_span": {"name": slowest["name"],
                         "dur_ms": round(slowest["dur"] * 1000.0, 3)},
        "names": sorted({s["name"] for s in spans}),
    }
    prof = phase_profile(spans)
    if prof.get("phases"):
        out["profile"] = prof
    return out
