"""Distributed tracing: W3C-traceparent contexts + per-process span buffers.

The reference punts on cross-process attribution — its forked apiserver
serves ``/metrics`` and ``/debug/pprof`` that nothing first-party touches
(SURVEY.md §5) — and upstream later closed the gap with API-server request
tracing (KEP-647, W3C ``traceparent`` propagation). This module is that
layer for the kcp-tpu fleet, Dapper-style:

- a :class:`TraceContext` (trace id, span id, sampled flag) minted by the
  first hop (RestClient or the serving handler) and propagated as a
  ``traceparent`` request header across router → shard → replica hops;
- head-based sampling (``KCP_TRACE_SAMPLE``, default 1-in-64) decided by
  a seeded coin BEFORE any ids are minted — the unsampled fast path
  costs one RNG draw, and a fixed ``KCP_TRACE_SEED`` reproduces the
  exact decision sequence; fault-injected runs (an active ``KCP_FAULTS``
  schedule) are always sampled, and the serving layer force-records
  requests that breach the SLO (``KCP_TRACE_SLO_MS``) even when the head
  decision said no;
- finished spans land in a bounded per-process ring buffer
  (``KCP_TRACE_BUFFER`` entries) served by ``GET /debug/trace?id=`` /
  ``?slowest=N`` — the router scatter-gathers shard buffers to assemble
  cross-process trees (:mod:`.assemble`);
- reconcile causality: a sampled spec write's context rides its WAL
  record (``rec["tc"]``) and its shared watch :class:`Event` (one stamp
  for every watcher, the PR 5/PR 11 shared-Event discipline), plus an
  object-identity link (:func:`link_obj`) so an in-process informer's
  snapshot resolves back to the committing trace with one dict probe;
- the convergence decomposition: :func:`phase` records one contiguous
  segment of the spec→status timeline as both a ``conv.<phase>`` span
  and a ``convergence_<phase>_seconds`` histogram — phases share
  boundary timestamps, so their sum telescopes to the end-to-end wall
  time by construction (the ``bench.py --trace`` reconciliation gate).

Wire neutrality is a hard contract: tracing adds a request header on
client hops and nothing else — response bytes, watch streams, and stored
objects are byte-identical with tracing on or off (``KCP_TRACE=0``
disables even the header), proven by the differential fuzz in
tests/test_tracing.py. Off-path cost when disabled is one attribute read
per hop; when enabled-but-unsampled, one contextvar read plus a
deterministic modulo per minted trace.
"""

from __future__ import annotations

import contextlib
import contextvars
import os
import random
import threading
import time
from collections import deque
from dataclasses import dataclass
from typing import Any, Iterator

from ..analysis.sanitize import make_lock
from ..utils.trace import REGISTRY

#: the W3C propagation header (lower-cased: the httpd lower-cases keys)
TRACEPARENT = "traceparent"

#: the convergence phases, in timeline order. ``write`` (client spec
#: write round trip), ``propagate`` (ack → syncer staged; derived from
#: span boundaries), ``stage`` (staged → tick start), ``tick`` (the
#: device/host reconcile tick that carried the row), ``patch`` (tick end
#: → downstream write applied), ``downstream`` (downstream status churn
#: → re-staged), ``upstatus`` (status upsync to the upstream store),
#: ``observe`` (status committed → the driver observed it; derived).
PHASES = ("write", "propagate", "stage", "tick", "patch", "downstream",
          "upstatus", "observe")

_current: contextvars.ContextVar["TraceContext | None"] = \
    contextvars.ContextVar("kcp_trace_ctx", default=None)

# lazily-bound faults module: the sampling coin checks for an active
# injector on every draw, and a per-call `from .. import` statement is
# measurable on the request fast path
_faults = None


@dataclass(frozen=True)
class TraceContext:
    """One position in a trace: (trace id, span id, sampled)."""

    trace_id: str  # 32 hex chars
    span_id: str  # 16 hex chars
    sampled: bool

    def header(self) -> str:
        """The W3C ``traceparent`` header value."""
        return f"00-{self.trace_id}-{self.span_id}-" \
               f"{'01' if self.sampled else '00'}"


class _Noop:
    """Reusable no-op context manager: the unsampled-path cost of
    :func:`span` is one contextvar read and this singleton."""

    __slots__ = ()

    def __enter__(self) -> None:
        return None

    def __exit__(self, *exc: object) -> bool:
        return False


_NOOP = _Noop()


class Tracer:
    """Per-process trace state: sampling policy + the span ring buffer."""

    def __init__(self) -> None:
        self._lock = make_lock("obs.tracer")
        self.reconfigure()

    def reconfigure(self) -> None:
        """(Re-)read the KCP_TRACE* environment — called at import and by
        tests/benches that flip modes mid-process."""
        self.enabled = os.environ.get("KCP_TRACE", "1").lower() not in (
            "0", "false", "off")
        self.sample_n = max(1, int(os.environ.get("KCP_TRACE_SAMPLE", "64")))
        self.slo_s = float(os.environ.get("KCP_TRACE_SLO_MS", "200")) / 1000.0
        seed = os.environ.get("KCP_TRACE_SEED", "")
        self._rng = random.Random(int(seed)) if seed else random.Random()
        self.proc = os.environ.get("KCP_TRACE_PROC", f"pid{os.getpid()}")
        self._buf: deque[dict] = deque(
            maxlen=max(64, int(os.environ.get("KCP_TRACE_BUFFER", "4096"))))
        # object-identity links: id(snapshot) -> (snapshot, ctx, seq).
        # Entries hold a strong snapshot ref (presence implies identity,
        # the encode-cache discipline); bounded FIFO — the deque carries
        # (id, seq) and eviction only removes a map entry whose seq still
        # matches, so a re-linked id is never evicted by its stale slot.
        self._links: deque[tuple[int, int]] = deque()
        self._link_seq = 0
        self._link_map: dict[int, tuple[dict, TraceContext, int]] = {}
        self._recorded = REGISTRY.counter(
            "trace_spans_recorded_total",
            "spans recorded into the per-process trace ring buffer")

    # --------------------------------------------------------- contexts

    def head_sampled(self) -> bool:
        """The head sampling coin — drawn from the seeded RNG BEFORE any
        ids exist, so the unsampled fast path never pays for id minting
        (one RNG draw ≈ 0.3µs vs ~5µs of hex formatting). A fixed
        ``KCP_TRACE_SEED`` reproduces the decision sequence exactly;
        fault-injected runs (an active ``KCP_FAULTS`` schedule) always
        sample — a chaos run's whole point is explaining what the
        injected failure did."""
        if self.sample_n <= 1:
            return True
        global _faults
        if _faults is None:
            from .. import faults as _faults_mod

            _faults = _faults_mod
        if _faults._ACTIVE is not None:
            return True
        # getrandbits is a single C call (GIL-atomic): no lock needed
        return self._rng.getrandbits(30) % self.sample_n == 0

    def mint(self, sampled: bool | None = None) -> TraceContext | None:
        """A fresh root context (None when tracing is disabled)."""
        if not self.enabled:
            return None
        if sampled is None:
            sampled = self.head_sampled()
        rng = self._rng
        return TraceContext(f"{rng.getrandbits(128):032x}",
                            f"{rng.getrandbits(64):016x}", sampled)

    def child(self, ctx: TraceContext) -> TraceContext:
        """Same trace, fresh span id (the caller becomes the parent)."""
        return TraceContext(ctx.trace_id,
                            f"{self._rng.getrandbits(64):016x}",
                            ctx.sampled)

    def from_headers(self, headers: dict) -> TraceContext | None:
        """Parse an incoming ``traceparent`` header (None = absent or
        malformed or tracing disabled)."""
        if not self.enabled:
            return None
        tp = headers.get(TRACEPARENT)
        if not tp:
            return None
        parts = tp.split("-")
        if len(parts) != 4 or len(parts[1]) != 32 or len(parts[2]) != 16:
            return None
        try:
            sampled = bool(int(parts[3], 16) & 1)
            int(parts[1], 16), int(parts[2], 16)
        except ValueError:
            return None
        return TraceContext(parts[1], parts[2], sampled)

    # --------------------------------------------------------- recording

    def record(self, name: str, ctx: TraceContext, parent: str | None,
               t0: float, dur: float, attrs: dict | None = None,
               force: bool = False) -> None:
        """Append one finished span (no-op unless sampled or forced)."""
        if not self.enabled or not (ctx.sampled or force):
            return
        span = {
            "trace": ctx.trace_id, "span": ctx.span_id, "parent": parent,
            "name": name, "proc": self.proc,
            "t0": round(t0, 6), "dur": round(max(0.0, dur), 6),
        }
        if attrs:
            span["attrs"] = attrs
        with self._lock:
            self._buf.append(span)
        self._recorded.inc()

    # ----------------------------------------------------- object links

    def link_obj(self, obj: dict, ctx: TraceContext,
                 limit: int = 512) -> None:
        """Associate a stored snapshot with the trace that committed it
        (in-process informers resolve causality with one dict probe)."""
        with self._lock:
            oid = id(obj)
            self._link_seq += 1
            self._link_map[oid] = (obj, ctx, self._link_seq)
            self._links.append((oid, self._link_seq))
            while len(self._links) > limit:
                old, seq = self._links.popleft()
                ent = self._link_map.get(old)
                if ent is not None and ent[2] == seq:
                    del self._link_map[old]

    def obj_link(self, obj: dict | None) -> TraceContext | None:
        """The committing trace context of a snapshot, if linked."""
        if obj is None or not self._link_map:
            return None
        ent = self._link_map.get(id(obj))
        if ent is not None and ent[0] is obj:
            return ent[1]
        return None

    # ------------------------------------------------------------ query

    def spans(self) -> list[dict]:
        with self._lock:
            return list(self._buf)

    def get(self, trace_id: str) -> list[dict]:
        """Every buffered span of one trace, oldest first."""
        with self._lock:
            return [s for s in self._buf if s["trace"] == trace_id]

    def slowest(self, n: int = 3) -> list[dict]:
        """The ``n`` slowest buffered traces: grouped by trace id, ranked
        by wall extent (max span end - min span start)."""
        by_trace: dict[str, list[dict]] = {}
        with self._lock:
            for s in self._buf:
                by_trace.setdefault(s["trace"], []).append(s)
        ranked = []
        for tid, spans in by_trace.items():
            t0 = min(s["t0"] for s in spans)
            t1 = max(s["t0"] + s["dur"] for s in spans)
            ranked.append({"id": tid, "dur": round(t1 - t0, 6),
                           "spans": spans})
        ranked.sort(key=lambda t: -t["dur"])
        return ranked[:max(1, n)]


TRACER = Tracer()


# ---------------------------------------------------------------------------
# module-level helpers — the call-site API (and what the kcp-lint span-
# table checker reads: literal names in obs.span/obs.phase/obs.record_span
# calls must appear in docs/operations.md's trace-span table)
# ---------------------------------------------------------------------------


def current() -> TraceContext | None:
    return _current.get()


def set_current(ctx: TraceContext | None) -> contextvars.Token:
    return _current.set(ctx)


def reset_current(token: contextvars.Token) -> None:
    _current.reset(token)


@contextlib.contextmanager
def use(ctx: TraceContext | None) -> Iterator[TraceContext | None]:
    """Install ``ctx`` as the current trace context for a block."""
    token = _current.set(ctx)
    try:
        yield ctx
    finally:
        _current.reset(token)


class _Span:
    __slots__ = ("name", "ctx", "attrs", "t0", "_token", "sub")

    def __init__(self, name: str, ctx: TraceContext, attrs: dict):
        self.name = name
        self.ctx = ctx
        self.attrs = attrs

    def __enter__(self) -> TraceContext:
        self.sub = TRACER.child(self.ctx)
        self._token = _current.set(self.sub)
        self.t0 = time.time()
        return self.sub

    def __exit__(self, etype, exc, tb) -> bool:
        _current.reset(self._token)
        if etype is not None:
            self.attrs["error"] = repr(exc)[:160]
        TRACER.record(self.name, self.sub, self.ctx.span_id, self.t0,
                      time.time() - self.t0, self.attrs or None)
        return False


def span(name: str, **attrs: Any):
    """Time a block as a child span of the current context; near-free
    (:data:`_NOOP`) when untraced or unsampled."""
    ctx = _current.get()
    if ctx is None or not ctx.sampled:
        return _NOOP
    return _Span(name, ctx, attrs)


def record_span(name: str, ctx: TraceContext, parent: str | None,
                t0: float, dur: float, attrs: dict | None = None,
                force: bool = False) -> None:
    """Record an explicitly-timed span (the non-context-manager twin of
    :func:`span`, for sites that measure their own boundaries)."""
    TRACER.record(name, ctx, parent, t0, dur, attrs, force=force)


def phase(name: str, ctx: TraceContext | None, t0: float, t1: float,
          **attrs: Any) -> None:
    """One convergence phase: a ``convergence_<phase>_seconds``
    observation always, plus a ``conv.<name>`` span when sampled.
    Adjacent phases share boundary timestamps, so the per-phase sum
    telescopes to the end-to-end wall time."""
    dur = max(0.0, t1 - t0)
    REGISTRY.histogram(
        f"convergence_{name}_seconds",
        "one phase of the spec-to-status convergence timeline").observe(dur)
    if ctx is not None and ctx.sampled and TRACER.enabled:
        sub = TRACER.child(ctx)
        TRACER.record("conv." + name, sub, ctx.span_id, t0, dur,
                      attrs or None)


def write_ctx() -> TraceContext | None:
    """The current context if it is worth stamping onto a commit
    (sampled), else None — the store's one-attribute fast path."""
    ctx = _current.get()
    return ctx if ctx is not None and ctx.sampled else None


def link_obj(obj: dict, ctx: TraceContext) -> None:
    TRACER.link_obj(obj, ctx)


def obj_link(obj: dict | None) -> TraceContext | None:
    if not TRACER.enabled:
        return None
    return TRACER.obj_link(obj)


def ctx_from_wal(tc: Any) -> TraceContext | None:
    """Rebuild a context from a WAL record's ``tc`` field
    (``[trace_id, span_id]``); None-safe and shape-tolerant."""
    if (not isinstance(tc, (list, tuple)) or len(tc) != 2
            or not all(isinstance(x, str) for x in tc)):
        return None
    return TraceContext(tc[0], tc[1], True)


def conv_begin(obj: dict | None) -> TraceContext | None:
    """The context a syncer engine should attribute a staged row to: the
    committing write's own context when the snapshot is identity-linked
    (in-process informers), else a fresh root ONLY under always-on
    sampling (cross-process engines correlate fragments by rv — see
    :mod:`.assemble` — and minting per event at default sampling would
    put an RNG call on the event hot path for nothing)."""
    t = TRACER
    if not t.enabled:
        return None
    ctx = t.obj_link(obj) if t._link_map else None
    if ctx is not None:
        return ctx
    if t.sample_n <= 1:
        return t.mint(sampled=True)
    return None
