"""kcp_tpu.obs — fleet-wide distributed tracing (see obs/trace.py)."""

from .trace import (
    PHASES,
    TRACEPARENT,
    TRACER,
    TraceContext,
    conv_begin,
    ctx_from_wal,
    current,
    link_obj,
    obj_link,
    phase,
    record_span,
    reset_current,
    set_current,
    span,
    use,
    write_ctx,
)

__all__ = [
    "PHASES", "TRACEPARENT", "TRACER", "TraceContext", "conv_begin",
    "ctx_from_wal", "current", "link_obj", "obj_link", "phase",
    "record_span", "reset_current", "set_current", "span", "use",
    "write_ctx",
]
