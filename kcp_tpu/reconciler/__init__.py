from .controller import Controller
from .queue import WorkQueue

__all__ = ["Controller", "WorkQueue"]
