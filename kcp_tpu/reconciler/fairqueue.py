"""FairWorkQueue: the native per-tenant-fair queue behind the WorkQueue
interface.

Cross-tenant controllers (negotiation, cluster lifecycle, namespace
sweep) share one queue across every logical cluster; with plain FIFO a
tenant flooding events starves the rest. The native scheduler
(native/workqueue.cc) keeps the client-go contract — dedup while
pending, per-item exponential backoff, redo-after-done — and drains
round-robin across tenants, so each batch carries at most one item per
tenant per pass.

Drop-in for :class:`kcp_tpu.reconciler.queue.WorkQueue` (same methods,
same Controller/BatchController compatibility). ``tenant_of`` maps an
item to its tenant; the default treats tuple items' first element as
the tenant (the (cluster, name) key shape every controller here uses).
When the native library is unavailable, :func:`make_queue` falls back
to the plain WorkQueue — correctness intact, fairness best-effort.
"""

from __future__ import annotations

import asyncio
import ctypes
import time
from typing import Callable, Hashable

from .queue import WorkQueue, queue_metrics

Item = Hashable


def _default_tenant(item: Item) -> str:
    if isinstance(item, tuple) and item:
        return str(item[0])
    return ""


class FairWorkQueue:
    """WorkQueue-compatible wrapper over the native fair scheduler."""

    def __init__(self, name: str = "fairqueue",
                 tenant_of: Callable[[Item], str] = _default_tenant):
        from ..native import load

        lib = load()
        if lib is None:
            raise RuntimeError("native library unavailable")
        self._lib = lib
        self._declare(lib)
        self._q = lib.wq_new()
        self.name = name
        self.tenant_of = tenant_of
        self._ids: dict[Item, int] = {}
        self._items: dict[int, Item] = {}
        self._next_id = 1
        self._tenants: dict[str, int] = {}
        self._wakeup = asyncio.Event()
        self._shutdown = False
        # backpressure observables (see queue.queue_metrics): queue time
        # is measured from immediate adds only — delayed/rate-limited
        # requeues would fold their intentional backoff into the
        # histogram and hide real queueing
        self._depth_gauge, self._wait_hist = queue_metrics(name)
        self._enq_t: dict[int, float] = {}

    @staticmethod
    def _declare(lib) -> None:
        if getattr(lib, "_wq_declared", False):
            return
        u64p = ctypes.POINTER(ctypes.c_uint64)
        lib.wq_new.restype = ctypes.c_void_p
        lib.wq_free.argtypes = [ctypes.c_void_p]
        lib.wq_add.argtypes = [ctypes.c_void_p, ctypes.c_uint64, ctypes.c_uint32]
        lib.wq_add_after.argtypes = [ctypes.c_void_p, ctypes.c_uint64,
                                     ctypes.c_uint32, ctypes.c_double, ctypes.c_double]
        lib.wq_add_rate_limited.restype = ctypes.c_uint32
        lib.wq_add_rate_limited.argtypes = [ctypes.c_void_p, ctypes.c_uint64,
                                            ctypes.c_uint32, ctypes.c_double]
        lib.wq_num_requeues.restype = ctypes.c_uint32
        lib.wq_num_requeues.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
        lib.wq_forget.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
        lib.wq_promote.restype = ctypes.c_double
        lib.wq_promote.argtypes = [ctypes.c_void_p, ctypes.c_double]
        lib.wq_drain.restype = ctypes.c_uint32
        lib.wq_drain.argtypes = [ctypes.c_void_p, ctypes.c_double, u64p, ctypes.c_uint32]
        lib.wq_done.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
        lib.wq_len.restype = ctypes.c_uint64
        lib.wq_len.argtypes = [ctypes.c_void_p]
        lib.wq_live.restype = ctypes.c_int
        lib.wq_live.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
        lib.wq_release.restype = ctypes.c_int
        lib.wq_release.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
        u32p = ctypes.POINTER(ctypes.c_uint32)
        u8p = ctypes.POINTER(ctypes.c_uint8)
        lib.wq_add_many.argtypes = [ctypes.c_void_p, u64p, u32p, ctypes.c_uint32]
        lib.wq_complete_many.argtypes = [ctypes.c_void_p, u64p, u8p,
                                         ctypes.c_uint32, u8p]
        lib._wq_declared = True

    # ---------------------------------------------------------- id mapping

    def _id(self, item: Item) -> int:
        i = self._ids.get(item)
        if i is None:
            i = self._next_id
            self._next_id += 1
            self._ids[item] = i
            self._items[i] = item
        return i

    def _tenant(self, item: Item) -> int:
        t = self.tenant_of(item)
        tid = self._tenants.get(t)
        if tid is None:
            tid = len(self._tenants)
            self._tenants[t] = tid
        return tid

    # -------------------------------------------------------------- adding

    def add(self, item: Item) -> None:
        if self._shutdown:
            return
        i = self._id(item)
        self._lib.wq_add(self._q, i, self._tenant(item))
        self._enq_t.setdefault(i, time.monotonic())
        self._depth_gauge.set(self._lib.wq_len(self._q))
        self._wakeup.set()

    def add_many(self, items) -> None:
        """Batch add: one ctypes crossing + one wakeup for a whole
        churn/feedback batch (the round-4 profile's top host cost)."""
        if self._shutdown:
            return
        items = list(items)
        n = len(items)
        if not n:
            return
        ids = (ctypes.c_uint64 * n)()
        tenants = (ctypes.c_uint32 * n)()
        now = time.monotonic()  # one clock read for the whole batch
        enq = self._enq_t
        for j, item in enumerate(items):
            i = self._id(item)
            ids[j] = i
            tenants[j] = self._tenant(item)
            enq.setdefault(i, now)
        self._lib.wq_add_many(self._q, ids, tenants, n)
        self._depth_gauge.set(self._lib.wq_len(self._q))
        self._wakeup.set()

    def complete_many(self, items, forget_flags) -> None:
        """Batch forget+done for a processed tick batch; releases the id
        interning of every item that left the queue."""
        items = list(items)
        n = len(items)
        if not n:
            return
        ids = (ctypes.c_uint64 * n)()
        forgets = (ctypes.c_uint8 * n)()
        released = (ctypes.c_uint8 * n)()
        known: list[tuple[int, Item, int]] = []
        for item, fg in zip(items, forget_flags):
            i = self._ids.get(item)
            if i is None:
                continue
            j = len(known)
            ids[j] = i
            forgets[j] = 1 if fg else 0
            known.append((j, item, i))
        if not known:
            return
        self._lib.wq_complete_many(self._q, ids, forgets, len(known), released)
        for j, item, i in known:
            if released[j]:
                del self._ids[item]
                del self._items[i]
                self._enq_t.pop(i, None)
        # done() may have requeued redo items natively — wake any getter
        self._wakeup.set()

    def add_after(self, item: Item, delay: float) -> None:
        if self._shutdown:
            return
        self._lib.wq_add_after(self._q, self._id(item), self._tenant(item),
                               time.monotonic(), delay)
        self._wakeup.set()

    def add_rate_limited(self, item: Item) -> None:
        if self._shutdown:
            return
        self._lib.wq_add_rate_limited(self._q, self._id(item),
                                      self._tenant(item), time.monotonic())
        self._wakeup.set()

    def num_requeues(self, item: Item) -> int:
        i = self._ids.get(item)
        return self._lib.wq_num_requeues(self._q, i) if i is not None else 0

    def forget(self, item: Item) -> None:
        i = self._ids.get(item)
        if i is not None:
            self._lib.wq_forget(self._q, i)
            self._release(item, i)

    def _release(self, item: Item, i: int) -> None:
        """Drop the id interning once the queue no longer references the
        id anywhere — without this, high-churn keys leak the maps."""
        if self._lib.wq_release(self._q, i):
            del self._ids[item]
            del self._items[i]
            self._enq_t.pop(i, None)

    # ------------------------------------------------------------ consuming

    def _pop_ready(self, max_items: int) -> list[Item]:
        buf = (ctypes.c_uint64 * max_items)()
        now = time.monotonic()
        n = self._lib.wq_drain(self._q, now, buf, max_items)
        if n:
            enq = self._enq_t
            observe = self._wait_hist.observe
            for i in range(n):
                t = enq.pop(buf[i], None)
                if t is not None:
                    observe(now - t)
            self._depth_gauge.set(self._lib.wq_len(self._q))
        return [self._items[buf[i]] for i in range(n)]

    async def get(self) -> Item | None:
        while True:
            got = self._pop_ready(1)
            if got:
                return got[0]
            if self._shutdown:
                return None
            next_due = self._lib.wq_promote(self._q, time.monotonic())
            # promote may itself have moved a just-due item into the ready
            # ring; re-check before sleeping or that item is stranded until
            # the next add() (there may be no further delayed entries to
            # bound the wait)
            got = self._pop_ready(1)
            if got:
                return got[0]
            self._wakeup.clear()
            try:
                await asyncio.wait_for(
                    self._wakeup.wait(),
                    timeout=next_due if next_due >= 0 else None)
            except asyncio.TimeoutError:
                pass

    async def drain(self, max_items: int = 1024, max_wait: float = 0.005) -> list[Item]:
        first = await self.get()
        if first is None:
            return []
        batch = [first]
        deadline = time.monotonic() + max_wait
        while len(batch) < max_items:
            more = self._pop_ready(max_items - len(batch))
            if more:
                batch.extend(more)
                continue
            remaining = deadline - time.monotonic()
            if remaining <= 0 or self._shutdown:
                break
            self._wakeup.clear()
            try:
                await asyncio.wait_for(self._wakeup.wait(), timeout=remaining)
            except asyncio.TimeoutError:
                break
        return batch

    def done(self, item: Item) -> None:
        i = self._ids.get(item)
        if i is not None:
            self._lib.wq_done(self._q, i)
            # done() may have re-queued a redo item natively — wake any
            # getter so it is not stranded until the next add()
            self._wakeup.set()
            self._release(item, i)

    # ------------------------------------------------------------- control

    def shut_down(self) -> None:
        self._shutdown = True
        self._wakeup.set()

    def __len__(self) -> int:
        return self._lib.wq_len(self._q)

    @property
    def shutting_down(self) -> bool:
        return self._shutdown

    def __del__(self):
        try:
            if getattr(self, "_q", None):
                self._lib.wq_free(self._q)
                self._q = None
        except Exception:
            pass


def make_queue(name: str = "queue",
               tenant_of: Callable[[Item], str] = _default_tenant):
    """FairWorkQueue when the native library loads, else WorkQueue."""
    try:
        return FairWorkQueue(name, tenant_of)
    except Exception:
        return WorkQueue(name)
