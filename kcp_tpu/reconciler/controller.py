"""Controller runtime: the reconcile pattern with a swappable backend.

The reference replicates one pattern in every controller (SURVEY.md §1
layer 5): informer -> rate-limited workqueue -> N worker goroutines ->
``process(key)`` -> reconcile -> status write; 5 retries then drop;
RetryableError retries forever (pkg/reconciler/cluster/
controller.go:226-263).

This runtime keeps that contract but makes the execution model swappable:

- :class:`Controller` — item-at-a-time async workers (``Backend=host``),
  the differential-testing reference path
- :class:`BatchController` — a reconcile *tick*: drain the queue into a
  batch, hand the whole batch to ``process_batch`` (which typically
  encodes it and runs one jitted device program), apply the returned
  effects. One vmapped program across all logical clusters instead of a
  goroutine per key — the core of the north-star design (``Backend=tpu``).

Retry semantics are identical in both: items whose processing raised are
requeued rate-limited up to ``max_retries`` (then dropped), RetryableError
indefinitely.
"""

from __future__ import annotations

import asyncio
import logging
from typing import Awaitable, Callable, Iterable, Sequence

from ..utils.errors import is_retryable
from .fairqueue import make_queue
from .queue import Item, WorkQueue

log = logging.getLogger(__name__)

DEFAULT_RETRIES = 5

ProcessFn = Callable[[Item], Awaitable[None]]
# process_batch returns the items that FAILED (to be retried); everything
# else in the batch is considered reconciled.
ProcessBatchFn = Callable[[Sequence[Item]], Awaitable[Iterable[tuple[Item, Exception]]]]


class Controller:
    """Item-at-a-time controller (the host reference backend).

    The default queue is per-tenant fair (:func:`make_queue`: the native
    FairWorkQueue when the library loads, plain WorkQueue otherwise).
    ``tenant_of`` maps an item to its fairness key; the default uses a
    tuple item's first element — pass a custom extractor when the tenant
    sits deeper in the item shape.
    """

    def __init__(
        self,
        name: str,
        process: ProcessFn,
        queue: WorkQueue | None = None,
        max_retries: int = DEFAULT_RETRIES,
        tenant_of=None,
    ):
        self.name = name
        if queue is None:
            queue = make_queue(name, tenant_of) if tenant_of else make_queue(name)
        self.queue = queue
        self.process = process
        self.max_retries = max_retries
        self._workers: list[asyncio.Task] = []

    def enqueue(self, item: Item) -> None:
        self.queue.add(item)

    def enqueue_many(self, items) -> None:
        """Batch enqueue (one queue crossing; see WorkQueue.add_many)."""
        self.queue.add_many(items)

    def enqueue_after(self, item: Item, delay: float) -> None:
        self.queue.add_after(item, delay)

    async def start(self, num_workers: int = 2) -> None:
        """Spawn ``num_workers`` worker tasks (reference default 2,
        pkg/server/server.go:241,250)."""
        for i in range(num_workers):
            self._workers.append(asyncio.create_task(self._worker(i)))

    async def _worker(self, i: int) -> None:
        while True:
            item = await self.queue.get()
            if item is None:
                return
            try:
                await self.process(item)
            except Exception as err:  # noqa: BLE001 — reconcile errors are data
                self._handle_error(item, err)
            else:
                self.queue.forget(item)
            finally:
                self.queue.done(item)

    def _handle_error(self, item: Item, err: Exception) -> None:
        if is_retryable(err):
            log.info("%s: retryable error on %r: %s", self.name, item, err)
            self.queue.add_rate_limited(item)
            return
        if self.queue.num_requeues(item) < self.max_retries:
            log.info("%s: error on %r (retry %d): %s", self.name, item,
                     self.queue.num_requeues(item), err)
            self.queue.add_rate_limited(item)
            return
        log.warning("%s: dropping %r after %d retries: %s", self.name, item,
                    self.max_retries, err)
        self.queue.forget(item)

    async def stop(self) -> None:
        self.queue.shut_down()
        if self._workers:
            await asyncio.gather(*self._workers, return_exceptions=True)
        self._workers.clear()


class BatchController(Controller):
    """Tick-based controller: drain -> one batched reconcile -> apply.

    ``process_batch`` receives the deduped drained items and returns the
    (item, error) pairs that failed; those are retried under the same
    policy as :class:`Controller`. A single worker loop is enough — the
    parallelism lives inside the batch program, not in the scheduler.

    ``overlap_drain=True`` pipelines the drain stage: the NEXT tick's
    queue drain (including its ``batch_window`` micro-batching wait)
    runs concurrently with the CURRENT tick's processing, so a tick that
    dispatches a device step and applies a previous step's patches never
    serializes with event accumulation. Safe because drained items sit
    in the queue's ``_processing`` set until ``complete_many`` — a
    concurrent drain can never hand out an item the in-flight tick still
    owns (re-adds park in ``_redo`` exactly as without overlap).
    """

    def __init__(
        self,
        name: str,
        process_batch: ProcessBatchFn,
        queue: WorkQueue | None = None,
        max_retries: int = DEFAULT_RETRIES,
        max_batch: int = 4096,
        batch_window: float = 0.005,
        tenant_of=None,
        overlap_drain: bool = False,
    ):
        async def _unused(_: Item) -> None:  # pragma: no cover
            raise NotImplementedError

        super().__init__(name, _unused, queue, max_retries, tenant_of=tenant_of)
        self.process_batch = process_batch
        self.max_batch = max_batch
        self.batch_window = batch_window
        self.overlap_drain = overlap_drain
        self.ticks = 0
        self.items_processed = 0

    async def start(self, num_workers: int = 1) -> None:
        # one tick loop; num_workers kept for interface parity
        self._workers.append(asyncio.create_task(self._tick_loop()))

    async def _tick_loop(self) -> None:
        next_drain: asyncio.Task | None = None
        while True:
            if next_drain is not None:
                batch = await next_drain
                next_drain = None
            else:
                batch = await self.queue.drain(self.max_batch, self.batch_window)
            if not batch:
                if self.queue.shutting_down:
                    return
                continue
            if self.overlap_drain and not self.queue.shutting_down:
                # start draining the next batch NOW: its micro-batch
                # window elapses while this tick encodes/dispatches
                next_drain = asyncio.create_task(
                    self.queue.drain(self.max_batch, self.batch_window))
            self.ticks += 1
            self.items_processed += len(batch)
            try:
                failed = list(await self.process_batch(batch))
            except Exception as err:  # noqa: BLE001 — whole-batch failure
                log.exception("%s: batch tick failed", self.name)
                failed = [(item, err) for item in batch]
            failed_items = set()
            for item, err in failed:
                failed_items.add(item)
                self._handle_error(item, err)
            # one queue crossing for the whole batch (forget successes,
            # done everything) — the per-item form cost ~30% of the
            # serving loop's wall time at bench scale
            self.queue.complete_many(
                batch, [item not in failed_items for item in batch])
