"""Rate-limited work queue with batch drain.

Behavioral parity with client-go's workqueue as the reference uses it
(dedup while pending, per-item exponential backoff, 5 retries then drop —
pkg/syncer/syncer.go:272-291, pkg/reconciler/cluster/controller.go:243-263)
plus the one capability the TPU backend needs that client-go never had:
:meth:`drain` — collect up to N ready items in one await, so a reconcile
tick can process a whole batch in a single vectorized step instead of one
goroutine wakeup per key.
"""

from __future__ import annotations

import asyncio
import heapq
import re
import time
from collections import deque
from typing import Hashable

from ..utils.trace import REGISTRY

Item = Hashable

BASE_DELAY = 0.005  # client-go default rate limiter: 5ms * 2^n, capped
MAX_DELAY = 1000.0


def queue_metrics(name: str):
    """(depth gauge, queue-seconds histogram) for a named workqueue —
    the backpressure observables (client-go's workqueue_depth /
    workqueue_queue_duration_seconds analogs): operators watch depth
    climb and queue time stretch to see admission throttling propagate
    into the controllers. Shared by WorkQueue and FairWorkQueue."""
    suffix = re.sub(r"[^A-Za-z0-9_]", "_", name)
    depth = REGISTRY.gauge(
        f"workqueue_depth_{suffix}",
        f"items ready or delayed in the {name} workqueue")
    wait = REGISTRY.histogram(
        "workqueue_queue_seconds",
        "time items spent queued before a worker picked them up")
    return depth, wait


class WorkQueue:
    def __init__(self, name: str = "queue"):
        self.name = name
        self._ready: deque[Item] = deque()
        self._pending: set[Item] = set()  # dedup: queued or scheduled
        self._processing: set[Item] = set()
        self._redo: set[Item] = set()  # re-added while processing
        self._delayed: list[tuple[float, int, Item]] = []  # heap
        self._seq = 0
        self._retries: dict[Item, int] = {}
        self._wakeup: asyncio.Event = asyncio.Event()
        self._shutdown = False
        self._depth_gauge, self._wait_hist = queue_metrics(name)
        self._enq_t: dict[Item, float] = {}

    # ------------------------------------------------------------ adding

    def add(self, item: Item) -> None:
        if self._shutdown:
            return
        if item in self._processing:
            self._redo.add(item)
            return
        if item in self._pending:
            return
        self._pending.add(item)
        self._ready.append(item)
        self._enq_t.setdefault(item, time.monotonic())
        self._depth_gauge.set(len(self))
        self._wakeup.set()

    def add_after(self, item: Item, delay: float) -> None:
        if self._shutdown:
            return
        if delay <= 0:
            self.add(item)
            return
        if item in self._pending and item not in self._processing:
            return
        self._seq += 1
        heapq.heappush(self._delayed, (time.monotonic() + delay, self._seq, item))
        self._depth_gauge.set(len(self))
        self._wakeup.set()

    def add_rate_limited(self, item: Item) -> None:
        """Requeue with exponential per-item backoff (5ms * 2^n, capped)."""
        n = self._retries.get(item, 0)
        self._retries[item] = n + 1
        self.add_after(item, min(BASE_DELAY * (2**n), MAX_DELAY))

    def num_requeues(self, item: Item) -> int:
        return self._retries.get(item, 0)

    def forget(self, item: Item) -> None:
        self._retries.pop(item, None)

    # ------------------------------------------------------- batch forms
    # (the native FairWorkQueue crosses ctypes once per batch; these
    # fallback loops keep the interface identical)

    def add_many(self, items) -> None:
        for item in items:
            self.add(item)

    def complete_many(self, items, forget_flags) -> None:
        """forget (where flagged) + done for a processed tick batch."""
        for item, fg in zip(items, forget_flags):
            if fg:
                self.forget(item)
            self.done(item)

    # ---------------------------------------------------------- consuming

    def _promote_delayed(self) -> float | None:
        """Move due delayed items to ready; return seconds until next due."""
        now = time.monotonic()
        while self._delayed and self._delayed[0][0] <= now:
            _, _, item = heapq.heappop(self._delayed)
            if item in self._processing:
                self._redo.add(item)
            elif item not in self._pending:
                self._pending.add(item)
                self._ready.append(item)
                self._enq_t.setdefault(item, now)
        if self._delayed:
            return max(0.0, self._delayed[0][0] - now)
        return None

    def _took(self, item: Item, now: float) -> None:
        t = self._enq_t.pop(item, None)
        if t is not None:
            self._wait_hist.observe(now - t)

    async def get(self) -> Item | None:
        """Next item, or None on shutdown. Caller must call done(item)."""
        while True:
            next_due = self._promote_delayed()
            if self._ready:
                item = self._ready.popleft()
                self._pending.discard(item)
                self._processing.add(item)
                self._took(item, time.monotonic())
                self._depth_gauge.set(len(self))
                return item
            if self._shutdown:
                return None
            self._wakeup.clear()
            try:
                await asyncio.wait_for(
                    self._wakeup.wait(), timeout=next_due if next_due is not None else None
                )
            except asyncio.TimeoutError:
                pass

    async def drain(self, max_items: int = 1024, max_wait: float = 0.005) -> list[Item]:
        """Batch get: await the first ready item, then keep collecting until
        the queue momentarily empties or ``max_items`` is hit.

        ``max_wait`` is the micro-batching window — the latency/batch-size
        dial for p99 convergence (SURVEY.md §7.3).
        """
        first = await self.get()
        if first is None:
            return []
        batch = [first]
        deadline = time.monotonic() + max_wait
        while len(batch) < max_items:
            self._promote_delayed()
            if self._ready:
                item = self._ready.popleft()
                self._pending.discard(item)
                self._processing.add(item)
                self._took(item, time.monotonic())
                batch.append(item)
                continue
            remaining = deadline - time.monotonic()
            if remaining <= 0 or self._shutdown:
                break
            self._wakeup.clear()
            try:
                await asyncio.wait_for(self._wakeup.wait(), timeout=remaining)
            except asyncio.TimeoutError:
                break
        self._depth_gauge.set(len(self))
        return batch

    def done(self, item: Item) -> None:
        self._processing.discard(item)
        if item in self._redo:
            self._redo.discard(item)
            self.add(item)

    # ----------------------------------------------------------- control

    def shut_down(self) -> None:
        self._shutdown = True
        self._wakeup.set()

    def __len__(self) -> int:
        return len(self._ready) + len(self._delayed)

    @property
    def shutting_down(self) -> bool:
        return self._shutdown
