"""Observability: metrics registry, timing spans, device profiling.

The reference has no first-party tracing — its forked apiserver serves
standard ``/metrics`` and ``/debug/pprof`` endpoints that nothing in the
repo touches (SURVEY.md §5). For a TPU control plane that is not enough:
the interesting time is split between host orchestration (asyncio
controllers, encode, apply) and device ticks (jit dispatch, transfer,
kernel time), so this module provides

- a process-global :class:`Registry` of counters / gauges / histograms
  with Prometheus-style text exposition (served at ``/metrics`` by the
  API server),
- :func:`span` — a context manager timing a named section into a
  histogram (host-side structured timing),
- :func:`device_trace` — a context manager around
  ``jax.profiler.trace`` emitting an XLA trace directory for
  TensorBoard/xprof when deeper device attribution is needed.

Everything is dependency-free and safe to call on hot paths: a span is
two ``perf_counter`` calls and a dict update.
"""

from __future__ import annotations

import contextlib
import threading
import time
from bisect import bisect_left

from ..analysis.sanitize import make_lock
from dataclasses import dataclass, field

_DEFAULT_BUCKETS = (
    0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
    0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
)

# small-integer occupancy histograms (e.g. the fused tick pipeline's
# in-flight depth, ``fused_pipeline_depth``): the time-shaped default
# edges would fold every observation into one bucket
DEPTH_BUCKETS = (0.0, 1.0, 2.0, 3.0, 4.0, 8.0)

# count-shaped histograms (batch sizes, e.g. ``watch_fanout_batch_size``):
# powers of two up to the store's emit-batch ceiling
SIZE_BUCKETS = (1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0, 128.0, 256.0, 512.0)


@dataclass
class Counter:
    name: str
    help: str = ""
    value: float = 0.0
    # REST clients, the store-I/O pool, and the router's scatter executor
    # all inc() off the serving loop: `self.value += amount` is a
    # read-add-store that can drop increments under thread interleaving.
    # A plain leaf lock (never held while acquiring anything else) keeps
    # the hot path one uncontended acquire.
    _lock: threading.Lock = field(default_factory=threading.Lock,
                                  repr=False, compare=False)

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self.value += amount


@dataclass
class Gauge:
    name: str
    help: str = ""
    value: float = 0.0

    def set(self, value: float) -> None:
        self.value = value  # single store: atomic under the GIL


@dataclass
class Histogram:
    name: str
    help: str = ""
    buckets: tuple = _DEFAULT_BUCKETS
    counts: list = field(default_factory=list)
    total: float = 0.0
    n: int = 0
    _lock: threading.Lock = field(default_factory=threading.Lock,
                                  repr=False, compare=False)

    def __post_init__(self):
        if not self.counts:
            self.counts = [0] * (len(self.buckets) + 1)

    def observe(self, value: float) -> None:
        # bisect_left: an observation equal to a bucket edge belongs in
        # that bucket (Prometheus's inclusive `le` semantics). The lock
        # makes the three mutations one transaction — observe() runs on
        # executor threads too, and a torn counts/total/n triple yields
        # impossible exposition (count < bucket cum sums).
        i = bisect_left(self.buckets, value)
        with self._lock:
            self.counts[i] += 1
            self.total += value
            self.n += 1

    @property
    def mean(self) -> float:
        return self.total / self.n if self.n else 0.0

    def quantile(self, q: float) -> float:
        """Approximate quantile from bucket boundaries (upper edge)."""
        if not self.n:
            return 0.0
        target = q * self.n
        seen = 0
        for i, c in enumerate(self.counts):
            seen += c
            if seen >= target:
                return self.buckets[i] if i < len(self.buckets) else float("inf")
        return float("inf")


class Registry:
    """Named metrics with Prometheus text exposition."""

    def __init__(self):
        self._lock = make_lock("trace.registry")
        self._metrics: dict[str, Counter | Gauge | Histogram] = {}

    def counter(self, name: str, help: str = "") -> Counter:
        return self._get_or_make(name, lambda: Counter(name, help))

    def gauge(self, name: str, help: str = "") -> Gauge:
        return self._get_or_make(name, lambda: Gauge(name, help))

    def histogram(self, name: str, help: str = "", buckets: tuple = _DEFAULT_BUCKETS) -> Histogram:
        return self._get_or_make(name, lambda: Histogram(name, help, buckets))

    def _get_or_make(self, name, factory):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = self._metrics[name] = factory()
            return m

    @staticmethod
    def _escape_help(text: str) -> str:
        """Prometheus text-format HELP escaping: backslash and newline
        are the two characters the exposition grammar reserves — an
        unescaped newline in help text splits the line and corrupts
        every scrape of the whole page."""
        return text.replace("\\", "\\\\").replace("\n", "\\n")

    def expose(self) -> str:
        """Prometheus text format (the /metrics body)."""
        out: list[str] = []
        with self._lock:
            for name in sorted(self._metrics):
                m = self._metrics[name]
                if m.help:
                    out.append(f"# HELP {name} {self._escape_help(m.help)}")
                if isinstance(m, Counter):
                    out.append(f"# TYPE {name} counter")
                    out.append(f"{name} {m.value}")
                elif isinstance(m, Gauge):
                    out.append(f"# TYPE {name} gauge")
                    out.append(f"{name} {m.value}")
                else:
                    out.append(f"# TYPE {name} histogram")
                    with m._lock:
                        counts, total, n = list(m.counts), m.total, m.n
                    cum = 0
                    for edge, c in zip(m.buckets, counts):
                        cum += c
                        out.append(f'{name}_bucket{{le="{edge}"}} {cum}')
                    out.append(f'{name}_bucket{{le="+Inf"}} {n}')
                    out.append(f"{name}_sum {total}")
                    out.append(f"{name}_count {n}")
        return "\n".join(out) + "\n"

    def snapshot(self) -> dict:
        """Structured dump for tests/logging."""
        with self._lock:
            out = {}
            for name, m in self._metrics.items():
                if isinstance(m, (Counter, Gauge)):
                    out[name] = m.value
                else:
                    out[name] = {"count": m.n, "mean": m.mean,
                                 "p50": m.quantile(0.5), "p99": m.quantile(0.99)}
            return out


REGISTRY = Registry()


@contextlib.contextmanager
def span(name: str, registry: Registry = REGISTRY):
    """Time a section into histogram ``<name>_seconds``."""
    h = registry.histogram(f"{name}_seconds")
    t0 = time.perf_counter()
    try:
        yield
    finally:
        h.observe(time.perf_counter() - t0)


@contextlib.contextmanager
def device_trace(log_dir: str):
    """XLA/TPU profiler trace around a block (view with xprof/TensorBoard).

    No-ops cleanly if the profiler cannot start (e.g. another trace is
    active or the backend does not support it).
    """
    import jax

    started = False
    try:
        jax.profiler.start_trace(log_dir)
        started = True
    except Exception:
        pass
    try:
        yield started
    finally:
        if started:
            try:
                jax.profiler.stop_trace()
            except Exception:
                pass


# ---------------------------------------------------------------------------
# Host profiling (the reference inherits /debug/pprof from its generic
# apiserver chain, pkg/server/server.go:145; this is the asyncio-native
# analog): a sampling wall profiler over every thread's stack plus an
# asyncio task dump, served at /debug/profile by the REST handler.
# ---------------------------------------------------------------------------


def dump_tasks() -> list[dict]:
    """All live asyncio tasks of the running loop with their current
    coroutine stacks — who is waiting where."""
    import asyncio

    try:
        loop = asyncio.get_running_loop()
    except RuntimeError:
        return []
    out = []
    for t in asyncio.all_tasks(loop):
        frames = []
        for f in t.get_stack(limit=8):
            frames.append(f"{f.f_code.co_filename.rsplit('/', 1)[-1]}:"
                          f"{f.f_lineno} {f.f_code.co_name}")
        out.append({
            "name": t.get_name(),
            "coro": getattr(t.get_coro(), "__qualname__", str(t.get_coro())),
            "done": t.done(),
            "stack": frames,
        })
    return sorted(out, key=lambda d: d["name"])


def _sample_once(agg: dict, skip_thread: int) -> None:
    import sys

    for tid, frame in sys._current_frames().items():
        if tid == skip_thread:
            continue
        stack = []
        f = frame
        depth = 0
        while f is not None and depth < 24:
            stack.append(f"{f.f_code.co_filename.rsplit('/', 1)[-1]}:"
                         f"{f.f_lineno} {f.f_code.co_name}")
            f = f.f_back
            depth += 1
        key = (tid, tuple(stack))
        agg[key] = agg.get(key, 0) + 1


async def sample_profile(seconds: float = 2.0, hz: float = 97.0) -> dict:
    """Statistical wall profile: a sampler thread walks every thread's
    stack at ~hz for ``seconds`` while the loop keeps serving. Returns
    aggregated stacks with sample counts (top 20), plus the asyncio task
    dump and the span/metric snapshot — everything needed to answer
    "where does tick time go" without stopping the server."""
    import asyncio
    import threading

    seconds = max(0.1, min(float(seconds), 10.0))
    agg: dict = {}
    done = threading.Event()

    def run() -> None:
        me = threading.get_ident()
        interval = 1.0 / hz
        end = time.perf_counter() + seconds
        while time.perf_counter() < end:
            _sample_once(agg, me)
            time.sleep(interval)
        done.set()

    t = threading.Thread(target=run, name="kcp-profiler", daemon=True)
    tasks_before = dump_tasks()
    t.start()
    while not done.is_set():
        await asyncio.sleep(0.02)

    names = {th.ident: th.name for th in threading.enumerate()}
    total = sum(agg.values()) or 1
    stacks = sorted(agg.items(), key=lambda kv: -kv[1])[:20]
    return {
        "seconds": seconds,
        "samples": total,
        "stacks": [
            {
                "thread": names.get(tid, str(tid)),
                "count": n,
                "pct": round(100.0 * n / total, 1),
                "stack": list(stack),
            }
            for (tid, stack), n in stacks
        ],
        "tasks": tasks_before,
        "spans": REGISTRY.snapshot(),
    }
