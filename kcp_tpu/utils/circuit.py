"""Circuit breaker for remote I/O (RestClient, remote-store frontends).

A dead peer must fail FAST: without a breaker, every verb against an
unreachable backend eats a full connect timeout (30 s here) on the
store-I/O executor — a handful of stuck requests and the serving loop's
thread pool is gone. The breaker trips after ``failure_threshold``
consecutive transport failures; while OPEN every call fails immediately
with :class:`~kcp_tpu.utils.errors.UnavailableError`; after a jittered
exponential backoff one HALF_OPEN probe is let through — success closes
the circuit, failure re-opens it with a doubled (capped) interval.

Only *transport* failures count (connection refused/reset, timeouts):
an HTTP error status is the peer answering, which is the opposite of
dead. Jitter comes from a per-breaker seeded PRNG so fault-injection
schedules stay replayable (KCP_FAULTS contract, kcp_tpu/faults.py).

State is exported on the metrics registry: ``circuit_state`` (0 closed /
1 open / 2 half-open; per-breaker gauges carry a sanitized name suffix),
``circuit_open_total`` and ``circuit_fastfail_total``.
"""

from __future__ import annotations

import logging
import random
import re
import threading
import time

from ..analysis.sanitize import make_lock

from .errors import UnavailableError
from .trace import REGISTRY

log = logging.getLogger(__name__)

CLOSED, OPEN, HALF_OPEN = 0, 1, 2
_STATE_NAMES = {CLOSED: "closed", OPEN: "open", HALF_OPEN: "half-open"}


def _metric_suffix(name: str) -> str:
    return re.sub(r"[^A-Za-z0-9_]", "_", name)


class CircuitBreaker:
    """Thread-safe three-state breaker around one remote peer."""

    def __init__(self, name: str, failure_threshold: int = 5,
                 reset_timeout: float = 0.5, max_timeout: float = 30.0,
                 jitter: float = 0.2, clock=time.monotonic,
                 seed: int | str | None = None):
        self.name = name
        self.failure_threshold = failure_threshold
        self.reset_timeout = reset_timeout
        self.max_timeout = max_timeout
        self.jitter = jitter
        self._clock = clock
        self._rng = random.Random(seed if seed is not None else name)
        self._lock = make_lock("circuit.breaker")
        self._state = CLOSED
        self._failures = 0
        self._backoff = reset_timeout
        self._probe_at = 0.0
        self._set_gauges()

    # ------------------------------------------------------------- state

    @property
    def state(self) -> int:
        with self._lock:
            return self._state

    def _set_gauges(self) -> None:
        REGISTRY.gauge(
            "circuit_state",
            "most recent breaker transition: 0 closed, 1 open, 2 half-open",
        ).set(self._state)
        REGISTRY.gauge(
            f"circuit_state_{_metric_suffix(self.name)}",
            f"breaker state for {self.name}: 0 closed, 1 open, 2 half-open",
        ).set(self._state)

    def _transition(self, state: int) -> None:
        if state != self._state:
            log.info("circuit %s: %s -> %s", self.name,
                     _STATE_NAMES[self._state], _STATE_NAMES[state])
        self._state = state
        self._set_gauges()

    # ------------------------------------------------------------- calls

    def allow(self) -> bool:
        """True if a call may proceed. An OPEN breaker past its backoff
        deadline admits exactly one HALF_OPEN probe; everything else
        while not CLOSED is refused."""
        with self._lock:
            if self._state == CLOSED:
                return True
            if self._state == OPEN and self._clock() >= self._probe_at:
                self._transition(HALF_OPEN)
                return True
            return False

    def check(self) -> None:
        """:meth:`allow` or raise UnavailableError (the fail-fast path)."""
        if not self.allow():
            REGISTRY.counter(
                "circuit_fastfail_total",
                "calls refused immediately by an open circuit breaker").inc()
            raise UnavailableError(
                f"circuit breaker open for {self.name} "
                f"(retry in <= {self._backoff:.2f}s)")

    def record_success(self) -> None:
        with self._lock:
            self._failures = 0
            self._backoff = self.reset_timeout
            if self._state != CLOSED:
                self._transition(CLOSED)

    def record_failure(self) -> None:
        with self._lock:
            self._failures += 1
            if self._state == HALF_OPEN:
                # failed probe: re-open with doubled, capped backoff
                self._backoff = min(self._backoff * 2, self.max_timeout)
                self._open()
            elif (self._state == CLOSED
                  and self._failures >= self.failure_threshold):
                self._backoff = self.reset_timeout
                self._open()

    def _open(self) -> None:
        delay = self._backoff * (1.0 + self.jitter * self._rng.random())
        self._probe_at = self._clock() + delay
        self._transition(OPEN)
        REGISTRY.counter(
            "circuit_open_total",
            "breaker trips (closed/half-open -> open)").inc()
        log.warning("circuit %s: OPEN for %.2fs after %d consecutive "
                    "failures", self.name, delay, self._failures)
