"""Multi-cluster write routing: the one copy of the wildcard rule.

Fork semantics (reference call site: clientutils.EnableMultiCluster,
pkg/server/server.go:230): a write issued against the wildcard cluster is
routed to the logical cluster named in ``metadata.clusterName``; a write
without that routing information is an error.
"""

from __future__ import annotations

from .errors import ApiError, InvalidError

WILDCARD = "*"


def resolve_write_cluster(cluster: str, obj: dict,
                          exc: type[ApiError] = InvalidError) -> str:
    if cluster != WILDCARD:
        return cluster
    target = (obj.get("metadata") or {}).get("clusterName")
    if not target:
        raise exc("wildcard client write requires metadata.clusterName routing")
    return target
