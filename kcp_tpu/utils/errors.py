"""Error taxonomy for the control plane.

Mirrors the behavioral contract of the reference's apimachinery errors plus
its retryable-error marker (reference: pkg/util/errors/retryable.go:3-19):
a ``RetryableError`` is retried without counting against the bounded retry
budget; everything else gets the workqueue's 5 rate-limited retries.
"""

from __future__ import annotations


class ApiError(Exception):
    """Base class for API-surface errors, carrying an HTTP-ish status code."""

    code = 500
    reason = "InternalError"

    def __init__(self, message: str = ""):
        super().__init__(message or self.reason)
        self.message = message or self.reason


class NotFoundError(ApiError):
    code = 404
    reason = "NotFound"


class AlreadyExistsError(ApiError):
    code = 409
    reason = "AlreadyExists"


class ConflictError(ApiError):
    """Optimistic-concurrency failure (stale resourceVersion)."""

    code = 409
    reason = "Conflict"


class GoneError(ConflictError):
    """410 Gone: the requested watch/list window has expired server-side
    (the apiserver's "too old resource version"). Subclasses
    :class:`ConflictError` so existing expired-window handling (which
    predates the dedicated type) keeps catching it; consumers that can
    react smarter — informers, the shard router's catchup path — match
    this type and re-list *immediately* instead of backoff-retrying a
    watch that can never be served."""

    code = 410
    reason = "Expired"


class InvalidError(ApiError):
    code = 422
    reason = "Invalid"


class BadRequestError(ApiError):
    code = 400
    reason = "BadRequest"


class ForbiddenError(ApiError):
    """Policy denial (quota exceeded, RBAC): the request is understood
    and well-formed but refused — retrying unchanged cannot succeed."""

    code = 403
    reason = "Forbidden"


class TooManyRequestsError(ApiError):
    """Flow-control rejection (429): the server is shedding load for this
    flow. ``retry_after`` carries the server's pacing hint in seconds —
    clients sleep (jittered, capped) instead of hammering an overloaded
    frontend; the REST surface mirrors it as a ``Retry-After`` header and
    a ``details.retryAfterSeconds`` Status field."""

    code = 429
    reason = "TooManyRequests"
    retry_after = 1.0


class UnavailableError(ApiError):
    """A dependency is (temporarily) unreachable or refusing service:
    injected 5xx faults, circuit-broken remote I/O, dead store backends.

    Deliberately NOT a RetryableError: callers get the bounded workqueue
    retry budget, and a new informer event resets it — an unavailable
    dependency must degrade, not spin."""

    code = 503
    reason = "ServiceUnavailable"


class FrontierTimeoutError(UnavailableError):
    """A consistent (RV-barrier) read timed out waiting for the replica
    to apply the required RV (KEP-2340 analog). 504 rather than 503: the
    replica is healthy but behind — the read itself, not the server, hit
    its freshness deadline. Subclasses :class:`UnavailableError` so
    generic 5xx handling (router fallback, smart-client re-route, writer
    backoff) keeps working; the router matches this type/status to fall
    back to the primary and meter the reason."""

    code = 504
    reason = "FrontierWaitTimeout"


class RetryableError(Exception):
    """Marker wrapper: retry the operation without a bounded retry budget.

    Reference behavior: pkg/util/errors/retryable.go defines NewRetryableError/
    IsRetryable; the syncer wraps not-yet-ready discovery in it
    (pkg/syncer/syncer.go:119-122, 152-163) and controller error handlers
    requeue such errors forever (pkg/reconciler/cluster/controller.go:253).
    """

    def __init__(self, cause: Exception | str):
        super().__init__(str(cause))
        self.cause = cause


def is_retryable(err: BaseException) -> bool:
    return isinstance(err, RetryableError)


def is_not_found(err: BaseException) -> bool:
    return isinstance(err, NotFoundError)


def is_conflict(err: BaseException) -> bool:
    return isinstance(err, ConflictError)


def is_already_exists(err: BaseException) -> bool:
    return isinstance(err, AlreadyExistsError)


def is_too_many_requests(err: BaseException) -> bool:
    return isinstance(err, TooManyRequestsError)


def is_gone(err: BaseException) -> bool:
    """True for an expired watch/list window (410): re-list now."""
    return isinstance(err, GoneError)


def retry_after_hint(err: BaseException) -> float | None:
    """The server's Retry-After pacing hint in seconds, if the error
    carries one (429 flow-control rejections do)."""
    ra = getattr(err, "retry_after", None)
    try:
        return float(ra) if ra is not None else None
    except (TypeError, ValueError):
        return None
