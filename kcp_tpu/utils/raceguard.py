"""Race detection for the asyncio control plane — the `go test -race` analog.

The reference's CI runs every test under Go's race detector
(.github/workflows/ci.yaml:64, SURVEY.md §5); its controllers are
goroutine soups where unsynchronized access is the failure mode. This
framework's concurrency model is different — ONE asyncio loop owns all
mutable control-plane state (store, informer caches, fused buckets), and
threads exist only at the edges (ServerThread embedding, applier
handoffs, the profiler) — so the race class to detect is exactly one:
**state touched from a thread that does not own it**. That is also
precisely what Go's detector catches: cross-goroutine unsynchronized
access.

Two tools:

- :class:`AffinityGuard` — objects register their owning thread at
  creation; ``check()`` asserts the caller is that thread. Zero-cost
  when disabled (``enabled()`` is False unless KCP_RACE=1); under
  KCP_RACE=1 every store mutation is affinity-checked, so the whole test
  suite runs race-checked the way `go test -race ./...` does.
- :class:`LoopWatchdog` — a sampling thread that measures event-loop
  callback latency; a loop stalled past the threshold is the asyncio
  analog of a blocked scheduler (a reconcile doing synchronous I/O on
  the tick loop), reported with the stacks captured by the profiler
  machinery (utils/trace.py).
"""

from __future__ import annotations

import asyncio
import logging
import os
import threading
import time

log = logging.getLogger(__name__)

_ENV = "KCP_RACE"


def enabled() -> bool:
    return os.environ.get(_ENV, "") == "1"


class RaceError(AssertionError):
    """Unsynchronized cross-thread access to loop-owned state."""


class AffinityGuard:
    """Thread-affinity assertion for loop-owned state.

    The owner is (re)bound lazily: the first checked access from a
    thread CLAIMS the object if it is unowned — objects built on a main
    thread and then handed to a server loop re-home on first use there
    (``rebind()`` makes the handoff explicit). After that, access from
    any other thread raises :class:`RaceError` naming both threads.
    """

    __slots__ = ("name", "_owner", "_owner_name")

    def __init__(self, name: str):
        self.name = name
        self._owner: int | None = None
        self._owner_name = ""

    def rebind(self) -> None:
        """Explicitly hand ownership to the current thread (the embedding
        seam: ServerThread moving a store into its loop)."""
        t = threading.current_thread()
        self._owner, self._owner_name = t.ident, t.name

    def check(self) -> None:
        if not enabled():
            return
        t = threading.current_thread()
        if self._owner is None:
            self._owner, self._owner_name = t.ident, t.name
            return
        if t.ident != self._owner:
            raise RaceError(
                f"race detected: {self.name} is owned by thread "
                f"{self._owner_name!r} but was mutated from {t.name!r} — "
                f"loop-owned state must only be touched on its loop "
                f"(hand off with call_soon_threadsafe / run_coroutine_"
                f"threadsafe, or rebind() at an explicit ownership seam)")


class LoopWatchdog:
    """Detect event-loop stalls (a blocked tick loop = a blocked
    scheduler). A daemon thread schedules a heartbeat callback onto the
    loop at ``interval`` and measures how long it takes to run; latency
    over ``threshold`` logs the offending stack via the profiler's
    sampler."""

    def __init__(self, loop: asyncio.AbstractEventLoop,
                 threshold: float = 0.25, interval: float = 0.05):
        self.loop = loop
        self.threshold = threshold
        self.interval = interval
        self.stalls: list[float] = []
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def start(self) -> "LoopWatchdog":
        self._thread = threading.Thread(
            target=self._run, name="kcp-loop-watchdog", daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        # no join: stop() is typically called from the monitored loop
        # itself (Server.shutdown) — joining would block the loop on a
        # heartbeat that cannot run while the loop is blocked, freezing
        # shutdown and then logging a spurious stall. The daemon thread
        # observes _stop and exits on its own.
        self._stop.set()

    def _run(self) -> None:
        while not self._stop.is_set():
            seen = threading.Event()
            t0 = time.perf_counter()
            try:
                self.loop.call_soon_threadsafe(seen.set)
            except RuntimeError:  # loop closed
                return
            # wait generously; a stall is measured, not assumed
            seen.wait(timeout=max(self.threshold * 40, 10.0))
            dt = time.perf_counter() - t0
            if dt > self.threshold and not self._stop.is_set():
                self.stalls.append(dt)
                from .trace import _sample_once

                agg: dict = {}
                _sample_once(agg, threading.get_ident())
                top = sorted(agg.items(), key=lambda kv: -kv[1])[:3]
                frames = [list(stack)[:5] for (_tid, stack), _n in top]
                log.warning(
                    "event loop stalled %.3fs (> %.3fs): a callback blocked "
                    "the reconcile loop; top stacks: %s", dt, self.threshold,
                    frames)
            self._stop.wait(self.interval)
