"""Fleet scheduler: inventory deltas -> batched solves -> splitter leafs.

The reconciler that closes the fleet loop. It does NOT replace the
DeploymentSplitter — it *drives* it: the splitter keeps its informers,
its leaf naming/labels/owner-refs, its status fan-in and its drain
machinery; this controller takes over only the placement *decision*
(``splitter.place = False``) and pushes solver assignments through
``splitter._apply_placement``.

Reconcile shape:

- Root Deployment events intern the root into a solver row (demand =
  spec.replicas, home region = the root's ``fleet.kcp.dev/region``
  label). Rows are never recycled; a deleted root zeroes out.
- Cluster events reach the shared :class:`ClusterInventory` through the
  splitter's existing handler; this controller just wakes up and asks
  ``inventory.delta_since(last_seen)`` which workspaces moved. Only
  roots in those workspaces re-solve — a Ready flap inside the
  hysteresis window bumps no version, so it re-solves NOTHING.
- Evacuation/readmission replans route here via ``splitter.replan_sink``
  (the splitter's delayed health check still makes the hysteresis call).
- One :class:`FleetSolver` dispatch covers every dirty row; the
  assignment diff against the previous solve feeds
  ``placement_churn_total`` (bounded-migration evidence), and every
  applied decision counts in ``placement_resolves_total``.
"""

from __future__ import annotations

import logging
import os
from typing import Sequence

import numpy as np

from ..apis.cluster import REGION_LABEL
from ..reconciler.controller import BatchController
from ..reconcilers.deployment.controller import is_root
from ..utils.trace import REGISTRY
from .solver import DEFAULT_LOCALITY_WEIGHT, FleetSolver

log = logging.getLogger(__name__)


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, "") or default)
    except ValueError:
        return default


class FleetScheduler:
    """Drives DeploymentSplitter leaf specs from FleetSolver decisions."""

    def __init__(self, splitter, spread: int | None = None,
                 locality_weight: int | None = None, solver=None, mesh=None):
        self.splitter = splitter
        self.inventory = splitter.inventory
        if spread is None:
            spread = _env_int("KCP_FLEET_SPREAD", 0)
        if locality_weight is None:
            locality_weight = _env_int("KCP_FLEET_LOCALITY_WEIGHT",
                                       DEFAULT_LOCALITY_WEIGHT)
        self.solver = solver or FleetSolver(
            spread=spread, locality_weight=locality_weight,
            backend=splitter.backend, mesh=mesh)
        # take over the placement decision; status fan-in stays put
        splitter.place = False
        splitter.replan_sink = self._on_replan
        # root interning: key -> row; parallel per-row arrays grown on use
        self._rows: dict[tuple[str, str, str], int] = {}
        self._row_keys: list[tuple[str, str, str]] = []
        self._demand = np.zeros(0, np.int32)
        self._home: list[str] = []
        self._ws_of: list[str] = []
        self._seen_version = 0
        self.controller = BatchController(
            "fleet-scheduler", self._process_batch,
            tenant_of=lambda item: item[1][0] if item[1] else "")
        splitter.informer.add_handler(self._on_deployment)
        splitter.cluster_informer.add_handler(self._on_cluster)
        self.stats = {"ticks": 0, "solves": 0, "applied": 0}

    # ------------------------------------------------------------ events

    def _on_deployment(self, etype: str, old: dict | None,
                       new: dict | None) -> None:
        obj = new or old
        if not is_root(obj):
            return
        m = obj["metadata"]
        key = (m.get("clusterName", ""), m.get("namespace", ""), m["name"])
        self.controller.enqueue(("root", key))

    def _on_cluster(self, etype: str, old: dict | None,
                    new: dict | None) -> None:
        # the splitter's handler (registered first) already folded this
        # event into the shared inventory; just schedule a delta sweep
        lc = (new or old)["metadata"].get("clusterName", "")
        self.controller.enqueue(("fleet", (lc,)))

    def _on_replan(self, lc: str,
                   rkeys: Sequence[tuple[str, str, str]]) -> None:
        """Evacuation/readmission sink from the splitter's health FSM."""
        for rkey in rkeys:
            self.controller.enqueue(("root", rkey))

    # -------------------------------------------------------------- tick

    def _row_for(self, key: tuple[str, str, str]) -> int:
        r = self._rows.get(key)
        if r is None:
            r = len(self._row_keys)
            self._rows[key] = r
            self._row_keys.append(key)
            self._demand = np.append(self._demand, np.int32(0))
            self._home.append("")
            self._ws_of.append(key[0])
        return r

    async def _process_batch(self, items: Sequence) -> list:
        self.stats["ticks"] += 1
        dirty: set[int] = set()
        for kind, key in items:
            if kind != "root":
                continue
            r = self._row_for(key)
            root = self.splitter.informer.cache.get(key)
            if root is None or not is_root(root):
                self._demand[r] = 0
            else:
                self._demand[r] = min(
                    int(root.get("spec", {}).get("replicas", 0) or 0), 65535)
                self._home[r] = ((root["metadata"].get("labels") or {})
                                 .get(REGION_LABEL, ""))
            dirty.add(r)
        ws_changed, self._seen_version = self.inventory.delta_since(
            self._seen_version)
        if ws_changed is None:
            dirty.update(range(len(self._row_keys)))
        elif ws_changed:
            dirty.update(r for r, ws in enumerate(self._ws_of)
                         if ws in ws_changed)
        if not dirty:
            return []
        view = self.inventory.view()
        W, P = len(self._row_keys), len(view.names)
        if P == 0:
            # no clusters registered at all: host-side status only
            return self._apply_rows(sorted(dirty), view,
                                    np.zeros((W, max(P, 1)), np.int32))
        cand = np.zeros((W, P), bool)
        home = np.zeros(W, np.int32)
        rid = {name: i for i, name in enumerate(view.regions)}
        for r in range(W):
            row = view.row_index.get(self._ws_of[r])
            if row is not None:
                cand[r] = view.candidates[row]
            # -1 matches no region id: an unlabeled root gets no bonus
            home[r] = rid.get(self._home[r], -1)
        try:
            counts = self.solver.solve(self._demand, cand, view.alloc,
                                       view.region_id, home,
                                       rows=sorted(dirty))
        except Exception as err:  # noqa: BLE001 — injected/solver failure
            log.warning("fleet-scheduler: solve failed (%s); %d rows "
                        "requeued, last good assignment stands", err,
                        len(dirty))
            return [(("root", self._row_keys[r]), err) for r in dirty]
        self.stats["solves"] += 1
        return self._apply_rows(sorted(dirty), view, counts)

    def _apply_rows(self, rows, view, counts) -> list:
        failed = []
        for r in rows:
            key = self._row_keys[r]
            root = self.splitter.informer.cache.get(key)
            if root is None or not is_root(root):
                continue
            lc = key[0]
            picked = [(view.names[p], int(counts[r, p]))
                      for p in np.nonzero(counts[r])[0]]
            picked.sort()
            if not picked and int(self._demand[r]) == 0:
                continue  # nothing to place; keep status honest
            clusters, ccounts = [], []
            for name, cnt in picked:
                obj = self.splitter.cluster_informer.get(lc, name)
                if obj is not None:
                    clusters.append(obj)
                    ccounts.append(cnt)
            leafs = self.splitter.informer.index("owned_by", "/".join(key))
            # forced: the splitter moves replicas between existing leafs
            # and drains de-selected ones even with `rebalance` off
            self.splitter._force_replan.add(key)
            try:
                self.splitter._apply_placement(
                    key, root, clusters, leafs,
                    np.asarray(ccounts, np.int32))
                self.stats["applied"] += 1
            except Exception as err:  # noqa: BLE001 — conflict etc: requeue
                failed.append((("root", key), err))
        return failed

    # ---------------------------------------------------------- lifecycle

    async def start(self) -> None:
        # the splitter owns the informers and must already be started
        await self.controller.start()
        REGISTRY.gauge("fleet_scheduler_up",
                       "1 while the fleet scheduler is running").set(1)

    async def stop(self) -> None:
        await self.controller.stop()
        REGISTRY.gauge("fleet_scheduler_up",
                       "1 while the fleet scheduler is running").set(0)
