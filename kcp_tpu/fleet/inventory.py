"""Fleet inventory: Cluster API objects -> versioned, device-shaped state.

The splitter and the fleet scheduler both need one answer to "which
physical clusters may receive replicas right now, and with what weight?".
:class:`ClusterInventory` is that single health/capacity authority:

- **Interned columns/rows.** Every pcluster name is interned to a stable
  column id, every workspace (logical cluster) to a stable row id, so the
  fleet's eligibility is a dense bool ``[W, P]`` matrix and its capacity
  a couple of ``[P]`` int vectors — exactly the shapes the device solver
  consumes, built incrementally instead of re-scanned per solve.
- **Hysteresis state machine per registration.** A Ready->NotReady flip
  starts a clock; only a flip that *holds* for ``evac_hysteresis``
  seconds evacuates the registration (mirroring the splitter semantics
  introduced with health-gated evacuation). A flap inside the window
  touches NO versioned state — zero placement churn by construction.
  The clock is injectable (``now=`` everywhere) so property tests drive
  10k workspaces through virtual time in milliseconds.
- **Versioned deltas.** Placement-relevant transitions (register/forget,
  evacuate/readmit, capacity or locality change) bump ``version`` and
  append to a journal; :meth:`delta_since` answers "which workspaces'
  candidate sets changed since version v?" so re-solves touch only those
  rows. The journal compacts; a consumer older than the floor gets
  ``None`` = resync everything.

Thread-model: informer handlers and controller ticks all run on the
asyncio loop thread, so the inventory is deliberately lock-free (same
discipline as the informer caches).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field

import numpy as np

from ..apis import cluster as capi
from ..apis.conditions import FALSE, find_condition
from ..utils.trace import REGISTRY

DEFAULT_EVAC_HYSTERESIS = 5.0

# journal entries older than this many versions are compacted away;
# consumers further behind do one full resync (delta_since -> None)
_JOURNAL_KEEP = 4096


@dataclass
class ObservedDelta:
    """What one observe() changed — the caller's routing decisions."""

    notready_started: bool = False   # hysteresis clock armed: schedule a check
    recovered: bool = False          # NotReady cleared inside the window
    readmitted: bool = False         # evacuated registration turned Ready
    placement_changed: bool = False  # candidate set / weights moved (version bumped)


@dataclass(frozen=True)
class FleetView:
    """An immutable snapshot of the fleet at one version — the solver's
    input arrays. ``candidates[w, p]`` is registered-and-not-evacuated;
    capacity vectors are per *pcluster* (physical truth, shared across
    workspaces); ``region_id`` interns WAN locality labels."""

    version: int
    workspaces: tuple[str, ...]
    names: tuple[str, ...]
    regions: tuple[str, ...]
    candidates: np.ndarray   # bool  [W, P]
    capacity: np.ndarray     # int32 [P]
    alloc: np.ndarray        # int32 [P]
    region_id: np.ndarray    # int32 [P]
    row_index: dict[str, int] = field(hash=False, default_factory=dict)


def _explicitly_not_ready(obj: dict | None) -> bool:
    """Only a PRESENT Ready condition with status False counts — fresh
    registrations that never reported health stay placement-eligible."""
    if obj is None:
        return False
    c = find_condition(obj, capi.READY)
    return c is not None and c.get("status") == FALSE


class ClusterInventory:
    """Reconciles Cluster objects into versioned fleet placement state."""

    def __init__(self, evac_hysteresis: float = DEFAULT_EVAC_HYSTERESIS,
                 clock=time.monotonic):
        self.evac_hysteresis = evac_hysteresis
        self._clock = clock
        self._rows: dict[str, int] = {}
        self._cols: dict[str, int] = {}
        self._row_names: list[str] = []
        self._col_names: list[str] = []
        self._region_ids: dict[str, int] = {"": 0}
        self._region_names: list[str] = [""]
        self._registered = np.zeros((8, 8), dtype=bool)
        self._evacuated = np.zeros((8, 8), dtype=bool)
        self._capacity = np.zeros(8, dtype=np.int32)
        self._alloc = np.zeros(8, dtype=np.int32)
        self._region = np.zeros(8, dtype=np.int32)
        # armed hysteresis clocks: (row, col) -> monotonic start
        self._notready_since: dict[tuple[int, int], float] = {}
        self.version = 0
        self._journal: list[tuple[int, str, int]] = []  # (version, 'w'|'p', idx)
        self._journal_floor = 0
        self._view: FleetView | None = None

    # --------------------------------------------------------- interning

    def _row(self, workspace: str) -> int:
        w = self._rows.get(workspace)
        if w is None:
            w = len(self._row_names)
            self._rows[workspace] = w
            self._row_names.append(workspace)
            if w >= self._registered.shape[0]:
                grow = self._registered.shape[0]
                pad = ((0, grow), (0, 0))
                self._registered = np.pad(self._registered, pad)
                self._evacuated = np.pad(self._evacuated, pad)
        return w

    def _col(self, name: str) -> int:
        p = self._cols.get(name)
        if p is None:
            p = len(self._col_names)
            self._cols[name] = p
            self._col_names.append(name)
            if p >= self._registered.shape[1]:
                grow = self._registered.shape[1]
                self._registered = np.pad(self._registered, ((0, 0), (0, grow)))
                self._evacuated = np.pad(self._evacuated, ((0, 0), (0, grow)))
                self._capacity = np.pad(self._capacity, (0, grow))
                self._alloc = np.pad(self._alloc, (0, grow))
                self._region = np.pad(self._region, (0, grow))
            REGISTRY.gauge(
                "fleet_pclusters",
                "physical clusters known to the fleet inventory").set(
                len(self._col_names))
        return p

    def _region_id(self, region: str) -> int:
        rid = self._region_ids.get(region)
        if rid is None:
            rid = len(self._region_names)
            self._region_ids[region] = rid
            self._region_names.append(region)
        return rid

    def _bump(self, kind: str, idx: int) -> None:
        self.version += 1
        self._journal.append((self.version, kind, idx))
        self._view = None
        if len(self._journal) > 2 * _JOURNAL_KEEP:
            floor = self.version - _JOURNAL_KEEP
            self._journal = [e for e in self._journal if e[0] > floor]
            self._journal_floor = floor

    # ----------------------------------------------------------- observe

    def observe(self, workspace: str, obj: dict, etype: str = "MODIFIED",
                now: float | None = None) -> ObservedDelta:
        """Fold one Cluster event into the fleet state. Health flips ride
        the hysteresis FSM; only placement-relevant transitions bump the
        version (a flap inside the window is invisible to consumers)."""
        now = self._clock() if now is None else now
        name = obj["metadata"]["name"]
        w, p = self._row(workspace), self._col(name)
        d = ObservedDelta()
        if etype == "DELETED":
            if self._registered[w, p]:
                self._registered[w, p] = False
                self._bump("w", w)
                d.placement_changed = True
            self._evacuated[w, p] = False
            self._notready_since.pop((w, p), None)
            return d
        if not self._registered[w, p]:
            self._registered[w, p] = True
            self._bump("w", w)
            d.placement_changed = True
        cap = capi.capacity_of(obj)
        alloc = capi.allocatable_of(obj)
        rid = self._region_id(capi.region_of(obj))
        if (cap != self._capacity[p] or alloc != self._alloc[p]
                or rid != self._region[p]):
            self._capacity[p] = cap
            self._alloc[p] = alloc
            self._region[p] = rid
            self._bump("p", p)
            d.placement_changed = True
        if _explicitly_not_ready(obj):
            if (w, p) not in self._notready_since:
                self._notready_since[(w, p)] = now
                d.notready_started = True
        else:
            if self._notready_since.pop((w, p), None) is not None:
                d.recovered = True
            if self._evacuated[w, p]:
                self._evacuated[w, p] = False
                self._bump("w", w)
                d.readmitted = True
                d.placement_changed = True
                REGISTRY.counter(
                    "cluster_readmissions_total",
                    "evacuated clusters readmitted on Ready recovery").inc()
                self._evac_gauge()
        return d

    def check_evacuate(self, workspace: str, name: str,
                       now: float | None = None) -> bool:
        """The delayed hysteresis decision: evacuate IFF the registration
        is still NotReady a full window after the flip. Returns True only
        on the pending->evacuated transition (bumps the version)."""
        now = self._clock() if now is None else now
        w, p = self._rows.get(workspace), self._cols.get(name)
        if w is None or p is None:
            return False
        since = self._notready_since.get((w, p))
        if since is None or self._evacuated[w, p]:
            return False
        if now - since < self.evac_hysteresis - 1e-3:
            return False  # a newer flap rescheduled its own check
        self._evacuated[w, p] = True
        self._bump("w", w)
        REGISTRY.counter(
            "cluster_evacuations_total",
            "physical clusters drained after sustained NotReady").inc()
        self._evac_gauge()
        return True

    def tick(self, now: float | None = None) -> list[tuple[str, str]]:
        """Sweep every armed clock; evacuate the expired ones. Returns the
        (workspace, name) pairs evacuated this sweep — the standalone-
        scheduler / property-test driver (informer-driven consumers use
        per-flip delayed checks instead)."""
        now = self._clock() if now is None else now
        out = []
        for (w, p), since in list(self._notready_since.items()):
            ws, name = self._row_names[w], self._col_names[p]
            if self.check_evacuate(ws, name, now=now):
                out.append((ws, name))
        return out

    def _evac_gauge(self) -> None:
        REGISTRY.gauge(
            "fleet_evacuated_pclusters",
            "registrations currently evacuated (sustained NotReady)").set(
            int(self._evacuated.sum()))

    # ----------------------------------------------------------- queries

    def is_evacuated(self, workspace: str, name: str) -> bool:
        w, p = self._rows.get(workspace), self._cols.get(name)
        return w is not None and p is not None and bool(self._evacuated[w, p])

    @property
    def evacuated_pairs(self) -> frozenset[tuple[str, str]]:
        """(workspace, name) pairs currently evacuated — the splitter's
        legacy ``_evacuated`` surface."""
        ws, ps = np.nonzero(self._evacuated)
        return frozenset(
            (self._row_names[w], self._col_names[p]) for w, p in zip(ws, ps))

    def pending(self) -> int:
        """Armed hysteresis clocks (NotReady inside the window)."""
        return len(self._notready_since)

    def row_of(self, workspace: str) -> int | None:
        return self._rows.get(workspace)

    def view(self) -> FleetView:
        """Snapshot at the current version (cached until the next bump)."""
        if self._view is None or self._view.version != self.version:
            W, P = len(self._row_names), len(self._col_names)
            self._view = FleetView(
                version=self.version,
                workspaces=tuple(self._row_names),
                names=tuple(self._col_names),
                regions=tuple(self._region_names),
                candidates=(self._registered[:W, :P]
                            & ~self._evacuated[:W, :P]).copy(),
                capacity=self._capacity[:P].copy(),
                alloc=self._alloc[:P].copy(),
                region_id=self._region[:P].copy(),
                row_index=dict(self._rows),
            )
        return self._view

    def delta_since(self, version: int) -> tuple[set[str] | None, int]:
        """Workspaces whose candidate set / weights changed after
        ``version`` (None = journal compacted past it, resync all), plus
        the version the caller should remember."""
        if version < self._journal_floor:
            return None, self.version
        rows: set[int] = set()
        cols: set[int] = set()
        for ver, kind, idx in self._journal:
            if ver <= version:
                continue
            (rows if kind == "w" else cols).add(idx)
        if cols:
            P = len(self._col_names)
            col_idx = np.fromiter(cols, dtype=np.int64)
            col_idx = col_idx[col_idx < P]
            if col_idx.size:
                hit = self._registered[:len(self._row_names), :P][:, col_idx]
                rows.update(int(w) for w in np.nonzero(hit.any(axis=1))[0])
        return {self._row_names[w] for w in rows}, self.version
