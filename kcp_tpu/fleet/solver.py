"""Device-batched fleet bin-pack: one vmapped program for every workload.

The placement question at fleet scale is [W x P]: W workloads (one row
per root Deployment), P physical clusters. Per row the solver scores the
candidate clusters, selects the top-k (spread constraint), and deals the
replicas proportionally to allocatable capacity — all as ONE jitted
program (`solve_batched`), so a 10k-workspace re-solve is a single device
dispatch instead of 10k host loops.

Determinism is the whole contract: the score is integer, ties break on
column id (stable argsort of the negated score), and the weighted deal is
integer floor-division with the remainder going to the best-ranked
clusters (`ops.placement.split_replicas_weighted`). `solve_host` is the
numpy twin built from the SAME ops — the differential fuzz in
tests/test_fleet.py proves byte-identical assignments, and the CI
placement smoke re-proves it on every run.

Overflow bounds (int32, x64 disabled): weights clip at 2^15-1 and demand
at 2^16-1 so `demand * weight` stays below 2^31.

`FleetSolver` adds the incremental layer: a [W, P] assignment cache where
a re-solve gathers only the rows whose candidate set changed (the
inventory's delta), runs the padded device program over that subset, and
scatters the results back — plus an optional mesh from parallel/mesh.py
to shard the row dimension of full solves across devices.
"""

from __future__ import annotations

import time

import jax
import jax.numpy as jnp
import numpy as np

from ..faults import maybe_fail
from ..ops.encode import pad_pow2
from ..ops.placement import split_replicas_weighted
from ..utils.trace import REGISTRY

CAP_CLIP = 32767      # weight clip: demand * weight < 2^31 (int32, x64 off)
DEMAND_CLIP = 65535
DEFAULT_LOCALITY_WEIGHT = 1024  # outweighs any capacity delta < 2^10
_INT32_MAX = 2**31 - 1


def _select_row(demand, cand, alloc, region, home_region, spread,
                locality_weight):
    """Score + top-k selection for ONE workload row (vmapped over [W]).

    score = locality_weight * in-home-region + min(alloc, CAP_CLIP);
    eligibility = candidate with positive allocatable. The rank is the
    stable argsort-of-argsort: rank r means "r clusters score strictly
    better or tie with a lower column id" — so selected rows occupy ranks
    0..k-1 exactly, which split_replicas_weighted relies on.
    """
    elig = cand & (alloc > 0)
    w = jnp.minimum(alloc, CAP_CLIP).astype(jnp.int32)
    score = jnp.where(region == home_region, locality_weight, 0) + w
    neg = jnp.where(elig, -score, _INT32_MAX).astype(jnp.int32)
    order = jnp.argsort(neg, stable=True)          # score desc, col asc
    rank = jnp.argsort(order, stable=True).astype(jnp.int32)
    n_elig = elig.sum().astype(jnp.int32)
    k = jnp.where(spread > 0, jnp.minimum(spread, n_elig), n_elig)
    sel = elig & (rank < k)
    return sel, rank


@jax.jit
def solve_batched(demand, cand, alloc, region, home_region, spread,
                  locality_weight):
    """The device program: [W] demand, [W,P] candidates, [P] (or [W,P])
    capacity/region vectors -> int32 [W,P] assignment."""
    alloc2 = jnp.broadcast_to(alloc, cand.shape).astype(jnp.int32)
    region2 = jnp.broadcast_to(region, cand.shape).astype(jnp.int32)
    sel, rank = jax.vmap(_select_row, in_axes=(0, 0, 0, 0, 0, None, None))(
        demand, cand, alloc2, region2, home_region, spread, locality_weight)
    w = jnp.minimum(alloc2, CAP_CLIP).astype(jnp.int32)
    return split_replicas_weighted(
        jnp.minimum(demand, DEMAND_CLIP).astype(jnp.int32), w, sel, rank)


def solve_host(demand, cand, alloc, region, home_region, spread=0,
               locality_weight=DEFAULT_LOCALITY_WEIGHT) -> np.ndarray:
    """Numpy twin of solve_batched — the same integer ops in the same
    order, so assignments match the device program byte-for-byte."""
    demand = np.minimum(np.asarray(demand, np.int32), DEMAND_CLIP)
    cand = np.asarray(cand, bool)
    alloc2 = np.broadcast_to(np.asarray(alloc, np.int32), cand.shape)
    region2 = np.broadcast_to(np.asarray(region, np.int32), cand.shape)
    home = np.asarray(home_region, np.int32)
    elig = cand & (alloc2 > 0)
    w = np.minimum(alloc2, CAP_CLIP).astype(np.int32)
    score = np.where(region2 == home[:, None], np.int32(locality_weight),
                     np.int32(0)) + w
    neg = np.where(elig, -score, np.int32(_INT32_MAX)).astype(np.int32)
    order = np.argsort(neg, axis=-1, kind="stable")
    rank = np.argsort(order, axis=-1, kind="stable").astype(np.int32)
    n_elig = elig.sum(axis=-1).astype(np.int32)
    k = np.where(spread > 0, np.minimum(np.int32(spread), n_elig), n_elig)
    sel = elig & (rank < k[:, None])
    wsel = np.where(sel, w, 0).astype(np.int32)
    total = wsel.sum(axis=-1, keepdims=True)
    base = (demand[:, None] * wsel) // np.maximum(total, 1)
    rem = demand - base.sum(axis=-1)
    extra = (rank < rem[:, None]) & sel
    return np.where(sel & (total > 0),
                    base + extra.astype(np.int32), 0).astype(np.int32)


def solve_sharded(mesh, demand, cand, alloc, region, home_region, spread=0,
                  locality_weight=DEFAULT_LOCALITY_WEIGHT) -> np.ndarray:
    """Full solve with the row dimension sharded over a parallel/mesh.py
    mesh (rows over hosts x tenants like every [B] batch dimension; the
    [P] fleet vectors replicate). Rows pad to the mesh's row factor."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ..parallel.mesh import HOSTS_AXIS, TENANTS_AXIS, row_factor

    W = int(np.asarray(demand).shape[0])
    rows = row_factor(mesh)
    Wp = max(((W + rows - 1) // rows) * rows, rows)
    row_axes = ((HOSTS_AXIS, TENANTS_AXIS)
                if HOSTS_AXIS in mesh.axis_names else TENANTS_AXIS)
    row_s = NamedSharding(mesh, P(row_axes))
    mat_s = NamedSharding(mesh, P(row_axes, None))
    rep_s = NamedSharding(mesh, P())

    def pad_rows(a, sharding):
        a = np.asarray(a)
        out = np.zeros((Wp,) + a.shape[1:], a.dtype)
        out[:W] = a
        return jax.device_put(out, sharding)

    out = solve_batched(
        pad_rows(np.asarray(demand, np.int32), row_s),
        pad_rows(np.asarray(cand, bool), mat_s),
        jax.device_put(np.asarray(alloc, np.int32), rep_s),
        jax.device_put(np.asarray(region, np.int32), rep_s),
        pad_rows(np.asarray(home_region, np.int32), row_s),
        jnp.int32(spread), jnp.int32(locality_weight))
    return np.asarray(out)[:W]


class FleetSolver:
    """Incremental wrapper: a [W, P] assignment cache where re-solves
    gather only the changed rows through the (padded, shape-stable)
    device program and scatter the results back."""

    def __init__(self, spread: int = 0,
                 locality_weight: int = DEFAULT_LOCALITY_WEIGHT,
                 backend: str = "tpu", mesh=None):
        self.spread = int(spread)
        self.locality_weight = int(locality_weight)
        self.backend = backend
        self.mesh = mesh
        self._counts: np.ndarray | None = None
        self.stats = {"solves": 0, "rows_solved": 0, "rows_skipped": 0}

    def solve(self, demand, cand, alloc, region, home_region,
              rows=None) -> np.ndarray:
        """Solve and return the full [W, P] assignment. ``rows`` (int
        indices) restricts the device dispatch to those rows when the
        cached shape still matches — the inventory-delta fast path."""
        delay = maybe_fail("fleet.solve")
        if delay:
            time.sleep(delay)
        t0 = time.perf_counter()
        demand = np.asarray(demand, np.int32)
        cand = np.asarray(cand, bool)
        W, P = cand.shape
        full = (rows is None or self._counts is None
                or self._counts.shape != (W, P))
        idx = np.arange(W) if full else np.unique(
            np.asarray(rows, np.int64))
        self.stats["solves"] += 1
        self.stats["rows_solved"] += int(idx.size)
        self.stats["rows_skipped"] += W - int(idx.size)
        if full:
            self._counts = np.zeros((W, P), np.int32)
        if idx.size:
            sub = self._dispatch(demand[idx], cand[idx], alloc, region,
                                 np.asarray(home_region, np.int32)[idx])
            self._counts[idx] = sub
        REGISTRY.histogram(
            "fleet_solve_seconds",
            "fleet bin-pack solve latency").observe(time.perf_counter() - t0)
        return self._counts

    def _dispatch(self, demand, cand, alloc, region, home) -> np.ndarray:
        if self.backend != "tpu":
            return solve_host(demand, cand, alloc, region, home,
                              self.spread, self.locality_weight)
        if self.mesh is not None:
            return solve_sharded(self.mesh, demand, cand, alloc, region,
                                 home, self.spread, self.locality_weight)
        n, P = cand.shape
        npad, ppad = pad_pow2(max(n, 1)), pad_pow2(max(P, 1))
        d = np.zeros(npad, np.int32)
        d[:n] = demand
        c = np.zeros((npad, ppad), bool)
        c[:n, :P] = cand
        a = np.zeros(ppad, np.int32)
        a[:P] = np.asarray(alloc, np.int32)
        r = np.zeros(ppad, np.int32)
        r[:P] = np.asarray(region, np.int32)
        h = np.zeros(npad, np.int32)
        h[:n] = home
        out = solve_batched(d, c, a, r, h, jnp.int32(self.spread),
                            jnp.int32(self.locality_weight))
        return np.asarray(out)[:n, :P]
