"""Fleet placement control plane: inventory -> batched solver -> scheduler.

Import :class:`FleetScheduler` from ``kcp_tpu.fleet.scheduler`` directly —
keeping it out of this namespace avoids an import cycle with the
deployment splitter (which owns a :class:`ClusterInventory`).
"""

from .inventory import ClusterInventory, FleetView, ObservedDelta
from .solver import FleetSolver, solve_batched, solve_host, solve_sharded

__all__ = [
    "ClusterInventory", "FleetView", "ObservedDelta",
    "FleetSolver", "solve_batched", "solve_host", "solve_sharded",
]
