#!/usr/bin/env python
"""End-to-end demo scenarios — the analog of the reference's contrib/demo.

The reference drives two demo-magic scripts against kind clusters and
diffs normalized output against golden files (runDemos.sh, SURVEY.md §4).
Here the same scenarios run hermetically against fake physical clusters
and print a normalized transcript; ``--check`` compares it against the
committed golden file.

Scenarios:
- ``apiNegotiation`` — register us-east1, import, publish, CRD
  established; register us-west1 with a narrower schema and observe
  Compatible=False on its import (reference: contrib/demo/apiNegotiation:36-60)
- ``kubecon`` — register two clusters, create a root Deployment, watch it
  split, sync down, and aggregate status back up
  (reference: contrib/demo/kubecon)

Usage:
    python contrib/demo/run_demo.py [apiNegotiation|kubecon|all] [--check]
"""

from __future__ import annotations

import asyncio
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))
os.environ.setdefault("DEMO_JAX_PLATFORM", "cpu")
if os.environ["DEMO_JAX_PLATFORM"] == "cpu":
    import jax

    jax.config.update("jax_platforms", "cpu")

from kcp_tpu.apis import apiresource as ar  # noqa: E402
from kcp_tpu.apis import cluster as clusterapi  # noqa: E402
from kcp_tpu.apis import conditions as cond  # noqa: E402
from kcp_tpu.apis import crd as crdapi  # noqa: E402
from kcp_tpu.client import MultiClusterClient  # noqa: E402
from kcp_tpu.physical import FakeClusterAgent, PhysicalRegistry  # noqa: E402
from kcp_tpu.reconcilers.apiresource import NegotiationController  # noqa: E402
from kcp_tpu.reconcilers.cluster import ClusterController, SyncerMode  # noqa: E402
from kcp_tpu.reconcilers.crdlifecycle import CRDLifecycleController  # noqa: E402
from kcp_tpu.reconcilers.deployment import DeploymentSplitter  # noqa: E402
from kcp_tpu.store import LogicalStore  # noqa: E402

GOLDEN = os.path.join(os.path.dirname(__file__), "demo.result")

out_lines: list[str] = []


def emit(line: str) -> None:
    out_lines.append(line)
    print(line)


async def eventually(pred, timeout=20.0, desc="condition"):
    loop = asyncio.get_event_loop()
    end = loop.time() + timeout
    last = None
    while loop.time() < end:
        try:
            last = pred()
            if last:
                return last
        except Exception as e:  # noqa: BLE001
            last = repr(e)
        await asyncio.sleep(0.02)
    raise RuntimeError(f"demo timed out waiting for {desc} (last={last!r})")


class ControlPlane:
    """One in-process control plane with all controllers running."""

    def __init__(self):
        self.store = LogicalStore()
        self.client = MultiClusterClient(self.store)
        self.registry = PhysicalRegistry()
        self.negotiation = NegotiationController(self.client, auto_publish=True)
        self.lifecycle = CRDLifecycleController(self.client)
        self.clusters = ClusterController(
            self.client, self.registry, resources_to_sync=["deployments.apps"],
            mode=SyncerMode.PUSH, poll_interval=0.2, import_poll_interval=0.2,
        )
        self.splitter = DeploymentSplitter(self.client)
        self.agents: list[FakeClusterAgent] = []

    async def start(self):
        await self.negotiation.start()
        await self.lifecycle.start()
        await self.clusters.start()
        await self.splitter.start()

    async def add_physical(self, name: str) -> None:
        client = self.registry.resolve(f"fake://{name}")
        agent = FakeClusterAgent(client)
        await agent.start()
        self.agents.append(agent)

    async def stop(self):
        for a in self.agents:
            await a.stop()
        await self.splitter.stop()
        await self.clusters.stop()
        await self.lifecycle.stop()
        await self.negotiation.stop()


async def demo_api_negotiation() -> None:
    emit("=== demo: apiNegotiation ===")
    cp = ControlPlane()
    await cp.start()
    await cp.add_physical("us-east1")
    t = cp.client.cluster_client("admin")

    emit("$ kubectl apply cluster us-east1")
    t.create(clusterapi.CLUSTERS, clusterapi.new_cluster("us-east1", "fake://us-east1"))
    await eventually(
        lambda: ar.is_compatible_and_available(
            t.get(ar.APIRESOURCEIMPORTS, "us-east1.deployments.v1.apps")),
        desc="us-east1 import compatible+available")
    emit("apiresourceimport us-east1.deployments.v1.apps: Compatible=True Available=True")
    await eventually(lambda: crdapi.is_established(t.get(crdapi.CRDS, "deployments.apps")),
                     desc="deployments CRD established")
    emit("crd deployments.apps: Established=True")
    await eventually(lambda: clusterapi.is_ready(t.get(clusterapi.CLUSTERS, "us-east1")),
                     desc="us-east1 Ready")
    emit("cluster us-east1: Ready=True syncedResources="
         + ",".join(clusterapi.synced_resources(t.get(clusterapi.CLUSTERS, "us-east1"))))

    emit("$ kubectl apply cluster us-west1 (narrower deployment schema)")
    # us-west1's fake cluster serves a deployments CRD whose spec.replicas
    # is a string -> incompatible with the negotiated integer schema
    west = cp.registry.resolve("fake://us-west1")
    bad = crdapi.new_crd("apps", "v1", "deployments", "Deployment", schema={
        "type": "object",
        "properties": {"spec": {"type": "object", "properties": {
            "replicas": {"type": "string"}}}},
    })
    west.create(crdapi.CRDS, bad)
    await cp.add_physical("us-west1")
    t.create(clusterapi.CLUSTERS, clusterapi.new_cluster("us-west1", "fake://us-west1"))

    imp = await eventually(
        lambda: (lambda o: cond.find_condition(o, ar.COMPATIBLE) is not None and o)(
            t.get(ar.APIRESOURCEIMPORTS, "us-west1.deployments.v1.apps")),
        desc="us-west1 import processed")
    c = cond.find_condition(imp, ar.COMPATIBLE)
    emit(f"apiresourceimport us-west1.deployments.v1.apps: Compatible={c['status']}"
         f" reason={c.get('reason', '')}")
    await cp.stop()


async def demo_kubecon() -> None:
    emit("=== demo: kubecon ===")
    cp = ControlPlane()
    await cp.start()
    await cp.add_physical("east")
    await cp.add_physical("west")
    t = cp.client.cluster_client("kubecon")

    emit("$ kubectl apply cluster east west")
    t.create(clusterapi.CLUSTERS, clusterapi.new_cluster("east", "fake://east"))
    t.create(clusterapi.CLUSTERS, clusterapi.new_cluster("west", "fake://west"))
    await eventually(lambda: clusterapi.is_ready(t.get(clusterapi.CLUSTERS, "east"))
                     and clusterapi.is_ready(t.get(clusterapi.CLUSTERS, "west")),
                     desc="both clusters ready")
    emit("cluster east: Ready=True")
    emit("cluster west: Ready=True")

    emit("$ kubectl apply deployment demo replicas=10")
    t.create("deployments.apps", {
        "apiVersion": "apps/v1", "kind": "Deployment",
        "metadata": {"name": "demo", "namespace": "default"},
        "spec": {"replicas": 10,
                 "selector": {"matchLabels": {"app": "demo"}},
                 "template": {"metadata": {"labels": {"app": "demo"}},
                              "spec": {"containers": [{"name": "demo", "image": "x"}]}}},
    })
    east = cp.registry.resolve("fake://east")
    west = cp.registry.resolve("fake://west")
    await eventually(lambda: east.get("deployments.apps", "demo--east", "default"),
                     desc="east physical deployment")
    await eventually(lambda: west.get("deployments.apps", "demo--west", "default"),
                     desc="west physical deployment")
    e = east.get("deployments.apps", "demo--east", "default")["spec"]["replicas"]
    w = west.get("deployments.apps", "demo--west", "default")["spec"]["replicas"]
    emit(f"deployment demo--east synced to east with replicas={e}")
    emit(f"deployment demo--west synced to west with replicas={w}")
    await eventually(
        lambda: t.get("deployments.apps", "demo", "default")
        .get("status", {}).get("readyReplicas") == 10,
        desc="root status aggregation")
    st = t.get("deployments.apps", "demo", "default")["status"]
    emit(f"deployment demo status: replicas={st['replicas']} ready={st['readyReplicas']}"
         f" available={st['availableReplicas']}")
    await cp.stop()


async def main() -> int:
    which = sys.argv[1] if len(sys.argv) > 1 and not sys.argv[1].startswith("-") else "all"
    if which in ("apiNegotiation", "all"):
        await demo_api_negotiation()
    if which in ("kubecon", "all"):
        await demo_kubecon()
    if "--check" in sys.argv:
        if which != "all":
            print("--check requires running all scenarios", file=sys.stderr)
            return 2
        want = open(GOLDEN, encoding="utf-8").read().splitlines()
        got = out_lines
        if want != got:
            print("GOLDEN MISMATCH", file=sys.stderr)
            for w, g in zip(want + [""] * len(got), got + [""] * len(want)):
                if w != g:
                    print(f"- {w}\n+ {g}", file=sys.stderr)
            return 1
        print("golden check: OK")
    return 0


if __name__ == "__main__":
    sys.exit(asyncio.run(main()))
