#!/usr/bin/env bash
# Typecheck gate (mypy, baseline-ratcheted) for kcp_tpu/analysis +
# kcp_tpu/utils. The analysis package is strict (mypy.ini); utils runs at
# default strictness with pre-existing findings frozen in the committed
# baseline — only NEW errors fail, so the gate ratchets without a
# whole-tree annotation project.
#
#   scripts/typecheck.sh            # gate: fail on errors not in baseline
#   scripts/typecheck.sh --update   # re-freeze the baseline (then commit)
#
# Hosts without mypy (this repo's container image does not ship it) skip
# with a note — the committed baseline still gates every host that has it,
# same policy as the ruff stage in scripts/ci.sh.
set -euo pipefail
cd "$(dirname "$0")/.."

BASELINE=scripts/typecheck_baseline.txt

if ! command -v mypy >/dev/null 2>&1; then
    echo "typecheck: mypy not installed on this host, skipped" \
         "(mypy.ini + $BASELINE still gate hosts that have it)"
    exit 0
fi

current=$(mypy --config-file mypy.ini 2>&1 | grep ': error:' | sort -u || true)

if [[ "${1:-}" == "--update" ]] || grep -q '^# UNINITIALIZED' "$BASELINE"; then
    {
        echo "# mypy baseline — frozen pre-existing findings for the"
        echo "# baseline-gated packages (see mypy.ini). Regenerate with"
        echo "# scripts/typecheck.sh --update and commit the diff; the"
        echo "# gate fails only on errors NOT listed here."
        printf '%s\n' "$current"
    } > "$BASELINE"
    n=$(printf '%s' "$current" | grep -c ': error:' || true)
    echo "typecheck: baseline (re)frozen with $n finding(s) — commit $BASELINE"
    exit 0
fi

new=$(comm -23 <(printf '%s\n' "$current" | sed '/^$/d') \
               <(grep -v '^#' "$BASELINE" | sed '/^$/d' | sort -u) || true)
if [[ -n "$new" ]]; then
    echo "typecheck: NEW errors not in $BASELINE:"
    printf '%s\n' "$new"
    exit 1
fi
n=$(grep -vc '^#' "$BASELINE" 2>/dev/null || echo 0)
echo "typecheck ok: no new errors (baseline carries $n frozen finding(s))"
