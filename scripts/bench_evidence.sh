#!/usr/bin/env bash
# Round-evidence battery on the real chip — run when the TPU tunnel is
# healthy and NOTHING else is touching it (docs/operations.md: one
# process on the tunnel at a time; everything here runs sequentially).
#
# Produces, under $OUT (default /tmp/bench_evidence):
#   p99_run_{1..5}.json    five consecutive default runs (multi-run p99
#                          table — the variance-aware convergence claim)
#   suite.json             kernel-lane suite (schema lane re-measure
#                          after the native tokenizer rebuild)
#   churn_{256,768,2048,4096}.json  event-rate headroom curve
#   rows1m.json            1M-resident-row scale run with the stall
#                          diagnostics (full_uploads/gap per segment)
#   fleet.json             ragged fleet-batch A/B (per-bucket vs one
#                          pipelined program; utilization + throughput)
# plus, at the repo root:
#   MULTICHIP_r06.json     ragged fleet step on a virtual 8-device mesh
#                          (byte-equality vs the single-device run)
# Each file is ONE bench JSON line; stderr logs sit next to each.
set -uo pipefail
cd "$(dirname "$0")/.."
OUT="${OUT:-/tmp/bench_evidence}"
mkdir -p "$OUT"

run() { # run <name> [env k=v...] [-- bench args...]
    local name="$1"; shift
    local envs=() args=() in_args=0
    for tok in "$@"; do
        if [[ "$tok" == "--" ]]; then in_args=1
        elif [[ "$in_args" == 1 ]]; then args+=("$tok")
        else envs+=("$tok"); fi
    done
    echo "== $name ($(date +%H:%M:%S))"
    if ! env "${envs[@]}" python bench.py "${args[@]}" \
            > "$OUT/$name.json" 2> "$OUT/$name.stderr.log"; then
        echo "FAILED: $name (see $OUT/$name.stderr.log)"
        FAILURES+=("$name")
    fi
    tail -c 400 "$OUT/$name.json"; echo
}
FAILURES=()

for i in 1 2 3 4 5; do
    run "p99_run_$i"
done
run suite -- --suite
for c in 256 768 2048 4096; do
    run "churn_$c" KCP_BENCH_CHURN="$c"
done
run rows1m KCP_BENCH_ROWS=1048576
run fleet -- --fleet

# MULTICHIP evidence: the ragged fleet batch on a virtual 8-device
# (tenants) mesh must emit patch streams byte-identical to the
# single-device run. Forced onto the host platform so it certifies the
# sharding math regardless of tunnel health; the JSON lands at the repo
# root as the round's MULTICHIP artifact.
echo "== fleet-equivalence (virtual 8-device mesh) ($(date +%H:%M:%S))"
if env JAX_PLATFORMS=cpu \
        XLA_FLAGS="--xla_force_host_platform_device_count=8" \
        python __graft_entry__.py fleet-equivalence 8 \
        > "$OUT/fleet_equivalence.json" \
        2> "$OUT/fleet_equivalence.stderr.log"; then
    python - "$OUT/fleet_equivalence.json" <<'PY'
import json, sys
body = json.load(open(sys.argv[1]))
out = {"n_devices": 8, "rc": 0, "ok": True, "skipped": False,
       "lane": "fleet-equivalence"}
out.update(body)
out["tail"] = (
    "ragged fleet batch on a virtual 8-device (tenants) mesh: "
    f"{body['owners']} owners across 2 buckets + straggler, "
    f"{body['ticks']} ticks, fleet B={body['fleet_rows']}; patch "
    "streams byte-identical to the single-device run")
json.dump(out, open("MULTICHIP_r06.json", "w"), indent=2)
print("MULTICHIP_r06.json:", out["tail"])
PY
else
    echo "FAILED: fleet-equivalence (see $OUT/fleet_equivalence.stderr.log)"
    FAILURES+=(fleet-equivalence)
fi

if ((${#FAILURES[@]})); then
    echo "evidence battery INCOMPLETE: ${FAILURES[*]} failed ($OUT)"
    exit 1
fi
echo "evidence battery complete: $OUT"
