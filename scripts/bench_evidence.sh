#!/usr/bin/env bash
# Round-evidence battery on the real chip — run when the TPU tunnel is
# healthy and NOTHING else is touching it (docs/operations.md: one
# process on the tunnel at a time; everything here runs sequentially).
#
# Produces, under $OUT (default /tmp/bench_evidence):
#   p99_run_{1..5}.json    five consecutive default runs (multi-run p99
#                          table — the variance-aware convergence claim)
#   suite.json             kernel-lane suite (schema lane re-measure
#                          after the native tokenizer rebuild)
#   churn_{256,768,2048,4096}.json  event-rate headroom curve
#   rows1m.json            1M-resident-row scale run with the stall
#                          diagnostics (full_uploads/gap per segment)
# Each file is ONE bench JSON line; stderr logs sit next to each.
set -uo pipefail
cd "$(dirname "$0")/.."
OUT="${OUT:-/tmp/bench_evidence}"
mkdir -p "$OUT"

run() { # run <name> [env k=v...] [-- bench args...]
    local name="$1"; shift
    local envs=() args=() in_args=0
    for tok in "$@"; do
        if [[ "$tok" == "--" ]]; then in_args=1
        elif [[ "$in_args" == 1 ]]; then args+=("$tok")
        else envs+=("$tok"); fi
    done
    echo "== $name ($(date +%H:%M:%S))"
    if ! env "${envs[@]}" python bench.py "${args[@]}" \
            > "$OUT/$name.json" 2> "$OUT/$name.stderr.log"; then
        echo "FAILED: $name (see $OUT/$name.stderr.log)"
        FAILURES+=("$name")
    fi
    tail -c 400 "$OUT/$name.json"; echo
}
FAILURES=()

for i in 1 2 3 4 5; do
    run "p99_run_$i"
done
run suite -- --suite
for c in 256 768 2048 4096; do
    run "churn_$c" KCP_BENCH_CHURN="$c"
done
run rows1m KCP_BENCH_ROWS=1048576
if ((${#FAILURES[@]})); then
    echo "evidence battery INCOMPLETE: ${FAILURES[*]} failed ($OUT)"
    exit 1
fi
echo "evidence battery complete: $OUT"
