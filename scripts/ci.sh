#!/usr/bin/env bash
# CI pipeline — the analog of the reference's committed workflows
# (.github/workflows/ci.yaml: vet + race-checked tests; demos.yaml:
# golden demo runs). A fresh checkout runs this green; every stage is
# CPU-pinned (tests via conftest, demo via DEMO_JAX_PLATFORM, dryrun via
# its XLA_FLAGS guard) so it is safe to run while a TPU bench is in
# flight elsewhere.
#
# Usage: scripts/ci.sh [--fast]   (--fast skips the demo + dryrun)
set -euo pipefail
cd "$(dirname "$0")/.."

fast=0
[[ "${1:-}" == "--fast" ]] && fast=1

echo "== vet: syntax-compile every tracked python file"
python -m compileall -q kcp_tpu tests contrib bench.py __graft_entry__.py

if command -v ruff >/dev/null 2>&1; then
    echo "== lint: ruff (present on this host)"
    ruff check kcp_tpu tests bench.py __graft_entry__.py
else
    echo "== lint: ruff not installed here, skipped (vet stage above still gates syntax)"
fi

echo "== kcp-lint: contract checkers (CoW / frozen-bytes / async / lock-order / fault points / metrics docs)"
# zero active findings required; waivers are counted and reported so
# exemptions stay visible in every CI log (scripts/lint.py --help)
python scripts/lint.py --format json > /tmp/_lint.json || {
    python scripts/lint.py; exit 1; }
python -c '
import json
r = json.load(open("/tmp/_lint.json"))
assert r["ok"], r["summary"]
for w in r["waived"]:
    print("  waived: %s:%s %s -- %s"
          % (w["path"], w["line"], w["rule"], w["justification"]))
print("kcp-lint ok: 0 findings | %d waiver(s), all justified | %d files"
      % (r["summary"]["waived"], r["files_checked"]))
'

echo "== typecheck: mypy baseline gate for kcp_tpu/analysis + kcp_tpu/utils"
scripts/typecheck.sh

echo "== native: build libkcpnative.so + kcptok extension"
make -s -C native
make -s -C native kcptok.so

echo "== tests: full suite, race-checked (KCP_RACE=1 via conftest)"
python -m pytest tests/ -q

echo "== chaos: seeded KCP_FAULTS smoke (store 5xx + one device-step raise)"
# the spec grammar is documented in kcp_tpu/faults.py; the test asserts
# tier-1 convergence with zero lost patches under the injected schedule
KCP_FAULTS='store.put:error=0.05;device.step:raise@tick=5' \
    KCP_FAULTS_SEED=1337 \
    python -m pytest tests/test_faults.py::test_ci_chaos_smoke -q

echo "== sanitize: tier-1 differential fuzzes under KCP_SANITIZE=1 (freeze proxies + byte verify + lock tracking)"
# the store-index and encode-cache equivalence fuzzes must stay green
# with every snapshot frozen and every cache hit re-verified — plus the
# deliberate-violation drills in tests/test_sanitize.py
KCP_SANITIZE=1 python -m pytest \
    tests/test_sanitize.py tests/test_store_index.py \
    tests/test_encode_cache.py -q

echo "== bench: CPU smoke of the serial-vs-pipelined tick A/B (tiny shape)"
ab_line=$(JAX_PLATFORMS=cpu KCP_BENCH_CHILD=1 KCP_BENCH_ROWS=2048 \
    KCP_BENCH_CHURN=64 KCP_BENCH_WARMUP=6 KCP_BENCH_SEGMENTS=1 \
    KCP_BENCH_SEGMENT_S=1 python bench.py --pipeline double | tail -1)
printf '%s\n' "$ab_line" | python -c '
import json, sys
r = json.loads(sys.stdin.readline())
ab = r.get("pipeline_ab") or {}
assert set(ab) == {"serial", "double"}, f"A/B modes missing: {sorted(ab)}"
for mode, res in ab.items():
    assert res.get("value", 0) > 0, f"{mode}: no measured rate"
    assert res.get("segment_rates"), f"{mode}: no per-segment rates"
    assert "convergence_p99_ms" in res, f"{mode}: no convergence percentiles"
print("pipeline A/B smoke ok:",
      {m: res["value"] for m, res in ab.items()},
      "| speedup:", r.get("pipeline_speedup"))
'

echo "== fleet: ragged-vs-per-bucket dispatch smoke (mixed buckets + stragglers)"
# small mixed-bucket fleet: the ragged batch must (1) emit byte-identical
# per-owner patch streams, (2) beat per-bucket dispatch >=1.5x combined
# throughput on this host, (3) amortize >=2x rows per device dispatch,
# and (4) pass the poison-row quarantine drill (segment-scoped bisection)
fleet_line=$(JAX_PLATFORMS=cpu KCP_BENCH_CHILD=1 KCP_BENCH_FLEET_ROWS=2048 \
    KCP_BENCH_FLEET_STEPS=16 KCP_BENCH_FLEET_WARMUP=6 \
    KCP_BENCH_FLEET_STRAGGLERS=8 python bench.py --fleet | tail -1)
printf '%s\n' "$fleet_line" | python -c '
import json, sys
r = json.loads(sys.stdin.readline())
fb = r["fleet_bench"]
assert fb["streams_equal"], "ragged and per-bucket patch streams diverged"
assert r["value"] >= 2.0, "device-utilization gain %sx < 2x floor" % r["value"]
assert fb["combined_speedup"] >= 1.5, (
    "ragged combined throughput %sx < 1.5x floor" % fb["combined_speedup"])
drill = fb["quarantine_drill"]
assert drill["ok"], "quarantine drill failed: %s" % drill
print("fleet smoke ok: %sx rows/dispatch | %sx combined | %d buckets"
      " -> 1 program | drill: %d quarantined, co-tenants ok"
      % (r["value"], fb["combined_speedup"], fb["buckets"],
         drill["quarantined"]))
'

echo "== placement: fleet bin-pack smoke (batched-vs-per-workspace floor + assignment byte-equality)"
# reduced-scale --placement lane (2k workspaces x 8 pclusters, 400-row
# loop sample): the batched device solve must beat the pre-fleet
# per-workspace host loop >=4x (the committed full-scale
# BENCH_r11_placement.json measured ~15x at 10k x 8), stay byte-identical
# to the numpy host twin AND the per-workspace answers, never overcommit
# or land on a non-candidate, and the incremental re-solve must touch
# exactly the dirty rows while matching a from-scratch recompute
pl_line=$(JAX_PLATFORMS=cpu KCP_BENCH_PLACEMENT_WORKSPACES=2000 \
    KCP_BENCH_PLACEMENT_LOOP_ROWS=400 KCP_BENCH_PLACEMENT_ITERS=3 \
    python bench.py --placement | tail -1)
printf '%s\n' "$pl_line" | python -c '
import json, sys
r = json.loads(sys.stdin.readline())
pb = r["placement_bench"]
assert pb["assignment_equal_host"], "batched assignment diverged from host twin"
assert pb["assignment_equal_per_workspace"], (
    "per-workspace loop diverged from the batched answer")
assert pb["overcommit_rows"] == 0, pb
assert pb["noncandidate_replicas"] == 0, pb
inc = pb["incremental"]
assert inc["rows_solved"] == inc["dirty_rows"], (
    "incremental re-solve touched %d rows for %d dirty"
    % (inc["rows_solved"], inc["dirty_rows"]))
assert inc["mismatches"] == 0, inc
assert r["value"] >= 4.0, "batched speedup %sx < 4x floor" % r["value"]
print("placement smoke ok: %sx batched vs per-workspace | %d rows byte-identical"
      " | incremental %d/%d rows, 0 mismatches"
      % (r["value"], pb["workspaces"], inc["rows_solved"], inc["dirty_rows"]))
'

echo "== store: CPU microbench smoke (10k objects, 64 watches) with regression floor"
store_line=$(KCP_BENCH_STORE_OBJECTS=10000 KCP_BENCH_STORE_MUTS=1500 \
    python bench.py --store | tail -1)
printf '%s\n' "$store_line" | python -c '
import json, sys
r = json.loads(sys.stdin.readline())
v = r["value"]
sb = r["store_bench"]
assert sb["events_equal"], "indexed/legacy watch event counts diverged"
# regression floor: the indexed read path measured ~9x combined at this
# shape when it landed; 4x leaves slack for slow CI hosts while still
# catching a lost index or a reintroduced per-event deepcopy
assert v >= 4.0, "store read-path speedup regressed: %sx < 4x floor" % v
print("store smoke ok: %sx combined | %sx list | %sx fan-out"
      % (v, sb["list_speedup"], sb["fanout_speedup"]))
'

echo "== encode: encode-once serving A/B smoke (10k objects, 64 watchers) with regression floor"
enc_line=$(KCP_BENCH_ENCODE_OBJECTS=10000 KCP_BENCH_ENCODE_MUTS=300 \
    python bench.py --encode | tail -1)
printf '%s\n' "$enc_line" | python -c '
import json, sys
r = json.loads(sys.stdin.readline())
eb = r["encode_bench"]
assert eb["bytes_equal"], "cached and uncached serving bytes diverged"
assert eb["events_equal"], "cached/uncached watch event counts diverged"
# regression floor: the encode-once path measured ~7x combined at this
# shape when it landed; 3x leaves slack for slow CI hosts while still
# catching a lost cache or a reintroduced per-watcher re-encode
assert r["value"] >= 3.0, "encode-once speedup regressed: %sx < 3x floor" % r["value"]
print("encode smoke ok: %sx combined | %sx churned-list | %sx fan-out-encode"
      % (r["value"], eb["churn_list_speedup"], eb["fanout_encode_speedup"]))
'

echo "== admission: happy-path overhead + noisy-neighbor storm smoke"
# 1 tenant floods writes at 10x its token rate alongside quiet tenants:
# quiet p99 must stay within 2x of its no-storm baseline with ZERO quiet
# rejections, the flood must see 429 + Retry-After, and the chain's
# happy-path overhead on the serving path must stay under 5%
adm_line=$(KCP_BENCH_ADM_WRITES=3000 KCP_BENCH_ADM_TENANTS=40 \
    KCP_BENCH_ADM_STORM_S=2 python bench.py --admission | tail -1)
printf '%s\n' "$adm_line" | python -c '
import json, sys
r = json.loads(sys.stdin.readline())
st = r["admission_bench"]["storm"]
assert r["value"] < 5.0, "happy-path admission overhead %s%% >= 5%%" % r["value"]
assert st["quiet_rejected"] == 0, st
assert st["quiet_p99_ratio"] <= 2.0, st
assert st["flood_429"] > 0 and st["flood_retry_after_seen"], st
assert st["flood_ok"] < st["flood_sent"] // 2, "flood was not throttled: %s" % st
print("admission smoke ok: overhead %.2f%% (direct %.2f%%) | quiet p99 ratio"
      " %.2f | flood throttled %d/%d with Retry-After"
      % (r["value"], r["admission_bench"]["happy"]["direct_overhead_pct"],
         st["quiet_p99_ratio"], st["flood_429"], st["flood_sent"]))
'

echo "== sharded: 2-shard fleet smoke (capacity scaling, shard-kill drill)"
# real kcp subprocesses: 2 shards + a --role router frontend. Gates the
# shared-nothing capacity floor (time-sliced per-shard rates — honest on
# 1-core CI hosts; see docs/operations.md "Benchmarking"), the router's
# fail-fast 503 once the breaker trips on a SIGKILLed shard, the merged
# watch's terminal in-stream 410, and zero acked writes lost after the
# WAL-restored restart + relist catchup.
sh_line=$(KCP_BENCH_SHARD_FLEETS=1,2 KCP_BENCH_SHARD_SECONDS=1.5 \
    KCP_BENCH_SHARD_CLUSTERS=16 KCP_BENCH_SHARD_EVENTS=12 \
    python bench.py --sharded | tail -1)
printf '%s\n' "$sh_line" | python -c '
import json, sys
r = json.loads(sys.stdin.readline())
sb = r["sharded_bench"]
kill = sb["kill"]
cap = sb["capacity_speedup"]["2"]
# floor 1.6x: a skewed ring or cross-shard write traffic drags the
# shared-nothing capacity sum toward 1x; near-linear is ~2x
assert cap >= 1.6, "2-shard capacity speedup %sx < 1.6x floor" % cap
assert kill["watch_terminal_410"], "merged watch did not end with 410: %s" % kill
assert kill["failfast_ms"] < 1000, "breaker not failing fast: %s" % kill
assert kill["lost_after_catchup"] == 0, "lost writes after catchup: %s" % kill
print("sharded smoke ok: capacity %sx @2 shards (concurrent %sx on %s cpu)"
      " | kill: 410 in %sms, fail-fast %sms, %d acked / 0 lost"
      % (cap, sb["concurrent_speedup"]["2"], sb["host_cpus"],
         kill["watch_410_ms"], kill["failfast_ms"], kill["acked_writes"]))
'

echo "== smartclient: direct-vs-routed smoke (2-shard fleet, byte equality, ring-change drill)"
# smart clients compute the HRW owner from GET /ring and skip the
# router hop. Floors: direct single-cluster write CAPACITY (per-shard
# time slices summed — see docs/operations.md "Benchmarking") >=1.5x
# the one-router routed ceiling (the committed BENCH_r08 measured
# 3.7x @2 shards), routed and direct
# responses byte-identical, the scatter wire path sha256-identical to
# the join path, and the mid-bench ring-change drill (shard drains,
# restarts on a NEW port, /ring republishes, all under an injected
# router.proxy fault schedule) completing with zero lost acked writes
# and zero surfaced client errors — one-shot fallbacks absorb the move.
smart_line=$(KCP_BENCH_SMART_SECONDS=1.5 KCP_BENCH_SMART_CLUSTERS=8 \
    python bench.py --smartclient | tail -1)
printf '%s\n' "$smart_line" | python -c '
import json, sys
r = json.loads(sys.stdin.readline())
sb = r["smartclient_bench"]
ab, wire, drill = sb["ab"], sb["wire"], sb["ring_change_drill"]
assert r["value"] >= 1.5, "direct/routed capacity %sx < 1.5x floor" % r["value"]
assert ab["bytes_equal"], "routed vs direct responses diverged"
assert ab["direct_requests"] > 0, "smart client never went direct: %s" % ab
assert wire["identical"], "scatter wire path diverged from join path"
assert wire["spans_written"] > 0, "scatter path never exercised: %s" % wire
assert drill["lost_after_move"] == 0, "acked writes lost in ring change: %s" % drill
assert drill["errors_surfaced"] == 0, "client errors surfaced in drill: %s" % drill
assert drill["fallbacks"] >= 1 and drill["ring_epoch_after"] >= 2, drill
print("smartclient smoke ok: %sx direct/routed capacity (p99 %s->%sms) | bytes equal"
      " | wire scatter identical (%d spans, %d bytes join-free)"
      " | ring-change drill: %d acked / 0 lost, %d fallbacks, epoch %d"
      % (r["value"], ab["routed_p99_ms"], ab["direct_p99_ms"],
         wire["spans_written"], wire["join_avoided_bytes"],
         drill["acked_writes"], drill["fallbacks"],
         drill["ring_epoch_after"]))
'

echo "== elastic: live scale-out smoke (fleet doubles mid-workload, zero lost acked writes, capacity floor)"
# in-process fleet doubles 2->4 shards while smart + routed writers keep
# going: every moving cluster's WAL streams to its new owner behind a
# fence, the ring flips atomically per cluster, and the acked-write
# ledger must come back intact. Floors: post-move capacity >=1.2x the
# 2-shard baseline (the committed BENCH_r10_elastic.json measured 1.88x
# on this shape; 1.2x leaves slack for loaded CI hosts while still
# catching a migration that parks clusters or a ring that never flips),
# zero lost acked writes, zero surfaced client errors (fence 503s are
# absorbed by retry), and real migration traffic on the wire.
el_line=$(KCP_BENCH_ELASTIC_SECONDS=0.8 KCP_BENCH_ELASTIC_CLUSTERS=16 \
    python bench.py --elastic | tail -1)
printf '%s\n' "$el_line" | python -c '
import json, sys
r = json.loads(sys.stdin.readline())
eb = r["elastic_bench"]
mv = eb["during_move"]
assert r["value"] >= 1.2, "post-scale-out capacity %sx < 1.2x CI floor" % r["value"]
assert mv["lost_after_move"] == 0, "acked writes lost across scale-out: %s" % mv
assert mv["errors_surfaced"] == 0, "client errors surfaced during move: %s" % mv
assert mv["migrated_clusters"] >= 1 and mv["migration_records"] >= 1, mv
assert len(eb["per_shard_after"]) == eb["shards_after"], (
    "scaled-out ring left shards idle: %s" % eb["per_shard_after"])
print("elastic smoke ok: %sx capacity %d->%d shards | move %ss:"
      " %d acked / 0 lost, %d clusters / %d records migrated,"
      " %d fence 503s absorbed (epoch %d)"
      % (r["value"], eb["shards_before"], eb["shards_after"],
         mv["move_seconds"], mv["acked_writes"], mv["migrated_clusters"],
         mv["migration_records"], mv["fenced_write_503s"],
         mv["ring_epoch_after"]))
'

echo "== replica: HA replication smoke (read scaling, lag, kill-the-primary drill)"
# primary + 0/1/2 WAL-fed read replicas, then a durable primary+standby
# kill drill. Floors: read capacity >=1.5x at 2 replicas (each endpoint
# measured in its own time slice — honest on 1-core hosts; near-linear
# is ~3x), list bytes identical to the primary at the same RV (the
# encode-once differential), and ZERO acknowledged writes lost after
# the standby promotes.
repl_line=$(KCP_BENCH_REPL_OBJECTS=500 KCP_BENCH_REPL_SECONDS=0.8 \
    KCP_BENCH_REPL_LAG_WRITES=60 KCP_BENCH_REPL_DRILL_WRITES=40 \
    python bench.py --replica | tail -1)
printf '%s\n' "$repl_line" | python -c '
import json, sys
r = json.loads(sys.stdin.readline())
rb = r["replica_bench"]
assert rb["bytes_equal"], "replica list bytes diverged from primary at same RV"
assert r["value"] >= 1.5, "read capacity %sx < 1.5x floor at 2 replicas" % r["value"]
kill = rb["kill"]
assert kill["lost_after_promotion"] == 0, "acked writes lost: %s" % kill
assert kill["promoted_role"] == "primary" and kill["epoch"] >= 1, kill
print("replica smoke ok: %sx read capacity @2 | lag p99 %sms | kill: %d acked"
      " / 0 lost, promoted in %sms (epoch %d)"
      % (r["value"], rb["lag"].get("p99_ms"), kill["acked_writes"],
         kill["promote_ms"], kill["epoch"]))
'

echo "== consistent: RV-barrier consistent-read smoke (read-your-writes, replica-local share, capacity A/B)"
# 1 primary + lagged replicas (repl.ship delay active): every session
# read-your-write through the router must come back fresh (zero stale —
# the barrier parks the read until the replica applies the session
# floor), >=80% of those consistent reads must be served replica-local
# (parked, not fallen back to the primary), and consistent-read
# capacity at 2 replicas must hold >=1.5x the primary-only pin at
# matched freshness (each endpoint in its own time slice; near-linear
# is ~3x). Bytes stay sha256-identical to the primary at the same RV.
cons_line=$(KCP_BENCH_CONS_OBJECTS=500 KCP_BENCH_CONS_SECONDS=0.8 \
    KCP_BENCH_CONS_LAG_WRITES=60 KCP_BENCH_CONS_RYWR_STEPS=60 \
    python bench.py --consistent | tail -1)
printf '%s\n' "$cons_line" | python -c '
import json, sys
r = json.loads(sys.stdin.readline())
cb = r["consistent_bench"]
assert cb["bytes_equal"], "consistent replica bytes diverged at same RV"
rw = cb["read_your_writes"]
assert rw["stale"] == 0, "stale read-your-writes: %s" % rw
share = rw["replica_local_share"]
assert share >= 0.8, "replica-local share %s < 0.8 floor" % share
assert r["value"] >= 1.5, (
    "consistent read capacity %sx < 1.5x floor at 2 replicas" % r["value"])
w = cb["wait_for_frontier"]
print("consistent smoke ok: %sx capacity @2 | rywr %d/%d fresh,"
      " %.0f%% replica-local | frontier wait p50 %sms p99 %sms"
      % (r["value"], rw["reads"] - rw["stale"], rw["reads"],
         share * 100, w["p50_ms"], w["p99_ms"]))
'

echo "== writes: group-commit A/B smoke (write-path speedup floor, state equality, kill-mid-window drill)"
# serial vs grouped under KCP_WAL_SYNC=fsync: the write-path component
# (store commit + WAL sync, the thing the commit window batches) must
# hold >=2x at 64 concurrent writers on a loaded CI host (the committed
# BENCH_r09_writes.json gate is 3x), grouped/serial state + RV sequences
# must match, and the kill-mid-window drill must lose zero acked writes
# with commit windows + batched standby acks actually moving.
wr_line=$(KCP_BENCH_WRITES_SECONDS=0.6 KCP_BENCH_WRITES_CONC=1,64 \
    KCP_BENCH_WRITES_EQ_OPS=150 KCP_BENCH_WRITES_STORE_OPS=120 \
    python bench.py --writes | tail -1)
printf '%s\n' "$wr_line" | python -c '
import json, sys
r = json.loads(sys.stdin.readline())
wb = r["writes_bench"]
drill = wb["kill_drill"]
assert r["value"] >= 2.0, "write-path speedup %sx < 2x CI floor at 64 writers" % r["value"]
assert wb["state_equal"], "grouped vs serial final state diverged"
assert wb["rv_sequence_equal"], "grouped vs serial RV sequences diverged"
assert drill["ok"], "kill-mid-window drill failed: %s" % drill
assert drill["lost_after_kill"] == 0, drill
assert drill["commit_windows"] > 0 and drill["acks_batched"] > 0, drill
print("writes smoke ok: %sx write-path @64 (http end-to-end %sx) | p99@1 %s->%sms"
      " | state equal | drill: %d acked / 0 lost, %d windows, %d batched acks"
      % (r["value"], wb["end_to_end_http"]["speedup_at_top"],
         wb["p99_1_writer_ms"]["serial"], wb["p99_1_writer_ms"]["grouped"],
         drill["acked_writes"], drill["commit_windows"], drill["acks_batched"]))
'

echo "== watchers: 1k-stream watcher-scale smoke (bounded RSS, delivery floor, flush A/B, evict drill)"
# reduced-scale --watchers lane: the server runs in its own child process
# (fd budget), 1k live streams at 10k objects. Floors: every stream
# established, bounded per-watcher memory with a soak plateau, a delivery
# p99 ceiling generous enough for loaded CI hosts, the flush-coalescing
# A/B byte-identical with a >=4x reduction (13x at the full-scale default
# tick on an idle host), and the slow-watcher eviction drill green.
w_line=$(KCP_BENCH_WATCHERS=1000 KCP_BENCH_WATCH_OBJECTS=10000 \
    KCP_BENCH_WATCH_CLUSTERS=20 KCP_BENCH_WATCH_MUTS=400 \
    KCP_BENCH_WATCH_AB=48 KCP_BENCH_WATCH_AB_MUTS=300 \
    python bench.py --watchers | tail -1)
printf '%s\n' "$w_line" | python -c '
import json, sys
r = json.loads(sys.stdin.readline())
wb = r["watchers_bench"]
sc, ab, drill = wb["scale"], wb["ab"], wb["evict_drill"]
assert sc["streams_established"] == sc["watchers"], sc
assert sc["rss_per_watcher_kb"] < 100, "per-watcher RSS %s kb" % sc["rss_per_watcher_kb"]
assert sc["rss_soak_growth"] < 1.15, "RSS grew under soak: %s" % sc["rss_soak_growth"]
assert sc["delivery_p99_ms"] is not None and sc["delivery_p99_ms"] < 3000, sc
assert ab["bytes_equal"] and ab["lines_equal"], "A/B streams diverged: %s" % ab
assert r["value"] >= 4.0, "flush reduction %sx < 4x floor" % r["value"]
assert drill["ok"], "evict drill failed: %s" % drill
print("watchers smoke ok: %d streams | p99 %sms | %s kb/watcher (soak %s)"
      " | flush A/B %sx byte-identical | evict drill green"
      % (sc["streams_established"], sc["delivery_p99_ms"],
         sc["rss_per_watcher_kb"], sc["rss_soak_growth"], r["value"]))
'

echo "== trace: distributed-tracing smoke (off-path overhead floor, wire neutrality, assembled convergence trace)"
# reduced-scale --trace lane: paired-block A/B of the serving and
# fan-out hot paths across KCP_TRACE=0 / default 1-in-64 / always-on
# (CI floor 5%; the committed BENCH_r07_trace.json gate is 3%),
# byte-identical wires across all three modes, and a router + 2-shard +
# standby convergence trace whose per-phase durations sum-reconcile
# (±5%) with the measured spec→status wall time.
tr_line=$(KCP_BENCH_TRACE_OBJECTS=1500 KCP_BENCH_TRACE_REQS=320 \
    KCP_BENCH_TRACE_WATCHES=24 KCP_BENCH_TRACE_MUTS=240 \
    KCP_BENCH_TRACE_CONV=2 python bench.py --trace | tail -1)
printf '%s\n' "$tr_line" | python -c '
import json, sys
r = json.loads(sys.stdin.readline())
tb = r["trace_bench"]
assert tb["bytes_equal"], "wire bytes diverged under tracing"
assert r["value"] < 5.0, "p50 overhead %s%% >= 5%% CI floor at default sampling" % r["value"]
conv = tb["convergence"]
assert conv["all_sum_ok"], conv["sum_reconciles"]
need = {"write", "stage", "tick", "patch", "downstream", "upstatus"}
assert need <= set(conv["phases_seen"]), conv["phases_seen"]
names = set(conv["traces"][0]["names"])
for s in ("server.request", "router.relay", "store.commit", "repl.ack", "repl.apply"):
    assert s in names, (s, sorted(names))
print("trace smoke ok: overhead %.2f%% | bytes equal | %d convergence traces sum-reconcile | %d span kinds"
      % (r["value"], conv["runs"], len(names)))
'

echo "== trace: crud-churn scenario under always-on tracing (scorecard carries assembled traces)"
# the scenario engine attaches the slowest assembled traces per phase
# to the scorecard: assert at least one fully-assembled write trace
# (driver conv.write + server span + store commit + fan-out) rode along
KCP_TRACE=1 KCP_TRACE_SAMPLE=1 JAX_PLATFORMS=cpu python scripts/scenarios.py run \
    --scenarios crud-churn --seed 7 --scale 0.25 --out SCENARIOS_trace_smoke.json
python -c '
import json
r = json.load(open("SCENARIOS_trace_smoke.json"))
s = r["scenarios"][0]
assert s["passed"], s["slos"]
traces = s.get("traces") or {}
attached = [t for ph in traces.values() for t in ph]
assert attached, "no traces attached to the scorecard"
names = set()
for t in attached:
    names.update(t.get("names", []))
for need in ("conv.write", "server.request", "store.commit", "store.fanout"):
    assert need in names, (need, sorted(names))
print("scenario trace smoke ok: %d attached traces across %d phases; %d distinct span names"
      % (len(attached), len(traces), len(names)))
'

echo "== scenarios: seeded end-to-end chaos smoke (churn + reconnect storm + kill-the-primary drill)"
# reduced-scale subset of the scenario harness (scripts/scenarios.py):
# real topologies over real HTTP, hard SLO floors (zero lost acked
# writes, zero lost watch events, convergence bounds, failover
# re-homing) asserted by the engine itself — exit 1 on any miss. The
# scorecard JSON persists as a build artifact alongside the BENCH_*
# files; the full catalog (incl. rolling-restart drain-vs-kill) runs
# via `scripts/scenarios.py run --all --seed 42`.
JAX_PLATFORMS=cpu python scripts/scenarios.py run \
    --scenarios crud-churn,reconnect-storm,kill-primary,ring-change-under-load,scale-out-under-load,partition-during-promotion \
    --seed 42 --scale 0.4 --out SCENARIOS_smoke.json
python -c '
import json
r = json.load(open("SCENARIOS_smoke.json"))
assert r["passed"], "scenario smoke failed"
for s in r["scenarios"]:
    miss = [row["name"] for row in s["slos"] if not row["passed"]]
    assert not miss, (s["name"], miss)
print("scenario smoke ok:", {s["name"]: s["schedule"]["hash"] for s in r["scenarios"]})
'

echo "== pagination: paged-vs-unpaged relist A/B (bytes identical, bounded peak)"
# reduced-scale --pagination lane: limit/continue pages through the
# real handler must concatenate byte-identically (sha256) to the
# one-shot body at the same RV, and cut peak relist allocation >=4x
# at 10k objects (the committed full-scale A/B floor is 5x at 100k)
pag_line=$(KCP_BENCH_PAG_OBJECTS=10000 KCP_BENCH_PAG_PAGE=1000 \
    python bench.py --pagination | tail -1)
printf '%s\n' "$pag_line" | python -c '
import json, sys
r = json.loads(sys.stdin.readline())
pb = r["pagination_bench"]
assert pb["bytes_equal"], "concatenated pages != one-shot body"
assert pb["rv_equal"], "paged rv pin diverged from one-shot rv"
assert r["value"] >= 4.0, "peak cut %sx < 4x CI floor" % r["value"]
print("pagination smoke ok: %d pages | bytes equal | peak cut %.2fx (%d KB -> %d KB)"
      % (pb["pages"], r["value"], pb["unpaged_peak_kb"], pb["paged_peak_kb"]))
'

echo "== gauntlet: composed BASELINE-shape smoke (1 config, 1/50th scale)"
# one gauntlet config end to end at CI scale: the demo-fleet shape (200
# clusters at 1/50th of the 10k-workspace config, ~2k acked objects)
# with smart-client writers — floors on zero loss and a real
# reconciles/sec number, plus the embedded relist A/B staying byte-equal
gl_line=$(KCP_GAUNTLET_CONFIGS=2 KCP_GAUNTLET_SCALE=50 KCP_GAUNTLET_OPS=10 \
    KCP_BENCH_PAG_OBJECTS=2000 KCP_BENCH_PAG_PAGE=250 \
    python bench.py --gauntlet | tail -1)
printf '%s\n' "$gl_line" | python -c '
import json, sys
r = json.loads(sys.stdin.readline())
rows = r["rows"]
assert rows, "gauntlet emitted no scorecard rows"
for row in rows:
    assert row.get("passed"), (row.get("name"), row.get("slos"), row.get("error"))
    assert row.get("lost_acked_writes") == 0, row
    assert (row.get("reconciles_per_sec") or 0) > 20, row
assert r["relist"]["bytes_equal"], "gauntlet relist A/B bytes diverged"
print("gauntlet smoke ok: %s | %.0f acked/s | conv p99 %.1fms | rss growth %.3f"
      % (rows[0]["name"], rows[0]["reconciles_per_sec"],
         rows[0]["convergence_p99_ms"], rows[0]["memory_growth_ratio"]))
'

if [[ "$fast" == "0" ]]; then
    echo "== demo: both golden scenarios, checked against committed output"
    python contrib/demo/run_demo.py all --check

    echo "== dryrun: full serving step jit + one tick on a virtual 8-device mesh"
    XLA_FLAGS="--xla_force_host_platform_device_count=8" \
        python -c "from __graft_entry__ import dryrun_multichip; dryrun_multichip(8)"
fi

echo "CI OK"
