#!/usr/bin/env python3
"""tracetool — pretty-print assembled traces and diff phase profiles.

Reads either a JSON file (the body of ``GET /debug/trace?id=`` /
``?slowest=N``, e.g. saved with curl) or fetches one live from a server
URL. On a router the endpoint scatter-gathers every shard's and
replica's span buffer, so the tree spans processes.

Usage::

    # pretty-print one trace (file or live endpoint)
    python scripts/tracetool.py tree trace.json
    python scripts/tracetool.py tree http://127.0.0.1:6443 --id <trace-id>
    python scripts/tracetool.py tree http://127.0.0.1:6443 --slowest 3

    # the convergence phase breakdown of one trace
    python scripts/tracetool.py profile trace.json

    # per-phase delta between two saved profiles (regression triage:
    # "convergence p99 regressed — which phase grew?")
    python scripts/tracetool.py diff before.json after.json
"""

from __future__ import annotations

import argparse
import json
import sys

sys.path.insert(0, __file__.rsplit("/", 2)[0])

from kcp_tpu.obs import assemble  # noqa: E402


def _load(source: str, trace_id: str | None, slowest: int) -> dict:
    if source.startswith("http://") or source.startswith("https://"):
        from kcp_tpu.server.rest import RestClient

        q = f"id={trace_id}" if trace_id else f"slowest={slowest}"
        client = RestClient(source)
        try:
            return client._request("GET", f"/debug/trace?{q}") or {}
        finally:
            client.close()
    with open(source, encoding="utf-8") as fh:
        return json.load(fh)


def _span_lists(doc: dict) -> list[tuple[str, list[dict]]]:
    """(trace id, spans) groups from either endpoint shape."""
    if "spans" in doc:
        return [(doc.get("id", "?"), doc["spans"])]
    return [(t.get("id", "?"), t.get("spans", []))
            for t in doc.get("traces", [])]


def cmd_tree(args: argparse.Namespace) -> int:
    doc = _load(args.source, args.id, args.slowest)
    for partial in doc.get("partial") or []:
        print(f"# partial assembly: {partial}", file=sys.stderr)
    for tid, spans in _span_lists(doc):
        if not spans:
            print(f"trace {tid}: no spans buffered")
            continue
        print(f"trace {tid} ({len(spans)} spans):")
        print(assemble.render_tree(spans))
        prof = assemble.phase_profile(spans)
        if prof.get("phases"):
            print("  phases: " + json.dumps(prof))
        print()
    return 0


def cmd_profile(args: argparse.Namespace) -> int:
    doc = _load(args.source, args.id, args.slowest)
    groups = _span_lists(doc)
    if not groups:
        print("no traces", file=sys.stderr)
        return 1
    tid, spans = groups[0]
    prof = assemble.phase_profile(spans)
    prof["id"] = tid
    print(json.dumps(prof, indent=2))
    return 0


def cmd_diff(args: argparse.Namespace) -> int:
    with open(args.a, encoding="utf-8") as fh:
        a = json.load(fh)
    with open(args.b, encoding="utf-8") as fh:
        b = json.load(fh)
    rows = assemble.diff_profiles(a, b)
    if not rows:
        print("no comparable phases", file=sys.stderr)
        return 1
    print(f"{'phase':<12} {'a (ms)':>10} {'b (ms)':>10} {'delta (ms)':>12}")
    for r in rows:
        fa = "-" if r["a"] is None else f"{r['a'] * 1000:.3f}"
        fb = "-" if r["b"] is None else f"{r['b'] * 1000:.3f}"
        print(f"{r['phase']:<12} {fa:>10} {fb:>10} "
              f"{r['delta'] * 1000:>+12.3f}")
    return 0


def main() -> int:
    p = argparse.ArgumentParser(description=__doc__,
                                formatter_class=argparse.RawDescriptionHelpFormatter)
    sub = p.add_subparsers(dest="cmd", required=True)
    for name, fn in (("tree", cmd_tree), ("profile", cmd_profile)):
        sp = sub.add_parser(name)
        sp.add_argument("source", help="JSON file or server base URL")
        sp.add_argument("--id", default=None, help="trace id (URL mode)")
        sp.add_argument("--slowest", type=int, default=3)
        sp.set_defaults(fn=fn)
    sp = sub.add_parser("diff")
    sp.add_argument("a", help="baseline phase-profile JSON")
    sp.add_argument("b", help="comparison phase-profile JSON")
    sp.set_defaults(fn=cmd_diff)
    args = p.parse_args()
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
