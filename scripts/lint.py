#!/usr/bin/env python
"""kcp-lint CLI — contract-aware static analysis for this repo.

Usage:
    python scripts/lint.py                      # all checkers, text output
    python scripts/lint.py --format json        # machine-readable (CI)
    python scripts/lint.py --rules cow-mutation,frozen-bytes
    python scripts/lint.py kcp_tpu/store        # lint a subtree only

Exit status: 0 = no active findings (waived ones never fail), 1 = at
least one finding. Waive a legitimate write-boundary site by appending a
comment ``kcp-lint: disable=<rule> -- <justification>`` to the flagged
line; waivers without justification are themselves findings.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from kcp_tpu.analysis.runner import main  # noqa: E402

if __name__ == "__main__":
    sys.exit(main())
