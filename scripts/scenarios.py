#!/usr/bin/env python3
"""Run the scenario harness and emit one JSON scorecard.

Usage:
    python scripts/scenarios.py list
    python scripts/scenarios.py run --all --seed 42 [--scale 1.0]
        [--out SCENARIOS.json]
    python scripts/scenarios.py run --scenarios crud-churn,kill-primary \
        --seed 7 --scale 0.3

Every run is seeded and replayable: the scorecard carries each
scenario's schedule hash — a second run with the same seed reproduces
the same op/fault schedule bit for bit (the determinism the engine's
tests assert). Exit status 1 when any scenario misses a declared SLO.

``--scale`` shrinks tenant counts and op volumes for CI smokes; SLO
targets never scale (docs/operations.md "Scenario harness runbook").
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def main(argv=None) -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    try:
        # watcher-scale scenarios hold one fd per live stream: lift the
        # soft nofile limit to the hard cap before the storm starts
        import resource

        _soft, hard = resource.getrlimit(resource.RLIMIT_NOFILE)
        resource.setrlimit(resource.RLIMIT_NOFILE, (hard, hard))
    except (ImportError, ValueError, OSError):
        pass
    p = argparse.ArgumentParser(description=__doc__)
    sub = p.add_subparsers(dest="command", required=True)
    sub.add_parser("list", help="list the named scenarios")
    run = sub.add_parser("run", help="run scenarios, emit a scorecard")
    run.add_argument("--all", action="store_true",
                     help="run every scenario in the catalog")
    run.add_argument("--scenarios", default="",
                     help="comma-separated scenario names")
    run.add_argument("--seed", type=int, default=42)
    run.add_argument("--scale", type=float, default=1.0,
                     help="tenant/op scale factor (SLO targets do NOT "
                          "scale)")
    run.add_argument("--out", default="",
                     help="scorecard JSON path (default: stdout only)")
    run.add_argument("--workdir", default="",
                     help="server root dirs (default: a fresh tempdir)")
    args = p.parse_args(argv)

    from kcp_tpu.scenarios import SCENARIOS, run_scenario

    if args.command == "list":
        for name, spec in SCENARIOS.items():
            print(f"{name:18s} [{spec.topology}] {spec.description}")
        return 0

    if args.all:
        names = list(SCENARIOS)
    else:
        names = [n for n in args.scenarios.split(",") if n]
    if not names:
        p.error("run needs --all or --scenarios a,b,c")
    unknown = [n for n in names if n not in SCENARIOS]
    if unknown:
        p.error(f"unknown scenario(s) {unknown}; "
                f"known: {sorted(SCENARIOS)}")

    workdir = args.workdir or tempfile.mkdtemp(prefix="kcp-scenarios-")
    t0 = time.time()
    results = []
    for name in names:
        print(f"== scenario: {name} (seed={args.seed} "
              f"scale={args.scale})", flush=True)
        r = run_scenario(SCENARIOS[name], seed=args.seed,
                         scale=args.scale, workdir=workdir)
        results.append(r)
        verdict = "PASS" if r["passed"] else "FAIL"
        print(f"   {verdict} in {r.get('measurements', {}).get('duration_s', '?')}s "
              f"schedule={r['schedule']['hash']}", flush=True)
        for row in r["slos"]:
            mark = "ok " if row["passed"] else "MISS"
            print(f"   [{mark}] {row['name']}: {row['metric']} "
                  f"{row['op']} {row['target']} "
                  f"(observed {row['observed']})", flush=True)
        if r.get("drain_bypassed"):
            print(f"   drain bypassed (kill): {r['drain_bypassed']}",
                  flush=True)

    scorecard = {
        "kind": "ScenarioScorecard",
        "seed": args.seed,
        "scale": args.scale,
        "duration_s": round(time.time() - t0, 2),
        "passed": all(r["passed"] for r in results),
        "scenarios": results,
    }
    out = json.dumps(scorecard, indent=2, sort_keys=False)
    if args.out:
        with open(args.out, "w", encoding="utf-8") as f:
            f.write(out + "\n")
        print(f"scorecard written to {args.out}")
    print(json.dumps({"passed": scorecard["passed"],
                      "scenarios": {r["name"]: r["passed"]
                                    for r in results}}))
    return 0 if scorecard["passed"] else 1


if __name__ == "__main__":
    sys.exit(main())
