#!/usr/bin/env python3
"""walreplay — deterministic offline WAL replay to a target RV.

The WAL is a total order of every mutation, so replaying it to RV ``N``
reconstructs the store exactly as it was at that RV — the time-travel
debugging half of the replication story (the other half, a follower
replaying to the tip, is ``kcp_tpu/replication/``), and the recovery
story for quarantine/evacuation forensics: "what did the fleet look
like right before the bad write?"

Reads both on-disk formats without the server (or the native library):

- the native binary engine (``native/walstore.cc``): ``KCPWAL1\\n`` magic
  then ``[u32 len][u32 crc32][payload]`` records, payload =
  ``u8 op | u64 rv | u32 klen | u32 vlen | key | val`` (op 1 put, 2 del,
  3 meta/rv-watermark, 4 epoch) — parsed in pure Python here, torn
  tails tolerated exactly like the engine's replay;
- the JSON-lines fallback (``kcp_tpu/store/store.py``): one record dict
  per line, plus the ``.snap`` snapshot.

A snapshot compacts history away: replay can only travel back to the
snapshot's RV watermark (the tool says so rather than guessing).

Usage:
    python scripts/walreplay.py <root-dir-or-wal-path> [--rv N]
        [--dump] [--keys] [--json] [--cluster C] [--emit-ndjson]

    --rv N         stop applying records with rv > N (default: the tip)
    --dump         print every object (key -> JSON) at the target RV
    --keys         print just the keys at the target RV
    --json         machine-readable one-line summary
    --cluster C    restrict the reconstructed state to one logical
                   cluster (the second key segment)
    --emit-ndjson  print the reconstructed state as WAL-shaped put
                   records (``{"op":"put","key":[...],"obj":{...}}``),
                   one per line, instead of the summary — byte-for-byte
                   the records a live migration streams off the fenced
                   source's filtered feed, so this is BOTH the offline
                   migration path (pipe to a shard's POST
                   /migration/ingest) and the transport oracle the
                   migration tests diff against
"""

from __future__ import annotations

import argparse
import json
import os
import struct
import sys
import zlib

MAGIC = b"KCPWAL1\n"
OP_PUT, OP_DEL, OP_META, OP_EPOCH = 1, 2, 3, 4


class ReplayState:
    def __init__(self) -> None:
        self.objects: dict[bytes, bytes] = {}
        self.rv = 0
        self.epoch = 0
        self.applied = 0
        self.skipped_beyond_target = 0
        self.floor_rv = 0  # snapshot watermark: can't travel before this
        self.torn_bytes = 0


def _iter_native_records(buf: bytes):
    """Yield (op, rv, key, val, end_offset); stops at the first torn or
    corrupt record (the engine's truncate-on-replay discipline)."""
    off = len(MAGIC) if buf.startswith(MAGIC) else 0
    while off + 8 <= len(buf):
        length, crc = struct.unpack_from("<II", buf, off)
        if off + 8 + length > len(buf):
            return
        payload = buf[off + 8:off + 8 + length]
        if zlib.crc32(payload) & 0xFFFFFFFF != crc:
            return
        if length < 17:
            return
        op = payload[0]
        rv, klen, vlen = struct.unpack_from("<QII", payload, 1)
        if 17 + klen + vlen != length:
            return
        key = payload[17:17 + klen]
        val = payload[17 + klen:17 + klen + vlen]
        off += 8 + length
        yield op, rv, key, val, off


def _replay_native_file(path: str, st: ReplayState, target: int | None,
                        is_snapshot: bool) -> None:
    try:
        with open(path, "rb") as f:
            buf = f.read()
    except OSError:
        return
    end = len(MAGIC) if buf.startswith(MAGIC) else 0
    for op, rv, key, val, end in _iter_native_records(buf):
        if op == OP_EPOCH and len(val) == 8:
            st.epoch = max(st.epoch, struct.unpack("<Q", val)[0])
            continue
        if op == OP_META:
            # snapshot watermark (or rv stamp): replay cannot travel
            # below a snapshot's watermark — history before it is gone
            if is_snapshot:
                st.floor_rv = max(st.floor_rv, rv)
            st.rv = max(st.rv, rv)
            continue
        if target is not None and not is_snapshot and rv > target:
            st.skipped_beyond_target += 1
            continue
        if op == OP_PUT:
            st.objects[bytes(key)] = bytes(val)
        elif op == OP_DEL:
            st.objects.pop(bytes(key), None)
        st.rv = max(st.rv, rv)
        st.applied += 1
    st.torn_bytes += len(buf) - end


def _replay_json(path: str, st: ReplayState, target: int | None) -> None:
    snap = path + ".snap"
    if os.path.exists(snap):
        with open(snap, encoding="utf-8") as f:
            data = json.load(f)
        st.floor_rv = max(st.floor_rv, int(data.get("rv", 0)))
        st.rv = max(st.rv, int(data.get("rv", 0)))
        st.epoch = max(st.epoch, int(data.get("epoch", 0)))
        for rec in data.get("objects", []):
            st.objects["\x00".join(rec["key"]).encode()] = json.dumps(
                rec["obj"], separators=(",", ":")).encode()
    if not os.path.exists(path):
        return
    with open(path, "rb") as f:
        raw = f.read()
    pos = 0
    while pos < len(raw):
        nl = raw.find(b"\n", pos)
        chunk = raw[pos:nl] if nl >= 0 else raw[pos:]
        nxt = nl + 1 if nl >= 0 else len(raw)
        if chunk.strip():
            try:
                rec = json.loads(chunk)
                op = rec.get("op")
            except ValueError:
                st.torn_bytes += len(raw) - pos
                return
            if op == "epoch":
                st.epoch = max(st.epoch, int(rec.get("epoch", 0)))
            else:
                rv = int(rec.get("rv", 0))
                if target is not None and rv > target:
                    st.skipped_beyond_target += 1
                else:
                    key = "\x00".join(rec["key"]).encode()
                    if op == "put":
                        st.objects[key] = json.dumps(
                            rec["obj"], separators=(",", ":")).encode()
                    elif op == "del":
                        st.objects.pop(key, None)
                    st.rv = max(st.rv, rv)
                    st.applied += 1
        pos = nxt


def replay(path: str, target: int | None = None) -> ReplayState:
    """Replay a WAL (auto-detecting format) up to ``target`` RV."""
    st = ReplayState()
    head = b""
    for candidate in (path, path + ".snap"):
        try:
            with open(candidate, "rb") as f:
                head = f.read(len(MAGIC))
            if head:
                break
        except OSError:
            continue
    if head == MAGIC or (head and not head.lstrip().startswith(b"{")):
        # native: the snapshot's records first, then the live WAL tail
        _replay_native_file(path + ".snap", st, target, is_snapshot=True)
        _replay_native_file(path, st, target, is_snapshot=False)
    else:
        _replay_json(path, st, target)
    return st


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        description="deterministic offline WAL replay to a target RV")
    ap.add_argument("path", help="a server --root-dir or a store.wal path")
    ap.add_argument("--rv", type=int, default=None,
                    help="target resourceVersion (default: the tip)")
    ap.add_argument("--dump", action="store_true",
                    help="print every object at the target RV")
    ap.add_argument("--keys", action="store_true",
                    help="print just the keys at the target RV")
    ap.add_argument("--json", action="store_true",
                    help="one-line machine-readable summary")
    ap.add_argument("--cluster", default=None,
                    help="restrict the reconstructed state to one "
                         "logical cluster (second key segment)")
    ap.add_argument("--emit-ndjson", action="store_true",
                    help="emit WAL-shaped put records (ndjson) for the "
                         "reconstructed state — pipeable to a shard's "
                         "POST /migration/ingest")
    args = ap.parse_args(argv)

    path = args.path
    if os.path.isdir(path):
        path = os.path.join(path, "store.wal")
    if not (os.path.exists(path) or os.path.exists(path + ".snap")):
        print(f"no WAL at {path}", file=sys.stderr)
        return 1
    st = replay(path, args.rv)
    if args.rv is not None and st.floor_rv > args.rv:
        print(f"warning: a snapshot compacted history up to rv "
              f"{st.floor_rv}; the earliest reachable state is rv "
              f"{st.floor_rv}, not {args.rv}", file=sys.stderr)
    if args.cluster is not None:
        # key layout: resource \x00 cluster \x00 namespace \x00 name —
        # a cluster filter keeps exactly the keys a live migration moves
        want = args.cluster.encode()
        st.objects = {k: v for k, v in st.objects.items()
                      if k.split(b"\x00")[1:2] == [want]}
    if args.emit_ndjson:
        # transport-oracle output: identical record shape to the fenced
        # source's filtered feed (SNAP -> {"op":"put",...}); stdout is
        # ONLY records so the stream pipes clean into /migration/ingest.
        # Records are written in bounded batches (mirroring the feed's
        # 256-line spans): one buffered write per batch instead of one
        # syscall per record, and never a whole-cluster join — a large
        # cluster streams at flat memory. Record bytes are unchanged.
        batch: list[str] = []
        out = sys.stdout
        for key in sorted(st.objects):
            parts = key.decode("utf-8", "replace").split("\x00")
            try:
                obj = json.loads(st.objects[key])
            except ValueError:
                print(f"skipping non-JSON value at {'/'.join(parts)}",
                      file=sys.stderr)
                continue
            batch.append(json.dumps({"op": "put", "key": parts, "obj": obj},
                                    separators=(",", ":")) + "\n")
            if len(batch) >= 256:
                out.write("".join(batch))
                batch = []
        if batch:
            out.write("".join(batch))
        out.flush()
        return 0
    summary = {
        "wal": path,
        "target_rv": args.rv,
        "rv": st.rv,
        "epoch": st.epoch,
        "objects": len(st.objects),
        "records_applied": st.applied,
        "records_beyond_target": st.skipped_beyond_target,
        "snapshot_floor_rv": st.floor_rv,
        "torn_bytes": st.torn_bytes,
    }
    if args.cluster is not None:
        summary["cluster"] = args.cluster
    if args.json:
        print(json.dumps(summary))
    else:
        for k, v in summary.items():
            print(f"{k}: {v}")
    if args.keys or args.dump:
        for key in sorted(st.objects):
            parts = key.decode("utf-8", "replace").split("\x00")
            if args.dump:
                print("/".join(parts), st.objects[key].decode("utf-8",
                                                              "replace"))
            else:
                print("/".join(parts))
    return 0


if __name__ == "__main__":
    sys.exit(main())
