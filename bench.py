#!/usr/bin/env python
"""North-star benchmark: reconciles/sec across 10k logical clusters.

Drives the SERVING engine, not an emulation: a
:class:`kcp_tpu.syncer.core.FusedCore` — the same BatchController tick
loop, packed-wire fused ``reconcile_step``, pipelined collection, and
patch dispatch that ``BatchSyncEngine`` serves through — with a synthetic
section owner standing in for the informer caches and the store applier.
At BASELINE.json scale: 10k logical clusters x 13 objects = 131,072
resident rows, 64 slots.

The loop is a real closed control loop:

  churn     — every core tick, CHURN random rows get new upstream specs
              (the informer event stream), enqueued key-by-key through
              the serving work queue
  reconcile — the core's tick drains the queue, stages the rows, and
              runs the fused step over ALL rows; the compact patch set
              pipelines back (copy_to_host_async, collected a tick later)
  apply     — the owner's ``fused_apply`` (the applier-pool seam) copies
              upstream -> downstream per patch row and enqueues the sync
              feedback, which rides a later tick's scatter — rows
              actually converge, exactly like the reference's
              upsertIntoDownstream (pkg/syncer/specsyncer.go:86-132)

A "reconcile" = one object row fully re-decided in a tick (the unit the
reference spends a goroutine wakeup on, pkg/syncer/syncer.go:227-244).

Convergence is sampled per patch batch: from the latest churn stamp of
its rows to the second dispatch after the batch's sync feedback was
enqueued — by then the tick that scattered the feedback has had its own
wire collected, so the sample is proven against device data, not host
bookkeeping. p99 reports against BASELINE.json's < 200 ms target.

Not measured here (the host json-encode path): the per-object dict ->
tensor encode runs in `BatchSyncEngine.fused_encode` in production; the
suite's schema-hash lane and tests/test_native.py cover it.

Prints exactly one JSON line:
    {"metric": "reconciles_per_sec", "value": ..., "unit": "rows/s",
     "vs_baseline": value / 1e6}
(vs_baseline > 1.0 beats the BASELINE.json target of 1M reconciles/s —
a target set for a v5e-8; this harness uses ONE chip.)
"""

from __future__ import annotations

import asyncio
import json
import sys
import time

import numpy as np


class _BenchOwner:
    """Synthetic SectionOwner: mirror arrays instead of informer caches,
    mirror copies instead of store writes. Everything between — queue,
    staging, fused step, pipeline, dispatch — is the serving code."""

    def __init__(self, core, b: int, s: int, seed: int = 7):
        self.core = core
        self.B, self.S = b, s
        self.rng = np.random.default_rng(seed)
        # status slots: the top s//8 columns, as example_state lays out
        mask = np.zeros(s, bool)
        mask[-max(1, s // 8):] = True
        self._mask = mask
        self.section = core.register(self, s)
        bucket = self.section.bucket
        for i in range(b):
            self.section.row_for(i)
        bucket.up_vals[:b] = self.rng.integers(1, 2**32, (b, s), dtype=np.uint32)
        bucket.down_vals[:b] = bucket.up_vals[:b]
        flip = self.rng.random(b) < 0.005
        bucket.down_vals[:b][flip, :1] ^= 1
        bucket.up_exists[:b] = True
        bucket.down_exists[:b] = True
        bucket.mark_stale()
        self.bucket = bucket
        self.t_create = np.full(b, time.perf_counter())
        self.dispatches = 0
        self.lat_ms: list[float] = []
        self.patch_rows = 0
        # (sample_at_dispatch, t_create snapshot) awaiting scatter proof
        self._awaiting: list[tuple[int, np.ndarray]] = []

    # --------------------------------------------- SectionOwner interface

    def fused_status_mask(self) -> np.ndarray:
        return self._mask

    def fused_encode(self, key: int):
        b = self.bucket
        return b.up_vals[key], True, b.down_vals[key], True

    def fused_overflow(self) -> None:  # pragma: no cover - fixed vocab
        raise AssertionError("bench vocabulary never grows")

    def fused_apply(self, patches) -> None:
        """The applier seam: sync each patch row downstream and enqueue
        the feedback event; close out convergence samples proven by this
        dispatch."""
        self.dispatches += 1
        now = time.perf_counter()
        while self._awaiting and self._awaiting[0][0] <= self.dispatches:
            _, created = self._awaiting.pop(0)
            self.lat_ms.extend((now - created) * 1e3)
        rows = np.fromiter((k for k, _c, _u in patches), np.int32, len(patches))
        self.patch_rows += rows.size
        self.bucket.down_vals[rows] = self.bucket.up_vals[rows]
        # sample two dispatches out: by then the tick that scattered this
        # feedback has itself been collected (FIFO pipeline, depth 1)
        self._awaiting.append((self.dispatches + 2, self.t_create[rows].copy()))
        enqueue = self.core.enqueue
        section = self.section
        for k in rows.tolist():
            enqueue(section, True, k)

    # ------------------------------------------------------------- churn

    def emit_churn(self, n: int) -> None:
        rows = self.rng.choice(self.B, size=n, replace=False)
        self.bucket.up_vals[rows] = self.rng.integers(
            1, 2**32, (n, self.S), dtype=np.uint32)
        self.t_create[rows] = time.perf_counter()
        enqueue = self.core.enqueue
        section = self.section
        for k in rows.tolist():
            enqueue(section, False, k)


def main() -> int:
    import jax

    from kcp_tpu.syncer.core import FusedCore

    TENANTS = 10_000
    B = 131_072  # ~13 objects per logical cluster, pow2-padded
    S = 64
    CHURN = 768  # new upstream-spec events per tick
    WARMUP_TICKS = 24
    MEASURE_BUDGET_S = 30.0
    MIN_TICKS = 30

    dev = jax.devices()[0]
    print(f"bench device: {dev}", file=sys.stderr)

    async def run() -> dict:
        core = FusedCore(batch_window=0.0005)
        owner = _BenchOwner(core, B, S)
        bucket = owner.bucket
        bucket.patch_capacity = 8192
        await core.start()

        async def churn_pump(until: float) -> None:
            """One churn batch per core tick (event stream pacing)."""
            last = -1
            while time.perf_counter() < until:
                t = bucket.stats["ticks"]
                if t != last:
                    last = t
                    owner.emit_churn(CHURN)
                await asyncio.sleep(0.0002)

        # warmup: first compile + full upload + pipeline fill
        t0 = time.perf_counter()
        owner.emit_churn(CHURN)
        while bucket.stats["ticks"] < WARMUP_TICKS:
            owner.emit_churn(CHURN)
            await asyncio.sleep(0.002)
        warmup_s = time.perf_counter() - t0
        print(f"warmup: {WARMUP_TICKS} ticks in {warmup_s:.1f}s", file=sys.stderr)

        owner.lat_ms.clear()
        owner.patch_rows = 0
        tick0 = bucket.stats["ticks"]
        t0 = time.perf_counter()
        await churn_pump(t0 + MEASURE_BUDGET_S)
        # let in-flight ticks land before reading counters
        while core._inflight:
            await asyncio.sleep(0.002)
        dt = time.perf_counter() - t0
        ticks = bucket.stats["ticks"] - tick0
        await core.stop()

        if ticks < MIN_TICKS:
            print(f"warning: only {ticks} ticks in {dt:.1f}s", file=sys.stderr)
        per_tick = dt / max(ticks, 1)
        lat = np.asarray(owner.lat_ms) if owner.lat_ms else np.zeros(1)
        p50, p99 = np.percentile(lat, [50, 99])
        print(
            f"tick={per_tick * 1e3:.3f} ms | rows={B} (={TENANTS} tenants) | "
            f"ticks={ticks} | events/tick~{CHURN}x2 | "
            f"patches/tick={owner.patch_rows / max(ticks, 1):.0f} | "
            f"full_uploads={bucket.stats['full_uploads']} | "
            f"spec->status convergence p50={p50:.1f} ms p99={p99:.1f} ms "
            f"(target p99 < 200 ms)",
            file=sys.stderr,
        )
        rps = B / per_tick
        return {
            "metric": "reconciles_per_sec",
            "value": round(rps),
            "unit": "rows/s",
            "vs_baseline": round(rps / 1_000_000, 3),
        }

    result = asyncio.run(run())
    print(json.dumps(result))
    return 0


def _time_kernel(fn, *args, iters: int = 30) -> float:
    """Median-of-three steady-state seconds per call (device inputs)."""
    import jax

    out = fn(*args)
    jax.block_until_ready(out)
    samples = []
    for _ in range(3):
        t0 = time.perf_counter()
        for _ in range(iters):
            out = fn(*args)
        jax.block_until_ready(out)
        samples.append((time.perf_counter() - t0) / iters)
    return sorted(samples)[1]


def suite() -> int:
    """Benchmark the kernel lanes of BASELINE.json (configs[2..4]); print
    a markdown table to stderr and one JSON object to stdout.

    Not covered here: configs[0] (the demo scenario — run
    ``contrib/demo/run_demo.py all --check``) and configs[1] (the
    closed-loop syncer measurement — the default ``python bench.py``
    run, whose single JSON line is the headline metric).
    """
    import jax
    import jax.numpy as jnp

    from kcp_tpu.ops.labelmatch import fanout_match
    from kcp_tpu.ops.placement import split_replicas_jit
    from kcp_tpu.ops.schemahash import schema_hashes_jit, tokenize_schema

    rng = np.random.default_rng(3)
    rows = []

    # configs[2]: splitter bin-packing, 10k workspaces x 8 pclusters
    replicas = jax.device_put(rng.integers(0, 100, 10_000).astype(np.int32))
    avail = jax.device_put(rng.random((10_000, 8)) < 0.9)
    dt = _time_kernel(split_replicas_jit, replicas, avail)
    rows.append(("splitter bin-packing", "10k workspaces x 8 pclusters",
                 f"{10_000 / dt / 1e6:.1f}M splits/s"))

    # configs[3]: schema hashing for batch bucketing, 5k tenant CRD sets —
    # host tokenization (per-schema) + one device hash reduce over the set
    n_schemas = 5_000
    schemas = [
        {"type": "object", "properties": {
            f"f{i}": {"type": "string"} for i in range(20)},
         "description": str(k)}
        for k in range(n_schemas)
    ]
    t0 = time.perf_counter()
    tokens = np.stack([tokenize_schema(s) for s in schemas])
    host_dt = time.perf_counter() - t0
    toks = jax.device_put(tokens)
    dev_dt = _time_kernel(schema_hashes_jit, toks)
    dt = host_dt / n_schemas + dev_dt / n_schemas
    rows.append(("schema hash bucketing", "5k tenant CRD sets",
                 f"{1 / dt / 1e3:.0f}k schemas/s"))

    # configs[4]: informer fan-out, 100k objects x 64 selectors
    pair = jax.device_put(rng.integers(1, 1000, (100_000, 8)).astype(np.uint32))
    sels = jax.device_put(rng.integers(1, 1000, 64).astype(np.uint32))
    fan = jax.jit(lambda p, s: fanout_match(p, s).sum(axis=0, dtype=jnp.int32))
    dt = _time_kernel(fan, pair, sels)
    rows.append(("label fan-out", "100k objects x 64 selectors",
                 f"{100_000 / dt / 1e6:.0f}M obj/s"))

    print("| lane | scale | rate |", file=sys.stderr)
    print("|---|---|---|", file=sys.stderr)
    for name, scale, rate in rows:
        print(f"| {name} | {scale} | {rate} |", file=sys.stderr)

    print(json.dumps({"suite": [
        {"lane": name, "scale": scale, "rate": rate} for name, scale, rate in rows
    ]}))
    return 0


# ---------------------------------------------------------------------------
# Orchestrator: the TPU rides a tunnel that wedges transiently, and a hung
# in-process backend init cannot be interrupted from within. So the default
# entry point (1) pins ITSELF to the CPU platform so the parent can never
# touch the tunnel (the image's sitecustomize imports jax with the TPU
# platform baked in — a lazy backend init in the parent would race the
# child for the single tunnel, the known wedge trigger), (2) runs the
# measurement directly as a watchdogged child — no probe gate: a probe is
# exactly as likely to wedge as the measurement and only delays it — and
# (3) always prints exactly one JSON line — a structured failure record if
# the device never comes up, never a bare traceback.
# ---------------------------------------------------------------------------

CHILD_TIMEOUT_S = 1200
CHILD_ATTEMPTS = 4
ATTEMPT_BACKOFFS_S = (45, 90, 180)  # sleeps between failed attempts


def _fail_json(stage: str, detail: str, attempts: int, for_suite: bool) -> None:
    err = {"stage": stage, "detail": detail[-2000:], "attempts": attempts}
    if for_suite:
        print(json.dumps({"suite": [], "error": err}))
    else:
        print(json.dumps({
            "metric": "reconciles_per_sec",
            "value": 0,
            "unit": "rows/s",
            "vs_baseline": 0.0,
            "error": err,
        }))


def orchestrate(child_args: list[str]) -> int:
    import os
    import subprocess
    import tempfile

    for_suite = "--suite" in child_args
    env = dict(os.environ, KCP_BENCH_CHILD="1")
    last = ""
    for attempt in range(1, CHILD_ATTEMPTS + 1):
        if attempt > 1:
            time.sleep(ATTEMPT_BACKOFFS_S[min(attempt - 2,
                                              len(ATTEMPT_BACKOFFS_S) - 1)])
        # child stderr goes to a file: TimeoutExpired.stderr is None with
        # capture_output on this platform, and the stderr tail is the only
        # diagnostic of where a hung child got stuck
        with tempfile.TemporaryFile(mode="w+") as errf:
            try:
                r = subprocess.run(
                    [sys.executable, os.path.abspath(__file__), *child_args],
                    env=env, stdout=subprocess.PIPE, stderr=errf, text=True,
                    timeout=CHILD_TIMEOUT_S,
                )
            except subprocess.TimeoutExpired:
                errf.seek(0)
                last = (f"bench child hung > {CHILD_TIMEOUT_S}s; stderr tail: "
                        + errf.read()[-500:])
                print(last, file=sys.stderr)
                continue
            errf.seek(0)
            stderr = errf.read()
        sys.stderr.write(stderr)
        lines = [ln for ln in (r.stdout or "").splitlines() if ln.strip()]
        if r.returncode == 0 and lines:
            try:
                json.loads(lines[-1])
            except ValueError:
                last = f"child stdout not JSON: {lines[-1][:200]}"
            else:
                print(lines[-1])
                return 0
        else:
            tail = stderr.strip().splitlines()
            last = f"child rc={r.returncode}: " + (tail[-1] if tail else "")
            print(f"attempt {attempt}: {last}", file=sys.stderr)
    _fail_json("measurement", last, CHILD_ATTEMPTS, for_suite)
    return 0


if __name__ == "__main__":
    import os

    args = [a for a in sys.argv[1:] if a != "--child"]
    if "--probe" in args:
        # manual diagnostic: always run in-process (never through the
        # orchestrator, whose JSON contract a probe's output would fail)
        os.environ["KCP_BENCH_CHILD"] = "1"
    if os.environ.get("KCP_BENCH_CHILD") != "1" and "--child" not in sys.argv:
        # Parent process: pin to CPU BEFORE anything can lazily init a
        # backend. sitecustomize has already imported jax with the TPU
        # platform; only the config lever works at this point. The child
        # (KCP_BENCH_CHILD=1) keeps the real platform — it must be the
        # ONLY process on the tunnel.
        try:
            import jax

            jax.config.update("jax_platforms", "cpu")
        except Exception:
            pass
        sys.exit(orchestrate(args))

    # honor an explicit JAX_PLATFORMS override: the image's sitecustomize
    # imports jax with the TPU platform baked in before shell env can
    # land, so the config lever is the one that works (same workaround as
    # __graft_entry__.dryrun_multichip)
    want = os.environ.get("JAX_PLATFORMS", "")
    if want and want != "axon":
        import jax

        try:
            jax.config.update("jax_platforms", want)
        except Exception as e:
            print(f"warning: could not force JAX platform {want!r} ({e}); "
                  f"continuing on the baked-in platform", file=sys.stderr)
    if "--probe" in args:
        # manual diagnostic only (KCP_BENCH_CHILD=1 python bench.py
        # --probe): quick device-availability check for tunnel debugging;
        # the orchestrator itself never probes
        import jax

        d = jax.devices()
        print(d[0].platform, len(d))
        sys.exit(0)
    if "--suite" in args:
        sys.exit(suite())
    sys.exit(main())
