#!/usr/bin/env python
"""North-star benchmark: reconciles/sec across 10k logical clusters.

Measures the fused reconcile step (kcp_tpu/models/reconcile_model.py) at
BASELINE.json scale on the available accelerator: 10k logical clusters x
13 objects = 131,072 resident object rows, 64 slots, plus the splitter
lane (10k roots x 8 clusters) and the informer fan-out lane (rows x 64
selectors) — every lane of the control plane in one device program.

Steady state per tick: ship one padded 4,096-row delta batch to the
device, run the full level-triggered reconcile over ALL rows, bring the
decision lanes back to host. A "reconcile" = one object row fully
re-decided in a tick (the unit the reference spends a goroutine wakeup
on, pkg/syncer/syncer.go:227-244).

Prints exactly one JSON line:
    {"metric": "reconciles_per_sec", "value": ..., "unit": "rows/s",
     "vs_baseline": value / 1e6}
(vs_baseline > 1.0 beats the BASELINE.json target of 1M reconciles/s.)

Extra lanes are reported on stderr for humans; stdout stays one line.
"""

from __future__ import annotations

import json
import sys
import time

import numpy as np


def main() -> int:
    import jax

    from kcp_tpu.models.reconcile_model import (
        ReconcileDeltas,
        example_state,
        reconcile_step,
    )

    TENANTS = 10_000
    B = 131_072  # ~13 objects per logical cluster, pow2-padded
    S = 64
    R = 10_000  # root deployments (configs[2]: 10k workspaces)
    P = 8  # physical clusters
    C = 64  # cluster selectors in the fan-out lane
    D = 4_096  # delta rows per tick
    WARMUP, ITERS = 3, 30

    dev = jax.devices()[0]
    print(f"bench device: {dev}", file=sys.stderr)

    state = example_state(b=B, s=S, r=R, p=P, l=8, c=C, dirty_frac=0.005)
    state = jax.tree.map(jax.device_put, state)

    rng = np.random.default_rng(7)
    # pre-build a handful of delta batches; steady state cycles them so the
    # scatter never degenerates into a no-op the compiler could hoist
    host_deltas = []
    for i in range(4):
        # unique in-batch indices: the apply_deltas dedup-by-key contract
        idx = rng.permutation(B)[:D].astype(np.int32)
        vals = rng.integers(1, 2**32, size=(D, S), dtype=np.uint32)
        host_deltas.append(
            ReconcileDeltas(
                idx=idx,
                up_vals=vals,
                up_exists=np.ones(D, bool),
                down_vals=vals,  # deltas arrive in-sync; dirt comes from churn
                down_exists=np.ones(D, bool),
                valid=(rng.random(D) < 0.95),
            )
        )

    step = jax.jit(reconcile_step, donate_argnums=(0,))

    for i in range(WARMUP):
        state, out = step(state, host_deltas[i % 4])
    jax.block_until_ready((state, out))

    t0 = time.perf_counter()
    for i in range(ITERS):
        state, out = step(state, host_deltas[i % 4])
        # the decision lanes the host applier actually consumes each tick
        np.asarray(out.decision)
        np.asarray(out.status_upsync)
        np.asarray(out.stats)
    jax.block_until_ready(state)
    dt = time.perf_counter() - t0

    per_tick = dt / ITERS
    reconciles_per_sec = B / per_tick
    print(
        f"tick={per_tick * 1e3:.3f} ms | rows={B} (={TENANTS} tenants) | "
        f"splitter {R}x{P} | fanout {B}x{C} | deltas {D}/tick | "
        f"convergence-latency floor = one tick",
        file=sys.stderr,
    )
    print(json.dumps({
        "metric": "reconciles_per_sec",
        "value": round(reconciles_per_sec),
        "unit": "rows/s",
        "vs_baseline": round(reconciles_per_sec / 1_000_000, 3),
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
