#!/usr/bin/env python
"""North-star benchmark: reconciles/sec across 10k logical clusters.

Measures the fused reconcile step (kcp_tpu/models/reconcile_model.py) at
BASELINE.json scale on the available accelerator: 10k logical clusters x
13 objects = 131,072 resident object rows, 64 slots, plus the splitter
lane (10k roots x 8 clusters) and the informer fan-out lane (rows x 64
selectors) — every lane of the control plane in one device program.

The loop is a real closed control loop, not a synthetic kernel drill:

  churn     — every tick, CHURN random objects get new upstream specs
              (the informer event stream; host mirror updated to match)
  reconcile — the device re-decides ALL rows and returns a compact patch
              set (actionable rows only) + global stats
  apply     — the host applier turns collected patches into downstream
              sync events (side=down, value = host's upstream object) and
              ships them back in a later tick's delta batch — dirty rows
              actually converge, exactly like the reference's
              upsertIntoDownstream (pkg/syncer/specsyncer.go:86-132)

A "reconcile" = one object row fully re-decided in a tick (the unit the
reference spends a goroutine wakeup on, pkg/syncer/syncer.go:227-244).

The link uses the packed wire format (reconcile_step_packed): exactly one
uint32 upload and one int32 download per tick, software-pipelined —
uploads issued UPLOAD_LEAD ticks ahead, downloads collected FETCH_DEPTH
ticks later via copy_to_host_async — so steady-state tick time is set by
device work + link bandwidth, not per-RPC round-trip latency.

Convergence is measured END TO END per churned row: from the moment the
new spec exists on the host to the collect of the tick whose delta batch
carried that row's downstream sync event — that collect blocks on output
data that is data-dependent on the sync scatter, so it proves the row
converged on device. p99 is reported against BASELINE.json's < 200 ms
target.

Prints exactly one JSON line:
    {"metric": "reconciles_per_sec", "value": ..., "unit": "rows/s",
     "vs_baseline": value / 1e6}
(vs_baseline > 1.0 beats the BASELINE.json target of 1M reconciles/s —
a target set for a v5e-8; this harness uses ONE chip.)
"""

from __future__ import annotations

import json
import sys
import time

import numpy as np


def main() -> int:
    import jax

    from kcp_tpu.models.reconcile_model import (
        ReconcileDeltas,
        example_state,
        pack_deltas,
        reconcile_step_packed,
        unpack_patches,
    )

    TENANTS = 10_000
    B = 131_072  # ~13 objects per logical cluster, pow2-padded
    S = 64
    R = 10_000  # root deployments (configs[2]: 10k workspaces)
    P = 8  # physical clusters
    C = 64  # cluster selectors in the fan-out lane
    D = 2_048  # delta events per tick (churn + sync feedback + padding)
    CHURN = 768  # new upstream-spec events per tick
    K = 8_192  # patch-set capacity per tick
    UPLOAD_LEAD = 1  # ticks a delta upload is issued ahead of its step
    FETCH_DEPTH = 2  # ticks between a step and collecting its patches
    WARMUP, SETTLE = 8, 16
    MEASURE_BUDGET_S = 30.0  # adaptive: ITERS chosen to fill this window
    MIN_ITERS, MAX_ITERS = 30, 600

    dev = jax.devices()[0]
    print(f"bench device: {dev}", file=sys.stderr)

    state = example_state(b=B, s=S, r=R, p=P, l=8, c=C, dirty_frac=0.005)
    # host's authoritative upstream mirror (the applier's object store
    # analog) — must match example_state's construction
    up_h = np.asarray(state.up_vals).copy()
    state = jax.tree.map(jax.device_put, state)

    rng = np.random.default_rng(7)
    backlog: list[np.ndarray] = []  # patch rows queued for a sync event
    pending = np.zeros(B, bool)  # rows queued or with a sync in flight
    t_create = np.full(B, time.perf_counter())  # latest churn time per row

    def make_batch() -> tuple[np.ndarray, np.ndarray, np.ndarray]:
        """One tick's event batch (packed), its sync rows, and the
        creation times of the churn each sync event converges."""
        churn_idx = rng.choice(B, size=CHURN, replace=False).astype(np.int32)
        churn_vals = rng.integers(1, 2**32, size=(CHURN, S), dtype=np.uint32)
        up_h[churn_idx] = churn_vals
        t_create[churn_idx] = time.perf_counter()

        sync_cap = D - CHURN
        pend = backlog.pop(0) if backlog else np.empty(0, np.int32)
        # rows churned this tick will re-appear in a later patch set;
        # syncing them now would race the in-flight churn
        requeue = np.isin(pend, churn_idx)
        pending[pend[requeue]] = False
        pend = pend[~requeue]
        sync_idx, rest = pend[:sync_cap], pend[sync_cap:]
        if rest.size:
            backlog.insert(0, rest)

        n = CHURN + sync_idx.size
        idx = np.zeros(D, np.int32)
        vals = np.zeros((D, S), np.uint32)
        side = np.zeros(D, bool)
        valid = np.zeros(D, bool)
        idx[:CHURN] = churn_idx
        vals[:CHURN] = churn_vals
        idx[CHURN:n] = sync_idx
        vals[CHURN:n] = up_h[sync_idx]
        side[CHURN:n] = True  # sync events target the downstream mirror
        valid[:n] = True
        packed = pack_deltas(ReconcileDeltas(
            idx=idx, vals=vals, exists=np.ones(D, bool), side=side, valid=valid
        ))
        # creation times are captured NOW: a row re-churned while this sync
        # is in flight must not re-stamp this sample (the sync still
        # converges the value this batch carries)
        return packed, sync_idx, t_create[sync_idx].copy()

    step = jax.jit(
        reconcile_step_packed, donate_argnums=(0,),
        static_argnames=("patch_capacity",),
    )

    lat_ms: list[float] = []
    applied = [0]

    def collect(item) -> None:
        """Block on one in-flight tick: finalize convergence samples for
        the sync events it carried (the wire read proves the scatter ran)
        and queue its newly-dirty patch rows for syncing."""
        wire, synced, created = item
        idx, _code, _upsync, _overflow, _stats = unpack_patches(np.asarray(wire))
        now = time.perf_counter()
        if synced.size:
            lat_ms.extend((now - created) * 1e3)
            pending[synced] = False  # re-churned rows may now re-enqueue
        fresh = idx[~pending[idx]].astype(np.int32)
        pending[fresh] = True
        backlog.append(fresh)
        applied[0] += fresh.size

    upload_q: list[tuple[object, np.ndarray]] = []
    in_flight: list[tuple[object, np.ndarray]] = []

    def tick():
        nonlocal state
        b, sync_rows, created = make_batch()
        upload_q.append((jax.device_put(b), sync_rows, created))
        dev_batch, synced, created = upload_q.pop(0)  # issued UPLOAD_LEAD ticks ago
        state, wire = step(state, dev_batch, patch_capacity=K)
        wire.copy_to_host_async()
        in_flight.append((wire, synced, created))
        if len(in_flight) > FETCH_DEPTH:
            collect(in_flight.pop(0))

    # fill the upload lead so steady-state ticks consume LEAD-old batches
    for _ in range(UPLOAD_LEAD):
        b, sync_rows, created = make_batch()
        upload_q.append((jax.device_put(b), sync_rows, created))

    for i in range(WARMUP):
        tick()
    jax.block_until_ready(state)

    # adaptive iteration count: size the measured run to MEASURE_BUDGET_S
    # so a slow start (cold tunnel, first-compile) still completes
    t0 = time.perf_counter()
    for _ in range(SETTLE):
        tick()
    jax.block_until_ready(state)
    settle_tick = (time.perf_counter() - t0) / SETTLE
    ITERS = max(MIN_ITERS, min(MAX_ITERS, int(MEASURE_BUDGET_S / max(settle_tick, 1e-6))))
    print(f"settle tick={settle_tick * 1e3:.3f} ms -> ITERS={ITERS}", file=sys.stderr)
    lat_ms.clear()
    applied[0] = 0

    t0 = time.perf_counter()
    for _ in range(ITERS):
        tick()
    jax.block_until_ready(state)
    dt = time.perf_counter() - t0
    while in_flight:
        collect(in_flight.pop(0))

    per_tick = dt / ITERS
    reconciles_per_sec = B / per_tick
    p50, p99 = np.percentile(lat_ms, [50, 99])
    print(
        f"tick={per_tick * 1e3:.3f} ms | rows={B} (={TENANTS} tenants) | "
        f"splitter {R}x{P} | fanout {B}x{C} | events {D}/tick "
        f"(churn {CHURN} + sync feedback) | patches/tick={applied[0] / ITERS:.0f} | "
        f"spec->status convergence p50={p50:.1f} ms p99={p99:.1f} ms "
        f"(target p99 < 200 ms)",
        file=sys.stderr,
    )
    print(json.dumps({
        "metric": "reconciles_per_sec",
        "value": round(reconciles_per_sec),
        "unit": "rows/s",
        "vs_baseline": round(reconciles_per_sec / 1_000_000, 3),
    }))
    return 0


def _time_kernel(fn, *args, iters: int = 30) -> float:
    """Median-of-three steady-state seconds per call (device inputs)."""
    import jax

    out = fn(*args)
    jax.block_until_ready(out)
    samples = []
    for _ in range(3):
        t0 = time.perf_counter()
        for _ in range(iters):
            out = fn(*args)
        jax.block_until_ready(out)
        samples.append((time.perf_counter() - t0) / iters)
    return sorted(samples)[1]


def suite() -> int:
    """Benchmark the kernel lanes of BASELINE.json (configs[2..4]); print
    a markdown table to stderr and one JSON object to stdout.

    Not covered here: configs[0] (the demo scenario — run
    ``contrib/demo/run_demo.py all --check``) and configs[1] (the
    closed-loop syncer measurement — the default ``python bench.py``
    run, whose single JSON line is the headline metric).
    """
    import jax
    import jax.numpy as jnp

    from kcp_tpu.ops.labelmatch import fanout_match
    from kcp_tpu.ops.placement import split_replicas_jit
    from kcp_tpu.ops.schemahash import schema_hashes_jit, tokenize_schema

    rng = np.random.default_rng(3)
    rows = []

    # configs[2]: splitter bin-packing, 10k workspaces x 8 pclusters
    replicas = jax.device_put(rng.integers(0, 100, 10_000).astype(np.int32))
    avail = jax.device_put(rng.random((10_000, 8)) < 0.9)
    dt = _time_kernel(split_replicas_jit, replicas, avail)
    rows.append(("splitter bin-packing", "10k workspaces x 8 pclusters",
                 f"{10_000 / dt / 1e6:.1f}M splits/s"))

    # configs[3]: schema hashing for batch bucketing, 5k tenant CRD sets —
    # host tokenization (per-schema) + one device hash reduce over the set
    n_schemas = 5_000
    schemas = [
        {"type": "object", "properties": {
            f"f{i}": {"type": "string"} for i in range(20)},
         "description": str(k)}
        for k in range(n_schemas)
    ]
    t0 = time.perf_counter()
    tokens = np.stack([tokenize_schema(s) for s in schemas])
    host_dt = time.perf_counter() - t0
    toks = jax.device_put(tokens)
    dev_dt = _time_kernel(schema_hashes_jit, toks)
    dt = host_dt / n_schemas + dev_dt / n_schemas
    rows.append(("schema hash bucketing", "5k tenant CRD sets",
                 f"{1 / dt / 1e3:.0f}k schemas/s"))

    # configs[4]: informer fan-out, 100k objects x 64 selectors
    pair = jax.device_put(rng.integers(1, 1000, (100_000, 8)).astype(np.uint32))
    sels = jax.device_put(rng.integers(1, 1000, 64).astype(np.uint32))
    fan = jax.jit(lambda p, s: fanout_match(p, s).sum(axis=0, dtype=jnp.int32))
    dt = _time_kernel(fan, pair, sels)
    rows.append(("label fan-out", "100k objects x 64 selectors",
                 f"{100_000 / dt / 1e6:.0f}M obj/s"))

    print("| lane | scale | rate |", file=sys.stderr)
    print("|---|---|---|", file=sys.stderr)
    for name, scale, rate in rows:
        print(f"| {name} | {scale} | {rate} |", file=sys.stderr)

    print(json.dumps({"suite": [
        {"lane": name, "scale": scale, "rate": rate} for name, scale, rate in rows
    ]}))
    return 0


# ---------------------------------------------------------------------------
# Orchestrator: the TPU rides a tunnel that wedges transiently, and a hung
# in-process backend init cannot be interrupted from within. So the default
# entry point (1) probes device availability in a short-timeout subprocess
# with backoff, (2) runs the actual measurement as a watchdogged child, and
# (3) always prints exactly one JSON line — a structured failure record if
# the device never comes up, never a bare traceback.
# ---------------------------------------------------------------------------

PROBE_TIMEOUT_S = 120
PROBE_BACKOFFS_S = (10, 20, 40, 60, 90)  # sleeps between failed probes
CHILD_TIMEOUT_S = 1200
CHILD_ATTEMPTS = 2


def _probe_device() -> tuple[bool, str]:
    """Check backend init in a throwaway ``bench.py --probe`` subprocess (a
    wedged tunnel hangs the caller forever; a child can be killed). The
    child path shares the __main__ platform-override logic."""
    import os
    import subprocess

    env = dict(os.environ, KCP_BENCH_CHILD="1")
    try:
        r = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--probe"],
            env=env, capture_output=True, text=True, timeout=PROBE_TIMEOUT_S,
        )
    except subprocess.TimeoutExpired:
        return False, f"device probe hung > {PROBE_TIMEOUT_S}s (tunnel wedged)"
    if r.returncode != 0:
        tail = (r.stderr or r.stdout or "").strip().splitlines()
        return False, tail[-1] if tail else f"probe rc={r.returncode}"
    return True, r.stdout.strip()


def _fail_json(stage: str, detail: str, attempts: int, for_suite: bool) -> None:
    err = {"stage": stage, "detail": detail[-2000:], "attempts": attempts}
    if for_suite:
        print(json.dumps({"suite": [], "error": err}))
    else:
        print(json.dumps({
            "metric": "reconciles_per_sec",
            "value": 0,
            "unit": "rows/s",
            "vs_baseline": 0.0,
            "error": err,
        }))


def orchestrate(child_args: list[str]) -> int:
    import os
    import subprocess
    import tempfile

    for_suite = "--suite" in child_args
    probes = 0
    for backoff in PROBE_BACKOFFS_S + (None,):
        probes += 1
        ok, msg = _probe_device()
        print(f"probe {probes}: {'ok ' if ok else 'FAIL '}{msg}", file=sys.stderr)
        if ok:
            break
        if backoff is None:
            _fail_json("backend-init", msg, probes, for_suite)
            return 0  # structured record IS the deliverable; rc 0 so it lands
        time.sleep(backoff)

    env = dict(os.environ, KCP_BENCH_CHILD="1")
    last = ""
    for attempt in range(1, CHILD_ATTEMPTS + 1):
        if attempt > 1:
            time.sleep(30)
        # child stderr goes to a file: TimeoutExpired.stderr is None with
        # capture_output on this platform, and the stderr tail is the only
        # diagnostic of where a hung child got stuck
        with tempfile.TemporaryFile(mode="w+") as errf:
            try:
                r = subprocess.run(
                    [sys.executable, os.path.abspath(__file__), *child_args],
                    env=env, stdout=subprocess.PIPE, stderr=errf, text=True,
                    timeout=CHILD_TIMEOUT_S,
                )
            except subprocess.TimeoutExpired:
                errf.seek(0)
                last = (f"bench child hung > {CHILD_TIMEOUT_S}s; stderr tail: "
                        + errf.read()[-500:])
                print(last, file=sys.stderr)
                continue
            errf.seek(0)
            stderr = errf.read()
        sys.stderr.write(stderr)
        lines = [ln for ln in (r.stdout or "").splitlines() if ln.strip()]
        if r.returncode == 0 and lines:
            try:
                json.loads(lines[-1])
            except ValueError:
                last = f"child stdout not JSON: {lines[-1][:200]}"
            else:
                print(lines[-1])
                return 0
        else:
            tail = stderr.strip().splitlines()
            last = f"child rc={r.returncode}: " + (tail[-1] if tail else "")
            print(f"attempt {attempt}: {last}", file=sys.stderr)
    _fail_json("measurement", last, CHILD_ATTEMPTS, for_suite)
    return 0


if __name__ == "__main__":
    import os

    args = [a for a in sys.argv[1:] if a != "--child"]
    if os.environ.get("KCP_BENCH_CHILD") != "1" and "--child" not in sys.argv:
        sys.exit(orchestrate(args))

    # honor an explicit JAX_PLATFORMS override: the image's sitecustomize
    # imports jax with the TPU platform baked in before shell env can
    # land, so the config lever is the one that works (same workaround as
    # __graft_entry__.dryrun_multichip)
    want = os.environ.get("JAX_PLATFORMS", "")
    if want and want != "axon":
        import jax

        try:
            jax.config.update("jax_platforms", want)
        except Exception as e:
            print(f"warning: could not force JAX platform {want!r} ({e}); "
                  f"continuing on the baked-in platform", file=sys.stderr)
    if "--probe" in args:
        import jax

        d = jax.devices()
        print(d[0].platform, len(d))
        sys.exit(0)
    if "--suite" in args:
        sys.exit(suite())
    sys.exit(main())
