#!/usr/bin/env python
"""North-star benchmark: reconciles/sec across 10k logical clusters.

Drives the SERVING engine, not an emulation: a
:class:`kcp_tpu.syncer.core.FusedCore` — the same BatchController tick
loop, packed-wire fused ``reconcile_step``, pipelined collection, and
patch dispatch that ``BatchSyncEngine`` serves through — with a synthetic
section owner standing in for the informer caches and the store applier.
At BASELINE.json scale: 10k logical clusters x 13 objects = 131,072
resident rows, 64 slots.

The loop is a real closed control loop:

  churn     — every core tick, CHURN random rows get new upstream specs
              (the informer event stream), enqueued key-by-key through
              the serving work queue
  reconcile — the core's tick drains the queue, stages the rows, and
              runs the fused step over ALL rows; the compact patch set
              pipelines back (copy_to_host_async, collected a tick later)
  apply     — the owner's ``fused_apply`` (the applier-pool seam) copies
              upstream -> downstream per patch row and enqueues the sync
              feedback, which rides a later tick's scatter — rows
              actually converge, exactly like the reference's
              upsertIntoDownstream (pkg/syncer/specsyncer.go:86-132)

A "reconcile" = one object row fully re-decided in a tick (the unit the
reference spends a goroutine wakeup on, pkg/syncer/syncer.go:227-244).

EVIDENCE-FIRST HARNESS CONTRACT (the r01-r03 lesson: three rounds lost
their number to init failures and device stalls that destroyed partial
evidence):

- the child prints a JSON result line after EVERY stage that produces
  one — a provisional line right after warmup, an updated best-so-far
  line after each measurement segment, and a final line — each flushed
  immediately, so the freshest evidence is always on disk;
- measurement runs in short segments with an in-child stall watchdog:
  if the tick counter stops advancing, the child reports the segments
  it already has and hard-exits instead of waiting on a wedged device;
- a last-resort timer hard-exits the child (with whatever was printed)
  before the orchestrator's kill;
- the orchestrator writes child stdout to a file and salvages the LAST
  parseable JSON line even when the child times out or crashes;
- timeouts are sized so >=3 attempts fit inside a ~20-minute driver
  window (r03 died with one 1200s attempt still in flight).

The headline JSON line:
    {"metric": "reconciles_per_sec", "value": ..., "unit": "rows/s",
     "vs_baseline": value / 125_000, ...}
BASELINE.json's 1M reconciles/s target is set for a v5e-8; this harness
runs ONE chip, so ``vs_baseline`` is reported against the per-chip
pro-rata bar (1M / 8 chips = 125k rows/s/chip). The full-pod ratio is
also included as ``vs_pod_target`` so nobody has to re-derive it.
"""

from __future__ import annotations

import asyncio
import json
import os
import re
import sys
import threading
import time

import numpy as np

TARGET_POD = 1_000_000  # BASELINE.json: v5e-8
TARGET_CHIP = TARGET_POD // 8

# measurement shape. KCP_BENCH_ROWS widens the resident fleet for scale-
# headroom runs (the reference's shard-capacity investigation targets
# ~100k objects per shard, logical-clusters.md:83; the default already
# exceeds it and the loop holds 1M+ rows on one chip) — the driver's
# default run is unchanged.
B = int(os.environ.get("KCP_BENCH_ROWS", "131072"))  # pow2
TENANTS = B // 13  # ~13 objects per logical cluster
S = 64
# new upstream-spec events per tick. KCP_BENCH_CHURN sweeps the event
# rate for the headroom curve (BASELINE.md "event-rate headroom"): the
# resident-fleet decision math is O(B) per tick, but staging, the packed
# wire, and the applier pool are O(events) — this knob finds where they
# take over.
CHURN = int(os.environ.get("KCP_BENCH_CHURN", "768"))
# measurement-shape knobs, env-overridable so the CI smoke (scripts/
# ci.sh: tiny rows, one short segment, CPU) can drive the same harness
WARMUP_TICKS = int(os.environ.get("KCP_BENCH_WARMUP", "24"))
SEGMENT_S = float(os.environ.get("KCP_BENCH_SEGMENT_S", "8.0"))
SEGMENTS = int(os.environ.get("KCP_BENCH_SEGMENTS", "3"))
STALL_S = 45.0  # no tick progress for this long => wedged device, abort

# orchestrator budget: 3 attempts x 240s + 2 short backoffs ~= 13.5 min,
# inside the ~20 min driver window demonstrated by the r03 record.
# KCP_BENCH_CHILD_TIMEOUT shrinks the per-attempt window where the
# failure mode is known-fast (and lets the degraded-fallback loop be
# exercised in minutes, not a full driver window).
CHILD_TIMEOUT_S = float(os.environ.get("KCP_BENCH_CHILD_TIMEOUT", "240"))
CHILD_GRACE_S = 25  # child hard-exits this long before the orchestrator kill
# device init not done by then => report + exit early. Overridable
# (KCP_BENCH_DEVICE_TIMEOUT) because the right budget is host-specific:
# r05 burned all three attempts on a tunnel that needed a few seconds
# more than the default and published value=0 for the whole round.
INIT_STALL_S = float(os.environ.get("KCP_BENCH_DEVICE_TIMEOUT", "110"))
CHILD_ATTEMPTS = 3
ATTEMPT_BACKOFFS_S = (20, 30)
DEADLINE_ENV = "KCP_BENCH_DEADLINE"  # unix time the orchestrator kills at
FINAL_ATTEMPT_ENV = "KCP_BENCH_FINAL"  # last attempt: init gets the full window


def emit(result: dict) -> None:
    """Print one JSON evidence line, flushed — the orchestrator keeps the
    last parseable line even if the child dies right after."""
    print(json.dumps(result), flush=True)


def result_json(rps: float, *, provisional: bool, stage: str,
                segments: list[float] | None = None,
                p50_ms: float | None = None, p99_ms: float | None = None,
                strict_p99_ms: float | None = None,
                diags: dict | None = None,
                note: str | None = None) -> dict:
    out = {
        "metric": "reconciles_per_sec",
        "value": round(rps),
        "unit": "rows/s",
        "vs_baseline": round(rps / TARGET_CHIP, 3),
        "vs_pod_target": round(rps / TARGET_POD, 3),
        "chips": 1,
        "target_per_chip": TARGET_CHIP,
        "stage": stage,
    }
    if CHURN != 768:
        out["churn_per_tick"] = CHURN
    if B != 131072:
        out["rows"] = B
    if "--pallas" in sys.argv or os.environ.get("KCP_PALLAS", "") == "1":
        out["pallas"] = True
    if provisional:
        out["provisional"] = True
    if segments:
        out["segment_rates"] = [round(r) for r in segments]
    if p50_ms is not None:
        # only ever set from real samples — an empty latency buffer must
        # not fabricate a perfect 0.0ms pass in the evidence record
        out["convergence_p50_ms"] = round(p50_ms, 1)
        out["convergence_p99_ms"] = round(p99_ms, 1)
        out["convergence_target_ms"] = 200
    if strict_p99_ms is not None:
        # the round-3 window (close two dispatches AFTER the downstream
        # write, proving the feedback re-scattered) — reported alongside
        # the headline so the definition change is measurable, not
        # merely disclosed (ADVICE r4)
        out["convergence_strict_p99_ms"] = round(strict_p99_ms, 1)
    if diags:
        out.update(diags)
    if note:
        out["note"] = note
    return out


class _BenchOwner:
    """Synthetic SectionOwner: mirror arrays instead of informer caches,
    mirror copies instead of store writes. Everything between — queue,
    staging, fused step, pipeline, dispatch — is the serving code."""

    def __init__(self, core, b: int, s: int, seed: int = 7):
        self.core = core
        self.B, self.S = b, s
        self.rng = np.random.default_rng(seed)
        # status slots: the top s//8 columns, as example_state lays out
        mask = np.zeros(s, bool)
        mask[-max(1, s // 8):] = True
        self._mask = mask
        self.section = core.register(self, s)
        bucket = self.section.bucket
        for i in range(b):
            self.section.row_for(i)
        bucket.up_vals[:b] = self.rng.integers(1, 2**32, (b, s), dtype=np.uint32)
        bucket.down_vals[:b] = bucket.up_vals[:b]
        flip = self.rng.random(b) < 0.005
        bucket.down_vals[:b][flip, :1] ^= 1
        bucket.up_exists[:b] = True
        bucket.down_exists[:b] = True
        bucket.mark_stale()
        self.bucket = bucket
        self.t_create = np.full(b, time.perf_counter())
        self.dispatches = 0
        self.lat_ms: list[float] = []
        self.lat_strict_ms: list[float] = []
        self._strict_pending: list[tuple[int, np.ndarray]] = []
        self.patch_rows = 0

    # --------------------------------------------- SectionOwner interface

    def fused_status_mask(self) -> np.ndarray:
        return self._mask

    def fused_encode(self, key: int):
        b = self.bucket
        return b.up_vals[key], True, b.down_vals[key], True

    def fused_encode_many(self, keys):
        b = self.bucket
        idx = np.fromiter(keys, np.int64, len(keys))
        return (b.up_vals[idx], np.ones(idx.size, bool),
                b.down_vals[idx], np.ones(idx.size, bool))

    def fused_overflow(self) -> None:  # pragma: no cover - fixed vocab
        raise AssertionError("bench vocabulary never grows")

    def fused_apply(self, patches) -> None:
        """The applier seam: sync each patch row downstream and enqueue
        the feedback event.

        Convergence samples close HERE — the downstream write is the
        upsertIntoDownstream moment (pkg/syncer/specsyncer.go:86-132),
        and this owner's apply also mirrors the status side, so it is the
        spec->status convergence instant BASELINE.json's 200 ms bounds.
        (Earlier rounds sampled two dispatches later to also prove the
        feedback re-scattered; that stricter window measured the harness'
        pipeline, not the convergence the target defines.)"""
        self.dispatches += 1
        now = time.perf_counter()
        rows = np.fromiter((k for k, _c, _u in patches), np.int32, len(patches))
        self.patch_rows += rows.size
        self.lat_ms.extend((now - self.t_create[rows]) * 1e3)
        # strict (round-3) window: the same rows also close two
        # dispatches later, once the feedback provably re-scattered
        self._strict_pending.append((self.dispatches, self.t_create[rows].copy()))
        while self._strict_pending and self.dispatches >= self._strict_pending[0][0] + 2:
            _, creates = self._strict_pending.pop(0)
            self.lat_strict_ms.extend((now - creates) * 1e3)
        self.bucket.down_vals[rows] = self.bucket.up_vals[rows]
        self.core.enqueue_many(self.section, True, rows.tolist())

    # ------------------------------------------------------------- churn

    def emit_churn(self, n: int) -> None:
        rows = self.rng.choice(self.B, size=n, replace=False)
        self.bucket.up_vals[rows] = self.rng.integers(
            1, 2**32, (n, self.S), dtype=np.uint32)
        self.t_create[rows] = time.perf_counter()
        self.core.enqueue_many(self.section, False, rows.tolist())


class Deadman:
    """Last-resort exit: emit the freshest evidence and hard-exit before
    the orchestrator's kill lands. A wedged device call cannot be
    interrupted from asyncio, so this runs on a daemon thread.

    The kill deadline comes from the orchestrator via ``DEADLINE_ENV``
    (unix time), so interpreter startup / sitecustomize jax import time
    cannot push the deadman past the kill. Stages re-arm it with shorter
    budgets (device init gets ``INIT_STALL_S``, not the whole window) so
    an init hang — r04's observed failure mode — burns ~2 minutes of the
    retry budget instead of all of it.
    """

    def __init__(self, best: dict):
        self.best = best
        self._timer: threading.Timer | None = None
        kill_at = float(os.environ.get(DEADLINE_ENV, "0") or 0)
        self.hard_deadline = (kill_at - CHILD_GRACE_S if kill_at
                              else time.time() + CHILD_TIMEOUT_S - CHILD_GRACE_S)

    def arm(self, stage: str, budget_s: float | None = None) -> None:
        if self._timer is not None:
            self._timer.cancel()
        fire_at = self.hard_deadline
        if budget_s is not None:
            fire_at = min(fire_at, time.time() + budget_s)
        delay = max(1.0, fire_at - time.time())

        def fire() -> None:
            r = dict(self.best.get("result") or result_json(
                0, provisional=True, stage=f"{stage}-stall",
                note=f"deadman fired during {stage}; no measurement yet"))
            r["note"] = (r.get("note", "") + f" [deadman exit in {stage}]").strip()
            emit(r)
            sys.stdout.flush()
            os._exit(0)

        self._timer = threading.Timer(delay, fire)
        self._timer.daemon = True
        self._timer.start()


def pipeline_arg(argv: list[str]) -> str | None:
    """--pipeline {serial,double}: run the serial-vs-pipelined tick A/B
    (both modes in one invocation); the named mode is the headline."""
    if "--pipeline" not in argv:
        return None
    i = argv.index("--pipeline")
    if i + 1 >= len(argv) or argv[i + 1] not in ("serial", "double"):
        print("--pipeline requires 'serial' or 'double'", file=sys.stderr)
        raise SystemExit(2)
    return argv[i + 1]


async def _measure(best: dict, pipeline: str | None = None,
                   ab: bool = False) -> dict:
    """One warmup + segments measurement pass over a fresh core.

    ``pipeline`` selects the core's tick-pipelining mode (None = the
    serving default, "double"); ``ab=True`` marks every emitted evidence
    line provisional (the combined A/B line is the headline) and
    prefixes stages with the mode name."""
    from kcp_tpu.syncer.core import FusedCore

    tag = f"{pipeline}-" if ab and pipeline else ""
    core = FusedCore(batch_window=0.0005,
                     use_pallas=True if "--pallas" in sys.argv else None,
                     pipeline=pipeline)
    owner = _BenchOwner(core, B, S)
    bucket = owner.bucket
    bucket.patch_capacity = 8192
    # pre-warm the acks-lane high-water: the wire's (packed, acks)
    # shape pair is compiled per capacity, and a mid-measurement
    # ack_capacity doubling costs one seconds-long recompile — the
    # prime suspect for r04's 1M-row segment-2 stall (a ~6.8 s
    # "full-upload-sized" gap with no full_uploads increment). Ack
    # bursts track the batch-drained event count (CHURN-proportional,
    # with batching slack) and grow with fleet-scale backlogs, so
    # fold both into the floor, kept pow2 for sticky shapes.
    ack_floor = max(8192, B // 64, 2 * CHURN)
    bucket.ack_capacity = 1 << (ack_floor - 1).bit_length()
    await core.start()

    # ---- warmup: first compile + full upload + pipeline fill, with
    # its own stall guard (r01's failure mode: init hangs forever)
    t0 = time.perf_counter()
    owner.emit_churn(CHURN)
    last_tick, last_progress = -1, t0
    while bucket.stats["ticks"] < WARMUP_TICKS:
        owner.emit_churn(CHURN)
        await asyncio.sleep(0.002)
        now = time.perf_counter()
        t = bucket.stats["ticks"]
        if t != last_tick:
            last_tick, last_progress = t, now
        elif now - last_progress > STALL_S:
            emit(result_json(
                0, provisional=True, stage=f"{tag}warmup-stall",
                note=f"tick counter stuck at {t} for {STALL_S:.0f}s"))
            os._exit(0)
    warmup_s = time.perf_counter() - t0
    warmup_rate = B * WARMUP_TICKS / warmup_s
    print(f"{tag}warmup: {WARMUP_TICKS} ticks in {warmup_s:.1f}s "
          f"({warmup_s / WARMUP_TICKS * 1e3:.0f} ms/tick incl. compile)",
          file=sys.stderr)
    # provisional evidence line: includes compile time, so it
    # UNDERSTATES steady state — but it survives anything after it
    best["result"] = result_json(
        warmup_rate, provisional=True, stage=f"{tag}warmup",
        note="rate includes XLA compile; steady-state segments follow")
    emit(best["result"])

    # ---- measurement: short segments, best-so-far after each
    owner.lat_ms.clear()
    owner.lat_strict_ms.clear()
    owner._strict_pending.clear()
    owner.patch_rows = 0
    seg_rates: list[float] = []

    async def churn_pump(budget_s: float) -> tuple[bool, float]:
        """One churn batch per core tick; (stalled, max tick gap s).

        The time budget only ends the segment once at least one tick
        has landed — a zero-tick segment keeps waiting so a wedged
        device hits the STALL_S check instead of "completing" with
        nothing measured (the r03 hang ran 20 minutes dark this way).
        The max inter-tick gap is the stall diagnostic: a segment
        whose rate collapses but whose gap stays at ~tick time lost
        throughput smoothly, while a multi-second gap is one discrete
        stall (e.g. an unintended full re-upload or a recompile).
        """
        seg_start = time.perf_counter()
        last, progress = bucket.stats["ticks"], seg_start
        ticked = False
        gap_max = 0.0
        # prime the loop: a fully-drained queue (fast ticks converge
        # everything between segments) would otherwise deadlock —
        # churn waits for a tick, the tick waits for events
        owner.emit_churn(CHURN)
        while True:
            now = time.perf_counter()
            if now - seg_start >= budget_s and ticked:
                return False, gap_max
            t = bucket.stats["ticks"]
            if t != last:
                gap_max = max(gap_max, now - progress)
                last, progress, ticked = t, now, True
                owner.emit_churn(CHURN)
            elif now - progress > STALL_S:
                return True, max(gap_max, now - progress)
            await asyncio.sleep(0.0002)

    stalled = False
    result: dict = best.get("result") or {}
    for seg in range(SEGMENTS):
        tick0 = bucket.stats["ticks"]
        fu0 = bucket.stats["full_uploads"]
        ov0 = bucket.stats["overflows"]
        t0 = time.perf_counter()
        stalled, gap_max = await churn_pump(SEGMENT_S)
        dt = time.perf_counter() - t0
        ticks = bucket.stats["ticks"] - tick0
        if ticks > 0:
            seg_rates.append(B * ticks / dt)
        lat = np.asarray(owner.lat_ms)
        pcts = np.percentile(lat, [50, 99]) if lat.size else (None, None)
        strict = np.asarray(owner.lat_strict_ms)
        strict_p99 = float(np.percentile(strict, 99)) if strict.size else None
        value = float(np.median(seg_rates)) if seg_rates else warmup_rate
        diags = {
            "full_uploads_delta": bucket.stats["full_uploads"] - fu0,
            "overflows_delta": bucket.stats["overflows"] - ov0,
            "max_tick_gap_ms": round(gap_max * 1e3, 1),
        }
        if pipeline is not None:
            diags["pipeline"] = pipeline
        print(f"{tag}segment {seg + 1}/{SEGMENTS}: {ticks} ticks in {dt:.1f}s "
              f"({dt / max(ticks, 1) * 1e3:.1f} ms/tick, "
              f"max gap {gap_max * 1e3:.0f} ms, "
              f"+{diags['full_uploads_delta']} full uploads)"
              + (" [STALLED]" if stalled else ""), file=sys.stderr)
        note = None
        if stalled:
            note = ("device stalled mid-measurement; median of completed "
                    "segments" if seg_rates
                    else "device stalled before any measured segment; "
                         "warmup rate (incl. compile)")
        result = result_json(
            value, provisional=ab or stalled or seg < SEGMENTS - 1,
            stage=f"{tag}segment-{seg + 1}", segments=seg_rates,
            p50_ms=float(pcts[0]) if pcts[0] is not None else None,
            p99_ms=float(pcts[1]) if pcts[1] is not None else None,
            strict_p99_ms=strict_p99,
            diags=diags,
            note=note)
        best["result"] = result
        emit(result)
        if stalled:
            break

    meas_ticks = bucket.stats["ticks"] - WARMUP_TICKS
    print(
        f"{tag}rows={B} (={TENANTS} tenants) | events/tick~{CHURN}x2 | "
        f"patches/tick={owner.patch_rows / max(meas_ticks, 1):.0f} | "
        f"full_uploads={bucket.stats['full_uploads']} | "
        f"overflows={bucket.stats['overflows']} | "
        f"acked={bucket.stats['acked']}",
        file=sys.stderr,
    )
    # tick-phase profile (fused_* spans recorded by syncer/core.py):
    # the "where does tick time go" answer, per tick, in ms
    from kcp_tpu.utils.trace import REGISTRY

    snap = REGISTRY.snapshot()
    parts = []
    for k, v in sorted(snap.items()):
        if (k.startswith("fused_") and k.endswith("_seconds")
                and isinstance(v, dict) and v["count"]):
            parts.append(f"{k[6:-8]}={v['mean'] * 1e3:.1f}ms"
                         f"(p99 {v['p99'] * 1e3:.1f})")
    if parts:
        print(f"{tag}tick phases: " + " ".join(parts), file=sys.stderr)
    if not stalled:
        # graceful stop, but never let a wedged drain eat the evidence
        try:
            await asyncio.wait_for(core.stop(), timeout=10)
        except Exception:  # noqa: BLE001 — evidence already emitted
            pass
    return result


class _FleetOwner:
    """Open-loop SectionOwner for the fleet A/B: fixed mirror arrays,
    every patch recorded (sha256-compared across modes), no feedback —
    so per-bucket and ragged runs see identical staging schedules and
    the patch-stream byte-equality check is exact."""

    def __init__(self, core, b: int, s: int):
        self.core = core
        self.B, self.S = b, s
        mask = np.zeros(s, bool)
        mask[-max(1, s // 8):] = True
        self._mask = mask
        self.up_vals = np.zeros((b, s), np.uint32)
        self.down_vals = np.zeros((b, s), np.uint32)
        self.patch_rows = 0
        self._digest = None  # lazily-created hashlib stream digest
        self.section = core.register(self, s)
        self.section.bucket.patch_capacity = 8192

    def fused_status_mask(self) -> np.ndarray:
        return self._mask

    def fused_encode(self, key: int):
        return self.up_vals[key], True, self.down_vals[key], True

    def fused_encode_many(self, keys):
        idx = np.fromiter(keys, np.int64, len(keys))
        ones = np.ones(idx.size, bool)
        return self.up_vals[idx], ones, self.down_vals[idx], ones

    def fused_overflow(self) -> None:  # pragma: no cover - fixed vocab
        raise AssertionError("fleet bench vocabulary never grows")

    def fused_apply(self, patches) -> None:
        import hashlib

        if self._digest is None:
            self._digest = hashlib.sha256()
        self.patch_rows += len(patches)
        self._digest.update(np.asarray(
            [(int(k), int(c), int(u)) for k, c, u in patches],
            np.int64).tobytes())

    def digest(self) -> str:
        return self._digest.hexdigest() if self._digest else "empty"


async def _fleet_mode_run(fleet: bool, shape, stragglers: int, steps: int,
                          warmup: int, churn_frac: float,
                          seed: int = 7) -> dict:
    """One lockstep run (per-bucket or ragged): every step churns every
    bucket, then waits for every bucket to tick once — so both modes
    decide the identical row set per tick and the streams compare."""
    from kcp_tpu.syncer.core import FusedCore

    core = FusedCore(batch_window=0.0005, fleet=fleet,
                     use_pallas=True if "--pallas" in sys.argv else None)
    owners = [_FleetOwner(core, b, s) for b, s in shape]
    srng = np.random.default_rng(seed + 1)
    straggler_owners = [
        _FleetOwner(core, int(srng.integers(1, 5)), 8)
        for _ in range(stragglers)]
    all_owners = owners + straggler_owners
    buckets = list({id(o.section.bucket): o.section.bucket
                    for o in all_owners}.values())
    total_rows = sum(o.B for o in all_owners)
    await core.start()

    rng = np.random.default_rng(seed)
    step_times: list[float] = []
    t_start = None
    decided = 0
    last_progress = time.perf_counter()
    for step in range(warmup + steps):
        if step == warmup:
            t_start = time.perf_counter()
            for o in all_owners:
                o.patch_rows = 0
        before = {id(b): b.stats["ticks"] for b in buckets}
        t0 = time.perf_counter()
        for o in all_owners:
            pool = min(o.B, 4096)
            n = max(1, int(pool * churn_frac))
            touched = (rng.choice(pool, size=n, replace=False)
                       if n < pool else np.arange(pool))
            o.up_vals[touched] = rng.integers(
                1, 2**32, (touched.size, o.S), dtype=np.uint32)
            core.enqueue_many(o.section, False, touched.tolist())
        while not all(b.stats["ticks"] > before[id(b)] for b in buckets):
            await asyncio.sleep(0.0002)
            if time.perf_counter() - last_progress > STALL_S:
                raise RuntimeError(f"fleet bench stalled at step {step}")
        last_progress = time.perf_counter()
        if step >= warmup:
            step_times.append(time.perf_counter() - t0)
            decided += total_rows  # every bucket decided all its rows
    wall = time.perf_counter() - t_start
    dispatches = (core._fleet.stats["ticks"] if fleet
                  else sum(b.stats["ticks"] for b in buckets))
    # dispatches since measurement start: subtract warmup's share
    warm_disp = warmup * (1 if fleet else len(buckets))
    dispatches -= warm_disp
    await core.stop()
    lat = np.asarray(step_times) * 1e3
    return {
        "rows": total_rows,
        "buckets": len(buckets),
        "sections": len(all_owners),
        "rows_per_sec": decided / wall,
        "dispatches": int(dispatches),
        "rows_per_dispatch": decided / max(dispatches, 1),
        "tick_ms_p50": float(np.percentile(lat, 50)),
        "tick_ms_p99": float(np.percentile(lat, 99)),
        "patch_rows": sum(o.patch_rows for o in all_owners),
        "stream_digests": [o.digest() for o in all_owners],
    }


async def _fleet_quarantine_drill() -> dict:
    """Green-path drill for the CI gate: a poison row in a 2-bucket
    fleet must quarantine ONLY the poison (segment-scoped bisection)
    while every co-tenant's patch still lands."""
    from kcp_tpu import faults
    from kcp_tpu.syncer.core import FusedCore

    faults.install(faults.FaultInjector("device.step:poison_row=3", seed=0))
    try:
        core = FusedCore(batch_window=0.0005, fleet=True)
        streams: dict[int, set] = {}

        class DrillOwner(_FleetOwner):
            def fused_apply(self, patches):
                streams.setdefault(id(self), set()).update(
                    int(k) for k, _c, _u in patches)

        owners = [DrillOwner(core, 32, w) for w in (8, 16)]
        await core.start()
        keys = list(range(20))
        for o in owners:
            o.up_vals[keys, 0] = 7
            core.enqueue_many(o.section, False, keys)
        deadline = time.perf_counter() + 60
        want = set(keys) - {3}
        while time.perf_counter() < deadline:
            if (core._fleet.stats["quarantined"] >= 2
                    and all(streams.get(id(o), set()) >= want
                            for o in owners)):
                break
            await asyncio.sleep(0.005)
        quarantined = core._fleet.stats["quarantined"]
        co_ok = all(streams.get(id(o), set()) >= want for o in owners)
        only_poison = all(3 not in streams.get(id(o), set()) for o in owners)
        faults.clear()
        await core.stop()
        return {"quarantined": int(quarantined), "co_tenants_ok": bool(co_ok),
                "only_poison": bool(only_poison),
                "ok": bool(quarantined >= 2 and co_ok and only_poison)}
    finally:
        faults.clear()


async def _measure_fleet(best: dict) -> dict:
    """``--fleet``: per-bucket vs ragged fleet dispatch A/B at 10k
    clusters x mixed bucket sizes (a 64-slot main fleet, 32/16-slot mid
    and small buckets, plus many 1-4-row straggler sections in an
    8-slot bucket). Headline value = device-utilization gain (rows
    decided per device dispatch, ragged / per-bucket: the dispatch
    amortization ragged batching exists for); combined reconcile
    throughput and tick latency ride along, and the per-owner patch
    streams must hash identically across modes."""
    rows = int(os.environ.get("KCP_BENCH_FLEET_ROWS", "131072"))
    stragglers = int(os.environ.get("KCP_BENCH_FLEET_STRAGGLERS", "24"))
    steps = int(os.environ.get("KCP_BENCH_FLEET_STEPS", "40"))
    warmup = int(os.environ.get("KCP_BENCH_FLEET_WARMUP", "8"))
    churn_frac = float(os.environ.get("KCP_BENCH_FLEET_CHURN_FRAC", "0.1"))
    shape = [(rows, 64), (max(rows // 8, 64), 32), (max(rows // 64, 16), 16)]
    tenants = sum(b for b, _s in shape) // 13

    results: dict[str, dict] = {}
    for mode, fleet in (("per_bucket", False), ("ragged", True)):
        print(f"--- fleet dispatch mode: {mode} ---", file=sys.stderr)
        r = await _fleet_mode_run(fleet, shape, stragglers, steps, warmup,
                                  churn_frac)
        results[mode] = r
        print(f"{mode}: {r['rows_per_sec'] / 1e6:.2f}M rows/s | "
              f"{r['dispatches']} dispatches | "
              f"{r['rows_per_dispatch'] / 1e3:.0f}k rows/dispatch | "
              f"tick p50 {r['tick_ms_p50']:.1f} ms p99 "
              f"{r['tick_ms_p99']:.1f} ms", file=sys.stderr)
        best["result"] = {
            "metric": "fleet_device_utilization", "unit": "x", "value": 0,
            "stage": f"fleet-{mode}", "provisional": True,
            "fleet_bench": {mode: {k: v for k, v in r.items()
                                   if k != "stream_digests"}},
        }
        emit(best["result"])

    streams_equal = (results["per_bucket"]["stream_digests"]
                     == results["ragged"]["stream_digests"])
    util_gain = (results["ragged"]["rows_per_dispatch"]
                 / max(results["per_bucket"]["rows_per_dispatch"], 1))
    speedup = (results["ragged"]["rows_per_sec"]
               / max(results["per_bucket"]["rows_per_sec"], 1e-9))
    drill = await _fleet_quarantine_drill()
    headline = {
        "metric": "fleet_device_utilization",
        "value": round(util_gain, 2),
        "unit": "x",
        "stage": "fleet-ab",
        "tenants": tenants,
        "fleet_bench": {
            "rows": results["ragged"]["rows"],
            "buckets": results["ragged"]["buckets"],
            "sections": results["ragged"]["sections"],
            "stragglers": stragglers,
            "streams_equal": streams_equal,
            "combined_speedup": round(speedup, 3),
            "combined_rows_per_sec": {
                m: round(r["rows_per_sec"]) for m, r in results.items()},
            "rows_per_dispatch": {
                m: round(r["rows_per_dispatch"]) for m, r in results.items()},
            "dispatches": {m: r["dispatches"] for m, r in results.items()},
            "tick_ms_p50": {
                m: round(r["tick_ms_p50"], 2) for m, r in results.items()},
            "tick_ms_p99": {
                m: round(r["tick_ms_p99"], 2) for m, r in results.items()},
            "quarantine_drill": drill,
        },
    }
    best["result"] = headline
    emit(headline)
    return headline


def main() -> int:
    best: dict = {}
    deadman = Deadman(best)
    # early attempts cap device init at INIT_STALL_S to keep retry budget;
    # the FINAL attempt has nothing left to save for, so a legitimately
    # slow (not hung) init gets the whole remaining window
    if os.environ.get(FINAL_ATTEMPT_ENV) == "1":
        deadman.arm("device-init")
    else:
        deadman.arm("device-init", INIT_STALL_S)
    print("initializing device...", file=sys.stderr, flush=True)

    import jax

    # persistent XLA compilation cache: recompiles are seconds-long p99
    # spikes (and most of warmup); cache them across runs — including the
    # driver's end-of-round run. Repo-local so the artifact rides along.
    from kcp_tpu.cli import enable_compilation_cache

    enable_compilation_cache(default_path=os.path.join(
        os.path.dirname(os.path.abspath(__file__)), ".jax_cache"))

    from kcp_tpu.syncer.core import FusedCore

    dev = jax.devices()[0]
    deadman.arm("measurement")
    print(f"bench device: {dev}", file=sys.stderr)

    if "--fleet" in sys.argv:
        # per-bucket vs ragged fleet dispatch A/B (device lane: runs
        # under the orchestrator's timeout/degraded-fallback discipline)
        asyncio.run(_measure_fleet(best))
        sys.stdout.flush()
        os._exit(0)

    ab = pipeline_arg(sys.argv)
    if ab is None:
        asyncio.run(_measure(best))
    else:
        # serial-vs-double A/B in ONE invocation: each mode gets a fresh
        # loop + core (the jit cache is shared, so the second mode skips
        # most compile time); the combined line is the headline evidence
        results: dict[str, dict] = {}
        for mode in ("serial", "double"):
            print(f"--- pipeline mode: {mode} ---", file=sys.stderr)
            results[mode] = asyncio.run(_measure(best, pipeline=mode, ab=True))
        headline = dict(results[ab])
        headline.pop("provisional", None)
        headline["stage"] = "pipeline-ab"
        headline["pipeline"] = ab
        headline["pipeline_ab"] = {
            mode: {k: r[k] for k in ("value", "segment_rates",
                                     "convergence_p50_ms",
                                     "convergence_p99_ms")
                   if k in r}
            for mode, r in results.items()
        }
        if results["serial"].get("value"):
            headline["pipeline_speedup"] = round(
                results[ab]["value"] / results["serial"]["value"], 3)
        best["result"] = headline
        emit(headline)
    # the last emitted line is the result; exit directly (a wedged device
    # leaves uninterruptible work on the loop — don't hang in teardown)
    sys.stdout.flush()
    os._exit(0)


def _time_kernel(fn, *args, iters: int = 30) -> float:
    """Median-of-three steady-state seconds per call (device inputs)."""
    import jax

    out = fn(*args)
    jax.block_until_ready(out)
    samples = []
    for _ in range(3):
        t0 = time.perf_counter()
        for _ in range(iters):
            out = fn(*args)
        jax.block_until_ready(out)
        samples.append((time.perf_counter() - t0) / iters)
    return sorted(samples)[1]


def suite() -> int:
    """Benchmark the kernel lanes of BASELINE.json (configs[2..4]) plus
    the Pallas-vs-XLA A/B of the fused decision+fanout pass; print a
    markdown table to stderr and one JSON object to stdout.

    Not covered here: configs[0] (the demo scenario — run
    ``contrib/demo/run_demo.py all --check``) and configs[1] (the
    closed-loop syncer measurement — the default ``python bench.py``
    run, whose single JSON line is the headline metric).
    """
    import jax
    import jax.numpy as jnp

    from kcp_tpu.ops.labelmatch import fanout_match
    from kcp_tpu.ops.placement import split_replicas_jit
    from kcp_tpu.ops.schemahash import schema_hashes_jit, tokenize_schemas

    best: dict = {}
    deadman = Deadman(best)
    deadman.arm("suite")

    rng = np.random.default_rng(3)
    rows = []

    def report(final: bool = False) -> None:
        best["result"] = {"suite": [
            {"lane": name, "scale": scale, "rate": rate}
            for name, scale, rate in rows
        ]}
        if not final:
            # partial table: a later attempt should still try for all lanes
            best["result"]["provisional"] = True
        emit(best["result"])

    # configs[2]: splitter bin-packing, 10k workspaces x 8 pclusters
    replicas = jax.device_put(rng.integers(0, 100, 10_000).astype(np.int32))
    avail = jax.device_put(rng.random((10_000, 8)) < 0.9)
    dt = _time_kernel(split_replicas_jit, replicas, avail)
    rows.append(("splitter bin-packing", "10k workspaces x 8 pclusters",
                 f"{10_000 / dt / 1e6:.1f}M splits/s"))
    report()

    # configs[3]: schema hashing for batch bucketing, 5k tenant CRD sets —
    # host tokenization (per-schema) + one device hash reduce over the set
    n_schemas = 5_000
    schemas = [
        {"type": "object", "properties": {
            f"f{i}": {"type": "string"} for i in range(20)},
         "description": str(k)}
        for k in range(n_schemas)
    ]
    t0 = time.perf_counter()
    tokens = tokenize_schemas(schemas)
    host_dt = time.perf_counter() - t0
    toks = jax.device_put(tokens)
    dev_dt = _time_kernel(schema_hashes_jit, toks)
    dt = host_dt / n_schemas + dev_dt / n_schemas
    rows.append(("schema hash bucketing", "5k tenant CRD sets",
                 f"{1 / dt / 1e3:.0f}k schemas/s"))
    report()

    # configs[4]: informer fan-out, 100k objects x 64 selectors
    pair = jax.device_put(rng.integers(1, 1000, (100_000, 8)).astype(np.uint32))
    sels = jax.device_put(rng.integers(1, 1000, 64).astype(np.uint32))
    fan = jax.jit(lambda p, s: fanout_match(p, s).sum(axis=0, dtype=jnp.int32))
    dt = _time_kernel(fan, pair, sels)
    rows.append(("label fan-out", "100k objects x 64 selectors",
                 f"{100_000 / dt / 1e6:.0f}M obj/s"))
    report()

    # Pallas-vs-XLA A/B: the fused decision+fanout pass at bench scale
    # (VERDICT r3 item 3 — the measured comparison)
    try:
        from kcp_tpu.ops.diff import sync_decisions
        from kcp_tpu.ops.pallas_kernels import decide_and_match

        b, s, l, c = B, S, 8, 64
        up = jax.device_put(rng.integers(1, 2**32, (b, s), dtype=np.uint32))
        down = jax.device_put(np.asarray(up))
        upe = jax.device_put(np.ones(b, bool))
        dne = jax.device_put(np.ones(b, bool))
        mask = np.zeros(s, bool)
        mask[-8:] = True
        maskd = jax.device_put(mask)
        pair = jax.device_put(rng.integers(1, 2**32, (b, l), dtype=np.uint32))
        sels = jax.device_put(rng.integers(1, 2**32, c, dtype=np.uint32))

        unfused = jax.jit(lambda uv, ue, dv, de, m, ph, sh: (
            sync_decisions(uv, ue, dv, de, m),
            (fanout_match(ph, sh) & ue[:, None]).sum(axis=0, dtype=jnp.int32)))
        dt_x = _time_kernel(unfused, up, upe, down, dne, maskd, pair, sels)
        rows.append(("decision+fanout XLA", f"{b} rows x {s} slots",
                     f"{b / dt_x / 1e6:.0f}M rows/s"))
        report()
        from kcp_tpu.ops.pallas_kernels import default_interpret

        dt_p = _time_kernel(decide_and_match, up, upe, down, dne, maskd,
                            pair, sels)
        interp = default_interpret()
        rows.append((
            "decision+fanout Pallas"
            + (" [interpret mode]" if interp else ""),
            f"{b} rows x {s} slots",
            f"{b / dt_p / 1e6:.1f}M rows/s ({dt_x / dt_p:.2f}x vs XLA"
            + ("; Mosaic-compiled only on TPU)" if interp else ")"),
        ))
        report()
    except Exception as e:  # noqa: BLE001 — A/B lane is best-effort
        print(f"pallas A/B lane failed: {e}", file=sys.stderr)

    print("| lane | scale | rate |", file=sys.stderr)
    print("|---|---|---|", file=sys.stderr)
    for name, scale, rate in rows:
        print(f"| {name} | {scale} | {rate} |", file=sys.stderr)
    report(final=True)
    sys.stdout.flush()
    os._exit(0)


def admission_bench() -> int:
    """Admission & flow control A/B (``--admission``): happy-path write
    overhead with the chain enabled (quota + flow on, no contention),
    plus the noisy-neighbor storm — 1 tenant flooding writes at 10x its
    token rate alongside quiet tenants. Pure host — no device, no
    orchestrator; one JSON line whose value is the happy-path overhead
    in percent.

    Two overhead measurements ride along:
    - ``overhead_pct`` (the headline): over the full serving path —
      real HTTP server, real client, keep-alive — chain on vs off;
    - ``direct_overhead_pct``: handler-dispatch only (no sockets), the
      strictest view of what the chain itself costs per write.
    """
    import asyncio

    from kcp_tpu.admission import FlowController, build_chain
    from kcp_tpu.apis.scheme import default_scheme
    from kcp_tpu.server.handler import RestHandler
    from kcp_tpu.server.httpd import Request
    from kcp_tpu.store.store import LogicalStore

    writes = int(os.environ.get("KCP_BENCH_ADM_WRITES", "4000"))
    tenants = int(os.environ.get("KCP_BENCH_ADM_TENANTS", "100"))
    storm_s = float(os.environ.get("KCP_BENCH_ADM_STORM_S", "2.5"))
    flow_rate = float(os.environ.get("KCP_BENCH_ADM_RATE", "40"))
    flood_x = 10  # the storm tenant's send rate vs its token rate
    scheme = default_scheme()

    def cm_body(name: str) -> bytes:
        return json.dumps({
            "apiVersion": "v1", "kind": "ConfigMap",
            "metadata": {"name": name, "namespace": "default"},
            "data": {"v": name},
        }).encode()

    def path(cluster: str) -> str:
        return f"/clusters/{cluster}/api/v1/namespaces/default/configmaps"

    # ---- direct-dispatch A/B: the chain's own cost per write
    def fresh_handler(admission_on: bool):
        store = LogicalStore(indexed=True)
        chain = None
        if admission_on:
            chain = build_chain(store, flow=FlowController(
                concurrency=64, rate=1e9, burst=1e9))
        return RestHandler(store, scheme, admission=chain)

    async def run_direct_ab() -> dict[bool, float]:
        """Alternating small segments against two live handlers; best
        segment rate per mode. Heap/GC drift lands on both modes instead
        of whichever ran second (a fresh-process A/B here shows +-15us
        run-to-run noise — 3x the true chain cost)."""
        import gc

        handlers = {on: fresh_handler(on) for on in (False, True)}
        seg_n = max(128, writes // 8)
        counters = {False: 0, True: 0}
        best = {False: 0.0, True: 0.0}

        async def burst(on: bool) -> None:
            handler = handlers[on]
            k0 = counters[on]
            counters[on] = k0 + seg_n
            reqs = [Request("POST", path(f"t{(k0 + i) % tenants}"), {}, {},
                            cm_body(f"d{int(on)}-{k0 + i}"))
                    for i in range(seg_n)]
            gc.collect()
            t0 = time.perf_counter()
            for r in reqs:
                resp = await handler(r)
                assert resp.status == 201, resp.body
            best[on] = max(best[on], seg_n / (time.perf_counter() - t0))

        for on in (False, True):  # warmup segment, untimed
            await burst(on)
            best[on] = 0.0
        for _seg in range(8):
            await burst(bool(_seg % 2))
        return best

    direct = asyncio.run(run_direct_ab())
    direct_overhead = (direct[False] / direct[True] - 1.0) * 100.0

    # ---- serving-path A/B: chain on/off over real HTTP (the overhead a
    # client actually observes; TLS off so the delta is the chain, not
    # handshake noise). Both servers run CONCURRENTLY and the timed
    # segments alternate between them, so host-wide drift (GC, noisy CI
    # neighbors) hits both modes symmetrically instead of whichever mode
    # ran second.
    def run_http_ab() -> dict:
        from kcp_tpu.server import Config, RestClient
        from kcp_tpu.server.threaded import ServerThread

        # ONE server, one client, one kept-alive connection; the A/B
        # toggles the handler's admission chain between alternating
        # segments (an attribute swap, done on the serving loop). Two
        # separate server processes showed whole-percentage systematic
        # bias from thread/core/allocator luck — with a single serving
        # stack the only difference between segments IS the chain.
        # Happy path means NO throttling: budgets are out of reach, so
        # one client hammering one flow measures the chain, not a 429.
        prev = {k: os.environ.get(k)
                for k in ("KCP_ADMISSION", "KCP_FLOW_RATE", "KCP_FLOW_BURST")}
        os.environ["KCP_ADMISSION"] = "1"
        os.environ["KCP_FLOW_RATE"] = "1000000000"
        os.environ["KCP_FLOW_BURST"] = "1000000000"
        # many SHORT alternating segments: host drift over the ~seconds
        # of measurement (thermal, background load) changes slowly, so
        # toggling modes every few tens of ms makes each mode sample the
        # same drift profile
        segments = 40
        seg_n = max(48, writes // 20)
        lat: dict[bool, list[float]] = {False: [], True: []}
        rates: dict[bool, float] = {False: 0.0, True: 0.0}
        try:
            with ServerThread(Config(durable=False,
                                     install_controllers=False,
                                     tls=False)) as st:
                handler = st.server.handler
                chain = handler.admission
                assert chain is not None
                c = RestClient(st.server.address, cluster="bench")
                for i in range(64):  # warm connection + discovery
                    c.create("configmaps", json.loads(
                        cm_body(f"warm-{i}")), "default")
                for seg in range(segments):
                    on = bool(seg % 2)
                    # swap on the serving loop so no request observes a
                    # half-written handler
                    st.call(setattr, handler, "admission",
                            chain if on else None)
                    samples = lat[on]
                    t0 = time.perf_counter()
                    for i in range(seg_n):
                        body = json.loads(cm_body(f"h{seg}-{i}"))
                        ts = time.perf_counter()
                        c.create("configmaps", body, "default")
                        samples.append(time.perf_counter() - ts)
                    rates[on] = max(rates[on],
                                    seg_n / (time.perf_counter() - t0))
                c.close()
        finally:
            for k, v in prev.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v
        # MEDIAN per-request latency, not best throughput: robust to the
        # stragglers (GC pauses, scheduler hiccups) that make a rate
        # ratio of two short runs swing by whole percentage points
        med = {on: float(np.median(np.asarray(lat[on]))) for on in lat}
        return {
            "overhead_pct": (med[True] / med[False] - 1.0) * 100.0,
            "med_off_us": med[False] * 1e6,
            "med_on_us": med[True] * 1e6,
            "rates": rates,
        }

    http_ab = run_http_ab()
    http_rates = http_ab["rates"]
    overhead = http_ab["overhead_pct"]

    # ---- noisy-neighbor storm: 1 flooding tenant vs quiet tenants
    async def run_phase(flood: bool, quiet_rps: float) -> dict:
        store = LogicalStore(indexed=True)
        chain = build_chain(store, flow=FlowController(
            concurrency=16, rate=flow_rate, burst=2 * flow_rate,
            queues=16, queue_depth=32, seed=1))
        handler = RestHandler(store, scheme, admission=chain)
        quiet_lat: list[float] = []
        counters = {"quiet_ok": 0, "quiet_rejected": 0, "flood_ok": 0,
                    "flood_429": 0, "flood_other": 0, "retry_after": 0}

        async def tenant(cluster: str, rps: float, is_flood: bool) -> None:
            loop = asyncio.get_running_loop()
            t0 = loop.time()
            k = 0
            while True:
                target = t0 + k / rps
                if target - t0 >= storm_s:
                    return
                now = loop.time()
                if target > now:
                    await asyncio.sleep(target - now)
                body = cm_body(f"{cluster}-{'f' if is_flood else 'q'}-{k}")
                ts = loop.time()
                resp = await handler(
                    Request("POST", path(cluster), {}, {}, body))
                dt = loop.time() - ts
                if is_flood:
                    if resp.status == 201:
                        counters["flood_ok"] += 1
                    elif resp.status == 429:
                        counters["flood_429"] += 1
                        if resp.headers.get("Retry-After"):
                            counters["retry_after"] += 1
                    else:
                        counters["flood_other"] += 1
                else:
                    quiet_lat.append(dt)
                    if resp.status == 201:
                        counters["quiet_ok"] += 1
                    else:
                        counters["quiet_rejected"] += 1
                k += 1

        tasks = [tenant(f"q{i}", quiet_rps, False)
                 for i in range(tenants - 1)]
        if flood:
            tasks.append(tenant("storm", flood_x * flow_rate, True))
        await asyncio.gather(*tasks)
        lat = np.asarray(quiet_lat)
        return {
            "quiet_p50_ms": round(float(np.percentile(lat, 50)) * 1e3, 3),
            "quiet_p99_ms": round(float(np.percentile(lat, 99)) * 1e3, 3),
            **counters,
        }

    quiet_rps = max(1.0, flow_rate / 8)
    baseline = asyncio.run(run_phase(flood=False, quiet_rps=quiet_rps))
    storm = asyncio.run(run_phase(flood=True, quiet_rps=quiet_rps))
    # ratio floor 0.5ms: sub-millisecond baselines would turn scheduler
    # jitter into the headline; queueing-induced starvation is >> 1ms
    p99_ratio = storm["quiet_p99_ms"] / max(baseline["quiet_p99_ms"], 0.5)
    flood_total = storm["flood_ok"] + storm["flood_429"] + storm["flood_other"]

    out = {
        "metric": "admission_overhead_pct",
        "value": round(overhead, 2),
        "unit": "%",
        "admission_bench": {
            "happy": {
                "writes": writes,
                "http_off_per_s": round(http_rates[False]),
                "http_on_per_s": round(http_rates[True]),
                "http_med_off_us": round(http_ab["med_off_us"], 1),
                "http_med_on_us": round(http_ab["med_on_us"], 1),
                "overhead_pct": round(overhead, 2),
                "direct_off_per_s": round(direct[False]),
                "direct_on_per_s": round(direct[True]),
                "direct_overhead_pct": round(direct_overhead, 2),
            },
            "storm": {
                "tenants": tenants,
                "flow_rate_per_s": flow_rate,
                "flood_x": flood_x,
                "storm_s": storm_s,
                "baseline_quiet_p99_ms": baseline["quiet_p99_ms"],
                "storm_quiet_p99_ms": storm["quiet_p99_ms"],
                "quiet_p99_ratio": round(p99_ratio, 3),
                "quiet_ok": storm["quiet_ok"],
                "quiet_rejected": storm["quiet_rejected"],
                "flood_ok": storm["flood_ok"],
                "flood_429": storm["flood_429"],
                "flood_sent": flood_total,
                "flood_retry_after_seen": storm["retry_after"] > 0,
            },
        },
    }
    emit(out)
    return 0


def store_bench() -> int:
    """BASELINE configs[4] host-side scenario: 100k-object list + watch
    fan-out against C selector-bound watches, A/B across the indexed
    (KCP_STORE_INDEX=1, CoW + batched fan-out) and legacy (linear scan +
    per-event deepcopy) store read paths. Pure host — no device, no
    orchestrator; one JSON line with the combined speedup as the value.
    """
    from kcp_tpu.store.selectors import parse_selector
    from kcp_tpu.store.store import LogicalStore

    n_objects = int(os.environ.get("KCP_BENCH_STORE_OBJECTS", "100000"))
    n_watches = int(os.environ.get("KCP_BENCH_STORE_WATCHES", "64"))
    n_lists = int(os.environ.get("KCP_BENCH_STORE_LISTS", "3"))
    n_muts = int(os.environ.get("KCP_BENCH_STORE_MUTS", "2000"))
    teams = [f"t{i}" for i in range(n_watches)]
    clusters = [f"c{i}" for i in range(16)]
    namespaces = [f"ns{i}" for i in range(8)]

    def run(indexed: bool) -> dict:
        s = LogicalStore(indexed=indexed)
        rng = np.random.default_rng(11)
        for i in range(n_objects):
            s.create("configmaps", clusters[i % 16], {
                "apiVersion": "v1", "kind": "ConfigMap",
                "metadata": {"name": f"cm-{i}",
                             "namespace": namespaces[i % 8],
                             "labels": {"team": teams[i % n_watches],
                                        "tier": str(i % 7)}},
                "data": {"v": str(i)},
            })
        watches = [s.watch("configmaps", selector=parse_selector(f"team={t}"))
                   for t in teams]

        t0 = time.perf_counter()
        for _ in range(n_lists):
            items, _rv = s.list("configmaps")
            assert len(items) == n_objects
            items, _rv = s.list("configmaps", clusters[0], namespaces[0])
        t_list = time.perf_counter() - t0

        events = 0
        t0 = time.perf_counter()
        for m in range(n_muts):
            i = int(rng.integers(n_objects))
            # every 8th mutation flips the team label — the selector-bound
            # ADDED/DELETED rewrite path, not just the match
            team = teams[(i + m) % n_watches] if m % 8 == 0 else teams[i % n_watches]
            s.update("configmaps", clusters[i % 16], {
                "apiVersion": "v1", "kind": "ConfigMap",
                "metadata": {"name": f"cm-{i}",
                             "namespace": namespaces[i % 8],
                             "labels": {"team": team, "tier": str(i % 7)}},
                "data": {"v": f"m{m}"},
            })
            if m % 128 == 127:
                events += sum(len(w.drain()) for w in watches)
        events += sum(len(w.drain()) for w in watches)
        t_fanout = time.perf_counter() - t0
        s.close()
        return {"list_s": round(t_list, 4), "fanout_s": round(t_fanout, 4),
                "events": events}

    legacy = run(False)
    indexed = run(True)
    combined = (legacy["list_s"] + legacy["fanout_s"]) / max(
        indexed["list_s"] + indexed["fanout_s"], 1e-9)
    out = {
        "metric": "store_read_path_speedup",
        "value": round(combined, 2),
        "unit": "x",
        "store_bench": {
            "objects": n_objects, "watches": n_watches,
            "lists": n_lists, "mutations": n_muts,
            "list_speedup": round(legacy["list_s"] / max(indexed["list_s"], 1e-9), 2),
            "fanout_speedup": round(legacy["fanout_s"] / max(indexed["fanout_s"], 1e-9), 2),
            "events_equal": legacy["events"] == indexed["events"],
            "indexed": indexed, "legacy": legacy,
        },
    }
    emit(out)
    return 0


def placement_bench() -> int:
    """Fleet bin-pack A/B (``--placement``): BASELINE configs[2] — the
    deployment-splitter replica bin-pack at 10k workspaces x 8 pclusters
    with lognormal-skewed capacity — solved as ONE device batch
    (fleet/solver.solve_batched via FleetSolver) vs the pre-fleet
    splitter's per-workspace host loop (one solve per root Deployment).
    Rows are independent, so both must produce the byte-identical
    assignment the numpy host twin gives; the speedup is pure batching.
    One JSON line; the batched-vs-loop throughput ratio is the value.
    """
    from kcp_tpu.fleet.solver import FleetSolver, solve_host

    W = int(os.environ.get("KCP_BENCH_PLACEMENT_WORKSPACES", "10000"))
    P = int(os.environ.get("KCP_BENCH_PLACEMENT_PCLUSTERS", "8"))
    spread = int(os.environ.get("KCP_BENCH_PLACEMENT_SPREAD", "2"))
    iters = int(os.environ.get("KCP_BENCH_PLACEMENT_ITERS", "5"))
    # the loop lane may sample (then extrapolate): at full scale it IS
    # the slow side, and CI smoke shouldn't pay 10k python solves twice
    loop_rows = min(
        int(os.environ.get("KCP_BENCH_PLACEMENT_LOOP_ROWS", "0")) or W, W)
    dirty = int(os.environ.get("KCP_BENCH_PLACEMENT_DIRTY_ROWS", "37"))

    rng = np.random.default_rng(17)
    demand = rng.integers(0, 48, W).astype(np.int32)
    alloc = np.clip(rng.lognormal(3.0, 1.2, P), 1, 30000).astype(np.int32)
    cand = rng.random((W, P)) < 0.9
    region = rng.integers(0, 4, P).astype(np.int32)
    home = rng.integers(-1, 4, W).astype(np.int32)

    solver = FleetSolver(spread=spread)
    solver.solve(demand, cand, alloc, region, home)  # compile warm-up
    t0 = time.perf_counter()
    for _ in range(iters):
        dev = solver.solve(demand, cand, alloc, region, home)
    batched_s = (time.perf_counter() - t0) / iters
    # solve() returns the solver's live cache — snapshot it before the
    # incremental lane below scatters the dirty-row delta into it
    dev = dev.copy()

    host = solve_host(demand, cand, alloc, region, home, spread)

    # the pre-fleet splitter re-solved each workspace on its own: one
    # host solve per row, W dispatches per fleet pass
    per = np.zeros_like(host)
    t0 = time.perf_counter()
    for i in range(loop_rows):
        per[i] = solve_host(demand[i:i + 1], cand[i:i + 1], alloc, region,
                            home[i:i + 1], spread)[0]
    loop_sample_s = time.perf_counter() - t0
    loop_s = loop_sample_s * (W / max(loop_rows, 1))

    # incremental re-solve: a dirty candidate delta must touch exactly
    # those rows and still match a from-scratch host recompute
    idx = rng.choice(W, size=min(dirty, W), replace=False)
    cand2 = cand.copy()
    cand2[idx] = rng.random((idx.size, P)) < 0.7
    before = solver.stats["rows_solved"]
    dev2 = solver.solve(demand, cand2, alloc, region, home,
                        rows=[int(i) for i in idx])
    inc_rows = solver.stats["rows_solved"] - before
    host2 = solve_host(demand, cand2, alloc, region, home, spread)

    speedup = loop_s / max(batched_s, 1e-9)
    out = {
        "metric": "placement_batched_speedup",
        "value": round(speedup, 2),
        "unit": "x",
        "placement_bench": {
            "workspaces": W, "pclusters": P, "spread": spread,
            "iters": iters, "loop_rows_sampled": loop_rows,
            "batched_ms": round(batched_s * 1e3, 3),
            "per_workspace_ms": round(loop_s * 1e3, 3),
            "batched_rows_per_s": int(W / max(batched_s, 1e-9)),
            "per_workspace_rows_per_s": int(W / max(loop_s, 1e-9)),
            "assignment_equal_host": bool((dev == host).all()),
            "assignment_equal_per_workspace": bool(
                (per[:loop_rows] == host[:loop_rows]).all()),
            "total_replicas": int(host.sum()),
            "overcommit_rows": int((dev.sum(axis=1) > demand).sum()),
            "noncandidate_replicas": int(dev[~cand].sum()),
            "incremental": {
                "dirty_rows": int(idx.size),
                "rows_solved": int(inc_rows),
                "mismatches": int((dev2 != host2).any(axis=1).sum()),
            },
        },
    }
    emit(out)
    return 0


def encode_bench() -> int:
    """Encode-once serving A/B (``--encode``): list-encode and
    watch-fan-out-encode through the real RestHandler at the BASELINE
    fan-out shape (100k objects x 64 watchers by default), with the
    store's serialization cache on vs off (``KCP_ENCODE_CACHE=1`` vs
    ``=0`` equivalent, toggled per-store in-process). Pure host, no
    sockets: watch producers stream into capture sinks that perform
    exactly the encoding ``httpd.StreamResponse`` would, so the measured
    delta is the serialization work itself. The runs also cross-check
    that cached and uncached serving produce byte-identical wires.
    """
    import asyncio
    import hashlib

    from kcp_tpu.apis.scheme import default_scheme
    from kcp_tpu.server.handler import RestHandler
    from kcp_tpu.server.httpd import Request
    from kcp_tpu.store.store import LogicalStore

    n_objects = int(os.environ.get("KCP_BENCH_ENCODE_OBJECTS", "100000"))
    n_watchers = int(os.environ.get("KCP_BENCH_ENCODE_WATCHES", "64"))
    n_lists = int(os.environ.get("KCP_BENCH_ENCODE_LISTS", "3"))
    n_muts = int(os.environ.get("KCP_BENCH_ENCODE_MUTS", "500"))

    class _CaptureStream:
        """StreamResponse's encode surface without a socket: the json
        sends re-serialize exactly like httpd.StreamResponse (that cost
        is what the uncached arm measures), the raw send takes the
        relay's pre-encoded lines. Wire bytes are kept and digested
        *after* the timed window so hashing never dilutes the A/B."""

        def __init__(self):
            self.chunks: list[bytes] = []
            self.events = 0
            self.encode_s = 0.0  # time spent serializing (json arms)

        async def send_json(self, obj):
            t0 = time.perf_counter()
            data = json.dumps(obj).encode() + b"\n"
            self.encode_s += time.perf_counter() - t0
            self.chunks.append(data)
            self.events += 1

        async def send_json_many(self, objs):
            if not objs:
                return
            t0 = time.perf_counter()
            data = b"".join(json.dumps(o).encode() + b"\n" for o in objs)
            self.encode_s += time.perf_counter() - t0
            self.chunks.append(data)
            self.events += len(objs)

        async def send_raw_many(self, lines):
            if not lines:
                return
            self.chunks.append(b"".join(lines))
            self.events += len(lines)

    def _cm(i: int, v: str) -> dict:
        # a realistically-sized ConfigMap (~0.5 KiB encoded): listed
        # k8s objects carry annotations and multi-key payloads, and the
        # serialization cost the cache removes scales with that
        return {
            "apiVersion": "v1", "kind": "ConfigMap",
            "metadata": {"name": f"cm-{i}", "namespace": f"ns{i % 8}",
                         "uid": f"uid-{i}",  # fixed: runs must be byte-equal
                         "labels": {"team": f"t{i % 64}", "tier": str(i % 7)},
                         "annotations": {
                             "kcp.dev/owned-by": f"workspace-{i % 128}",
                             "kubectl.kubernetes.io/last-applied-configuration":
                                 f"cm-{i}/rev-{v}",
                             "config.example.dev/checksum": f"{i:08x}{i:08x}",
                         }},
            "data": {"server.yaml": f"replicas: {i % 9}\nshard: {i % 64}\n",
                     "feature-flags": f"a={i % 2},b={i % 3},c={i % 5}",
                     "rev": v},
        }

    async def run(cache_on: bool) -> dict:
        from kcp_tpu.utils.trace import REGISTRY

        hist = REGISTRY.histogram("response_encode_seconds")
        store = LogicalStore(indexed=True, encode_cache=cache_on,
                             clock=lambda: 1_700_000_000.0)
        handler = RestHandler(store, default_scheme(), admission=None)
        for i in range(n_objects):
            store.create("configmaps", f"c{i % 16}", _cm(i, str(i)))

        digest = hashlib.sha256()
        lreq = Request("GET", "/clusters/*/api/v1/configmaps", {}, {}, b"")
        # cold pass populates the byte cache (all misses); timed apart so
        # the steady-state number is the warm cache the fleet serves from
        t0 = time.perf_counter()
        resp = await handler(lreq)
        cold_list_s = time.perf_counter() - t0
        digest.update(resp.body)
        bodies = []
        enc0 = hist.total
        t0 = time.perf_counter()
        for _ in range(n_lists):
            resp = await handler(lreq)
            bodies.append(resp.body)
        t_list = time.perf_counter() - t0
        # serialization seconds alone (the handler meters both the splice
        # and the dict-dump list paths into response_encode_seconds)
        list_encode_s = hist.total - enc0
        for body in bodies:
            digest.update(body)
        bodies = []
        # churned lists: a mutation between lists moves the store RV, so
        # the RV-keyed body cache misses and the byte-splice over the
        # (warm) per-record cache is what gets measured
        enc0 = hist.total
        t0 = time.perf_counter()
        for j in range(n_lists):
            store.update("configmaps", "c0", _cm(0, f"l{j}"))
            resp = await handler(lreq)
            bodies.append(resp.body)
        t_churn = time.perf_counter() - t0
        churn_encode_s = hist.total - enc0
        for body in bodies:
            digest.update(body)
        del bodies

        wreq = Request("GET", "/clusters/*/api/v1/configmaps",
                       {"watch": ["true"]}, {}, b"")
        sinks, tasks = [], []
        for _ in range(n_watchers):
            stream = await handler(wreq)
            sink = _CaptureStream()
            sinks.append(sink)
            tasks.append(asyncio.ensure_future(stream.producer(sink)))
        await asyncio.sleep(0.01)  # let every producer subscribe

        enc0 = hist.total
        t0 = time.perf_counter()
        for m in range(n_muts):
            i = m % n_objects
            store.update("configmaps", f"c{i % 16}", _cm(i, f"m{m}"))
            if m % 64 == 63:
                await asyncio.sleep(0)  # let the relays drain the burst
        deadline = time.monotonic() + 120
        while (min(s.events for s in sinks) < n_muts
               and time.monotonic() < deadline):
            await asyncio.sleep(0)
        t_fanout = time.perf_counter() - t0
        # serialization seconds alone: the raw relay meters its line
        # encodes into response_encode_seconds, the json arms meter their
        # dumps in the sink — exactly one term is nonzero per arm
        fanout_encode_s = (hist.total - enc0
                           + sum(s.encode_s for s in sinks))
        store.close()
        await asyncio.gather(*tasks, return_exceptions=True)
        handler.close()
        for s in sinks:
            for chunk in s.chunks:
                digest.update(chunk)
        return {"cold_list_s": round(cold_list_s, 4),
                "list_s": round(t_list, 4),
                "churn_list_s": round(t_churn, 4),
                "fanout_s": round(t_fanout, 4),
                "list_encode_s": round(list_encode_s, 4),
                "churn_encode_s": round(churn_encode_s, 4),
                "fanout_encode_s": round(fanout_encode_s, 4),
                "events": sum(s.events for s in sinks),
                "sha256": digest.hexdigest()}

    cached = asyncio.run(run(True))
    legacy = asyncio.run(run(False))
    combined = (
        legacy["list_s"] + legacy["churn_list_s"] + legacy["fanout_s"]
    ) / max(
        cached["list_s"] + cached["churn_list_s"] + cached["fanout_s"], 1e-9)
    out = {
        "metric": "encode_once_speedup",
        "value": round(combined, 2),
        "unit": "x",
        "encode_bench": {
            "objects": n_objects, "watchers": n_watchers,
            "lists": n_lists, "mutations": n_muts,
            "list_speedup": round(
                legacy["list_s"] / max(cached["list_s"], 1e-9), 2),
            "churn_list_speedup": round(
                legacy["churn_list_s"] / max(cached["churn_list_s"], 1e-9), 2),
            "fanout_speedup": round(
                legacy["fanout_s"] / max(cached["fanout_s"], 1e-9), 2),
            "list_encode_speedup": round(
                legacy["list_encode_s"] / max(cached["list_encode_s"], 1e-9), 2),
            "churn_encode_speedup": round(
                legacy["churn_encode_s"]
                / max(cached["churn_encode_s"], 1e-9), 2),
            "fanout_encode_speedup": round(
                legacy["fanout_encode_s"]
                / max(cached["fanout_encode_s"], 1e-9), 2),
            "events_equal": legacy["events"] == cached["events"],
            "bytes_equal": legacy["sha256"] == cached["sha256"],
            "cached": cached, "legacy": legacy,
        },
    }
    emit(out)
    return 0


def _pagination_cm(i: int) -> dict:
    # same realistic ~0.5 KiB shape as the encode bench: the allocation
    # the page bound caps scales with per-object size
    return {
        "apiVersion": "v1", "kind": "ConfigMap",
        "metadata": {"name": f"cm-{i:06d}", "namespace": f"ns{i % 8}",
                     "uid": f"uid-{i}",
                     "labels": {"team": f"t{i % 64}", "tier": str(i % 7)},
                     "annotations": {
                         "kcp.dev/owned-by": f"workspace-{i % 128}",
                         "kubectl.kubernetes.io/last-applied-configuration":
                             f"cm-{i}/rev-0",
                         "config.example.dev/checksum": f"{i:08x}{i:08x}",
                     }},
        "data": {"server.yaml": f"replicas: {i % 9}\nshard: {i % 64}\n",
                 "feature-flags": f"a={i % 2},b={i % 3},c={i % 5}",
                 "rev": "0"},
    }


def _pagination_ab(n_objects: int, page: int) -> dict:
    """One paged-vs-unpaged relist A/B through the real RestHandler:
    peak allocation (tracemalloc) of a full one-shot relist vs iterating
    limit/continue pages holding at most one page at a time — with the
    concatenated page bytes proven sha256-identical to the one-shot
    ``items`` span. Used by ``--pagination`` and embedded in the
    gauntlet scorecard as the relist-memory column."""
    import asyncio
    import hashlib
    import tracemalloc

    from kcp_tpu.apis.scheme import default_scheme
    from kcp_tpu.server.handler import RestHandler
    from kcp_tpu.server.httpd import Request
    from kcp_tpu.store.store import LogicalStore

    marker = b'"items": ['
    rv_re = re.compile(rb'"resourceVersion": "(\d+)"')
    cont_re = re.compile(rb'"continue": "([^"]*)"')

    def span_of(body: bytes) -> bytes:
        i = body.find(marker)
        assert i >= 0 and body.endswith(b"]}")
        return body[i + len(marker):-2]

    def head_meta(body: bytes) -> tuple[str, str]:
        """(rv, continue) parsed from the envelope head bytes alone —
        what a streaming client reads; never materializes item dicts."""
        head = body[:body.find(marker)]
        rv_m = rv_re.search(head)
        cont_m = cont_re.search(head)
        return (rv_m.group(1).decode() if rv_m else "",
                cont_m.group(1).decode() if cont_m else "")

    async def run() -> dict:
        store = LogicalStore(indexed=True, encode_cache=True,
                             clock=lambda: 1_700_000_000.0)
        handler = RestHandler(store, default_scheme(), admission=None)
        for i in range(n_objects):
            store.create("configmaps", f"c{i % 16}", _pagination_cm(i))
        path = "/clusters/*/api/v1/configmaps"
        # warm the per-record byte cache outside both timed/traced
        # windows so the A/B measures body assembly, not first-encode
        await handler(Request("GET", path, {}, {}, b""))

        tracemalloc.start()
        tracemalloc.reset_peak()
        base = tracemalloc.get_traced_memory()[0]
        t0 = time.perf_counter()
        resp = await handler(Request("GET", path, {}, {}, b""))
        body = resp.body
        unpaged_s = time.perf_counter() - t0
        unpaged_peak = tracemalloc.get_traced_memory()[1] - base
        tracemalloc.stop()
        # verification outside the traced window: neither arm's peak
        # should include the A/B's own proof bookkeeping
        one_shot_sha = hashlib.sha256(span_of(body)).hexdigest()
        rv, _ = head_meta(body)
        del resp, body

        tracemalloc.start()
        tracemalloc.reset_peak()
        base = tracemalloc.get_traced_memory()[0]
        digest = hashlib.sha256()
        pages = 0
        cont = None
        first = True
        rv_paged = None
        t0 = time.perf_counter()
        while True:
            q = {"limit": [str(page)]}
            if cont:
                q["continue"] = [cont]
            resp = await handler(Request("GET", path, q, {}, b""))
            body = resp.body
            pages += 1
            # hash through a memoryview: the page's items bytes feed the
            # equality proof without a second whole-page copy
            i = body.find(marker)
            assert i >= 0 and body.endswith(b"]}")
            if len(body) - i - len(marker) > 2:
                if not first:
                    digest.update(b", ")
                digest.update(memoryview(body)[i + len(marker):-2])
                first = False
            page_rv, cont = head_meta(body)
            if rv_paged is None:
                rv_paged = page_rv
            del resp, body
            if not cont:
                break
        paged_s = time.perf_counter() - t0
        paged_peak = tracemalloc.get_traced_memory()[1] - base
        tracemalloc.stop()
        store.close()
        handler.close()
        return {
            "objects": n_objects, "page": page, "pages": pages,
            "rv_equal": rv == rv_paged,
            "bytes_equal": digest.hexdigest() == one_shot_sha,
            "sha256": one_shot_sha,
            "unpaged_peak_kb": round(unpaged_peak / 1024),
            "paged_peak_kb": round(paged_peak / 1024),
            "peak_cut": round(unpaged_peak / max(paged_peak, 1), 2),
            "unpaged_s": round(unpaged_s, 4),
            "paged_s": round(paged_s, 4),
        }

    return asyncio.run(run())


def pagination_bench() -> int:
    """Paged-relist A/B (``--pagination``): peak relist allocation with
    one-shot lists vs limit/continue pages at the BASELINE 100k-object
    watch-fan-out shape. The headline is the peak-allocation cut; the
    run self-verifies that concatenated pages are byte-identical to the
    one-shot body (anything else is a paging bug, not a measurement)."""
    n_objects = int(os.environ.get("KCP_BENCH_PAG_OBJECTS", "100000"))
    page = int(os.environ.get("KCP_BENCH_PAG_PAGE", "10000"))
    ab = _pagination_ab(n_objects, page)
    emit({
        "metric": "paged_relist_peak_cut",
        "value": ab["peak_cut"],
        "unit": "x",
        "pagination_bench": ab,
    })
    return 0


def gauntlet_bench() -> int:
    """The north-star gauntlet (``--gauntlet``): one composed run per
    BASELINE.json config — router + shard fleets + replicas, smart
    clients as the default write driver — each scored by the scenario
    engine (reconciles/sec as acked-writes/sec, spec->status
    convergence p50/p99 from assembled trace phases, per-phase RSS) and
    emitted as one scorecard row. A paged-relist A/B at the 100k-object
    fan-out shape rides the scorecard as the relist-memory column.

    Knobs: KCP_GAUNTLET_SCALE (divisor, default 50 — CI runs 1/50th of
    BASELINE shape; 1 is the full gauntlet), KCP_GAUNTLET_CONFIGS (csv
    of config indices, default all), KCP_GAUNTLET_SOAK (repeat each
    config's phases N times so the RSS-growth SLO spans a soak, with a
    scorecard snapshot per round), KCP_GAUNTLET_OPS (override ops per
    tenant per phase), KCP_GAUNTLET_OUT (also write the scorecard to a
    file), KCP_BENCH_PAG_OBJECTS/_PAGE (relist A/B shape)."""
    import dataclasses

    from kcp_tpu.scenarios.engine import run_scenario
    from kcp_tpu.scenarios.spec import SLO, Phase, ScenarioSpec
    from kcp_tpu.utils.trace import REGISTRY

    divisor = float(os.environ.get("KCP_GAUNTLET_SCALE", "50"))
    scale = 1.0 / max(divisor, 1e-9)
    soak = int(os.environ.get("KCP_GAUNTLET_SOAK", "0"))
    ops_override = os.environ.get("KCP_GAUNTLET_OPS", "")
    out_path = os.environ.get("KCP_GAUNTLET_OUT", "")

    try:
        with open(os.path.join(os.path.dirname(os.path.abspath(__file__)),
                               "BASELINE.json"), encoding="utf-8") as f:
            cfg_names = list(json.load(f).get("configs", []))
    except OSError:
        cfg_names = []

    slos_common = (
        SLO("no-lost-acked-writes", "lost_acked_writes", "==", 0),
        SLO("no-lost-watch-events", "lost_watch_events", "==", 0),
        SLO("bounded-rss-growth", "memory_growth_ratio", "<=", 3.0),
    )
    slos_crd = (
        SLO("no-lost-acked-cr-writes", "lost_acked_writes", "==", 0),
        SLO("all-crds-established", "crd_unestablished", "==", 0),
        SLO("bounded-rss-growth", "memory_growth_ratio", "<=", 3.0),
    )
    phases = (Phase("warm", ops_per_tenant=8),
              Phase("sustain", ops_per_tenant=24, settle_s=0.5),
              Phase("drain", ops_per_tenant=8, settle_s=0.5))
    # one full-scale spec per BASELINE.json config line, in file order;
    # .scaled() brings each down to 1/KCP_GAUNTLET_SCALE of the
    # BASELINE shape (SLO targets never scale)
    specs = [
        # contrib/demo: splitter over 2 physical clusters, 1 logical
        ScenarioSpec(
            name="gauntlet-demo",
            description="demo shape: 2-shard fleet, a handful of "
                        "logical clusters, smart-client writers",
            topology="fleet", topology_args={"shards": 2},
            tenants=100, watchers_per_tenant=1, phases=phases,
            options={"smart_all": True}, slos=slos_common),
        # syncer diff batched across 1k logical clusters (cm churn)
        ScenarioSpec(
            name="gauntlet-syncer-churn",
            description="1k-logical-cluster ConfigMap churn through a "
                        "durable 4-shard fleet, smart-client writers",
            topology="fleet", topology_args={"shards": 4, "durable": True},
            tenants=1000, watchers_per_tenant=1, phases=phases,
            options={"smart_all": True}, slos=slos_common),
        # splitter bin-packing across 10k workspaces x 8 pclusters
        ScenarioSpec(
            name="gauntlet-splitter-10k",
            description="10k-workspace write fan-in across a 4-shard "
                        "fleet (the 10k-logical-cluster north-star "
                        "shape), smart-client writers",
            topology="fleet", topology_args={"shards": 4},
            tenants=10000, watchers_per_tenant=0, phases=phases,
            options={"smart_all": True},
            slos=(SLO("no-lost-acked-writes", "lost_acked_writes",
                      "==", 0),
                  SLO("bounded-rss-growth", "memory_growth_ratio",
                      "<=", 3.0))),
        # NegotiatedAPIResource schema-compat across 5k tenant CRD sets
        ScenarioSpec(
            name="gauntlet-crd-5k",
            description="5k-tenant CRD establish/negotiate churn with "
                        "live CR traffic (schema-compat reconcile)",
            topology="monolith", topology_args={"controllers": True},
            tenants=5000, watchers_per_tenant=0, workload="crd",
            phases=(Phase("establish", ops_per_tenant=10, settle_s=0.5),
                    Phase("negotiate", ops_per_tenant=16, settle_s=0.5)),
            slos=slos_crd),
        # informer watch fan-out: 100k objects, 10k watchers
        ScenarioSpec(
            name="gauntlet-watch-fanout",
            description="watch fan-out at the 10k-watcher shape: 100 "
                        "tenants x 100 streams over one server process "
                        "under sustained churn",
            topology="monolith", topology_args={"proc": True},
            tenants=100, watchers_per_tenant=100, phases=phases,
            options={"pace_s": 0.01, "coverage_timeout_s": 120.0},
            slos=slos_common),
    ]
    sel_env = os.environ.get("KCP_GAUNTLET_CONFIGS", "")
    selected = ([int(x) for x in sel_env.split(",") if x.strip() != ""]
                if sel_env else list(range(len(specs))))

    # a FRESH workdir per invocation: fleet shards are durable by
    # default, and a reused root would replay a previous run's WAL
    # into this run's fold (stale objects -> phantom 409s/losses)
    import shutil
    import tempfile
    workdir = tempfile.mkdtemp(prefix="kcp-gauntlet-")

    rows = []
    degraded_any = False
    for idx in selected:
        spec = specs[idx]
        if ops_override:
            n = int(ops_override)
            spec = dataclasses.replace(spec, phases=tuple(
                dataclasses.replace(p, ops_per_tenant=n if p.ops_per_tenant
                                    else 0) for p in spec.phases))
        if soak > 1:
            # soak mode: the same phase block repeated N rounds under
            # one topology — RSS is sampled at every phase boundary, so
            # rss_kb_per_phase is the periodic snapshot series and the
            # growth SLO spans the whole soak
            spec = dataclasses.replace(spec, phases=tuple(
                dataclasses.replace(p, name=f"{p.name}-r{r}")
                for r in range(soak) for p in spec.phases))
        cfg = (cfg_names[idx] if idx < len(cfg_names)
               else f"config[{idx}]")
        print(f"# gauntlet [{idx}] {spec.name}: {cfg}", file=sys.stderr)
        try:
            res = run_scenario(spec, seed=42, scale=scale,
                               workdir=workdir)
        except Exception as e:  # noqa: BLE001 - a wedged config must
            # not take down the other rows; the failure IS the row
            rows.append({"config": cfg, "name": spec.name,
                         "scale": f"1/{divisor:g}", "passed": False,
                         "degraded": True, "error": f"{type(e).__name__}: {e}"})
            degraded_any = True
            continue
        m = res.get("measurements", {})
        row = {
            "config": cfg,
            "name": spec.name,
            "scale": f"1/{divisor:g}",
            "tenants": res.get("tenants"),
            "reconciles_per_sec": m.get("acked_per_sec"),
            "acked": m.get("acked"),
            "convergence_p50_ms": m.get("p50_convergence_ms"),
            "convergence_p99_ms": m.get("p99_convergence_ms"),
            "lost_acked_writes": m.get("lost_acked_writes"),
            "lost_watch_events": m.get("lost_watch_events"),
            "rss_kb_per_phase": m.get("rss_kb_per_phase"),
            "memory_growth_ratio": m.get("memory_growth_ratio"),
            "duration_s": m.get("duration_s"),
            "passed": res.get("passed"),
            "slos": res.get("slos"),
        }
        if res.get("aborted"):
            row["degraded"] = True
            row["error"] = res["aborted"]
            degraded_any = True
        rows.append(row)

    shutil.rmtree(workdir, ignore_errors=True)
    pag = _pagination_ab(
        int(os.environ.get("KCP_BENCH_PAG_OBJECTS", "100000")),
        int(os.environ.get("KCP_BENCH_PAG_PAGE", "10000")))
    REGISTRY.counter(
        "gauntlet_runs_total",
        "composed gauntlet scorecard runs completed").inc()
    scorecard = {
        "metric": "gauntlet_configs_passed",
        "value": sum(1 for r in rows if r.get("passed")),
        "unit": f"of {len(rows)} configs",
        "scale": f"1/{divisor:g}",
        "soak_rounds": soak,
        "rows": rows,
        "relist": pag,
    }
    if degraded_any:
        scorecard["degraded"] = True
    if out_path:
        with open(out_path, "w", encoding="utf-8") as f:
            json.dump(scorecard, f, indent=1)
            f.write("\n")
    emit(scorecard)
    return 0


def _spawn_kcp(extra_args: list[str], timeout: float = 60.0):
    """Spawn a real ``kcp start`` subprocess (plaintext, no controllers,
    no syncer) and block until it announces its serving address. Returns
    ``(Popen, address)``. The child never imports jax (no JAX_PLATFORMS,
    compile cache off), so spawn cost is interpreter + server imports."""
    import subprocess

    cmd = [sys.executable, "-m", "kcp_tpu.cli.kcp", "start",
           "--no-install-controllers", "--no-tls",
           "--syncer-mode", "none"] + extra_args
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    env.pop("KCP_FAULTS", None)  # a CI chaos schedule must not leak in
    env["KCP_NO_COMPILE_CACHE"] = "1"
    p = subprocess.Popen(cmd, stdout=subprocess.PIPE,
                         stderr=subprocess.DEVNULL, env=env, text=True)
    deadline = time.time() + timeout
    while True:
        line = p.stdout.readline()
        if not line:
            raise RuntimeError(
                f"kcp start exited rc={p.poll()} before serving: {cmd}")
        if line.startswith("kcp-tpu serving at "):
            return p, line.rsplit(None, 1)[-1]
        if time.time() > deadline:
            p.kill()
            raise RuntimeError(f"kcp start did not serve in {timeout}s")


def shard_loadgen() -> int:
    """Write-loadgen child for ``--sharded`` (``bench.py --shard-loadgen``,
    parameters via ``KCP_LG_*``): ring-routes configmap creates straight
    to each cluster's owning shard (the smart-client mode the rendezvous
    ring is deterministic FOR — a production fleet scales routers
    horizontally; the loadgen measures the shards, not one router
    process). Prints ``ready`` after warmup, starts on a ``go`` line from
    stdin (the cross-loadgen barrier), writes for KCP_LG_SECONDS, and
    reports ``{"writes": N, "seconds": measured}`` as JSON."""
    from kcp_tpu.server.rest import MultiClusterRestClient
    from kcp_tpu.sharding import ShardRing

    ring = ShardRing.from_spec(os.environ["KCP_LG_SPEC"])
    clusters = os.environ["KCP_LG_CLUSTERS"].split(",")
    seconds = float(os.environ["KCP_LG_SECONDS"])
    prefix = os.environ["KCP_LG_PREFIX"]
    # one wildcard client (= one kept-alive connection) per shard; writes
    # carry metadata.clusterName, which the shard's own wildcard-write
    # rule resolves — the same body works against a monolith unchanged
    clients = [MultiClusterRestClient(s.url) for s in ring]
    owner = {c: ring.owner_index(c) for c in clusters}

    def body(k: int, warm: bool = False) -> dict:
        c = clusters[k % len(clusters)]
        name = f"{prefix}-{'w' if warm else 'n'}{k}"
        return {"apiVersion": "v1", "kind": "ConfigMap",
                "metadata": {"name": name, "namespace": "default",
                             "clusterName": c},
                "data": {}}, owner[c]

    for k in range(2 * len(clients)):  # warm connections + discovery
        obj, idx = body(k, warm=True)
        clients[idx].create("configmaps", obj)
    print("ready", flush=True)
    sys.stdin.readline()  # the barrier: every loadgen starts together
    n = 0
    t0 = time.perf_counter()
    stop = t0 + seconds
    while time.perf_counter() < stop:
        obj, idx = body(n)
        clients[idx].create("configmaps", obj)
        n += 1
    print(json.dumps({"writes": n,
                      "seconds": time.perf_counter() - t0}), flush=True)
    return 0


def sharded_bench() -> int:
    """Sharded control plane A/B (``--sharded``): fleet write capacity at
    1/2/4 shards, merged wildcard list/watch behavior through the router,
    and the shard-kill drill. One JSON line; ``value`` is the fleet
    *capacity* speedup at the largest fleet vs the 1-shard monolith.

    Two scaling numbers, because they answer different questions:

    - ``capacity_speedup`` (the headline): shards share nothing — no
      cross-shard traffic on single-cluster writes, ring-partitioned
      keyspace — so fleet capacity on N hosts is the sum of per-shard
      rates. Each shard's rate is measured in its own time slice under
      exactly its ring partition of the clusters (idle peers cost
      nothing), which stays honest on CI hosts with fewer cores than
      server processes. The gate is real: a ring that routed everything
      to one shard, or any cross-shard chatter on the write path, drags
      the sum back toward 1x.
    - ``concurrent_speedup``: all shards driven simultaneously on THIS
      host — the wall-clock truth, bounded by host cores (~1x on a
      1-core CI runner; near the capacity number when cores >= fleet).

    The router phases measure what the frontend adds: single-cluster
    relay throughput through one router process, merged wildcard list
    latency, write->merged-watch-event latency, and the kill drill
    (victim SIGKILLed mid-traffic: fail-fast 503 once the breaker trips,
    terminal in-stream 410 on the merged watch, zero acked writes lost
    after the WAL-restored restart + relist catchup).
    """
    import signal
    import subprocess
    import tempfile
    from urllib.parse import urlsplit

    from kcp_tpu.server.rest import MultiClusterRestClient, RestClient
    from kcp_tpu.sharding import ShardRing
    from kcp_tpu.utils import errors as kerrors

    fleets = sorted(int(x) for x in os.environ.get(
        "KCP_BENCH_SHARD_FLEETS", "1,2,4").split(",") if x)
    seconds = float(os.environ.get("KCP_BENCH_SHARD_SECONDS", "2.0"))
    n_loadgens = int(os.environ.get("KCP_BENCH_SHARD_CLIENTS", "2"))
    n_clusters = int(os.environ.get("KCP_BENCH_SHARD_CLUSTERS", "24"))
    lat_events = int(os.environ.get("KCP_BENCH_SHARD_EVENTS", "40"))
    clusters = [f"t{i}" for i in range(n_clusters)]

    def start_loadgens(spec: str, subset: list[str], secs: float,
                       tag: str) -> float:
        """n_loadgens barrier-synced loadgen children over ``subset`` of
        the clusters; returns the aggregate write rate."""
        procs = []
        for j in range(n_loadgens):
            env = dict(os.environ,
                       KCP_LG_SPEC=spec, KCP_LG_SECONDS=str(secs),
                       KCP_LG_CLUSTERS=",".join(
                           subset[j::n_loadgens] or subset),
                       KCP_LG_PREFIX=f"{tag}-lg{j}")
            env.pop("JAX_PLATFORMS", None)
            env.pop("KCP_FAULTS", None)
            env["KCP_NO_COMPILE_CACHE"] = "1"
            procs.append(subprocess.Popen(
                [sys.executable, sys.argv[0], "--shard-loadgen"],
                stdin=subprocess.PIPE, stdout=subprocess.PIPE,
                stderr=subprocess.DEVNULL, env=env, text=True))
        for p in procs:
            assert p.stdout.readline().strip() == "ready"
        for p in procs:  # release the barrier everywhere at once
            p.stdin.write("go\n")
            p.stdin.flush()
        rate = 0.0
        for p in procs:
            r = json.loads(p.stdout.readline())
            rate += r["writes"] / r["seconds"]
            p.stdin.close()
            p.wait(timeout=30)
        return rate

    def stop_all(procs) -> None:
        for p in procs:
            if p.poll() is None:
                p.send_signal(signal.SIGTERM)
        for p in procs:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()

    # ---- phase 1: write capacity at each fleet size
    fleet_stats: dict[str, dict] = {}
    largest: tuple[list, str, ShardRing] | None = None
    for n in fleets:
        procs, urls = [], []
        try:
            for _ in range(n):
                p, addr = _spawn_kcp(["--in-memory", "--listen-port", "0"])
                procs.append(p)
                urls.append(addr)
            spec = ",".join(f"s{i}={u}" for i, u in enumerate(urls))
            ring = ShardRing.from_spec(spec)
            owned = [[c for c in clusters if ring.owner_index(c) == i]
                     for i in range(n)]
            concurrent = start_loadgens(spec, clusters, seconds, f"f{n}c")
            per_shard = []
            for i in range(n):
                # time-sliced capacity: only shard i's partition driven
                rate = start_loadgens(spec, owned[i], max(1.0, seconds / n),
                                      f"f{n}s{i}")
                per_shard.append({"shard": i, "clusters": len(owned[i]),
                                  "per_s": round(rate)})
            fleet_stats[str(n)] = {
                "concurrent_per_s": round(concurrent),
                "capacity_per_s": round(sum(s["per_s"] for s in per_shard)),
                "per_shard": per_shard,
            }
            if n == fleets[-1]:
                largest = (procs, spec, ring)
                procs = []  # keep the largest fleet alive for the router
        finally:
            stop_all(procs)

    base = fleet_stats[str(fleets[0])]
    capacity_speedup = {
        str(n): round(fleet_stats[str(n)]["capacity_per_s"]
                      / max(base["capacity_per_s"], 1), 2)
        for n in fleets[1:]}
    concurrent_speedup = {
        str(n): round(fleet_stats[str(n)]["concurrent_per_s"]
                      / max(base["concurrent_per_s"], 1), 2)
        for n in fleets[1:]}

    # ---- phase 2: the router over the largest fleet
    assert largest is not None
    shard_procs, spec, ring = largest
    router_stats: dict = {}
    try:
        rp, raddr = _spawn_kcp(["--role", "router", "--shards", spec,
                                "--in-memory", "--listen-port", "0"])
        shard_procs.append(rp)
        wc = MultiClusterRestClient(raddr)

        # relay throughput: single-cluster writes through ONE router hop
        c0 = clusters[0]
        rc = RestClient(raddr, cluster=c0)
        rc.create("configmaps", {"apiVersion": "v1", "kind": "ConfigMap",
                                 "metadata": {"name": "relay-warm",
                                              "namespace": "default"}})
        t0 = time.perf_counter()
        relay_n = 0
        while time.perf_counter() - t0 < max(1.0, seconds / 2):
            rc.create("configmaps", {
                "apiVersion": "v1", "kind": "ConfigMap", "metadata": {
                    "name": f"relay-{relay_n}", "namespace": "default"}})
            relay_n += 1
        relay_per_s = relay_n / (time.perf_counter() - t0)

        # merged wildcard list latency (the fleet holds phase-1 objects)
        lists = []
        for _ in range(10):
            t0 = time.perf_counter()
            items, rv = wc.list("configmaps")
            lists.append(time.perf_counter() - t0)

        # write -> merged-watch-event latency across all shards
        async def watch_lat() -> list[float]:
            _items, rv = wc.list("configmaps")
            w = wc.watch("configmaps", since_rv=rv)
            await w.next_batch(0.05)
            await asyncio.sleep(0.2)
            lats = []
            try:
                for k in range(lat_events):
                    c = clusters[k % len(clusters)]
                    name = f"lat-{k}"
                    t0 = time.perf_counter()
                    wc.create("configmaps", {
                        "apiVersion": "v1", "kind": "ConfigMap",
                        "metadata": {"name": name, "namespace": "default",
                                     "clusterName": c}})
                    seen = False
                    for _ in range(400):
                        for ev in await w.next_batch(0.05):
                            if ev.name == name:
                                lats.append(time.perf_counter() - t0)
                                seen = True
                        if seen:
                            break
                    assert seen, f"merged watch never delivered {name}"
            finally:
                w.close()
            return lats

        lats = asyncio.run(watch_lat())
        router_stats = {
            "shards": len(ring),
            "relay_per_s": round(relay_per_s),
            "list_p50_ms": round(
                float(np.percentile(np.asarray(lists), 50)) * 1e3, 2),
            "watch_events": len(lats),
            "watch_lat_p50_ms": round(
                float(np.percentile(np.asarray(lats), 50)) * 1e3, 2),
            "watch_lat_p99_ms": round(
                float(np.percentile(np.asarray(lats), 99)) * 1e3, 2),
        }
    finally:
        stop_all(shard_procs)

    # ---- phase 3: shard-kill drill (2 durable shards + router)
    kill_stats: dict = {}
    with tempfile.TemporaryDirectory(prefix="kcp-sharded-") as tmp:
        procs = []
        try:
            urls = []
            for i in range(2):
                p, addr = _spawn_kcp(["--root-dir",
                                      os.path.join(tmp, f"shard{i}"),
                                      "--listen-port", "0"])
                procs.append(p)
                urls.append(addr)
            spec = ",".join(f"s{i}={u}" for i, u in enumerate(urls))
            ring = ShardRing.from_spec(spec)
            rp, raddr = _spawn_kcp(["--role", "router", "--shards", spec,
                                    "--in-memory", "--listen-port", "0"])
            procs.append(rp)
            wc = MultiClusterRestClient(raddr)
            # two clusters on distinct shards: a victim and a survivor
            owners: dict[int, str] = {}
            for i in range(64):
                owners.setdefault(ring.owner_index(f"k{i}"), f"k{i}")
                if len(owners) == 2:
                    break
            victim_idx, victim_c = sorted(owners.items())[0]
            _surv_idx, surv_c = sorted(owners.items())[1]
            acked: set[tuple[str, str]] = set()

            def write(c: str, name: str, retry: bool = False) -> None:
                while True:
                    try:
                        wc.create("configmaps", {
                            "apiVersion": "v1", "kind": "ConfigMap",
                            "metadata": {"name": name,
                                         "namespace": "default",
                                         "clusterName": c}})
                        acked.add((c, name))
                        return
                    except kerrors.AlreadyExistsError:
                        acked.add((c, name))
                        return
                    except (kerrors.UnavailableError, ConnectionError,
                            OSError):
                        if not retry:
                            raise
                        time.sleep(0.05)

            for k in range(20):
                write(victim_c, f"pre-{k}")
                write(surv_c, f"pre-{k}")

            async def drill() -> None:
                _items, rv = wc.list("configmaps")
                w = wc.watch("configmaps", since_rv=rv)
                await w.next_batch(0.05)
                await asyncio.sleep(0.2)
                t_kill = time.perf_counter()
                procs[victim_idx].kill()
                procs[victim_idx].wait(timeout=10)
                # the merged watch must end with a terminal in-stream 410
                gone_ms = None
                try:
                    for _ in range(600):
                        await w.next_batch(0.05)
                except kerrors.GoneError:
                    gone_ms = (time.perf_counter() - t_kill) * 1e3
                finally:
                    w.close()
                kill_stats["watch_terminal_410"] = gone_ms is not None
                kill_stats["watch_410_ms"] = round(gone_ms or -1.0, 1)
                # victim-owned requests fail; once the breaker trips they
                # fail FAST (503 without a connect attempt)
                vc = RestClient(raddr, cluster=victim_c)
                first_503_ms = None
                attempt_ms = []
                for k in range(8):
                    t0 = time.perf_counter()
                    try:
                        vc.get("configmaps", "pre-0", "default")
                    except (kerrors.UnavailableError, ConnectionError,
                            OSError):
                        pass
                    dt = (time.perf_counter() - t0) * 1e3
                    attempt_ms.append(dt)
                    if first_503_ms is None:
                        first_503_ms = round(
                            (time.perf_counter() - t_kill) * 1e3, 1)
                kill_stats["unavailable_after_kill_ms"] = first_503_ms
                kill_stats["failfast_ms"] = round(min(attempt_ms[-3:]), 2)
                # survivor keeps serving through the router all along
                for k in range(10):
                    write(surv_c, f"out-{k}")
                # revive the victim on its OLD address, WAL-restored
                port = urlsplit(urls[victim_idx]).port
                deadline = time.time() + 30
                while True:
                    try:
                        p2, _ = _spawn_kcp(
                            ["--root-dir",
                             os.path.join(tmp, f"shard{victim_idx}"),
                             "--listen-port", str(port)])
                        procs[victim_idx] = p2
                        break
                    except RuntimeError:
                        if time.time() > deadline:
                            raise
                        # must yield the loop: the merged-watch reader
                        # runs on it while we wait out the shard restart
                        await asyncio.sleep(0.3)
                # catchup writes land once the breaker's probe re-closes
                for k in range(10):
                    write(victim_c, f"back-{k}", retry=True)

            asyncio.run(drill())
            # relist catchup: every acked write is present — zero lost
            deadline = time.time() + 30
            while True:
                items, _rv = wc.list("configmaps")
                have = {(o["metadata"]["clusterName"], o["metadata"]["name"])
                        for o in items}
                missing = acked - have
                if not missing or time.time() > deadline:
                    break
                time.sleep(0.3)
            kill_stats["acked_writes"] = len(acked)
            kill_stats["lost_after_catchup"] = len(missing)
        finally:
            stop_all(procs)

    top = str(fleets[-1])
    out = {
        "metric": "sharded_write_capacity_speedup",
        "value": capacity_speedup.get(top, 1.0),
        "unit": "x",
        "sharded_bench": {
            "host_cpus": os.cpu_count(),
            "clusters": n_clusters,
            "loadgens": n_loadgens,
            "seconds": seconds,
            "fleets": fleet_stats,
            "capacity_speedup": capacity_speedup,
            "concurrent_speedup": concurrent_speedup,
            "router": router_stats,
            "kill": kill_stats,
        },
    }
    emit(out)
    return 0


def smartclient_bench() -> int:
    """Smart-client + zero-copy wire A/B (``--smartclient``): single-
    cluster write throughput routed (client→router→shard) vs DIRECT
    (client→owning shard over the rendezvous ring, ``GET /ring``
    handshake), byte-equality of routed vs direct responses, the
    scatter-vs-join wire A/B (sha256 over real sockets), and the
    mid-bench ring-change drill — a shard drains, restarts on a NEW
    port, the ring republishes, and smart writers under an injected
    ``router.proxy`` fault schedule must complete with zero lost acked
    writes and zero surfaced errors (one-shot fallbacks absorb the
    move).

    One JSON line; ``value`` is the single-cluster write CAPACITY
    speedup: direct capacity (per-shard time slices summed — shards
    share nothing once the router hop is gone, the --sharded bench's
    honest-on-1-cpu discipline) over the routed ceiling through ONE
    router (routers don't sum: the hop being deleted IS the shared
    bottleneck). ``concurrent_speedup`` rides along — all writers at
    once on THIS host, the wall-clock truth (≈(client+router+shard) /
    (client+shard) cpu per op on a host with fewer cores than
    processes; near the capacity number when cores ≥ processes)."""
    import tempfile

    from kcp_tpu import faults as kfaults
    from kcp_tpu.client.smart import SmartRestClient
    from kcp_tpu.server.rest import RestClient
    from kcp_tpu.utils import errors as kerrors
    from kcp_tpu.utils.trace import REGISTRY

    n_shards = int(os.environ.get("KCP_BENCH_SMART_SHARDS", "2"))
    seconds = float(os.environ.get("KCP_BENCH_SMART_SECONDS", "2.0"))
    n_clusters = int(os.environ.get("KCP_BENCH_SMART_CLUSTERS", "8"))
    n_threads = int(os.environ.get("KCP_BENCH_SMART_THREADS", "2"))
    clusters = [f"t{i}" for i in range(n_clusters)]
    names = ",".join(f"s{i}" for i in range(n_shards))

    def stop_all(procs) -> None:
        import signal

        for p in procs:
            if p.poll() is None:
                p.send_signal(signal.SIGTERM)
        for p in procs:
            try:
                p.wait(timeout=10)
            except Exception:  # noqa: BLE001 — escalate
                p.kill()

    def obj(cluster: str, name: str) -> dict:
        return {"apiVersion": "v1", "kind": "ConfigMap",
                "metadata": {"name": name, "namespace": "default",
                             "clusterName": cluster}, "data": {}}

    def write_loop(make_base, tag: str, pool: list[str] | None = None,
                   secs: float | None = None) -> tuple[float, list[float]]:
        """n_threads barrier-synced writer threads, each rotating its
        slice of ``pool`` (default: all clusters); returns
        (aggregate writes/s, per-op seconds)."""
        pool = pool if pool is not None else clusters
        secs = secs if secs is not None else seconds
        counts = [0] * n_threads
        lats: list[list[float]] = [[] for _ in range(n_threads)]
        barrier = threading.Barrier(n_threads + 1)

        def worker(k: int) -> None:
            base = make_base()
            subset = pool[k::n_threads] or pool
            scoped = {c: base.scoped(c) for c in subset}
            for j, c in enumerate(subset):  # warm conns + ring + schema
                scoped[c].create("configmaps", obj(c, f"{tag}-w{k}-{j}"))
            barrier.wait()
            stop_at = time.perf_counter() + secs
            n = 0
            while time.perf_counter() < stop_at:
                c = subset[n % len(subset)]
                t0 = time.perf_counter()
                scoped[c].create("configmaps", obj(c, f"{tag}-{k}-{n}"))
                lats[k].append(time.perf_counter() - t0)
                n += 1
            counts[k] = n
            base.close()

        threads = [threading.Thread(target=worker, args=(k,), daemon=True)
                   for k in range(n_threads)]
        for t in threads:
            t.start()
        barrier.wait()
        t0 = time.perf_counter()
        for t in threads:
            t.join()
        wall = time.perf_counter() - t0
        return sum(counts) / max(wall, 1e-9), [x for la in lats for x in la]

    def pct(vals: list[float], q: float) -> float:
        return round(float(np.percentile(np.asarray(vals), q)) * 1e3, 3)

    # ---- phase 1: routed vs direct throughput on a real subprocess fleet
    procs: list = []
    ab: dict = {}
    bytes_equal = True
    try:
        urls = []
        for i in range(n_shards):
            p, addr = _spawn_kcp(["--in-memory", "--listen-port", "0",
                                  "--shard-name", f"s{i}",
                                  "--ring-names", names,
                                  "--ring-epoch", "1"])
            procs.append(p)
            urls.append(addr)
        spec = ",".join(f"s{i}={u}" for i, u in enumerate(urls))
        rp, raddr = _spawn_kcp(["--role", "router", "--shards", spec,
                                "--in-memory", "--listen-port", "0"])
        procs.append(rp)
        # alternating segments (r,d,r,d): host-load drift lands on both
        # arms instead of whichever ran second
        segs = max(1, int(os.environ.get("KCP_BENCH_SMART_SEGMENTS", "2")))
        d0 = REGISTRY.counter("smart_client_direct_total").value
        f0 = REGISTRY.counter("smart_client_fallback_total").value
        routed_rates, direct_rates = [], []
        routed_lat: list[float] = []
        direct_lat: list[float] = []
        for s in range(segs):
            rate, lat = write_loop(
                lambda: RestClient(raddr, cluster=clusters[0]), f"r{s}")
            routed_rates.append(rate)
            routed_lat.extend(lat)
            rate, lat = write_loop(
                lambda: SmartRestClient(raddr, cluster=clusters[0]),
                f"d{s}")
            direct_rates.append(rate)
            direct_lat.extend(lat)
        routed_rate = sum(routed_rates) / len(routed_rates)
        direct_rate = sum(direct_rates) / len(direct_rates)
        # direct CAPACITY: each shard's ring partition driven alone in
        # its own time slice (idle peers cost nothing on a 1-cpu host),
        # summed — shards share nothing on the direct write path, so
        # the sum is what N hosts serve. The routed ceiling is the ONE
        # router's concurrent rate: routers are the shared hop, they
        # don't sum — which is exactly the bottleneck going direct
        # deletes.
        from kcp_tpu.sharding import ShardRing

        ring = ShardRing.from_spec(spec)
        per_shard = []
        for i in range(n_shards):
            owned = [c for c in clusters if ring.owner_index(c) == i]
            if not owned:
                continue
            rate, _lat = write_loop(
                lambda: SmartRestClient(raddr, cluster=owned[0]),
                f"c{i}", pool=owned, secs=max(1.0, seconds / n_shards))
            per_shard.append({"shard": i, "clusters": len(owned),
                              "per_s": round(rate)})
        capacity_direct = sum(s["per_s"] for s in per_shard)
        direct_n = REGISTRY.counter("smart_client_direct_total").value - d0
        fallback_n = REGISTRY.counter(
            "smart_client_fallback_total").value - f0
        # byte equality: the same GETs and lists, routed vs direct
        sc = SmartRestClient(raddr, cluster=clusters[0])
        rc = RestClient(raddr, cluster=clusters[0])
        import hashlib

        paths = [f"/clusters/{c}/api/v1/namespaces/default/configmaps"
                 for c in clusters[:4]]
        paths.append(f"/clusters/{clusters[0]}/api/v1/namespaces/"
                     f"default/configmaps/r0-w0-0")
        for path in paths:
            s1, _h1, b1 = sc.request_raw("GET", path)
            s2, _h2, b2 = rc.request_raw("GET", path)
            if (s1, hashlib.sha256(b1).hexdigest()) != (
                    s2, hashlib.sha256(b2).hexdigest()):
                bytes_equal = False
        sc.close()
        rc.close()
        ab = {
            "routed_per_s": round(routed_rate),
            "direct_per_s": round(direct_rate),
            "direct_capacity_per_s": capacity_direct,
            "per_shard": per_shard,
            "capacity_speedup": round(
                capacity_direct / max(routed_rate, 1e-9), 2),
            "concurrent_speedup": round(
                direct_rate / max(routed_rate, 1e-9), 2),
            "routed_p50_ms": pct(routed_lat, 50),
            "routed_p99_ms": pct(routed_lat, 99),
            "direct_p50_ms": pct(direct_lat, 50),
            "direct_p99_ms": pct(direct_lat, 99),
            "direct_requests": int(direct_n),
            "fallbacks_during_ab": int(fallback_n),
            "bytes_equal": bytes_equal,
        }
    finally:
        stop_all(procs)

    # ---- phase 2: scatter-vs-join wire A/B over real sockets
    from kcp_tpu.server.rest import MultiClusterRestClient
    from kcp_tpu.server.server import Config
    from kcp_tpu.server.threaded import ServerThread

    wire: dict = {}
    with ServerThread(Config(durable=False, install_controllers=False,
                             tls=False)) as srv:
        import hashlib
        import http.client as hc
        from urllib.parse import urlsplit

        wc = MultiClusterRestClient(srv.address)
        pad = "y" * 50000
        for i in range(400):
            wc.create("configmaps", {
                "apiVersion": "v1", "kind": "ConfigMap",
                "metadata": {"name": f"w-{i}", "namespace": "default",
                             "clusterName": "wire"},
                "data": {"v": str(i), "pad": pad if i % 37 == 0 else "s"}})

        def fetch(scatter: bool) -> tuple[bytes, float]:
            os.environ["KCP_WIRE_SCATTER"] = "1" if scatter else "0"
            parts = urlsplit(srv.address)
            conn = hc.HTTPConnection(parts.hostname, parts.port,
                                     timeout=60)
            try:
                t0 = time.perf_counter()
                conn.request(
                    "GET",
                    "/clusters/wire/api/v1/namespaces/default/configmaps")
                resp = conn.getresponse()
                body = resp.read()
                return body, time.perf_counter() - t0
            finally:
                conn.close()
                os.environ.pop("KCP_WIRE_SCATTER", None)

        fetch(True)  # warm the encode caches so both arms splice
        sp0 = REGISTRY.counter("wire_spans_written_total").value
        jv0 = REGISTRY.counter("wire_join_avoided_total").value
        b_scatter, t_scatter = fetch(True)
        spans_written = REGISTRY.counter(
            "wire_spans_written_total").value - sp0
        join_avoided = REGISTRY.counter(
            "wire_join_avoided_total").value - jv0
        b_join, t_join = fetch(False)
        wire = {
            "list_bytes": len(b_scatter),
            "identical": hashlib.sha256(b_scatter).hexdigest()
            == hashlib.sha256(b_join).hexdigest(),
            "scatter_ms": round(t_scatter * 1e3, 2),
            "join_ms": round(t_join * 1e3, 2),
            "spans_written": int(spans_written),
            "join_avoided_bytes": int(join_avoided),
        }
        wc.close()

    # ---- phase 3: mid-bench ring change under an injected router fault
    from kcp_tpu.scenarios.topology import move_shard, shard_fleet

    drill: dict = {}
    with tempfile.TemporaryDirectory(prefix="kcp-smart-") as tmp:
        with shard_fleet(2, durable=True, root_dir=str(tmp)) as (
                router, shards, ring):
            dcl = ["da", "db"]
            victim = ring.owner_index(dcl[0])
            acked: set[tuple[str, str]] = set()
            errors_surfaced = 0
            retries = 0
            f0 = REGISTRY.counter("smart_client_fallback_total").value
            r0 = REGISTRY.counter(
                "smart_client_ring_refreshes_total").value
            base = SmartRestClient(router.address, cluster=dcl[0])
            scoped = {c: base.scoped(c) for c in dcl}
            kfaults.install(kfaults.FaultInjector(
                "router.proxy:error=0.15", seed=7))
            try:
                moved = False
                for k in range(80):
                    if k == 30:
                        # the ring change, mid-workload: drain the
                        # owner of dcl[0], restart on a NEW port,
                        # republish /ring
                        move_shard(shards, victim, router.address)
                        moved = True
                    c = dcl[k % 2]
                    name = f"drill-{k}"
                    deadline = time.time() + 30
                    while True:
                        try:
                            scoped[c].create("configmaps", obj(c, name))
                            acked.add((c, name))
                            break
                        except kerrors.AlreadyExistsError:
                            acked.add((c, name))
                            break
                        except (kerrors.UnavailableError,
                                kerrors.GoneError, ConnectionError,
                                OSError):
                            # the production retry discipline: a move
                            # window answers 503/refused; retry until
                            # the fallback+republish absorbs it
                            retries += 1
                            if time.time() > deadline:
                                errors_surfaced += 1
                                break
                            time.sleep(0.05)
                assert moved
            finally:
                kfaults.clear()
                base.close()
            # every acked write present through the router (WAL carried
            # the victim's data across the move)
            wc = MultiClusterRestClient(router.address)
            deadline = time.time() + 30
            missing: set = set()
            while True:
                items, _rv = wc.list("configmaps")
                have = {(o["metadata"]["clusterName"],
                         o["metadata"]["name"]) for o in items}
                missing = acked - have
                if not missing or time.time() > deadline:
                    break
                time.sleep(0.2)
            wc.close()
            drill = {
                "acked_writes": len(acked),
                "lost_after_move": len(missing),
                "errors_surfaced": errors_surfaced,
                "retries": retries,
                "fallbacks": int(REGISTRY.counter(
                    "smart_client_fallback_total").value - f0),
                "ring_refreshes": int(REGISTRY.counter(
                    "smart_client_ring_refreshes_total").value - r0),
                "ring_epoch_after": RestClient(
                    router.address)._request("GET", "/ring")["epoch"],
            }

    out = {
        "metric": "smartclient_write_capacity_speedup",
        "value": ab.get("capacity_speedup", 0.0),
        "unit": "x",
        "smartclient_bench": {
            "host_cpus": os.cpu_count(),
            "shards": n_shards,
            "clusters": n_clusters,
            "threads": n_threads,
            "seconds": seconds,
            "ab": ab,
            "wire": wire,
            "ring_change_drill": drill,
        },
    }
    emit(out)
    return 0


def elastic_bench() -> int:
    """Elastic scale-out A/B (``--elastic``): write capacity on an
    N-shard fleet, then the fleet DOUBLES live — new shards join the
    ring, every moving cluster's WAL streams to its new owner behind a
    fence, ownership flips atomically per cluster — and capacity is
    re-measured on 2N shards. One JSON line; ``value`` is the
    post-scale-out capacity speedup (target >= 1.6x for a doubling:
    migration cannot conjure capacity beyond the hardware, but it must
    deliver most of it).

    Capacity is honest on few-core CI hosts (the --sharded discipline):
    each shard's ring partition is driven DIRECT (smart client, no
    router hop) alone in its own time slice and the rates sum — shards
    share nothing on the direct write path, so the sum is what N hosts
    serve. The during-move lane rides along: writer threads (half
    smart, half routed) run THROUGH both migrations with the production
    retry discipline, and the bench reports their p99, the fence-window
    503s, the migrated record count, and — the point — zero acked
    writes lost across the move."""
    from kcp_tpu.client.smart import SmartRestClient
    from kcp_tpu.server.rest import MultiClusterRestClient, RestClient
    from kcp_tpu.server.server import Config
    from kcp_tpu.server.threaded import ServerThread
    from kcp_tpu.sharding import ShardRing, migrate, owner_name
    from kcp_tpu.utils import errors as kerrors
    from kcp_tpu.utils.trace import REGISTRY

    n_before = int(os.environ.get("KCP_BENCH_ELASTIC_SHARDS", "2"))
    n_after = 2 * n_before
    seconds = float(os.environ.get("KCP_BENCH_ELASTIC_SECONDS", "2.0"))
    # 16 clusters: enough keyspace that HRW lands work on EVERY shard of
    # the doubled ring (fewer leaves a shard idle and understates the
    # honest capacity sum)
    n_clusters = int(os.environ.get("KCP_BENCH_ELASTIC_CLUSTERS", "16"))
    n_threads = int(os.environ.get("KCP_BENCH_ELASTIC_THREADS", "2"))
    clusters = [f"t{i}" for i in range(n_clusters)]

    def obj(cluster: str, name: str) -> dict:
        return {"apiVersion": "v1", "kind": "ConfigMap",
                "metadata": {"name": name, "namespace": "default",
                             "clusterName": cluster}, "data": {}}

    def pct(vals: list[float], q: float) -> float:
        if not vals:
            return 0.0
        return round(float(np.percentile(np.asarray(vals), q)) * 1e3, 3)

    threads: list[ServerThread] = []
    try:
        # ---- the starting fleet: n_before in-process shards + router
        names0 = ",".join(f"s{i}" for i in range(n_before))
        for i in range(n_before):
            threads.append(ServerThread(Config(
                durable=False, install_controllers=False, tls=False,
                shard_name=f"s{i}", ring_names=names0,
                ring_epoch=1)).start())
        spec = ",".join(f"s{i}={t.address}"
                        for i, t in enumerate(threads))
        router = ServerThread(Config(role="router", shards=spec,
                                     durable=False, tls=False)).start()
        threads.append(router)
        raddr = router.address

        def slice_capacity(tag: str) -> list[dict]:
            """Per-shard time slices over the router's CURRENT ring:
            each shard's owned clusters driven direct, alone; summing
            the slices is the N-host capacity claim."""
            rc = RestClient(raddr)
            doc = rc._request("GET", "/ring")
            rc.close()
            ring_names = [s["name"] for s in doc["shards"]]
            per = []
            for i, nm in enumerate(ring_names):
                owned = [c for c in clusters
                         if owner_name(ring_names, c) == nm]
                if not owned:
                    continue
                sc = SmartRestClient(raddr, cluster=owned[0])
                scoped = {c: sc.scoped(c) for c in owned}
                for j, c in enumerate(owned):  # warm conns + ring
                    scoped[c].create("configmaps",
                                     obj(c, f"{tag}-warm-{i}-{j}"))
                stop_at = time.perf_counter() + max(
                    0.5, seconds / len(ring_names))
                n = 0
                t0 = time.perf_counter()
                while time.perf_counter() < stop_at:
                    c = owned[n % len(owned)]
                    scoped[c].create("configmaps", obj(c, f"{tag}-{i}-{n}"))
                    n += 1
                wall = time.perf_counter() - t0
                sc.close()
                per.append({"shard": nm, "clusters": len(owned),
                            "per_s": round(n / max(wall, 1e-9))})
            return per

        per_before = slice_capacity("cb")
        cap_before = sum(s["per_s"] for s in per_before)

        # ---- the move: writers run THROUGH the 2N doubling
        mr0 = REGISTRY.counter("migration_records_total").value
        mf0 = REGISTRY.counter("migration_fenced_writes_total").value
        acked: set[tuple[str, str]] = set()
        acked_lock = threading.Lock()
        lats: list[list[float]] = [[] for _ in range(n_threads)]
        retries = [0] * n_threads
        surfaced = [0] * n_threads
        stop = threading.Event()

        def mover_writer(k: int) -> None:
            # half smart (direct + fallback), half routed: both client
            # shapes must survive the move with plain retry discipline
            cls = SmartRestClient if k % 2 == 0 else RestClient
            base = cls(raddr, cluster=clusters[0])
            scoped = {c: base.scoped(c) for c in clusters}
            n = 0
            while not stop.is_set():
                c = clusters[n % len(clusters)]
                name = f"mv-{k}-{n}"
                t0 = time.perf_counter()
                deadline = t0 + 30.0
                while True:
                    try:
                        scoped[c].create("configmaps", obj(c, name))
                        with acked_lock:
                            acked.add((c, name))
                        break
                    except kerrors.AlreadyExistsError:
                        with acked_lock:
                            acked.add((c, name))
                        break
                    except (kerrors.UnavailableError, kerrors.GoneError,
                            ConnectionError, OSError):
                        # fence-window 503s and flip-window 410s are the
                        # mechanism, not failures; retry until the ring
                        # settles (a stuck client would surface below)
                        retries[k] += 1
                        if time.perf_counter() > deadline:
                            surfaced[k] += 1
                            break
                        time.sleep(0.02)
                lats[k].append(time.perf_counter() - t0)
                n += 1
                time.sleep(0.005)
            base.close()

        writers = [threading.Thread(target=mover_writer, args=(k,),
                                    daemon=True) for k in range(n_threads)]
        for t in writers:
            t.start()
        time.sleep(0.3)
        t_move0 = time.perf_counter()
        migrated = []
        for i in range(n_before, n_after):
            grown = ",".join(f"s{j}" for j in range(i + 1))
            shard = ServerThread(Config(
                durable=False, install_controllers=False, tls=False,
                shard_name=f"s{i}", ring_names=grown,
                ring_epoch=1)).start()
            threads.append(shard)
            migrated.append(migrate.scale_out(
                raddr, f"s{i}={shard.address}"))
        t_move = time.perf_counter() - t_move0
        time.sleep(0.3)
        stop.set()
        for t in writers:
            t.join()

        # zero lost acked writes: every ack readable through the router
        wc = MultiClusterRestClient(raddr)
        items, _rv = wc.list("configmaps")
        have = {(o["metadata"].get("clusterName", ""),
                 o["metadata"]["name"]) for o in items}
        rc = RestClient(raddr)
        epoch_after = rc._request("GET", "/ring")["epoch"]
        rc.close()
        wc.close()
        missing = acked - have
        move_lat = [x for la in lats for x in la]

        per_after = slice_capacity("ca")
        cap_after = sum(s["per_s"] for s in per_after)
        speedup = round(cap_after / max(cap_before, 1e-9), 2)
    finally:
        for t in reversed(threads):
            t.stop()

    out = {
        "metric": "elastic_scaleout_capacity_speedup",
        "value": speedup,
        "unit": "x",
        "elastic_bench": {
            "host_cpus": os.cpu_count(),
            "shards_before": n_before,
            "shards_after": n_after,
            "clusters": n_clusters,
            "seconds": seconds,
            "capacity_before_per_s": cap_before,
            "capacity_after_per_s": cap_after,
            "per_shard_before": per_before,
            "per_shard_after": per_after,
            "during_move": {
                "move_seconds": round(t_move, 3),
                "acked_writes": len(acked),
                "lost_after_move": len(missing),
                "errors_surfaced": sum(surfaced),
                "retries": sum(retries),
                "write_p50_ms": pct(move_lat, 50),
                "write_p99_ms": pct(move_lat, 99),
                "migrated_clusters": sum(
                    len(m["migrated"]) for m in migrated),
                "migration_records": int(REGISTRY.counter(
                    "migration_records_total").value - mr0),
                "fenced_write_503s": int(REGISTRY.counter(
                    "migration_fenced_writes_total").value - mf0),
                "ring_epoch_after": epoch_after,
            },
        },
    }
    emit(out)
    return 0


def replica_bench() -> int:
    """HA replication A/B (``--replica``): read capacity at 0/1/2 read
    replicas, replica visibility lag, byte-equality at the same RV, and
    the kill-the-primary drill. One JSON line; ``value`` is the fleet
    read-capacity speedup at the largest replica count vs the bare
    primary.

    Like the sharded lane, capacity is honest on few-core CI hosts:
    each serving endpoint (primary + each replica) is measured in its
    own time slice under the same fixed list query, and fleet capacity
    is the sum — replicas share nothing on the read path (each serves
    from its own store + encode cache), so the sum is what N hosts
    would serve. Lag is measured as write-to-replica-visibility: after
    each primary write, the time until the replica's applied RV covers
    it (p50/p99 ms). The kill drill runs durable primary+standby,
    SIGKILL-equivalent death mid-workload, and reports promotion
    latency (kill -> first successful standby write) and acked-write
    loss (floor: zero).
    """
    import tempfile

    from kcp_tpu.server.rest import MultiClusterRestClient, RestClient
    from kcp_tpu.server.server import Config
    from kcp_tpu.server.threaded import ServerThread

    objects = int(os.environ.get("KCP_BENCH_REPL_OBJECTS", "2000"))
    seconds = float(os.environ.get("KCP_BENCH_REPL_SECONDS", "1.0"))
    counts = sorted(int(x) for x in os.environ.get(
        "KCP_BENCH_REPL_COUNTS", "0,1,2").split(",") if x.strip())
    lag_writes = int(os.environ.get("KCP_BENCH_REPL_LAG_WRITES", "200"))
    drill_writes = int(os.environ.get("KCP_BENCH_REPL_DRILL_WRITES", "80"))
    clusters = [f"t{i}" for i in range(8)]

    def cm(name: str, cluster: str, data: str = "") -> dict:
        return {"apiVersion": "v1", "kind": "ConfigMap",
                "metadata": {"name": name, "namespace": "default",
                             "clusterName": cluster}, "data": {"v": data}}

    def status(address: str) -> dict:
        c = RestClient(address)
        try:
            return c._request("GET", "/replication/status")
        finally:
            c.close()

    def wait_applied(address: str, rv: int, timeout: float = 60.0) -> None:
        deadline = time.time() + timeout
        while time.time() < deadline:
            if status(address)["applied_rv"] >= rv:
                return
            time.sleep(0.02)
        raise RuntimeError(f"replica {address} never reached rv {rv}")

    def read_rate(address: str, target: str, secs: float) -> float:
        c = RestClient(address)
        try:
            c.request_raw("GET", target)  # warm connection + caches
            n = 0
            t0 = time.perf_counter()
            stop = t0 + secs
            while time.perf_counter() < stop:
                s, _h, _b = c.request_raw("GET", target)
                assert s == 200, s
                n += 1
            return n / (time.perf_counter() - t0)
        finally:
            c.close()

    primary = ServerThread(Config(durable=False, install_controllers=False,
                                  tls=False)).start()
    replicas: list[ServerThread] = []
    results: dict = {"host_cpus": os.cpu_count(), "objects": objects,
                     "seconds": seconds}
    capacities: dict[str, float] = {}
    bytes_equal = True
    try:
        pc = MultiClusterRestClient(primary.address)
        for i in range(objects):
            pc.create("configmaps", cm(f"seed{i}", clusters[i % 8], str(i)))
        seed_rv = status(primary.address)["applied_rv"]
        target = "/clusters/t0/api/v1/namespaces/default/configmaps"
        per_slice = max(0.25, seconds / (max(counts) + 1))
        for n in counts:
            while len(replicas) < n:
                replicas.append(ServerThread(Config(
                    durable=False, install_controllers=False, tls=False,
                    role="replica", primary=primary.address)).start())
                wait_applied(replicas[-1].address, seed_rv)
            endpoints = [primary.address] + [r.address for r in replicas[:n]]
            capacities[str(n)] = round(sum(
                read_rate(a, target, per_slice) for a in endpoints), 1)
        base = capacities.get("0") or 1.0
        speedup = {k: round(v / base, 2) for k, v in capacities.items()}

        # byte equality at the same RV (encode-once path on both sides)
        c0 = RestClient(primary.address)
        _s, _h, pb = c0.request_raw("GET", target)
        c0.close()
        for r in replicas:
            cr = RestClient(r.address)
            _s, _h, rb = cr.request_raw("GET", target)
            cr.close()
            if rb != pb:
                bytes_equal = False

        # replica visibility lag (1 replica attached is the common case)
        lags_ms: list[float] = []
        if replicas:
            rep = replicas[0]
            rc = RestClient(rep.address)
            for i in range(lag_writes):
                out = pc.create("configmaps", cm(f"lag{i}", "t1", str(i)))
                rv = int(out["metadata"]["resourceVersion"])
                t0 = time.perf_counter()
                while True:
                    st = rc._request("GET", "/replication/status")
                    if st["applied_rv"] >= rv:
                        break
                    time.sleep(0.0005)
                lags_ms.append((time.perf_counter() - t0) * 1e3)
            rc.close()
        pc.close()
    finally:
        for r in replicas:
            r.stop()
        primary.stop()

    lag_stats = {}
    if lags_ms:
        import numpy as _np

        lag_stats = {"p50_ms": round(float(_np.percentile(lags_ms, 50)), 3),
                     "p99_ms": round(float(_np.percentile(lags_ms, 99)), 3),
                     "writes": len(lags_ms)}

    # ---- kill-the-primary drill (durable pair, real WAL on disk) ----
    drill: dict = {}
    with tempfile.TemporaryDirectory() as td:
        p = ServerThread(Config(durable=True, install_controllers=False,
                                tls=False,
                                root_dir=os.path.join(td, "p"))).start()
        s = ServerThread(Config(durable=True, install_controllers=False,
                                tls=False, role="standby",
                                primary=p.address, repl_hysteresis_s=0.4,
                                root_dir=os.path.join(td, "s"))).start()
        try:
            deadline = time.time() + 10
            while time.time() < deadline:
                if p.call(lambda: p.server.repl_hub.has_sync_subscribers):
                    break
                time.sleep(0.05)
            pc = MultiClusterRestClient(p.address)
            sc = MultiClusterRestClient(s.address)
            acked: list[str] = []
            killed_at = None
            promoted_at = None
            kill_at = drill_writes // 2
            for i in range(drill_writes):
                name = f"d{i}"
                if i == kill_at:
                    killed_at = time.perf_counter()
                    p.kill()
                stop = time.time() + 30
                while True:
                    client = pc if killed_at is None else sc
                    try:
                        client.create("configmaps", cm(name, "t1", str(i)))
                        acked.append(name)
                        if killed_at is not None and promoted_at is None:
                            promoted_at = time.perf_counter()
                        break
                    except Exception as e:
                        from kcp_tpu.utils import errors as kerrors

                        if isinstance(e, kerrors.AlreadyExistsError):
                            acked.append(name)
                            break
                        if time.time() > stop:
                            raise
                        time.sleep(0.02)
            items, _rv = sc.list("configmaps", namespace="default")
            names = {o["metadata"]["name"] for o in items}
            st = status(s.address)
            drill = {
                "acked_writes": len(acked),
                "lost_after_promotion": len(
                    [n for n in acked if n not in names]),
                "promote_ms": round((promoted_at - killed_at) * 1e3, 1)
                if promoted_at else None,
                "promoted_role": st["role"],
                "epoch": st["epoch"],
            }
            pc.close()
            sc.close()
        finally:
            s.stop()
            p.stop()

    top = str(max(counts))
    out = {
        "metric": "replica_read_capacity_speedup",
        "value": speedup.get(top, 1.0),
        "unit": "x",
        "stage": "replica-bench",
        "replica_bench": {
            **results,
            "read_capacity_rps": capacities,
            "capacity_speedup": speedup,
            "bytes_equal": bytes_equal,
            "lag": lag_stats,
            "kill": drill,
        },
    }
    emit(out)
    return 0


def consistent_bench() -> int:
    """Consistent-read A/B (``--consistent``, the ``--replica`` lane's
    KEP-2340 growth): read capacity when every read must be *consistent*
    (no staler than the issuing session's own writes), primary-pinned vs
    RV-barrier reads spread over the replicas at matched freshness. One
    JSON line; ``value`` is the consistent-read capacity speedup at 2
    replicas vs the primary-only pin.

    Riders: (1) wait-for-frontier latency — under an active
    ``repl.ship`` delay, write on the primary then immediately read the
    replica pinned to the write's RV; p50/p99 of the observed barrier
    park (the consistent read's freshness cost, vs the replica lane's
    raw visibility lag). (2) session read-your-writes through the
    router — every read of the session's own write must come back fresh
    (zero stale), with a replica-local share high enough to prove the
    barrier parks instead of falling back. (3) byte equality — the
    replica's consistent list bytes sha256-equal the primary's at the
    same RV (encode-once on both sides)."""
    import hashlib

    from kcp_tpu import faults
    from kcp_tpu.server.rest import MultiClusterRestClient, RestClient
    from kcp_tpu.server.server import Config
    from kcp_tpu.server.threaded import ServerThread
    from kcp_tpu.utils.trace import REGISTRY

    objects = int(os.environ.get("KCP_BENCH_CONS_OBJECTS", "2000"))
    seconds = float(os.environ.get("KCP_BENCH_CONS_SECONDS", "1.0"))
    n_replicas = int(os.environ.get("KCP_BENCH_CONS_REPLICAS", "2"))
    lag_writes = int(os.environ.get("KCP_BENCH_CONS_LAG_WRITES", "120"))
    rywr_steps = int(os.environ.get("KCP_BENCH_CONS_RYWR_STEPS", "120"))
    clusters = [f"t{i}" for i in range(8)]

    def cm(name: str, cluster: str, data: str = "") -> dict:
        return {"apiVersion": "v1", "kind": "ConfigMap",
                "metadata": {"name": name, "namespace": "default",
                             "clusterName": cluster}, "data": {"v": data}}

    def status(address: str) -> dict:
        c = RestClient(address)
        try:
            return c._request("GET", "/replication/status")
        finally:
            c.close()

    def wait_applied(address: str, rv: int, timeout: float = 60.0) -> None:
        deadline = time.time() + timeout
        while time.time() < deadline:
            if status(address)["applied_rv"] >= rv:
                return
            time.sleep(0.02)
        raise RuntimeError(f"replica {address} never reached rv {rv}")

    def read_rate(address: str, target: str, secs: float,
                  headers: dict | None = None) -> float:
        c = RestClient(address)
        try:
            c.request_raw("GET", target, headers=headers)  # warm
            n = 0
            t0 = time.perf_counter()
            stop = t0 + secs
            while time.perf_counter() < stop:
                s, _h, _b = c.request_raw("GET", target, headers=headers)
                assert s == 200, s
                n += 1
            return n / (time.perf_counter() - t0)
        finally:
            c.close()

    primary = ServerThread(Config(durable=False, install_controllers=False,
                                  tls=False)).start()
    replicas = [ServerThread(Config(
        durable=False, install_controllers=False, tls=False,
        role="replica", primary=primary.address)).start()
        for _ in range(n_replicas)]
    router = ServerThread(Config(
        role="router", durable=False, tls=False,
        shards="s0=" + "|".join(
            [primary.address] + [r.address for r in replicas]))).start()
    out: dict = {}
    try:
        pc = MultiClusterRestClient(primary.address)
        for i in range(objects):
            pc.create("configmaps", cm(f"seed{i}", clusters[i % 8], str(i)))
        seed_rv = int(status(primary.address)["applied_rv"])
        for r in replicas:
            wait_applied(r.address, seed_rv)
        target = "/clusters/t0/api/v1/namespaces/default/configmaps"
        pin = {"X-Kcp-Min-Rv": str(seed_rv)}

        # --- capacity A/B at matched freshness (every read carries the
        # session pin; the primary IS the frontier, replicas barrier) ---
        per_slice = max(0.25, seconds / (n_replicas + 1))
        primary_pinned = read_rate(primary.address, target, per_slice,
                                   headers=pin)
        spread = primary_pinned + sum(
            read_rate(r.address, target, per_slice, headers=pin)
            for r in replicas)
        speedup = round(spread / max(primary_pinned, 1e-9), 2)

        # --- byte equality at the same RV (sha256 rider) ---
        c0 = RestClient(primary.address)
        _s, _h, pb = c0.request_raw("GET", target)
        c0.close()
        digest = hashlib.sha256(pb).hexdigest()
        bytes_equal = True
        for r in replicas:
            _s, rb = 0, b""
            cr = RestClient(r.address)
            _s, _h, rb = cr.request_raw("GET", target, headers=pin)
            cr.close()
            if hashlib.sha256(rb).hexdigest() != digest:
                bytes_equal = False

        # --- wait-for-frontier latency under a real ship delay ---
        faults.install(faults.FaultInjector("repl.ship:latency=5ms",
                                            seed=20260807))
        rep = replicas[0]
        waits_ms: list[float] = []
        rc = RestClient(rep.address)
        one = "/clusters/t1/api/v1/namespaces/default/configmaps"
        for i in range(lag_writes):
            w = pc.create("configmaps", cm(f"lag{i}", "t1", str(i)))
            rv = w["metadata"]["resourceVersion"]
            t0 = time.perf_counter()
            s, _h, _b = rc.request_raw(
                "GET", one, headers={"X-Kcp-Min-Rv": str(rv)})
            waits_ms.append((time.perf_counter() - t0) * 1e3)
            assert s == 200, s
        rc.close()

        # --- session read-your-writes through the router ---
        reads_before = REGISTRY.counter("router_replica_reads_total").value
        fb_before = REGISTRY.counter("router_replica_fallback_total").value
        sc = RestClient(router.address, cluster="t2")
        stale = 0
        for i in range(rywr_steps):
            sc.create("configmaps", cm(f"rw{i}", "t2", str(i)))
            got = sc.get("configmaps", f"rw{i}", "default")
            if got["data"]["v"] != str(i):
                stale += 1
        sc.close()
        faults.clear()
        replica_reads = (REGISTRY.counter(
            "router_replica_reads_total").value - reads_before)
        fallbacks = (REGISTRY.counter(
            "router_replica_fallback_total").value - fb_before)
        replica_local = round(
            replica_reads / max(replica_reads + fallbacks, 1), 3)

        import numpy as _np

        out = {
            "metric": "consistent_read_capacity_speedup",
            "value": speedup,
            "unit": "x",
            "stage": "consistent-bench",
            "consistent_bench": {
                "host_cpus": os.cpu_count(), "objects": objects,
                "replicas": n_replicas,
                "capacity_rps": {"primary_pinned": round(primary_pinned, 1),
                                 "spread": round(spread, 1)},
                "capacity_speedup": speedup,
                "bytes_equal": bytes_equal,
                "list_sha256": digest[:16],
                "wait_for_frontier": {
                    "p50_ms": round(float(_np.percentile(waits_ms, 50)), 3),
                    "p99_ms": round(float(_np.percentile(waits_ms, 99)), 3),
                    "writes": len(waits_ms)},
                "read_your_writes": {
                    "reads": rywr_steps, "stale": stale,
                    "replica_local_share": replica_local,
                    "fallbacks": int(fallbacks)},
            },
        }
        pc.close()
    finally:
        faults.clear()
        router.stop()
        for r in replicas:
            r.stop()
        primary.stop()
    emit(out)
    return 0


def writes_bench() -> int:
    """Write-path group commit A/B (``--writes``): serial
    (``KCP_GROUP_COMMIT=0``) vs grouped (``=1``) at 1/16/64/256
    concurrent writers under honest per-commit durability
    (``KCP_WAL_SYNC=fsync`` by default — the cost the commit window
    exists to amortize).

    Two measurement altitudes. The HEADLINE (``value``) is the
    **write-path component**: concurrent writer tasks driving
    ``store.create`` + the durability barrier directly on one event
    loop — the mutation + WAL append + sync + fan-out work the tentpole
    batches, with no HTTP serving overhead diluting it (median of 3
    trials per lane; the same altitude discipline as ``--store`` /
    ``--encode``). The **end-to-end** lanes run the same A/B through
    real HTTP serving (threads x RestClient against a ServerThread) and
    are reported alongside — on a 1-cpu host request serving dominates
    there, so the ratio is honest-but-smaller. Plus: (1) a seeded
    sequential CRUD equality pass — serial and grouped final state
    byte-identical modulo per-process identity fields
    (uid/creationTimestamp; the store-level fuzz in
    tests/test_group_commit.py pins those and proves FULL byte equality
    incl. the WAL), with identical RV sequences; (2) the
    kill-mid-window drill — durable primary + semi-sync standby,
    SIGKILL mid-storm, offline WAL replay must carry every acked write.
    ``value`` is the grouped/serial write-path ratio at 64 writers.
    """
    import hashlib
    import tempfile
    import threading

    from kcp_tpu.server.rest import RestClient
    from kcp_tpu.server.server import Config
    from kcp_tpu.server.threaded import ServerThread
    from kcp_tpu.store.store import LogicalStore
    from kcp_tpu.utils.trace import REGISTRY

    seconds = float(os.environ.get("KCP_BENCH_WRITES_SECONDS", "1.5"))
    concs = [int(x) for x in os.environ.get(
        "KCP_BENCH_WRITES_CONC", "1,16,64,256").split(",") if x.strip()]
    sync_mode = os.environ.get("KCP_BENCH_WRITES_SYNC", "fsync")
    eq_ops = int(os.environ.get("KCP_BENCH_WRITES_EQ_OPS", "400"))
    drill_writers = int(os.environ.get("KCP_BENCH_WRITES_DRILL_CONC", "8"))
    store_ops = int(os.environ.get("KCP_BENCH_WRITES_STORE_OPS", "200"))
    _raise_nofile()

    def cm(name: str, cluster: str, data: str = "") -> dict:
        return {"apiVersion": "v1", "kind": "ConfigMap",
                "metadata": {"name": name, "namespace": "default",
                             "clusterName": cluster}, "data": {"v": data}}

    def pctile(vals: list[float], q: float) -> float:
        if not vals:
            return 0.0
        s = sorted(vals)
        return s[max(0, min(len(s) - 1, int(q * len(s)) - 1))]

    def spawn(root: str, grouped: bool, role: str = "",
              primary: str = "") -> ServerThread:
        # the store reads KCP_GROUP_COMMIT/KCP_WAL_SYNC at construction:
        # patch only for the constructor window (scenario-topology
        # discipline), restore after
        saved = {k: os.environ.get(k)
                 for k in ("KCP_GROUP_COMMIT", "KCP_WAL_SYNC",
                           "KCP_FLOW_CONCURRENCY")}
        os.environ["KCP_GROUP_COMMIT"] = "1" if grouped else "0"
        os.environ["KCP_WAL_SYNC"] = sync_mode
        # flow control off: a 1-writer lane would saturate one tenant's
        # default token rate and measure throttling, not the write path
        # (bench.py --admission owns the flow-control story)
        os.environ["KCP_FLOW_CONCURRENCY"] = "0"
        try:
            kw: dict = dict(durable=True, install_controllers=False,
                            tls=False, root_dir=root)
            if role:
                kw.update(role=role, primary=primary,
                          repl_hysteresis_s=30.0)
            return ServerThread(Config(**kw)).start()
        finally:
            for k, v in saved.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v

    def hammer(address: str, writers: int, secs: float
               ) -> tuple[int, list[float], int]:
        """N writer threads creating as fast as acks return; returns
        (acked, per-write latencies, errors)."""
        lock = threading.Lock()
        acked = [0]
        errs = [0]
        lats: list[float] = []
        stop_at = time.perf_counter() + secs
        start = threading.Barrier(writers + 1)

        def work(wi: int) -> None:
            c = RestClient(address, cluster=f"t{wi % 8}")
            i = 0
            my: list[float] = []
            n = e = 0
            start.wait()
            try:
                while time.perf_counter() < stop_at:
                    t0 = time.perf_counter()
                    try:
                        c.create("configmaps",
                                 cm(f"w{wi}-{i}", f"t{wi % 8}", str(i)))
                        n += 1
                        my.append(time.perf_counter() - t0)
                    except Exception:
                        e += 1
                    i += 1
            finally:
                c.close()
            with lock:
                acked[0] += n
                errs[0] += e
                lats.extend(my)

        threads = [threading.Thread(target=work, args=(i,))
                   for i in range(writers)]
        for t in threads:
            t.start()
        start.wait()
        t0 = time.perf_counter()
        for t in threads:
            t.join()
        dt = time.perf_counter() - t0
        return int(acked[0] / max(dt, 1e-9)), lats, errs[0]

    # ------------------------------------ write-path component (headline)
    def store_lane(grouped: bool, conc: int) -> tuple[float, list[float]]:
        """One trial: conc writer tasks on one loop, store.create + the
        durability barrier; returns (writes/s, latencies)."""
        os.environ["KCP_GROUP_COMMIT"] = "1" if grouped else "0"
        os.environ["KCP_WAL_SYNC"] = sync_mode
        # comparable sample sizes per lane: low-concurrency lanes get
        # proportionally more ops per writer so a 1-writer trial is not
        # a 50ms noise measurement
        per_writer = store_ops * max(1, 64 // max(conc, 1))
        with tempfile.TemporaryDirectory() as root:
            store = LogicalStore(wal_path=os.path.join(root, "w.wal"))

            async def drive():
                async def writer(wi: int) -> list[float]:
                    lat: list[float] = []
                    for i in range(per_writer):
                        t0 = time.perf_counter()
                        store.create("configmaps", f"t{wi % 8}",
                                     cm(f"w{wi}-{i}", f"t{wi % 8}", str(i)))
                        aw = store.commit_durable(store.resource_version)
                        if aw is not None:
                            await aw
                        else:
                            await asyncio.sleep(0)
                        lat.append(time.perf_counter() - t0)
                    return lat

                t0 = time.perf_counter()
                per = await asyncio.gather(
                    *(writer(i) for i in range(conc)))
                dt = time.perf_counter() - t0
                return conc * per_writer / dt, [x for ls in per for x in ls]

            rps, lats = asyncio.run(drive())
            store.close()
        return rps, lats

    saved_env = {k: os.environ.get(k)
                 for k in ("KCP_GROUP_COMMIT", "KCP_WAL_SYNC")}
    path_lanes: dict[str, dict] = {}
    try:
        for mode, grouped in (("serial", False), ("grouped", True)):
            path_lanes[mode] = {}
            for n in concs:
                trials = [store_lane(grouped, n) for _ in range(3)]
                trials.sort(key=lambda t: t[0])
                rps, lats = trials[1]  # median by throughput
                path_lanes[mode][str(n)] = {
                    "rps": round(rps),
                    "p50_ms": round(pctile(lats, 0.50) * 1e3, 3),
                    "p99_ms": round(pctile(lats, 0.99) * 1e3, 3),
                }
                print(f"write-path {mode} x{n}: {round(rps)} w/s  p99 "
                      f"{path_lanes[mode][str(n)]['p99_ms']}ms",
                      file=sys.stderr, flush=True)
    finally:
        for k, v in saved_env.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v

    # --------------------------------------- end-to-end HTTP serving lanes
    lanes: dict[str, dict] = {}
    for mode, grouped in (("serial", False), ("grouped", True)):
        lanes[mode] = {}
        for n in concs:
            with tempfile.TemporaryDirectory() as root:
                srv = spawn(root, grouped)
                try:
                    rps, lats, errors = hammer(srv.address, n, seconds)
                finally:
                    srv.stop()
            lanes[mode][str(n)] = {
                "rps": rps, "errors": errors,
                "p50_ms": round(pctile(lats, 0.50) * 1e3, 3),
                "p99_ms": round(pctile(lats, 0.99) * 1e3, 3),
            }
            print(f"writes http {mode} x{n}: {rps} acks/s  "
                  f"p99 {lanes[mode][str(n)]['p99_ms']}ms "
                  f"({errors} errors)", file=sys.stderr, flush=True)

    # ------------------------------------------------ equality (A/B state)
    def equality_pass(grouped: bool) -> tuple[str, list[int]]:
        """One seeded sequential CRUD stream; returns (state digest
        modulo identity fields, rv sequence)."""
        rng = np.random.default_rng(7)
        rvs: list[int] = []
        with tempfile.TemporaryDirectory() as root:
            srv = spawn(root, grouped)
            try:
                c = RestClient(srv.address, cluster="t0")
                live: set[str] = set()
                for i in range(eq_ops):
                    name = f"eq{int(rng.integers(eq_ops // 4))}"
                    kind = int(rng.integers(3))
                    try:
                        if kind == 0 or name not in live:
                            out = c.create("configmaps",
                                           cm(name, "t0", str(i)))
                            live.add(name)
                        elif kind == 1:
                            cur = c.get("configmaps", name, "default")
                            cur["data"] = {"v": str(i)}
                            out = c.update("configmaps", cur)
                        else:
                            c.delete("configmaps", name, "default")
                            live.discard(name)
                            out = None
                    except Exception:
                        out = None
                    if out is not None:
                        rvs.append(int(out["metadata"]["resourceVersion"]))
                items, rv = c.list("configmaps", "default")
                stripped = [
                    {**o, "metadata": {
                        k: v for k, v in o["metadata"].items()
                        if k not in ("uid", "creationTimestamp")}}
                    for o in items]
                digest = hashlib.sha256(json.dumps(
                    [rv, stripped], sort_keys=True).encode()).hexdigest()
                c.close()
            finally:
                srv.stop()
        return digest, rvs

    d_serial, rv_serial = equality_pass(grouped=False)
    d_grouped, rv_grouped = equality_pass(grouped=True)
    state_equal = d_serial == d_grouped and rv_serial == rv_grouped

    # ------------------------------------------ kill-mid-window drill
    win0 = REGISTRY.counter("store_commit_windows_total").value
    ack0 = REGISTRY.counter("repl_ack_batched_total").value
    drill_root = tempfile.mkdtemp(prefix="kcp-writes-drill-")
    p = spawn(os.path.join(drill_root, "p"), grouped=True)
    s = spawn(os.path.join(drill_root, "s"), grouped=True,
              role="standby", primary=p.address)
    acked_names: list[str] = []
    lock = threading.Lock()

    # storm bounded in time, not ops: the kill must land mid-storm, and
    # a slow server teardown must not stretch the drill indefinitely
    drill_deadline = time.perf_counter() + max(0.5, seconds / 3) + 3.0

    def drill_writer(wi: int) -> None:
        c = RestClient(p.address, cluster="t1")
        try:
            for i in range(100_000):
                if time.perf_counter() > drill_deadline:
                    return
                name = f"dr{wi}-{i}"
                try:
                    c.create("configmaps", cm(name, "t1", str(i)))
                except Exception:
                    return  # dead primary: unacked by definition
                with lock:
                    acked_names.append(name)
        finally:
            c.close()

    storm = [threading.Thread(target=drill_writer, args=(i,))
             for i in range(drill_writers)]
    for t in storm:
        t.start()
    time.sleep(max(0.5, seconds / 3))
    p.kill()  # SIGKILL-equivalent: mid-window, no compaction
    for t in storm:
        t.join(timeout=30)
    s.stop()
    windows = REGISTRY.counter("store_commit_windows_total").value - win0
    acks_batched = REGISTRY.counter("repl_ack_batched_total").value - ack0
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "walreplay", os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                  "scripts", "walreplay.py"))
    walreplay = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(walreplay)
    st = walreplay.replay(os.path.join(drill_root, "p", "store.wal"))
    have = {key.decode().split("\x00")[3] for key in st.objects}
    lost = [nm for nm in acked_names if nm not in have]
    drill = {
        "writers": drill_writers,
        "acked_writes": len(acked_names),
        "lost_after_kill": len(lost),
        "commit_windows": windows,
        "acks_batched": acks_batched,
        "ok": not lost and windows > 0 and len(acked_names) > 0,
    }

    at = str(64 if 64 in concs else max(concs))
    base = max(path_lanes["serial"][at]["rps"], 1)
    http_base = max(lanes["serial"][at]["rps"], 1)
    out = {
        "metric": "write_group_commit_speedup",
        "value": round(path_lanes["grouped"][at]["rps"] / base, 2),
        "unit": "x",
        "stage": "writes-bench",
        "writes_bench": {
            "host_cpus": os.cpu_count(),
            "seconds": seconds,
            "wal_sync": sync_mode,
            "concurrency": concs,
            "write_path": {
                "serial": path_lanes["serial"],
                "grouped": path_lanes["grouped"],
                "speedup": {
                    str(n): round(
                        path_lanes["grouped"][str(n)]["rps"]
                        / max(path_lanes["serial"][str(n)]["rps"], 1), 2)
                    for n in concs},
            },
            "end_to_end_http": {
                "serial": lanes["serial"],
                "grouped": lanes["grouped"],
                "speedup_at_top": round(
                    lanes["grouped"][at]["rps"] / http_base, 2),
            },
            "p99_1_writer_ms": {
                "serial": path_lanes["serial"].get("1", {}).get("p99_ms"),
                "grouped": path_lanes["grouped"].get("1", {}).get("p99_ms"),
            },
            "state_equal": state_equal,
            "rv_sequence_equal": rv_serial == rv_grouped,
            "kill_drill": drill,
        },
    }
    emit(out)
    return 0


# ---------------------------------------------------------------------------
# Orchestrator: the TPU rides a tunnel that wedges transiently, and a hung
# in-process backend init cannot be interrupted from within. So the default
# entry point (1) pins ITSELF to the CPU platform so the parent can never
# touch the tunnel (the image's sitecustomize imports jax with the TPU
# platform baked in — a lazy backend init in the parent would race the
# child for the single tunnel, the known wedge trigger), (2) runs the
# measurement as a watchdogged child whose stdout goes to a FILE so the
# last evidence line survives any kill, and (3) always prints exactly one
# final JSON line — the freshest salvaged evidence, or a structured
# failure record; never a bare traceback.
# ---------------------------------------------------------------------------


def _raise_nofile() -> None:
    """Lift RLIMIT_NOFILE's soft cap to the hard cap: 10k live watch
    streams are 10k fds on this side of the wire."""
    try:
        import resource

        _soft, hard = resource.getrlimit(resource.RLIMIT_NOFILE)
        resource.setrlimit(resource.RLIMIT_NOFILE, (hard, hard))
    except (ImportError, ValueError, OSError):
        pass


def watchers_serve() -> int:
    """Internal child for ``--watchers``: one in-process asyncio server
    (LogicalStore + RestHandler + HttpServer, admission off) seeded with
    ``KCP_WB_OBJECTS`` deterministic configmaps across
    ``KCP_WB_CLUSTERS`` tenants, announced as one JSON line on stdout.

    Split across processes deliberately: the parent holds the client end
    of every stream and this child holds the server end, so a 10k-stream
    run bills ~10k fds to EACH process instead of 20k to one (the
    RLIMIT_NOFILE wall). Determinism (fixed clock, preset uids, preset
    RV sequence) is what lets the A/B passes compare per-watcher stream
    hashes across separate child processes.
    """
    from kcp_tpu.apis.scheme import default_scheme
    from kcp_tpu.server.handler import RestHandler
    from kcp_tpu.server.httpd import HttpServer
    from kcp_tpu.store.store import LogicalStore

    _raise_nofile()
    n_objects = int(os.environ.get("KCP_WB_OBJECTS", "100000"))
    n_clusters = int(os.environ.get("KCP_WB_CLUSTERS", "100"))

    async def run() -> None:
        store = LogicalStore(clock=lambda: 0.0)
        per = max(1, n_objects // n_clusters)
        for c in range(n_clusters):
            cl = f"w{c}"
            for i in range(per):
                store.create("configmaps", cl, {
                    "apiVersion": "v1", "kind": "ConfigMap",
                    "metadata": {"name": f"cm-{i}", "namespace": "default",
                                 "uid": f"uid-{cl}-{i}"},
                    "data": {"v": "0"},
                })
        handler = RestHandler(store, default_scheme(), admission=None)
        handler.ready = True
        srv = HttpServer(handler)
        await srv.start()
        print(json.dumps({"addr": srv.address, "objects": len(store),
                          "pid": os.getpid()}), flush=True)
        await asyncio.Event().wait()  # parent terminates us

    asyncio.run(run())
    return 0


_WB_TOKEN_RE = re.compile(rb'"v": "m(\d+)"')


class _WatcherStats:
    """Shared accounting the raw watcher tasks append into."""

    def __init__(self):
        self.lines = 0
        self.established = 0
        self.lat: list[float] = []
        self.t_send: dict[int, float] = {}  # token -> just-before-send
        self.hashes: dict[int, str] = {}    # watcher idx -> stream sha256


async def _wb_watcher(i: int, host: str, port: int, cluster: str,
                      stats: _WatcherStats, ready: asyncio.Event,
                      hash_lines: bool = False) -> None:
    """One raw watch stream: minimal HTTP, chunked-line reassembly,
    latency sampling off the mutation tokens. Deliberately NOT RestWatch
    — 10k of these must cost a task + a socket + a buffer, nothing else."""
    import hashlib

    reader, writer = await asyncio.open_connection(host, port)
    h = hashlib.sha256() if hash_lines else None
    try:
        writer.write(
            f"GET /clusters/{cluster}/api/v1/configmaps?watch=true "
            f"HTTP/1.1\r\nHost: bench\r\n\r\n".encode())
        await writer.drain()
        await reader.readuntil(b"\r\n\r\n")
        stats.established += 1
        ready.set()
        buf = b""
        while True:
            size_line = await reader.readline()
            if not size_line:
                return
            size = int(size_line.strip() or b"0", 16)
            if size == 0:
                return
            payload = await reader.readexactly(size)
            await reader.readexactly(2)  # \r\n
            now = time.monotonic()
            buf += payload
            *lines, buf = buf.split(b"\n")
            for line in lines:
                if not line:
                    continue
                stats.lines += 1
                if h is not None:
                    h.update(line + b"\n")
                m = _WB_TOKEN_RE.search(line)
                if m is not None:
                    t0 = stats.t_send.get(int(m.group(1)))
                    if t0 is not None:
                        stats.lat.append(now - t0)
    except (ConnectionError, asyncio.IncompleteReadError, OSError,
            ValueError):
        return
    finally:
        if h is not None:
            stats.hashes[i] = h.hexdigest()
        writer.close()
        try:
            await writer.wait_closed()
        except (ConnectionError, OSError):
            pass


def _wb_spawn_child(objects: int, clusters: int, coalesce: bool,
                    flush_ms: str, extra_env: dict | None = None):
    """Spawn the --watchers-serve child; returns (Popen, host, port)."""
    import subprocess
    from urllib.parse import urlsplit

    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    env.pop("KCP_FAULTS", None)
    env["KCP_NO_COMPILE_CACHE"] = "1"
    env["KCP_WB_OBJECTS"] = str(objects)
    env["KCP_WB_CLUSTERS"] = str(clusters)
    env["KCP_WATCH_COALESCE"] = "1" if coalesce else "0"
    env["KCP_WATCH_FLUSH_MS"] = flush_ms
    env.update(extra_env or {})
    p = subprocess.Popen([sys.executable, os.path.abspath(__file__),
                          "--watchers-serve"],
                         stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
                         env=env, text=True)
    line = p.stdout.readline()
    if not line:
        raise RuntimeError(f"--watchers-serve child died rc={p.poll()}")
    info = json.loads(line)
    parts = urlsplit(info["addr"])
    return p, parts.hostname, parts.port


def _wb_child_rss_kb(pid: int) -> int:
    with open(f"/proc/{pid}/status", encoding="ascii") as f:
        for line in f:
            if line.startswith("VmRSS:"):
                return int(line.split()[1])
    return 0


def _wb_scrape_counter(host: str, port: int, name: str) -> float:
    import http.client

    conn = http.client.HTTPConnection(host, port, timeout=10)
    try:
        conn.request("GET", "/metrics")
        text = conn.getresponse().read().decode()
    finally:
        conn.close()
    for line in text.splitlines():
        if line.startswith(name + " ") or line.startswith(name + "{"):
            return float(line.rsplit(None, 1)[-1])
    return 0.0


def _wb_mutate(host: str, port: int, schedule: list[tuple[str, str]],
               stats: _WatcherStats, threads: int = 4,
               pad: int = 0) -> float:
    """Drive the seeded update schedule over HTTP from worker threads
    (the serving loop lives in the child; the parent loop must stay free
    for 10k readers). Tokens stamp ``data.v`` so watchers can clock
    send→delivery without sharing a wall clock with the child. Returns
    elapsed seconds."""
    import threading as _threading

    from kcp_tpu.server.rest import RestClient

    lock = _threading.Lock()
    pos = 0

    def worker() -> None:
        nonlocal pos
        # wildcard client: each update routes to the cluster named in
        # metadata.clusterName (the schedule spans many tenants)
        c = RestClient(f"http://{host}:{port}", cluster="*")
        try:
            while True:
                with lock:
                    if pos >= len(schedule):
                        return
                    tok = pos
                    cl, name = schedule[pos]
                    pos += 1
                stats.t_send[tok] = time.monotonic()
                data = {"v": f"m{tok}"}
                if pad:
                    data["pad"] = "x" * pad
                c.update("configmaps", {
                    "apiVersion": "v1", "kind": "ConfigMap",
                    "metadata": {"name": name, "namespace": "default",
                                 "clusterName": cl},
                    "data": data,
                })
        finally:
            c.close()

    t0 = time.perf_counter()
    ts = [_threading.Thread(target=worker, daemon=True)
          for _ in range(threads)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    return time.perf_counter() - t0


async def _wb_mutate_pipelined(host: str, port: int,
                               schedule: list[tuple[str, str]],
                               stats: _WatcherStats,
                               pace_s: float = 0.0) -> float:
    """Drive the seeded update schedule over ONE pipelined HTTP/1.1
    connection: requests go out back-to-back and responses are reaped
    concurrently, so the commit rate is the server's processing rate,
    not one client round trip per write — the sustained-burst shape the
    flush A/B measures. A single connection also makes the COMMIT ORDER
    (and with it every rv and every watcher's byte stream) exactly the
    schedule order, which is what lets two separate child processes be
    compared hash-for-hash."""
    reader, writer = await asyncio.open_connection(host, port)
    t0 = time.perf_counter()

    async def reap() -> None:
        for _ in schedule:
            head = await reader.readuntil(b"\r\n\r\n")
            clen = 0
            for line in head.split(b"\r\n"):
                if line.lower().startswith(b"content-length:"):
                    clen = int(line.split(b":", 1)[1])
            if clen:
                await reader.readexactly(clen)

    reaper = asyncio.ensure_future(reap())
    try:
        for tok, (cl, name) in enumerate(schedule):
            body = json.dumps({
                "apiVersion": "v1", "kind": "ConfigMap",
                "metadata": {"name": name, "namespace": "default",
                             "clusterName": cl},
                "data": {"v": f"m{tok}"},
            }).encode()
            stats.t_send[tok] = time.monotonic()
            writer.write(
                f"PUT /clusters/{cl}/api/v1/configmaps/{name} HTTP/1.1\r\n"
                f"Host: bench\r\nContent-Type: application/json\r\n"
                f"Content-Length: {len(body)}\r\n\r\n".encode() + body)
            if pace_s:
                # sustained rate, not one mega-burst: the A/B measures
                # flush amortization under a steady commit stream (a
                # burst that outruns every producer collapses both modes
                # into one flush and measures nothing)
                await asyncio.sleep(pace_s)
            elif tok % 32 == 31:
                await writer.drain()
        await writer.drain()
        await reaper
    finally:
        reaper.cancel()
        writer.close()
        try:
            await writer.wait_closed()
        except (ConnectionError, OSError):
            pass
    return time.perf_counter() - t0


def watchers_bench() -> int:
    """Watcher-scale serving bench (``--watchers``): can ONE server
    sustain 10k live watch streams at 100k objects with bounded memory
    and bounded delivery latency?

    Three lanes, one child server process per lane (fd bill split
    across processes; see :func:`watchers_serve`):

    - **scale**: connect ``KCP_BENCH_WATCHERS`` streams in two halves
      against a 100k-object store, drive seeded update bursts, measure
      send→delivery p50/p99 across every stream and the child's RSS at
      each checkpoint — the gate is the RSS *slope* (per-watcher cost
      bounded, plateau under sustained load), not a magic number;
    - **flush A/B** (the headline value): the same seeded schedule at
      reduced scale against coalesced (KCP_WATCH_COALESCE=1) and
      per-batch (=0) children — per-watcher stream sha256 must be
      IDENTICAL across modes while ``watch_flush_total`` drops by the
      reported factor;
    - **evict drill**: a watcher that never reads while writes flood a
      tiny KCP_WATCH_BUFFER_MAX child — the slow socket must be evicted
      (metric + terminal typed 410 on the wire) while a healthy watcher
      on the same cluster keeps every event.
    """
    _raise_nofile()
    n_watchers = int(os.environ.get("KCP_BENCH_WATCHERS", "10000"))
    n_objects = int(os.environ.get("KCP_BENCH_WATCH_OBJECTS", "100000"))
    n_clusters = int(os.environ.get("KCP_BENCH_WATCH_CLUSTERS", "100"))
    n_muts = int(os.environ.get("KCP_BENCH_WATCH_MUTS", "1200"))
    # A/B width: small enough that the PER-BATCH baseline can actually
    # flush once per event batch (a saturated baseline auto-batches in
    # self-defense, flattering itself) — the reduction is measured where
    # the comparison is honest
    ab_watchers = int(os.environ.get("KCP_BENCH_WATCH_AB", "64"))
    ab_muts = int(os.environ.get("KCP_BENCH_WATCH_AB_MUTS", "600"))
    # scale lane serves at the production cadence; the A/B lane runs a
    # throughput-shaped tick (merging is bounded by commits-per-tick, so
    # the amortization factor is measured AT a declared cadence — the
    # docs' latency/syscall tradeoff, not a hidden knob)
    flush_ms = os.environ.get("KCP_BENCH_WATCH_FLUSH_MS", "2")
    ab_flush_ms = os.environ.get("KCP_BENCH_WATCH_AB_FLUSH_MS", "100")
    ab_pace_ms = float(os.environ.get("KCP_BENCH_WATCH_AB_PACE_MS", "3"))
    per_cluster = max(1, n_objects // n_clusters)

    def schedule_for(muts: int, clusters: int, focus: int = 0) -> list:
        """Seeded (cluster, name) update schedule. ``focus`` > 0 pins
        all updates onto that many clusters — the fan-out pressure
        shape the flush A/B measures."""
        rng = np.random.default_rng(1234)
        span = focus if focus else clusters
        return [(f"w{int(rng.integers(span))}",
                 f"cm-{int(rng.integers(per_cluster))}")
                for _ in range(muts)]

    async def scale_lane() -> dict:
        p, host, port = _wb_spawn_child(n_objects, n_clusters, True,
                                        flush_ms)
        stats = _WatcherStats()
        out: dict = {"watchers": n_watchers, "objects": n_objects,
                     "clusters": n_clusters, "mutations": n_muts}
        tasks: list[asyncio.Task] = []
        loop = asyncio.get_running_loop()
        try:
            rss0 = _wb_child_rss_kb(p.pid)

            async def connect(count: int, base: int) -> None:
                chunk = 200
                for at in range(0, count, chunk):
                    evs = []
                    for i in range(at, min(at + chunk, count)):
                        ready = asyncio.Event()
                        evs.append(ready)
                        tasks.append(asyncio.ensure_future(_wb_watcher(
                            base + i, host, port,
                            f"w{(base + i) % n_clusters}", stats, ready)))
                    await asyncio.gather(*(e.wait() for e in evs))

            half = n_watchers // 2
            await connect(half, 0)
            await loop.run_in_executor(
                None, _wb_mutate, host, port,
                schedule_for(n_muts // 2, n_clusters), stats)
            await asyncio.sleep(0.5)
            rss_half = _wb_child_rss_kb(p.pid)
            stats.lat.clear()
            await connect(n_watchers - half, half)
            out["streams_established"] = stats.established
            await loop.run_in_executor(
                None, _wb_mutate, host, port,
                schedule_for(n_muts // 2, n_clusters), stats)
            await asyncio.sleep(0.5)
            rss_full = _wb_child_rss_kb(p.pid)
            lat = sorted(stats.lat)
            out["delivery_p50_ms"] = round(
                1000 * lat[len(lat) // 2], 2) if lat else None
            out["delivery_p99_ms"] = round(
                1000 * lat[int(len(lat) * 0.99) - 1], 2) if lat else None
            out["latency_samples"] = len(lat)
            # plateau: more sustained load at FULL width must not grow
            # the resident set (bounded queues + bounded caches)
            await loop.run_in_executor(
                None, _wb_mutate, host, port,
                schedule_for(n_muts // 2, n_clusters), stats)
            await asyncio.sleep(0.5)
            rss_soak = _wb_child_rss_kb(p.pid)
            out["rss_kb"] = {"start": rss0, "half": rss_half,
                             "full": rss_full, "soak": rss_soak}
            out["rss_per_watcher_kb"] = round(
                (rss_full - rss_half) / max(n_watchers - half, 1), 2)
            out["rss_soak_growth"] = round(
                rss_soak / max(rss_full, 1), 4)
            out["lines_delivered"] = stats.lines
            out["evicted"] = _wb_scrape_counter(
                host, port, "watch_evicted_total")
            out["resumes_shared"] = _wb_scrape_counter(
                host, port, "watch_resume_shared_total")
        finally:
            for t in tasks:
                t.cancel()
            await asyncio.gather(*tasks, return_exceptions=True)
            p.terminate()
            p.wait(timeout=10)
        return out

    async def ab_lane() -> dict:
        """Coalesced vs per-batch flush A/B: identical seeded schedule,
        per-watcher stream hashes must match; flush count is the value."""
        ab_objects = min(n_objects, 10000)
        ab_clusters = 2  # all pressure on few clusters: every event
        # fans out to ~half the A/B watchers, the shape coalescing serves
        results: dict[str, dict] = {}
        for label, coalesce in (("per_batch", False), ("coalesced", True)):
            p, host, port = _wb_spawn_child(ab_objects, ab_clusters,
                                            coalesce, ab_flush_ms)
            stats = _WatcherStats()
            tasks: list[asyncio.Task] = []
            try:
                flush0 = _wb_scrape_counter(host, port, "watch_flush_total")
                evs = []
                for i in range(ab_watchers):
                    ready = asyncio.Event()
                    evs.append(ready)
                    tasks.append(asyncio.ensure_future(_wb_watcher(
                        i, host, port, f"w{i % ab_clusters}", stats, ready,
                        hash_lines=True)))
                await asyncio.gather(*(e.wait() for e in evs))
                elapsed = await _wb_mutate_pipelined(
                    host, port,
                    schedule_for(ab_muts, ab_clusters, focus=ab_clusters),
                    stats, pace_s=ab_pace_ms / 1000.0)
                # let the tail of the fan-out land before hashing stops
                target = ab_watchers  # every watcher sees its cluster's share
                for _ in range(200):
                    if stats.lines >= ab_muts * (ab_watchers // ab_clusters):
                        break
                    await asyncio.sleep(0.05)
                flush1 = _wb_scrape_counter(host, port, "watch_flush_total")
                del target
            finally:
                for t in tasks:
                    t.cancel()
                await asyncio.gather(*tasks, return_exceptions=True)
                p.terminate()
                p.wait(timeout=10)
            results[label] = {
                "flushes": flush1 - flush0,
                "lines": stats.lines,
                "elapsed_s": round(elapsed, 3),
                "hashes": dict(stats.hashes),
            }
        a, b = results["per_batch"], results["coalesced"]
        bytes_equal = (a["hashes"] == b["hashes"]
                       and len(a["hashes"]) == ab_watchers)
        reduction = a["flushes"] / max(b["flushes"], 1.0)
        return {
            "watchers": ab_watchers, "mutations": ab_muts,
            "clusters": ab_clusters, "flush_ms": ab_flush_ms,
            "pace_ms": ab_pace_ms,
            "bytes_equal": bytes_equal,
            "lines_equal": a["lines"] == b["lines"],
            "flushes_per_batch": a["flushes"],
            "flushes_coalesced": b["flushes"],
            "flush_reduction": round(reduction, 2),
            "per_batch_s": a["elapsed_s"], "coalesced_s": b["elapsed_s"],
        }

    async def evict_lane() -> dict:
        """Slow-watcher eviction drill: one stream that never reads, one
        healthy stream, writes until the slow socket passes the buffer
        bound — expect the eviction metric, a terminal typed 410 on the
        wire, and zero disturbance to the healthy stream."""
        p, host, port = _wb_spawn_child(
            64, 1, True, "1", {"KCP_WATCH_BUFFER_MAX": "4096"})
        out: dict = {}
        stats = _WatcherStats()
        tasks: list[asyncio.Task] = []
        try:
            # the slow client: sends the watch request, never reads. A
            # tiny SO_RCVBUF keeps the kernel from absorbing megabytes
            # on our behalf — backpressure must reach the server's
            # transport buffer, where the eviction policy watches.
            import socket as _socket

            sk = _socket.socket()
            sk.setsockopt(_socket.SOL_SOCKET, _socket.SO_RCVBUF, 4096)
            sk.setblocking(False)
            await asyncio.get_running_loop().sock_connect(sk, (host, port))
            s_reader, s_writer = await asyncio.open_connection(sock=sk)
            s_writer.write(b"GET /clusters/w0/api/v1/configmaps?watch=true "
                           b"HTTP/1.1\r\nHost: bench\r\n\r\n")
            await s_writer.drain()
            ready = asyncio.Event()
            tasks.append(asyncio.ensure_future(_wb_watcher(
                0, host, port, "w0", stats, ready)))
            await ready.wait()
            loop = asyncio.get_running_loop()
            writes = 400
            await loop.run_in_executor(
                None, _wb_mutate, host, port,
                [("w0", f"cm-{i % 64}") for i in range(writes)], stats, 2,
                16384)  # padded events: the backlog must outrun the
            # kernel's own socket buffering to reach the eviction bound
            deadline = loop.time() + 20
            evicted = 0.0
            while loop.time() < deadline:
                evicted = _wb_scrape_counter(host, port,
                                             "watch_evicted_total")
                if evicted:
                    break
                await asyncio.sleep(0.2)
            out["evicted_total"] = evicted
            # now read what the server buffered for the slow client: the
            # stream must end in a terminal typed 410 Status
            data = b""
            try:
                while True:
                    chunk = await asyncio.wait_for(s_reader.read(65536),
                                                   timeout=5)
                    if not chunk:
                        break
                    data += chunk
            except asyncio.TimeoutError:
                pass
            out["terminal_410"] = (b'"code": 410' in data
                                   and b'"reason": "Expired"' in data)
            s_writer.close()
            # the healthy stream saw every committed write
            for _ in range(100):
                if stats.lines >= writes:
                    break
                await asyncio.sleep(0.05)
            out["healthy_lines"] = stats.lines
            out["healthy_expected"] = writes
            out["ok"] = bool(out["terminal_410"]) and evicted >= 1 \
                and stats.lines >= writes
        finally:
            for t in tasks:
                t.cancel()
            await asyncio.gather(*tasks, return_exceptions=True)
            p.terminate()
            p.wait(timeout=10)
        return out

    async def run() -> dict:
        scale = await scale_lane()
        ab = await ab_lane()
        drill = await evict_lane()
        return {"scale": scale, "ab": ab, "evict_drill": drill}

    res = asyncio.run(run())
    out = {
        "metric": "watch_flush_reduction",
        "value": res["ab"]["flush_reduction"],
        "unit": "x",
        "stage": "watchers",
        "watchers_bench": res,
    }
    emit(out)
    return 0


def _fail_json(stage: str, detail: str, attempts: int, for_suite: bool) -> None:
    err = {"stage": stage, "detail": detail[-2000:], "attempts": attempts}
    # a dead tunnel must not erase the round's record: committed
    # measurements exist independently of this run. If a round-long
    # probe log exists (round 5 ran the bench repeatedly all day waiting
    # for the tunnel), summarize it so a zero here is self-explanatory.
    committed = ("committed evidence: BENCH_r04_early/tuned/pallas/suite/1m"
                 ".json + BASELINE.md 'Measured results'")
    # probe logs are a per-host diagnostic, not a committed artifact:
    # KCP_BENCH_PROBE_LOGS names them (os.pathsep-separated); unset =
    # nothing to summarize (the round-5 runs exported it to the scratch
    # files the retrying probe loop appended to)
    probes: list[str] = []
    probe_logs = os.environ.get("KCP_BENCH_PROBE_LOGS", "")
    for log_path in filter(None, probe_logs.split(os.pathsep)):
        try:
            with open(log_path, encoding="utf-8", errors="replace") as f:
                probes += [ln.strip() for ln in f if ln.strip()]
        except (OSError, UnicodeError):
            pass
    if probes:
        # bounded like detail[-2000:]: this is one JSON line
        committed += (" | tunnel probes this round: "
                      + "; ".join(probes[-10:])[:800])
    if for_suite:
        print(json.dumps({"suite": [], "error": err, "note": committed}))
    else:
        print(json.dumps({
            "metric": "reconciles_per_sec",
            "value": 0,
            "unit": "rows/s",
            "vs_baseline": 0.0,
            "error": err,
            "note": committed,
        }))


def trace_bench() -> int:
    """Distributed-tracing cost + convergence attribution (``--trace``).

    Three questions, answered in one lane:

    1. **Off-path cost** — the ``--store``-shaped serving hot path
       (list/get/update through the real RestHandler) and the
       ``--watchers``-shaped fan-out hot path (mutation → batched
       fan-out → encode-once event lines), each run three ways:
       ``KCP_TRACE=0``, default 1-in-64 sampling, and always-on. The
       committed gate is <3% p50 overhead at default sampling.
    2. **Wire neutrality** — every response body and event line across
       all three modes feeds one sha256 per mode; the digests must be
       identical (tracing never touches the wire).
    3. **Attribution** — a router + 2 durable shards + standby topology
       with a host-backend sync engine over it: sampled spec writes are
       traced client → router → shard → store/WAL → standby ack →
       engine stage/tick/patch → downstream status → status upsync,
       assembled via the router's ``/debug/trace`` scatter + the
       engine's rv-linked fragment, and each trace's per-phase durations
       must sum-reconcile (±5%) with the measured spec→status wall time.
    """
    import asyncio
    import hashlib
    import tempfile

    from kcp_tpu import obs
    from kcp_tpu.apis.scheme import default_scheme
    from kcp_tpu.client import Client
    from kcp_tpu.obs import assemble
    from kcp_tpu.server.handler import RestHandler
    from kcp_tpu.server.httpd import Request
    from kcp_tpu.server.rest import RestClient
    from kcp_tpu.store.store import LogicalStore
    from kcp_tpu.utils import errors as kerrors

    n_objects = int(os.environ.get("KCP_BENCH_TRACE_OBJECTS", "5000"))
    n_reqs = int(os.environ.get("KCP_BENCH_TRACE_REQS", "400"))
    n_watchers = int(os.environ.get("KCP_BENCH_TRACE_WATCHES", "64"))
    n_muts = int(os.environ.get("KCP_BENCH_TRACE_MUTS", "300"))
    n_conv = int(os.environ.get("KCP_BENCH_TRACE_CONV", "4"))

    def _cm(i: int, v: str) -> dict:
        return {
            "apiVersion": "v1", "kind": "ConfigMap",
            "metadata": {"name": f"cm-{i}", "namespace": f"ns{i % 8}",
                         "uid": f"uid-{i}",  # fixed: modes must be byte-equal
                         "labels": {"team": f"t{i % 64}"}},
            "data": {"v": v, "pad": "x" * 64},
        }

    def _p50(vals: list[float]) -> float:
        s = sorted(vals)
        return s[len(s) // 2] if s else 0.0

    def set_mode(env: dict) -> None:
        for k in ("KCP_TRACE", "KCP_TRACE_SAMPLE"):
            os.environ.pop(k, None)
        os.environ.update(env)
        os.environ["KCP_TRACE_SEED"] = "7"
        obs.TRACER.reconfigure()

    mode_envs = (("off", {"KCP_TRACE": "0"}),
                 ("sampled", {"KCP_TRACE": "1", "KCP_TRACE_SAMPLE": "64"}),
                 ("always", {"KCP_TRACE": "1", "KCP_TRACE_SAMPLE": "1"}))
    lanes = ("p50_list_us", "p50_get_us", "p50_put_us", "p50_fanout_us")

    def _greq(i: int) -> Request:
        return Request("GET", f"/clusters/c0/api/v1/namespaces/ns{i % 8}"
                              f"/configmaps/cm-{i}", {}, {}, b"")

    def _preq(i: int, v: str) -> Request:
        return Request("PUT", f"/clusters/c0/api/v1/namespaces/ns{i % 8}"
                              f"/configmaps/cm-{i}",
                       {}, {"content-type": "application/json"},
                       json.dumps(_cm(i, v)).encode())

    def _fan_mut(store, watches, i: int, v: str) -> None:
        """One production-shaped fan-out beat: mutate under the serving
        layer's sampling decision, flush, and encode every watcher's
        lines through the shared encode-once cache."""
        ctx = None
        if obs.TRACER.enabled and obs.TRACER.head_sampled():
            ctx = obs.TRACER.mint(sampled=True)
        if ctx is not None:
            with obs.use(ctx):
                store.update("configmaps", "c0", _cm(i, v))
        else:
            store.update("configmaps", "c0", _cm(i, v))
        store._flush_events()
        for w in watches:
            store.encode_events(w.drain())

    async def measure() -> dict:
        """Overhead A/B on ONE shared store, modes interleaved per
        small op block — host drift and cache state hit every mode
        equally, so a p50 delta is tracing cost, not weather. (Byte
        identity is proven separately on fresh per-mode stores, where
        response bytes are comparable.)"""
        set_mode({"KCP_TRACE": "0"})
        store = LogicalStore(indexed=True, clock=lambda: 1_700_000_000.0)
        handler = RestHandler(store, default_scheme(), admission=None)
        for i in range(n_objects):
            store.create("configmaps", "c0", _cm(i, str(i)))
        watches = [store.watch("configmaps") for _ in range(n_watchers)]
        lreq = Request("GET", "/clusters/c0/api/v1/configmaps", {}, {}, b"")
        times = {name: {"list": [], "get": [], "put": [], "fanout": []}
                 for name, _env in mode_envs}
        for j in range(30):  # warmup: caches hot before the first sample
            await handler(lreq if j % 5 == 0 else _greq(j))
            _fan_mut(store, watches, j, f"w{j}")
        pc = time.perf_counter
        blocks = max(8, n_reqs // 8)
        ctr = 0
        for _b in range(blocks):
            block: dict[str, dict[str, list[float]]] = {}
            for name, env in mode_envs:
                set_mode(env)
                bl = block[name] = {"list": [], "get": [], "put": [],
                                    "fanout": []}
                for _k in range(2):
                    t0 = pc()
                    await handler(lreq)
                    bl["list"].append(pc() - t0)
                for k in range(4):
                    i = (ctr * 13 + k * 5) % n_objects
                    t0 = pc()
                    resp = await handler(_greq(i))
                    bl["get"].append(pc() - t0)
                    assert resp.status == 200, resp.status
                for k in range(4):
                    i = (ctr * 11 + k * 7) % n_objects
                    t0 = pc()
                    resp = await handler(_preq(i, f"u{ctr}-{k}"))
                    bl["put"].append(pc() - t0)
                    assert resp.status == 200, resp.status
                for k in range(max(2, n_muts // (blocks * 3))):
                    i = (ctr * 17 + k * 3) % n_objects
                    t0 = pc()
                    _fan_mut(store, watches, i, f"m{ctr}-{k}")
                    bl["fanout"].append(pc() - t0)
                ctr += 1
            # paired per-block p50s: the ratio within one block cancels
            # the drift this host shows BETWEEN blocks
            for name, bl in block.items():
                for lane, vals in bl.items():
                    times[name][lane].append(_p50(vals))
        for w in watches:
            w.close()
        store.close()
        handler.close()
        out = {name: {"p50_list_us": round(_p50(tl["list"]) * 1e6, 2),
                      "p50_get_us": round(_p50(tl["get"]) * 1e6, 2),
                      "p50_put_us": round(_p50(tl["put"]) * 1e6, 2),
                      "p50_fanout_us": round(_p50(tl["fanout"]) * 1e6, 2)}
               for name, tl in times.items()}
        # per-lane overhead = median over blocks of the paired ratio;
        # the two GATED lanes pool every op class's per-block ratios
        # (ratios are dimensionless, so pooling list/get/put is sound
        # and the median over ~150 paired ratios beats any single
        # class's noise floor)
        for name, tl in times.items():
            if name == "off":
                continue
            ratios = {}
            pooled: dict[str, list[float]] = {"store": [], "watchers": []}
            for lane in ("list", "get", "put", "fanout"):
                pairs = [m / b for m, b in zip(tl[lane], times["off"][lane])
                         if b > 0]
                ratios[f"p50_{lane}_us"] = round(
                    100.0 * (_p50(pairs) - 1.0), 2)
                pooled["watchers" if lane == "fanout"
                       else "store"].extend(pairs)
            out[name]["paired_overhead_pct"] = ratios
            out[name]["lane_overhead_pct"] = {
                k: round(100.0 * (_p50(v) - 1.0), 2)
                for k, v in pooled.items()}
        return out

    async def byte_check() -> dict[str, str]:
        """The wire-neutrality proof: an identical op sequence against a
        fresh deterministic store per mode; every response body and
        event line feeds the mode's digest."""
        digests: dict[str, str] = {}
        for name, env in mode_envs:
            set_mode(env)
            store = LogicalStore(indexed=True,
                                 clock=lambda: 1_700_000_000.0)
            handler = RestHandler(store, default_scheme(), admission=None)
            for i in range(min(n_objects, 1000)):
                store.create("configmaps", "c0", _cm(i, str(i)))
            watches = [store.watch("configmaps")
                       for _ in range(min(n_watchers, 16))]
            digest = hashlib.sha256()
            lreq = Request("GET", "/clusters/c0/api/v1/configmaps",
                           {}, {}, b"")
            for j in range(min(n_reqs, 200)):
                i = j % min(n_objects, 1000)
                req = (lreq if j % 4 == 0
                       else _greq(i) if j % 4 == 1
                       else _preq(i, f"u{j}"))
                resp = await handler(req)
                digest.update(resp.body)
                for w in watches:
                    for line in store.encode_events(w.drain()):
                        digest.update(line)
            for w in watches:
                w.close()
            store.close()
            handler.close()
            digests[name] = digest.hexdigest()
        return digests

    modes = asyncio.run(measure())
    digests = asyncio.run(byte_check())
    for name in modes:
        modes[name]["sha256"] = digests[name]
    bytes_equal = (digests["off"] == digests["sampled"]
                   == digests["always"])
    sampled_overhead = modes["sampled"]["lane_overhead_pct"]
    headline = max(sampled_overhead.values())

    # ---- convergence attribution on a router + 2 shards + standby ----

    async def conv_drive(router_url: str, cluster: str) -> dict:
        from kcp_tpu.syncer.engine import CLUSTER_LABEL, BatchSyncEngine

        phys = LogicalStore()
        up = RestClient(router_url, cluster=cluster)
        driver = RestClient(router_url, cluster=cluster)
        down = Client(phys, "phys")
        engine = BatchSyncEngine(up, down, "configmaps", "bench-loc",
                                 backend="host", batch_window=0.005,
                                 resync_period=None)
        await engine.start()
        profiles: list[dict] = []
        traces: list[dict] = []
        try:
            for k in range(n_conv):
                name = f"conv-{k}"
                body = {"apiVersion": "v1", "kind": "ConfigMap",
                        "metadata": {"name": name, "namespace": "default",
                                     "clusterName": cluster,
                                     "labels": {CLUSTER_LABEL: "bench-loc"}},
                        "data": {"v": "0"}}
                ctx = obs.TRACER.mint(sampled=True)
                t0 = time.time()
                with obs.use(ctx):
                    resp = driver.create("configmaps", body)
                t_ack = time.time()
                rv = resp["metadata"]["resourceVersion"]
                obs.phase("write", ctx, t0, t_ack, rv=str(rv), obj=name)
                deadline = time.time() + 30.0
                while time.time() < deadline:
                    try:
                        dobj = down.get("configmaps", name, "default")
                        break
                    except kerrors.NotFoundError:
                        await asyncio.sleep(0.01)
                else:
                    raise RuntimeError(f"{name} never synced downstream")
                dobj["status"] = {"observed": True, "k": k}
                down.update_status("configmaps", dobj)
                while time.time() < deadline:
                    o = driver.get("configmaps", name, "default")
                    if (o.get("status") or {}).get("observed"):
                        break
                    await asyncio.sleep(0.01)
                else:
                    raise RuntimeError(f"{name} status never upsynced")
                t_obs = time.time()
                obs.phase("e2e", ctx, t0, t_obs, rv=str(rv), obj=name)
                # assemble: router scatter (client→router→shard→repl
                # spans) + the engine's rv-linked convergence fragment
                rc = RestClient(router_url)
                try:
                    doc = rc._request(
                        "GET", f"/debug/trace?id={ctx.trace_id}") or {}
                finally:
                    rc.close()
                by_trace: dict[str, list[dict]] = {}
                for s in obs.TRACER.spans():
                    by_trace.setdefault(s["trace"], []).append(s)
                span_lists = [doc.get("spans", [])] + list(by_trace.values())
                merged = assemble.merge_fragments(span_lists, rv=rv)
                profiles.append(assemble.phase_profile(merged))
                traces.append(assemble.summarize_trace(merged,
                                                       ctx.trace_id))
        finally:
            await engine.stop()
            up.close()
            driver.close()
            phys.close()
        return {"profiles": profiles, "traces": traces}

    def conv_run() -> dict:
        from kcp_tpu.server.server import Config
        from kcp_tpu.server.threaded import ServerThread
        from kcp_tpu.sharding import ShardRing

        set_mode({"KCP_TRACE": "1", "KCP_TRACE_SAMPLE": "1"})
        tmp = tempfile.mkdtemp(prefix="kcp-bench-trace-")
        threads: list = []
        try:
            s0 = ServerThread(Config(
                durable=True, root_dir=os.path.join(tmp, "s0"), tls=False,
                install_controllers=False)).start()
            threads.append(s0)
            s1 = ServerThread(Config(
                durable=True, root_dir=os.path.join(tmp, "s1"), tls=False,
                install_controllers=False)).start()
            threads.append(s1)
            standby = ServerThread(Config(
                role="standby", primary=s0.address, durable=True,
                root_dir=os.path.join(tmp, "sb"), tls=False)).start()
            threads.append(standby)
            spec = f"s0={s0.address}|{standby.address},s1={s1.address}"
            router = ServerThread(Config(role="router", shards=spec,
                                         durable=False, tls=False)).start()
            threads.append(router)
            ring = ShardRing.from_spec(spec)
            cluster = next(f"conv{i}" for i in range(256)
                           if ring.owner_index(f"conv{i}") == 0)
            # semi-sync must be live before the first traced write, or
            # the repl.ack span never appears: wait for the standby feed
            sc = RestClient(s0.address)
            try:
                deadline = time.time() + 30.0
                while time.time() < deadline:
                    st = sc._request("GET", "/replication/status") or {}
                    if st.get("subscribers", 0) >= 1:
                        break
                    time.sleep(0.05)
            finally:
                sc.close()
            out = asyncio.run(conv_drive(router.address, cluster))
            out["cluster"] = cluster
            out["topology"] = "router + 2 durable shards + standby(s0)"
            return out
        finally:
            for t in reversed(threads):
                try:
                    t.stop()
                except Exception:
                    pass

    conv = conv_run()
    sums_ok = [bool(p.get("sum_ok")) for p in conv["profiles"]]
    phase_names = sorted({p for prof in conv["profiles"]
                          for p in prof.get("phases", {})})
    out = {
        "metric": "trace_overhead_p50_pct",
        "value": round(headline, 2),
        "unit": "%",
        "trace_bench": {
            "objects": n_objects, "requests": n_reqs,
            "watchers": n_watchers, "mutations": n_muts,
            "modes": modes,
            "overhead_pct": {
                "sampled": sampled_overhead,
                "always": modes["always"]["lane_overhead_pct"]},
            "bytes_equal": bytes_equal,
            "convergence": {
                "runs": n_conv,
                "topology": conv.get("topology"),
                "cluster": conv.get("cluster"),
                "sum_reconciles": sums_ok,
                "all_sum_ok": all(sums_ok) and bool(sums_ok),
                "phases_seen": phase_names,
                "profiles": conv["profiles"],
                "traces": conv["traces"],
            },
        },
    }
    emit(out)
    return 0


def _salvage(stdout_text: str, for_suite: bool) -> tuple[dict | None, dict | None]:
    """(last evidence line with a real value, last diagnostic line) from
    a child's stdout. Diagnostic lines (value 0, e.g. deadman stage
    reports) never become the result but name where the child died."""
    found = diag = None
    for ln in stdout_text.splitlines():
        ln = ln.strip()
        if not (ln.startswith("{") and ln.endswith("}")):
            continue
        try:
            obj = json.loads(ln)
        except ValueError:
            continue
        if for_suite and obj.get("suite"):
            found = obj
        elif not for_suite and obj.get("value", 0) > 0:
            found = obj
        else:
            diag = obj
    return found, diag


def orchestrate(child_args: list[str]) -> int:
    import subprocess
    import tempfile

    for_suite = "--suite" in child_args
    last = ""
    best: dict | None = None  # best salvaged evidence across attempts
    for attempt in range(1, CHILD_ATTEMPTS + 1):
        if attempt > 1:
            time.sleep(ATTEMPT_BACKOFFS_S[min(attempt - 2,
                                              len(ATTEMPT_BACKOFFS_S) - 1)])
        env = dict(os.environ, KCP_BENCH_CHILD="1")
        env[DEADLINE_ENV] = str(time.time() + CHILD_TIMEOUT_S)
        if attempt == CHILD_ATTEMPTS:
            env[FINAL_ATTEMPT_ENV] = "1"
        # child stdout AND stderr go to files: TimeoutExpired's captures
        # are None with pipes on this platform, and the salvaged evidence
        # line + stderr tail are the whole point of the harness
        with tempfile.TemporaryFile(mode="w+") as outf, \
                tempfile.TemporaryFile(mode="w+") as errf:
            timed_out = False
            try:
                r = subprocess.run(
                    [sys.executable, os.path.abspath(__file__), *child_args],
                    env=env, stdout=outf, stderr=errf, text=True,
                    timeout=CHILD_TIMEOUT_S,
                )
            except subprocess.TimeoutExpired:
                timed_out = True
            outf.seek(0)
            stdout = outf.read()
            errf.seek(0)
            stderr = errf.read()
        sys.stderr.write(stderr)
        salvaged, diag = _salvage(stdout, for_suite)
        if salvaged is not None:
            how = ("timeout" if timed_out
                   else f"rc={r.returncode}" if r.returncode else None)
            if how:
                salvaged["note"] = (salvaged.get("note", "")
                                    + f" [salvaged after child {how}]").strip()
            # a final (non-provisional) result wins immediately; a
            # provisional-only child leaves budget for a cleaner attempt
            if not salvaged.get("provisional"):
                print(json.dumps(salvaged))
                return 0
            # completeness metric: suite = lanes measured, else the rate
            def _merit(obj: dict | None) -> float:
                if obj is None:
                    return -1.0
                if for_suite:
                    return float(len(obj.get("suite", [])))
                return float(obj.get("value", 0))

            if _merit(salvaged) > _merit(best):
                best = salvaged
            last = f"attempt {attempt}: provisional evidence only"
        else:
            where = (f"stage={diag.get('stage')}" if diag
                     else "no evidence line")
            tail = stderr.strip().splitlines()
            how = (f"hung > {CHILD_TIMEOUT_S}s" if timed_out
                   else f"rc={r.returncode}")
            last = (f"attempt {attempt}: child {how}, {where}; stderr tail: "
                    + " | ".join(tail[-3:]))
        print(last, file=sys.stderr)
    if best is not None:
        print(json.dumps(best))
        return 0
    # every device attempt died without evidence (r05: three
    # device-init stalls published value=0 and the round went blind).
    # Run once more on the CPU backend: a real number tagged degraded
    # keeps the perf trajectory measurable even when the accelerator
    # path is down — the tag (not the value) is the alarm.
    print("all device attempts failed; running CPU-backend fallback",
          file=sys.stderr)
    env = dict(os.environ, KCP_BENCH_CHILD="1", JAX_PLATFORMS="cpu",
               KCP_BENCH_FINAL="1")
    env[DEADLINE_ENV] = str(time.time() + CHILD_TIMEOUT_S)
    with tempfile.TemporaryFile(mode="w+") as outf, \
            tempfile.TemporaryFile(mode="w+") as errf:
        try:
            subprocess.run(
                [sys.executable, os.path.abspath(__file__), *child_args],
                env=env, stdout=outf, stderr=errf, text=True,
                timeout=CHILD_TIMEOUT_S,
            )
        except subprocess.TimeoutExpired:
            pass
        outf.seek(0)
        salvaged, _diag = _salvage(outf.read(), for_suite)
        errf.seek(0)
        sys.stderr.write(errf.read())
    if salvaged is not None:
        salvaged["degraded"] = True
        salvaged["note"] = (salvaged.get("note", "") + " [device unavailable "
                            "after " + str(CHILD_ATTEMPTS) + " attempts; "
                            "CPU-backend fallback measurement]").strip()
        print(json.dumps(salvaged))
        return 0
    _fail_json("measurement", last, CHILD_ATTEMPTS, for_suite)
    return 0


if __name__ == "__main__":
    args = [a for a in sys.argv[1:] if a != "--child"]
    if "--shard-loadgen" in args:
        # internal: the --sharded bench's write-driver child (never
        # touches jax; shards are separate kcp processes)
        sys.exit(shard_loadgen())
    if "--watchers-serve" in args:
        # internal: the --watchers bench's server child (never touches
        # jax; the parent holds the client end of every stream)
        sys.exit(watchers_serve())
    if ("--store" in args or "--admission" in args or "--encode" in args
            or "--sharded" in args or "--replica" in args
            or "--consistent" in args
            or "--watchers" in args or "--trace" in args
            or "--smartclient" in args or "--writes" in args
            or "--elastic" in args or "--pagination" in args
            or "--gauntlet" in args or "--placement" in args):
        # pure-host microbenches: pin CPU (never touch the tunnel)
        # and run in-process — no watchdog child needed
        try:
            import jax

            jax.config.update("jax_platforms", "cpu")
        except Exception:
            pass
        sys.exit(store_bench() if "--store" in args
                 else admission_bench() if "--admission" in args
                 else sharded_bench() if "--sharded" in args
                 else replica_bench() if "--replica" in args
                 else consistent_bench() if "--consistent" in args
                 else watchers_bench() if "--watchers" in args
                 else trace_bench() if "--trace" in args
                 else smartclient_bench() if "--smartclient" in args
                 else elastic_bench() if "--elastic" in args
                 else writes_bench() if "--writes" in args
                 else pagination_bench() if "--pagination" in args
                 else gauntlet_bench() if "--gauntlet" in args
                 else placement_bench() if "--placement" in args
                 else encode_bench())
    if "--probe" in args:
        # manual diagnostic: always run in-process (never through the
        # orchestrator, whose JSON contract a probe's output would fail)
        os.environ["KCP_BENCH_CHILD"] = "1"
    if os.environ.get("KCP_BENCH_CHILD") != "1" and "--child" not in sys.argv:
        # Parent process: pin to CPU BEFORE anything can lazily init a
        # backend. sitecustomize has already imported jax with the TPU
        # platform; only the config lever works at this point. The child
        # (KCP_BENCH_CHILD=1) keeps the real platform — it must be the
        # ONLY process on the tunnel.
        try:
            import jax

            jax.config.update("jax_platforms", "cpu")
        except Exception:
            pass
        sys.exit(orchestrate(args))

    # honor an explicit JAX_PLATFORMS override: the image's sitecustomize
    # imports jax with the TPU platform baked in before shell env can
    # land, so the config lever is the one that works (same workaround as
    # __graft_entry__.dryrun_multichip)
    want = os.environ.get("JAX_PLATFORMS", "")
    if want and want != "axon":
        import jax

        try:
            jax.config.update("jax_platforms", want)
        except Exception as e:
            print(f"warning: could not force JAX platform {want!r} ({e}); "
                  f"continuing on the baked-in platform", file=sys.stderr)
    if "--probe" in args:
        # manual diagnostic only (KCP_BENCH_CHILD=1 python bench.py
        # --probe): quick device-availability check for tunnel debugging;
        # the orchestrator itself never probes
        import jax

        d = jax.devices()
        print(d[0].platform, len(d))
        sys.exit(0)
    if "--suite" in args:
        sys.exit(suite())
    sys.exit(main())
