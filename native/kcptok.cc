// kcptok — CPython extension: schema tokenizer that walks Python dicts
// DIRECTLY (no json.dumps, no re-parse). Twin of
// kcp_tpu/ops/schemahash.tokenize_schema_py; the batch JSON-blob path
// (encode.cc enc_tokenize_schemas) remains as the mid fallback and the
// Python walk as the reference implementation.
//
// Why this exists: BASELINE configs[3] re-buckets 5k tenant CRD sets per
// negotiation pass. The Python walk costs ~35-50 us/schema and even the
// serialize-then-native path pays ~11 us of json.dumps per schema; this
// walk touches each PyObject once and feeds bytes straight into FNV,
// with zero allocation per scalar. Anything non-JSON-shaped (tuples,
// custom types, non-str keys) returns a "unsupported" rc and the caller
// falls back — the extension never guesses.
//
// Hash semantics are locked to kcp_tpu/ops/hashing.py:
//   key tokens   = fnv1a(utf8(key))                        (no 0->1 map)
//   leaf tokens  = fnv1a(json.dumps-rendered scalar), 0->1
// and the structural markers + truncation semantics are locked to
// tokenize_schema_py (size check at walk entry only; trailing length
// token; row truncated to max_tokens, zero-padded).
#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include <algorithm>
#include <cmath>
#include <cstring>
#include <vector>

#include "common.h"

namespace {

constexpr uint32_t TOK_OPEN = 0xA11CE;
constexpr uint32_t TOK_CLOSE = 0xB0B;
constexpr uint32_t TOK_LIST_OPEN = 0xC0DE;
constexpr uint32_t TOK_LIST_CLOSE = 0xD00D;

// Streaming FNV-1a so scalar rendering never allocates.
struct Fnv {
  uint32_t h = kcpnative::FNV_OFFSET;
  inline void byte(unsigned char b) {
    h ^= b;
    h *= kcpnative::FNV_PRIME;
  }
  inline void feed(const char* d, size_t n) {
    for (size_t i = 0; i < n; i++) byte((unsigned char)d[i]);
  }
};

// Feed a UTF-8 string rendered exactly as json.dumps(ensure_ascii=False)
// would: quoted, with ", \, \b \f \n \r \t short-escaped and remaining
// control bytes as \u00xx (jsoncanon.cc write_escaped is the same table).
void feed_escaped(Fnv* f, const char* s, Py_ssize_t n) {
  f->byte('"');
  for (Py_ssize_t i = 0; i < n; i++) {
    unsigned char c = (unsigned char)s[i];
    switch (c) {
      case '"': f->feed("\\\"", 2); break;
      case '\\': f->feed("\\\\", 2); break;
      case '\b': f->feed("\\b", 2); break;
      case '\f': f->feed("\\f", 2); break;
      case '\n': f->feed("\\n", 2); break;
      case '\r': f->feed("\\r", 2); break;
      case '\t': f->feed("\\t", 2); break;
      default:
        if (c < 0x20) {
          char buf[8];
          snprintf(buf, sizeof(buf), "\\u%04x", c);
          f->feed(buf, 6);
        } else {
          f->byte(c);
        }
    }
  }
  f->byte('"');
}

// Hash one JSON scalar as canonical_json renders it. Returns false on a
// non-JSON-scalar type (caller falls back to the Python walk) or on a
// Python-level error (error indicator set).
bool scalar_hash(PyObject* v, uint32_t* out) {
  Fnv f;
  if (v == Py_None) {
    f.feed("null", 4);
  } else if (PyBool_Check(v)) {  // before PyLong_Check: bool is an int
    if (v == Py_True)
      f.feed("true", 4);
    else
      f.feed("false", 5);
  } else if (PyLong_Check(v)) {
    int overflow = 0;
    long long x = PyLong_AsLongLongAndOverflow(v, &overflow);
    if (x == -1 && PyErr_Occurred()) return false;
    if (!overflow) {
      char buf[32];
      int n = snprintf(buf, sizeof(buf), "%lld", x);
      f.feed(buf, (size_t)n);
    } else {
      // arbitrary-precision tail: render via str() like json.dumps does
      PyObject* s = PyObject_Str(v);
      if (!s) return false;
      Py_ssize_t n;
      const char* u = PyUnicode_AsUTF8AndSize(s, &n);
      if (!u) {
        Py_DECREF(s);
        return false;
      }
      f.feed(u, (size_t)n);
      Py_DECREF(s);
    }
  } else if (PyFloat_Check(v)) {
    double d = PyFloat_AS_DOUBLE(v);
    if (std::isnan(d)) {
      f.feed("NaN", 3);
    } else if (std::isinf(d)) {
      if (d > 0)
        f.feed("Infinity", 8);
      else
        f.feed("-Infinity", 9);
    } else {
      // float.__repr__'s shortest-repr rendering — the exact bytes
      // json.dumps emits for a finite float
      char* buf = PyOS_double_to_string(d, 'r', 0, Py_DTSF_ADD_DOT_0, nullptr);
      if (!buf) return false;
      f.feed(buf, strlen(buf));
      PyMem_Free(buf);
    }
  } else if (PyUnicode_Check(v)) {
    Py_ssize_t n;
    const char* u = PyUnicode_AsUTF8AndSize(v, &n);
    if (!u) return false;
    feed_escaped(&f, u, n);
  } else {
    return false;  // tuple / custom type: not JSON-shaped, fall back
  }
  *out = f.h ? f.h : 1;
  return true;
}

struct KeyRef {
  const char* bytes;
  Py_ssize_t len;
  PyObject* value;  // borrowed
};

// UTF-8 byte order == code-point order == Python's sorted() on str.
inline bool key_less(const KeyRef& a, const KeyRef& b) {
  int c = memcmp(a.bytes, b.bytes, (size_t)std::min(a.len, b.len));
  if (c != 0) return c < 0;
  return a.len < b.len;
}

// Exact twin of the Python walk (truncation check at entry only).
// Returns false on unsupported type / Python error. Depth-bounded well
// under the C stack limit; the Python fallback covers deeper nests (it
// is itself bounded by the interpreter recursion limit).
bool walk(PyObject* v, uint32_t max_tokens, int depth, std::vector<uint32_t>* toks) {
  if (depth > 512) return false;
  if (toks->size() >= max_tokens) return true;
  if (PyDict_Check(v)) {
    toks->push_back(TOK_OPEN);
    std::vector<KeyRef> keys;
    keys.reserve((size_t)PyDict_Size(v));
    PyObject *key, *val;
    Py_ssize_t pos = 0;
    while (PyDict_Next(v, &pos, &key, &val)) {
      if (!PyUnicode_Check(key)) return false;  // non-str key: not JSON
      Py_ssize_t kn;
      const char* ku = PyUnicode_AsUTF8AndSize(key, &kn);
      if (!ku) return false;
      keys.push_back({ku, kn, val});
    }
    std::sort(keys.begin(), keys.end(), key_less);
    for (const KeyRef& k : keys) {
      toks->push_back(kcpnative::fnv1a((const uint8_t*)k.bytes, (size_t)k.len));
      if (!walk(k.value, max_tokens, depth + 1, toks)) return false;
    }
    toks->push_back(TOK_CLOSE);
    return true;
  }
  if (PyList_Check(v)) {
    toks->push_back(TOK_LIST_OPEN);
    for (Py_ssize_t i = 0; i < PyList_GET_SIZE(v); i++) {
      if (!walk(PyList_GET_ITEM(v, i), max_tokens, depth + 1, toks)) return false;
    }
    toks->push_back(TOK_LIST_CLOSE);
    return true;
  }
  uint32_t h;
  if (!scalar_hash(v, &h)) return false;
  toks->push_back(h);
  return true;
}

// tokenize(schemas: list, max_tokens: int, out: writable buffer) -> int
//   0  on success (out filled with len(schemas) rows of max_tokens u32)
//  -(i+1) if schema i is not JSON-shaped (caller falls back; no Python
//         error is left set). Raises only on misuse (wrong arg types /
//         undersized buffer).
PyObject* tokenize(PyObject* /*self*/, PyObject* args) {
  PyObject* seq;
  unsigned int max_tokens;
  Py_buffer buf;
  if (!PyArg_ParseTuple(args, "O!Iw*", &PyList_Type, &seq, &max_tokens, &buf)) return nullptr;
  Py_ssize_t n = PyList_GET_SIZE(seq);
  if (!PyBuffer_IsContiguous(&buf, 'C') ||
      buf.len < (Py_ssize_t)((size_t)n * max_tokens * sizeof(uint32_t))) {
    PyBuffer_Release(&buf);
    PyErr_SetString(PyExc_ValueError, "output buffer too small or not contiguous");
    return nullptr;
  }
  auto* out = (uint32_t*)buf.buf;
  std::vector<uint32_t> toks;
  long rc = 0;
  for (Py_ssize_t i = 0; i < n; i++) {
    toks.clear();
    if (!walk(PyList_GET_ITEM(seq, i), max_tokens, 0, &toks)) {
      if (PyErr_Occurred()) {
        PyBuffer_Release(&buf);
        return nullptr;
      }
      rc = -(long)(i + 1);
      break;
    }
    toks.push_back((uint32_t)toks.size());  // length token
    uint32_t* row = out + (size_t)i * max_tokens;
    uint32_t m = toks.size() < max_tokens ? (uint32_t)toks.size() : max_tokens;
    memcpy(row, toks.data(), (size_t)m * sizeof(uint32_t));
    memset(row + m, 0, (size_t)(max_tokens - m) * sizeof(uint32_t));
  }
  PyBuffer_Release(&buf);
  return PyLong_FromLong(rc);
}

PyMethodDef methods[] = {
    {"tokenize", tokenize, METH_VARARGS,
     "tokenize(schemas, max_tokens, out_buffer) -> 0 | -(i+1)"},
    {nullptr, nullptr, 0, nullptr},
};

PyModuleDef moduledef = {
    PyModuleDef_HEAD_INIT, "kcptok",
    "Direct-walk schema tokenizer (twin of kcp_tpu.ops.schemahash).",
    -1, methods, nullptr, nullptr, nullptr, nullptr,
};

}  // namespace

PyMODINIT_FUNC PyInit_kcptok(void) { return PyModule_Create(&moduledef); }
