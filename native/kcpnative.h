// C ABI of the kcp-tpu native runtime library (loaded via ctypes from
// kcp_tpu/native/__init__.py).
//
// Two subsystems:
//   ws_*  — durable WAL storage engine (the embedded-etcd analog;
//           reference: pkg/etcd/etcd.go runs a real etcd, our store
//           journals through this engine instead)
//   enc_* — native object encoder (JSON -> canonical flatten -> FNV
//           slot hashes; the host hot loop feeding the device diff
//           kernels, twin of kcp_tpu/ops/encode.py BucketEncoder)
//
// All functions are thread-compatible (callers serialize access per
// handle); no global state beyond lazily-initialized lookup tables.
#pragma once

#include <stddef.h>
#include <stdint.h>

#ifdef __cplusplus
extern "C" {
#endif

// ---------------------------------------------------------------- WAL store

// Open (creating if absent) a WAL store. Replays <path>.snap then
// <path>; torn trailing records are truncated away. sync_every batches
// fsync: 1 = fsync every record (etcd-like durability), N = every N
// records (group commit), 0 = never (tests). Returns NULL on error.
void* ws_open(const char* path, int sync_every);
void ws_close(void* h);
const char* ws_last_error(void* h);  // valid until next call on h

// Upsert / delete. rv is the store's resourceVersion for the mutation;
// the engine tracks max(rv). Returns 0 on success, -1 on I/O error.
int ws_put(void* h, const uint8_t* key, uint32_t klen, const uint8_t* val, uint32_t vlen,
           uint64_t rv);
int ws_del(void* h, const uint8_t* key, uint32_t klen, uint64_t rv);

// Point lookup. Returns 1 if found (ptrs valid until next mutation),
// 0 if absent.
int ws_get(void* h, const uint8_t* key, uint32_t klen, const uint8_t** val, uint32_t* vlen);

uint64_t ws_rv(void* h);
uint64_t ws_count(void* h);
int ws_flush(void* h);     // fsync now

// Multi-record group-commit append: between begin and commit, ws_put /
// ws_del frame into an in-memory batch instead of the fd; commit writes
// the whole batch as ONE write() and applies the sync policy once
// (do_fsync != 0 forces fsync; otherwise sync_every batching applies).
// Abort drops the buffered records (a failed window commits none).
int ws_batch_begin(void* h);
int ws_batch_commit(void* h, int do_fsync);
int ws_batch_abort(void* h);

// Replication epoch: persisted as an OP_EPOCH WAL record (and re-stamped
// into every snapshot) so a fence/promotion survives restart. ws_set_rv
// advances the RV watermark without a mutation record (snapshot resync).
uint64_t ws_epoch(void* h);
int ws_set_epoch(void* h, uint64_t epoch);
void ws_set_rv(void* h, uint64_t rv);
int ws_snapshot(void* h);  // write snapshot from the engine index, truncate WAL

// Streaming snapshot: the caller supplies the live objects (so the
// engine need not keep its own copy of values — see ws_index_release).
// begin -> add per object -> commit (atomic rename + WAL truncate).
// A failed add/commit aborts and removes the tmp file.
int ws_snapshot_begin(void* h);
int ws_snapshot_add(void* h, const uint8_t* key, uint32_t klen, const uint8_t* val,
                    uint32_t vlen);
int ws_snapshot_commit(void* h);

// Journal-only mode: drop the in-memory index (the host keeps the
// authoritative object map). ws_get/ws_scan return nothing and
// ws_snapshot fails after this; use the streaming snapshot API.
void ws_index_release(void* h);

// Ordered prefix scan (etcd range-scan analog over the
// /<resource>/<cluster>/<ns>/<name> keyspace). Cursor is invalidated
// by mutations; scan fully before mutating.
void* ws_scan(void* h, const uint8_t* prefix, uint32_t plen);
int ws_scan_next(void* cur, const uint8_t** key, uint32_t* klen, const uint8_t** val,
                 uint32_t* vlen);  // 1 = yielded, 0 = done
void ws_scan_free(void* cur);

// ------------------------------------------------------------ object encoder

// A schema-bucket encoder: path -> slot vocabulary plus the flatten +
// hash pipeline. enc_bucket_encode parses a JSON object (as produced by
// Python's json.dumps) and fills out[0..capacity) with value hashes by
// slot (0 = absent).
void* enc_bucket_new(uint32_t capacity);
void enc_bucket_free(void* b);
// Returns 0 ok; -1 slot overflow (re-bucket larger); -2 parse error;
// -3 not a JSON object.
int enc_bucket_encode(void* b, const char* json, size_t len, uint32_t* out);
uint32_t enc_bucket_nslots(void* b);
// Slot path readback (for vocab mirroring into Python). Returns 1 if
// slot exists.
int enc_bucket_path(void* b, uint32_t slot, const char** path, uint32_t* plen);
// Seed the vocabulary (e.g. restoring a bucket). Returns slot or -1.
int enc_bucket_add_path(void* b, const char* path, uint32_t plen);

// ---------------------------------------------------------- fair workqueue

// Round-robin-fair, rate-limited work queue (workqueue.cc). Items are
// opaque uint64 ids grouped by uint32 tenant; time is caller-supplied
// monotonic seconds. Contract mirrors kcp_tpu/reconciler/queue.py
// (client-go semantics) plus per-tenant fairness on drain.
void* wq_new(void);
void wq_free(void* q);
void wq_add(void* q, uint64_t id, uint32_t tenant);
void wq_add_after(void* q, uint64_t id, uint32_t tenant, double now, double delay);
// Returns the new retry count.
uint32_t wq_add_rate_limited(void* q, uint64_t id, uint32_t tenant, double now);
uint32_t wq_num_requeues(void* q, uint64_t id);
void wq_forget(void* q, uint64_t id);
// Promote due delayed items; returns seconds to next due item (-1 none).
double wq_promote(void* q, double now);
// Fill out[0..max) with ready ids, one per tenant per round-robin pass;
// returns the count. Items must be wq_done()d.
uint32_t wq_drain(void* q, double now, uint64_t* out, uint32_t max_items);
void wq_done(void* q, uint64_t id);
uint64_t wq_len(void* q);

// Hash one JSON value canonically (twin of hashing.hash_value).
// Returns 0 only on parse error (real hashes are never 0).
uint32_t enc_hash_value(const char* json, size_t len);
// FNV-1a (twin of hashing.fnv1a).
uint32_t enc_fnv1a(const uint8_t* data, size_t len, uint32_t seed);
// Label pair hash: fnv1a(key + "\0" + value), 0 mapped to 1.
uint32_t enc_hash_pair(const uint8_t* key, size_t klen, const uint8_t* value, size_t vlen);

// Batch schema tokenizer (twin of kcp_tpu/ops/schemahash.tokenize_schema).
// data holds n concatenated canonical-JSON schemas; schema i spans
// [offsets[i], offsets[i+1]). Writes n rows of max_tokens uint32 tokens
// (zero-padded) into out. Returns 0 on success, -(i+1) if schema i
// failed to parse (out rows before i are valid).
int enc_tokenize_schemas(const char* data, const uint64_t* offsets, uint32_t n,
                         uint32_t max_tokens, uint32_t* out);

#ifdef __cplusplus
}
#endif
