// Fair work queue — the native scheduler for cross-tenant controllers.
//
// The reference's workqueue (client-go) gives dedup-while-pending,
// per-item exponential backoff, and FIFO order, but nothing stops one
// noisy tenant from monopolizing a controller shared by thousands of
// logical clusters (SURVEY.md §2.4 names "batched priority queue with
// per-tenant fairness" as the native equivalent to build). This queue
// keeps the client-go contract and adds round-robin fairness across
// tenants: drains take one item per tenant per pass, so a tenant
// flooding events gets at most 1/T of each batch while quiet tenants
// keep their latency.
//
// Time is supplied by the caller (monotonic seconds) — the queue does no
// clock reads, which keeps it deterministic under test and trivially
// embeddable in the asyncio wrapper (kcp_tpu/reconciler/fairqueue.py).
// Items are opaque uint64 ids; the Python side interns objects to ids.
#include "kcpnative.h"

#include <cstdint>
#include <deque>
#include <queue>
#include <unordered_map>
#include <unordered_set>
#include <vector>

namespace {

constexpr double BASE_DELAY = 0.005;  // client-go default: 5ms * 2^n
constexpr double MAX_DELAY = 1000.0;

struct Delayed {
  double due;
  uint64_t seq;
  uint64_t id;
  uint32_t tenant;
  bool operator>(const Delayed& o) const {
    return due != o.due ? due > o.due : seq > o.seq;
  }
};

struct FairQueue {
  std::unordered_map<uint32_t, std::deque<uint64_t>> ready;  // per-tenant FIFO
  std::deque<uint32_t> rr;  // round-robin ring of tenants with ready items
  std::unordered_set<uint32_t> in_rr;
  std::unordered_set<uint64_t> pending;     // in a ready ring (dedup)
  std::unordered_set<uint64_t> delayed_ids; // scheduled in the delay heap (dedup)
  std::unordered_set<uint64_t> processing;  // handed out, not yet done
  std::unordered_set<uint64_t> redo;        // re-added while processing
  std::unordered_map<uint64_t, uint32_t> redo_tenant;
  std::priority_queue<Delayed, std::vector<Delayed>, std::greater<Delayed>> delayed;
  std::unordered_map<uint64_t, uint32_t> retries;
  uint64_t seq = 0;
  size_t ready_count = 0;

  void push_ready(uint64_t id, uint32_t tenant) {
    auto& dq = ready[tenant];
    if (dq.empty() && !in_rr.count(tenant)) {
      rr.push_back(tenant);
      in_rr.insert(tenant);
    }
    dq.push_back(id);
    ready_count++;
  }

  void add(uint64_t id, uint32_t tenant) {
    if (processing.count(id)) {
      redo.insert(id);
      redo_tenant[id] = tenant;
      return;
    }
    if (pending.count(id)) return;
    pending.insert(id);
    push_ready(id, tenant);
  }

  void add_after(uint64_t id, uint32_t tenant, double now, double delay) {
    if (delay <= 0) {
      add(id, tenant);
      return;
    }
    if (delayed_ids.count(id)) return;  // earliest schedule wins
    if (pending.count(id)) return;      // already in a ready ring
    delayed_ids.insert(id);
    delayed.push(Delayed{now + delay, ++seq, id, tenant});
  }

  // Move due delayed items to ready; returns seconds until the next due
  // item, or -1 when none are scheduled.
  double promote(double now) {
    while (!delayed.empty() && delayed.top().due <= now) {
      Delayed d = delayed.top();
      delayed.pop();
      delayed_ids.erase(d.id);
      if (processing.count(d.id)) {
        redo.insert(d.id);
        redo_tenant[d.id] = d.tenant;
      } else if (!pending.count(d.id)) {
        pending.insert(d.id);
        push_ready(d.id, d.tenant);
      }
    }
    if (delayed.empty()) return -1.0;
    double dt = delayed.top().due - now;
    return dt > 0 ? dt : 0.0;
  }

  bool live(uint64_t id) const {
    return pending.count(id) || delayed_ids.count(id) ||
           processing.count(id) || redo.count(id);
  }

  // Fair drain: one item per tenant per round-robin pass.
  uint32_t drain(double now, uint64_t* out, uint32_t max_items) {
    promote(now);
    uint32_t n = 0;
    while (n < max_items && !rr.empty()) {
      uint32_t tenant = rr.front();
      rr.pop_front();
      auto it = ready.find(tenant);
      if (it == ready.end() || it->second.empty()) {
        in_rr.erase(tenant);
        continue;
      }
      uint64_t id = it->second.front();
      it->second.pop_front();
      ready_count--;
      pending.erase(id);
      processing.insert(id);
      out[n++] = id;
      if (it->second.empty()) {
        in_rr.erase(tenant);
        ready.erase(it);
      } else {
        rr.push_back(tenant);  // rotate: next pass takes its next item
      }
    }
    return n;
  }

  void done(uint64_t id) {
    processing.erase(id);
    auto it = redo.find(id);
    if (it != redo.end()) {
      redo.erase(it);
      uint32_t tenant = redo_tenant[id];
      redo_tenant.erase(id);
      add(id, tenant);
    }
  }
};

}  // namespace

extern "C" {

void* wq_new() { return new FairQueue(); }
void wq_free(void* q) { delete static_cast<FairQueue*>(q); }

void wq_add(void* q, uint64_t id, uint32_t tenant) {
  static_cast<FairQueue*>(q)->add(id, tenant);
}

void wq_add_after(void* q, uint64_t id, uint32_t tenant, double now, double delay) {
  static_cast<FairQueue*>(q)->add_after(id, tenant, now, delay);
}

uint32_t wq_add_rate_limited(void* q, uint64_t id, uint32_t tenant, double now) {
  auto* fq = static_cast<FairQueue*>(q);
  uint32_t n = fq->retries[id]++;
  double delay = BASE_DELAY * double(1ull << (n < 60 ? n : 60));
  fq->add_after(id, tenant, now, delay < MAX_DELAY ? delay : MAX_DELAY);
  return n + 1;
}

uint32_t wq_num_requeues(void* q, uint64_t id) {
  auto* fq = static_cast<FairQueue*>(q);
  auto it = fq->retries.find(id);
  return it == fq->retries.end() ? 0 : it->second;
}

void wq_forget(void* q, uint64_t id) { static_cast<FairQueue*>(q)->retries.erase(id); }

double wq_promote(void* q, double now) { return static_cast<FairQueue*>(q)->promote(now); }

uint32_t wq_drain(void* q, double now, uint64_t* out, uint32_t max_items) {
  return static_cast<FairQueue*>(q)->drain(now, out, max_items);
}

void wq_done(void* q, uint64_t id) { static_cast<FairQueue*>(q)->done(id); }

// Batch enqueue: one ctypes crossing for a whole churn/feedback batch.
// Profiling (round 4) showed per-item add() crossings costing ~15% of
// the serving loop's wall time at 1.5k events/tick.
void wq_add_many(void* q, const uint64_t* ids, const uint32_t* tenants,
                 uint32_t n) {
  auto* fq = static_cast<FairQueue*>(q);
  for (uint32_t i = 0; i < n; ++i) fq->add(ids[i], tenants[i]);
}

// Batch forget+done for a processed tick batch (~30% of loop wall time
// as per-item crossings). forget[i]=1 clears the retry counter (the
// success path). out_released[i]=1 when the id left the queue entirely —
// the caller then drops its interning entry.
void wq_complete_many(void* q, const uint64_t* ids, const uint8_t* forget,
                      uint32_t n, uint8_t* out_released) {
  auto* fq = static_cast<FairQueue*>(q);
  for (uint32_t i = 0; i < n; ++i) {
    const uint64_t id = ids[i];
    if (forget[i]) fq->retries.erase(id);
    fq->done(id);
    if (fq->live(id)) {
      out_released[i] = 0;
    } else {
      fq->retries.erase(id);
      out_released[i] = 1;
    }
  }
}

uint64_t wq_len(void* q) {
  auto* fq = static_cast<FairQueue*>(q);
  return fq->ready_count + fq->delayed_ids.size();
}

int wq_live(void* q, uint64_t id) {
  return static_cast<FairQueue*>(q)->live(id) ? 1 : 0;
}

// Release an id's bookkeeping if it is no longer anywhere in the queue;
// returns 1 when released (the caller may then drop its interning entry).
int wq_release(void* q, uint64_t id) {
  auto* fq = static_cast<FairQueue*>(q);
  if (fq->live(id)) return 0;
  fq->retries.erase(id);
  return 1;
}

}  // extern "C"
