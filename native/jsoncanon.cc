#include "jsoncanon.h"

#include <algorithm>
#include <cstdio>
#include <cstring>

namespace kcpnative {

namespace {

struct Parser {
  const char* p;
  const char* end;
  std::string* err;

  bool fail(const char* msg) {
    if (err) *err = msg;
    return false;
  }

  void skip_ws() {
    while (p < end && (*p == ' ' || *p == '\t' || *p == '\n' || *p == '\r')) p++;
  }

  bool literal(const char* lit) {
    size_t n = strlen(lit);
    if (size_t(end - p) < n || memcmp(p, lit, n) != 0) return false;
    p += n;
    return true;
  }

  // Decode a \uXXXX escape (possibly a surrogate pair) into UTF-8.
  bool unicode_escape(std::string* out) {
    auto hex4 = [&](uint32_t* v) -> bool {
      if (end - p < 4) return false;
      uint32_t r = 0;
      for (int i = 0; i < 4; i++) {
        char c = p[i];
        r <<= 4;
        if (c >= '0' && c <= '9') r |= uint32_t(c - '0');
        else if (c >= 'a' && c <= 'f') r |= uint32_t(c - 'a' + 10);
        else if (c >= 'A' && c <= 'F') r |= uint32_t(c - 'A' + 10);
        else return false;
      }
      p += 4;
      *v = r;
      return true;
    };
    uint32_t cp;
    if (!hex4(&cp)) return fail("bad \\u escape");
    if (cp >= 0xD800 && cp <= 0xDBFF && end - p >= 6 && p[0] == '\\' && p[1] == 'u') {
      p += 2;
      uint32_t lo;
      if (!hex4(&lo)) return fail("bad surrogate pair");
      if (lo >= 0xDC00 && lo <= 0xDFFF) {
        cp = 0x10000 + ((cp - 0xD800) << 10) + (lo - 0xDC00);
      } else {
        // unpaired high surrogate followed by a non-low \u escape:
        // emit replacement-style passthrough of both (Python would have
        // errored producing this; keep it lossy but total)
        out->append("\xEF\xBF\xBD");
        cp = lo;
      }
    }
    if (cp < 0x80) {
      out->push_back(char(cp));
    } else if (cp < 0x800) {
      out->push_back(char(0xC0 | (cp >> 6)));
      out->push_back(char(0x80 | (cp & 0x3F)));
    } else if (cp < 0x10000) {
      out->push_back(char(0xE0 | (cp >> 12)));
      out->push_back(char(0x80 | ((cp >> 6) & 0x3F)));
      out->push_back(char(0x80 | (cp & 0x3F)));
    } else {
      out->push_back(char(0xF0 | (cp >> 18)));
      out->push_back(char(0x80 | ((cp >> 12) & 0x3F)));
      out->push_back(char(0x80 | ((cp >> 6) & 0x3F)));
      out->push_back(char(0x80 | (cp & 0x3F)));
    }
    return true;
  }

  bool parse_string(std::string* out) {
    if (p >= end || *p != '"') return fail("expected string");
    p++;
    while (p < end) {
      char c = *p;
      if (c == '"') {
        p++;
        return true;
      }
      if (c == '\\') {
        p++;
        if (p >= end) return fail("truncated escape");
        char e = *p++;
        switch (e) {
          case '"': out->push_back('"'); break;
          case '\\': out->push_back('\\'); break;
          case '/': out->push_back('/'); break;
          case 'b': out->push_back('\b'); break;
          case 'f': out->push_back('\f'); break;
          case 'n': out->push_back('\n'); break;
          case 'r': out->push_back('\r'); break;
          case 't': out->push_back('\t'); break;
          case 'u':
            if (!unicode_escape(out)) return false;
            break;
          default: return fail("bad escape");
        }
      } else {
        out->push_back(c);
        p++;
      }
    }
    return fail("unterminated string");
  }

  bool parse_number(JValue* v) {
    const char* start = p;
    if (p < end && *p == '-') p++;
    while (p < end && ((*p >= '0' && *p <= '9') || *p == '.' || *p == 'e' || *p == 'E' ||
                       *p == '+' || *p == '-'))
      p++;
    if (p == start) return fail("bad number");
    v->type = JValue::Num;
    v->num.assign(start, size_t(p - start));
    return true;
  }

  bool parse_value(JValue* v, int depth) {
    if (depth > 128) return fail("nesting too deep");
    skip_ws();
    if (p >= end) return fail("unexpected end");
    char c = *p;
    if (c == '{') {
      p++;
      v->type = JValue::Obj;
      skip_ws();
      if (p < end && *p == '}') {
        p++;
        return true;
      }
      while (true) {
        skip_ws();
        std::string key;
        if (!parse_string(&key)) return false;
        skip_ws();
        if (p >= end || *p != ':') return fail("expected ':'");
        p++;
        JValue child;
        if (!parse_value(&child, depth + 1)) return false;
        v->obj.emplace_back(std::move(key), std::move(child));
        skip_ws();
        if (p < end && *p == ',') {
          p++;
          continue;
        }
        if (p < end && *p == '}') {
          p++;
          return true;
        }
        return fail("expected ',' or '}'");
      }
    }
    if (c == '[') {
      p++;
      v->type = JValue::Arr;
      skip_ws();
      if (p < end && *p == ']') {
        p++;
        return true;
      }
      while (true) {
        JValue child;
        if (!parse_value(&child, depth + 1)) return false;
        v->arr.push_back(std::move(child));
        skip_ws();
        if (p < end && *p == ',') {
          p++;
          continue;
        }
        if (p < end && *p == ']') {
          p++;
          return true;
        }
        return fail("expected ',' or ']'");
      }
    }
    if (c == '"') {
      v->type = JValue::Str;
      return parse_string(&v->str);
    }
    if (literal("true")) {
      v->type = JValue::Bool;
      v->b = true;
      return true;
    }
    if (literal("false")) {
      v->type = JValue::Bool;
      v->b = false;
      return true;
    }
    if (literal("null")) {
      v->type = JValue::Null;
      return true;
    }
    // Python's json emits these non-standard tokens for float
    // nan/inf — keep them as verbatim number tokens.
    if (literal("NaN")) {
      v->type = JValue::Num;
      v->num = "NaN";
      return true;
    }
    if (literal("Infinity")) {
      v->type = JValue::Num;
      v->num = "Infinity";
      return true;
    }
    if (c == '-' && size_t(end - p) >= 9 && memcmp(p, "-Infinity", 9) == 0) {
      p += 9;
      v->type = JValue::Num;
      v->num = "-Infinity";
      return true;
    }
    return parse_number(v);
  }
};

// Python json.dumps(ensure_ascii=False) escaping: ", \, short escapes
// for \b \t \n \f \r, \u00xx for remaining control chars, everything
// else raw.
void write_escaped(const std::string& s, std::string* out) {
  out->push_back('"');
  for (unsigned char c : s) {
    switch (c) {
      case '"': out->append("\\\""); break;
      case '\\': out->append("\\\\"); break;
      case '\b': out->append("\\b"); break;
      case '\f': out->append("\\f"); break;
      case '\n': out->append("\\n"); break;
      case '\r': out->append("\\r"); break;
      case '\t': out->append("\\t"); break;
      default:
        if (c < 0x20) {
          char buf[8];
          snprintf(buf, sizeof(buf), "\\u%04x", c);
          out->append(buf);
        } else {
          out->push_back(char(c));
        }
    }
  }
  out->push_back('"');
}

}  // namespace

std::vector<const std::pair<std::string, JValue>*> sorted_entries(const JValue& v) {
  // sort by key bytes (== Python's code-point sort for UTF-8);
  // duplicate keys keep the last occurrence, like json.loads
  std::vector<const std::pair<std::string, JValue>*> entries;
  entries.reserve(v.obj.size());
  for (const auto& e : v.obj) entries.push_back(&e);
  std::stable_sort(entries.begin(), entries.end(),
                   [](const auto* a, const auto* b) { return a->first < b->first; });
  std::vector<const std::pair<std::string, JValue>*> out;
  out.reserve(entries.size());
  for (size_t i = 0; i < entries.size(); i++) {
    if (i + 1 < entries.size() && entries[i]->first == entries[i + 1]->first) continue;
    out.push_back(entries[i]);
  }
  return out;
}

bool json_parse(const char* data, size_t len, JValue* out, std::string* err) {
  Parser parser{data, data + len, err};
  if (!parser.parse_value(out, 0)) return false;
  parser.skip_ws();
  if (parser.p != parser.end) {
    if (err) *err = "trailing data";
    return false;
  }
  return true;
}

void json_canon(const JValue& v, std::string* out) {
  switch (v.type) {
    case JValue::Null: out->append("null"); break;
    case JValue::Bool: out->append(v.b ? "true" : "false"); break;
    case JValue::Num: out->append(v.num); break;
    case JValue::Str: write_escaped(v.str, out); break;
    case JValue::Arr: {
      out->push_back('[');
      for (size_t i = 0; i < v.arr.size(); i++) {
        if (i) out->push_back(',');
        json_canon(v.arr[i], out);
      }
      out->push_back(']');
      break;
    }
    case JValue::Obj: {
      out->push_back('{');
      bool first = true;
      for (const auto* e : sorted_entries(v)) {
        if (!first) out->push_back(',');
        first = false;
        write_escaped(e->first, out);
        out->push_back(':');
        json_canon(e->second, out);
      }
      out->push_back('}');
      break;
    }
  }
}

}  // namespace kcpnative
