// Shared primitives for the kcp-tpu native runtime library.
//
// The hash functions here are byte-for-byte twins of
// kcp_tpu/ops/hashing.py (FNV-1a over canonical JSON); the CRC32 guards
// WAL records against torn writes. Host Python, device kernels and this
// library must agree on every hash, so change nothing here without
// changing the Python side in lockstep.
#pragma once

#include <cstddef>
#include <cstdint>

namespace kcpnative {

constexpr uint32_t FNV_OFFSET = 0x811C9DC5u;
constexpr uint32_t FNV_PRIME = 0x01000193u;

inline uint32_t fnv1a(const uint8_t* data, size_t len, uint32_t seed = FNV_OFFSET) {
  uint32_t h = seed;
  for (size_t i = 0; i < len; i++) {
    h ^= data[i];
    h *= FNV_PRIME;
  }
  return h;
}

// CRC-32 (IEEE 802.3, reflected), table generated on first use.
inline uint32_t crc32(const uint8_t* data, size_t len) {
  static uint32_t table[256];
  static bool init = false;
  if (!init) {
    for (uint32_t i = 0; i < 256; i++) {
      uint32_t c = i;
      for (int k = 0; k < 8; k++) c = (c & 1) ? 0xEDB88320u ^ (c >> 1) : c >> 1;
      table[i] = c;
    }
    init = true;
  }
  uint32_t c = 0xFFFFFFFFu;
  for (size_t i = 0; i < len; i++) c = table[(c ^ data[i]) & 0xFF] ^ (c >> 8);
  return c ^ 0xFFFFFFFFu;
}

}  // namespace kcpnative
