// Minimal JSON DOM + canonical serializer, matching Python's
// json.dumps(value, sort_keys=True, separators=(",", ":"),
// ensure_ascii=False) byte for byte for any document Python's json
// module itself produced (number tokens pass through verbatim, which is
// what makes the parity exact — see kcp_tpu/ops/hashing.py
// canonical_json()).
#pragma once

#include <cstdint>
#include <string>
#include <utility>
#include <vector>

namespace kcpnative {

struct JValue {
  enum Type : uint8_t { Null, Bool, Num, Str, Arr, Obj } type = Null;
  bool b = false;
  std::string num;  // original token text, passed through verbatim
  std::string str;  // decoded UTF-8
  std::vector<JValue> arr;
  std::vector<std::pair<std::string, JValue>> obj;  // decoded keys, source order
};

// Parse one JSON document. Returns false (and sets *err) on malformed
// input. Accepts Python's non-standard NaN/Infinity/-Infinity tokens.
bool json_parse(const char* data, size_t len, JValue* out, std::string* err);

// Append the canonical serialization (sorted keys, compact separators,
// ensure_ascii=False escaping) to *out.
void json_canon(const JValue& v, std::string* out);

// An object's entries sorted by key bytes with duplicate keys keeping
// the last occurrence (json.loads semantics). The single source of key
// ordering for both canonicalization and path enumeration — the
// hash-parity invariant requires those to agree exactly.
std::vector<const std::pair<std::string, JValue>*> sorted_entries(const JValue& obj);

}  // namespace kcpnative
