// Native object encoder — the host hot loop feeding the device diff
// kernels. Byte-for-byte twin of kcp_tpu/ops/encode.py (flatten_object
// + BucketEncoder + hash_value): parses an object's JSON (as produced
// by Python's json.dumps), flattens it to dotted-path leaves (sorted
// keys, volatile metadata dropped, subtrees deeper than max_depth=8
// hashed whole), assigns slots first-seen, and writes FNV-1a hashes of
// each leaf's canonical JSON into the output vector.
//
// Reference behavior being vectorized: pkg/syncer/specsyncer.go:17-41
// deepEqualApartFromStatus runs a full deep-equal per informer event;
// here the equal collapses to uint32 lane compares on device, and this
// encoder is what gets objects into lane form.
#include "kcpnative.h"

#include <algorithm>
#include <string>
#include <unordered_map>
#include <vector>

#include "common.h"
#include "jsoncanon.h"

namespace {

using kcpnative::fnv1a;
using kcpnative::JValue;

constexpr int MAX_DEPTH = 8;

const char* const VOLATILE_META[] = {"resourceVersion", "generation", "uid",
                                     "creationTimestamp", "managedFields"};

bool is_volatile_meta(const std::string& k) {
  for (const char* m : VOLATILE_META)
    if (k == m) return true;
  return false;
}

struct Bucket {
  uint32_t capacity;
  std::unordered_map<std::string, uint32_t> slots;
  std::vector<std::string> paths;

  int slot_for(const std::string& path) {
    auto it = slots.find(path);
    if (it != slots.end()) return int(it->second);
    if (paths.size() >= capacity) return -1;
    uint32_t slot = uint32_t(paths.size());
    slots.emplace(path, slot);
    paths.push_back(path);
    return int(slot);
  }
};

using kcpnative::sorted_entries;  // shared with json_canon: one key order

uint32_t hash_jvalue(const JValue& v) {
  std::string canon;
  kcpnative::json_canon(v, &canon);
  uint32_t h = fnv1a(reinterpret_cast<const uint8_t*>(canon.data()), canon.size());
  return h ? h : 1;  // 0 is the "absent" sentinel in encoded tensors
}

// Returns false on slot overflow.
bool walk(Bucket* b, const std::string& prefix, const JValue& v, int depth, uint32_t* out) {
  if (v.type == JValue::Obj && depth < MAX_DEPTH) {
    if (v.obj.empty()) {
      int slot = b->slot_for(prefix);
      if (slot < 0) return false;
      out[slot] = hash_jvalue(v);  // hash of "{}"
      return true;
    }
    for (const auto* e : sorted_entries(v)) {
      if (depth == 1 && prefix == "metadata" && is_volatile_meta(e->first)) continue;
      if (!walk(b, prefix + "." + e->first, e->second, depth + 1, out)) return false;
    }
    return true;
  }
  int slot = b->slot_for(prefix);
  if (slot < 0) return false;
  out[slot] = hash_jvalue(v);
  return true;
}

}  // namespace

extern "C" {

void* enc_bucket_new(uint32_t capacity) {
  auto* b = new Bucket();
  b->capacity = capacity;
  return b;
}

void enc_bucket_free(void* b) { delete static_cast<Bucket*>(b); }

int enc_bucket_encode(void* bp, const char* json, size_t len, uint32_t* out) {
  auto* b = static_cast<Bucket*>(bp);
  JValue root;
  std::string err;
  if (!kcpnative::json_parse(json, len, &root, &err)) return -2;
  if (root.type != JValue::Obj) return -3;
  for (uint32_t i = 0; i < b->capacity; i++) out[i] = 0;
  for (const auto* e : sorted_entries(root)) {
    if (e->first == "apiVersion" || e->first == "kind") {
      int slot = b->slot_for(e->first);
      if (slot < 0) return -1;
      out[slot] = hash_jvalue(e->second);
      continue;
    }
    if (!walk(b, e->first, e->second, 1, out)) return -1;
  }
  return 0;
}

uint32_t enc_bucket_nslots(void* b) { return uint32_t(static_cast<Bucket*>(b)->paths.size()); }

int enc_bucket_path(void* bp, uint32_t slot, const char** path, uint32_t* plen) {
  auto* b = static_cast<Bucket*>(bp);
  if (slot >= b->paths.size()) return 0;
  *path = b->paths[slot].c_str();
  *plen = uint32_t(b->paths[slot].size());
  return 1;
}

int enc_bucket_add_path(void* bp, const char* path, uint32_t plen) {
  return static_cast<Bucket*>(bp)->slot_for(std::string(path, plen));
}

uint32_t enc_hash_value(const char* json, size_t len) {
  JValue v;
  std::string err;
  if (!kcpnative::json_parse(json, len, &v, &err)) return 0;
  return hash_jvalue(v);
}

uint32_t enc_fnv1a(const uint8_t* data, size_t len, uint32_t seed) {
  return fnv1a(data, len, seed);
}

uint32_t enc_hash_pair(const uint8_t* key, size_t klen, const uint8_t* value, size_t vlen) {
  std::string buf;
  buf.reserve(klen + 1 + vlen);
  buf.append(reinterpret_cast<const char*>(key), klen);
  buf.push_back('\0');
  buf.append(reinterpret_cast<const char*>(value), vlen);
  uint32_t h = fnv1a(reinterpret_cast<const uint8_t*>(buf.data()), buf.size());
  return h ? h : 1;
}

}  // extern "C"

// ----------------------------------------------------------- schema tokenizer

namespace {

// Structural markers — must equal kcp_tpu/ops/schemahash.tokenize_schema's
// OPEN/CLOSE/LIST_OPEN/LIST_CLOSE so native and Python token streams are
// byte-for-byte interchangeable (the differential test feeds both the
// same corpus).
constexpr uint32_t TOK_OPEN = 0xA11CE;
constexpr uint32_t TOK_CLOSE = 0xB0B;
constexpr uint32_t TOK_LIST_OPEN = 0xC0DE;
constexpr uint32_t TOK_LIST_CLOSE = 0xD00D;

// Exact twin of the Python walk, including its truncation semantics:
// the size check happens only at walk entry, so a wide dict still
// appends every key hash and the trailing CLOSE past max_tokens — the
// final copy truncates, and the appended length token disambiguates.
void tok_walk(const JValue& v, uint32_t max_tokens, std::vector<uint32_t>* toks) {
  if (toks->size() >= max_tokens) return;
  switch (v.type) {
    case JValue::Obj: {
      toks->push_back(TOK_OPEN);
      for (const auto* e : sorted_entries(v)) {
        toks->push_back(
            fnv1a(reinterpret_cast<const uint8_t*>(e->first.data()), e->first.size()));
        tok_walk(e->second, max_tokens, toks);
      }
      toks->push_back(TOK_CLOSE);
      break;
    }
    case JValue::Arr: {
      toks->push_back(TOK_LIST_OPEN);
      for (const auto& item : v.arr) tok_walk(item, max_tokens, toks);
      toks->push_back(TOK_LIST_CLOSE);
      break;
    }
    default:
      toks->push_back(hash_jvalue(v));
  }
}

}  // namespace

extern "C" {

int enc_tokenize_schemas(const char* data, const uint64_t* offsets, uint32_t n,
                         uint32_t max_tokens, uint32_t* out) {
  std::vector<uint32_t> toks;
  for (uint32_t i = 0; i < n; i++) {
    const char* s = data + offsets[i];
    size_t len = size_t(offsets[i + 1] - offsets[i]);
    JValue root;
    std::string err;
    if (!kcpnative::json_parse(s, len, &root, &err)) return -int(i) - 1;
    toks.clear();
    tok_walk(root, max_tokens, &toks);
    toks.push_back(uint32_t(toks.size()));  // length token guards truncation collisions
    uint32_t* row = out + size_t(i) * max_tokens;
    uint32_t m = uint32_t(toks.size()) < max_tokens ? uint32_t(toks.size()) : max_tokens;
    for (uint32_t j = 0; j < m; j++) row[j] = toks[j];
    for (uint32_t j = m; j < max_tokens; j++) row[j] = 0;
  }
  return 0;
}

}  // extern "C"
