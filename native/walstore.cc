// Durable WAL storage engine — the embedded-etcd analog of this
// framework (reference: pkg/etcd/etcd.go embeds a real etcd server;
// kcp_tpu.store.LogicalStore journals through this engine instead and
// keeps watch/event semantics host-side in Python).
//
// On-disk format (little-endian): an 8-byte magic header "KCPWAL1\n"
// (so format detection never depends on heuristics — a record length
// whose low byte happens to be 0x7B ('{') must not read as JSON), then
// one record per mutation:
//   [u32 payload_len][u32 crc32(payload)][payload]
//   payload = u8 op | u64 rv | u32 klen | u32 vlen | key | val
//   op: 1 = put, 2 = del, 3 = meta (rv watermark, empty key/val)
// Replay stops at the first short/corrupt record and truncates the file
// there (torn-write recovery). Snapshot compaction writes the full
// ordered map into <path>.snap (atomic rename) and truncates the WAL.
// The streaming snapshot API (ws_snapshot_begin/add/commit) lets the
// caller supply the live objects itself, which permits journal-only
// mode (ws_index_release) where the engine keeps no in-memory copy of
// values the host already holds.
#include "kcpnative.h"

#include <fcntl.h>
#include <sys/stat.h>
#include <unistd.h>

#include <cerrno>
#include <cstring>
#include <map>
#include <string>
#include <vector>

#include "common.h"

namespace {

using kcpnative::crc32;

constexpr uint8_t OP_PUT = 1;
constexpr uint8_t OP_DEL = 2;
constexpr uint8_t OP_META = 3;
// replication epoch stamp: the 8-byte little-endian epoch rides the val
// field; the rv field carries the engine's current rv at stamp time so
// pre-epoch readers (which treat unknown ops as rv-watermark-only
// no-ops, like OP_META) replay the record harmlessly.
constexpr uint8_t OP_EPOCH = 4;

constexpr char MAGIC[8] = {'K', 'C', 'P', 'W', 'A', 'L', '1', '\n'};

struct WalStore {
  std::string path;
  int fd = -1;
  int sync_every = 256;
  int unsynced = 0;
  uint64_t rv = 0;
  uint64_t epoch = 0;
  bool index_enabled = true;
  std::map<std::string, std::string> index;  // ordered: prefix scans
  // multi-record append in progress (ws_batch_begin/commit): ws_put/
  // ws_del frame into batch_buf instead of the fd, and the commit is
  // ONE write() + at most one fsync for the whole group-commit window
  bool batch_active = false;
  std::string batch_buf;
  int batch_records = 0;
  // streaming snapshot in progress (ws_snapshot_begin/add/commit)
  int snap_fd = -1;
  std::string snap_buf;
  std::string last_error;

  bool fail(const std::string& msg) {
    last_error = msg + (errno ? std::string(": ") + strerror(errno) : std::string());
    return false;
  }
};

struct Scan {
  WalStore* store;
  std::map<std::string, std::string>::const_iterator it;
  std::string prefix;
};

void abort_snapshot(WalStore* s);  // defined with the snapshot helpers below
bool write_all(int fd, const std::string& buf);  // ditto

void put_u32(std::string* out, uint32_t v) { out->append(reinterpret_cast<char*>(&v), 4); }
void put_u64(std::string* out, uint64_t v) { out->append(reinterpret_cast<char*>(&v), 8); }

std::string encode_payload(uint8_t op, uint64_t rv, const uint8_t* key, uint32_t klen,
                           const uint8_t* val, uint32_t vlen) {
  std::string payload;
  payload.reserve(1 + 8 + 4 + 4 + klen + vlen);
  payload.push_back(char(op));
  put_u64(&payload, rv);
  put_u32(&payload, klen);
  put_u32(&payload, vlen);
  if (klen) payload.append(reinterpret_cast<const char*>(key), klen);
  if (vlen) payload.append(reinterpret_cast<const char*>(val), vlen);
  return payload;
}

bool append_record(WalStore* s, const std::string& payload) {
  std::string rec;
  rec.reserve(8 + payload.size());
  put_u32(&rec, uint32_t(payload.size()));
  put_u32(&rec, crc32(reinterpret_cast<const uint8_t*>(payload.data()), payload.size()));
  rec += payload;
  if (s->batch_active) {
    // group commit: buffer the framed record; ws_batch_commit writes
    // the whole window in one syscall and applies the sync policy once
    s->batch_buf += rec;
    ++s->batch_records;
    return true;
  }
  const char* p = rec.data();
  size_t left = rec.size();
  while (left) {
    ssize_t n = write(s->fd, p, left);
    if (n < 0) {
      if (errno == EINTR) continue;
      return s->fail("write");
    }
    p += n;
    left -= size_t(n);
  }
  if (s->sync_every > 0 && ++s->unsynced >= s->sync_every) {
    if (fsync(s->fd) != 0) return s->fail("fsync");
    s->unsynced = 0;
  }
  return true;
}

// Replay a record stream from a buffer; returns the offset of the first
// bad/short record (== buf.size() when everything parsed).
size_t replay(WalStore* s, const std::string& buf) {
  size_t off = 0;
  if (buf.size() >= sizeof(MAGIC) && memcmp(buf.data(), MAGIC, sizeof(MAGIC)) == 0)
    off = sizeof(MAGIC);
  while (off + 8 <= buf.size()) {
    uint32_t len, crc;
    memcpy(&len, buf.data() + off, 4);
    memcpy(&crc, buf.data() + off + 4, 4);
    if (off + 8 + len > buf.size()) break;
    const uint8_t* payload = reinterpret_cast<const uint8_t*>(buf.data()) + off + 8;
    if (crc32(payload, len) != crc) break;
    if (len < 1 + 8 + 4 + 4) break;
    uint8_t op = payload[0];
    uint64_t rv;
    uint32_t klen, vlen;
    memcpy(&rv, payload + 1, 8);
    memcpy(&klen, payload + 9, 4);
    memcpy(&vlen, payload + 13, 4);
    if (17 + uint64_t(klen) + vlen != len) break;
    std::string key(reinterpret_cast<const char*>(payload) + 17, klen);
    if (op == OP_PUT) {
      s->index[key].assign(reinterpret_cast<const char*>(payload) + 17 + klen, vlen);
    } else if (op == OP_DEL) {
      s->index.erase(key);
    } else if (op == OP_EPOCH && vlen == 8) {
      uint64_t e;
      memcpy(&e, payload + 17 + klen, 8);
      if (e > s->epoch) s->epoch = e;
    }  // OP_META: rv watermark only
    if (rv > s->rv) s->rv = rv;
    off += 8 + len;
  }
  return off;
}

bool read_file(const std::string& path, std::string* out) {
  int fd = open(path.c_str(), O_RDONLY);
  if (fd < 0) return false;
  char buf[1 << 16];
  ssize_t n;
  while ((n = read(fd, buf, sizeof(buf))) > 0) out->append(buf, size_t(n));
  close(fd);
  return true;
}

}  // namespace

extern "C" {

void* ws_open(const char* path, int sync_every) {
  auto* s = new WalStore();
  s->path = path;
  s->sync_every = sync_every;

  std::string snap;
  if (read_file(s->path + ".snap", &snap)) replay(s, snap);

  std::string wal;
  if (read_file(s->path, &wal)) {
    size_t good = replay(s, wal);
    if (good < wal.size()) {
      // torn tail: truncate the file to the last good record
      if (truncate(path, off_t(good)) != 0) {
        delete s;
        return nullptr;
      }
    }
  }

  s->fd = open(path, O_CREAT | O_WRONLY | O_APPEND, 0644);
  if (s->fd < 0) {
    delete s;
    return nullptr;
  }
  struct stat st;
  if (fstat(s->fd, &st) == 0 && st.st_size == 0) {
    if (write(s->fd, MAGIC, sizeof(MAGIC)) != ssize_t(sizeof(MAGIC))) {
      close(s->fd);
      delete s;
      return nullptr;
    }
  }
  return s;
}

void ws_close(void* h) {
  auto* s = static_cast<WalStore*>(h);
  if (!s) return;
  if (s->snap_fd >= 0) abort_snapshot(s);  // caller died mid-stream
  if (s->fd >= 0) {
    if (s->unsynced) fsync(s->fd);
    close(s->fd);
  }
  delete s;
}

const char* ws_last_error(void* h) { return static_cast<WalStore*>(h)->last_error.c_str(); }

int ws_put(void* h, const uint8_t* key, uint32_t klen, const uint8_t* val, uint32_t vlen,
           uint64_t rv) {
  auto* s = static_cast<WalStore*>(h);
  if (!append_record(s, encode_payload(OP_PUT, rv, key, klen, val, vlen))) return -1;
  if (s->index_enabled)
    s->index[std::string(reinterpret_cast<const char*>(key), klen)].assign(
        reinterpret_cast<const char*>(val), vlen);
  if (rv > s->rv) s->rv = rv;
  return 0;
}

int ws_del(void* h, const uint8_t* key, uint32_t klen, uint64_t rv) {
  auto* s = static_cast<WalStore*>(h);
  if (!append_record(s, encode_payload(OP_DEL, rv, key, klen, nullptr, 0))) return -1;
  if (s->index_enabled)
    s->index.erase(std::string(reinterpret_cast<const char*>(key), klen));
  if (rv > s->rv) s->rv = rv;
  return 0;
}

int ws_get(void* h, const uint8_t* key, uint32_t klen, const uint8_t** val, uint32_t* vlen) {
  auto* s = static_cast<WalStore*>(h);
  auto it = s->index.find(std::string(reinterpret_cast<const char*>(key), klen));
  if (it == s->index.end()) return 0;
  *val = reinterpret_cast<const uint8_t*>(it->second.data());
  *vlen = uint32_t(it->second.size());
  return 1;
}

uint64_t ws_rv(void* h) { return static_cast<WalStore*>(h)->rv; }
uint64_t ws_count(void* h) { return static_cast<WalStore*>(h)->index.size(); }

int ws_batch_begin(void* h) {
  auto* s = static_cast<WalStore*>(h);
  if (s->batch_active) return -1;  // nested batches are a caller bug
  s->batch_active = true;
  s->batch_buf.clear();
  s->batch_records = 0;
  return 0;
}

int ws_batch_commit(void* h, int do_fsync) {
  auto* s = static_cast<WalStore*>(h);
  if (!s->batch_active) return -1;
  s->batch_active = false;
  int n = s->batch_records;
  s->batch_records = 0;
  std::string buf;
  buf.swap(s->batch_buf);
  if (buf.empty()) return 0;
  if (!write_all(s->fd, buf)) {
    s->fail("write");
    return -1;
  }
  if (do_fsync) {
    if (fsync(s->fd) != 0) {
      s->fail("fsync");
      return -1;
    }
    s->unsynced = 0;
  } else if (s->sync_every > 0 && (s->unsynced += n) >= s->sync_every) {
    // KCP_WAL_SYNC=flush keeps the engine's legacy amortized fsync
    if (fsync(s->fd) != 0) {
      s->fail("fsync");
      return -1;
    }
    s->unsynced = 0;
  }
  return 0;
}

int ws_batch_abort(void* h) {
  auto* s = static_cast<WalStore*>(h);
  s->batch_active = false;
  s->batch_buf.clear();
  s->batch_records = 0;
  return 0;
}

int ws_flush(void* h) {
  auto* s = static_cast<WalStore*>(h);
  if (s->fd >= 0 && fsync(s->fd) != 0) return -1;
  s->unsynced = 0;
  return 0;
}

uint64_t ws_epoch(void* h) { return static_cast<WalStore*>(h)->epoch; }

int ws_set_epoch(void* h, uint64_t epoch) {
  auto* s = static_cast<WalStore*>(h);
  uint8_t val[8];
  memcpy(val, &epoch, 8);
  if (!append_record(s, encode_payload(OP_EPOCH, s->rv, nullptr, 0, val, 8))) return -1;
  if (epoch > s->epoch) s->epoch = epoch;
  // the fence/promotion must be on disk before anything acts on it
  if (s->fd >= 0 && fsync(s->fd) != 0) {
    s->fail("fsync");
    return -1;
  }
  s->unsynced = 0;
  return 0;
}

void ws_set_rv(void* h, uint64_t rv) {
  auto* s = static_cast<WalStore*>(h);
  if (rv > s->rv) s->rv = rv;
}

}  // extern "C"

namespace {

void emit_record(std::string* buf, const std::string& payload) {
  put_u32(buf, uint32_t(payload.size()));
  put_u32(buf, crc32(reinterpret_cast<const uint8_t*>(payload.data()), payload.size()));
  *buf += payload;
}

bool write_all(int fd, const std::string& buf) {
  const char* p = buf.data();
  size_t left = buf.size();
  while (left) {
    ssize_t n = write(fd, p, left);
    if (n < 0) {
      if (errno == EINTR) continue;
      return false;
    }
    p += n;
    left -= size_t(n);
  }
  return true;
}

void abort_snapshot(WalStore* s) {
  if (s->snap_fd >= 0) close(s->snap_fd);
  s->snap_fd = -1;
  s->snap_buf.clear();
  unlink((s->path + ".snap.tmp").c_str());
}

// Commit whatever is buffered in snap_buf/snap_fd: flush, fsync, atomic
// rename, truncate the live WAL (re-stamping its magic header).
int commit_snapshot(WalStore* s) {
  int fd = s->snap_fd;
  s->snap_fd = -1;
  bool ok = write_all(fd, s->snap_buf);
  s->snap_buf.clear();
  ok = ok && fsync(fd) == 0;
  ok = close(fd) == 0 && ok;  // close unconditionally, even after failure
  if (!ok || rename((s->path + ".snap.tmp").c_str(), (s->path + ".snap").c_str()) != 0) {
    unlink((s->path + ".snap.tmp").c_str());
    return -1;
  }
  // truncate the WAL: everything live is now in the snapshot
  if (s->fd >= 0) close(s->fd);
  s->fd = open(s->path.c_str(), O_CREAT | O_WRONLY | O_TRUNC | O_APPEND, 0644);
  s->unsynced = 0;
  if (s->fd < 0) return -1;
  if (write(s->fd, MAGIC, sizeof(MAGIC)) != ssize_t(sizeof(MAGIC))) return -1;
  return 0;
}

}  // namespace

extern "C" {

int ws_snapshot(void* h) {
  auto* s = static_cast<WalStore*>(h);
  if (!s->index_enabled) return -1;  // journal-only mode: use the streaming API
  if (ws_snapshot_begin(h) != 0) return -1;
  for (const auto& [k, v] : s->index) {
    emit_record(&s->snap_buf,
                encode_payload(OP_PUT, 0, reinterpret_cast<const uint8_t*>(k.data()),
                               uint32_t(k.size()), reinterpret_cast<const uint8_t*>(v.data()),
                               uint32_t(v.size())));
  }
  return commit_snapshot(s);
}

int ws_snapshot_begin(void* h) {
  auto* s = static_cast<WalStore*>(h);
  if (s->snap_fd >= 0) abort_snapshot(s);
  s->snap_fd = open((s->path + ".snap.tmp").c_str(), O_CREAT | O_WRONLY | O_TRUNC, 0644);
  if (s->snap_fd < 0) return -1;
  s->snap_buf.assign(MAGIC, sizeof(MAGIC));
  emit_record(&s->snap_buf, encode_payload(OP_META, s->rv, nullptr, 0, nullptr, 0));
  if (s->epoch) {
    // re-stamp the epoch: the snapshot replaces the WAL that carried
    // the OP_EPOCH record, and a fence must survive compaction
    uint8_t val[8];
    memcpy(val, &s->epoch, 8);
    emit_record(&s->snap_buf, encode_payload(OP_EPOCH, s->rv, nullptr, 0, val, 8));
  }
  return 0;
}

int ws_snapshot_add(void* h, const uint8_t* key, uint32_t klen, const uint8_t* val,
                    uint32_t vlen) {
  auto* s = static_cast<WalStore*>(h);
  if (s->snap_fd < 0) return -1;
  emit_record(&s->snap_buf, encode_payload(OP_PUT, 0, key, klen, val, vlen));
  if (s->snap_buf.size() >= (1u << 20)) {  // stream out in ~1MB slabs
    if (!write_all(s->snap_fd, s->snap_buf)) {
      abort_snapshot(s);
      return -1;
    }
    s->snap_buf.clear();
  }
  return 0;
}

int ws_snapshot_commit(void* h) {
  auto* s = static_cast<WalStore*>(h);
  if (s->snap_fd < 0) return -1;
  return commit_snapshot(s);
}

void ws_index_release(void* h) {
  auto* s = static_cast<WalStore*>(h);
  s->index_enabled = false;
  s->index.clear();
}

void* ws_scan(void* h, const uint8_t* prefix, uint32_t plen) {
  auto* s = static_cast<WalStore*>(h);
  auto* c = new Scan();
  c->store = s;
  c->prefix.assign(reinterpret_cast<const char*>(prefix), plen);
  c->it = s->index.lower_bound(c->prefix);
  return c;
}

int ws_scan_next(void* cur, const uint8_t** key, uint32_t* klen, const uint8_t** val,
                 uint32_t* vlen) {
  auto* c = static_cast<Scan*>(cur);
  if (c->it == c->store->index.end()) return 0;
  const std::string& k = c->it->first;
  if (k.compare(0, c->prefix.size(), c->prefix) != 0) return 0;
  *key = reinterpret_cast<const uint8_t*>(k.data());
  *klen = uint32_t(k.size());
  *val = reinterpret_cast<const uint8_t*>(c->it->second.data());
  *vlen = uint32_t(c->it->second.size());
  ++c->it;
  return 1;
}

void ws_scan_free(void* cur) { delete static_cast<Scan*>(cur); }

}  // extern "C"
