"""Controller runtime: queue semantics, retry policy, informers, batching."""

import asyncio

import pytest

from kcp_tpu.client import Client, Informer
from kcp_tpu.reconciler import Controller, WorkQueue
from kcp_tpu.reconciler.controller import BatchController
from kcp_tpu.store import LogicalStore
from kcp_tpu.store.store import ADDED, DELETED, MODIFIED
from kcp_tpu.utils.errors import RetryableError


def cm(name, data=None):
    return {"apiVersion": "v1", "kind": "ConfigMap",
            "metadata": {"name": name, "namespace": "d"}, "data": data or {}}


# ----------------------------------------------------------------- queue

def test_queue_dedup_while_pending():
    async def main():
        q = WorkQueue()
        q.add("a")
        q.add("a")
        q.add("b")
        assert await q.get() == "a"
        assert await q.get() == "b"
        q.done("a"), q.done("b")
        assert len(q) == 0
    asyncio.run(main())


def test_queue_readd_during_processing_redelivers():
    async def main():
        q = WorkQueue()
        q.add("a")
        item = await q.get()
        q.add("a")  # while processing -> redo after done
        q.done(item)
        assert await q.get() == "a"
    asyncio.run(main())


def test_queue_add_after_and_rate_limited():
    async def main():
        q = WorkQueue()
        q.add_after("later", 0.02)
        q.add("now")
        assert await q.get() == "now"
        q.done("now")
        assert await q.get() == "later"
        q.done("later")
        q.add_rate_limited("x")
        assert q.num_requeues("x") == 1
        assert await q.get() == "x"
        q.done("x")
        q.forget("x")
        assert q.num_requeues("x") == 0
    asyncio.run(main())


def test_queue_drain_batches():
    async def main():
        q = WorkQueue()
        for i in range(10):
            q.add(i)
        batch = await q.drain(max_items=8, max_wait=0.001)
        assert batch == list(range(8))
        for i in batch:
            q.done(i)
        batch2 = await q.drain(max_items=8, max_wait=0.001)
        assert batch2 == [8, 9]
        for i in batch2:
            q.done(i)
    asyncio.run(main())


# ------------------------------------------------------------ controller

def test_controller_retries_then_drops():
    async def main():
        attempts = []

        async def process(item):
            attempts.append(item)
            raise RuntimeError("boom")

        c = Controller("t", process, max_retries=3)
        await c.start(1)
        c.enqueue("k")
        await asyncio.sleep(0.3)
        await c.stop()
        # initial + 3 retries = 4 attempts, then dropped
        assert len(attempts) == 4
    asyncio.run(main())


def test_controller_retryable_error_keeps_retrying():
    async def main():
        attempts = []
        done = asyncio.Event()

        async def process(item):
            attempts.append(item)
            if len(attempts) < 8:  # well past max_retries=2
                raise RetryableError("not ready yet")
            done.set()

        c = Controller("t", process, max_retries=2)
        await c.start(1)
        c.enqueue("k")
        await asyncio.wait_for(done.wait(), 5)
        await c.stop()
        assert len(attempts) == 8
    asyncio.run(main())


def test_batch_controller_processes_batches_and_retries_failures():
    async def main():
        batches = []
        fail_once = {"bad"}

        async def process_batch(items):
            batches.append(list(items))
            failed = []
            for it in items:
                if it in fail_once:
                    fail_once.discard(it)
                    failed.append((it, RuntimeError("flaky")))
            return failed

        c = BatchController("t", process_batch, batch_window=0.001)
        await c.start()
        for i in ["a", "b", "bad", "c"]:
            c.enqueue(i)
        await asyncio.sleep(0.3)
        await c.stop()
        flat = [i for b in batches for i in b]
        assert flat.count("bad") == 2  # failed once, retried once
        assert set(flat) == {"a", "b", "bad", "c"}
        assert c.ticks >= 2
    asyncio.run(main())


# -------------------------------------------------------------- informer

def test_informer_cache_events_and_index():
    async def main():
        store = LogicalStore()
        client = Client(store, "tenant")
        client.create("configmaps", cm("pre", {"k": "v"}))

        inf = Informer(client, "configmaps")
        events = []
        inf.add_handler(lambda t, old, new: events.append((t, (new or old)["metadata"]["name"])))
        inf.add_indexer("by_data_k", lambda o: [o.get("data", {}).get("k", "")])
        await inf.start()
        assert inf.synced
        assert events == [(ADDED, "pre")]

        client.create("configmaps", cm("x", {"k": "v"}))
        obj = client.get("configmaps", "x", "d")
        obj["data"]["k"] = "v2"
        client.update("configmaps", obj)
        client.delete("configmaps", "pre", "d")
        await asyncio.sleep(0.05)

        assert events[1:] == [(ADDED, "x"), (MODIFIED, "x"), (DELETED, "pre")]
        assert inf.get("tenant", "x", "d")["data"]["k"] == "v2"
        assert [o["metadata"]["name"] for o in inf.index("by_data_k", "v2")] == ["x"]
        assert inf.index("by_data_k", "v") == []
        await inf.stop()
    asyncio.run(main())


def test_informer_resync_replays_cache():
    async def main():
        store = LogicalStore()
        client = Client(store, "t")
        client.create("configmaps", cm("a"))
        inf = Informer(client, "configmaps")
        await inf.start()
        events = []
        inf.add_handler(lambda t, old, new: events.append(t))
        assert events == [ADDED]  # replay to late subscriber
        inf.resync()
        assert events == [ADDED, MODIFIED]
        await inf.stop()
    asyncio.run(main())


def test_informer_wildcard_spans_tenants():
    async def main():
        store = LogicalStore()
        from kcp_tpu.client import MultiClusterClient
        mc = MultiClusterClient(store)
        Client(store, "a").create("configmaps", cm("x"))
        Client(store, "b").create("configmaps", cm("x"))
        inf = Informer(mc, "configmaps")
        await inf.start()
        assert len(inf.list()) == 2
        Client(store, "c").create("configmaps", cm("y"))
        await asyncio.sleep(0.05)
        assert len(inf.list()) == 3
        await inf.stop()
    asyncio.run(main())


if __name__ == "__main__":
    import sys
    sys.exit(pytest.main([__file__, "-q"]))
