"""Batched sync-decision kernel vs the host deep-diff oracle."""

import jax
import numpy as np

from kcp_tpu.ops.diff import (
    DECISION_CREATE,
    DECISION_DELETE,
    DECISION_NOOP,
    DECISION_UPDATE,
    apply_deltas_jit,
    sync_decisions_jit,
)
from kcp_tpu.ops.encode import BucketEncoder


def obj(name, data, status=None):
    o = {
        "apiVersion": "v1",
        "kind": "ConfigMap",
        "metadata": {"name": name, "namespace": "d", "resourceVersion": "9"},
        "data": data,
    }
    if status is not None:
        o["status"] = status
    return o


def run(pairs):
    """pairs: list of (upstream_obj|None, downstream_obj|None)."""
    enc = BucketEncoder(capacity=64)
    up = enc.encode_batch([p[0] for p in pairs])
    down = enc.encode_batch([p[1] for p in pairs])
    d = sync_decisions_jit(
        up.values, up.exists, down.values, down.exists, enc.status_mask()
    )
    return np.asarray(d.decision), np.asarray(d.status_upsync)


def test_decision_matrix():
    decisions, upsync = run(
        [
            (obj("a", {"k": "v"}), obj("a", {"k": "v"})),  # in sync
            (obj("b", {"k": "v"}), None),  # create downstream
            (None, obj("c", {"k": "v"})),  # delete downstream
            (obj("d", {"k": "NEW"}), obj("d", {"k": "old"})),  # spec update
            (None, None),  # nothing anywhere
        ]
    )
    assert decisions.tolist() == [
        DECISION_NOOP,
        DECISION_CREATE,
        DECISION_DELETE,
        DECISION_UPDATE,
        DECISION_NOOP,
    ]
    assert not upsync.any()


def test_status_lane_independent_of_spec_lane():
    decisions, upsync = run(
        [
            # same spec, downstream grew status -> upsync only
            (obj("a", {"k": "v"}), obj("a", {"k": "v"}, status={"ready": True})),
            # spec differs AND status differs -> update + upsync
            (obj("b", {"k": "1"}, status={"n": 1}), obj("b", {"k": "2"}, status={"n": 2})),
            # only exists upstream -> no upsync possible
            (obj("c", {}, status={"n": 1}), None),
        ]
    )
    assert decisions.tolist() == [DECISION_NOOP, DECISION_UPDATE, DECISION_CREATE]
    assert upsync.tolist() == [True, True, False]


def test_volatile_metadata_does_not_dirty():
    enc = BucketEncoder(capacity=64)
    a = obj("a", {"k": "v"})
    b = obj("a", {"k": "v"})
    b["metadata"]["resourceVersion"] = "9999"
    b["metadata"]["uid"] = "different"
    up = enc.encode_batch([a])
    down = enc.encode_batch([b])
    d = sync_decisions_jit(up.values, up.exists, down.values, down.exists, enc.status_mask())
    assert int(d.decision[0]) == DECISION_NOOP


def test_apply_deltas_scatter_and_padding():
    enc = BucketEncoder(capacity=32)
    base = enc.encode_batch([obj("a", {"v": "0"}), obj("b", {"v": "0"}), None], pad_to=4)
    vals, exists = base.values, base.exists

    delta = enc.encode_batch([obj("b", {"v": "1"}), obj("c", {"v": "2"})], pad_to=4)
    idx = np.array([1, 2, 0, 0], dtype=np.int32)
    new_exists = np.array([True, True, False, False])
    valid = np.array([True, True, False, False])

    out_vals, out_exists = apply_deltas_jit(vals, exists, idx, delta.values, new_exists, valid)
    out_vals, out_exists = np.asarray(out_vals), np.asarray(out_exists)
    # row 1 updated, row 2 created, row 0 untouched by padding
    np.testing.assert_array_equal(out_vals[0], vals[0])
    np.testing.assert_array_equal(out_vals[1], delta.values[0])
    np.testing.assert_array_equal(out_vals[2], delta.values[1])
    assert out_exists.tolist() == [True, True, True, False]


def test_delete_via_delta():
    enc = BucketEncoder(capacity=32)
    base = enc.encode_batch([obj("a", {"v": "0"})], pad_to=2)
    idx = np.array([0, 0], dtype=np.int32)
    zeros = np.zeros_like(base.values)
    new_exists = np.array([False, False])
    valid = np.array([True, False])
    _, out_exists = apply_deltas_jit(base.values, base.exists, idx, zeros, new_exists, valid)
    assert not np.asarray(out_exists)[0]


def test_compact_patches_extracts_actionable_rows():
    from kcp_tpu.ops.diff import compact_patches

    decision = np.array([0, 1, 0, 2, 3, 0, 0, 0], np.uint8)
    upsync = np.array([False, False, True, False, False, False, True, False])
    p = jax.jit(compact_patches, static_argnames=("capacity",))(
        decision, upsync, capacity=16
    )
    count = int(p.count)
    assert count == 5 and not bool(p.overflow)
    idx = np.asarray(p.idx)[:count]
    np.testing.assert_array_equal(idx, [1, 2, 3, 4, 6])
    np.testing.assert_array_equal(np.asarray(p.code)[:count], [1, 0, 2, 3, 0])
    np.testing.assert_array_equal(
        np.asarray(p.upsync)[:count], [False, True, False, False, True]
    )
    # padding rows are routed to B and carry NOOP
    assert (np.asarray(p.idx)[count:] == 8).all()
    assert (np.asarray(p.code)[count:] == DECISION_NOOP).all()


def test_compact_patches_overflow():
    from kcp_tpu.ops.diff import compact_patches

    decision = np.full(32, 2, np.uint8)
    p = jax.jit(compact_patches, static_argnames=("capacity",))(
        decision, np.zeros(32, bool), capacity=4
    )
    assert int(p.count) == 4 and bool(p.overflow)
    np.testing.assert_array_equal(np.asarray(p.idx), [0, 1, 2, 3])
