"""Watcher-scale serving: shared resume window, bounded queues +
eviction, flush coalescing, bookmark-advanced fast resume, and the
router's watch spread across replicas.

The differential contract under test: a watcher that drops and resumes
through the shared window (``watch(since_rv=...)`` answered by one
bisect over the window index) must observe a byte-identical event
stream to one that never dropped — including through an eviction → 410
→ relist recovery, which may *re-deliver* but must never *lose*.
"""

from __future__ import annotations

import asyncio
import json
import random
import time
from collections import deque

import pytest

from kcp_tpu import faults
from kcp_tpu.apis.scheme import default_scheme
from kcp_tpu.client.informer import Informer
from kcp_tpu.server.handler import RestHandler
from kcp_tpu.server.httpd import HttpServer
from kcp_tpu.server.rest import RestClient
from kcp_tpu.store.selectors import parse_selector
from kcp_tpu.store.store import LogicalStore
from kcp_tpu.utils import errors
from kcp_tpu.utils.trace import REGISTRY


@pytest.fixture(autouse=True)
def _clear_faults():
    yield
    faults.clear()


def _cm(name: str, cluster: str, data: str = "", labels: dict | None = None):
    meta = {"name": name, "namespace": "default", "clusterName": cluster}
    if labels:
        meta["labels"] = labels
    return {"apiVersion": "v1", "kind": "ConfigMap", "metadata": meta,
            "data": {"v": data}}


# ---------------------------------------------------------------------------
# shared resume window: differential fuzz vs a never-dropped watcher
# ---------------------------------------------------------------------------


def _drive(store: LogicalStore, rng: random.Random, n: int) -> None:
    clusters = ["t0", "t1", "t2"]
    for i in range(n):
        cl = clusters[rng.randrange(3)]
        name = f"cm-{rng.randrange(24)}"
        labels = {"team": f"g{rng.randrange(3)}"}
        try:
            if rng.random() < 0.2:
                store.delete("configmaps", cl, name)
            elif rng.random() < 0.5:
                store.update("configmaps", cl,
                             _cm(name, cl, str(i), labels))
            else:
                store.create("configmaps", cl, _cm(name, cl, str(i), labels))
        except (errors.NotFoundError, errors.AlreadyExistsError):
            pass


@pytest.mark.parametrize("seed", [3, 17, 92])
def test_window_resume_byte_identical_to_continuous(seed):
    """Drop/resume through the shared window at random points; the
    resumed stream's encoded wire lines must be byte-identical to what
    a continuous watcher saw over the same rv span — for unselected AND
    selector-bound watches (whose replay runs the label-transition
    rewrite)."""
    rng = random.Random(seed)
    store = LogicalStore()
    _drive(store, rng, 60)

    for selector in (None, parse_selector("team=g1")):
        continuous = store.watch("configmaps", selector=selector)
        seen: list = []
        resumer = store.watch("configmaps", selector=selector,
                              since_rv=store.resource_version)
        for _round in range(6):
            _drive(store, rng, rng.randrange(5, 40))
            seen.extend(continuous.drain())
            # sever + resume from the last rv this watcher observed
            last = seen[-1].rv if seen else store.resource_version
            resumer.close()
            resumer = store.watch("configmaps", selector=selector,
                                  since_rv=last)
        seen.extend(continuous.drain())
        resumed_tail = resumer.drain()
        # the final resume's replay must equal the continuous stream's
        # suffix over the same span, byte for byte on the wire
        span = [ev for ev in seen if ev.rv > (seen[-len(resumed_tail) - 1].rv
                                              if len(resumed_tail) < len(seen)
                                              else 0)]
        assert [store.encode_event(e) for e in resumed_tail] == \
            [store.encode_event(e) for e in span[-len(resumed_tail):]]
        continuous.close()
        resumer.close()
    store.close()


def test_resume_served_from_shared_index_and_survives_history_surgery():
    store = LogicalStore()
    for i in range(30):
        store.create("configmaps", "t0", _cm(f"a{i}", "t0"))
    before = REGISTRY.counter("watch_resume_shared_total").value
    w = store.watch("configmaps", since_rv=store.resource_version - 10)
    assert len(w.drain()) == 10
    assert REGISTRY.counter("watch_resume_shared_total").value == before + 1
    w.close()

    # direct history surgery (what tests do to shrink the window): the
    # mirror must self-heal, honoring the NEW window
    store._history = deque(store._history, maxlen=8)
    with pytest.raises(errors.GoneError):
        store.watch("configmaps", since_rv=store.resource_version - 20)
    w2 = store.watch("configmaps", since_rv=store.resource_version - 4)
    assert len(w2.drain()) == 4
    w2.close()
    store.close()


# ---------------------------------------------------------------------------
# bounded queues + eviction
# ---------------------------------------------------------------------------


def test_queue_overflow_evicts_slow_watcher_only():
    store = LogicalStore()
    store._watch_queue = 8
    slow = store.watch("configmaps")
    store._watch_queue = 0
    healthy = store.watch("configmaps")
    before = REGISTRY.counter("watch_evicted_total").value
    for i in range(20):
        store.create("configmaps", "t0", _cm(f"x{i}", "t0"))
    store._flush_events()
    assert slow.closed and slow.evicted
    assert REGISTRY.counter("watch_evicted_total").value == before + 1
    # the healthy watcher is untouched: every committed event delivered
    assert len(healthy.drain()) == 20
    assert not healthy.evicted
    healthy.close()
    store.close()


def test_watch_evict_fault_drill():
    """The ``watch.evict`` KCP_FAULTS point force-evicts as if the
    bounded queue overflowed — the backpressure path has a drill."""
    faults.install(faults.FaultInjector("watch.evict:drop@tick=3"))
    store = LogicalStore()
    w = store.watch("configmaps")
    for i in range(5):
        store.create("configmaps", "t0", _cm(f"d{i}", "t0"))
    store._flush_events()
    assert w.closed and w.evicted
    assert len(w.drain()) == 2  # pushes 1..2 landed; tick 3 evicted
    store.close()


def test_eviction_recovery_zero_lost_updates():
    """Eviction → typed 410 → informer relist: the consumer converges
    on the store's final state with zero lost updates (the PR 6
    relist-NOW path closing the loop on backpressure)."""
    from kcp_tpu.server.server import Config
    from kcp_tpu.server.threaded import ServerThread

    srv = ServerThread(Config(durable=False, install_controllers=False,
                              tls=False)).start()
    client = RestClient(srv.address, cluster="t0")

    async def run() -> None:
        loop = asyncio.get_running_loop()
        client.create("configmaps", _cm("seed", "t0"))
        inf = Informer(client, "configmaps")
        await inf.start()
        try:
            # force-evict the server-side watch: stream must end in a
            # terminal typed 410 and the informer must recover by relist
            faults.install(faults.FaultInjector("watch.evict:drop@tick=1"))
            await loop.run_in_executor(
                None, client.create, "configmaps", _cm("during", "t0"))
            await asyncio.sleep(0.3)
            faults.clear()
            await loop.run_in_executor(
                None, client.create, "configmaps", _cm("after", "t0"))
            deadline = loop.time() + 15
            while loop.time() < deadline:
                if {"seed", "during", "after"} <= \
                        {k[2] for k in inf.cache}:
                    break
                await asyncio.sleep(0.05)
            assert {"seed", "during", "after"} <= \
                {k[2] for k in inf.cache}
        finally:
            await inf.stop()

    try:
        asyncio.run(run())
    finally:
        faults.clear()
        client.close()
        srv.stop()


def test_slow_socket_evicted_with_terminal_410(monkeypatch):
    """Handler-level eviction: a client that stops reading while the
    fan-out keeps writing crosses KCP_WATCH_BUFFER_MAX and gets a
    terminal typed 410 buffered on its way out."""
    import socket as _socket
    from urllib.parse import urlsplit

    from kcp_tpu.server.server import Config
    from kcp_tpu.server.threaded import ServerThread

    monkeypatch.setenv("KCP_WATCH_BUFFER_MAX", "2048")
    monkeypatch.setenv("KCP_WATCH_FLUSH_MS", "1")
    srv = ServerThread(Config(durable=False, install_controllers=False,
                              tls=False)).start()
    client = RestClient(srv.address, cluster="t0")
    sk = _socket.socket()
    try:
        client.create("configmaps", _cm("seed", "t0"))
        before = REGISTRY.counter("watch_evicted_total").value
        parts = urlsplit(srv.address)
        # a tiny receive window: backpressure must reach the server's
        # transport buffer instead of vanishing into kernel buffers
        sk.setsockopt(_socket.SOL_SOCKET, _socket.SO_RCVBUF, 2048)
        sk.settimeout(5)
        sk.connect((parts.hostname, parts.port))
        sk.sendall(b"GET /clusters/t0/api/v1/configmaps?watch=true "
                   b"HTTP/1.1\r\nHost: t\r\n\r\n")
        pad = "x" * 8192
        deadline = time.time() + 20
        i = 0
        while (REGISTRY.counter("watch_evicted_total").value == before
               and time.time() < deadline):
            client.update("configmaps", {
                "apiVersion": "v1", "kind": "ConfigMap",
                "metadata": {"name": "seed", "namespace": "default",
                             "clusterName": "t0"},
                "data": {"v": str(i), "pad": pad}})
            i += 1
        assert REGISTRY.counter("watch_evicted_total").value == before + 1
        data = b""
        try:
            while True:
                chunk = sk.recv(65536)
                if not chunk:
                    break
                data += chunk
        except (TimeoutError, OSError):
            pass
        assert b'"code": 410' in data and b'"reason": "Expired"' in data
    finally:
        sk.close()
        client.close()
        srv.stop()


# ---------------------------------------------------------------------------
# flush coalescing: byte-identical to the per-batch wire
# ---------------------------------------------------------------------------


def test_coalesced_stream_byte_identical_to_per_batch(monkeypatch):
    """The same seeded mutation run served with KCP_WATCH_COALESCE on
    and off yields the exact same reassembled line stream (chunk
    framing may differ; the payload may not), while the coalesced run
    uses fewer flushes."""

    async def one_mode(coalesce: bool) -> tuple[list[bytes], float]:
        monkeypatch.setenv("KCP_WATCH_COALESCE", "1" if coalesce else "0")
        monkeypatch.setenv("KCP_WATCH_FLUSH_MS", "5")
        store = LogicalStore(clock=lambda: 0.0)
        for i in range(8):
            store.create("configmaps", "t0", {
                "apiVersion": "v1", "kind": "ConfigMap",
                "metadata": {"name": f"c{i}", "namespace": "default",
                             "uid": f"u{i}"},
                "data": {"v": "0"}})
        handler = RestHandler(store, default_scheme(), admission=None)
        handler.ready = True
        srv = HttpServer(handler)
        await srv.start()
        flush0 = REGISTRY.counter("watch_flush_total").value
        reader, writer = await asyncio.open_connection(srv.host, srv.port)
        lines: list[bytes] = []
        try:
            writer.write(b"GET /clusters/t0/api/v1/configmaps?watch=true "
                         b"HTTP/1.1\r\nHost: t\r\n\r\n")
            await writer.drain()
            await reader.readuntil(b"\r\n\r\n")

            async def pump() -> None:
                buf = b""
                while True:
                    size_line = await reader.readline()
                    size = int(size_line.strip() or b"0", 16)
                    if size == 0:
                        return
                    buf += await reader.readexactly(size)
                    await reader.readexactly(2)
                    *done, buf = buf.split(b"\n")
                    lines.extend(d for d in done if d)

            task = asyncio.ensure_future(pump())
            for i in range(40):
                store.update("configmaps", "t0", {
                    "apiVersion": "v1", "kind": "ConfigMap",
                    "metadata": {"name": f"c{i % 8}",
                                 "namespace": "default"},
                    "data": {"v": f"m{i}"}})
                await asyncio.sleep(0.001)
            deadline = asyncio.get_running_loop().time() + 5
            while (len(lines) < 40
                   and asyncio.get_running_loop().time() < deadline):
                await asyncio.sleep(0.02)
            task.cancel()
        finally:
            writer.close()
            await srv.stop()
            handler.close()
            store.close()
        return lines, REGISTRY.counter("watch_flush_total").value - flush0

    async def run() -> None:
        per_batch, f_pb = await one_mode(False)
        coalesced, f_co = await one_mode(True)
        assert per_batch == coalesced
        assert len(per_batch) == 40
        assert f_co < f_pb

    asyncio.run(run())


# ---------------------------------------------------------------------------
# bookmarks: quiet-period resume without a relist (satellite regression)
# ---------------------------------------------------------------------------


def test_bookmark_quiet_period_resumes_without_410(monkeypatch):
    """A stream that sat quiet while OTHER tenants churned past its
    original rv must still resume without a 410: periodic server
    BOOKMARKs advance the informer's resume point (without waking any
    handler), so the drop lands inside the window and fast resume skips
    the relist entirely."""
    from kcp_tpu.server.server import Config
    from kcp_tpu.server.threaded import ServerThread

    monkeypatch.setenv("KCP_WATCH_BOOKMARK_S", "0.15")
    srv = ServerThread(Config(durable=False, install_controllers=False,
                              tls=False)).start()
    client = RestClient(srv.address, cluster="t0")
    other = RestClient(srv.address, cluster="t9")
    lists = 0
    orig_list = client.list

    def counting_list(*a, **kw):
        nonlocal lists
        lists += 1
        return orig_list(*a, **kw)

    client.list = counting_list

    async def run() -> None:
        loop = asyncio.get_running_loop()
        store = srv.server.store
        client.create("configmaps", _cm("seed", "t0"))
        inf = Informer(client, "configmaps")
        await inf.start()
        try:
            assert lists == 1
            # the RestWatch connects lazily from the pump task — it must
            # be ESTABLISHED (at its in-window resume point) before the
            # window shrinks, or the shrink races the initial connect
            deadline = loop.time() + 10
            while (not getattr(inf._watch, "responded", False)
                   and loop.time() < deadline):
                await asyncio.sleep(0.01)
            assert getattr(inf._watch, "responded", False)
            # shrink the window, then churn a DIFFERENT tenant far past
            # it — without bookmarks the informer's resume point (the
            # initial list rv) would now be outside the window
            srv.call(lambda: setattr(
                store, "_history", deque(store._history, maxlen=8)))
            for i in range(40):
                other.create("configmaps", _cm(f"noise{i}", "t9"))
            # quiet period long enough for >=1 bookmark at 0.15s cadence
            deadline = loop.time() + 8
            while loop.time() < deadline:
                if (inf._watch is not None
                        and getattr(inf._watch, "last_rv", 0)
                        >= store.resource_version):
                    break
                await asyncio.sleep(0.05)
            assert getattr(inf._watch, "last_rv", 0) >= \
                store.resource_version, "bookmark never advanced last_rv"
            before = REGISTRY.counter("informer_fast_resumes_total").value
            # sever the stream; the informer must fast-resume (no 410,
            # no relist) because the bookmark kept it inside the window
            inf._watch.close()
            deadline = loop.time() + 10
            while loop.time() < deadline:
                if REGISTRY.counter(
                        "informer_fast_resumes_total").value > before:
                    break
                await asyncio.sleep(0.05)
            assert REGISTRY.counter(
                "informer_fast_resumes_total").value == before + 1
            # the resumed stream is live: a new event reaches the cache
            await loop.run_in_executor(
                None, client.create, "configmaps", _cm("fresh", "t0"))
            deadline = loop.time() + 10
            while loop.time() < deadline:
                if any(k[2] == "fresh" for k in inf.cache):
                    break
                await asyncio.sleep(0.05)
            assert any(k[2] == "fresh" for k in inf.cache)
            assert lists == 1, "fast resume must not relist"
        finally:
            await inf.stop()

    try:
        asyncio.run(run())
    finally:
        client.close()
        other.close()
        srv.stop()


# ---------------------------------------------------------------------------
# router: fresh watch streams spread across a shard's replicas
# ---------------------------------------------------------------------------


def test_router_spreads_fresh_watches_across_replicas(tmp_path):
    from kcp_tpu.server.server import Config
    from kcp_tpu.server.threaded import ServerThread

    primary = ServerThread(Config(
        durable=True, install_controllers=False, tls=False,
        root_dir=str(tmp_path / "p"))).start()
    replica = ServerThread(Config(
        durable=False, install_controllers=False, tls=False,
        role="replica", primary=primary.address)).start()
    router = ServerThread(Config(
        role="router", durable=False, tls=False,
        shards=f"s0={primary.address}|{replica.address}")).start()
    try:
        pc = RestClient(primary.address, cluster="t0")
        pc.create("configmaps", _cm("pre", "t0"))
        pc.close()
        # wait for the replica to apply the seed write
        rc = RestClient(replica.address, cluster="t0")
        deadline = time.time() + 10
        while time.time() < deadline:
            st = rc._request("GET", "/replication/status")
            if st["applied_rv"] >= 1 and st["connected"]:
                break
            time.sleep(0.05)
        rc.close()

        before = REGISTRY.counter("router_watch_spread_total").value
        c = RestClient(router.address, cluster="t0")
        wc = RestClient(router.address, cluster="t0")

        async def scenario() -> None:
            loop = asyncio.get_running_loop()
            watches = [c.watch("configmaps", "default") for _ in range(4)]
            try:
                for w in watches:
                    w._ensure_started()
                deadline = loop.time() + 10
                while (not all(w.responded for w in watches)
                       and loop.time() < deadline):
                    await asyncio.sleep(0.05)
                assert all(w.responded for w in watches)
                await loop.run_in_executor(
                    None, wc.create, "configmaps", _cm("during", "t0"))
                for w in watches:
                    ev = await asyncio.wait_for(w.__anext__(), timeout=15)
                    assert ev.name == "during"
            finally:
                for w in watches:
                    w.close()

        asyncio.run(scenario())
        wc.close()
        c.close()
        # round-robin over [replica, primary]: 4 fresh streams = 2 spread
        assert REGISTRY.counter(
            "router_watch_spread_total").value == before + 2
    finally:
        router.stop()
        replica.stop()
        primary.stop()


def test_resume_through_router_spreads_to_replica(tmp_path):
    """A watch resume (?resourceVersion=) is no longer pinned to the
    primary: the replica's RV barrier parks the resume until its applied
    RV covers the pin, so resumes round-robin across primary+replicas
    like fresh watches. Two consecutive resumes land one on each, and
    both replay the identical window."""
    from kcp_tpu.server.server import Config
    from kcp_tpu.server.threaded import ServerThread

    primary = ServerThread(Config(
        durable=True, install_controllers=False, tls=False,
        root_dir=str(tmp_path / "p"))).start()
    replica = ServerThread(Config(
        durable=False, install_controllers=False, tls=False,
        role="replica", primary=primary.address)).start()
    router = ServerThread(Config(
        role="router", durable=False, tls=False,
        shards=f"s0={primary.address}|{replica.address}")).start()
    try:
        pc = RestClient(router.address, cluster="t0")
        for i in range(5):
            pc.create("configmaps", _cm(f"r{i}", "t0"))
        before = REGISTRY.counter("router_watch_spread_total").value

        async def collect(w) -> list:
            out = []
            async for ev in w:
                out.append(ev.name)
                if len(out) == 3:
                    break
            return out

        # round-robin over [replica, primary]: exactly one of the two
        # resumes is spread, and both must replay the same window
        for _ in range(2):
            w = pc.watch("configmaps", "default", since_rv=2)
            assert asyncio.run(collect(w)) == ["r2", "r3", "r4"]
            w.close()
        assert REGISTRY.counter(
            "router_watch_spread_total").value == before + 1
        pc.close()
    finally:
        router.stop()
        replica.stop()
        primary.stop()
