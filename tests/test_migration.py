"""Live shard scale-out + per-cluster WAL migration (PR 15).

Drills the elastic-capacity stack bottom-up: the store's fence / floor /
re-stamp primitives, watch-stream eviction through a live move, the
``migrate.cutover`` kill drill (the fault point's required exercise —
dying between migration finish and the ring flip must leave the fleet
serving from the source), the walreplay ``--cluster --emit-ndjson``
transport oracle, and the tentpole acceptance: a seeded workload run
against a fleet that DOUBLES mid-workload is byte-identical (modulo
per-store RV/timestamp stamps) to the same workload on an unmigrated
monolith.
"""

from __future__ import annotations

import asyncio
import json
import subprocess
import sys
import time
from pathlib import Path

import pytest

from helpers import shard_fleet
from kcp_tpu import faults
from kcp_tpu.server.rest import MultiClusterRestClient, RestClient
from kcp_tpu.server.server import Config
from kcp_tpu.server.threaded import ServerThread
from kcp_tpu.sharding import migrate, owner_name
from kcp_tpu.store.store import LogicalStore
from kcp_tpu.utils import errors
from test_sharding import (
    _apply_ops,
    _cm,
    _norm,
    _normalized_state,
    _workload,
)

REPO = Path(__file__).resolve().parent.parent


def _movers(n_before: int, n_after: int, candidates=50):
    """Cluster names that change owners when the ring grows from
    ``n_before`` to ``n_after`` shards (HRW is deterministic on names,
    so this is a pure function, not a probe)."""
    old = [f"s{i}" for i in range(n_before)]
    new = [f"s{i}" for i in range(n_after)]
    out = []
    for i in range(candidates):
        c = f"c{i}"
        if owner_name(old, c) != owner_name(new, c):
            out.append(c)
    return out


def _stayers(n_before: int, n_after: int, candidates=50):
    old = [f"s{i}" for i in range(n_before)]
    new = [f"s{i}" for i in range(n_after)]
    return [f"c{i}" for i in range(candidates)
            if owner_name(old, f"c{i}") == owner_name(new, f"c{i}")]


def _grow_shard(i: int) -> ServerThread:
    """Start shard ``s<i>`` booted with the grown ring identity (the
    shape RouterFleet.scale_out uses)."""
    names = ",".join(f"s{j}" for j in range(i + 1))
    return ServerThread(Config(durable=False, install_controllers=False,
                               tls=False, shard_name=f"s{i}",
                               ring_names=names, ring_epoch=1)).start()


# ------------------------------------------------------ store primitives


def test_fence_refuses_writes_and_unfence_restores():
    s = LogicalStore()
    s.create("configmaps", "ca", {"metadata": {"name": "a"}})
    cut = s.fence_cluster("ca")
    assert cut >= 1 and s.fence_cluster("ca") == cut  # idempotent
    with pytest.raises(errors.UnavailableError):
        s.create("configmaps", "ca", {"metadata": {"name": "b"}})
    with pytest.raises(errors.UnavailableError):
        s.delete("configmaps", "ca", "a")
    # reads and OTHER clusters are untouched: the fence is per-cluster
    assert s.get("configmaps", "ca", "a")["metadata"]["name"] == "a"
    s.create("configmaps", "cb", {"metadata": {"name": "b"}})
    s.unfence_cluster("ca")
    s.create("configmaps", "ca", {"metadata": {"name": "b"}})
    s.close()


def test_apply_migrated_restamps_rv_and_preserves_identity():
    src = LogicalStore()
    made = src.create("configmaps", "ca", {"metadata": {"name": "a"},
                                           "data": {"k": "v"}})
    dst = LogicalStore()
    dst.create("configmaps", "other", {"metadata": {"name": "x"}})
    rec = {"op": "put",
           "key": ["configmaps", "ca", "", "a"],
           "obj": json.loads(json.dumps(made))}
    rv = dst.apply_migrated(rec)
    got = dst.get("configmaps", "ca", "a")
    # fresh LOCAL rv (source counters mean nothing here)...
    assert got["metadata"]["resourceVersion"] == str(rv)
    assert rv != int(made["metadata"]["resourceVersion"]) or rv > 1
    # ...but uid/creationTimestamp/payload survive byte-for-byte
    assert got["metadata"]["uid"] == made["metadata"]["uid"]
    assert (got["metadata"]["creationTimestamp"]
            == made["metadata"]["creationTimestamp"])
    assert got["data"] == {"k": "v"}
    # epoch records are transport framing, not state
    assert dst.apply_migrated({"op": "epoch", "epoch": 3}) is None
    # del of an absent key is a no-op (idempotent re-streams)
    assert dst.apply_migrated({"op": "del",
                               "key": ["configmaps", "ca", "", "gone"],
                               }) is None
    src.close()
    dst.close()


def test_migration_floor_answers_410_on_stale_resume():
    dst = LogicalStore()
    dst.apply_migrated({"op": "put", "key": ["configmaps", "ca", "", "a"],
                        "obj": {"metadata": {"name": "a"}}})
    floor = dst.finish_migration("ca", source_rv=500)
    assert dst._rv >= 501  # every future rv sorts after the source's
    # a source-minted resume rv answers an honest typed 410
    with pytest.raises(errors.GoneError):
        dst.watch("configmaps", cluster="ca", since_rv=7)
    # resumes at/after the floor, and other clusters, are fine
    dst.watch("configmaps", cluster="ca", since_rv=floor).close()
    dst.watch("configmaps", cluster="cb", since_rv=None).close()
    dst.close()


def test_purge_drops_objects_without_delete_events():
    s = LogicalStore()
    w_mover = s.watch("configmaps", cluster="ca")
    w_other = s.watch("configmaps", cluster="cb")
    for n in ("a", "b"):
        s.create("configmaps", "ca", {"metadata": {"name": n}})
    s.create("configmaps", "cb", {"metadata": {"name": "keep"}})
    assert s.purge_cluster("ca") == 2
    s._flush_events()

    async def drain(w):
        evs = []
        try:
            while True:
                evs.append(await asyncio.wait_for(w.__anext__(), 0.3))
        except (StopAsyncIteration, asyncio.TimeoutError, errors.GoneError):
            pass
        return evs

    # the mover's watch ends via EVICTION (typed 410 relist), with the
    # pre-purge ADDED events delivered first and no DELETED events — a
    # move is not a delete
    evs = asyncio.run(drain(w_mover))
    assert [e.type for e in evs] == ["ADDED", "ADDED"]
    assert w_mover.evicted
    # the bystander's stream stays open and saw nothing new
    assert not w_other.evicted
    w_other.close()
    assert s.get("configmaps", "cb", "keep")
    with pytest.raises(errors.NotFoundError):
        s.get("configmaps", "ca", "a")
    s.close()


# --------------------------------------------------- live fleet behavior


def test_live_scale_out_moves_cluster_and_keeps_serving():
    mover = _movers(2, 3)[0]
    stayer = _stayers(2, 3)[0]
    with shard_fleet(2) as (router, shards, ring):
        for cl in (mover, stayer):
            c = RestClient(router.address, cl)
            for i in range(4):
                c.create("configmaps", _cm(f"m{i}", cl, {"i": str(i)}))
            c.close()
        new = _grow_shard(2)
        try:
            out = migrate.scale_out(router.address, f"s2={new.address}")
            assert mover in out["pending"]
            assert out["records"] >= 4
            c = RestClient(router.address, mover)
            items, _rv = c.list("configmaps", "default")
            assert {o["metadata"]["name"] for o in items} == {
                f"m{i}" for i in range(4)}
            # post-flip writes land on the new owner
            c.create("configmaps", _cm("post", mover, {}))
            doc = c._request("GET", "/ring")
            assert len(doc["shards"]) == 3 and not doc["overrides"]
            c.close()
            # the source purged the moved cluster (no wildcard dupes)
            src = next(t for t in shards
                       if t.server.config.shard_name
                       == owner_name(["s0", "s1"], mover))
            assert not any(k[1] == mover
                           for k in src.server.store._objects)
            assert sum(1 for k in new.server.store._objects
                       if k[1] == mover) == 5
        finally:
            new.stop()


def test_watch_rides_migration_with_typed_410_relist():
    mover = _movers(2, 3)[0]
    with shard_fleet(2) as (router, _shards, _ring):
        rc = RestClient(router.address, mover)
        rc.create("configmaps", _cm("w0", mover, {"i": "0"}))

        async def main():
            w = rc.watch("configmaps")
            await w.next_batch(0.05)
            await asyncio.sleep(0.2)
            rc.create("configmaps", _cm("w1", mover, {"i": "1"}))
            got = []
            for _ in range(100):
                got.extend(await w.next_batch(0.05))
                if got:
                    break
            assert got and got[0].name == "w1"
            new = _grow_shard(2)
            try:
                migrate.scale_out(router.address, f"s2={new.address}")
                # the source's purge ends the stream with a terminal
                # typed 410 — the informer contract: relist, never hang
                with pytest.raises(errors.GoneError):
                    for _ in range(200):
                        await w.next_batch(0.05)
                w.close()
                # the relist against the new owner sees every object
                items, rv = rc.list("configmaps", "default")
                assert {o["metadata"]["name"] for o in items} == {
                    "w0", "w1"}
                # and a fresh watch from the relist RV serves new events
                w2 = rc.watch("configmaps", since_rv=rv)
                await w2.next_batch(0.05)
                await asyncio.sleep(0.2)
                rc.create("configmaps", _cm("w2", mover, {"i": "2"}))
                got2 = []
                for _ in range(100):
                    got2.extend(await w2.next_batch(0.05))
                    if got2:
                        break
                assert got2 and got2[0].name == "w2"
                w2.close()
            finally:
                new.stop()

        asyncio.run(main())
        rc.close()


def test_cutover_fault_drill_rolls_back_then_retry_completes():
    """The ``migrate.cutover`` drill: die at the WORST instant — target
    loaded, ring not yet flipped. The fence must roll back (the fleet
    keeps serving from the source) and a bare retry must complete the
    move (idempotent re-stream + upsert)."""
    mover = _movers(2, 3)[0]
    with shard_fleet(2) as (router, shards, _ring):
        rc = RestClient(router.address, mover)
        for i in range(3):
            rc.create("configmaps", _cm(f"d{i}", mover, {"i": str(i)}))
        new = _grow_shard(2)
        faults.install(faults.FaultInjector("migrate.cutover:raise",
                                            seed=1))
        try:
            with pytest.raises(faults.InjectedFault):
                migrate.scale_out(router.address, f"s2={new.address}")
            # rollback: ownership never flipped (the pin survives), the
            # fence lifted, and the SOURCE still serves reads AND writes
            doc = rc._request("GET", "/ring")
            assert doc["overrides"].get(mover) == owner_name(
                ["s0", "s1"], mover)
            items, _rv = rc.list("configmaps", "default")
            assert len(items) == 3
            rc.create("configmaps", _cm("post-abort", mover, {}))
            faults.clear()
            # the retry (per pending cluster, off the ring doc — the
            # grown ring is already published) completes the move and
            # carries the post-abort write with it
            for cluster in sorted(doc["overrides"]):
                out = migrate.migrate_cluster(router.address, cluster)
                assert out["target"] == owner_name(
                    ["s0", "s1", "s2"], cluster)
            doc = rc._request("GET", "/ring")
            assert not doc["overrides"]
            items, _rv = rc.list("configmaps", "default")
            assert {o["metadata"]["name"] for o in items} == {
                "d0", "d1", "d2", "post-abort"}
            src = next(t for t in shards
                       if t.server.config.shard_name
                       == owner_name(["s0", "s1"], mover))
            assert not any(k[1] == mover
                           for k in src.server.store._objects)
        finally:
            faults.clear()
            rc.close()
            new.stop()


def test_fence_window_answers_503_through_router():
    mover = _movers(2, 3)[0]
    with shard_fleet(2) as (router, shards, _ring):
        rc = RestClient(router.address, mover)
        rc.create("configmaps", _cm("f0", mover, {}))
        src_url = next(t.address for t in shards
                       if t.server.config.shard_name
                       == owner_name(["s0", "s1"], mover))
        migrate._req(src_url, "POST", "/migration/fence",
                     {"cluster": mover})
        try:
            # a fenced write is a typed 503 — the client's plain retry
            # discipline covers the window, nothing special-cased
            with pytest.raises(errors.UnavailableError):
                rc.create("configmaps", _cm("f1", mover, {}))
            # reads keep working mid-window
            assert rc.get("configmaps", "f0", "default")
        finally:
            migrate._req(src_url, "POST", "/migration/unfence",
                         {"cluster": mover})
        rc.create("configmaps", _cm("f1", mover, {}))
        rc.close()


# -------------------------------------------- walreplay transport oracle


def test_walreplay_cluster_ndjson_matches_live_feed(tmp_path):
    """``walreplay.py --cluster --emit-ndjson`` must reproduce EXACTLY
    the records a live migration streams off the fenced source — the
    offline transport is the oracle for the online one."""
    mover = _movers(2, 3)[0]
    stayer = _stayers(2, 3)[0]
    with shard_fleet(2, durable=True, root_dir=str(tmp_path)) as (
            router, shards, _ring):
        for cl in (mover, stayer):
            c = RestClient(router.address, cl)
            for i in range(5):
                c.create("configmaps", _cm(f"o{i}", cl, {"i": str(i)}))
            c.delete("configmaps", "o1", "default")
            c.close()
        src = next(t for t in shards
                   if t.server.config.shard_name
                   == owner_name(["s0", "s1"], mover))
        live, barrier = migrate.fetch_cluster_records(src.address, mover)
        assert barrier > 0
        root = src.server.config.root_dir
        out = subprocess.run(
            [sys.executable, str(REPO / "scripts" / "walreplay.py"),
             root, "--cluster", mover, "--emit-ndjson"],
            capture_output=True, text=True, timeout=60, check=True)
        offline = [json.loads(line) for line in out.stdout.splitlines()
                   if line.strip()]
        key = lambda r: tuple(r["key"])  # noqa: E731
        assert sorted(offline, key=key) == sorted(live, key=key)
        assert len(offline) == 4  # o1 deleted; stayer filtered out
        # and the records actually LOAD: ingest into a fresh store
        dst = LogicalStore()
        for rec in offline:
            dst.apply_migrated(rec)
        assert sorted(k[3] for k in dst._objects) == [
            "o0", "o2", "o3", "o4"]
        dst.close()


def test_walreplay_oracle_holds_across_feed_batches(tmp_path):
    """The paged transport drill: a cluster LARGER than one feed batch
    (>256 objects — the hub streams SNAP lines in 256-line spans and
    fetches objects per batch rather than materializing a pair list;
    walreplay buffers stdout in the same 256-record batches). The
    byte-set oracle must hold exactly as it does for small clusters."""
    mover = _movers(2, 3)[0]
    n = 300
    with shard_fleet(2, durable=True, root_dir=str(tmp_path)) as (
            router, shards, _ring):
        c = RestClient(router.address, mover)
        for i in range(n):
            c.create("configmaps", _cm(f"big{i:04d}", mover,
                                       {"i": str(i), "pad": "x" * 64}))
        c.close()
        src = next(t for t in shards
                   if t.server.config.shard_name
                   == owner_name(["s0", "s1"], mover))
        live, barrier = migrate.fetch_cluster_records(src.address, mover)
        assert barrier > 0 and len(live) == n
        out = subprocess.run(
            [sys.executable, str(REPO / "scripts" / "walreplay.py"),
             src.server.config.root_dir, "--cluster", mover,
             "--emit-ndjson"],
            capture_output=True, text=True, timeout=120, check=True)
        offline = [json.loads(line) for line in out.stdout.splitlines()
                   if line.strip()]
        key = lambda r: tuple(r["key"])  # noqa: E731
        assert sorted(offline, key=key) == sorted(live, key=key)
        assert len(offline) == n


# ------------------------------------------- tentpole differential fuzz


@pytest.mark.parametrize("seed", [29])
def test_migrated_fleet_differential_fuzz(seed):
    """The acceptance bar: a seeded workload whose fleet DOUBLES (2->4,
    one shard at a time, migrations live) mid-workload ends
    byte-identical — modulo per-store RV/timestamp stamps — to the same
    workload against an unmigrated monolith."""
    clusters = [f"c{i}" for i in range(8)]
    assert set(_movers(2, 4)) & set(clusters)  # the move is real
    ops = _workload(seed, clusters, 100)
    split = 50

    with ServerThread(Config(durable=False, install_controllers=False,
                             tls=False)) as mono:
        wc = MultiClusterRestClient(mono.address)
        _apply_ops(wc, ops)
        want = _normalized_state(wc)
        wc.close()

    with shard_fleet(2) as (router, _shards, _ring):
        wc = MultiClusterRestClient(router.address)
        _apply_ops(wc, ops[:split])
        grown: list[ServerThread] = []
        try:
            moved = 0
            for i in (2, 3):
                t = _grow_shard(i)
                grown.append(t)
                out = migrate.scale_out(router.address,
                                        f"s{i}={t.address}")
                moved += out["records"]
            assert moved >= 1
            # retry=True: the second half may race residual fence 503s
            _apply_ops(wc, ops[split:], retry=True)
            deadline = time.time() + 30
            while True:
                got = _normalized_state(wc)
                if got == want or time.time() > deadline:
                    break
                time.sleep(0.2)
            assert got == want
            doc = RestClient(router.address)._request("GET", "/ring")
            assert len(doc["shards"]) == 4 and not doc["overrides"]
        finally:
            wc.close()
            for t in grown:
                t.stop()
