"""Negotiation controller: the 3-way CRD/import/negotiated state machine.

Mirrors the end-to-end assertions of the reference's apiNegotiation demo
(contrib/demo/apiNegotiation:36-60): first import founds + publishes the
negotiated resource; a second, narrower import is flagged Compatible=False.
"""

import asyncio

import pytest

from kcp_tpu.apis import apiresource as ar
from kcp_tpu.apis import conditions as cond
from kcp_tpu.apis import crd as crdapi
from kcp_tpu.client import MultiClusterClient
from kcp_tpu.reconcilers.apiresource import NegotiationController
from kcp_tpu.reconcilers.crdlifecycle import CRDLifecycleController
from kcp_tpu.store import LogicalStore


def widget_spec(schema=None, version="v1alpha1"):
    return ar.common_spec("example.io", version, "widgets", "Widget",
                          schema=schema or {"type": "object", "properties": {
                              "spec": {"type": "object", "properties": {
                                  "size": {"type": "integer"}}}}},
                          sub_resources=["status"])


async def eventually(pred, timeout=5.0):
    loop = asyncio.get_event_loop()
    end = loop.time() + timeout
    last = None
    while loop.time() < end:
        try:
            last = pred()
            if last:
                return last
        except Exception:
            pass
        await asyncio.sleep(0.01)
    raise AssertionError(f"condition not reached (last={last!r})")


def setup_controllers(store, auto_publish=True):
    mc = MultiClusterClient(store)
    neg = NegotiationController(mc, auto_publish=auto_publish)
    lifecycle = CRDLifecycleController(mc)
    return mc, neg, lifecycle


def test_import_founds_negotiated_and_publishes_crd():
    async def main():
        store = LogicalStore()
        mc, negc, lifecycle = setup_controllers(store)
        await negc.start()
        await lifecycle.start()
        t = mc.cluster_client("tenant")

        imp = ar.new_api_resource_import("us-east1", widget_spec())
        t.create(ar.APIRESOURCEIMPORTS, imp)

        # negotiated resource appears, Submitted, then Published via CRD
        neg = await eventually(
            lambda: t.get(ar.NEGOTIATEDAPIRESOURCES, "widgets.v1alpha1.example.io")
        )
        assert neg["spec"]["publish"] is True
        crd = await eventually(lambda: t.get(crdapi.CRDS, "widgets.example.io"))
        assert crd["spec"]["names"]["kind"] == "Widget"
        assert crd["spec"]["versions"][0]["name"] == "v1alpha1"
        assert crd["spec"]["versions"][0]["subresources"] == {"status": {}}
        # lifecycle establishes -> negotiation marks Published -> import Available
        await eventually(lambda: crdapi.is_established(t.get(crdapi.CRDS, "widgets.example.io")))
        await eventually(lambda: cond.is_condition_true(
            t.get(ar.NEGOTIATEDAPIRESOURCES, "widgets.v1alpha1.example.io"), ar.PUBLISHED))
        imp_now = await eventually(lambda: (
            lambda o: ar.is_compatible_and_available(o) and o
        )(t.get(ar.APIRESOURCEIMPORTS, imp["metadata"]["name"])))
        assert cond.is_condition_true(imp_now, ar.COMPATIBLE)
        # the widget resource is now served
        assert "widgets.example.io" in t.resources()
        await negc.stop()
        await lifecycle.stop()
    asyncio.run(main())


def test_second_incompatible_import_flagged():
    """The apiNegotiation demo's core assertion: us-west1's narrower schema
    (string size vs integer size) gets Compatible=False."""
    async def main():
        store = LogicalStore()
        mc, negc, lifecycle = setup_controllers(store)
        await negc.start()
        await lifecycle.start()
        t = mc.cluster_client("tenant")

        t.create(ar.APIRESOURCEIMPORTS, ar.new_api_resource_import("us-east1", widget_spec()))
        await eventually(lambda: cond.is_condition_true(
            t.get(ar.NEGOTIATEDAPIRESOURCES, "widgets.v1alpha1.example.io"), ar.PUBLISHED))

        bad_schema = {"type": "object", "properties": {
            "spec": {"type": "object", "properties": {"size": {"type": "string"}}}}}
        imp2 = ar.new_api_resource_import("us-west1", widget_spec(schema=bad_schema))
        t.create(ar.APIRESOURCEIMPORTS, imp2)

        imp2_now = await eventually(lambda: (
            lambda o: cond.find_condition(o, ar.COMPATIBLE) and o
        )(t.get(ar.APIRESOURCEIMPORTS, imp2["metadata"]["name"])))
        c = cond.find_condition(imp2_now, ar.COMPATIBLE)
        assert c["status"] == "False"
        assert "IncompatibleSchema" == c["reason"]
        assert "type changed" in c["message"]
        # the first import stays healthy
        assert ar.is_compatible_and_available(
            t.get(ar.APIRESOURCEIMPORTS, "us-east1.widgets.v1alpha1.example.io"))
        await negc.stop()
        await lifecycle.stop()
    asyncio.run(main())


def test_compatible_import_narrows_lcd():
    """A second import missing an optional property narrows the negotiated
    schema to the LCD (UpdatePublished strategy allows it)."""
    async def main():
        store = LogicalStore()
        mc, negc, lifecycle = setup_controllers(store)
        await negc.start()
        await lifecycle.start()
        t = mc.cluster_client("tenant")

        rich = {"type": "object", "properties": {
            "spec": {"type": "object", "properties": {
                "size": {"type": "integer"}, "color": {"type": "string"}}}}}
        poor = {"type": "object", "properties": {
            "spec": {"type": "object", "properties": {
                "size": {"type": "integer"}}}}}
        t.create(ar.APIRESOURCEIMPORTS, ar.new_api_resource_import("east", widget_spec(rich)))
        await eventually(lambda: t.get(ar.NEGOTIATEDAPIRESOURCES, "widgets.v1alpha1.example.io"))
        t.create(ar.APIRESOURCEIMPORTS, ar.new_api_resource_import("west", widget_spec(poor)))

        def narrowed():
            neg = t.get(ar.NEGOTIATEDAPIRESOURCES, "widgets.v1alpha1.example.io")
            props = neg["spec"]["openAPIV3Schema"]["properties"]["spec"]["properties"]
            return "color" not in props and "size" in props
        await eventually(narrowed)
        await negc.stop()
        await lifecycle.stop()
    asyncio.run(main())


def test_manually_created_crd_enforces():
    async def main():
        store = LogicalStore()
        mc, negc, lifecycle = setup_controllers(store)
        await negc.start()
        await lifecycle.start()
        t = mc.cluster_client("tenant")

        # import founds a negotiated resource first
        t.create(ar.APIRESOURCEIMPORTS, ar.new_api_resource_import("east", widget_spec()))
        await eventually(lambda: t.get(ar.NEGOTIATEDAPIRESOURCES, "widgets.v1alpha1.example.io"))

        # an operator manually applies a CRD for the same GVR (no owner ref)
        manual_schema = {"type": "object", "properties": {
            "spec": {"type": "object", "properties": {"mode": {"type": "string"}}}}}
        manual = crdapi.new_crd("example.io", "v1alpha1", "widgets", "Widget",
                                schema=manual_schema)
        try:
            t.create(crdapi.CRDS, manual)
        except Exception:
            existing = t.get(crdapi.CRDS, "widgets.example.io")
            existing["spec"]["versions"][0]["schema"]["openAPIV3Schema"] = manual_schema
            existing["metadata"]["ownerReferences"] = []
            t.update(crdapi.CRDS, existing)

        neg = await eventually(lambda: (
            lambda o: cond.is_condition_true(o, ar.ENFORCED) and o
        )(t.get(ar.NEGOTIATEDAPIRESOURCES, "widgets.v1alpha1.example.io")))
        # schema overwritten by the CRD's
        await eventually(lambda: t.get(
            ar.NEGOTIATEDAPIRESOURCES, "widgets.v1alpha1.example.io"
        )["spec"]["openAPIV3Schema"] == manual_schema)
        del neg
        await negc.stop()
        await lifecycle.stop()
    asyncio.run(main())


def test_orphan_negotiated_deleted_when_last_import_goes():
    async def main():
        store = LogicalStore()
        mc, negc, lifecycle = setup_controllers(store)
        await negc.start()
        await lifecycle.start()
        t = mc.cluster_client("tenant")
        imp = ar.new_api_resource_import("east", widget_spec())
        t.create(ar.APIRESOURCEIMPORTS, imp)
        await eventually(lambda: t.get(ar.NEGOTIATEDAPIRESOURCES, "widgets.v1alpha1.example.io"))
        t.delete(ar.APIRESOURCEIMPORTS, imp["metadata"]["name"])

        def neg_gone():
            try:
                t.get(ar.NEGOTIATEDAPIRESOURCES, "widgets.v1alpha1.example.io")
                return False
            except Exception:
                return True
        await eventually(neg_gone)
        await negc.stop()
        await lifecycle.stop()
    asyncio.run(main())


def test_lcd_memoization_across_identical_tenants():
    """configs[3] shape: many tenants with identical schemas walk the LCD
    tree O(distinct), not O(imports)."""
    async def main():
        store = LogicalStore()
        mc, negc, lifecycle = setup_controllers(store)
        await negc.start()
        await lifecycle.start()
        for i in range(40):
            t = mc.cluster_client(f"tenant-{i}")
            t.create(ar.APIRESOURCEIMPORTS, ar.new_api_resource_import("east", widget_spec()))
        await eventually(lambda: all(
            cond.is_condition_true(
                mc.cluster_client(f"tenant-{i}").get(
                    ar.NEGOTIATEDAPIRESOURCES, "widgets.v1alpha1.example.io"),
                ar.PUBLISHED)
            for i in range(40)), timeout=15)
        # second wave: every tenant's west import folds into its negotiated
        # resource — 40 structurally identical LCD comparisons
        for i in range(40):
            t = mc.cluster_client(f"tenant-{i}")
            t.create(ar.APIRESOURCEIMPORTS, ar.new_api_resource_import("west", widget_spec()))
        await eventually(lambda: all(
            ar.is_compatible_and_available(
                mc.cluster_client(f"tenant-{i}").get(
                    ar.APIRESOURCEIMPORTS, "west.widgets.v1alpha1.example.io"))
            for i in range(40)), timeout=15)
        # identical (negotiated, import) schema pairs hit the memo
        assert negc.stats["lcd_hits"] > 0
        assert negc.stats["lcd_walks"] < 40
        await negc.stop()
        await lifecycle.stop()
    asyncio.run(main())


if __name__ == "__main__":
    import sys
    sys.exit(pytest.main([__file__, "-q"]))
