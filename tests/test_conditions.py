"""Condition helpers: transitions, lastTransitionTime stability."""

from kcp_tpu.apis import conditions as c


def test_set_and_find():
    obj = {}
    assert c.set_condition(obj, "Ready", c.TRUE, "AllGood")
    cond = c.find_condition(obj, "Ready")
    assert cond["status"] == "True"
    assert cond["reason"] == "AllGood"
    assert c.is_condition_true(obj, "Ready")


def test_transition_time_only_moves_on_status_flip():
    obj = {}
    c.set_condition(obj, "Ready", c.TRUE)
    t0 = c.find_condition(obj, "Ready")["lastTransitionTime"]
    # same status, new message: no transition
    changed = c.set_condition(obj, "Ready", c.TRUE, message="still fine")
    assert changed
    assert c.find_condition(obj, "Ready")["lastTransitionTime"] == t0
    # unchanged call reports no change
    assert not c.set_condition(obj, "Ready", c.TRUE, message="still fine")


def test_remove():
    obj = {}
    c.set_condition(obj, "Ready", c.TRUE)
    c.set_condition(obj, "Compatible", c.FALSE)
    assert c.remove_condition(obj, "Ready")
    assert c.find_condition(obj, "Ready") is None
    assert c.find_condition(obj, "Compatible")
    assert not c.remove_condition(obj, "Ready")
