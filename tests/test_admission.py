"""Admission & flow control: chain routing, vectorized quota ledgers,
APF-style flow control, and the 429/Retry-After contract end to end.

Covers the subsystem the reference carves out in
docs/investigations/self-service-policy.md (per-workspace policy/quota)
plus KEP-1040-shaped flow control: reserve → commit/rollback around
store writes, limits sourced from ResourceQuota objects, usage-recount
drift repair, shuffle-sharded bounded queues, and the client-side
Retry-After pacing (RestClient typed error, informer/syncer hints).

The concurrent-writer quota fuzz is the acceptance-bar test: N threads
create/delete against tight quotas with ``admission:*`` faults active;
the ledger must never go negative, never oversubscribe, and must equal
a naive full recount after quiescence.
"""

import asyncio
import json
import threading
import time

import pytest

from kcp_tpu import faults
from kcp_tpu.admission import (
    FlowController,
    QuotaLedger,
    build_chain,
    normalize_hard,
)
from kcp_tpu.apis.scheme import default_scheme
from kcp_tpu.server.handler import RestHandler
from kcp_tpu.server.httpd import Request
from kcp_tpu.store.store import LogicalStore
from kcp_tpu.utils import errors
from kcp_tpu.utils.trace import REGISTRY


@pytest.fixture(autouse=True)
def _clear_faults():
    faults.clear()
    yield
    faults.clear()


def cm(name, ns="default", data=None):
    return {"apiVersion": "v1", "kind": "ConfigMap",
            "metadata": {"name": name, "namespace": ns},
            "data": data or {"v": name}}


def rq(name, hard, ns="default"):
    return {"apiVersion": "v1", "kind": "ResourceQuota",
            "metadata": {"name": name, "namespace": ns},
            "spec": {"hard": hard}}


def req(method, path, body=None):
    payload = json.dumps(body).encode() if body is not None else b""
    return Request(method, path, {}, {}, payload)


def make_handler(flow=None, store=None):
    store = store or LogicalStore()
    chain = build_chain(store, flow=flow)
    return RestHandler(store, default_scheme(), admission=chain), store, chain


def post(handler, cluster, body, resource="configmaps"):
    return handler(req(
        "POST", f"/clusters/{cluster}/api/v1/namespaces/default/{resource}",
        body))


def delete(handler, cluster, name, resource="configmaps"):
    return handler(req(
        "DELETE",
        f"/clusters/{cluster}/api/v1/namespaces/default/{resource}/{name}"))


# ------------------------------------------------------------- quota ledger


def test_ledger_reserve_commit_rollback_protocol():
    led = QuotaLedger()
    led.set_hard("c1", "configmaps", 2)
    r1 = led.reserve("c1", "configmaps")
    r2 = led.reserve("c1", "configmaps")
    # both slots reserved: a third concurrent writer must be refused
    # even though usage is still 0
    with pytest.raises(errors.ForbiddenError):
        led.reserve("c1", "configmaps")
    led.record("configmaps", "c1", 1)
    r1.commit()
    r2.rollback()
    assert led.peek("c1", "configmaps") == (1, 0, 2)
    # commit/rollback are idempotent
    r1.commit()
    r2.rollback()
    assert led.peek("c1", "configmaps") == (1, 0, 2)
    # freed reservation is available again
    assert led.reserve("c1", "configmaps") is not None


def test_ledger_unlimited_keys_skip_reservations():
    led = QuotaLedger()
    assert led.reserve("c1", "secrets") is None  # nothing to oversubscribe
    led.record("secrets", "c1", 1)
    assert led.usage_of("c1", "secrets") == 1


def test_ledger_recount_repairs_drift():
    store = LogicalStore()
    led = QuotaLedger()
    led.attach(store)
    store.create("configmaps", "c1", cm("a"))
    store.create("configmaps", "c1", cm("b"))
    assert led.usage_of("c1", "configmaps") == 2
    # inject drift, then recount against the store's true buckets
    led.record("configmaps", "c1", 5)
    assert led.usage_of("c1", "configmaps") == 7
    drift = led.recount(store)
    assert drift == 1
    assert led.usage_of("c1", "configmaps") == 2
    assert led.recount(store) == 0


def test_ledger_attach_counts_preexisting_objects():
    store = LogicalStore()
    store.create("configmaps", "c1", cm("pre"))
    store.create("resourcequotas", "c1", rq("budget", {"configmaps": 1}))
    led = QuotaLedger()
    led.attach(store)  # WAL-restore shape: usage + limits from live state
    assert led.usage_of("c1", "configmaps") == 1
    with pytest.raises(errors.ForbiddenError):
        led.reserve("c1", "configmaps")


def test_normalize_hard():
    assert normalize_hard({"count/configmaps": "3", "secrets": 2}) == {
        "configmaps": 3, "secrets": 2}
    # duplicate spellings combine by minimum
    assert normalize_hard({"count/configmaps": 5, "configmaps": 2}) == {
        "configmaps": 2}
    with pytest.raises(ValueError):
        normalize_hard({"configmaps": -1})
    with pytest.raises(ValueError):
        normalize_hard({"configmaps": "lots"})


# ----------------------------------------------------------- chain over REST


def test_quota_enforced_over_rest_and_freed_by_delete():
    async def main():
        handler, store, chain = make_handler()
        assert (await post(handler, "t1", rq("budget", {"configmaps": 2}),
                           "resourcequotas")).status == 201
        assert (await post(handler, "t1", cm("a"))).status == 201
        assert (await post(handler, "t1", cm("b"))).status == 201
        resp = await post(handler, "t1", cm("c"))
        assert resp.status == 403
        body = json.loads(resp.body)
        assert body["reason"] == "Forbidden"
        assert "exceeded quota" in body["message"]
        # other tenants are not limited
        assert (await post(handler, "t2", cm("a"))).status == 201
        # delete frees the slot
        assert (await delete(handler, "t1", "a")).status == 200
        assert (await post(handler, "t1", cm("c"))).status == 201
        # raising the limit (quota object update) binds synchronously
        quota = store.get("resourcequotas", "t1", "budget", "default")
        quota["spec"]["hard"] = {"count/configmaps": 10}
        r = await handler(req(
            "PUT", "/clusters/t1/api/v1/namespaces/default/resourcequotas/budget",
            quota))
        assert r.status == 200
        assert (await post(handler, "t1", cm("d"))).status == 201

    asyncio.run(main())


def test_defaulting_normalizes_resourcequota_spec():
    async def main():
        handler, store, _ = make_handler()
        assert (await post(handler, "t1",
                           rq("budget", {"configmaps": "4", "count/secrets": 2}),
                           "resourcequotas")).status == 201
        obj = store.get("resourcequotas", "t1", "budget", "default")
        assert obj["spec"]["hard"] == {"count/configmaps": 4,
                                       "count/secrets": 2}

    asyncio.run(main())


def test_validation_rejects_malformed_quota_and_nameless_create():
    async def main():
        handler, _, _ = make_handler()
        r = await post(handler, "t1", rq("bad", {"configmaps": "many"}),
                       "resourcequotas")
        assert r.status == 422
        r = await post(handler, "t1", {"apiVersion": "v1", "kind": "ConfigMap",
                                       "metadata": {}, "data": {}})
        assert r.status == 422

    asyncio.run(main())


def test_admission_disabled_keeps_write_path_open():
    async def main():
        store = LogicalStore()
        handler = RestHandler(store, default_scheme(), admission=None)
        assert handler.admission is None
        assert (await post(handler, "t1", cm("a"))).status == 201

    asyncio.run(main())


def test_store_write_failure_rolls_back_reservation():
    async def main():
        handler, _, chain = make_handler()
        assert (await post(handler, "t1", rq("budget", {"configmaps": 1}),
                           "resourcequotas")).status == 201
        faults.install(faults.FaultInjector("store.put:error=1.0", seed=7))
        resp = await post(handler, "t1", cm("a"))
        assert resp.status == 503
        faults.clear()
        # the failed write's reservation was rolled back: the single
        # quota slot is still free
        assert chain.ledger.peek("t1", "configmaps") == (0, 0, 1)
        assert (await post(handler, "t1", cm("a"))).status == 201

    asyncio.run(main())


def test_injected_admission_quota_fault_rolls_back():
    async def main():
        handler, _, chain = make_handler()
        assert (await post(handler, "t1", rq("budget", {"configmaps": 1}),
                           "resourcequotas")).status == 201
        faults.install(faults.FaultInjector("admission.quota:error=1.0", seed=3))
        resp = await post(handler, "t1", cm("a"))
        assert resp.status == 503
        faults.clear()
        assert chain.ledger.peek("t1", "configmaps") == (0, 0, 1)

    asyncio.run(main())


# -------------------------------------------------------------- flow control


def test_flow_token_exhaustion_gets_429_with_retry_after():
    async def main():
        clock = [0.0]
        fc = FlowController(concurrency=8, rate=2.0, burst=2.0,
                            clock=lambda: clock[0])
        rel = fc.try_acquire("t1", "create")
        rel()
        fc.try_acquire("t1", "create")()
        with pytest.raises(errors.TooManyRequestsError) as exc:
            fc.try_acquire("t1", "create")
        assert exc.value.retry_after > 0
        # a different tenant's flow is untouched
        fc.try_acquire("t2", "create")()
        # and a different verb-class of the same tenant too
        fc.try_acquire("t1", "delete")()
        # refill: after the hinted interval the flow admits again
        clock[0] += exc.value.retry_after
        fc.try_acquire("t1", "create")()

    asyncio.run(main())


def test_flow_concurrency_queues_then_dispatches_fifo():
    async def main():
        fc = FlowController(concurrency=1, rate=1e9, burst=1e9)
        rel = fc.try_acquire("t1", "create")
        got = fc.try_acquire("t2", "create")
        assert isinstance(got, int)  # must queue
        waiter = asyncio.ensure_future(fc.queue_wait(got))
        await asyncio.sleep(0.01)
        assert not waiter.done()
        rel()  # frees the slot -> dispatches the queued waiter
        rel2 = await asyncio.wait_for(waiter, 1.0)
        rel2()

    asyncio.run(main())


def test_flow_queue_bound_rejects_with_429():
    async def main():
        fc = FlowController(concurrency=1, rate=1e9, burst=1e9,
                            queues=1, queue_depth=2, hand_size=1)
        hold = fc.try_acquire("t1", "create")
        waiters = []
        for _ in range(2):
            got = fc.try_acquire("t1", "create")
            waiters.append(asyncio.ensure_future(fc.queue_wait(got)))
        await asyncio.sleep(0.01)
        with pytest.raises(errors.TooManyRequestsError):
            got = fc.try_acquire("t1", "create")
            if isinstance(got, int):
                await fc.queue_wait(got)
        hold()
        for w in waiters:
            (await asyncio.wait_for(w, 1.0))()

    asyncio.run(main())


def test_flow_shuffle_shards_are_deterministic():
    fc1 = FlowController(seed=42)
    fc2 = FlowController(seed=42)
    fc1.try_acquire("t1", "create")()
    fc2.try_acquire("t1", "create")()
    assert fc1._hand[0] == fc2._hand[0]
    fc3 = FlowController(seed=43)
    hands = set()
    for t in range(32):
        fc3.try_acquire(f"t{t}", "create")()
        hands.add(fc3._hand[t])
    assert len(hands) > 1  # flows spread across queue hands


def test_flow_429_over_rest_carries_retry_after_header():
    async def main():
        fc = FlowController(concurrency=8, rate=1.0, burst=1.0)
        handler, _, _ = make_handler(flow=fc)
        assert (await post(handler, "t1", cm("a"))).status == 201
        resp = await post(handler, "t1", cm("b"))
        assert resp.status == 429
        assert int(resp.headers["Retry-After"]) >= 1
        body = json.loads(resp.body)
        assert body["reason"] == "TooManyRequests"
        assert body["details"]["retryAfterSeconds"] >= 1
        m = REGISTRY.counter("flow_rejected_total", "")
        assert m.value >= 1

    asyncio.run(main())


def test_reads_bypass_admission_entirely():
    async def main():
        # a flow controller with ZERO budget: any admitted write would 429
        fc = FlowController(concurrency=1, rate=1e-9, burst=1e-9)
        handler, store, _ = make_handler(flow=fc)
        store.create("configmaps", "t1", cm("a"))
        r = await handler(req(
            "GET", "/clusters/t1/api/v1/namespaces/default/configmaps"))
        assert r.status == 200
        r = await handler(req(
            "GET", "/clusters/t1/api/v1/namespaces/default/configmaps/a"))
        assert r.status == 200

    asyncio.run(main())


# ------------------------------------------------- client-side Retry-After


def test_status_error_mapping_429_and_403():
    from kcp_tpu.server.rest import _status_error

    err = _status_error(429, "TooManyRequests", "slow down",
                        details={"retryAfterSeconds": 7})
    assert isinstance(err, errors.TooManyRequestsError)
    assert err.retry_after == 7.0
    err = _status_error(429, "", "slow down", retry_after=3.5)
    assert isinstance(err, errors.TooManyRequestsError)
    assert err.retry_after == 3.5
    err = _status_error(403, "Forbidden", "quota")
    assert isinstance(err, errors.ForbiddenError)
    assert errors.retry_after_hint(err) is None


def test_informer_retry_delay_honors_hint_jittered_capped():
    from kcp_tpu.client.informer import Informer

    inf = Informer.__new__(Informer)
    inf.rewatch_backoff = 0.2
    inf.retry_after_cap = 30.0
    assert inf._retry_delay(RuntimeError("x")) == 0.2
    err = errors.TooManyRequestsError("throttled")
    err.retry_after = 4.0
    for _ in range(20):
        d = inf._retry_delay(err)
        assert 4.0 <= d <= 5.0  # hint .. hint * 1.25
    err.retry_after = 1e9
    assert inf._retry_delay(err) <= 30.0 * 1.25  # capped

    asyncio_err = errors.TooManyRequestsError("hint below floor")
    asyncio_err.retry_after = 0.01
    assert inf._retry_delay(asyncio_err) >= inf.rewatch_backoff


# ------------------------------------------------------- workqueue metrics


def test_workqueue_exports_depth_and_queue_seconds():
    from kcp_tpu.reconciler.fairqueue import make_queue

    async def main():
        q = make_queue("adm-test")
        q.add(("tenant", "a"))
        q.add(("tenant", "b"))
        depth = REGISTRY.gauge("workqueue_depth_adm_test", "")
        assert depth.value == 2
        hist = REGISTRY.histogram("workqueue_queue_seconds", "")
        n0 = hist.n
        item = await q.get()
        assert item is not None
        assert hist.n == n0 + 1
        assert depth.value == 1
        q.done(item)
        q.shut_down()

    asyncio.run(main())


def test_plain_workqueue_metrics_too():
    from kcp_tpu.reconciler.queue import WorkQueue

    async def main():
        q = WorkQueue("adm-plain")
        q.add("x")
        assert REGISTRY.gauge("workqueue_depth_adm_plain", "").value == 1
        hist = REGISTRY.histogram("workqueue_queue_seconds", "")
        n0 = hist.n
        await q.get()
        assert hist.n == n0 + 1

    asyncio.run(main())


# -------------------------------------------------------- 413 body ceiling


def test_oversized_body_rejected_413(monkeypatch):
    from kcp_tpu.server import httpd as httpd_mod

    monkeypatch.setattr(httpd_mod, "MAX_BODY_BYTES", 1024)

    async def main():
        handler, _, _ = make_handler()
        server = httpd_mod.HttpServer(handler)
        await server.start()
        try:
            reader, writer = await asyncio.open_connection(
                "127.0.0.1", server.port)
            big = json.dumps(cm("big", data={"v": "x" * 4096})).encode()
            writer.write(
                f"POST /clusters/t1/api/v1/namespaces/default/configmaps "
                f"HTTP/1.1\r\nHost: x\r\nContent-Type: application/json\r\n"
                f"Content-Length: {len(big)}\r\n\r\n".encode())
            # send only part of the body: the server must answer 413 from
            # the declared length WITHOUT waiting for (or buffering) it
            writer.write(big[:128])
            await writer.drain()
            head = await asyncio.wait_for(reader.readuntil(b"\r\n\r\n"), 5.0)
            assert b"413" in head.split(b"\r\n", 1)[0]
            assert b"Connection: close" in head
            body = await asyncio.wait_for(reader.read(64 * 1024), 5.0)
            status = json.loads(body)
            assert status["reason"] == "RequestEntityTooLarge"
            writer.close()
        finally:
            await server.stop()

    asyncio.run(main())


# --------------------------------------------- concurrent-writer quota fuzz


def test_concurrent_quota_fuzz_under_faults():
    """N threads create/delete against a tight quota with admission:*
    and store faults active: the ledger never goes negative, never
    oversubscribes, and equals a naive full recount after quiescence."""
    HARD = 12
    THREADS = 8
    OPS = 120

    store = LogicalStore()
    led = QuotaLedger()
    led.attach(store)
    led.set_hard("fuzz", "configmaps", HARD)

    faults.install(faults.FaultInjector(
        "admission.quota:error=0.08;store.put:error=0.08;"
        "admission.chain:latency=1ms", seed=1337))

    store_lock = threading.Lock()  # the store itself is loop-affine;
    # the LEDGER's thread-safety is what this fuzz exercises
    import random as _random

    violations: list[str] = []
    stop = threading.Event()

    def sampler():
        while not stop.is_set():
            used, reserved, hard = led.peek("fuzz", "configmaps")
            if used < 0:
                violations.append(f"negative usage {used}")
            if used > HARD:
                # the oversubscription bar: committed usage may never
                # pass the hard limit
                violations.append(f"oversubscribed usage {used}")
            if used + reserved > HARD + THREADS:
                # between a store write landing and its commit, one
                # object is transiently counted in both usage and its
                # still-open reservation — bounded by in-flight writers
                violations.append(f"reservation leak {used}+{reserved}")
            time.sleep(0.0002)

    def writer(tid: int):
        rng = _random.Random(tid)
        mine: list[str] = []
        for k in range(OPS):
            try:
                if mine and rng.random() < 0.4:
                    name = mine.pop()
                    with store_lock:
                        store.delete("configmaps", "fuzz", name, "default")
                else:
                    name = f"cm-{tid}-{k}"
                    r = led.reserve("fuzz", "configmaps")
                    try:
                        faults.maybe_fail("admission.quota")
                        with store_lock:
                            store.create("configmaps", "fuzz",
                                         cm(name), "default")
                    except BaseException:
                        if r is not None:
                            r.rollback()
                        raise
                    if r is not None:
                        r.commit()
                    mine.append(name)
            except (errors.ApiError, faults.InjectedFault):
                pass

    # the store's race guard is thread-affinity-based; claim it for the
    # fuzz's serialized multi-thread access
    import os

    prev_race = os.environ.get("KCP_RACE")
    os.environ["KCP_RACE"] = "0"
    try:
        smp = threading.Thread(target=sampler, daemon=True)
        smp.start()
        threads = [threading.Thread(target=writer, args=(t,))
                   for t in range(THREADS)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        stop.set()
        smp.join(timeout=5)
    finally:
        if prev_race is None:
            os.environ.pop("KCP_RACE", None)
        else:
            os.environ["KCP_RACE"] = prev_race
    faults.clear()

    assert not violations, violations[:5]
    used, reserved, hard = led.peek("fuzz", "configmaps")
    assert reserved == 0  # every reservation settled
    # byte-identical to a naive recount of the store
    naive = store.counts().get(("configmaps", "fuzz"), 0)
    assert used == naive
    assert used <= HARD
    assert led.recount(store) == 0  # nothing to repair
    neg = REGISTRY.counter("quota_ledger_negative_total", "")
    assert neg.value == 0


def test_http_quota_fuzz_with_faults_matches_recount():
    """The same invariant end-to-end over the REST handler: interleaved
    create/delete with injected admission + store faults; afterwards the
    ledger equals the store recount and never exceeded the limit."""

    async def main():
        handler, store, chain = make_handler()
        assert (await post(handler, "fz", rq("budget", {"configmaps": 5}),
                           "resourcequotas")).status == 201
        faults.install(faults.FaultInjector(
            "admission.quota:error=0.05;store.put:error=0.05", seed=99))
        import random as _random

        rng = _random.Random(5)
        live: list[str] = []
        created = 0
        for k in range(300):
            if live and rng.random() < 0.45:
                name = live.pop()
                r = await delete(handler, "fz", name)
                assert r.status in (200, 503)
                if r.status != 200:
                    live.append(name)
            else:
                name = f"cm-{k}"
                r = await post(handler, "fz", cm(name))
                assert r.status in (201, 403, 503), r.body
                if r.status == 201:
                    live.append(name)
                    created += 1
            used, reserved, hard = chain.ledger.peek("fz", "configmaps")
            assert used + reserved <= 5
            assert used >= 0
        faults.clear()
        assert created > 0  # the quota admitted work under faults
        naive = store.counts().get(("configmaps", "fz"), 0)
        assert chain.ledger.usage_of("fz", "configmaps") == naive
        assert chain.ledger.recount(store) == 0

    asyncio.run(main())


# -------------------------------------------------- noisy-neighbor fairness


def test_noisy_neighbor_throttled_quiet_tenant_unaffected():
    async def main():
        fc = FlowController(concurrency=8, rate=20.0, burst=20.0, seed=2)
        handler, _, _ = make_handler(flow=fc)
        flood_429 = flood_ok = 0
        for k in range(80):  # flood tenant far past its budget
            r = await post(handler, "noisy", cm(f"f-{k}"))
            if r.status == 429:
                flood_429 += 1
            elif r.status == 201:
                flood_ok += 1
        assert flood_429 > 0 and flood_ok > 0
        # the quiet tenant's writes all pass while the flood is throttled
        for k in range(5):
            r = await post(handler, "quiet", cm(f"q-{k}"))
            assert r.status == 201

    asyncio.run(main())
