"""Namespace lifecycle controller tests.

Covers the reference's namespace-controller semantics (wired at
pkg/server/server.go:325-356): finalizer stamping, content sweep on
deletion, finalizer release once empty, and per-tenant isolation of the
sweep across logical clusters.
"""

from __future__ import annotations

import asyncio

from kcp_tpu.client import MultiClusterClient
from kcp_tpu.reconcilers.namespace import FINALIZER, NamespaceLifecycleController
from kcp_tpu.store import LogicalStore
from kcp_tpu.utils.errors import NotFoundError


async def _settle(predicate, timeout=3.0, interval=0.02):
    deadline = asyncio.get_event_loop().time() + timeout
    while asyncio.get_event_loop().time() < deadline:
        if predicate():
            return True
        await asyncio.sleep(interval)
    return predicate()


def _absent(store, resource, cluster, name, namespace="") -> bool:
    try:
        store.get(resource, cluster, name, namespace)
        return False
    except NotFoundError:
        return True


def _has_finalizer(store, cluster, name) -> bool:
    try:
        ns = store.get("namespaces", cluster, name)
    except NotFoundError:
        return False
    return FINALIZER in (ns["metadata"].get("finalizers") or [])


def test_live_namespace_gains_finalizer():
    async def main():
        store = LogicalStore(namespace_lifecycle=True)
        client = MultiClusterClient(store)
        ctrl = NamespaceLifecycleController(client)
        await ctrl.start()
        try:
            client.scoped("root").create("namespaces", {"metadata": {"name": "team-a"}})
            assert await _settle(lambda: _has_finalizer(store, "root", "team-a"))
        finally:
            await ctrl.stop()

    asyncio.run(main())


def test_deletion_sweeps_contents_then_removes_namespace():
    async def main():
        store = LogicalStore(namespace_lifecycle=True)
        client = MultiClusterClient(store)
        ctrl = NamespaceLifecycleController(client)
        await ctrl.start()
        try:
            scoped = client.scoped("root")
            scoped.create("namespaces", {"metadata": {"name": "team-a"}})
            scoped.create("configmaps",
                          {"metadata": {"name": "cm1", "namespace": "team-a"}},
                          namespace="team-a")
            scoped.create("secrets",
                          {"metadata": {"name": "s1", "namespace": "team-a"}},
                          namespace="team-a")
            await _settle(lambda: _has_finalizer(store, "root", "team-a"))

            scoped.delete("namespaces", "team-a")
            gone = await _settle(lambda: _absent(store, "namespaces", "root", "team-a"))
            assert gone, "namespace should disappear once swept"
            assert _absent(store, "configmaps", "root", "cm1", "team-a")
            assert _absent(store, "secrets", "root", "s1", "team-a")
        finally:
            await ctrl.stop()

    asyncio.run(main())


def test_sweep_is_tenant_scoped():
    async def main():
        store = LogicalStore(namespace_lifecycle=True)
        client = MultiClusterClient(store)
        ctrl = NamespaceLifecycleController(client)
        await ctrl.start()
        try:
            for cluster in ("east", "west"):
                client.scoped(cluster).create(
                    "namespaces", {"metadata": {"name": "shared"}})
                client.scoped(cluster).create(
                    "configmaps",
                    {"metadata": {"name": "cm", "namespace": "shared"}},
                    namespace="shared")
            await _settle(lambda: _has_finalizer(store, "east", "shared")
                          and _has_finalizer(store, "west", "shared"))

            client.scoped("east").delete("namespaces", "shared")
            gone = await _settle(lambda: _absent(store, "namespaces", "east", "shared"))
            assert gone
            # the other tenant's namespace and contents are untouched
            assert store.get("namespaces", "west", "shared")
            assert store.get("configmaps", "west", "cm", "shared")
        finally:
            await ctrl.stop()

    asyncio.run(main())


def test_create_delete_race_cannot_orphan_contents():
    """The store stamps the finalizer synchronously at create, so a
    delete issued before the controller's first reconcile still sweeps."""

    async def main():
        store = LogicalStore(namespace_lifecycle=True)
        client = MultiClusterClient(store)
        scoped = client.scoped("root")
        # namespace + contents + delete all BEFORE the controller starts
        scoped.create("namespaces", {"metadata": {"name": "racy"}})
        scoped.create("configmaps",
                      {"metadata": {"name": "cm", "namespace": "racy"}},
                      namespace="racy")
        scoped.delete("namespaces", "racy")
        ns = store.get("namespaces", "root", "racy")
        assert ns["metadata"]["deletionTimestamp"]  # finalizer held it

        ctrl = NamespaceLifecycleController(client)
        await ctrl.start()
        try:
            assert await _settle(lambda: _absent(store, "namespaces", "root", "racy"))
            assert _absent(store, "configmaps", "root", "cm", "racy")
        finally:
            await ctrl.stop()

    asyncio.run(main())


def test_orphaned_contents_swept_after_out_of_band_finalizer_removal():
    async def main():
        store = LogicalStore(namespace_lifecycle=True)
        client = MultiClusterClient(store)
        ctrl = NamespaceLifecycleController(client)
        await ctrl.start()
        try:
            scoped = client.scoped("root")
            scoped.create("namespaces", {"metadata": {"name": "ns1"}})
            scoped.create("configmaps",
                          {"metadata": {"name": "cm", "namespace": "ns1"}},
                          namespace="ns1")
            await _settle(lambda: _has_finalizer(store, "root", "ns1"))
            # strip the finalizer out of band, then delete: the namespace
            # vanishes instantly, contents become orphans
            ns = store.get("namespaces", "root", "ns1")
            ns["metadata"]["finalizers"] = []
            scoped.update("namespaces", ns)
            scoped.delete("namespaces", "ns1")
            assert _absent(store, "namespaces", "root", "ns1")
            assert await _settle(
                lambda: _absent(store, "configmaps", "root", "cm", "ns1"))
        finally:
            await ctrl.stop()

    asyncio.run(main())


def test_bare_store_does_not_stamp_finalizer():
    """Physical-cluster fakes / controller-less stores must not hold
    namespaces hostage: no stamping without namespace_lifecycle=True."""
    store = LogicalStore()
    client = MultiClusterClient(store)
    client.scoped("phys").create("namespaces", {"metadata": {"name": "plain"}})
    ns = store.get("namespaces", "phys", "plain")
    assert FINALIZER not in (ns["metadata"].get("finalizers") or [])
    client.scoped("phys").delete("namespaces", "plain")
    assert _absent(store, "namespaces", "phys", "plain")  # deletes instantly


def test_finalizered_content_defers_namespace_removal():
    async def main():
        store = LogicalStore(namespace_lifecycle=True)
        client = MultiClusterClient(store)
        ctrl = NamespaceLifecycleController(client)
        await ctrl.start()
        try:
            scoped = client.scoped("root")
            scoped.create("namespaces", {"metadata": {"name": "team-a"}})
            scoped.create(
                "configmaps",
                {"metadata": {"name": "held", "namespace": "team-a",
                              "finalizers": ["example.dev/hold"]}},
                namespace="team-a")
            await _settle(lambda: _has_finalizer(store, "root", "team-a"))

            scoped.delete("namespaces", "team-a")
            await asyncio.sleep(0.3)
            # held content -> namespace still terminating, not gone
            ns = store.get("namespaces", "root", "team-a")
            assert ns["metadata"].get("deletionTimestamp")
            held = store.get("configmaps", "root", "held", "team-a")
            assert held["metadata"].get("deletionTimestamp")

            # release the hold; everything drains
            held["metadata"]["finalizers"] = []
            scoped.update("configmaps", held, namespace="team-a")
            gone = await _settle(lambda: _absent(store, "namespaces", "root", "team-a"))
            assert gone
        finally:
            await ctrl.stop()

    asyncio.run(main())
