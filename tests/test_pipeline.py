"""Double-buffered tick pipeline: serial-vs-pipelined equivalence.

The pipelined loop ("double": 2-deep in-flight window, overlapped queue
drain, double-buffered wire staging) must be an OBSERVATIONALLY
invisible optimization: over an identical randomized churn schedule it
must emit the byte-identical patch stream the serial loop emits — no
reordered, duplicated, or dropped decisions — the same invariant the
differential fuzz family protects for the decision math itself. Plus
the lifecycle half: shutting down with steps in flight must deliver
every submitted tick's patches (stop drains the controller BEFORE the
in-flight wires, or the last window is silently lost).
"""

import asyncio

import numpy as np
import pytest

from kcp_tpu.syncer.core import PIPELINE_DEPTH, FusedCore

from helpers import wait_until

S = 16  # slot width (one shared bucket)


class RecordingOwner:
    """Open-loop SectionOwner: a fixed mirror array pair, every patch
    recorded, NO feedback — so both pipeline modes see an identical
    staging schedule and the patch streams are comparable byte for byte.
    (A closed loop would legitimately diverge: apply timing shifts which
    churn lands before which tick.)"""

    def __init__(self, core, b: int):
        self.core = core
        self.B = b
        mask = np.zeros(S, bool)
        mask[-2:] = True
        self._mask = mask
        self.up_vals = np.zeros((b, S), np.uint32)
        self.down_vals = np.zeros((b, S), np.uint32)
        self.stream: list[tuple[int, int, bool]] = []
        self.dispatches = 0
        self.section = core.register(self, S)

    def fused_status_mask(self) -> np.ndarray:
        return self._mask

    def fused_encode(self, key: int):
        return self.up_vals[key], True, self.down_vals[key], True

    def fused_encode_many(self, keys):
        idx = np.fromiter(keys, np.int64, len(keys))
        ones = np.ones(idx.size, bool)
        return self.up_vals[idx], ones, self.down_vals[idx], ones

    def fused_apply(self, patches) -> None:
        self.dispatches += 1
        self.stream.extend((int(k), int(c), bool(u)) for k, c, u in patches)

    def fused_overflow(self) -> None:  # pragma: no cover - fixed vocab
        raise AssertionError("pipeline fuzz vocabulary never grows")


def _stream_bytes(stream) -> bytes:
    return np.asarray(
        [(k, c, int(u)) for k, c, u in stream], np.int64).tobytes()


async def _run_schedule(pipeline: str, seed: int, rows: int = 512,
                        steps: int = 30) -> tuple[bytes, int]:
    """Drive one deterministic churn schedule in lockstep (one enqueued
    batch per tick) and return the fully-drained patch stream."""
    core = FusedCore(batch_window=0.0005, pipeline=pipeline)
    owner = RecordingOwner(core, rows)
    await core.start()
    bucket = owner.section.bucket
    rng = np.random.default_rng(seed)
    # churn pool < MIN_PATCH_CAPACITY so the level-triggered re-patches
    # never overflow the wire (overflow reticks at mode-dependent times,
    # which would legitimately fork the schedules)
    pool = 200
    for step in range(steps):
        n = int(rng.integers(1, 32))
        touched = rng.choice(pool, size=n, replace=False)
        owner.up_vals[touched] = rng.integers(
            1, 2**32, (n, S), dtype=np.uint32)
        before = bucket.stats["ticks"]
        self_keys = touched.tolist()
        core.enqueue_many(owner.section, False, self_keys)
        assert await wait_until(
            lambda: bucket.stats["ticks"] > before, 10), (
            f"{pipeline}: tick never ran for step {step}")
    await core.stop()
    # stop() must leave nothing in flight
    assert not core._inflight
    return _stream_bytes(owner.stream), bucket.stats["ticks"]


@pytest.mark.parametrize("seed", [1, 9, 27])
def test_pipelined_vs_serial_equivalence_fuzz(seed):
    """Byte-identical patch streams over a randomized churn schedule:
    pipelining must not reorder, duplicate, or drop decisions."""

    async def main():
        serial, serial_ticks = await _run_schedule("serial", seed)
        double, double_ticks = await _run_schedule("double", seed)
        # lockstep drove one staged batch per tick in both modes
        assert serial_ticks == double_ticks
        assert serial == double, (
            f"seed={seed}: pipelined patch stream diverged from serial "
            f"({len(serial)} vs {len(double)} bytes)")
        assert len(serial) > 0, "schedule produced no patches — vacuous"

    asyncio.run(main())


def test_shutdown_drains_inflight_steps():
    """No tick is lost with steps in flight: churn enqueued and never
    awaited must still deliver its patches through stop()'s shutdown
    drain (controller final ticks first, THEN the in-flight wires)."""

    async def main():
        core = FusedCore(batch_window=0.0005, pipeline="double")
        owner = RecordingOwner(core, 64)
        await core.start()
        touched = list(range(40))
        owner.up_vals[touched, 0] = 7  # diverge 40 rows
        core.enqueue_many(owner.section, False, touched)
        # stop IMMEDIATELY: the batch may not even have ticked yet; the
        # controller's shutdown drain must run it, and the wire it puts
        # in flight must be collected by stop's inflight drain
        await core.stop()
        assert not core._inflight
        patched = {k for k, _c, _u in owner.stream}
        assert patched.issuperset(touched), (
            f"lost {sorted(set(touched) - patched)} in shutdown")

    asyncio.run(main())


def test_serial_mode_never_leaves_wires_inflight():
    """pipeline="serial" is the A/B reference: every tick fetches its
    own wire before returning (depth 0), so nothing pipelines."""

    async def main():
        core = FusedCore(batch_window=0.0005, pipeline="serial")
        assert core.fetch_depth == 0
        assert not core.controller.overlap_drain
        owner = RecordingOwner(core, 64)
        await core.start()
        for step in range(5):
            owner.up_vals[step, 1] = step + 1
            before = owner.section.bucket.stats["ticks"]
            core.enqueue(owner.section, False, step)
            assert await wait_until(
                lambda: owner.section.bucket.stats["ticks"] > before, 10)
            assert not core._inflight, "serial mode left a wire in flight"
        await core.stop()

    asyncio.run(main())


def test_pipeline_modes_validated_and_metered():
    """Mode plumbing: bad modes rejected; the double-mode run exposes
    the per-stage occupancy metrics on the /metrics registry."""
    with pytest.raises(ValueError):
        FusedCore(pipeline="triple")

    async def main():
        core = FusedCore(batch_window=0.0005, pipeline="double")
        assert core.fetch_depth == PIPELINE_DEPTH
        assert core.controller.overlap_drain
        owner = RecordingOwner(core, 64)
        await core.start()
        bucket = owner.section.bucket
        for step in range(8):
            owner.up_vals[step, 1] = step + 1
            before = bucket.stats["ticks"]
            core.enqueue(owner.section, False, step)
            assert await wait_until(
                lambda: bucket.stats["ticks"] > before, 10)
        await core.stop()

    asyncio.run(main())
    from kcp_tpu.utils.trace import REGISTRY

    exposition = REGISTRY.expose()
    assert "fused_pipeline_depth_bucket" in exposition
    assert "fused_pipeline_window" in exposition
    # ticks ran through the fetch path, so exactly one of the ready/
    # blocked counters must have counted them
    assert ("fused_collect_ready_total" in exposition
            or "fused_collect_blocked_total" in exposition)
