"""Fleet-wide ragged batching: ragged-vs-per-bucket equivalence.

The fleet batch (syncer/core.py FleetBatch, KCP_FLEET_BATCH=1 default)
packs every schema bucket's rows into ONE pipelined device program per
tick. It must be an OBSERVATIONALLY invisible optimization: over an
identical seeded churn schedule spanning several buckets it must emit
byte-identical per-owner patch streams vs per-bucket dispatch (the
differential-fuzz contract every perf PR in this repo ships with), it
must preserve the PR 2 poison-row semantics (segment-scoped bisection
quarantining ONLY the poison rows), the PR 1 shutdown-drain ordering,
and it must feed the admission quota ledger from the device-side
per-segment counters.
"""

import asyncio

import numpy as np
import pytest

from kcp_tpu import faults
from kcp_tpu.syncer.core import FusedCore

from helpers import wait_until


class Owner:
    """Open-loop SectionOwner at a chosen slot width: fixed mirrors,
    every patch recorded, NO feedback — so fleet and per-bucket modes
    see identical staging schedules and streams compare byte-for-byte
    (the test_pipeline.py RecordingOwner pattern, width-parameterized)."""

    def __init__(self, core, b: int, s: int):
        self.core = core
        self.B, self.S = b, s
        mask = np.zeros(s, bool)
        mask[-2:] = True
        self._mask = mask
        self.up_vals = np.zeros((b, s), np.uint32)
        self.down_vals = np.zeros((b, s), np.uint32)
        self.stream: list[tuple[int, int, bool]] = []
        self.section = core.register(self, s)

    def fused_status_mask(self) -> np.ndarray:
        return self._mask

    def fused_encode(self, key: int):
        return self.up_vals[key], True, self.down_vals[key], True

    def fused_encode_many(self, keys):
        idx = np.fromiter(keys, np.int64, len(keys))
        ones = np.ones(idx.size, bool)
        return self.up_vals[idx], ones, self.down_vals[idx], ones

    def fused_apply(self, patches) -> None:
        self.stream.extend((int(k), int(c), bool(u)) for k, c, u in patches)

    def fused_overflow(self) -> None:  # pragma: no cover - fixed vocab
        raise AssertionError("fleet fuzz vocabulary never grows")


class LedgerOwner(Owner):
    """Owner that accounts to the quota ledger (the engine seam)."""

    def __init__(self, core, b, s, ledger_key):
        self._ledger_key = ledger_key
        super().__init__(core, b, s)

    def fused_ledger_key(self):
        return self._ledger_key


def _stream_bytes(stream) -> bytes:
    return np.asarray(
        [(k, c, int(u)) for k, c, u in stream], np.int64).tobytes()


WIDTHS = (16, 32)  # two slot widths -> two schema buckets


async def _run_schedule(fleet: bool, seed: int, rows: int = 256,
                        steps: int = 15, mesh=None,
                        straggler_rows: int = 3):
    """Drive one deterministic multi-bucket churn schedule in lockstep
    (all owners enqueue, then wait for every bucket to tick once) and
    return per-owner fully-drained patch streams + stats."""
    core = FusedCore(batch_window=0.0005, pipeline="double", fleet=fleet,
                     mesh=mesh)
    owners = [Owner(core, rows, w) for w in WIDTHS]
    # a 1-4-row straggler section sharing the narrow bucket: the ragged
    # case the fleet batch exists for
    straggler = Owner(core, straggler_rows, WIDTHS[0])
    owners.append(straggler)
    await core.start()
    buckets = list({id(o.section.bucket): o.section.bucket for o in owners}
                   .values())
    assert len(buckets) == len(WIDTHS), "widths must map to distinct buckets"
    rng = np.random.default_rng(seed)
    pool = 100  # < patch capacity so level-triggered re-patches never overflow
    for step in range(steps):
        before = {id(b): b.stats["ticks"] for b in buckets}
        for o in owners:
            hi = min(pool, o.B)
            n = int(rng.integers(1, min(16, hi + 1)))
            touched = rng.choice(hi, size=n, replace=False)
            o.up_vals[touched] = rng.integers(
                1, 2**32, (n, o.S), dtype=np.uint32)
            core.enqueue_many(o.section, False, touched.tolist())
        assert await wait_until(
            lambda: all(b.stats["ticks"] > before[id(b)] for b in buckets),
            10), f"fleet={fleet}: tick never ran for step {step}"
    await core.stop()
    assert not core._inflight
    return ([_stream_bytes(o.stream) for o in owners],
            [dict(b.stats) for b in buckets],
            dict(core._fleet.stats) if core._fleet is not None else None)


@pytest.mark.parametrize("seed", [2, 11, 29])
def test_ragged_vs_per_bucket_differential_fuzz(seed):
    """Byte-identical per-owner patch streams across several buckets
    (including a 3-row straggler section): fleet packing must not
    reorder, duplicate, drop, or cross-route decisions."""

    async def main():
        per_bucket, pb_stats, _ = await _run_schedule(False, seed)
        ragged, rg_stats, fleet_stats = await _run_schedule(True, seed)
        for i, (a, b) in enumerate(zip(per_bucket, ragged)):
            assert a == b, (
                f"seed={seed}: owner {i} stream diverged "
                f"({len(a)} vs {len(b)} bytes)")
        assert any(len(s) > 0 for s in ragged), "no patches — vacuous"
        # the lockstep drove one staged batch per tick in both modes
        assert [s["ticks"] for s in pb_stats] == [s["ticks"] for s in rg_stats]
        # and the whole fleet rode ONE dispatch per tick, not one per bucket
        assert fleet_stats["ticks"] == rg_stats[0]["ticks"]

    asyncio.run(main())


def test_fleet_on_mesh_matches_unsharded_fleet():
    """The same schedule on an 8-device (virtual) tenants mesh emits the
    byte-identical streams the single-device fleet emits, and the fleet
    state actually carries the canonical row sharding."""
    from kcp_tpu.parallel.mesh import SLOTS_AXIS, TENANTS_AXIS, make_mesh

    async def main():
        single, _, _ = await _run_schedule(True, seed=5)
        mesh = make_mesh(n_devices=8, tenants=8, slots=1)
        core = FusedCore(batch_window=0.0005, pipeline="double", fleet=True,
                         mesh=mesh)
        owners = [Owner(core, 256, w) for w in WIDTHS]
        straggler = Owner(core, 3, WIDTHS[0])
        owners.append(straggler)
        await core.start()
        buckets = list({id(o.section.bucket): o.section.bucket
                        for o in owners}.values())
        rng = np.random.default_rng(5)
        for step in range(15):
            before = {id(b): b.stats["ticks"] for b in buckets}
            for o in owners:
                hi = min(100, o.B)
                n = int(rng.integers(1, min(16, hi + 1)))
                touched = rng.choice(hi, size=n, replace=False)
                o.up_vals[touched] = rng.integers(
                    1, 2**32, (n, o.S), dtype=np.uint32)
                core.enqueue_many(o.section, False, touched.tolist())
            assert await wait_until(
                lambda: all(b.stats["ticks"] > before[id(b)]
                            for b in buckets), 15)
        spec = core._fleet._state.up_vals.sharding.spec
        assert tuple(spec) == (TENANTS_AXIS, SLOTS_AXIS), spec
        # fleet rows pad to the row factor: 8-way mesh -> B % 8 == 0
        assert core._fleet.B % 8 == 0 and core._fleet.B > 0
        await core.stop()
        meshed = [_stream_bytes(o.stream) for o in owners]
        assert meshed == single, "mesh-sharded fleet diverged from single-device"

    asyncio.run(main())


# ---------------------------------------------------------------------------
# poison-row quarantine: segment-scoped bisection
# ---------------------------------------------------------------------------


def test_fleet_poison_quarantine_is_segment_scoped(monkeypatch):
    """device.step:poison_row=3 poisons bucket-LOCAL row 3 — the same
    rows a per-bucket schedule would poison. The fleet bisection must
    isolate within segments and quarantine ONLY those rows: every
    co-tenant in every bucket still converges."""
    # keep the wall-clock requeue backoff out of the run
    monkeypatch.setattr("kcp_tpu.syncer.core.QUARANTINE_BASE_BACKOFF", 0.001)

    async def main():
        faults.install(faults.FaultInjector("device.step:poison_row=3",
                                            seed=0))
        try:
            core = FusedCore(batch_window=0.0005, pipeline="double",
                             fleet=True)
            owners = [Owner(core, 64, w) for w in WIDTHS]
            await core.start()
            fleet = core._fleet
            keys = list(range(30))
            for o in owners:
                o.up_vals[keys, 0] = 7  # diverge rows 0..29 in BOTH buckets
                core.enqueue_many(o.section, False, keys)
            # the poisoned fleet submission fails, retries once (full
            # re-upload, fails again), bisects BY SEGMENT, and
            # quarantines only local row 3 of each poisoned bucket
            assert await wait_until(
                lambda: fleet.stats["quarantined"] >= 2, 30), (
                "fleet never quarantined both buckets' poison rows")
            for i, o in enumerate(owners):
                assert await wait_until(
                    lambda o=o: {k for k, _c, _u in o.stream}
                    >= set(keys) - {3}, 30), (
                    f"owner {i} co-tenants stalled")
                assert 3 not in {k for k, _c, _u in o.stream}
                assert o.section.bucket.stats["quarantined"] >= 1
            assert fleet.stats["step_failures"] >= 2
            # lifting the fault lets the level-triggered loop recover
            # the quarantined keys (requeued with backoff)
            faults.clear()
            for o in owners:
                assert await wait_until(
                    lambda o=o: 3 in {k for k, _c, _u in o.stream}, 30), (
                    "quarantined key never recovered after the fault cleared")
            await core.stop()
        finally:
            faults.clear()

    asyncio.run(main())


def test_fleet_systemic_failure_still_propagates():
    """A row-independent failure (the empty probe fails too) must not be
    eaten by segment quarantine: after the single wholesale retry it
    surfaces, and the loop survives."""

    async def main():
        faults.install(faults.FaultInjector("device.step:raise", seed=0))
        try:
            core = FusedCore(batch_window=0.0005, pipeline="serial",
                             fleet=True)
            owner = Owner(core, 64, 16)
            await core.start()
            owner.up_vals[0, 0] = 1
            before = core._fleet.stats["step_failures"]
            core.enqueue(owner.section, False, 0)
            assert await wait_until(
                lambda: core._fleet.stats["step_failures"] >= before + 2, 30)
            assert core._fleet.stats["quarantined"] == 0
            faults.clear()
            owner.up_vals[1, 0] = 2
            core.enqueue(owner.section, False, 1)
            assert await wait_until(
                lambda: 1 in {k for k, _c, _u in owner.stream}, 30)
            await core.stop()
        finally:
            faults.clear()

    asyncio.run(main())


# ---------------------------------------------------------------------------
# shutdown drain
# ---------------------------------------------------------------------------


def test_fleet_shutdown_drains_inflight_window():
    """No tick is lost with fleet wires in flight: churn across several
    buckets enqueued and never awaited must still deliver every owner's
    patches through stop()'s shutdown drain (PR 1 ordering: controller
    final ticks first, THEN the in-flight fleet wires)."""

    async def main():
        core = FusedCore(batch_window=0.0005, pipeline="double", fleet=True)
        owners = [Owner(core, 64, w) for w in WIDTHS]
        await core.start()
        touched = list(range(40))
        for o in owners:
            o.up_vals[touched, 0] = 9
            core.enqueue_many(o.section, False, touched)
        await core.stop()
        assert not core._inflight
        for i, o in enumerate(owners):
            patched = {k for k, _c, _u in o.stream}
            assert patched.issuperset(touched), (
                f"owner {i} lost {sorted(set(touched) - patched)} in shutdown")

    asyncio.run(main())


# ---------------------------------------------------------------------------
# device-side per-segment counters -> quota ledger
# ---------------------------------------------------------------------------


def test_fleet_segment_counts_feed_quota_ledger():
    """The fused step's per-segment live-row counts reach the attached
    quota ledger (admission accounting rides the batch), agree with the
    ledger's usage when accounting is correct, and flag drift when not."""
    from kcp_tpu.admission.quota import QuotaLedger

    async def main():
        ledger = QuotaLedger()
        core = FusedCore(batch_window=0.0005, fleet=True)
        core.ledger = ledger
        o1 = LedgerOwner(core, 64, 16, ("c1", "configmaps"))
        o2 = LedgerOwner(core, 64, 32, ("c2", "widgets"))
        await core.start()
        o1.up_vals[:10, 0] = 1
        o2.up_vals[:4, 0] = 1
        core.enqueue_many(o1.section, False, list(range(10)))
        core.enqueue_many(o2.section, False, list(range(4)))
        assert await wait_until(
            lambda: ledger.device_usage_of("c1", "configmaps") == 10
            and ledger.device_usage_of("c2", "widgets") == 4, 10), (
            ledger.snapshot())
        # ledger usage agrees -> the recount fast path may skip the host
        # walk for limited keys
        for _ in range(10):
            ledger.record("configmaps", "c1", +1)
        for _ in range(4):
            ledger.record("widgets", "c2", +1)
        ledger.set_hard("c1", "configmaps", 100)
        ledger.set_hard("c2", "widgets", 100)
        # a fresh tick re-reports the counts after the limits landed
        core.enqueue(o1.section, False, 0)
        await asyncio.sleep(0.05)
        assert ledger.device_counts_agree(60.0)
        # drift (an uncounted write) breaks agreement -> host recount runs
        ledger.record("configmaps", "c1", +1)
        assert not ledger.device_counts_agree(60.0)
        await core.stop()

    asyncio.run(main())


def test_fleet_patch_overflow_doubles_member_capacity():
    """Fleet overflow pools member budgets: overflow doubles every
    member's patch capacity and the level-triggered retick converges."""

    async def main():
        core = FusedCore(batch_window=0.0005, fleet=True)
        owners = [Owner(core, 64, w) for w in WIDTHS]
        for o in owners:
            o.section.bucket.patch_capacity = 8  # force overflow
        await core.start()
        keys = list(range(40))
        for o in owners:
            o.up_vals[keys, 0] = 3
            core.enqueue_many(o.section, False, keys)
        for o in owners:
            assert await wait_until(
                lambda o=o: {k for k, _c, _u in o.stream} >= set(keys), 30)
        assert core._fleet.stats["overflows"] >= 1
        assert all(o.section.bucket.patch_capacity > 8 for o in owners)
        await core.stop()

    asyncio.run(main())
