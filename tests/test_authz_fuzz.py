"""RBAC escalation property fuzz: no sequence of ADMITTED writes can
grow the fleet's permission union.

The escalation check (server/authz.py, Kubernetes' RBAC escalation
prevention) admits a clusterrole/clusterrolebinding write only when the
writer already holds what the write grants (or the escalate/bind verbs).
The security property that should FOLLOW from per-write checks is
global: starting from admin's initial grants, random sequences of
admitted non-admin writes may SPREAD permissions between users (granting
what you hold is delegation) but must never mint a permission triple
nobody held — and a user's own admitted write must never enlarge that
user's own effective set. Both are checked over a concrete probe matrix
after every admitted write.

The property is only true in a delegation-only world: the ``escalate``
and ``bind`` verbs (and ``*``, which implies them) are Kubernetes'
DESIGNED escalation bypasses — a user holding them may legitimately
mint. So the bootstrap and the fuzz's generated rules draw from the
non-bypass verb pool, while the PROBE matrix still includes
escalate/bind/*: the strongest form of the property is that those
verbs never get minted for anyone.
"""

import itertools
import random

from kcp_tpu.server.authz import BINDINGS, CLUSTERROLES, Authorizer
from kcp_tpu.store import LogicalStore

CLUSTER = "team-a"
USERS = ["u1", "u2", "u3"]
# probe verbs include the bypass verbs; GRANT verbs exclude them (see
# module docstring — holding escalate/bind/* legitimately mints)
VERBS = ["get", "list", "create", "update", "delete", "escalate", "bind", "*"]
GRANT_VERBS = ["get", "list", "create", "update", "delete"]
GROUPS = ["", "rbac.authorization.k8s.io", "apps"]
RESOURCES = ["configmaps", "clusterroles", "clusterrolebindings",
             "deployments", "widgets"]
PROBES = [(v, g, r) for v in VERBS for g in GROUPS for r in RESOURCES]
RBAC_GROUP = "rbac.authorization.k8s.io"


def _effective(authz: Authorizer, user: str) -> frozenset:
    return frozenset(p for p in PROBES
                     if authz.allowed(user, CLUSTER, *p))


def _rand_rules(rng: random.Random) -> list[dict]:
    rules = []
    for _ in range(rng.randrange(1, 3)):
        rules.append({
            "verbs": rng.sample(GRANT_VERBS, rng.randrange(1, 3)),
            "apiGroups": rng.sample(GROUPS, rng.randrange(1, 3)),
            "resources": rng.sample(RESOURCES, rng.randrange(1, 3)),
        })
    return rules


def _admit(authz: Authorizer, user: str, resource_short: str,
           body: dict) -> bool:
    """Mirror the REST handler's gate: verb RBAC + escalation check."""
    if not authz.allowed(user, CLUSTER, "create", RBAC_GROUP,
                         resource_short):
        return False
    return authz.escalation_denied(user, CLUSTER, resource_short,
                                   body) is None


def test_admitted_writes_never_mint_permissions():
    total_admitted = 0
    for seed in range(12):
        rng = random.Random(seed)
        store = LogicalStore()
        authz = Authorizer(store)
        # admin bootstrap: random roles, randomly bound to users — always
        # including write access to rbac objects for at least one user so
        # the fuzz has an interesting actor
        names = itertools.count()
        for i in range(rng.randrange(2, 5)):
            role = f"boot-{i}"
            rules = _rand_rules(rng)
            if i == 0:
                rules.append({"verbs": ["create", "update"],
                              "apiGroups": [RBAC_GROUP],
                              "resources": ["clusterroles",
                                            "clusterrolebindings"]})
            store.create(CLUSTERROLES, CLUSTER,
                         {"metadata": {"name": role}, "rules": rules})
            for u in rng.sample(USERS, rng.randrange(1, len(USERS) + 1)):
                store.create(BINDINGS, CLUSTER, {
                    "metadata": {"name": f"bind-{next(names)}"},
                    "subjects": [{"kind": "User", "name": u}],
                    "roleRef": {"name": role},
                })

        union0 = frozenset().union(*(_effective(authz, u) for u in USERS))
        admitted = 0
        for step in range(40):
            user = rng.choice(USERS)
            before_self = _effective(authz, user)
            if rng.random() < 0.5:
                body = {"metadata": {"name": f"r-{next(names)}"},
                        "rules": _rand_rules(rng)}
                ok = _admit(authz, user, "clusterroles", body)
                if ok:
                    store.create(CLUSTERROLES, CLUSTER, body)
            else:
                target_role = rng.choice(
                    [o["metadata"]["name"]
                     for o in store.list(CLUSTERROLES, CLUSTER)[0]]
                    + ["cluster-admin", "ghost-role"])
                body = {"metadata": {"name": f"b-{next(names)}"},
                        "subjects": [{"kind": "User",
                                      "name": rng.choice(USERS)}],
                        "roleRef": {"name": target_role}}
                ok = _admit(authz, user, "clusterrolebindings", body)
                if ok:
                    store.create(BINDINGS, CLUSTER, body)
            if not ok:
                continue
            admitted += 1
            # 1. the writer's own set never grows from their own write
            after_self = _effective(authz, user)
            assert after_self - before_self == frozenset(), (
                seed, step, user, sorted(after_self - before_self))
            # 2. the fleet union never exceeds the bootstrap union
            union = frozenset().union(
                *(_effective(authz, u) for u in USERS))
            assert union <= union0, (
                seed, step, user, sorted(union - union0))
        total_admitted += admitted
    # the fuzz must actually admit writes to mean anything (aggregate:
    # individual seeds may bootstrap stingy grants)
    assert total_admitted >= 18, f"only {total_admitted} admitted writes"
