"""Encode-once serving: cached vs uncached byte-identity.

The encode-once stack (KCP_ENCODE_CACHE=1: per-snapshot byte cache,
per-bucket list spans, RV-keyed list bodies, shared watch-event lines)
must serve wires byte-identical to the per-call ``json.dumps`` path
(KCP_ENCODE_CACHE=0). The differential fuzz drives two full
RestHandler+LogicalStore stacks side-by-side through random REST traffic
and compares every observable: response status + body bytes for lists
(repeated at the same RV, churned, selector-filtered, namespaced,
wildcard), single GETs, status-subresource reads, and watch streams
(live ADDED/MODIFIED/DELETED, selector-rewrite events, ``since_rv``
replay) — including under an active ``encode.cache`` fault schedule that
force-drops cached entries mid-serve.

Also pins the cache's safety contract (a cached body never reflects a
later write) and the RestWatch chunk reassembly satellite (multi-event
chunks decoded once and split, surviving arbitrary chunk boundaries).
"""

import asyncio
import json
import random

import pytest

from kcp_tpu import faults
from kcp_tpu.apis.scheme import default_scheme
from kcp_tpu.server.handler import RestHandler
from kcp_tpu.server.httpd import Request
from kcp_tpu.server.rest import RestWatch
from kcp_tpu.store.store import LogicalStore
from kcp_tpu.utils.trace import REGISTRY

CLUSTERS = ("c0", "c1", "c2")
NAMESPACES = ("ns0", "ns1")
NAMES = tuple(f"n{i}" for i in range(6))
LABELS = [None, {"team": "a"}, {"team": "b"},
          {"team": "a", "tier": "web"}, {"tier": "db"}]


def _req(method, path, query=None, body=None):
    payload = b"" if body is None else json.dumps(body).encode()
    return Request(method, path, query or {}, {}, payload)


def _cm(name, ns, v, labels=None, finalizers=None):
    meta = {"name": name, "namespace": ns, "uid": f"uid-{name}-{ns}"}
    if labels:
        meta["labels"] = dict(labels)
    if finalizers:
        meta["finalizers"] = list(finalizers)
    return {"apiVersion": "v1", "kind": "ConfigMap", "metadata": meta,
            "data": {"v": v}}


class _Sink:
    """The StreamResponse encode surface without a socket: json sends
    serialize exactly like httpd.StreamResponse, the raw send takes the
    relay's pre-encoded lines — so comparing accumulated bytes between
    the cached (raw) and uncached (json) stacks proves wire identity."""

    def __init__(self):
        self.data = b""

    async def send_json(self, obj):
        self.data += json.dumps(obj).encode() + b"\n"

    async def send_json_many(self, objs):
        self.data += b"".join(json.dumps(o).encode() + b"\n" for o in objs)

    async def send_raw_many(self, lines):
        self.data += b"".join(lines)


class _Stack:
    def __init__(self, encode_cache: bool):
        self.store = LogicalStore(indexed=True, encode_cache=encode_cache,
                                  clock=lambda: 1_700_000_000.0)
        self.handler = RestHandler(self.store, default_scheme(),
                                   admission=None)


class _Pair:
    """The same REST request executed against both stacks, every
    response compared byte-for-byte."""

    def __init__(self):
        self.stacks = (_Stack(True), _Stack(False))

    async def call(self, method, path, query=None, body=None):
        out = []
        for st in self.stacks:
            resp = await st.handler(_req(method, path, query, body))
            out.append((resp.status, resp.body))
        (sa, ba), (sb, bb) = out
        assert sa == sb, (method, path, query, sa, sb, ba, bb)
        assert ba == bb, (method, path, query, sa, ba, bb)
        return out[0]

    def path(self, cluster, ns=None, name=None, sub=None):
        p = f"/clusters/{cluster}/api/v1"
        if ns:
            p += f"/namespaces/{ns}"
        p += "/configmaps"
        if name:
            p += f"/{name}"
        if sub:
            p += f"/{sub}"
        return p


def _rand_op(pair, rng, counter):
    cluster = rng.choice(CLUSTERS)
    ns = rng.choice(NAMESPACES)
    name = rng.choice(NAMES)
    roll = rng.random()
    if roll < 0.4:
        counter[0] += 1
        obj = _cm(name, ns, str(counter[0]), rng.choice(LABELS),
                  ["t.dev/hold"] if rng.random() < 0.15 else None)
        obj["metadata"]["uid"] = f"uid-{counter[0]}"
        return ("POST", pair.path(cluster, ns), None, obj)
    if roll < 0.75:
        # update from the cached stack's current state (stacks agree
        # inductively); relabels force the selector-rewrite fan-out
        obj = _cm(name, ns, f"u{counter[0]}", rng.choice(LABELS))
        counter[0] += 1
        if rng.random() < 0.25:
            obj["status"] = {"phase": rng.choice(["Ready", "Pending"])}
            return ("PUT", pair.path(cluster, ns, name, "status"), None, obj)
        return ("PUT", pair.path(cluster, ns, name), None, obj)
    return ("DELETE", pair.path(cluster, ns, name), None, None)


async def _fuzz(seed, steps=220):
    rng = random.Random(seed)
    pair = _Pair()
    counter = [0]
    for _step in range(steps):
        method, path, query, body = _rand_op(pair, rng, counter)
        # PUTs need the live resourceVersion: read it through the
        # handler (GETs are compared too) and graft it onto the body
        if method == "PUT" and body is not None:
            status, raw = await pair.call("GET", path.removesuffix("/status"))
            if status != 200:
                continue
            current = json.loads(raw)
            body["metadata"]["resourceVersion"] = (
                current["metadata"]["resourceVersion"])
            body["metadata"]["uid"] = current["metadata"]["uid"]
        await pair.call(method, path, query, body)
        if rng.random() < 0.25:
            cluster = rng.choice(("*",) + CLUSTERS)
            q = {}
            if rng.random() < 0.5:
                q["labelSelector"] = [rng.choice(
                    ["team=a", "team!=a", "tier in (web,db)", "!team"])]
            ns = rng.choice((None,) + NAMESPACES)
            lp = pair.path(cluster, ns)
            # twice at the same RV: the second serve must come out of
            # the RV-keyed body cache on the cached stack, byte-equal
            await pair.call("GET", lp, q)
            await pair.call("GET", lp, q)
        if rng.random() < 0.15:
            await pair.call(
                "GET", pair.path(rng.choice(CLUSTERS), rng.choice(NAMESPACES),
                                 rng.choice(NAMES)))
    # final exhaustive sweep
    for cluster in ("*",) + CLUSTERS:
        for ns in (None,) + NAMESPACES:
            await pair.call("GET", pair.path(cluster, ns))
    for st in pair.stacks:
        st.store.close()
        st.handler.close()


@pytest.mark.parametrize("seed", [7, 23, 91])
def test_rest_serving_byte_identical_fuzz(seed):
    asyncio.run(_fuzz(seed))


def test_rest_serving_byte_identical_under_cache_faults():
    """encode.cache drops force mid-serve re-encodes; the wire must not
    change by a byte, and the drops must actually fire."""
    faults.install(faults.FaultInjector("encode.cache:drop=0.4", seed=5))
    try:
        before = REGISTRY.counter("fault_injected_encode_cache_total").value
        asyncio.run(_fuzz(13, steps=120))
        fired = (REGISTRY.counter("fault_injected_encode_cache_total").value
                 - before)
        assert fired > 0, "encode.cache fault schedule never fired"
    finally:
        faults.clear()


async def _watch_stream_bytes(seed):
    rng = random.Random(seed)
    pair = _Pair()
    specs = [
        ({}, None),                                   # everything
        ({"labelSelector": ["team=a"]}, None),        # eq fast path + rewrites
        ({"labelSelector": ["team in (a,b),tier!=db"]}, None),
        ({}, "ns0"),                                  # namespaced scope
    ]
    sinks = {0: [], 1: []}
    tasks = []
    for si, st in enumerate(pair.stacks):
        for q, ns in specs:
            query = dict(q)
            query["watch"] = ["true"]
            p = "/clusters/*/api/v1"
            if ns:
                p += f"/namespaces/{ns}"
            p += "/configmaps"
            stream = await st.handler(_req("GET", p, query))
            sink = _Sink()
            sinks[si].append(sink)
            tasks.append(asyncio.ensure_future(stream.producer(sink)))
    await asyncio.sleep(0.01)  # all producers subscribed

    counter = [0]
    for _step in range(120):
        method, path, query, body = _rand_op(pair, rng, counter)
        if method == "PUT" and body is not None:
            status, raw = await pair.call("GET", path.removesuffix("/status"))
            if status != 200:
                continue
            current = json.loads(raw)
            body["metadata"]["resourceVersion"] = (
                current["metadata"]["resourceVersion"])
            body["metadata"]["uid"] = current["metadata"]["uid"]
        await pair.call(method, path, query, body)
        if _step % 16 == 15:
            await asyncio.sleep(0)  # let the relays drain
    # drain everything, then close the stores to end the producers
    for _ in range(3):
        await asyncio.sleep(0.01)
    for st in pair.stacks:
        st.store.close()
    await asyncio.gather(*tasks, return_exceptions=True)
    for i, (cached, uncached) in enumerate(zip(sinks[0], sinks[1])):
        assert cached.data == uncached.data, f"watch stream {i} diverged"
    assert any(s.data for s in sinks[0]), "streams delivered nothing"
    for st in pair.stacks:
        st.handler.close()
    return pair


@pytest.mark.parametrize("seed", [3, 17])
def test_watch_stream_bytes_identical(seed):
    asyncio.run(_watch_stream_bytes(seed))


def test_watch_stream_bytes_identical_under_cache_faults():
    faults.install(faults.FaultInjector("encode.cache:drop=0.3", seed=9))
    try:
        asyncio.run(_watch_stream_bytes(29))
    finally:
        faults.clear()


async def _since_rv_replay_bytes():
    pair = _Pair()
    # scripted history: creates, a label flip (selector rewrite), a
    # status write, a finalizer-held delete, a real delete
    for st in pair.stacks:
        s = st.store
        s.create("configmaps", "c0", _cm("a", "ns0", "1", {"team": "a"}))
        s.create("configmaps", "c0", _cm("b", "ns0", "2", {"team": "b"}))
        obj = s.get("configmaps", "c0", "b", "ns0")
        obj["metadata"]["labels"] = {"team": "a"}
        s.update("configmaps", "c0", obj, "ns0")
        obj = s.get("configmaps", "c0", "a", "ns0")
        obj["status"] = {"phase": "Ready"}
        s.update_status("configmaps", "c0", obj, "ns0")
        s.delete("configmaps", "c0", "a", "ns0")
    for since in (0, 1, 3):
        for q in ({}, {"labelSelector": ["team=a"]}):
            outs = []
            for st in pair.stacks:
                query = dict(q)
                query["watch"] = ["true"]
                query["resourceVersion"] = [str(since)]
                query["timeoutSeconds"] = ["0.3"]
                stream = await st.handler(
                    _req("GET", "/clusters/*/api/v1/configmaps", query))
                sink = _Sink()
                await stream.producer(sink)
                outs.append(sink.data)
            assert outs[0] == outs[1], (since, q)
            assert since > 3 or outs[0], "replay produced nothing"
    for st in pair.stacks:
        st.store.close()
        st.handler.close()


def test_since_rv_replay_bytes_identical():
    asyncio.run(_since_rv_replay_bytes())


def test_cached_body_never_reflects_later_write():
    """Mutation safety: bytes handed out for a snapshot stay frozen; the
    write replaces the snapshot, so the next encode sees the new state
    and the old bytes still parse to the old state."""
    s = LogicalStore(indexed=True, encode_cache=True)
    s.create("configmaps", "t", _cm("x", "d", "old"))
    snap = s.get_snapshot("configmaps", "t", "x", "d")
    b1 = s.encode_obj(snap)
    obj = s.get("configmaps", "t", "x", "d")
    obj["data"] = {"v": "new"}
    s.update("configmaps", "t", obj, "d")
    b2 = s.encode_obj(s.get_snapshot("configmaps", "t", "x", "d"))
    assert json.loads(b1)["data"] == {"v": "old"}
    assert json.loads(b2)["data"] == {"v": "new"}
    # the retained old snapshot still serves its own (old) bytes
    assert s.encode_obj(snap) == b1
    s.close()


def test_rv_keyed_list_cache_invalidates_on_write():
    async def main():
        st = _Stack(True)
        st.store.create("configmaps", "t", _cm("x", "d", "1"))
        r1 = await st.handler(_req("GET", "/clusters/t/api/v1/configmaps"))
        r2 = await st.handler(_req("GET", "/clusters/t/api/v1/configmaps"))
        assert r1.body == r2.body  # same RV: served from the body cache
        obj = st.store.get("configmaps", "t", "x", "d")
        obj["data"] = {"v": "2"}
        st.store.update("configmaps", "t", obj, "d")
        r3 = await st.handler(_req("GET", "/clusters/t/api/v1/configmaps"))
        assert r3.body != r1.body
        assert json.loads(r3.body)["items"][0]["data"] == {"v": "2"}
        st.store.close()
        st.handler.close()

    asyncio.run(main())


def test_encode_cache_metrics_count_hits_and_misses():
    hits0 = REGISTRY.counter("encode_cache_hits_total").value
    miss0 = REGISTRY.counter("encode_cache_misses_total").value
    shared0 = REGISTRY.counter("encode_cache_bytes_shared_total").value
    s = LogicalStore(indexed=True, encode_cache=True)
    s.create("configmaps", "t", _cm("x", "d", "1"))
    snap = s.get_snapshot("configmaps", "t", "x", "d")
    b = s.encode_obj(snap)
    assert REGISTRY.counter("encode_cache_misses_total").value == miss0 + 1
    assert s.encode_obj(snap) is b
    assert REGISTRY.counter("encode_cache_hits_total").value == hits0 + 1
    assert (REGISTRY.counter("encode_cache_bytes_shared_total").value
            == shared0 + len(b))
    s.close()


def test_encode_disabled_keeps_plain_dumps():
    s = LogicalStore(indexed=True, encode_cache=False)
    assert not s.encode_cache_enabled
    s.create("configmaps", "t", _cm("x", "d", "1"))
    snap = s.get_snapshot("configmaps", "t", "x", "d")
    assert s.encode_obj(snap) == json.dumps(snap).encode()
    assert not s._enc_bytes  # nothing cached when disabled
    s.close()


# ------------------------------------------------- RestWatch reassembly


def _watch_lines(n=3):
    lines = []
    for i in range(1, n + 1):
        lines.append(json.dumps({
            "type": "ADDED",
            "object": {"metadata": {"name": f"obj-é{i}",
                                    "clusterName": "c", "namespace": "ns",
                                    "resourceVersion": str(i)}},
        }, ensure_ascii=False))
    return lines


def _drain_events(rw):
    out = []
    while not rw._events.empty():
        out.append(rw._events.get_nowait())
    return out


def test_restwatch_multi_event_chunk_single_split():
    """A relay burst (send_raw_many/send_json_many) arrives as ONE chunk
    holding many newline-terminated events: one decode, one split."""
    rw = RestWatch("127.0.0.1", 1, "/w", "configmaps")
    chunk = ("\n".join(_watch_lines(3)) + "\n").encode()
    rw._feed(chunk)
    evs = _drain_events(rw)
    assert [(e.type, e.name, e.rv) for e in evs] == [
        ("ADDED", "obj-é1", 1),
        ("ADDED", "obj-é2", 2),
        ("ADDED", "obj-é3", 3),
    ]
    assert rw._buf == ""


def test_restwatch_chunks_survive_arbitrary_boundaries():
    """Every possible chunk boundary — including ones splitting a
    multi-byte UTF-8 sequence — reassembles the same events."""
    payload = ("\n".join(_watch_lines(2)) + "\n").encode()
    for cut in range(1, len(payload)):
        rw = RestWatch("127.0.0.1", 1, "/w", "configmaps")
        rw._feed(payload[:cut])
        rw._feed(payload[cut:])
        evs = _drain_events(rw)
        assert [(e.name, e.rv) for e in evs] == [
            ("obj-é1", 1), ("obj-é2", 2)], f"boundary {cut}"


def test_restwatch_partial_line_carries_over():
    rw = RestWatch("127.0.0.1", 1, "/w", "configmaps")
    line = _watch_lines(1)[0]
    rw._feed(line[:10].encode())
    assert _drain_events(rw) == []
    rw._feed((line[10:] + "\n").encode())
    evs = _drain_events(rw)
    assert [(e.name, e.rv) for e in evs] == [("obj-é1", 1)]
