"""Randomized differential fuzz: tpu and host backends must converge a
random op sequence to IDENTICAL state.

The fixed-pattern differential test (test_syncer_e2e) covers the happy
paths; this drives seeded random interleavings of the whole op
vocabulary — create (labeled and unlabeled), update, delete, label
flip-off/flip-on (placement unassign/assign), and downstream status
writes (upsync) — and asserts both backends land on byte-identical
converged state. A short resync period is part of the scenario: racing
ops legitimately exhaust some keys' apply-retry budgets (the
reference's 5-retries-then-drop), and the informer resync is the
mechanism that heals the drops — the fuzz proves that recovery path
end to end. Any divergence is a decision-lane bug by construction:
the backends share the store, informers, and applier; only the decision
math differs (SURVEY.md §7.1's differential-testing seam).
"""

import asyncio
import random

import pytest

from kcp_tpu.client import Client
from kcp_tpu.store import LogicalStore
from kcp_tpu.syncer import start_syncer
from kcp_tpu.syncer.engine import CLUSTER_LABEL

from helpers import wait_until as _wait_until

POOL = 24  # distinct object names
OPS = 120


def _cm(name, v, labeled=True):
    labels = {CLUSTER_LABEL: "c1"} if labeled else {}
    return {"apiVersion": "v1", "kind": "ConfigMap",
            "metadata": {"name": name, "namespace": "default",
                         "labels": labels},
            "data": {"v": str(v)}}


async def _run_backend(backend: str, seed: int, mesh=None, datafn=None,
                       disrupt=None):
    """``datafn(rng, step) -> data dict`` shapes update payloads (the
    schema-evolution family grows the field vocabulary through it);
    ``disrupt(kcp, syncer)`` fires once mid-sequence (the
    compaction/watch-drop family)."""
    rng = random.Random(seed)
    kcp, phys = LogicalStore(), LogicalStore()
    up, down = Client(kcp, "t"), Client(phys, "p")
    syncer = await start_syncer(up, down, ["configmaps"], "c1",
                                backend=backend, resync_period=1.5,
                                mesh=mesh)
    for step in range(OPS):
        if disrupt is not None and step == OPS // 2:
            disrupt(kcp, syncer)
        name = f"cm-{rng.randrange(POOL)}"
        op = rng.random()
        try:
            if op < 0.30:
                o = _cm(name, step, labeled=rng.random() < 0.85)
                if datafn is not None:
                    o["data"] = datafn(rng, step)
                up.create("configmaps", o)
            elif op < 0.55:
                o = up.get("configmaps", name, "default")
                o["data"] = (datafn(rng, step) if datafn is not None
                             else {"v": str(step)})
                up.update("configmaps", o)
            elif op < 0.70:
                up.delete("configmaps", name, "default")
            elif op < 0.85:
                # label flip: unassign or (re)assign placement
                o = up.get("configmaps", name, "default")
                labels = o["metadata"].get("labels") or {}
                if CLUSTER_LABEL in labels:
                    labels.pop(CLUSTER_LABEL)
                else:
                    labels[CLUSTER_LABEL] = "c1"
                o["metadata"]["labels"] = labels
                up.update("configmaps", o)
            else:
                # downstream status write -> upsync
                d = down.get("configmaps", name, "default")
                d["status"] = {"observed": str(step)}
                down.update_status("configmaps", d)
        except Exception:
            # racing our own ops (not-found, conflict) is part of the fuzz
            pass
        if step % 8 == 0:
            await asyncio.sleep(0.01)

    def _pairs():
        up_items = {o["metadata"]["name"]: o for o in up.list("configmaps")[0]
                    if (o["metadata"].get("labels") or {})
                    .get(CLUSTER_LABEL) == "c1"}
        down_items = {o["metadata"]["name"]: o
                      for o in down.list("configmaps")[0]}
        if set(up_items) != set(down_items):
            return None
        return up_items, down_items

    def spec_converged():
        pairs = _pairs()
        if pairs is None:
            return False
        up_items, down_items = pairs
        return all(down_items[n]["data"] == u["data"]
                   for n, u in up_items.items())

    def converged():
        pairs = _pairs()
        if pairs is None:
            return False
        up_items, down_items = pairs
        for name, u in up_items.items():
            d = down_items[name]
            if u["data"] != d["data"]:
                return False
            if d.get("status") != u.get("status"):
                return False
        return True

    if mesh is not None:
        # positive control: a mesh-plumbing regression would otherwise
        # make sharded == flat pass vacuously on two unsharded runs
        assert syncer.engines[0]._section.bucket.mesh is mesh
    if datafn is not None:
        # positive control for the schema-evolution family: the growing
        # field vocabulary must actually overflow the 64-slot encoder
        # (bucket regrow + re-register), or the scenario silently
        # degenerated into the plain-churn fuzz. Whether the fuzz loop
        # alone got there is tick-batching-dependent (coalesced updates
        # mean the engine encodes only a timing-dependent subset of
        # intermediate snapshots — borderline seeds flaked under suite
        # load), so force it with a fixed trio of objects carrying 90
        # fresh field names, identical in both backend runs: the regrow
        # seam is exercised every run and the cross-backend state
        # comparison still sees the same object set.
        for j in range(3):
            o = _cm(f"cm-grow-{j}", OPS + j)
            o["data"] = {f"grow{j}_{k}": "x" for k in range(30)}
            up.create("configmaps", o)
        assert await _wait_until(
            lambda: syncer.engines[0].enc.capacity > 64, 20), (
            f"vocabulary never outgrew the bucket "
            f"(capacity={syncer.engines[0].enc.capacity})")
    # the mid-run status ops race the engine (a down.get can hit a
    # not-yet-downsynced object), so WHICH of them landed is timing- and
    # backend-speed-dependent — legitimate chaos, but not a deterministic
    # final state. Settle specs first, then write one deterministic
    # status round over every surviving downstream object and require it
    # to upsync: a stronger proof than the racing subset (every surviving
    # row must upsync — the MASK_STAMP class of bug cannot hide), and the
    # cross-backend state comparison becomes exact.
    assert await _wait_until(spec_converged, 20), (
        f"{backend} seed={seed} specs did not converge")
    for o in down.list("configmaps")[0]:
        name = o["metadata"]["name"]
        for _ in range(5):  # re-read on conflict with an in-flight apply
            try:
                final = dict(down.get("configmaps", name, "default"))
                final["status"] = {"observed": "final"}
                down.update_status("configmaps", final)
                break
            except Exception:  # noqa: BLE001 — conflict / racing delete
                await asyncio.sleep(0.02)
    assert await _wait_until(converged, 20), (
        f"{backend} seed={seed} did not converge")
    state = sorted(
        (o["metadata"]["name"], str(o["data"]), str(o.get("status")))
        for o in down.list("configmaps")[0])
    await syncer.stop()
    return state


@pytest.mark.parametrize("seed", [3, 11, 42, 57, 63])
def test_randomized_churn_differential(seed):
    async def main():
        tpu_state = await _run_backend("tpu", seed)
        host_state = await _run_backend("host", seed)
        assert tpu_state == host_state

    asyncio.run(main())


def test_randomized_churn_differential_sharded():
    """The same fuzz over a mesh-sharded serving core: a (4 tenants x 2
    slots) mesh on the virtual 8-device CPU fleet must converge the
    random op sequence to the same state as the unsharded tpu backend —
    random interleavings through the sharded scatter/ack/mask-stamp wire
    included."""
    from kcp_tpu.parallel.mesh import make_mesh

    async def main():
        mesh = make_mesh(n_devices=8, tenants=4, slots=2)
        sharded = await _run_backend("tpu", 11, mesh=mesh)
        flat = await _run_backend("tpu", 11)
        assert sharded == flat

    asyncio.run(main())


@pytest.mark.parametrize("seed", [5, 23, 41])
def test_schema_evolution_differential(seed):
    """Mid-sync vocabulary growth: updates keep introducing NEW field
    names, so the shared bucket overflows its 64-slot encoder, regrows,
    and re-registers while churn continues — rows migrate to a fresh
    bucket with live events in flight. Both backends must still converge
    identically (the round-4 MASK_STAMP bug lived exactly in this
    re-registration seam)."""
    def wide(rng, step):
        data = {"v": str(step)}
        for _ in range(rng.randrange(2, 6)):
            data[f"f{rng.randrange(150)}"] = str(step)
        return data

    async def main():
        tpu_state = await _run_backend("tpu", seed, datafn=wide)
        host_state = await _run_backend("host", seed, datafn=wide)
        assert tpu_state == host_state

    asyncio.run(main())


@pytest.mark.parametrize("seed", [13, 29, 37])
def test_compaction_watch_drop_differential(seed):
    """Mid-sequence, the upstream store compacts away its retained watch
    history AND both informer streams break — the reflector loop must
    re-list (resume-by-RV is impossible past compaction) and the engines
    must heal to the exact converged state, on both backends."""
    def disrupt(kcp, syncer):
        kcp._history.clear()  # snapshot-compaction analog
        for e in syncer.engines:
            for inf in (e.up_informer, e.down_informer):
                if inf._watch is not None:
                    inf._watch.close()  # stream drop -> relist + rewatch

    async def main():
        tpu_state = await _run_backend("tpu", seed, disrupt=disrupt)
        host_state = await _run_backend("host", seed, disrupt=disrupt)
        assert tpu_state == host_state

    asyncio.run(main())


def test_engine_register_retire_races():
    """A second syncer (placement owner) randomly starts and stops while
    the first keeps serving: its sections register into and retire from
    the SAME shared fused bucket mid-churn. Retired rows must neither
    leak decisions nor corrupt the survivor's lanes, and the final
    placement must be exact for both clusters."""

    async def main():
        rng = random.Random(17)
        kcp, phys1, phys2 = LogicalStore(), LogicalStore(), LogicalStore()
        up = Client(kcp, "t")
        down1, down2 = Client(phys1, "p1"), Client(phys2, "p2")
        s1 = await start_syncer(up, down1, ["configmaps"], "c1",
                                resync_period=1.5)
        s2 = None
        for step in range(90):
            if rng.random() < 0.08:
                if s2 is None:
                    s2 = await start_syncer(up, down2, ["configmaps"], "c2",
                                            resync_period=1.5)
                else:
                    await s2.stop()
                    s2 = None
            name = f"cm-{rng.randrange(12)}"
            op = rng.random()
            try:
                if op < 0.35:
                    cluster = "c1" if rng.random() < 0.5 else "c2"
                    o = _cm(name, step, labeled=False)
                    o["metadata"]["labels"] = {CLUSTER_LABEL: cluster}
                    up.create("configmaps", o)
                elif op < 0.6:
                    o = up.get("configmaps", name, "default")
                    o["data"] = {"v": str(step)}
                    up.update("configmaps", o)
                elif op < 0.75:
                    up.delete("configmaps", name, "default")
                else:
                    o = up.get("configmaps", name, "default")
                    labels = o["metadata"].get("labels") or {}
                    cur = labels.get(CLUSTER_LABEL)
                    labels[CLUSTER_LABEL] = "c2" if cur == "c1" else "c1"
                    o["metadata"]["labels"] = labels
                    up.update("configmaps", o)
            except Exception:
                pass
            if step % 8 == 0:
                await asyncio.sleep(0.01)
        # end with BOTH syncers serving so both placements can settle
        if s2 is None:
            s2 = await start_syncer(up, down2, ["configmaps"], "c2",
                                    resync_period=1.5)

        def placed():
            want = {"c1": {}, "c2": {}}
            for o in up.list("configmaps")[0]:
                cl = (o["metadata"].get("labels") or {}).get(CLUSTER_LABEL)
                if cl in want:
                    want[cl][o["metadata"]["name"]] = o["data"]
            got1 = {o["metadata"]["name"]: o["data"]
                    for o in down1.list("configmaps")[0]}
            got2 = {o["metadata"]["name"]: o["data"]
                    for o in down2.list("configmaps")[0]}
            return want["c1"] == got1 and want["c2"] == got2

        try:
            assert await _wait_until(placed, 25), (
                "placement did not converge after register/retire races")
        finally:
            await s1.stop()
            await s2.stop()

    asyncio.run(main())


def test_randomized_two_cluster_migration():
    """Random label migrations between TWO physical clusters: an object
    labeled c1 must live in phys1 only, c2 in phys2 only, and random
    flips migrate it — each syncer sees a filtered DELETE on one side
    and an ADD on the other (the transparent-multi-cluster mechanic
    under the deployment splitter). Both syncers share the per-loop
    fused core, so this also stresses two engines' rows interleaved in
    one bucket under churn."""

    async def main():
        rng = random.Random(7)
        kcp, phys1, phys2 = LogicalStore(), LogicalStore(), LogicalStore()
        up = Client(kcp, "t")
        down1, down2 = Client(phys1, "p1"), Client(phys2, "p2")
        s1 = await start_syncer(up, down1, ["configmaps"], "c1",
                                resync_period=1.5)
        s2 = await start_syncer(up, down2, ["configmaps"], "c2",
                                resync_period=1.5)
        pool = 12
        for step in range(90):
            name = f"cm-{rng.randrange(pool)}"
            op = rng.random()
            try:
                if op < 0.3:
                    cluster = "c1" if rng.random() < 0.5 else "c2"
                    o = _cm(name, step, labeled=False)
                    o["metadata"]["labels"] = {CLUSTER_LABEL: cluster}
                    up.create("configmaps", o)
                elif op < 0.55:
                    o = up.get("configmaps", name, "default")
                    o["data"] = {"v": str(step)}
                    up.update("configmaps", o)
                elif op < 0.7:
                    up.delete("configmaps", name, "default")
                else:
                    # migrate: flip the placement label c1 <-> c2
                    o = up.get("configmaps", name, "default")
                    labels = o["metadata"].get("labels") or {}
                    cur = labels.get(CLUSTER_LABEL)
                    labels[CLUSTER_LABEL] = "c2" if cur == "c1" else "c1"
                    o["metadata"]["labels"] = labels
                    up.update("configmaps", o)
            except Exception:
                pass
            if step % 8 == 0:
                await asyncio.sleep(0.01)

        def placed():
            want = {"c1": {}, "c2": {}}
            for o in up.list("configmaps")[0]:
                cl = (o["metadata"].get("labels") or {}).get(CLUSTER_LABEL)
                if cl in want:
                    want[cl][o["metadata"]["name"]] = o["data"]
            got1 = {o["metadata"]["name"]: o["data"]
                    for o in down1.list("configmaps")[0]}
            got2 = {o["metadata"]["name"]: o["data"]
                    for o in down2.list("configmaps")[0]}
            return want["c1"] == got1 and want["c2"] == got2

        try:
            assert await _wait_until(placed, 25), (
                "placement did not converge after migrations")
        finally:
            await s1.stop()
            await s2.stop()

    asyncio.run(main())
